"""Differential test: mesh-sharded full-tree merkleization vs the SSZ
List hash_tree_root, on the virtual 8-device CPU mesh."""
import numpy as np
import pytest

from consensus_specs_tpu.parallel import build_mesh
from consensus_specs_tpu.parallel.merkle_sharded import sharded_uint64_list_root
from consensus_specs_tpu.ssz.types import List, uint64

LIMIT = 2**40


@pytest.fixture(scope="module")
def mesh():
    import jax

    return build_mesh(8, devices=jax.devices())


@pytest.mark.parametrize("n", [0, 1, 5, 64, 100, 1000, 4096])
def test_sharded_root_matches_ssz_list(mesh, n):
    rng = np.random.default_rng(n + 1)
    arr = rng.integers(0, 2**62, n).astype(np.int64)
    expected = List[uint64, LIMIT]([int(x) for x in arr]).hash_tree_root()
    got = sharded_uint64_list_root(mesh, arr, LIMIT)
    assert got == expected


def test_sharded_root_respects_limit_depth(mesh):
    arr = np.arange(16, dtype=np.int64)
    for limit in (16, 1024, 2**30):
        expected = List[uint64, limit]([int(x) for x in arr]).hash_tree_root()
        assert sharded_uint64_list_root(mesh, arr, limit) == expected
