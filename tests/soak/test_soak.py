"""Soak-endurance run (ISSUE 9, `make soak`): long seeded faulted walks
with breaker-recovery, root-parity, cache-coherence, and memory-flatness
assertions, emitting the SOAK.json timeline artifact.

Both profiles are slow-marked so tier-1 (`-m 'not slow'`) never pays
them; `make soak` runs this directory without the marker filter.  The
deep profile additionally needs CSTPU_SOAK_DEEP=1 (`make soak-deep`)."""
import json
import os

import pytest

from consensus_specs_tpu.telemetry import soak


def _check_report(report, expected_forks):
    assert report["failure"] is None
    assert [s["fork"] for s in report["forks"]] == list(expected_forks)
    for section in report["forks"]:
        assert section["walk_stats"]["breaker_state"] == "closed"
        assert section["walk_stats"]["breaker_trips"] >= 1  # epoch 0 trip
        assert section["rerun_stats"]["replayed_blocks"] == 0
        assert section["rerun_stats"]["fast_blocks"] == section["blocks"]
        assert section["fired"], "no scheduled fault fired"
        for sample in section["cache_samples"]:
            for entry in sample["sizes"]:
                if entry["cap"]:
                    assert entry["size"] <= entry["cap"], entry
        # RSS endurance tracking (ISSUE 11): every epoch carries a
        # sample and the walk's flatness verdict is recorded green
        rss = [s["rss_mb"] for s in section["cache_samples"]]
        assert all(r is None or r > 0 for r in rss)
        flat = section["rss_flatness"]
        if flat is not None:  # None only when RSS was unsampleable
            assert flat["flat"], flat
            assert flat["final_mb"] > 0 and flat["budget_mb"] >= 128.0
    # the artifact carries the post-mortem surfaces
    assert report["snapshot"]["providers"]["stf.engine"]
    kinds = [e["kind"] for e in report["timeline"]]
    assert "breaker_open" in kinds and "breaker_close" in kinds
    assert kinds.index("breaker_open") < kinds.index("breaker_close")


@pytest.mark.slow
def test_soak_bounded():
    # default out path: the repo-root SOAK.json artifact (CSTPU_SOAK_OUT
    # overrides), the same convention as BENCH_DETAILS.json
    report = soak.run_soak("bounded")
    _check_report(report, ("phase0", "altair"))
    with open(report["out_path"]) as f:
        on_disk = json.load(f)
    assert on_disk["profile"] == "bounded"
    assert on_disk["failure"] is None
    assert on_disk["timeline"], "artifact carries no timeline"


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("CSTPU_SOAK_DEEP") != "1",
                    reason="deep endurance profile: CSTPU_SOAK_DEEP=1 "
                           "(make soak-deep)")
def test_soak_deep():
    report = soak.run_soak("deep")
    _check_report(report, ("phase0", "altair"))


@pytest.mark.slow
@pytest.mark.skipif(not os.environ.get("CSTPU_SOAK_MINUTES"),
                    reason="wall-clock endurance mode: "
                           "CSTPU_SOAK_MINUTES=<minutes> "
                           "(make soak-endurance)")
def test_soak_endurance():
    """ISSUE 20 satellite / ROADMAP item 3: the budgeted loop runs to
    expiry, every sampled cap holds, and the whole multi-pass RSS series
    sits inside the same flatness envelope the per-walk soak asserts."""
    report = soak.run_endurance()
    assert report["failure"] is None
    section = report["forks"][0]
    assert section["mode"] == "endurance"
    assert section["passes"] >= 1
    assert section["blocks_applied"] > 0
    assert section["elapsed_s"] >= section["budget_minutes"] * 60.0 * 0.9 \
        or section["passes"] == 1  # a single pass may outlast a tiny budget
    for sample in section["cache_samples"]:
        for entry in sample["sizes"]:
            if entry["cap"]:
                assert entry["size"] <= entry["cap"], entry
    rss = [s["rss_mb"] for s in section["cache_samples"]]
    assert all(r is None or r > 0 for r in rss)
    flat = section["rss_flatness"]
    if flat is not None:  # None only when RSS was unsampleable
        assert flat["flat"], flat
        assert flat["budget_mb"] >= 128.0
    with open(report["out_path"]) as f:
        on_disk = json.load(f)
    assert on_disk["profile"] == "endurance"
    assert on_disk["failure"] is None
