"""Soak-endurance run (ISSUE 9, `make soak`): long seeded faulted walks
with breaker-recovery, root-parity, cache-coherence, and memory-flatness
assertions, emitting the SOAK.json timeline artifact.

Both profiles are slow-marked so tier-1 (`-m 'not slow'`) never pays
them; `make soak` runs this directory without the marker filter.  The
deep profile additionally needs CSTPU_SOAK_DEEP=1 (`make soak-deep`)."""
import json
import os

import pytest

from consensus_specs_tpu.telemetry import soak


def _check_report(report, expected_forks):
    assert report["failure"] is None
    assert [s["fork"] for s in report["forks"]] == list(expected_forks)
    for section in report["forks"]:
        assert section["walk_stats"]["breaker_state"] == "closed"
        assert section["walk_stats"]["breaker_trips"] >= 1  # epoch 0 trip
        assert section["rerun_stats"]["replayed_blocks"] == 0
        assert section["rerun_stats"]["fast_blocks"] == section["blocks"]
        assert section["fired"], "no scheduled fault fired"
        for sample in section["cache_samples"]:
            for entry in sample["sizes"]:
                if entry["cap"]:
                    assert entry["size"] <= entry["cap"], entry
        # RSS endurance tracking (ISSUE 11): every epoch carries a
        # sample and the walk's flatness verdict is recorded green
        rss = [s["rss_mb"] for s in section["cache_samples"]]
        assert all(r is None or r > 0 for r in rss)
        flat = section["rss_flatness"]
        if flat is not None:  # None only when RSS was unsampleable
            assert flat["flat"], flat
            assert flat["final_mb"] > 0 and flat["budget_mb"] >= 128.0
    # the artifact carries the post-mortem surfaces
    assert report["snapshot"]["providers"]["stf.engine"]
    kinds = [e["kind"] for e in report["timeline"]]
    assert "breaker_open" in kinds and "breaker_close" in kinds
    assert kinds.index("breaker_open") < kinds.index("breaker_close")


@pytest.mark.slow
def test_soak_bounded():
    # default out path: the repo-root SOAK.json artifact (CSTPU_SOAK_OUT
    # overrides), the same convention as BENCH_DETAILS.json
    report = soak.run_soak("bounded")
    _check_report(report, ("phase0", "altair"))
    with open(report["out_path"]) as f:
        on_disk = json.load(f)
    assert on_disk["profile"] == "bounded"
    assert on_disk["failure"] is None
    assert on_disk["timeline"], "artifact carries no timeline"


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("CSTPU_SOAK_DEEP") != "1",
                    reason="deep endurance profile: CSTPU_SOAK_DEEP=1 "
                           "(make soak-deep)")
def test_soak_deep():
    report = soak.run_soak("deep")
    _check_report(report, ("phase0", "altair"))
