"""Thread-safety of the tracing/metrics core (ISSUE 9 satellite): the
bare-defaultdict counter increment and the SHARED span-nesting stack
raced under the native thread pool and the parallel/ paths — mutation is
now lock-guarded and span nesting is per-thread.  The nesting test fails
deterministically against the pre-fix shared-stack implementation
(cross-thread key contamination like ``outer3/outer2/inner`` and wildly
wrong counts — verified); the counter tests pin the lock around the
load-modify-store window, whose loss under the GIL is real but timing
dependent."""
import threading

import pytest

from consensus_specs_tpu import tracing


@pytest.fixture(autouse=True)
def _clean():
    tracing.reset()
    tracing.disable()
    yield
    tracing.reset()
    tracing.disable()


def test_concurrent_counter_increments_are_exact():
    tracing.enable()
    n_threads, n_incr = 8, 20_000
    barrier = threading.Barrier(n_threads)

    def worker():
        barrier.wait()
        for _ in range(n_incr):
            tracing.count("race.shared")
            tracing.count("race.shared", 2)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert tracing.report()["counters"]["race.shared"] == n_threads * n_incr * 3


def test_concurrent_spans_keep_per_thread_nesting():
    tracing.enable()
    n_threads, n_spans = 4, 2_000
    barrier = threading.Barrier(n_threads)

    def worker(tid):
        barrier.wait()
        for _ in range(n_spans):
            with tracing.span(f"outer{tid}"):
                with tracing.span("inner"):
                    pass

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = tracing.report()["spans"]
    for tid in range(n_threads):
        # a shared nesting stack would cross-contaminate the key paths
        # (outer0/outer1/inner etc.); per-thread stacks keep them exact
        assert spans[f"outer{tid}"]["count"] == n_spans
        assert spans[f"outer{tid}/inner"]["count"] == n_spans
    assert not any("outer0/outer" in k for k in spans)


def test_concurrent_span_and_counter_mix():
    tracing.enable()
    n_threads = 6
    barrier = threading.Barrier(n_threads)

    def worker():
        barrier.wait()
        for _ in range(3_000):
            with tracing.span("mix"):
                tracing.count("mix.c")

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rep = tracing.report()
    assert rep["spans"]["mix"]["count"] == n_threads * 3_000
    assert rep["counters"]["mix.c"] == n_threads * 3_000
