"""Re-entrant spec instrumentation (ISSUE 9 satellite): a spec rebuild
rebinds ``process_*`` module globals (the builder's kernel substitution,
bench's ``__wrapped__`` unwrap idiom), which silently dropped the tracing
wrappers; and a copied boolean flag (``functools.wraps`` copies
``__dict__``) made re-instrumentation SKIP exactly the functions that
needed re-wrapping.  ``instrument_spec`` now identity-marks its wrappers
and re-wraps anything that is not literally one of its own."""
import functools

import pytest

from consensus_specs_tpu import tracing
from consensus_specs_tpu.specs.builder import build_spec


@pytest.fixture(autouse=True)
def _clean():
    tracing.reset()
    tracing.disable()
    yield
    tracing.reset()
    tracing.disable()


@pytest.fixture(scope="module")
def spec():
    # dedicated module name: instrumentation mutates spec globals and
    # must never leak into the shared cached builds other tests use
    return build_spec("phase0", "minimal", name="reentrant_phase0")


def _run_epoch(spec):
    from consensus_specs_tpu.testing.context import (
        default_activation_threshold,
        default_balances,
    )
    from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state
    from consensus_specs_tpu.testing.helpers.state import next_epoch

    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec))
    next_epoch(spec, state)


def test_reinstrument_after_reset_and_rebuild_produces_spans(spec):
    assert tracing.instrument_spec(spec) > 10
    assert tracing.instrument_spec(spec) == 0  # idempotent

    # "rebuild": rebind a few transition globals to fresh unwrapped
    # functions, the way the builder's substitution pass and bench's
    # __wrapped__ idiom do — the old wrappers are silently gone
    dropped = ["process_epoch", "process_slot", "process_justification_and_finalization"]
    for name in dropped:
        spec.__dict__[name] = spec.__dict__[name].__wrapped__
    tracing.reset()

    n = tracing.instrument_spec(spec)
    assert n == len(dropped)  # exactly the dropped functions re-wrap

    tracing.enable()
    _run_epoch(spec)
    spans = tracing.report()["spans"]
    assert any(k.endswith("process_epoch") for k in spans)
    assert any("process_epoch/" in k for k in spans)


def test_copied_flag_cannot_fake_instrumentation(spec):
    tracing.instrument_spec(spec)
    wrapper = spec.__dict__["process_epoch"]

    # a substitution that functools.wraps the OLD wrapper copies its
    # __dict__ (including any marker) onto a brand-new function; the old
    # boolean-flag scheme then skipped it forever
    @functools.wraps(wrapper)
    def substituted(state):
        return wrapper.__wrapped__(state)

    spec.__dict__["process_epoch"] = substituted
    assert tracing.instrument_spec(spec) == 1  # identity check re-wraps

    tracing.enable()
    _run_epoch(spec)
    assert any(k.endswith("process_epoch")
               for k in tracing.report()["spans"])


def test_instrumented_spec_still_transitions_correctly(spec):
    # behavior preservation after a wrap -> unwrap -> re-wrap cycle
    tracing.instrument_spec(spec)
    _run_epoch(spec)  # disabled: wrappers must be pass-through
