"""Causal trace timeline (ISSUE 11): deterministic Chrome-trace export
under a fake clock, cap-bounded ring eviction, causality links surviving
a pipeline drain (drained blocks' spans marked cancelled, verified
against the chaos-harness corpus), and the disabled-path overhead
contract."""
import json
import threading
import time

import pytest

from consensus_specs_tpu.telemetry import timeline


@pytest.fixture(autouse=True)
def _clean():
    was = timeline.enabled()
    timeline.reset()
    yield
    timeline.set_clock()
    timeline.reset()
    timeline.disable() if not was else timeline.enable()


class FakeClock:
    """Deterministic monotonic clock: each read advances 1 ms."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 0.001
        return self.t


def test_disabled_records_nothing():
    timeline.disable()
    sid = timeline.begin("ghost")
    assert sid == 0
    timeline.end(sid)
    timeline.instant("ghost")
    assert timeline.events() == []
    assert timeline.stats()["spans"] == 0


def test_span_events_are_paired_and_thread_stamped():
    timeline.enable()
    with timeline.span("outer", link=7, slot=3):
        with timeline.span("inner", link=7):
            pass
    evs = timeline.events()
    assert [e["ph"] for e in evs] == ["B", "B", "E", "E"]
    outer_b, inner_b, inner_e, outer_e = evs
    assert outer_b["name"] == "outer" and outer_b["link"] == 7
    assert outer_b["slot"] == 3
    assert inner_e["sid"] == inner_b["sid"]
    assert outer_e["sid"] == outer_b["sid"]
    assert outer_b["tid"] == threading.get_ident()
    assert outer_b["tname"] == threading.current_thread().name


def test_chrome_trace_is_deterministic_under_fake_clock(tmp_path):
    def build():
        timeline.reset()
        timeline.set_clock(FakeClock())
        link = timeline.next_link()
        with timeline.span("host/phases", link=link, slot=1):
            with timeline.span("host/slot_roots", link=link):
                pass
        sid = timeline.begin("native/verify", link=link, entries=4)
        timeline.end(sid)
        timeline.instant("commit", link=link)
        return timeline.dump_chrome_trace()

    timeline.enable()
    first, second = build(), build()
    # byte-deterministic: same fake-clock schedule, same export
    assert json.dumps(first, sort_keys=True) == \
        json.dumps(second, sort_keys=True)
    xs = [e for e in first["traceEvents"] if e["ph"] == "X"]
    # complete events are ordered by begin time, µs-relative to t0
    assert [e["name"] for e in xs] == \
        ["host/phases", "host/slot_roots", "native/verify"]
    assert [e["ts"] for e in xs] == sorted(e["ts"] for e in xs)
    assert xs[0]["ts"] == 0.0 and xs[0]["dur"] == 3000.0  # 3 fake-ms span
    assert xs[0]["args"] == {"link": 1, "slot": 1, "status": "ok"}
    # the flow: one start + one finish per later event on the same link
    flows = [e for e in first["traceEvents"] if e["ph"] in ("s", "f")]
    assert [e["ph"] for e in flows] == ["s", "f", "f", "f"]
    assert {e["id"] for e in flows} == {1}
    # instants and thread-name metadata present
    assert any(e["ph"] == "i" and e["name"] == "commit"
               for e in first["traceEvents"])
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in first["traceEvents"])


def test_dump_writes_atomic_json(tmp_path):
    timeline.enable()
    with timeline.span("x"):
        pass
    path = tmp_path / "trace.json"
    payload = timeline.dump_chrome_trace(str(path))
    on_disk = json.loads(path.read_text())
    assert on_disk == json.loads(json.dumps(payload))
    assert on_disk["displayTimeUnit"] == "ms"


def test_cap_bounded_ring_eviction():
    timeline.enable(cap=8)
    try:
        # 5 concurrently-open spans = 10 appends against an 8-event cap:
        # the two oldest begins fall off (counted), leaving orphan ends
        sids = [timeline.begin("s", i=i) for i in range(5)]
        for sid in sids:
            timeline.end(sid)
        st = timeline.stats()
        assert st["events"] == 8 and st["cap"] == 8
        assert st["spans"] == 5 and st["dropped"] == 2
        held = timeline.events()
        assert [e["i"] for e in held if e["ph"] == "B"] == [2, 3, 4]
        # export pairs what survived and SKIPS the orphan ends whose
        # begins were evicted — never a fabricated span
        trace = timeline.dump_chrome_trace()
        xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        assert [e["args"]["i"] for e in xs] == [2, 3, 4]
    finally:
        timeline.enable(cap=timeline.DEFAULT_CAP)


def test_unclosed_span_exports_as_open():
    timeline.enable()
    timeline.set_clock(FakeClock())
    sid = timeline.begin("never/closed")
    try:
        with timeline.span("closed"):
            pass
        trace = timeline.dump_chrome_trace()
        by_name = {e["name"]: e for e in trace["traceEvents"]
                   if e["ph"] == "X"}
        assert by_name["never/closed"]["args"]["status"] == "open"
        assert by_name["closed"]["args"]["status"] == "ok"
    finally:
        timeline.end(sid)


def test_cancel_link_marks_only_that_flow():
    timeline.enable()
    with timeline.span("a", link=1):
        pass
    with timeline.span("b", link=2):
        pass
    timeline.cancel_link(1)
    trace = timeline.dump_chrome_trace()
    statuses = {e["name"]: e["args"]["status"]
                for e in trace["traceEvents"] if e["ph"] == "X"}
    assert statuses == {"a": "cancelled", "b": "ok"}
    timeline.cancel_link(None)  # no-op, never raises


# -- engine integration: overlap + drain cancellation -------------------------


def _pipeline_corpus():
    """A seeded multi-block BLS-on walk (the chaos-harness corpus shape)
    + literal-replay oracle roots."""
    from consensus_specs_tpu.testing.context import spec_state_test, with_phases
    from consensus_specs_tpu.testing.helpers.attestations import (
        next_slots_with_attestations,
    )
    from consensus_specs_tpu.testing.helpers.state import next_epoch

    out = {}

    @with_phases(["phase0"])
    @spec_state_test
    def build(spec, state):
        next_epoch(spec, state)
        pre = state.copy()
        _, signed, _ = next_slots_with_attestations(
            spec, state.copy(), 8, True, True)
        s = pre.copy()
        roots = []
        for sb in signed:
            spec.state_transition(s, sb, True)
            roots.append(bytes(s.hash_tree_root()))
        out["corpus"] = (spec, pre, signed, roots)
        yield None

    build(phase="phase0")
    return out["corpus"]


def _fresh_engine():
    from consensus_specs_tpu import stf
    from consensus_specs_tpu.stf import attestations as stf_attestations
    from consensus_specs_tpu.stf import verify as stf_verify

    stf.reset_stats()
    stf_verify.reset_memo()
    stf_verify.reset_degraded()
    stf_attestations.reset_caches()


def test_pipelined_run_links_host_and_native_spans():
    """The PR 10 overlap, visible: native-verify spans run on the
    dispatch thread, host spans on the main thread, and each block's
    flow chains them by link (the acceptance trace in miniature)."""
    from consensus_specs_tpu import stf
    from consensus_specs_tpu.crypto import bls

    spec, pre, signed, roots = _pipeline_corpus()
    _fresh_engine()
    timeline.enable()
    timeline.reset()
    prev = bls.bls_active
    bls.bls_active = True
    try:
        s = pre.copy()
        stf.apply_signed_blocks(spec, s, signed, True)
        assert bytes(s.hash_tree_root()) == roots[-1]
    finally:
        bls.bls_active = prev
    evs = timeline.events()
    native = [e for e in evs
              if e["ph"] == "B" and e["name"] == "native/verify"]
    host = [e for e in evs
            if e["ph"] == "B" and e["name"] == "host/slot_roots"]
    assert native and host
    assert {e["tname"] for e in native} == {"cstpu-sigpipe_0"}
    assert {e["tname"] for e in host} == {threading.current_thread().name}
    # every native span carries the SAME link as some host span: the
    # causal chain block seq -> dispatch -> native verify holds
    host_links = {e["link"] for e in host}
    assert all(e["link"] in host_links for e in native)
    # await spans close the chain on the host side
    assert any(e.get("name") == "host/await_verdict" for e in evs)
    trace = timeline.dump_chrome_trace()
    assert {e["ph"] for e in trace["traceEvents"]} >= {"X", "s", "f", "M"}


def test_drained_speculation_spans_marked_cancelled():
    """Chaos-harness verification of the drain contract: an injected
    native-call fault mid-window fails a verdict, the drained blocks'
    spans flip to cancelled, and the causality links survive — while the
    walk still lands the literal-replay roots."""
    from consensus_specs_tpu import faults, stf
    from consensus_specs_tpu.crypto import bls
    from consensus_specs_tpu.stf import pipeline

    spec, pre, signed, roots = _pipeline_corpus()
    _fresh_engine()
    timeline.enable()
    timeline.reset()
    plan = faults.FaultPlan([faults.Fault("stf.verify.native_call", nth=3)])
    prev = bls.bls_active
    bls.bls_active = True
    try:
        with faults.inject(plan):
            s = pre.copy()
            stf.apply_signed_blocks(spec, s, signed, True)
            assert bytes(s.hash_tree_root()) == roots[-1]
    finally:
        bls.bls_active = prev
    assert plan.fired, "the schedule never fired"
    assert pipeline.stats["drains"] >= 1
    evs = timeline.events()
    # the failing block AND every newer speculation rolled back: their
    # whole flows (host phases + native span) are marked cancelled
    cancelled_links = {e["link"] for e in evs
                       if e.get("status") == "cancelled" and "link" in e}
    assert cancelled_links, "no drained flow was marked cancelled"
    for link in cancelled_links:
        flow = [e for e in evs if e.get("link") == link]
        assert flow, "cancelled link lost its events"
        assert all(e.get("status", "cancelled") == "cancelled"
                   for e in flow if e["ph"] in ("B", "i"))
    # settled blocks keep ok spans, so the trace distinguishes the two
    ok_links = {e["link"] for e in evs
                if e["ph"] == "B" and "link" in e
                and e.get("status", "ok") == "ok"}
    assert ok_links - cancelled_links, "no settled flow survived"
    # the drain itself is a point event on the failing flow
    assert any(e["ph"] == "i" and e["name"] == "pipeline_drain"
               for e in evs)
    # coherence: the caches carry no poison (the chaos-harness contract)
    _fresh_engine()
    s2 = pre.copy()
    bls.bls_active = True
    try:
        stf.apply_signed_blocks(spec, s2, signed, True)
    finally:
        bls.bls_active = prev
    assert stf.stats["replayed_blocks"] == 0


# -- disabled-path overhead (ISSUE 11 acceptance) ------------------------------


def _per_call(fn, n=200_000):
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - t0) / n


def test_disabled_path_adds_no_measurable_cost():
    """The acceptance microbench, pinned like the flight recorder's:
    with the timeline off, begin/end/instant are a global load + truth
    check (bounded at 5µs/call — ~50x margin over measured cost on the
    1 vCPU host) and the span context manager stays under 10µs."""
    timeline.disable()
    assert _per_call(lambda: timeline.begin("off")) < 5e-6
    assert _per_call(lambda: timeline.end(0)) < 5e-6
    assert _per_call(lambda: timeline.instant("off")) < 5e-6
    assert _per_call(lambda: timeline.cancel_link(3)) < 5e-6

    def _span():
        with timeline.span("off"):
            pass

    assert _per_call(_span, n=50_000) < 10e-6
