"""Latency histograms (ISSUE 11): log2 bucketing, percentile estimation,
thread safety under the metrics-lock discipline, bus-snapshot shape, and
the engine integration (per-phase distributions observed per block)."""
import math
import threading

import pytest

from consensus_specs_tpu.telemetry import histogram


@pytest.fixture(autouse=True)
def _clean():
    histogram.reset()
    yield
    histogram.reset()


def test_bucket_index_covers_the_range():
    # buckets are [2^(e-1), 2^e): an exact power of two sits at the
    # lower edge of the bucket it opens
    assert histogram._bucket_index(0.0) == 0
    assert histogram._bucket_index(1e-9) == 0          # under the floor
    assert histogram._bucket_index(2.0 ** -20) == 1
    assert histogram._bucket_index(0.75) == -histogram._MIN_EXP
    assert histogram._bucket_index(48.0) == \
        histogram._MAX_EXP - histogram._MIN_EXP
    assert histogram._bucket_index(64.0) == histogram.N_BUCKETS - 1
    assert histogram._bucket_index(1e9) == histogram.N_BUCKETS - 1
    # monotone: a larger value never lands in a smaller bucket
    prev = -1
    for exp in range(-30, 12):
        idx = histogram._bucket_index(2.0 ** exp * 0.75)
        assert idx >= prev
        prev = idx


def test_quantiles_are_order_of_magnitude_right():
    # 90 fast observations + 10 slow ones: p50 in the fast band, p99 in
    # the slow band, max exact
    for _ in range(90):
        histogram.observe("phase", 0.001)
    for _ in range(10):
        histogram.observe("phase", 0.512)
    snap = histogram.snapshot()["phase"]
    assert snap["count"] == 100
    assert 0.0005 <= snap["p50_s"] <= 0.002
    assert 0.256 <= snap["p99_s"] <= 0.512
    assert snap["max_s"] == 0.512
    assert snap["p50_s"] <= snap["p90_s"] <= snap["p99_s"] <= snap["max_s"]


def test_overflow_bucket_reports_the_tracked_max():
    histogram.observe("slow", 100.0)
    histogram.observe("slow", 500.0)
    snap = histogram.snapshot()["slow"]
    assert snap["p99_s"] == 500.0  # exact max, not a bucket boundary
    assert "inf" in snap["buckets"] and snap["buckets"]["inf"] == 2


def test_snapshot_shape_and_bus_provider():
    histogram.observe("x", 0.25)
    snap = histogram.snapshot()
    assert set(snap) == {"x"}
    entry = snap["x"]
    assert set(entry) == {"count", "total_s", "mean_s", "max_s",
                          "p50_s", "p90_s", "p99_s", "buckets"}
    assert entry["total_s"] == 0.25 and entry["count"] == 1
    # non-zero buckets only, keyed by their (exclusive) upper bound:
    # 0.25 opens the [0.25, 0.5) bucket
    assert entry["buckets"] == {"0.5": 1}
    # the bus serves the same tree under the "histograms" provider
    from consensus_specs_tpu import telemetry

    bus = telemetry.snapshot()["providers"]["histograms"]
    assert bus == snap


def test_empty_after_reset():
    histogram.observe("x", 1.0)
    histogram.reset()
    assert histogram.snapshot() == {}
    assert histogram.names() == ()


def test_concurrent_observers_lose_nothing():
    # the metrics-lock discipline: N threads x M observations all land
    n_threads, per_thread = 8, 2000

    def worker(k):
        for i in range(per_thread):
            histogram.observe("conc", (k + 1) * 1e-6 * (i % 7 + 1))

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = histogram.snapshot()["conc"]
    assert snap["count"] == n_threads * per_thread
    assert sum(snap["buckets"].values()) == n_threads * per_thread


def test_engine_observes_per_phase_distributions():
    # a real block through the stf engine lands observations in the
    # per-phase histograms the bench rows report p50/p99 from
    from consensus_specs_tpu import stf
    from consensus_specs_tpu.specs.builder import build_spec
    from consensus_specs_tpu.stf import attestations as stf_attestations
    from consensus_specs_tpu.testing.context import (
        default_activation_threshold,
        default_balances,
    )
    from consensus_specs_tpu.testing.helpers.block import (
        build_empty_block_for_next_slot,
    )
    from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state
    from consensus_specs_tpu.testing.helpers.state import (
        state_transition_and_sign_block,
    )

    spec = build_spec("phase0", "minimal", name="histogram_phase0")
    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec))
    stf.reset_stats()  # also resets the histograms (per-pass contract)
    stf_attestations.reset_caches()
    walk = state.copy()
    signed = state_transition_and_sign_block(
        spec, walk, build_empty_block_for_next_slot(spec, walk))
    s = state.copy()
    stf.apply_signed_blocks(spec, s, [signed], True)
    snap = histogram.snapshot()
    assert "slot_roots" in snap and snap["slot_roots"]["count"] >= 1
    assert snap["slot_roots"]["p99_s"] > 0
    # reset_stats drops the distributions with the counters
    stf.reset_stats()
    assert histogram.snapshot() == {}


def test_bucket_bounds_are_contiguous():
    lo0, hi0 = histogram._bucket_bounds(0)
    assert lo0 == 0.0
    for i in range(1, histogram.N_BUCKETS):
        lo, hi = histogram._bucket_bounds(i)
        _, prev_hi = histogram._bucket_bounds(i - 1)
        assert lo == prev_hi
        assert hi > lo
    assert math.isinf(histogram._bucket_bounds(histogram.N_BUCKETS - 1)[1])
