"""Telemetry bus (ISSUE 9): provider registration, snapshot schema
stability, error isolation, and the production providers' presence."""
import json
import threading

import pytest

from consensus_specs_tpu import telemetry
from consensus_specs_tpu.telemetry import registry


@pytest.fixture
def scratch_provider():
    names = []

    def add(name, fn, **kw):
        registry.register_provider(name, fn, **kw)
        names.append(name)

    yield add
    for name in names:
        registry.unregister_provider(name)


def test_register_snapshot_unregister(scratch_provider):
    scratch_provider("test.alpha", lambda: {"x": 1})
    snap = telemetry.snapshot()
    assert snap["schema"] == 1
    assert snap["providers"]["test.alpha"] == {"x": 1}
    registry.unregister_provider("test.alpha")
    assert "test.alpha" not in telemetry.snapshot()["providers"]


def test_duplicate_provider_rejected(scratch_provider):
    scratch_provider("test.dup", lambda: {})
    with pytest.raises(ValueError, match="duplicate"):
        registry.register_provider("test.dup", lambda: {})
    # explicit replace is the sanctioned override (module re-import path)
    registry.register_provider("test.dup", lambda: {"v": 2}, replace=True)
    assert telemetry.snapshot()["providers"]["test.dup"] == {"v": 2}


def test_failing_provider_is_isolated(scratch_provider):
    def boom():
        raise RuntimeError("sick subsystem")

    scratch_provider("test.boom", boom)
    scratch_provider("test.ok", lambda: {"fine": True})
    providers = telemetry.snapshot()["providers"]
    assert "sick subsystem" in providers["test.boom"]["error"]
    assert providers["test.ok"] == {"fine": True}


def test_snapshot_is_a_copy(scratch_provider):
    live = {"n": 0}
    scratch_provider("test.live", lambda: live)
    snap = telemetry.snapshot()["providers"]["test.live"]
    snap["n"] = 99
    assert live["n"] == 0  # deep copy: consumers can't write back


def test_production_providers_register_at_import():
    # importing the engines registers their providers; the bus then
    # carries every stats surface the ISSUE names, JSON-serializable
    import consensus_specs_tpu.forkchoice.engine  # noqa: F401
    import consensus_specs_tpu.stf  # noqa: F401

    snap = telemetry.snapshot()
    names = set(snap["providers"])
    assert {"tracing", "native.bls", "faults", "flight_recorder",
            "stf.engine", "stf.verify", "stf.plan_cache", "stf.columns",
            "stf.sync", "forkchoice.engine"} <= names
    json.dumps(snap)  # schema-stable == JSON-able, whole tree
    # stable key sets across consecutive snapshots (schema stability)
    assert set(telemetry.snapshot()["providers"]) == names


def test_engine_provider_reflects_counters():
    from consensus_specs_tpu import stf

    stf.reset_stats()
    stf.stats["fast_blocks"] += 3
    try:
        engine_tree = telemetry.snapshot()["providers"]["stf.engine"]
        assert engine_tree["fast_blocks"] == 3
        assert engine_tree["breaker"]["open"] is False
        assert engine_tree["breaker_state"] == "closed"
    finally:
        stf.reset_stats()


def test_concurrent_registration_and_snapshot(scratch_provider):
    # registration is lock-guarded: hammering both sides must neither
    # deadlock nor corrupt the registry
    stop = threading.Event()
    errors = []

    def snapper():
        while not stop.is_set():
            try:
                telemetry.snapshot()
            except Exception as exc:  # pragma: no cover - failure capture
                errors.append(exc)

    t = threading.Thread(target=snapper)
    t.start()
    try:
        for i in range(50):
            registry.register_provider(f"test.c{i}", lambda: {}, replace=True)
    finally:
        stop.set()
        t.join()
        for i in range(50):
            registry.unregister_provider(f"test.c{i}")
    assert errors == []
