"""Flight recorder (ISSUE 9): ring bounds + drop accounting, ordering,
dump payloads, engine event integration, and the disabled-path overhead
contract."""
import json
import time

import pytest

from consensus_specs_tpu.telemetry import recorder


@pytest.fixture(autouse=True)
def _clean():
    was = recorder.enabled()
    recorder.reset()
    yield
    recorder.reset()
    recorder.disable() if not was else recorder.enable()


def test_disabled_records_nothing():
    recorder.disable()
    recorder.record("ghost", x=1)
    assert recorder.timeline() == []
    assert recorder.stats()["total"] == 0


def test_events_are_ordered_and_structured():
    recorder.enable()
    recorder.record("alpha", a=1)
    recorder.record("beta", b="two")
    events = recorder.timeline()
    assert [e["kind"] for e in events] == ["alpha", "beta"]
    assert events[0]["seq"] < events[1]["seq"]
    assert events[0]["t"] <= events[1]["t"]
    assert events[0]["a"] == 1 and events[1]["b"] == "two"


def test_ring_bound_and_drop_accounting():
    recorder.enable(cap=8)
    try:
        for i in range(20):
            recorder.record("e", i=i)
        events = recorder.timeline()
        assert len(events) == 8
        assert [e["i"] for e in events] == list(range(12, 20))  # last-N
        st = recorder.stats()
        assert st["total"] == 20 and st["dropped"] == 12 and st["cap"] == 8
    finally:
        recorder.enable(cap=recorder.DEFAULT_CAP)


def test_timeline_returns_copies():
    recorder.enable()
    recorder.record("x", n=1)
    recorder.timeline()[0]["n"] = 99
    assert recorder.timeline()[0]["n"] == 1


def test_dump_writes_post_mortem_json(tmp_path):
    recorder.enable()
    recorder.record("breaker_open", consecutive_errors=3)
    path = tmp_path / "dump.json"
    payload = recorder.dump("unit-test failure", path=str(path))
    on_disk = json.loads(path.read_text())
    assert on_disk["reason"] == "unit-test failure"
    assert on_disk["events"][-1]["kind"] == "breaker_open"
    assert on_disk["snapshot"]["schema"] == 1
    assert payload["recorder"]["events"] == 1


def test_engine_emits_block_events():
    # a real minimal-spec block through the stf engine lands a block_fast
    # event carrying per-block phase deltas and plan-cache movement
    from consensus_specs_tpu import stf
    from consensus_specs_tpu.specs.builder import build_spec
    from consensus_specs_tpu.stf import attestations as stf_attestations
    from consensus_specs_tpu.testing.context import (
        default_activation_threshold,
        default_balances,
    )
    from consensus_specs_tpu.testing.helpers.block import (
        build_empty_block_for_next_slot,
    )
    from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state
    from consensus_specs_tpu.testing.helpers.state import (
        state_transition_and_sign_block,
    )

    spec = build_spec("phase0", "minimal", name="recorder_phase0")
    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec))
    stf.reset_stats()
    stf_attestations.reset_caches()
    walk = state.copy()
    signed = state_transition_and_sign_block(
        spec, walk, build_empty_block_for_next_slot(spec, walk))

    recorder.enable()
    s = state.copy()
    stf.apply_signed_blocks(spec, s, [signed], True)
    kinds = [e["kind"] for e in recorder.timeline()]
    assert "block_fast" in kinds
    assert "cache_commit" in kinds
    # the commit event precedes the block_fast event: settlement first
    assert kinds.index("cache_commit") < kinds.index("block_fast")
    fast = next(e for e in recorder.timeline() if e["kind"] == "block_fast")
    assert fast["slot"] == int(signed.message.slot)
    for key in ("slot_roots_s", "sig_verify_s", "plan_hits", "plan_misses"):
        assert key in fast


# -- disabled-path overhead (ISSUE 9 acceptance) ------------------------------


def _per_call(fn, n=200_000):
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - t0) / n


def test_disabled_path_adds_no_measurable_cost():
    """The acceptance microbench: with the recorder off, record() is a
    global load + truth check — bounded here at 5µs/call (a ~50x margin
    over its measured cost on the 1 vCPU host, so scheduler noise cannot
    flake the gate while a real regression — locking, dict building —
    still trips it)."""
    from consensus_specs_tpu import tracing

    recorder.disable()
    tracing.disable()
    assert _per_call(lambda: recorder.record("off")) < 5e-6
    assert _per_call(lambda: tracing.count("off")) < 5e-6

    def _span():
        with tracing.span("off"):
            pass

    assert _per_call(_span, n=50_000) < 10e-6
