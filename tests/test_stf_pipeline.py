"""Cross-block overlapped verification pipeline (ISSUE 10): unit
contract of ``stf/pipeline.py`` + the engine's speculative path.

The differential/chaos suites own the correctness story (byte parity,
drain coherence, exception parity ON/OFF); this module pins the
pipeline-specific mechanics: the env gate, byte-identical results and
identical memo content pipeline ON vs OFF, the overlap accounting
identity, speculative dedup actually engaging across the window, and
the always-drained invariant (no verdict outlives a call).
"""
from consensus_specs_tpu import stf
from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.stf import pipeline
from consensus_specs_tpu.stf import verify as stf_verify

from .chaos.test_stf_chaos import _corpus, _fresh_engine_env

# -- re-carry corpus ----------------------------------------------------------

# the helper-built chaos corpus includes each aggregate exactly once; a
# live node's blocks re-carry the previous slots' aggregates (the bench
# corpus models that), and the speculative-dedup test needs it: block
# N+1 must probe keys block N has in flight.  Build the smallest such
# chain: two consecutive blocks carrying the SAME valid aggregates
# (process_attestation accepts duplicates within the inclusion window).

_RECARRY = {}


def _recarry_corpus():
    if not _RECARRY:
        from consensus_specs_tpu.testing.context import (
            spec_state_test,
            with_phases,
        )
        from consensus_specs_tpu.testing.helpers.attestations import (
            _get_valid_attestation_at_slot,
        )
        from consensus_specs_tpu.testing.helpers.block import (
            build_empty_block_for_next_slot,
        )
        from consensus_specs_tpu.testing.helpers.state import (
            next_epoch,
            state_transition_and_sign_block,
        )

        @with_phases(["phase0"])
        @spec_state_test
        def build(spec, state):
            next_epoch(spec, state)
            pre = state.copy()
            walk = state.copy()
            b0 = build_empty_block_for_next_slot(spec, walk)
            signed = [state_transition_and_sign_block(spec, walk, b0)]
            atts = list(_get_valid_attestation_at_slot(
                walk, spec, int(walk.slot)))
            for _ in range(2):  # both blocks carry the same aggregates
                blk = build_empty_block_for_next_slot(spec, walk)
                for a in atts:
                    blk.body.attestations.append(a)
                signed.append(
                    state_transition_and_sign_block(spec, walk, blk))
            _RECARRY["phase0"] = (spec, pre, signed,
                                  bytes(walk.hash_tree_root()))
            yield None

        build(phase="phase0")  # DEFAULT_BLS_ACTIVE: signatures are real
    return _RECARRY["phase0"]


def _one_call_walk(fork="phase0"):
    spec, pre, blocks, roots = _corpus(fork)
    _fresh_engine_env()
    s = pre.copy()
    prev = bls.bls_active
    bls.bls_active = True
    try:
        stf.apply_signed_blocks(spec, s, blocks, True)
    finally:
        bls.bls_active = prev
    assert bytes(s.hash_tree_root()) == roots[-1]
    return blocks


def test_enabled_env_gate(monkeypatch):
    monkeypatch.delenv("CSTPU_PIPELINE", raising=False)
    assert pipeline.enabled()
    monkeypatch.setenv("CSTPU_PIPELINE", "0")
    assert not pipeline.enabled()
    monkeypatch.setenv("CSTPU_PIPELINE", "1")
    assert pipeline.enabled()


def test_on_off_byte_identical_and_same_memo(monkeypatch):
    """The same walk pipeline ON and OFF: identical post-state roots,
    identical verified-triple memo content, identical settled-entry
    counts — speculation changes WHEN work happens, never what."""
    spec, pre, blocks, roots = _corpus("phase0")
    prev = bls.bls_active
    bls.bls_active = True
    try:
        results = {}
        for mode in ("0", "1"):
            monkeypatch.setenv("CSTPU_PIPELINE", mode)
            _fresh_engine_env()
            s = pre.copy()
            stf.apply_signed_blocks(spec, s, blocks, True)
            results[mode] = (
                bytes(s.hash_tree_root()),
                frozenset(stf_verify._VERIFIED_MEMO),
                stf_verify.stats["entries"],
                stf_verify.stats["memo_hits"],
                stf.stats["fast_blocks"],
            )
    finally:
        bls.bls_active = prev
    assert results["0"] == results["1"]
    assert results["1"][0] == roots[-1]
    assert results["1"][4] == len(blocks)


def test_overlap_accounting_identity():
    """Every dispatched batch is drained, worker time splits exactly into
    overlapped + awaited seconds, and nothing stays in flight after the
    call returns."""
    blocks = _one_call_walk()
    st = pipeline.stats
    assert st["dispatched"] == len(blocks)
    assert st["drained"] == st["dispatched"]
    assert st["cancelled"] == 0 and st["drains"] == 0
    assert len(pipeline._INFLIGHT) == 0
    assert st["worker_s"] > 0
    # identity: worker_s = overlap_s + awaited-worker overlap residue;
    # overlap can never exceed what the worker actually spent
    assert 0.0 <= st["overlap_s"] <= st["worker_s"] + 1e-9
    assert st["overlap_s"] + st["await_s"] >= st["worker_s"] - 1e-6
    snap = pipeline._telemetry_provider()
    assert snap["depth"] == 0
    assert snap["overlap_ratio"] == round(st["overlap_s"] / st["worker_s"], 3)


def test_speculative_dedup_engages_across_window(monkeypatch):
    """A successor re-carrying the pending predecessor's aggregates hits
    the in-flight key set (not yet committed to the memo): speculative
    hits move pipeline ON, total dedup and results match the serial
    path's byte for byte."""
    spec, pre, blocks, final_root = _recarry_corpus()
    prev = bls.bls_active
    bls.bls_active = True
    try:
        results = {}
        for mode in ("0", "1"):
            monkeypatch.setenv("CSTPU_PIPELINE", mode)
            _fresh_engine_env()
            s = pre.copy()
            stf.apply_signed_blocks(spec, s, blocks, True)
            results[mode] = (bytes(s.hash_tree_root()),
                             stf_verify.stats["memo_hits"],
                             stf_verify.stats["entries"],
                             stf.stats["fast_blocks"])
            if mode == "1":
                assert stf_verify.stats["speculative_hits"] > 0, \
                    "re-carried aggregates never hit the in-flight key set"
            else:
                assert stf_verify.stats["speculative_hits"] == 0
    finally:
        bls.bls_active = prev
    assert results["0"] == results["1"]
    assert results["1"][0] == final_root


def test_depth_bounded_by_window():
    _one_call_walk()
    assert 1 <= pipeline.stats["depth_max"] <= pipeline.window_depth() + 1


def test_window_depth_env_gate(monkeypatch):
    monkeypatch.delenv("CSTPU_PIPELINE_DEPTH", raising=False)
    assert pipeline.window_depth() == 2
    monkeypatch.setenv("CSTPU_PIPELINE_DEPTH", "1")
    assert pipeline.window_depth() == 1
    monkeypatch.setenv("CSTPU_PIPELINE_DEPTH", "0")
    assert pipeline.window_depth() == 1  # clamped
    monkeypatch.setenv("CSTPU_PIPELINE_DEPTH", "junk")
    assert pipeline.window_depth() == 2


def test_depth_one_window_still_byte_identical(monkeypatch):
    """The minimal window (depth 1) is the same contract, less slack."""
    spec, pre, blocks, roots = _corpus("phase0")
    monkeypatch.setenv("CSTPU_PIPELINE_DEPTH", "1")
    _fresh_engine_env()
    s = pre.copy()
    prev = bls.bls_active
    bls.bls_active = True
    try:
        stf.apply_signed_blocks(spec, s, blocks, True)
    finally:
        bls.bls_active = prev
    assert bytes(s.hash_tree_root()) == roots[-1]
    assert stf.stats["fast_blocks"] == len(blocks)


def test_serial_path_untouched_by_pipeline_counters(monkeypatch):
    monkeypatch.setenv("CSTPU_PIPELINE", "0")
    _one_call_walk()
    assert pipeline.stats["dispatched"] == 0
    assert stf_verify.stats["speculative_hits"] == 0
