"""Device Fr NTT parity (ops/fr_jax.py): Montgomery limb arithmetic and
the shard_map four-step FFT must match the host python-int oracle
(crypto/fr.py) bit-for-bit — the SP/CP sharding axis of SURVEY §2.7
(DAS erasure extension, das/das-core.md:90-128)."""
import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from consensus_specs_tpu.crypto import fr
from consensus_specs_tpu.ops import fr_jax


def test_limb_mul_parity():
    rng = np.random.default_rng(1)
    for _ in range(20):
        a = int(rng.integers(0, 2**62)) * int(rng.integers(0, 2**62)) % fr.R
        b = int(rng.integers(0, 2**62)) ** 2 % fr.R
        am, bm = fr_jax.host_to_mont(a), fr_jax.host_to_mont(b)
        got = fr_jax.canonical_int(np.asarray(fr_jax.mul(
            fr_jax.jnp.asarray(am), fr_jax.jnp.asarray(bm))))
        # mont(a)*mont(b)*R^-1 = mont(a*b); canonical_int strips one R
        assert got == a * b % fr.R


@pytest.mark.parametrize("n", [2, 8, 64])
def test_local_ntt_matches_host(n):
    rng = np.random.default_rng(n)
    vals = [int(x) for x in rng.integers(0, 2**63, n)]
    assert fr_jax.ntt_device(vals) == fr.fft(vals)


@pytest.mark.parametrize("n", [16, 128])
def test_sharded_ntt_matches_host(n):
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = Mesh(np.array(devices[:8]), ("d",))
    rng = np.random.default_rng(n)
    vals = [int(x) for x in rng.integers(0, 2**63, n)]
    assert fr_jax.sharded_ntt(vals, mesh) == fr.fft(vals)


def test_sharded_das_extension_shape():
    """das_fft_extension-style use: extend the data vector via the sharded
    inverse/forward pair and check against the host helpers."""
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = Mesh(np.array(devices[:8]), ("d",))
    rng = np.random.default_rng(77)
    data = [int(x) for x in rng.integers(0, 2**61, 32)]
    # polynomial through the data (host inverse), then sharded forward
    # evaluation over the doubled domain must equal the host forward pass
    coeffs = fr.fft(data, inv=True)
    padded = coeffs + [0] * len(coeffs)
    assert fr_jax.sharded_ntt(padded, mesh) == fr.fft(padded)
