"""The checkpoint store (ISSUE 14): tree-codec fidelity, window
serialization, the bounded on-disk ring, the restore ladder, and the
async writer."""
import os

import pytest

from consensus_specs_tpu.persist import store as persist_store
from consensus_specs_tpu.persist.store import (
    CheckpointError,
    CheckpointStore,
    decode_tree,
    deserialize_checkpoint,
    encode_tree,
    serialize_checkpoint,
)
from consensus_specs_tpu.testing.context import (
    default_activation_threshold,
    default_balances,
)
from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state

_CACHE = {}


def _spec_and_state():
    if not _CACHE:
        from consensus_specs_tpu.specs.builder import get_spec

        spec = get_spec("phase0", "minimal")
        state = create_genesis_state(
            spec, default_balances(spec), default_activation_threshold(spec))
        _CACHE["x"] = (spec, state)
    return _CACHE["x"]


@pytest.fixture(autouse=True)
def _fresh_stats():
    persist_store.reset_stats()
    yield


def _roundtrip_tree(spec, view, typ):
    out = bytearray()
    index = {}
    view.hash_tree_root()
    encode_tree(view.get_backing(), out, index)
    nodes = []
    rebuilt, off = decode_tree(bytes(out), 0, nodes)
    assert off == len(out)
    return typ.view_from_backing(rebuilt)


# -- tree codec ---------------------------------------------------------------


def test_codec_roundtrips_a_genesis_state():
    spec, state = _spec_and_state()
    rebuilt = _roundtrip_tree(spec, state, spec.BeaconState)
    assert bytes(rebuilt.hash_tree_root()) == bytes(state.hash_tree_root())
    # roots install from the stream: the rebuilt tree is pre-memoized
    assert rebuilt.get_backing()._root is not None
    # and a deep field read agrees byte-for-byte
    assert bytes(rebuilt.validators[3].pubkey) == \
        bytes(state.validators[3].pubkey)
    assert int(rebuilt.balances[7]) == int(state.balances[7])


def test_codec_roundtrips_a_mutated_state():
    spec, state = _spec_and_state()
    st = state.copy()
    st.slot = 17
    st.balances[0] = 123456789
    st.genesis_validators_root = b"\x42" * 32
    rebuilt = _roundtrip_tree(spec, st, spec.BeaconState)
    assert bytes(rebuilt.hash_tree_root()) == bytes(st.hash_tree_root())
    assert int(rebuilt.balances[0]) == 123456789


def test_codec_leaf_content_equal_to_a_subtree_root_does_not_alias():
    """The genesis_validators_root LEAF stores the registry subtree's
    digest as CONTENT — shape-aware dedup must keep them distinct (the
    bug the (is_leaf, root) key exists for)."""
    spec, state = _spec_and_state()
    st = state.copy()
    st.genesis_validators_root = st.validators.hash_tree_root()
    rebuilt = _roundtrip_tree(spec, st, spec.BeaconState)
    assert bytes(rebuilt.genesis_validators_root) == \
        bytes(st.validators.hash_tree_root())
    assert bytes(rebuilt.hash_tree_root()) == bytes(st.hash_tree_root())


def test_codec_dedups_shared_subtrees_across_states():
    """Two consecutive states share almost everything: the second tree's
    marginal encoding must be a small fraction of the first's."""
    spec, state = _spec_and_state()
    st2 = state.copy()
    st2.slot = int(state.slot) + 1
    out1, index = bytearray(), {}
    state.hash_tree_root()
    st2.hash_tree_root()
    encode_tree(state.get_backing(), out1, index)
    first = len(out1)
    encode_tree(st2.get_backing(), out1, index)
    marginal = len(out1) - first
    assert marginal < first // 4, (first, marginal)
    nodes = []
    a, off = decode_tree(bytes(out1), 0, nodes)
    b, off = decode_tree(bytes(out1), off, nodes)
    assert bytes(spec.BeaconState.view_from_backing(a).hash_tree_root()) \
        == bytes(state.hash_tree_root())
    assert bytes(spec.BeaconState.view_from_backing(b).hash_tree_root()) \
        == bytes(st2.hash_tree_root())


def test_codec_rejects_unknown_tags_and_forward_refs():
    with pytest.raises(CheckpointError):
        decode_tree(bytes([0x7F]), 0, [])
    with pytest.raises(CheckpointError):
        # a REF to a node that was never emitted
        decode_tree(bytes([0x05, 9, 0, 0, 0]), 0, [None] * 20)


# -- checkpoint payload -------------------------------------------------------


def _payload(spec, state, journal_pos=5):
    from consensus_specs_tpu.node.service import default_anchor_block

    anchor_block = default_anchor_block(spec, state)
    state.hash_tree_root()
    root = bytes(anchor_block.hash_tree_root())
    lm = {spec.ValidatorIndex(2): spec.LatestMessage(
        epoch=spec.Epoch(1), root=spec.Root(b"\x07" * 32))}
    return persist_store.CheckpointPayload(
        journal_pos=journal_pos, trigger=("tick", 1234),
        time=int(state.genesis_time),
        justified=(0, root), best_justified=(0, root), finalized=(0, root),
        proposer_boost_root=b"\x00" * 32,
        latest_messages=lm, equivocating=frozenset({11, 3}),
        anchor_root=root,
        window=((root, anchor_block, state),),
        head_state_root=bytes(state.hash_tree_root()))


def test_checkpoint_payload_roundtrip():
    spec, state = _spec_and_state()
    payload = _payload(spec, state)
    restored = deserialize_checkpoint(spec, serialize_checkpoint(payload))
    assert restored.journal_pos == 5
    assert tuple(restored.trigger) == ("tick", 1234)
    assert restored.meta["equivocating"] == [3, 11]
    assert restored.anchor_root == payload.anchor_root
    st = restored.states[payload.anchor_root]
    assert bytes(st.hash_tree_root()) == bytes(state.hash_tree_root())
    store = restored.as_store(spec)
    assert dict(store.latest_messages) == dict(payload.latest_messages)
    assert store.equivocating_indices == {3, 11}


def test_checkpoint_block_state_pairing_is_cross_checked():
    spec, state = _spec_and_state()
    payload = _payload(spec, state)
    raw = bytearray(serialize_checkpoint(payload))
    # damage one byte of the tree stream (the artifact digest normally
    # catches this first; the codec's own cross-checks are the backstop)
    raw[-5] ^= 0xFF
    with pytest.raises(CheckpointError):
        deserialize_checkpoint(spec, bytes(raw))


# -- the store ----------------------------------------------------------------


def test_store_write_prune_and_scan_adopt(tmp_path):
    spec, state = _spec_and_state()
    store = CheckpointStore(str(tmp_path), cap=2, asynchronous=False)
    for pos in (10, 20, 30):
        store.write_checkpoint(spec, _payload(spec, state, journal_pos=pos))
    assert store.depth() == 2  # pruned past the cap
    assert persist_store.stats["pruned"] == 1
    positions = sorted(m["journal_pos"] for m in store.entries().values())
    assert positions == [20, 30]
    assert store.bytes_on_disk() > 0
    # a fresh store instance adopts the survivors from disk
    persist_store.reset_index()
    again = CheckpointStore(str(tmp_path), cap=2, asynchronous=False)
    assert sorted(m["journal_pos"]
                  for m in again.entries().values()) == [20, 30]
    restored = again.restore(spec, again.candidates()[0])
    assert restored.journal_pos == 30


def test_store_restore_ladder_quarantines_damage(tmp_path):
    spec, state = _spec_and_state()
    store = CheckpointStore(str(tmp_path), cap=3, asynchronous=False)
    store.write_checkpoint(spec, _payload(spec, state, journal_pos=7))
    path = store.candidates()[0]
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[: len(data) // 2])  # truncation
    with pytest.raises(CheckpointError):
        store.restore(spec, path)
    assert persist_store.stats["corruptions"] == 1
    assert store.candidates() == []
    assert os.path.exists(path + ".corrupt")


def test_store_stale_format_tag_walks_the_ladder(tmp_path, monkeypatch):
    spec, state = _spec_and_state()
    store = CheckpointStore(str(tmp_path), cap=3, asynchronous=False)
    store.write_checkpoint(spec, _payload(spec, state, journal_pos=7))
    monkeypatch.setattr(persist_store, "FORMAT_TAG", "ckpt-v999")
    with pytest.raises(CheckpointError):
        store.restore(spec, store.candidates()[0])
    assert persist_store.stats["stale_artifacts"] == 1
    assert persist_store.stats["corruptions"] == 0


def test_store_async_writer_flush_and_newest_wins(tmp_path):
    spec, state = _spec_and_state()
    store = CheckpointStore(str(tmp_path), cap=5, asynchronous=True)
    try:
        for pos in (10, 20):
            store.submit(spec, _payload(spec, state, journal_pos=pos))
        assert store.flush(timeout=30.0)
        # at least the newest landed (an earlier pending may be
        # superseded before its write starts: newest-wins by design)
        positions = {m["journal_pos"] for m in store.entries().values()}
        assert 20 in positions
        assert persist_store.stats["write_failures"] == 0
    finally:
        store.close()
    with pytest.raises(RuntimeError):
        store.submit(spec, _payload(spec, state, journal_pos=30))


def test_telemetry_provider_reports_the_store():
    from consensus_specs_tpu import telemetry

    snap = telemetry.snapshot()["providers"]["persist"]
    for key in ("checkpoints_written", "checkpoints_restored",
                "corruptions", "stale_artifacts", "restore_fallbacks",
                "pruned", "size", "cap", "bytes_on_disk"):
        assert key in snap, key


def test_store_missing_candidate_is_a_miss_not_corruption(tmp_path):
    spec, state = _spec_and_state()
    store = CheckpointStore(str(tmp_path), cap=3, asynchronous=False)
    store.write_checkpoint(spec, _payload(spec, state, journal_pos=7))
    path = store.candidates()[0]
    os.unlink(path)  # out-of-band cleanup between candidates() and restore()
    with pytest.raises(CheckpointError):
        store.restore(spec, path)
    assert persist_store.stats["corruptions"] == 0
    assert persist_store.stats["stale_artifacts"] == 0
    assert store.candidates() == []  # index entry dropped
    assert not os.path.exists(path + ".corrupt")


def test_async_writer_insert_survives_a_foreign_block_rollback(tmp_path):
    """The writer thread must never record its index insert in another
    thread's open block transaction: a routine block rollback would then
    delete the entry of a checkpoint that IS durably on disk."""
    import threading

    from consensus_specs_tpu.stf import staging

    spec, state = _spec_and_state()
    store = CheckpointStore(str(tmp_path), cap=3, asynchronous=True)
    try:
        txn = staging.begin_block()  # the apply thread is mid-block
        try:
            done = threading.Event()

            def _writer():
                store.submit(spec, _payload(spec, state, journal_pos=9))
                store.flush(timeout=30.0)
                done.set()

            t = threading.Thread(target=_writer)
            t.start()
            t.join(timeout=30.0)
            assert done.is_set()
            assert store.depth() == 1
        finally:
            staging.rollback_block(txn)  # the block fails — routine
        # the durable checkpoint's index entry survives the rollback
        assert store.depth() == 1
        assert [m["journal_pos"]
                for m in store.entries().values()] == [9]
    finally:
        store.close()
