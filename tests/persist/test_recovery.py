"""Checkpoint-fast node recovery (ISSUE 14 acceptance, tier-1 scale):
the fast path restores byte-identically; truncated, bit-flipped, and
stale-ABI-tag artifacts are each detected at load, never crash the node
or taint a served state (parity held via the fallback ladder), and are
visible on the telemetry bus and in the flight recorder."""
import os

import pytest

from consensus_specs_tpu import telemetry
from consensus_specs_tpu.node import firehose, recover_node, service
from consensus_specs_tpu.persist import store as persist_store
from consensus_specs_tpu.persist.store import CheckpointStore
from consensus_specs_tpu.telemetry import recorder
from consensus_specs_tpu.testing.context import (
    default_activation_threshold,
    default_balances,
)
from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state


@pytest.fixture(autouse=True)
def _bls_off():
    from consensus_specs_tpu.crypto import bls

    prev = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = prev


_SCAFFOLD = {}


def _scaffold():
    if not _SCAFFOLD:
        from consensus_specs_tpu.specs.builder import get_spec

        spec = get_spec("phase0", "minimal")
        state = create_genesis_state(
            spec, default_balances(spec), default_activation_threshold(spec))
        corpus = firehose.build_corpus(
            spec, state, n_epochs=2, gossip_target=100)
        _SCAFFOLD["phase0"] = (spec, state, corpus)
    return _SCAFFOLD["phase0"]


def _serve(spec, state, corpus, store, max_items=None):
    service.reset_stats()
    persist_store.reset_stats()
    node = service.Node(spec, state, corpus.anchor_block,
                        checkpoint_store=store)
    for signed in corpus.chain:
        s = int(signed.message.slot)
        node.enqueue_tick(int(state.genesis_time)
                          + s * int(spec.config.SECONDS_PER_SLOT))
        node.enqueue_block(signed)
        for att in corpus.gossip.get(s - 1, ()):
            node.enqueue_attestations([att])
    last = int(corpus.chain[-1].message.slot)
    node.enqueue_tick(int(state.genesis_time)
                      + (last + 1) * int(spec.config.SECONDS_PER_SLOT))
    node.queue.close()
    node.run_apply_loop(max_items=max_items)
    return node


def _assert_byte_identical(node, recovered):
    head = bytes(node.get_head())
    assert bytes(recovered.get_head()) == head
    assert bytes(recovered.store.block_states[head].hash_tree_root()) == \
        bytes(node.store.block_states[head].hash_tree_root())
    assert recovered.store.justified_checkpoint == \
        node.store.justified_checkpoint
    assert recovered.store.finalized_checkpoint == \
        node.store.finalized_checkpoint
    assert dict(recovered.store.latest_messages) == \
        dict(node.store.latest_messages)


def test_checkpoint_fast_path_is_byte_identical_and_literal_true(tmp_path):
    spec, state, corpus = _scaffold()
    store = CheckpointStore(str(tmp_path), asynchronous=False)
    node = _serve(spec, state, corpus, store)
    assert persist_store.stats["checkpoints_written"] >= 1
    recovered = recover_node(spec, state, corpus.anchor_block, node.journal,
                             checkpoint_store=store)
    assert service.stats["checkpoint_recoveries"] == 1
    assert persist_store.stats["restore_fallbacks"] == 0
    _assert_byte_identical(node, recovered)
    # the recovered node's journal is the crashed node's full history,
    # so the literal spec replays it to the same world
    assert recovered.journal == node.journal
    ref = firehose.replay_journal_literal(
        spec, state, corpus.anchor_block, recovered._journal)
    firehose.assert_parity(spec, recovered, ref)


def test_kill_mid_serve_recovers_from_checkpoint_plus_suffix(tmp_path):
    """The crash drill: stop the loop mid-stream (max_items), recover
    off the newest checkpoint + the journal suffix, resume serving the
    remaining backlog on the recovered node, and end byte-identical to
    an uninterrupted literal replay."""
    spec, state, corpus = _scaffold()
    store = CheckpointStore(str(tmp_path), asynchronous=False)
    crashed = _serve(spec, state, corpus, store, max_items=60)
    if persist_store.stats["checkpoints_written"] == 0:
        pytest.skip("no epoch fence before the kill at this scale")
    journal = crashed.journal
    recovered = recover_node(spec, state, corpus.anchor_block, journal,
                             checkpoint_store=store)
    assert service.stats["checkpoint_recoveries"] == 1
    _assert_byte_identical(crashed, recovered)
    # drain the backlog the crashed node never applied
    while True:
        item = crashed.queue.get(timeout=0.1)
        if item is None:
            break
        recovered.queue.put(item.kind, item.payload)
    recovered.queue.close()
    recovered.run_apply_loop()
    ref = firehose.replay_journal_literal(
        spec, state, corpus.anchor_block, recovered._journal)
    firehose.assert_parity(spec, recovered, ref)


@pytest.mark.parametrize("damage", ["truncated", "bit_flipped", "stale_tag"])
def test_damaged_artifacts_degrade_and_are_visible(tmp_path, damage,
                                                   monkeypatch):
    """Each corruption shape on EVERY artifact: detected at load, never
    a crash, never a wrong state (full-replay fallback parity), and
    visible on the bus + in the flight recorder."""
    spec, state, corpus = _scaffold()
    store = CheckpointStore(str(tmp_path), asynchronous=False)
    node = _serve(spec, state, corpus, store)
    paths = store.candidates()
    assert paths
    if damage == "stale_tag":
        monkeypatch.setattr(persist_store, "FORMAT_TAG", "ckpt-v999")
    else:
        for path in paths:
            data = open(path, "rb").read()
            with open(path, "wb") as f:
                if damage == "truncated":
                    f.write(data[: len(data) // 3])
                else:
                    f.write(data[:64] + bytes([data[64] ^ 0x01])
                            + data[65:])
    was_recording = recorder.enabled()
    recorder.reset()
    recorder.enable()
    try:
        recovered = recover_node(spec, state, corpus.anchor_block,
                                 node.journal, checkpoint_store=store)
    finally:
        if not was_recording:
            recorder.disable()
    # fell back to the full journal replay, parity held
    assert service.stats["checkpoint_recoveries"] == 0
    assert persist_store.stats["restore_fallbacks"] == 1
    _assert_byte_identical(node, recovered)
    # visible on the bus...
    snap = telemetry.snapshot()["providers"]["persist"]
    if damage == "stale_tag":
        assert snap["stale_artifacts"] == len(paths)
    else:
        assert snap["corruptions"] == len(paths)
    # ...and in the flight recorder, with the evidence quarantined
    events = [e for e in recorder.timeline() if e["kind"] == "store_corrupt"]
    assert len(events) == len(paths)
    reasons = {e["reason"] for e in events}
    assert reasons == ({"stale_tag"} if damage == "stale_tag"
                       else {"corrupt"})
    assert len([p for p in os.listdir(tmp_path)
                if p.endswith(".corrupt")]) == len(paths)


def test_foreign_journal_checkpoint_is_a_stale_miss(tmp_path):
    """An intact checkpoint directory from a DIFFERENT run must not
    splice onto this journal: the trigger-token check degrades it to a
    miss and recovery replays the true history."""
    spec, state, corpus = _scaffold()
    store = CheckpointStore(str(tmp_path), asynchronous=False)
    node = _serve(spec, state, corpus, store)
    # a "foreign" journal: same length, different content ordering —
    # drop the first gossip batch and pad with a duplicate tick
    journal = node.journal
    foreign = [e for e in journal if e[0] != "attestations"]
    recovered = recover_node(spec, state, corpus.anchor_block, foreign,
                             checkpoint_store=store)
    assert service.stats["checkpoint_recoveries"] == 0
    assert persist_store.stats["restore_fallbacks"] == 1
    assert persist_store.stats["stale_artifacts"] >= 1
    assert persist_store.stats["corruptions"] == 0  # nothing quarantined
    assert store.candidates()  # the artifacts survive for THEIR journal


def test_same_slot_schedule_foreign_run_is_a_stale_miss(tmp_path):
    """The dangerous foreign-directory case: a checkpoint directory
    reused across runs on the SAME slot schedule (identical tick times)
    whose journals differ only in gossip density.  Trigger tokens alone
    would collide on a tick fence; the recorded last-block anchor pins
    (position, root) content and degrades the foreign checkpoint to a
    stale miss — recovery then honestly replays THIS journal in full."""
    spec, state, corpus = _scaffold()
    store = CheckpointStore(str(tmp_path), asynchronous=False)
    node_a = _serve(spec, state, corpus, store)
    assert persist_store.stats["checkpoints_written"] >= 1
    # run B: same anchor, same chain, same ticks — different gossip
    # density, so every journal position shifts (a VALID history the
    # fallback can replay, unlike run A's checkpoints' view of it)
    corpus_b = firehose.build_corpus(spec, state, n_epochs=2,
                                     gossip_target=40)
    node_b = _serve(spec, state, corpus_b, None)
    assert len(node_b.journal) != len(node_a.journal)
    service.reset_stats()
    persist_store.reset_stats()
    recovered = recover_node(spec, state, corpus_b.anchor_block,
                             node_b.journal, checkpoint_store=store)
    assert service.stats["checkpoint_recoveries"] == 0
    assert persist_store.stats["restore_fallbacks"] == 1
    assert persist_store.stats["stale_artifacts"] >= 1
    assert persist_store.stats["corruptions"] == 0
    _assert_byte_identical(node_b, recovered)
