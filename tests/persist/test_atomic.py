"""The atomic-artifact layer (ISSUE 14): torn-write-safe promotion,
digest verification, kind/format-tag staleness, and the quarantine
helper — the discipline every durable byte in the tree now rides."""
import os

import pytest

from consensus_specs_tpu.persist import atomic


def test_roundtrip_and_size(tmp_path):
    path = str(tmp_path / "a.bin")
    payload = os.urandom(4096)
    size = atomic.write_artifact(path, payload, "test-kind", "v1")
    assert os.path.getsize(path) == size
    assert atomic.read_artifact(path, "test-kind", "v1") == payload


def test_empty_payload_roundtrips(tmp_path):
    path = str(tmp_path / "a.bin")
    atomic.write_artifact(path, b"", "k")
    assert atomic.read_artifact(path, "k") == b""


def test_missing_file_is_a_plain_miss(tmp_path):
    with pytest.raises(atomic.ArtifactMissing):
        atomic.read_artifact(str(tmp_path / "nope.bin"), "k")


def test_truncation_is_corrupt(tmp_path):
    path = str(tmp_path / "a.bin")
    atomic.write_artifact(path, os.urandom(512), "k")
    data = open(path, "rb").read()
    for cut in (0, 3, len(data) // 2, len(data) - 1):
        with open(path, "wb") as f:
            f.write(data[:cut])
        with pytest.raises(atomic.ArtifactCorrupt):
            atomic.read_artifact(path, "k")


def test_any_flipped_byte_is_corrupt(tmp_path):
    """Header, payload, or the digest itself: one flipped bit anywhere
    fails verification — never garbage handed to the consumer."""
    path = str(tmp_path / "a.bin")
    atomic.write_artifact(path, os.urandom(256), "k", "t")
    data = open(path, "rb").read()
    for pos in (0, 5, len(data) // 2, len(data) - 1):
        with open(path, "wb") as f:
            f.write(data[:pos] + bytes([data[pos] ^ 0x40]) + data[pos + 1:])
        with pytest.raises(atomic.ArtifactError):
            atomic.read_artifact(path, "k", "t")
    with open(path, "wb") as f:
        f.write(data)  # pristine again
    atomic.read_artifact(path, "k", "t")


def test_wrong_kind_or_tag_is_stale_not_corrupt(tmp_path):
    path = str(tmp_path / "a.bin")
    atomic.write_artifact(path, b"payload", "kind-a", "tag-1")
    with pytest.raises(atomic.ArtifactStaleTag):
        atomic.read_artifact(path, "kind-b", "tag-1")
    with pytest.raises(atomic.ArtifactStaleTag):
        atomic.read_artifact(path, "kind-a", "tag-2")


def test_format_version_bump_is_stale(tmp_path, monkeypatch):
    path = str(tmp_path / "a.bin")
    atomic.write_artifact(path, b"payload", "k")
    monkeypatch.setattr(atomic, "FORMAT_VERSION", atomic.FORMAT_VERSION + 1)
    with pytest.raises(atomic.ArtifactStaleTag):
        atomic.read_artifact(path, "k")


def test_expected_payload_len_structural_check(tmp_path):
    path = str(tmp_path / "a.bin")
    atomic.write_artifact(path, b"x" * 100, "k")
    assert atomic.read_artifact(path, "k",
                                expected_payload_len=100) == b"x" * 100
    with pytest.raises(atomic.ArtifactCorrupt):
        atomic.read_artifact(path, "k", expected_payload_len=99)


def test_overwrite_promotes_atomically_no_strays(tmp_path):
    path = str(tmp_path / "a.bin")
    atomic.write_artifact(path, b"one", "k")
    atomic.write_artifact(path, b"two", "k")
    assert atomic.read_artifact(path, "k") == b"two"
    assert [p for p in os.listdir(tmp_path) if p.endswith(".tmp")] == []


def test_failed_write_leaves_previous_artifact_and_no_temp(tmp_path):
    from consensus_specs_tpu import faults

    path = str(tmp_path / "a.bin")
    atomic.write_artifact(path, b"good", "k")
    plan = faults.FaultPlan([faults.Fault("persist.replace", nth=1)])
    with faults.inject(plan):
        with pytest.raises(faults.InjectedFault):
            atomic.write_artifact(path, b"torn", "k")
    assert atomic.read_artifact(path, "k") == b"good"
    assert [p for p in os.listdir(tmp_path) if p.endswith(".tmp")] == []


def test_quarantine_moves_the_evidence_aside(tmp_path):
    path = str(tmp_path / "a.bin")
    atomic.write_artifact(path, b"damaged-later", "k")
    dest = atomic.quarantine(path)
    assert dest == path + ".corrupt"
    assert not os.path.exists(path)
    assert os.path.exists(dest)
    assert atomic.quarantine(str(tmp_path / "gone.bin")) is None


def test_verify_buffer_accepts_mmap(tmp_path):
    import mmap

    path = str(tmp_path / "a.bin")
    payload = os.urandom(8192)
    atomic.write_artifact(path, payload, "k", "t")
    with open(path, "rb") as f:
        with mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ) as mm:
            assert atomic.verify_buffer(path, mm, "k", "t") == payload
