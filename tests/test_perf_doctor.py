"""Phase-attribution regression doctor (ISSUE 11): a synthetically
regressed snapshot pair must name the injected phase as the top
contributor, telemetry drift must ride the attribution, and the CLI path
must survive missing/uncomparable snapshots."""
import json
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import perf_doctor


def _row(value, **overrides):
    row = {
        "metric": "mainnet_epoch_e2e_bls_on_400000", "value": value,
        "unit": "s",
        "sig_verify_s": 0.60, "attestation_apply_s": 0.80,
        "sync_apply_s": 0.0, "slot_roots_s": 0.57, "other_s": 0.29,
        "resolve_s": 0.12, "apply_s": 0.42, "mirror_flush_s": 0.26,
        "hash_to_g2_s": 0.29, "msm_s": 0.44, "miller_s": 0.38,
        "marshal_s": 0.27, "overlap_s": 0.85,
        "telemetry": {"plan_hit_ratio": 0.49, "memo_hit_ratio": 0.46,
                      "h2c_hit_ratio": 0.01, "overlap_ratio": 0.55,
                      "replayed_blocks": 0, "breaker_trips": 0,
                      "native_degraded": 0, "pipeline_drains": 0},
    }
    tel = overrides.pop("telemetry", None)
    row.update(overrides)
    if tel:
        row["telemetry"] = {**row["telemetry"], **tel}
    return row


def test_injected_phase_is_the_top_contributor():
    # the acceptance case: +0.9 s injected into attestation_apply_s (with
    # a matching plan-cache collapse) on a +1.1 s total regression
    prev = _row(2.38)
    cur = _row(3.48, attestation_apply_s=1.70, apply_s=1.32,
               telemetry={"plan_hit_ratio": 0.22})
    diag = perf_doctor.diagnose_row(cur, prev)
    assert diag is not None and diag["regressed"]
    top = diag["contributors"][0]
    assert top["phase"] == "attestation_apply_s"
    assert abs(top["delta_s"] - 0.90) < 1e-6
    assert 0.7 <= top["share"] <= 0.9  # ~81% of the 1.10 s move
    # the sub-phase detail names apply_s as the interior mover
    assert top["sub_phases"][0]["phase"] == "apply_s"
    # and the telemetry drift carries the WHY
    drift = {d["key"]: d for d in diag["telemetry_drift"]}
    assert drift["plan_hit_ratio"]["prev"] == 0.49
    assert drift["plan_hit_ratio"]["cur"] == 0.22


def test_attribution_line_reads_like_the_issue_example():
    prev = _row(2.38)
    cur = _row(3.48, attestation_apply_s=1.70,
               telemetry={"plan_hit_ratio": 0.22})
    line = perf_doctor.attribution_line(cur, prev)
    assert line is not None
    assert "attestation_apply_s +0.90 s" in line
    assert "of the regression" in line
    assert "plan_hit_ratio fell 0.49 -> 0.22" in line


def test_regressed_phase_outranks_a_larger_improvement():
    # a regressed run whose largest-|delta| phase actually IMPROVED must
    # still name a regressed phase as the suspect (direction-aware rank)
    prev = _row(4.60, sig_verify_s=2.0, attestation_apply_s=1.0,
                slot_roots_s=1.0)
    cur = _row(4.90, sig_verify_s=1.5, attestation_apply_s=1.4,
               slot_roots_s=1.4)
    diag = perf_doctor.diagnose_row(cur, prev)
    assert diag["regressed"]
    assert diag["contributors"][0]["phase"] in ("attestation_apply_s",
                                                "slot_roots_s")
    assert diag["contributors"][0]["delta_s"] > 0
    line = perf_doctor.attribution_line(cur, prev)
    assert "+0.40 s" in line and "sig_verify_s" not in line.split(";")[0]


def test_improvement_attributes_without_regression_claim():
    prev = _row(3.48, sig_verify_s=1.58)
    cur = _row(2.38, sig_verify_s=0.48)
    diag = perf_doctor.diagnose_row(cur, prev)
    assert not diag["regressed"]
    assert diag["contributors"][0]["phase"] == "sig_verify_s"
    line = perf_doctor.attribution_line(cur, prev)
    assert "of the regression" not in line
    # render never crashes on either direction
    assert "sig_verify_s" in perf_doctor.render(diag)


def test_not_comparable_rows_return_none():
    assert perf_doctor.diagnose_row(None, _row(2.0)) is None
    assert perf_doctor.diagnose_row(_row(2.0), {"error": "x"}) is None
    other = _row(2.0, metric="mainnet_epoch_e2e_bls_on_1048576")
    assert perf_doctor.diagnose_row(_row(3.0), other) is None
    # a row with no phase keys (pre-PR-2 shape) is not attributable
    bare = {"metric": "m", "value": 2.0}
    assert not perf_doctor.is_e2e_row(bare)
    assert perf_doctor.diagnose_row(bare, bare) is None
    assert perf_doctor.attribution_line(_row(3.0), other) is None


def test_counter_appearance_is_drift():
    prev = _row(2.38)
    cur = _row(2.90, other_s=0.81,
               telemetry={"replayed_blocks": 3, "pipeline_drains": 3})
    diag = perf_doctor.diagnose_row(cur, prev)
    keys = {d["key"] for d in diag["telemetry_drift"]}
    assert {"replayed_blocks", "pipeline_drains"} <= keys


def test_histogram_tail_shifts_are_reported():
    prev = _row(2.38, phase_histograms={
        "slot_roots": {"count": 32, "p50_ms": 15.0, "p99_ms": 30.0}})
    cur = _row(2.50, phase_histograms={
        "slot_roots": {"count": 32, "p50_ms": 15.0, "p99_ms": 80.0}})
    diag = perf_doctor.diagnose_row(cur, prev)
    assert diag["histogram_shifts"] == [
        {"phase": "slot_roots", "prev_p99_ms": 30.0, "cur_p99_ms": 80.0}]
    assert "p99" in perf_doctor.render(diag)


def test_cli_on_snapshot_files(tmp_path, capsys):
    cur = {"epoch_e2e_bls": _row(3.48, attestation_apply_s=1.70),
           "unrelated": {"metric": "x", "value": 1}}
    prev = {"epoch_e2e_bls": _row(2.38)}
    a, b = tmp_path / "cur.json", tmp_path / "prev.json"
    a.write_text(json.dumps(cur))
    b.write_text(json.dumps(prev))
    assert perf_doctor.main([str(a), str(b)]) == 0
    out = capsys.readouterr().out
    assert "attestation_apply_s" in out and "REGRESSED" in out
    assert "verdict:" in out


def test_cli_single_arg_is_an_error(capsys):
    assert perf_doctor.main(["only-one.json"]) == 2


def test_newest_snapshot_pair_prefers_prev_file(tmp_path):
    (tmp_path / "BENCH_DETAILS.json").write_text(
        json.dumps({"epoch_e2e_bls": _row(3.0)}))
    (tmp_path / "BENCH_DETAILS_PREV.json").write_text(
        json.dumps({"epoch_e2e_bls": _row(2.0)}))
    cur, prev, label = perf_doctor.newest_snapshot_pair(str(tmp_path))
    assert label == "BENCH_DETAILS_PREV.json"
    assert prev["epoch_e2e_bls"]["value"] == 2.0


def test_newest_snapshot_pair_falls_back_to_git_history():
    # the live repo: BENCH_DETAILS.json has committed history, so the
    # fallback finds a differing previous version (or a PREV file once
    # bench has run) — either way the pair is comparable
    cur, prev, label = perf_doctor.newest_snapshot_pair()
    assert isinstance(cur, dict)
    if prev is not None:
        assert label in ("BENCH_DETAILS_PREV.json", "git history")
        assert isinstance(prev, dict)
