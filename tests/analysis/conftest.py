"""Make the analyzer package importable the same way tools/lint.py does
(the repo is not an installed distribution; tools/ rides on sys.path)."""
import sys
from pathlib import Path

TOOLS = Path(__file__).resolve().parent.parent.parent / "tools"
if str(TOOLS) not in sys.path:
    sys.path.insert(0, str(TOOLS))
