"""TH01 thread-role dataflow: registered shared structures demand their
lock, role-confined structures reject foreign roles (with the
propagation chain named), undeclared globals may not be mutated from
spawned-role code, and every spawn site maps to a declared role
(ISSUE 15)."""
import pytest

from analysis import analyze_text
from analysis import concurrency_registry as creg
from analysis.concurrency_registry import LockSpec, RoleSeed, SharedSpec
from analysis.dataflow import build_project

MOD = "consensus_specs_tpu.stf.x"
PATH = "consensus_specs_tpu/stf/x.py"


@pytest.fixture
def registry(monkeypatch):
    """A minimal fixture registry: one lock-guarded global, one
    instance-attr structure sharing a condition alias, one confined
    structure with an entry point, one declared worker role, one seam."""
    monkeypatch.setattr(creg, "LOCKS", (
        LockSpec("x lock", MOD, frozenset({"_LOCK"})),
        LockSpec("box lock", MOD,
                 frozenset({"Box._lock", "Box._not_full", "Box._guard"})),
    ))
    monkeypatch.setattr(creg, "SHARED", (
        SharedSpec("x table", MOD, module_globals=frozenset({"_TABLE"}),
                   lock="x lock", lock_holders=frozenset({"_table_put"})),
        SharedSpec("box items", MOD,
                   instance_attrs=frozenset({"Box._items"}),
                   lock="box lock"),
        SharedSpec("x journal", MOD, module_globals=frozenset({"_JOURNAL"}),
                   entrypoints=frozenset({f"{MOD}.journal_append"})),
    ))
    monkeypatch.setattr(creg, "ROLE_SEEDS", (
        RoleSeed(f"{MOD}.run_worker", "producer", "fixture worker"),
        RoleSeed(f"{MOD}.Box.run", "pipeline-worker", "fixture method"),
    ))
    monkeypatch.setattr(creg, "HANDOFF_SEAMS",
                        frozenset({f"{MOD}.enqueue"}))


def th01(path, src, project=None):
    return [f for f in analyze_text(path, src, project=project)
            if f.code == "TH01"]


def check(src, project=None):
    return th01(PATH, src, project=project)


# -- lock-guarded structures ---------------------------------------------------

def test_unguarded_write_to_registered_global_flagged(registry):
    src = ("import threading\n"
           "_LOCK = threading.Lock()\n"
           "_TABLE = {}\n"
           "def put(k, v):\n"
           "    _TABLE[k] = v\n")
    found = check(src)
    assert [f.line for f in found] == [5]
    assert "x table" in found[0].message and "_LOCK" in found[0].message


def test_with_lock_guards_the_write(registry):
    src = ("import threading\n"
           "_LOCK = threading.Lock()\n"
           "_TABLE = {}\n"
           "def put(k, v):\n"
           "    with _LOCK:\n"
           "        _TABLE[k] = v\n")
    assert check(src) == []


def test_condition_alias_spelling_guards_instance_attr(registry):
    # _not_full is a Condition sharing _lock: ONE registered identity
    src = ("import threading\n"
           "class Box:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._not_full = threading.Condition(self._lock)\n"
           "        self._items = []\n"
           "    def put(self, v):\n"
           "        with self._not_full:\n"
           "            self._items.append(v)\n"
           "    def bad_put(self, v):\n"
           "        self._items.append(v)\n")
    found = check(src)
    assert [f.line for f in found] == [11]
    assert "box items" in found[0].message


def test_init_constructs_unshared_and_lock_holders_pardoned(registry):
    # __init__ writes before the object is shared; _table_put is the
    # registered caller-holds-lock helper
    src = ("import threading\n"
           "_LOCK = threading.Lock()\n"
           "_TABLE = {}\n"
           "def _table_put(k, v):\n"
           "    _TABLE[k] = v\n"
           "class Box:\n"
           "    def __init__(self):\n"
           "        self._items = []\n")
    assert check(src) == []


def test_removal_also_races(registry):
    # unlike CC01, pop/clear are concurrency mutations too
    src = ("import threading\n"
           "_LOCK = threading.Lock()\n"
           "_TABLE = {}\n"
           "def drop(k):\n"
           "    _TABLE.pop(k, None)\n")
    assert [f.line for f in check(src)] == [5]


def test_closure_under_outer_with_is_not_guarded(registry):
    # a callback DEFINED inside `with _LOCK:` runs later, without the
    # lock: the guard walk must stop at the def boundary
    src = ("import threading\n"
           "_LOCK = threading.Lock()\n"
           "_TABLE = {}\n"
           "def register(bus):\n"
           "    with _LOCK:\n"
           "        def cb(k, v):\n"
           "            _TABLE[k] = v\n"
           "        bus.subscribe(cb)\n")
    found = check(src)
    assert [f.line for f in found] == [7]
    assert "x table" in found[0].message


def test_init_pardon_covers_only_self_attrs(registry):
    # constructors may run on any thread: a registered module global
    # written in __init__ stays checked (only self/cls attrs pardon)
    src = ("import threading\n"
           "_LOCK = threading.Lock()\n"
           "_TABLE = {}\n"
           "class Box:\n"
           "    def __init__(self, k, v):\n"
           "        _TABLE[k] = v\n")
    found = check(src)
    assert [f.line for f in found] == [6]
    assert "x table" in found[0].message


def test_module_alias_lock_spelling_guards_cross_file_write(registry):
    # the owner's registered lock held through a module alias: a
    # correctly-guarded foreign-file write must not be flagged
    other_path = "consensus_specs_tpu/node/z.py"
    other_src = ("from consensus_specs_tpu.stf import x\n"
                 "def put(k, v):\n"
                 "    with x._LOCK:\n"
                 "        x._TABLE[k] = v\n"
                 "def bad_put(k, v):\n"
                 "    x._TABLE[k] = v\n")
    found = th01(other_path, other_src)
    assert [f.line for f in found] == [6]


# -- role confinement + propagation --------------------------------------------

_WORKER_HEADER = ("import threading\n"
                  "_JOURNAL = []\n"
                  "def journal_append(entry):\n"
                  "    _JOURNAL.append(entry)\n"
                  "def enqueue(item):\n"
                  "    pass\n")


def test_confined_entrypoint_from_foreign_role_names_chain(registry):
    src = _WORKER_HEADER + (
        "def helper(entry):\n"
        "    journal_append(entry)\n"
        "def run_worker():\n"
        "    helper(1)\n"
        "def spawn():\n"
        "    threading.Thread(target=run_worker).start()\n")
    found = check(src)
    assert len(found) == 1
    assert found[0].line == 8
    assert "producer" in found[0].message
    assert "run_worker -> stf.x.helper" in found[0].message


def test_role_propagates_through_partial(registry):
    src = _WORKER_HEADER + (
        "from functools import partial\n"
        "def run_worker(q):\n"
        "    journal_append(q)\n"
        "def spawn(q):\n"
        "    threading.Thread(target=partial(run_worker, q)).start()\n")
    found = check(src)
    assert [f.line for f in found] == [9]
    assert "producer" in found[0].message


def test_role_propagates_through_method_refs(registry):
    # pool.submit(self.run) seeds the declared pipeline-worker role on
    # the method; its self-call chain carries the role to the write
    src = _WORKER_HEADER + (
        "class Box:\n"
        "    def start(self, pool):\n"
        "        pool.submit(self.run)\n"
        "    def run(self):\n"
        "        self._emit()\n"
        "    def _emit(self):\n"
        "        journal_append(1)\n")
    found = check(src)
    assert [f.line for f in found] == [13]
    assert "pipeline-worker" in found[0].message
    assert "Box.run -> " in found[0].message


def test_handoff_seam_is_sanctioned(registry):
    src = _WORKER_HEADER + (
        "def run_worker(item):\n"
        "    enqueue(item)\n"
        "def spawn(item):\n"
        "    threading.Thread(target=run_worker, args=(item,)).start()\n")
    assert check(src) == []


def test_confined_write_from_foreign_role_flagged(registry):
    src = _WORKER_HEADER + (
        "def run_worker(entry):\n"
        "    _JOURNAL.append(entry)\n")
    found = check(src)
    assert [f.line for f in found] == [8]
    assert "role-confined" in found[0].message


def test_role_propagates_from_nested_spawn_target(registry):
    # the live firehose/adversary producers are NESTED defs inside
    # their runner: the seed must not be a dead end — its calls carry
    # the role onward (the code-review soundness hole, pinned)
    src = _WORKER_HEADER + (
        "def helper(entry):\n"
        "    journal_append(entry)\n"
        "def run_all():\n"
        "    def run_worker():\n"
        "        helper(1)\n"
        "    threading.Thread(target=run_worker).start()\n")
    found = check(src)
    assert [f.line for f in found] == [8]
    assert "producer" in found[0].message
    assert "run_worker -> stf.x.helper" in found[0].message


def test_lock_holder_pardon_is_module_qualified(registry):
    # a same-named function in a FOREIGN module earns no lock-holder
    # exemption; only the owner module's documented helper does
    other_path = "consensus_specs_tpu/node/y.py"
    other_src = ("from consensus_specs_tpu.stf import x\n"
                 "def _table_put(k, v):\n"
                 "    x._TABLE[k] = v\n")
    found = th01(other_path, other_src)
    assert [f.line for f in found] == [3]
    assert "x table" in found[0].message


def test_cross_file_role_propagation(registry):
    # the spawn seam lives a file away from the write it taints
    spawn_path = "consensus_specs_tpu/node/spawn.py"
    spawn_src = ("import threading\n"
                 "from consensus_specs_tpu.stf.x import run_worker\n"
                 "def launch():\n"
                 "    threading.Thread(target=run_worker).start()\n")
    x_src = _WORKER_HEADER + ("def run_worker():\n"
                              "    journal_append(1)\n")
    proj = build_project({spawn_path: spawn_src, PATH: x_src})
    found = th01(PATH, x_src, project=proj)
    assert [f.line for f in found] == [8]
    assert "producer" in found[0].message


# -- undeclared shared state ---------------------------------------------------

def test_undeclared_global_mutated_in_spawned_role_flagged(registry):
    src = _WORKER_HEADER + (
        "_NEST = []\n"
        "def run_worker(name):\n"
        "    stack = _NEST\n"
        "    stack.append(name)\n")
    found = check(src)
    assert [f.line for f in found] == [10]
    assert "_NEST" in found[0].message and "producer" in found[0].message


def test_undeclared_global_under_a_lock_is_tolerated(registry):
    src = _WORKER_HEADER + (
        "_NEST = []\n"
        "_L = threading.Lock()\n"
        "def run_worker(name):\n"
        "    with _L:\n"
        "        _NEST.append(name)\n")
    assert check(src) == []


def test_locals_and_main_only_globals_are_not_flagged(registry):
    src = _WORKER_HEADER + (
        "_MAIN_ONLY = []\n"
        "def run_worker(name):\n"
        "    mine = []\n"
        "    mine.append(name)\n"
        "def main_path(name):\n"
        "    _MAIN_ONLY.append(name)\n")
    assert check(src) == []


# -- spawn-site completeness ---------------------------------------------------

def test_spawn_target_without_declared_role_flagged(registry):
    src = ("import threading\n"
           "def orphan_worker():\n"
           "    pass\n"
           "def spawn():\n"
           "    threading.Thread(target=orphan_worker).start()\n")
    found = check(src)
    assert [f.line for f in found] == [5]
    assert "no declared role" in found[0].message


def test_unresolvable_spawn_target_flagged(registry):
    src = ("import threading\n"
           "def spawn(fn):\n"
           "    threading.Thread(target=fn()).start()\n")
    found = check(src)
    assert [f.line for f in found] == [3]
    assert "cannot resolve" in found[0].message


# -- escapes -------------------------------------------------------------------

def test_thread_safe_annotation_sanctions_with_justification(registry):
    src = ("import threading\n"
           "_LOCK = threading.Lock()\n"
           "_TABLE = {}\n"
           "def put(k, v):\n"
           "    # thread-safe: single-writer by documented contract\n"
           "    _TABLE[k] = v\n"
           "def put2(k, v):\n"
           "    _TABLE[k] = v  # thread-safe: ditto, trailing form\n")
    assert check(src) == []


def test_bare_annotation_does_not_sanction(registry):
    src = ("import threading\n"
           "_LOCK = threading.Lock()\n"
           "_TABLE = {}\n"
           "def put(k, v):\n"
           "    _TABLE[k] = v  # thread-safe:\n")
    assert [f.line for f in check(src)] == [5]


def test_noqa_suppresses(registry):
    src = ("import threading\n"
           "_LOCK = threading.Lock()\n"
           "_TABLE = {}\n"
           "def put(k, v):\n"
           "    _TABLE[k] = v  # noqa: TH01\n")
    assert check(src) == []


def test_tests_and_specs_are_exempt(registry):
    src = ("import threading\n"
           "_TABLE = {}\n"
           "def put(k, v):\n"
           "    _TABLE[k] = v\n")
    assert th01("tests/test_x.py", src) == []
    assert th01("consensus_specs_tpu/specs/src/x.py", src) == []
