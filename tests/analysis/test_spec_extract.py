"""Unit tests for the spec-source extraction pass (SP01–SP03 substrate):
AST-normalized digests, raise-site facts, fork-chain layering, and the
bare-name reachability walk."""

from analysis import spec_extract

P0 = spec_extract.fork_display("phase0")
AL = spec_extract.fork_display("altair")
BE = spec_extract.fork_display("bellatrix")
CA = spec_extract.fork_display("capella")
SSZ = spec_extract.fork_display("ssz")


def _snap(phase0, altair="", bellatrix="", capella="", ssz=""):
    return spec_extract.snapshot({
        P0: phase0, AL: altair, BE: bellatrix, CA: capella, SSZ: ssz})


def test_digest_ignores_comments_docstrings_and_whitespace():
    a = _snap("def f(x):\n    return x + 1\n")
    b = _snap(
        "# leading comment\n"
        "def f(x):\n"
        '    """docstring."""\n'
        "    # inline comment\n"
        "    return x + 1\n"
    )
    fa, fb = a.get("phase0", "f"), b.get("phase0", "f")
    assert fa is not None and fb is not None
    assert fa.digest == fb.digest
    assert fa.raise_digest == fb.raise_digest


def test_digest_changes_on_semantic_edit():
    a = _snap("def f(x):\n    return x + 1\n")
    b = _snap("def f(x):\n    return x + 2\n")
    assert a.get("phase0", "f").digest != b.get("phase0", "f").digest


def test_raise_sites_are_ordered_and_digested():
    snap = _snap(
        "def f(x):\n"
        "    assert x > 0\n"
        "    if x > 9:\n"
        "        raise ValueError('big')\n"
        "    assert x < 5, 'small'\n"
    )
    fn = snap.get("phase0", "f")
    assert fn.raise_count == 3
    kinds = [s.kind for s in fn.raise_sites]
    assert kinds == ["assert", "raise", "assert"]
    assert fn.raise_sites[0].source == "assert x > 0"
    # the raise digest covers conditions, not line numbers: shifting the
    # function down a line keeps it stable
    shifted = _snap(
        "# shim\n"
        "def f(x):\n"
        "    assert x > 0\n"
        "    if x > 9:\n"
        "        raise ValueError('big')\n"
        "    assert x < 5, 'small'\n"
    )
    assert shifted.get("phase0", "f").raise_digest == fn.raise_digest
    # ...while editing one condition moves it
    edited = _snap(
        "def f(x):\n"
        "    assert x >= 0\n"
        "    if x > 9:\n"
        "        raise ValueError('big')\n"
        "    assert x < 5, 'small'\n"
    )
    assert edited.get("phase0", "f").raise_digest != fn.raise_digest


def test_fork_chain_layering_latest_definition_wins():
    snap = _snap(
        phase0="def f():\n    return 0\n\ndef g():\n    return f()\n",
        altair="def f():\n    return 1\n",
    )
    assert snap.get("phase0", "f").fork == "phase0"
    assert snap.get("altair", "f").fork == "altair"
    assert snap.get("bellatrix", "f").fork == "altair"  # inherited
    # unredefined names flow through the whole chain
    assert snap.get("capella", "g").fork == "phase0"
    # per-fork digests differ exactly when the effective defs differ
    assert snap.fork_digests["phase0"] != snap.fork_digests["altair"]
    assert snap.fork_digests["altair"] == snap.fork_digests["bellatrix"]


def test_missing_source_is_recorded_not_fatal():
    snap = spec_extract.snapshot({P0: "def f():\n    return 0\n", AL: None,
                                  BE: None, CA: None, SSZ: None})
    assert AL in snap.missing
    assert snap.get("altair", "f") is not None  # phase0 layer still applies


def test_reachable_walks_bare_name_calls_only():
    snap = _snap(
        "def process_a():\n    helper()\n"
        "def helper():\n    return 1\n"
        "def process_b():\n    spec.process_a()\n"  # attribute call: opaque
        "def orphan():\n    return 2\n"
        "def entry():\n    process_a()\n    process_b()\n"
    )
    seen = spec_extract.reachable(snap, "phase0", ("entry",))
    assert set(seen) == {"entry", "process_a", "process_b", "helper"}
    assert "orphan" not in seen


def test_live_spec_sources_extract_cleanly():
    from analysis import REPO_ROOT

    texts = {d: (REPO_ROOT / d).read_text()
             for d in spec_extract.spec_source_displays()}
    snap = spec_extract.snapshot(texts)
    assert snap.missing == ()
    assert set(snap.fork_digests) == {
        "phase0", "altair", "bellatrix", "capella", "ssz"}
    st = snap.get("phase0", "state_transition")
    assert st is not None and st.raise_count >= 1
    reach = spec_extract.reachable(snap, "phase0", ("state_transition",))
    assert "process_block_header" in reach
