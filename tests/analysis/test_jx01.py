"""JX01 jit purity: traced functions must not print, mutate module state,
or write in place into traced arguments."""
from analysis import analyze_text


def jx01(path, src):
    return [f for f in analyze_text(path, src) if f.code == "JX01"]


_DECORATED = """\
import jax

STATS = {"calls": 0}

@jax.jit
def bad(x):
    print("tracing")        # trace-time only
    STATS["calls"] += 1     # module-state mutation
    x[0] = 1                # in-place write on a tracer
    return x
"""

_WRAPPED = """\
import jax

def kernel(buf, v):
    buf.fill(v)
    return buf

_jit_kernel = jax.jit(kernel)
"""

_PARTIAL = """\
import jax
from functools import partial

@partial(jax.jit, static_argnums=0)
def bad(n, arr):
    global TOTAL
    TOTAL = n
    return arr
"""

_SHARD_MAP = """\
import jax
from jax.experimental.shard_map import shard_map

def step(x):
    x[:] = 0
    return x

fn = jax.jit(shard_map(step, mesh=None, in_specs=None, out_specs=None))
"""

_ALIASED_IMPORT = """\
from jax import jit as J

@J
def bad(x):
    print(x)
    return x
"""

_PURE = """\
import jax
import jax.numpy as jnp

@jax.jit
def good(x, known):
    pool = jnp.zeros((4, 8))
    pool = jax.lax.dynamic_update_slice(pool, known, (0, 0))
    y = x.at[0].set(5)          # functional update
    outs = []
    for i in range(3):
        outs.append(y)          # local list: fine
    table = {}
    table["k"] = y              # local dict: fine
    for row in outs:
        z = row[0]              # loop-bound name reads
    return pool, y, z

def untraced(x):
    print(x)                    # not traced: not JX01's business
    x[0] = 1
    return x
"""


def test_jx01_flags_decorated_function():
    assert [f.line for f in jx01("m.py", _DECORATED)] == [7, 8, 9]


def test_jx01_flags_function_passed_to_jit():
    assert [f.line for f in jx01("m.py", _WRAPPED)] == [4]


def test_jx01_flags_partial_jit_decorator():
    # reported at the global declaration inside the traced function
    assert [f.line for f in jx01("m.py", _PARTIAL)] == [6]


def test_jx01_flags_shard_map_target():
    assert [f.line for f in jx01("m.py", _SHARD_MAP)] == [5]


def test_jx01_resolves_import_aliases():
    assert [f.line for f in jx01("m.py", _ALIASED_IMPORT)] == [5]


def test_jx01_ignores_pure_and_untraced():
    assert jx01("m.py", _PURE) == []


def test_jx01_nested_helper_locals_are_not_module_state():
    # the canonical scan/body-function pattern: a nested helper mutating
    # its OWN locals is pure
    src = ("import jax\n"
           "@jax.jit\n"
           "def outer(x):\n"
           "    def init(n):\n"
           "        buf = {}\n"
           "        buf['a'] = n\n"
           "        return buf\n"
           "    return init(3), x\n")
    assert jx01("m.py", src) == []
