"""``tools/lint.py --explain CODE``: rule-catalog entry + annotated fix
example for every registered code, round-tripped through the CLI."""

import subprocess
import sys

import lint
from analysis.core import REGISTRY, all_rules


def test_every_registered_rule_has_catalog_material():
    all_rules()
    assert REGISTRY, "registry empty"
    for code, cls in REGISTRY.items():
        assert cls.summary.strip(), code
        assert (cls.__doc__ or "").strip(), code
        assert cls.fix_example.strip(), code


def test_explain_prints_summary_doc_and_fix(capsys):
    all_rules()
    for code in REGISTRY:
        rc = lint.main(["--explain", code])
        out = capsys.readouterr().out
        assert rc == 0, code
        assert out.startswith(f"{code}: "), out[:80]
        assert REGISTRY[code].fix_example.rstrip() in out, code


def test_explain_unknown_code_lists_registry(capsys):
    rc = lint.main(["--explain", "ZZ99"])
    out = capsys.readouterr().out
    assert rc == 2
    for code in ("SP01", "SP02", "SP03", "TH01", "E501"):
        assert code in out


def test_explain_missing_argument(capsys):
    assert lint.main(["--explain"]) == 2


def test_explain_cli_round_trip():
    # true subprocess round-trip: the documented developer invocation
    proc = subprocess.run(
        [sys.executable, "tools/lint.py", "--explain", "SP01"],
        capture_output=True, text=True, cwd=lint.Path(lint.__file__).parent.parent)
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.startswith("SP01: ")
    assert "mirror_registry" in proc.stdout
