"""Fixture tests for the hygiene rules: the legacy codes keep their exact
semantics (incl. the specs/src E501 exemption and __init__ F401
re-export exemption), plus the W605/B006 additions."""
from analysis import analyze_text


def only(path, src, code):
    return [f for f in analyze_text(path, src) if f.code == code]


# -- legacy codes -------------------------------------------------------------

def test_e501_flags_long_lines():
    src = "x = " + "'a' + " * 30 + "'end'\n"
    assert len(src.splitlines()[0]) > 120
    assert [f.line for f in only("m.py", src, "E501")] == [1]


def test_e501_exempts_spec_sources():
    src = "x = " + "'a' + " * 30 + "'end'\n"
    assert only("consensus_specs_tpu/specs/src/phase0.py", src, "E501") == []


def test_w291_trailing_whitespace_and_w191_tabs():
    src = "a = 1   \n\tb = 2\n"
    assert [f.line for f in only("m.py", src, "W291")] == [1]
    assert [f.line for f in only("m.py", src, "W191")] == [2]


def test_e999_syntax_error_single_finding():
    findings = analyze_text("m.py", "def f(:\n")
    assert [f.code for f in findings] == ["E999"]


def test_b001_bare_except():
    src = "try:\n    x = 1\nexcept:\n    pass\n"
    assert [f.line for f in only("m.py", src, "B001")] == [3]


def test_f401_unused_import_and_exemptions():
    src = "import os\nimport sys\nprint(sys.argv)\n"
    assert [f.line for f in only("m.py", src, "F401")] == [1]
    # __init__.py imports are re-exports
    assert only("pkg/__init__.py", src, "F401") == []
    # a whole-word occurrence in a string (e.g. __all__) counts as a use
    src2 = 'import os\n__all__ = ["os"]\n'
    assert only("m.py", src2, "F401") == []


# -- W605: invalid escape sequence --------------------------------------------

def test_w605_flags_invalid_escape_in_plain_string():
    src = 'pat = "\\d+"\n'  # \d is not a recognized string escape
    assert [f.line for f in only("m.py", src, "W605")] == [1]


def test_w605_ignores_raw_strings_and_valid_escapes():
    src = 'a = r"\\d+"\nb = "\\n\\t\\x41\\101"\nc = b"\\x00"\n'
    assert only("m.py", src, "W605") == []


def test_w605_bytes_reject_unicode_escapes():
    src = 'a = b"\\u1234"\n'
    assert [f.line for f in only("m.py", src, "W605")] == [1]
    assert only("m.py", 'a = "\\u1234"\n', "W605") == []


def test_w605_line_numbers_in_multiline_strings():
    src = 'doc = """line one\nbad \\q here\n"""\n'
    assert [f.line for f in only("m.py", src, "W605")] == [2]


# -- B006: mutable default argument -------------------------------------------

def test_b006_flags_mutable_defaults():
    src = ("def f(a, b=[], c={}, d=set(), *, e=dict()):\n"
           "    return a\n")
    assert len(only("m.py", src, "B006")) == 4


def test_b006_ignores_immutable_defaults():
    src = "def f(a=1, b=(), c=None, d='x', e=frozenset()):\n    return a\n"
    assert only("m.py", src, "B006") == []
