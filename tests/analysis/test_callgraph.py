"""Pass 1 + project graph: file summaries, fixed-point fact
propagation, and the dependency closure the incremental cache keys on."""
from analysis.callgraph import (FileSummary, absolutize, anchor_for,
                                module_name_for, summarize)
from analysis.dataflow import build_project

import ast


def _summ(display, src):
    return summarize(display, ast.parse(src))


def test_module_name_for():
    assert module_name_for("a/b/c.py") == "a.b.c"
    assert module_name_for("a/b/__init__.py") == "a.b"
    assert module_name_for("bench.py") == "bench"


def test_absolutize_relative_imports():
    assert absolutize(".attestations.f", "pkg.stf.sync") == \
        "pkg.stf.attestations.f"
    assert absolutize("..ops.segment.g", "pkg.stf.sync") == \
        "pkg.ops.segment.g"
    assert absolutize("numpy.sum", "pkg.stf.sync") == "numpy.sum"
    assert absolutize(None, "pkg.stf.sync") is None


def test_anchor_for_packages_absolutizes_against_the_package_itself():
    # ``from . import shuffle`` inside a/b/__init__.py means a.b.shuffle
    s = _summ("a/b/__init__.py", "from . import shuffle\n")
    assert s.imports["shuffle"] == "a.b.shuffle"
    assert anchor_for("a/b/c.py") == "a.b.c"


def test_summary_collects_imports_functions_and_flows():
    src = (
        "import numpy as np\n"
        "import pkg.ops.shuffle\n"
        "from .attestations import _fifo_put\n"
        "def wrap(balances, k):\n"
        "    total = np.sum(balances)\n"
        "    _fifo_put(CACHE, k, total)\n"
        "    return helper(k)\n"
        "def helper(k):\n"
        "    return k\n")
    s = _summ("pkg/stf/sync.py", src)
    assert s.module == "pkg.stf.sync"
    assert s.imports["_fifo_put"] == "pkg.stf.attestations._fifo_put"
    assert s.imports["pkg.ops.shuffle"] == "pkg.ops.shuffle"  # plain import
    w = s.functions["wrap"]
    assert w.params == ["balances", "k"]
    assert "pkg.stf.attestations._fifo_put" in w.calls
    assert w.return_calls == ["pkg.stf.sync.helper"]  # local fully qualified
    assert w.reduce_params == ["balances"]  # np.sum with no dtype kwarg
    assert ["pkg.stf.attestations._fifo_put", 1,
            ["k"]] in w.arg_flows
    # guarded reduction contributes no reduce fact
    s2 = _summ("pkg/stf/sync.py",
               src.replace("np.sum(balances)",
                           "np.sum(balances, dtype=np.uint64)"))
    assert s2.functions["wrap"].reduce_params == []


def test_summary_json_roundtrip():
    s = _summ("pkg/stf/sync.py",
              "import numpy as np\n"
              "def f(x):\n"
              "    return np.sum(x)\n")
    assert FileSummary.from_json(s.to_json()) == s


def test_device_residency_propagates_through_return_chains():
    files = {
        "pkg/ops/a.py": ("import jax.numpy as jnp\n"
                         "def leaf(x):\n"
                         "    return jnp.asarray(x)\n"),
        "pkg/ops/b.py": ("from pkg.ops.a import leaf\n"
                         "def mid(x):\n"
                         "    return leaf(x)\n"),
        "pkg/ops/c.py": ("from pkg.ops.b import mid\n"
                         "def top(x):\n"
                         "    return mid(x)\n"
                         "def host(x):\n"
                         "    return [mid(x)[0] * 0]\n"),
    }
    p = build_project(files)
    for key in ("pkg.ops.a.leaf", "pkg.ops.b.mid", "pkg.ops.c.top"):
        assert key in p.device_fns, key
    assert "pkg.ops.c.host" not in p.device_fns  # list wrap: not a view
    assert p.returns_device("pkg/ops/c.py", "mid")
    assert p.returns_device("pkg/ops/c.py", "jax.device_put")
    assert not p.returns_device("pkg/ops/c.py", "jax.device_count")


def test_producer_passthrough_is_tracked_across_files():
    files = {
        "consensus_specs_tpu/ops/epoch_jax.py": (
            "_COLS_CACHE = {}\n"
            "def registry_columns(spec, state):\n"
            "    return _COLS_CACHE.setdefault(id(state), {})\n"),
        "consensus_specs_tpu/ops/view.py": (
            "from consensus_specs_tpu.ops.epoch_jax import registry_columns\n"
            "def cols_view(spec, state):\n"
            "    return registry_columns(spec, state)\n"),
    }
    p = build_project(files)
    assert p.producer_behind(
        "consensus_specs_tpu/ops/view.py", "cols_view") == \
        "consensus_specs_tpu.ops.epoch_jax.registry_columns"
    assert p.producer_behind(
        "consensus_specs_tpu/ops/view.py", "unrelated") is None


def test_staging_routers_and_raw_inserters():
    files = {
        "consensus_specs_tpu/stf/helper.py": (
            "_VERIFIED_MEMO = {}\n"
            "def raw_put(k, v):\n"
            "    _VERIFIED_MEMO[k] = v\n"),
        "consensus_specs_tpu/stf/wrapper.py": (
            "from consensus_specs_tpu.stf.helper import raw_put\n"
            "def wraps(k, v):\n"
            "    raw_put(k, v)\n"),
        "consensus_specs_tpu/stf/routed.py": (
            "from consensus_specs_tpu.stf import staging\n"
            "from consensus_specs_tpu.stf.helper import raw_put\n"
            "def good(k, v):\n"
            "    staging.note_insert({}, k)\n"
            "    raw_put(k, v)\n"),
    }
    p = build_project(files)
    assert p.raw_inserts_of("consensus_specs_tpu/stf/wrapper.py",
                            "raw_put") == {"_VERIFIED_MEMO"}
    # the wrapper transitively raw-inserts; the staging router does not
    assert "consensus_specs_tpu.stf.wrapper.wraps" in p.raw_inserters
    assert p.routes_through_staging("consensus_specs_tpu/stf/routed.py",
                                    "good")
    assert "consensus_specs_tpu.stf.routed.good" not in p.raw_inserters


def test_dependencies_are_the_transitive_import_closure():
    files = {
        "pkg/a.py": "def leaf():\n    return 1\n",
        "pkg/b.py": "from pkg.a import leaf\ndef mid():\n    return leaf()\n",
        "pkg/c.py": "from pkg.b import mid\ndef top():\n    return mid()\n",
        "pkg/d.py": "def alone():\n    return 0\n",
    }
    p = build_project(files)
    assert p.dependencies("pkg/c.py") == {"pkg/a.py", "pkg/b.py"}
    assert p.dependencies("pkg/b.py") == {"pkg/a.py"}
    assert p.dependencies("pkg/a.py") == set()
    assert p.dependencies("pkg/d.py") == set()


def test_dependencies_see_plain_import_form():
    files = {
        "pkg/a.py": "def leaf():\n    return 1\n",
        "pkg/c.py": "import pkg.a\ndef top():\n    return pkg.a.leaf()\n",
    }
    p = build_project(files)
    assert p.dependencies("pkg/c.py") == {"pkg/a.py"}


def test_mesh_axes_collected_from_axis_parameter_defaults():
    files = {"consensus_specs_tpu/parallel/mesh.py": (
        "def build_mesh(devices, axis='v', *, axis_dcn='h'):\n"
        "    return (axis, axis_dcn)\n")}
    assert build_project(files).mesh_axis_names() == {"v", "h"}


def test_probe_names_and_defer_targets():
    src = ("from consensus_specs_tpu import faults\n"
           "from consensus_specs_tpu.stf import staging\n"
           "_SITE = faults.site('stf.x.y')\n"
           "def commit(k):\n"
           "    pass\n"
           "def settle(k):\n"
           "    staging.defer(commit, k)\n")
    s = _summ("consensus_specs_tpu/stf/x.py", src)
    assert s.probe_names == ["_SITE"]
    assert s.defer_targets == ["commit"]


def test_tuple_unpack_shares_the_producing_call_origin():
    # ``rewards, penalties = _jit(...)``: both names carry the origin
    import analysis.symbols as symbols

    tree = ast.parse("import jax\n"
                     "_k = jax.jit(lambda x: x)\n"
                     "def f(x):\n"
                     "    r, p = _k(x)\n"
                     "    return r, p\n")
    table = symbols.SymbolTable(tree)
    fn = tree.body[2]
    info = table.scope_info(fn)
    assert info.origin_of("r") == "_k"
    assert info.origin_of("p") == "_k"


def test_methods_summarized_with_self_calls_resolved(monkeypatch):
    from analysis import concurrency_registry as creg
    monkeypatch.setattr(creg, "LOCKS", ())
    s = _summ("consensus_specs_tpu/node/q.py",
              "import threading\n"
              "class Box:\n"
              "    def start(self, pool):\n"
              "        pool.submit(self.run)\n"
              "    def run(self):\n"
              "        self._emit()\n"
              "    def _emit(self):\n"
              "        pass\n")
    assert set(s.methods) == {"Box.start", "Box.run", "Box._emit"}
    assert "consensus_specs_tpu.node.q.Box._emit" in s.methods["Box.run"].calls
    assert s.spawn_sites == [[4, "submit",
                              "consensus_specs_tpu.node.q.Box.run"]]


def test_spawn_sites_thread_partial_and_nested(monkeypatch):
    from analysis import concurrency_registry as creg
    monkeypatch.setattr(creg, "LOCKS", ())
    s = _summ("consensus_specs_tpu/node/s.py",
              "import threading\n"
              "from functools import partial\n"
              "def run():\n"
              "    def inner():\n"
              "        pass\n"
              "    threading.Thread(target=inner).start()\n"
              "    threading.Thread(target=partial(run, 1)).start()\n")
    assert s.spawn_sites == [
        [6, "Thread", "consensus_specs_tpu.node.s.inner"],
        [7, "Thread", "consensus_specs_tpu.node.s.run"]]


def test_plain_submit_methods_are_not_spawn_sites(monkeypatch):
    # any class may name a method `submit`: only verifiable function
    # references count (the CheckpointStore.submit false-positive shape)
    from analysis import concurrency_registry as creg
    monkeypatch.setattr(creg, "LOCKS", ())
    s = _summ("consensus_specs_tpu/node/t.py",
              "import threading\n"
              "def schedule(store, spec, payload):\n"
              "    store.submit(spec, payload)\n")
    assert s.spawn_sites == []


def test_lock_edges_record_nesting_with_threading_origins(monkeypatch):
    from analysis import concurrency_registry as creg
    monkeypatch.setattr(creg, "LOCKS", ())
    s = _summ("consensus_specs_tpu/node/l.py",
              "import threading\n"
              "_A = threading.Lock()\n"
              "_B = threading.Lock()\n"
              "def f():\n"
              "    with _A:\n"
              "        with _B:\n"
              "            pass\n")
    assert s.lock_edges == [["consensus_specs_tpu.node.l:_A",
                             "consensus_specs_tpu.node.l:_B", 6]]


def test_nested_defs_summarized_and_lock_stack_resets(monkeypatch):
    from analysis import concurrency_registry as creg
    monkeypatch.setattr(creg, "LOCKS", ())
    s = _summ("consensus_specs_tpu/node/n.py",
              "import threading\n"
              "_A = threading.Lock()\n"
              "_B = threading.Lock()\n"
              "def helper():\n"
              "    pass\n"
              "def run():\n"
              "    def worker():\n"
              "        helper()\n"
              "    with _A:\n"
              "        def cb():\n"
              "            with _B:\n"
              "                pass\n")
    # nested defs join the flat module.name key space with their calls
    # qualified, so role propagation can follow them
    assert set(s.nested) == {"worker", "cb"}
    assert "consensus_specs_tpu.node.n.helper" in s.nested["worker"].calls
    # cb runs later, not under _A: no phantom cross-def lock edge
    assert s.lock_edges == []


def test_role_propagation_reaches_fixed_point(monkeypatch):
    from analysis import concurrency_registry as creg
    from analysis.concurrency_registry import RoleSeed
    monkeypatch.setattr(creg, "LOCKS", ())
    monkeypatch.setattr(creg, "ROLE_SEEDS", (
        RoleSeed("consensus_specs_tpu.a.worker", "producer", "t"),))
    proj = build_project({
        "consensus_specs_tpu/a.py": (
            "from consensus_specs_tpu.b import helper\n"
            "def worker():\n"
            "    helper()\n"),
        "consensus_specs_tpu/b.py": (
            "def helper():\n"
            "    leaf()\n"
            "def leaf():\n"
            "    pass\n")})
    assert "producer" in proj.roles.get("consensus_specs_tpu.b.leaf", {})
    chain = proj.role_chain("consensus_specs_tpu.b.leaf", "producer")
    assert chain == ["consensus_specs_tpu.a.worker",
                     "consensus_specs_tpu.b.helper",
                     "consensus_specs_tpu.b.leaf"]
    # the salt is deterministic and sensitive to the role map
    assert proj.role_salt() == proj.role_salt()
