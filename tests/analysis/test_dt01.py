"""DT01 Gwei dtype safety: numpy reductions over balance/weight arrays
need an explicit 64-bit accumulator."""
from analysis import analyze_text


def dt01(path, src, project=None):
    return [f for f in analyze_text(path, src, project=project)
            if f.code == "DT01"]


_VIOLATIONS = """\
import numpy as np

def totals(balances, weights, eff, mask, cols):
    a = np.sum(balances)                               # plain np.sum
    b = np.cumsum(weights)                             # cumsum
    c = balances.sum()                                 # method form
    d = np.sum(np.where(mask, eff, 0))                 # eff through where
    e = np.sum(np.where(mask, cols["effective_balance"], 0))  # string key
    f = np.dot(balances, weights)                      # dot
    return a, b, c, d, e, f
"""

_CLEAN = """\
import numpy as np
import jax.numpy as jnp

def totals(balances, weights, eff, mask, counts, active):
    a = np.sum(balances, dtype=np.uint64)
    b = np.cumsum(weights, dtype=np.uint64)
    c = balances.sum(dtype=np.uint64)
    d = np.sum(rewards_minus := np.where(mask, eff, 0), dtype=np.int64)
    e = np.dot(balances.astype(np.uint64), weights.astype(np.uint64))
    f = np.sum(counts)          # not a balance/weight array
    g = int(active.sum())       # bool attendance count: no hint
    h = jnp.sum(jnp.where(mask, eff, 0))  # jnp: width policy is x64 flag
    return a, b, c, d, e, f, g, h
"""


def test_dt01_flags_every_reduction_shape():
    assert [f.line for f in dt01("m.py", _VIOLATIONS)] == [4, 5, 6, 7, 8, 9]


def test_dt01_accepts_explicit_64bit_dtypes_and_skips_non_gwei():
    assert dt01("m.py", _CLEAN) == []


def test_dt01_exempts_spec_sources():
    assert dt01("consensus_specs_tpu/specs/src/phase0.py", _VIOLATIONS) == []


def test_dt01_skips_method_form_on_jax_arrays():
    # the x64 flag governs jnp arrays; only numpy receivers are flagged
    src = ("import jax.numpy as jnp\n"
           "def t(state):\n"
           "    balances = jnp.asarray(state.balances)\n"
           "    return balances.sum()\n")
    assert dt01("m.py", src) == []


def test_dt01_respects_targeted_noqa():
    src = ("import numpy as np\n"
           "def t(balances):\n"
           "    return np.sum(balances)  # noqa: DT01 (tiny fixture state)\n")
    assert dt01("m.py", src) == []


# -- the extended forms: prod / matmul / @ / narrowing casts ------------------

_EXTENDED_VIOLATIONS = """\
import numpy as np

def more(balances, weights, flags):
    a = np.prod(weights)                       # prod
    b = np.matmul(flags, balances)             # matmul
    c = flags @ balances                       # @ operator
    d = balances.astype(int)                   # platform-intp narrowing
    e = balances.astype(np.int32)              # explicit narrow
    f = np.int32(balances[0])                  # constructor cast
    g = np.array(weights, dtype=np.int32)      # narrowing dtype kwarg
    return a, b, c, d, e, f, g
"""

_EXTENDED_CLEAN = """\
import numpy as np

def more(balances, weights, flags, counts):
    a = np.prod(weights, dtype=np.uint64)
    b = np.matmul(flags.astype(np.uint64), balances.astype(np.uint64))
    c = flags.astype(np.uint64) @ balances.astype(np.uint64)
    d = balances.astype(np.uint64)
    e = int(balances[0])               # python int: unbounded, stays legal
    f = counts.astype(np.int32)        # not a balance/weight array
    g = np.prod(counts)
    return a, b, c, d, e, f, g
"""


def test_dt01_flags_extended_reduction_and_narrowing_forms():
    assert [f.line for f in dt01("m.py", _EXTENDED_VIOLATIONS)] == \
        [4, 5, 6, 7, 8, 9, 10]


def test_dt01_extended_forms_accept_64bit_remedies():
    assert dt01("m.py", _EXTENDED_CLEAN) == []


# -- interprocedural sinks (facts follow helpers across files) ----------------

def _proj(files):
    from analysis.dataflow import build_project

    return build_project(files)


_REDUCING_HELPER = ("import numpy as np\n"
                    "def total_of(values):\n"
                    "    return np.sum(values)\n")


def test_dt01_flags_callsite_feeding_an_unguarded_reducer():
    user = ("from consensus_specs_tpu.ops.helper import total_of\n"
            "def tally(balances):\n"
            "    return total_of(balances)\n")
    files = {"consensus_specs_tpu/ops/helper.py": _REDUCING_HELPER,
             "consensus_specs_tpu/stf/user.py": user}
    found = dt01("consensus_specs_tpu/stf/user.py", user,
                 project=_proj(files))
    assert [f.line for f in found] == [3]
    assert "total_of" in found[0].message
    # without the project graph the callsite carries no cross-file fact
    assert dt01("consensus_specs_tpu/stf/user.py", user) == []


def test_dt01_guarded_helper_clears_the_callsite():
    helper = _REDUCING_HELPER.replace("np.sum(values)",
                                      "np.sum(values, dtype=np.uint64)")
    user = ("from consensus_specs_tpu.ops.helper import total_of\n"
            "def tally(balances):\n"
            "    return total_of(balances)\n")
    files = {"consensus_specs_tpu/ops/helper.py": helper,
             "consensus_specs_tpu/stf/user.py": user}
    found = dt01("consensus_specs_tpu/stf/user.py", user,
                 project=_proj(files))
    assert found == []


def test_dt01_operand_cast_guarded_helper_clears_the_callsite():
    # the product-form operand-cast remedy is a guard on the summary
    # side too: a correctly written helper must not taint its callsites
    helper = ("import numpy as np\n"
              "def total_of(values, w):\n"
              "    return np.dot(values.astype(np.uint64),\n"
              "                  w.astype(np.uint64))\n")
    user = ("from consensus_specs_tpu.ops.helper import total_of\n"
            "def tally(balances, w):\n"
            "    return total_of(balances, w)\n")
    files = {"consensus_specs_tpu/ops/helper.py": helper,
             "consensus_specs_tpu/stf/user.py": user}
    assert dt01("consensus_specs_tpu/ops/helper.py", helper,
                project=_proj(files)) == []
    assert dt01("consensus_specs_tpu/stf/user.py", user,
                project=_proj(files)) == []


def test_dt01_boundary_cast_clears_the_callsite():
    # the message says "fix the callee or cast at the boundary" — the
    # cast form must actually clear the finding
    user = ("from consensus_specs_tpu.ops.helper import total_of\n"
            "import numpy as np\n"
            "def tally(balances):\n"
            "    return total_of(balances.astype(np.uint64))\n")
    files = {"consensus_specs_tpu/ops/helper.py": _REDUCING_HELPER,
             "consensus_specs_tpu/stf/user.py": user}
    assert dt01("consensus_specs_tpu/stf/user.py", user,
                project=_proj(files)) == []


def test_dt01_narrow_accumulator_reports_once():
    # one defect, one finding: the explicit-but-narrow dtype kwarg is
    # the narrowing check's finding, not also the reduction check's
    src = ("import numpy as np\n"
           "def f(balances):\n"
           "    return np.sum(balances, dtype=np.int32)\n")
    found = dt01("m.py", src)
    assert len(found) == 1 and "dtype=np.int32 narrows" in found[0].message
    method = ("import numpy as np\n"
              "def f(balances):\n"
              "    return balances.sum(dtype=np.int32)\n")
    found = dt01("m.py", method)
    assert len(found) == 1 and "narrows" in found[0].message


def test_dt01_reduction_fact_propagates_through_wrappers():
    # helper reduces; wrapper passes through; the caller three files away
    # still gets the finding
    wrapper = ("from consensus_specs_tpu.ops.helper import total_of\n"
               "def via(values):\n"
               "    return total_of(values)\n")
    user = ("from consensus_specs_tpu.ops.wrapper import via\n"
            "def tally(balances):\n"
            "    return via(balances)\n")
    files = {"consensus_specs_tpu/ops/helper.py": _REDUCING_HELPER,
             "consensus_specs_tpu/ops/wrapper.py": wrapper,
             "consensus_specs_tpu/stf/user.py": user}
    found = dt01("consensus_specs_tpu/stf/user.py", user,
                 project=_proj(files))
    assert [f.line for f in found] == [3]


def test_dt01_hinted_callee_params_stay_the_callees_finding():
    # the callee's own parameter carries the hint: the callee is flagged
    # where it reduces, and callsites are NOT double-reported
    helper = ("import numpy as np\n"
              "def total_of(balances):\n"
              "    return np.sum(balances)\n")
    user = ("from consensus_specs_tpu.ops.helper import total_of\n"
            "def tally(eff):\n"
            "    return total_of(eff)\n")
    files = {"consensus_specs_tpu/ops/helper.py": helper,
             "consensus_specs_tpu/stf/user.py": user}
    proj = _proj(files)
    assert [f.line for f in dt01("consensus_specs_tpu/ops/helper.py",
                                 helper, project=proj)] == [3]
    assert dt01("consensus_specs_tpu/stf/user.py", user, project=proj) == []


def test_dt01_gwei_residency_follows_producers_across_files():
    # the reduction site has NO lexical hint: the operand's producer is
    # known to return balance-shaped values via the call graph
    helper = ("import numpy as np\n"
              "def effective_balances(state):\n"
              "    return np.asarray(state.v)\n")
    user = ("import numpy as np\n"
            "from consensus_specs_tpu.ops.helper import effective_balances\n"
            "def tally(state):\n"
            "    cols = effective_balances(state)\n"
            "    return np.sum(cols)\n")
    files = {"consensus_specs_tpu/ops/helper.py": helper,
             "consensus_specs_tpu/stf/user.py": user}
    found = dt01("consensus_specs_tpu/stf/user.py", user,
                 project=_proj(files))
    assert [f.line for f in found] == [5]
