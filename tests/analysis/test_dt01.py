"""DT01 Gwei dtype safety: numpy reductions over balance/weight arrays
need an explicit 64-bit accumulator."""
from analysis import analyze_text


def dt01(path, src):
    return [f for f in analyze_text(path, src) if f.code == "DT01"]


_VIOLATIONS = """\
import numpy as np

def totals(balances, weights, eff, mask, cols):
    a = np.sum(balances)                               # plain np.sum
    b = np.cumsum(weights)                             # cumsum
    c = balances.sum()                                 # method form
    d = np.sum(np.where(mask, eff, 0))                 # eff through where
    e = np.sum(np.where(mask, cols["effective_balance"], 0))  # string key
    f = np.dot(balances, weights)                      # dot
    return a, b, c, d, e, f
"""

_CLEAN = """\
import numpy as np
import jax.numpy as jnp

def totals(balances, weights, eff, mask, counts, active):
    a = np.sum(balances, dtype=np.uint64)
    b = np.cumsum(weights, dtype=np.uint64)
    c = balances.sum(dtype=np.uint64)
    d = np.sum(rewards_minus := np.where(mask, eff, 0), dtype=np.int64)
    e = np.dot(balances.astype(np.uint64), weights.astype(np.uint64))
    f = np.sum(counts)          # not a balance/weight array
    g = int(active.sum())       # bool attendance count: no hint
    h = jnp.sum(jnp.where(mask, eff, 0))  # jnp: width policy is x64 flag
    return a, b, c, d, e, f, g, h
"""


def test_dt01_flags_every_reduction_shape():
    assert [f.line for f in dt01("m.py", _VIOLATIONS)] == [4, 5, 6, 7, 8, 9]


def test_dt01_accepts_explicit_64bit_dtypes_and_skips_non_gwei():
    assert dt01("m.py", _CLEAN) == []


def test_dt01_exempts_spec_sources():
    assert dt01("consensus_specs_tpu/specs/src/phase0.py", _VIOLATIONS) == []


def test_dt01_skips_method_form_on_jax_arrays():
    # the x64 flag governs jnp arrays; only numpy receivers are flagged
    src = ("import jax.numpy as jnp\n"
           "def t(state):\n"
           "    balances = jnp.asarray(state.balances)\n"
           "    return balances.sum()\n")
    assert dt01("m.py", src) == []


def test_dt01_respects_targeted_noqa():
    src = ("import numpy as np\n"
           "def t(balances):\n"
           "    return np.sum(balances)  # noqa: DT01 (tiny fixture state)\n")
    assert dt01("m.py", src) == []
