"""RB01 rollback safety: spec-state writes in stf/ stay inside the
snapshot-protected region of apply_signed_blocks."""
from analysis import analyze_text


def rb01(path, src):
    return [f for f in analyze_text(path, src) if f.code == "RB01"]


_VIOLATIONS = """\
def resolve_helper(spec, state, root):
    state.latest_block_header.state_root = root   # attribute chain write
    state.state_roots[0] = root                   # subscript write
    state.slot += 1                               # augmented assignment
    state.current_epoch_attestations.append(root) # mutating method
    state.set_backing(root)                       # backing swap
    st = state
    st.slot = 5                                   # aliased write
"""

_READS = """\
def reader(spec, state):
    snapshot = state.get_backing()
    slot = state.slot
    return snapshot, slot, len(state.validators), state.copy()
"""

_WHITELISTED = """\
def _apply_one(spec, state, signed_block, validate_result):
    pre = state.get_backing()
    state.set_backing(pre)

def _header(spec, state, block):
    state.latest_block_header = block

def _attestations_inner(spec, state, pending):
    state.current_epoch_attestations.append(pending)

    def closure():
        state.slot += 1   # nested inside a whitelisted function: protected
    closure()
"""


def test_rb01_flags_every_write_shape():
    found = rb01("consensus_specs_tpu/stf/engine.py", _VIOLATIONS)
    assert sorted(f.line for f in found) == [2, 3, 4, 5, 6, 8]


def test_rb01_ignores_reads():
    assert rb01("consensus_specs_tpu/stf/engine.py", _READS) == []


def test_rb01_whitelists_the_protected_region():
    assert rb01("consensus_specs_tpu/stf/engine.py", _WHITELISTED) == []


def test_rb01_whitelist_is_per_file():
    # _header is protected in engine.py, not in a random stf module
    assert [f.line for f in rb01(
        "consensus_specs_tpu/stf/verify.py", _WHITELISTED)] == [3, 6, 9, 12]


def test_rb01_only_applies_to_stf():
    assert rb01("consensus_specs_tpu/forkchoice/engine.py", _VIOLATIONS) == []
    assert rb01("tests/helper.py", _VIOLATIONS) == []


def test_rb01_catches_state_like_parameter_names():
    # naming the parameter `st` or `*_state` must not bypass the gate
    src = ("def sneaky(spec, st, root):\n"
           "    st.latest_block_header.state_root = root\n"
           "def sneakier(spec, pre_state):\n"
           "    pre_state.slot += 1\n"
           "def fine(cache, key, value):\n"
           "    cache[key] = value\n")
    found = rb01("consensus_specs_tpu/stf/verify.py", src)
    assert [f.line for f in found] == [2, 4]


def test_rb01_slot_roots_whitelist():
    src = ("def _process_slot(spec, state):\n"
           "    state.state_roots[0] = b'x'\n"
           "def other(spec, state):\n"
           "    state.state_roots[0] = b'x'\n")
    found = rb01("consensus_specs_tpu/stf/slot_roots.py", src)
    assert [f.line for f in found] == [4]
