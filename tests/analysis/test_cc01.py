"""CC01 cache coherence: insertions into registered memos and mutations
of producer-returned values, outside the owning module, without a paired
invalidation."""
from analysis import analyze_text


def cc01(path, src):
    return [f for f in analyze_text(path, src) if f.code == "CC01"]


_ALIAS_INSERT = """\
from consensus_specs_tpu.ops import shuffle

def warm(seed, n, perm):
    shuffle._cache[(seed, n, 90)] = perm
"""

_PRODUCER_MUTATION = """\
from consensus_specs_tpu.ops.shuffle import compute_shuffle_permutation

def corrupt(seed, n):
    perm = compute_shuffle_permutation(seed, n, 90)
    view = perm[:16]
    view[0] = 3          # derived view of the shared cached array
    perm.fill(0)         # mutating ndarray method
    return perm
"""

_PAIRED_INVALIDATION = """\
from consensus_specs_tpu.ops import shuffle
from consensus_specs_tpu.stf.attestations import reset_caches

def rebuild(seed, n, perm):
    shuffle._cache[(seed, n, 90)] = perm
    reset_caches()
"""

_READS_AND_INVALIDATIONS = """\
from consensus_specs_tpu.ops import shuffle
from consensus_specs_tpu.ops.shuffle import compute_shuffle_permutation

def fine(seed, n, engine):
    perm = compute_shuffle_permutation(seed, n, 90)
    local = perm.copy()
    local[0] = 1                  # mutating a copy is not the cache
    shuffle._cache.clear()        # full invalidation: always legal
    shuffle._cache.pop((seed, n, 90), None)
    del shuffle._cache[(seed, n, 90)]
    engine._head = None           # = None rebind IS the invalidation
    return perm[0], len(shuffle._cache)
"""

_HEAD_POKE = """\
def poke(engine, node):
    engine._head = node
"""

_MEMO_INSERT = """\
from consensus_specs_tpu.stf import verify

def fake_verified(key):
    verify._VERIFIED_MEMO[key] = True
"""


def test_cc01_flags_alias_insertion():
    assert [f.line for f in cc01("tests/helper.py", _ALIAS_INSERT)] == [4]


def test_cc01_flags_producer_value_mutation():
    lines = [f.line for f in cc01("tests/helper.py", _PRODUCER_MUTATION)]
    assert lines == [6, 7]


def test_cc01_pardons_paired_invalidation():
    assert cc01("tests/helper.py", _PAIRED_INVALIDATION) == []


def test_cc01_ignores_reads_copies_and_invalidations():
    assert cc01("tests/helper.py", _READS_AND_INVALIDATIONS) == []


def test_cc01_flags_head_cache_poke_but_not_none():
    assert [f.line for f in cc01("tests/helper.py", _HEAD_POKE)] == [2]


def test_cc01_flags_verified_memo_insertion():
    assert [f.line for f in cc01("tests/helper.py", _MEMO_INSERT)] == [4]


def test_cc01_exempts_owner_modules():
    # the same writes inside the owning module are the implementation
    owner = "consensus_specs_tpu/ops/shuffle.py"
    src = "_cache = {}\n\ndef put(k, v):\n    _cache[k] = v\n"
    assert cc01(owner, src) == []
    assert cc01("consensus_specs_tpu/forkchoice/engine.py", _HEAD_POKE) == []
    assert cc01("consensus_specs_tpu/stf/verify.py", _MEMO_INSERT) == []


def test_cc01_ignores_unrelated_functions_sharing_producer_names():
    # a local helper that merely shares a producer's name is not the cache
    src = ("def active_indices(n):\n"
           "    return list(range(n))\n"
           "def use(n):\n"
           "    idx = active_indices(n)\n"
           "    idx[0] = 5\n"
           "    return idx\n")
    assert cc01("tools/helper.py", src) == []


def test_cc01_ignores_own_class_attributes():
    # an unrelated class reusing a registered attr name writes into ITS
    # namespace, not the engines' caches
    src = ("class TreeNode:\n"
           "    def __init__(self, root):\n"
           "        self._root = root\n"
           "        self._head = None\n"
           "    def rehash(self, d):\n"
           "        self._root = d\n")
    assert cc01("tools/helper.py", src) == []


def test_cc01_respects_targeted_noqa():
    src = _ALIAS_INSERT.replace(
        "] = perm", "] = perm  # noqa: CC01 (test warms the cache)")
    assert cc01("tests/helper.py", src) == []


def test_cc01_flags_node_queue_and_journal_pokes():
    # ISSUE 12: the ingest deque and the apply journal are single-writer
    # structures — an outside append breaks back-pressure/FIFO causality
    # (queue) or fakes an applied history (journal)
    src = ("def smuggle(queue, node, item):\n"
           "    queue._items.append(item)\n"
           "    node._journal[0] = ('block', item)\n")
    found = cc01("consensus_specs_tpu/stf/helper.py", src)
    assert [f.line for f in found] == [2, 3]
    assert "node ingest queue" in found[0].message
    assert "node apply journal" in found[1].message


def test_cc01_node_owner_module_is_exempt():
    src = ("def requeue_front(self, item):\n"
           "    self._items.appendleft(item)\n")
    assert cc01("consensus_specs_tpu/node/ingest.py", src) == []


# -- ISSUE 13: node admission survival structures -----------------------------


_ORPHAN_POOL_INSERT = """\
from consensus_specs_tpu.node import admission

def inject(parent, item):
    admission._ORPHANS.setdefault(parent, []).append((0, item))
"""

_DEAD_LETTER_APPEND = """\
from consensus_specs_tpu.node import admission

def forge(record):
    admission._DEAD_LETTERS.append(record)
"""

_OWNER_SIDE_POOL = """\
_ORPHANS = {}

def _pool(parent, item):
    _ORPHANS.setdefault(parent, []).append((0, item))
"""


def test_cc01_flags_outside_orphan_pool_insert():
    found = cc01("consensus_specs_tpu/stf/x.py", _ORPHAN_POOL_INSERT)
    assert [f.line for f in found] == [4]
    assert "node orphan pool" in found[0].message


def test_cc01_flags_forged_dead_letter():
    # a producer writing its own dead letter would fake the post-mortem's
    # "every entry came from an exhausted retry" claim
    found = cc01("consensus_specs_tpu/forkchoice/x.py", _DEAD_LETTER_APPEND)
    assert [f.line for f in found] == [4]
    assert "node dead-letter ring" in found[0].message


def test_cc01_owner_module_pool_writes_are_legal():
    assert cc01("consensus_specs_tpu/node/admission.py",
                _OWNER_SIDE_POOL) == []
