"""Incremental cache: findings are keyed by content hash — editing one
file re-analyzes only that file, and a rule-source change drops the
whole cache (version digest)."""
import analysis
from analysis import run
from analysis.cachefile import AnalysisCache


def _tree(tmp_path):
    (tmp_path / "a.py").write_text("import os\n")  # F401
    (tmp_path / "b.py").write_text("x = 1\n")
    (tmp_path / "c.py").write_text("y = 2   \n")   # W291
    return tmp_path


def _run(tmp_path):
    return run([tmp_path], root=tmp_path,
               cache_path=tmp_path / "cache.json",
               baseline_path=tmp_path / "missing-baseline.json")


def test_second_run_is_fully_cached_and_identical(tmp_path):
    _tree(tmp_path)
    first = _run(tmp_path)
    assert first.cache_hits == 0 and first.n_files == 3
    second = _run(tmp_path)
    assert second.cache_hits == 3
    assert [(f.file, f.line, f.code) for f in second.findings] == \
        [(f.file, f.line, f.code) for f in first.findings]
    assert {f.code for f in second.findings} == {"F401", "W291"}


def test_editing_one_file_reanalyzes_only_it(tmp_path):
    _tree(tmp_path)
    _run(tmp_path)
    (tmp_path / "b.py").write_text("import sys\n")  # now has a finding
    third = _run(tmp_path)
    assert third.cache_hits == 2  # a.py and c.py came from the cache
    assert any(f.file == "b.py" and f.code == "F401"
               for f in third.findings)


def test_rule_subset_runs_never_poison_the_cache(tmp_path):
    import analysis
    _tree(tmp_path)
    # a subset run must not seed entries a later full run would trust
    subset = run([tmp_path], root=tmp_path,
                 cache_path=tmp_path / "cache.json",
                 baseline_path=tmp_path / "missing-baseline.json",
                 rules=analysis.all_rules(codes=["W291"]))
    assert {f.code for f in subset.findings} == {"W291"}
    full = _run(tmp_path)
    assert full.cache_hits == 0  # nothing trusted from the subset run
    assert {f.code for f in full.findings} == {"F401", "W291"}


def test_version_change_drops_cache(tmp_path):
    _tree(tmp_path)
    cache_file = tmp_path / "cache.json"
    c1 = AnalysisCache(cache_file, version="v1")
    c1.put("a.py", "sha", [])
    c1.save()
    assert AnalysisCache(cache_file, version="v1").get("a.py", "sha") == []
    assert AnalysisCache(cache_file, version="v2").get("a.py", "sha") is None


def test_overlapping_roots_do_not_double_report(tmp_path):
    _tree(tmp_path)
    result = run([tmp_path, tmp_path / "a.py"], root=tmp_path,
                 cache_path=tmp_path / "cache.json",
                 baseline_path=tmp_path / "missing-baseline.json")
    assert result.n_files == 3
    assert [f.code for f in result.findings if f.file == "a.py"] == ["F401"]


def test_analyzer_version_digests_rule_sources():
    v = analysis.runner.analyzer_version()
    assert v == analysis.runner.analyzer_version()  # deterministic
    assert len(v) == 64
