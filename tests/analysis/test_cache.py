"""Incremental cache: findings are keyed by content hash AND the shas of
the file's call-graph fan-in — editing one file re-analyzes it plus its
dependents (nothing else), override/subset runs consult the cache
read-only, and a rule-source change drops the whole cache (version
digest)."""
import analysis
from analysis import run
from analysis.cachefile import AnalysisCache


def _tree(tmp_path):
    (tmp_path / "a.py").write_text("import os\n")  # F401
    (tmp_path / "b.py").write_text("x = 1\n")
    (tmp_path / "c.py").write_text("y = 2   \n")   # W291
    return tmp_path


def _run(tmp_path):
    return run([tmp_path], root=tmp_path,
               cache_path=tmp_path / "cache.json",
               baseline_path=tmp_path / "missing-baseline.json")


def test_second_run_is_fully_cached_and_identical(tmp_path):
    _tree(tmp_path)
    first = _run(tmp_path)
    assert first.cache_hits == 0 and first.n_files == 3
    second = _run(tmp_path)
    assert second.cache_hits == 3
    assert [(f.file, f.line, f.code) for f in second.findings] == \
        [(f.file, f.line, f.code) for f in first.findings]
    assert {f.code for f in second.findings} == {"F401", "W291"}


def test_editing_one_file_reanalyzes_only_it(tmp_path):
    _tree(tmp_path)
    _run(tmp_path)
    (tmp_path / "b.py").write_text("import sys\n")  # now has a finding
    third = _run(tmp_path)
    assert third.cache_hits == 2  # a.py and c.py came from the cache
    assert any(f.file == "b.py" and f.code == "F401"
               for f in third.findings)


def test_rule_subset_runs_never_poison_the_cache(tmp_path):
    import analysis
    _tree(tmp_path)
    # a subset run must not seed entries a later full run would trust
    subset = run([tmp_path], root=tmp_path,
                 cache_path=tmp_path / "cache.json",
                 baseline_path=tmp_path / "missing-baseline.json",
                 rules=analysis.all_rules(codes=["W291"]))
    assert {f.code for f in subset.findings} == {"W291"}
    full = _run(tmp_path)
    assert full.cache_hits == 0  # nothing trusted from the subset run
    assert {f.code for f in full.findings} == {"F401", "W291"}


def _dep_tree(tmp_path):
    """helper.py <- user.py (cross-file DT01 evidence), other.py alone."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "helper.py").write_text(
        "import numpy as np\n"
        "def total_of(values):\n"
        "    return np.sum(values, dtype=np.uint64)\n")
    (pkg / "user.py").write_text(
        "from pkg.helper import total_of\n"
        "def f(balances):\n"
        "    return total_of(balances)\n")
    (pkg / "other.py").write_text("x = 1\n")
    return pkg


def test_editing_a_leaf_helper_reanalyzes_its_dependents(tmp_path):
    pkg = _dep_tree(tmp_path)
    assert _run(tmp_path).findings == []
    assert _run(tmp_path).cache_hits == 3  # warm
    # drop the guard in the LEAF: user.py's bytes are untouched, but its
    # finding set changes — the dependency digest must force the miss
    (pkg / "helper.py").write_text(
        "import numpy as np\n"
        "def total_of(values):\n"
        "    return np.sum(values)\n")
    third = _run(tmp_path)
    assert third.cache_hits == 1  # other.py alone came from the cache
    assert [(f.file, f.code) for f in third.findings] == \
        [("pkg/user.py", "DT01")]
    # and the re-derived result is itself cached
    fourth = _run(tmp_path)
    assert fourth.cache_hits == 3
    assert [(f.file, f.code) for f in fourth.findings] == \
        [("pkg/user.py", "DT01")]


def test_override_runs_consult_the_cache_for_untouched_files(tmp_path):
    pkg = _dep_tree(tmp_path)
    _run(tmp_path)  # seed
    unguarded = (pkg / "helper.py").read_text().replace(
        ", dtype=np.uint64", "")
    mutated = run([tmp_path], root=tmp_path,
                  cache_path=tmp_path / "cache.json",
                  baseline_path=tmp_path / "missing-baseline.json",
                  overrides={"pkg/helper.py": unguarded})
    # other.py came from the cache; helper.py (overridden) and user.py
    # (its dependent) re-analyzed with the hypothetical content
    assert mutated.cache_hits == 1
    assert [(f.file, f.code) for f in mutated.findings] == \
        [("pkg/user.py", "DT01")]
    # read-only: the real tree is still fully warm and clean afterwards
    after = _run(tmp_path)
    assert after.cache_hits == 3
    assert after.findings == []


def test_path_scoped_runs_keep_the_whole_project_graph(tmp_path):
    # ``python tools/lint.py <path>`` must not lose cross-file facts:
    # pass 1 widens to the default roots, pass 2 reports only the
    # requested paths — and the cache digests match a full run's
    (tmp_path / "tools").mkdir()
    (tmp_path / "tools" / "helper.py").write_text(
        "import numpy as np\n"
        "def total_of(values):\n"
        "    return np.sum(values)\n")
    (tmp_path / "tests").mkdir()
    (tmp_path / "tests" / "user.py").write_text(
        "from tools.helper import total_of\n"
        "def f(balances):\n"
        "    return total_of(balances)\n")
    scoped = run([tmp_path / "tests"], root=tmp_path,
                 cache_path=tmp_path / "cache.json",
                 baseline_path=tmp_path / "missing-baseline.json")
    assert scoped.n_files == 1  # only the requested path is reported...
    assert [(f.file, f.code) for f in scoped.findings] == \
        [("tests/user.py", "DT01")]  # ...with out-of-root callee facts
    full = run([tmp_path], root=tmp_path,
               cache_path=tmp_path / "cache.json",
               baseline_path=tmp_path / "missing-baseline.json")
    assert full.cache_hits == 1  # the scoped entry is full-run-compatible


def test_subset_runs_consult_a_warm_cache(tmp_path):
    _tree(tmp_path)
    full = _run(tmp_path)
    assert {f.code for f in full.findings} == {"F401", "W291"}
    subset = run([tmp_path], root=tmp_path,
                 cache_path=tmp_path / "cache.json",
                 baseline_path=tmp_path / "missing-baseline.json",
                 rules=analysis.all_rules(codes=["W291"]))
    assert subset.cache_hits == 3  # filtered from cached full-registry runs
    assert {f.code for f in subset.findings} == {"W291"}


def test_version_change_drops_cache(tmp_path):
    _tree(tmp_path)
    cache_file = tmp_path / "cache.json"
    c1 = AnalysisCache(cache_file, version="v1")
    c1.put_findings("a.py", "sha", "deps", [])
    c1.save()
    assert AnalysisCache(cache_file, version="v1").get_findings(
        "a.py", "sha", "deps") == []
    assert AnalysisCache(cache_file, version="v2").get_findings(
        "a.py", "sha", "deps") is None


def test_overlapping_roots_do_not_double_report(tmp_path):
    _tree(tmp_path)
    result = run([tmp_path, tmp_path / "a.py"], root=tmp_path,
                 cache_path=tmp_path / "cache.json",
                 baseline_path=tmp_path / "missing-baseline.json")
    assert result.n_files == 3
    assert [f.code for f in result.findings if f.file == "a.py"] == ["F401"]


def test_analyzer_version_digests_rule_sources():
    v = analysis.runner.analyzer_version()
    assert v == analysis.runner.analyzer_version()  # deterministic
    assert len(v) == 64


def test_role_seed_edit_rederives_role_dependents(tmp_path, monkeypatch):
    """The role-seed salt (ISSUE 15): role facts flow AGAINST import
    direction, so a spawn-seam edit must re-derive files whose bytes and
    import closure never changed — while unrelated leaf edits keep the
    warm path warm."""
    from analysis import concurrency_registry as creg
    from analysis.concurrency_registry import RoleSeed

    pkg = tmp_path / "consensus_specs_tpu"
    pkg.mkdir()
    monkeypatch.setattr(creg, "SHARED", ())
    monkeypatch.setattr(creg, "LOCKS", ())
    monkeypatch.setattr(creg, "ROLE_SEEDS", (
        RoleSeed("consensus_specs_tpu.spawn.worker", "producer", "fixture"),))
    (pkg / "helper.py").write_text(
        "_SHARED = []\n"
        "def touch(v):\n"
        "    _SHARED.append(v)\n")
    (pkg / "spawn.py").write_text(
        "import threading\n"
        "from consensus_specs_tpu.helper import touch\n"
        "def worker():\n"
        "    touch(1)\n"
        "def launch():\n"
        "    threading.Thread(target=worker).start()\n")
    (pkg / "other.py").write_text("x = 1\n")

    first = _run(tmp_path)
    # the producer role reaches helper.touch: its unguarded global is red
    assert [(f.file, f.code) for f in first.findings] == \
        [("consensus_specs_tpu/helper.py", "TH01")], first.findings
    assert _run(tmp_path).cache_hits == 3  # warm and stable

    # retire the seeded entry function: helper.py's bytes are untouched
    # and spawn.py is NOT in its import closure, but helper's roles (and
    # finding) change — the role salt must force the re-derive
    (pkg / "spawn.py").write_text(
        "from consensus_specs_tpu.helper import touch\n"
        "def direct():\n"
        "    touch(1)\n")
    third = _run(tmp_path)
    assert third.findings == []
    fourth = _run(tmp_path)
    assert fourth.cache_hits == 3

    # an edit that leaves the role map alone keeps everyone else warm
    (pkg / "other.py").write_text("x = 2\n")
    fifth = _run(tmp_path)
    assert fifth.cache_hits == 2
    assert fifth.findings == []
