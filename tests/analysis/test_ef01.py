"""EF01 effect safety: registered-cache inserts in fault-probed
functions must route through stf/staging or invalidate in try/finally —
PR 5's hand-audited transactional discipline as a machine invariant."""
from analysis import analyze_text
from analysis.dataflow import build_project


def ef01(path, src, project=None):
    return [f for f in analyze_text(path, src, project=project)
            if f.code == "EF01"]


_HEADER = ("from consensus_specs_tpu import faults\n"
           "from consensus_specs_tpu.stf import staging\n"
           "_SITE = faults.site('stf.x.probe')\n"
           "_VERIFIED_MEMO = {}\n")


def test_ef01_flags_unrouted_insert_next_to_probe():
    src = _HEADER + ("def risky(k, v):\n"
                     "    _SITE()\n"
                     "    _VERIFIED_MEMO[k] = v\n")
    found = ef01("consensus_specs_tpu/stf/x.py", src)
    assert [f.line for f in found] == [7]
    assert "strand" in found[0].message


def test_ef01_flags_update_and_setdefault_inserts():
    src = _HEADER + ("def risky(k, v):\n"
                     "    _SITE()\n"
                     "    _VERIFIED_MEMO.update({k: v})\n"
                     "    _VERIFIED_MEMO.setdefault(k, v)\n")
    assert [f.line for f in ef01("consensus_specs_tpu/stf/x.py", src)] == \
        [7, 8]


def test_ef01_note_insert_routes_the_mutation():
    src = _HEADER + ("def routed(txn, k, v):\n"
                     "    _SITE()\n"
                     "    staging.note_insert(_VERIFIED_MEMO, k)\n"
                     "    _VERIFIED_MEMO[k] = v\n")
    assert ef01("consensus_specs_tpu/stf/x.py", src) == []


def test_ef01_try_finally_invalidation_pardons():
    src = _HEADER + ("def contained(k, v):\n"
                     "    try:\n"
                     "        _VERIFIED_MEMO[k] = v\n"
                     "        _SITE()\n"
                     "    except Exception:\n"
                     "        _VERIFIED_MEMO.pop(k, None)\n"
                     "        raise\n")
    assert ef01("consensus_specs_tpu/stf/x.py", src) == []


def test_ef01_deferred_commit_functions_are_sanctioned():
    src = _HEADER + ("def commit(k, v):\n"
                     "    _SITE()\n"
                     "    _VERIFIED_MEMO[k] = v\n"
                     "def settle(txn, k, v):\n"
                     "    staging.defer(commit, k, v)\n")
    assert ef01("consensus_specs_tpu/stf/x.py", src) == []


def test_ef01_functions_without_probes_are_out_of_scope():
    src = _HEADER + ("def quiet(k, v):\n"
                     "    _VERIFIED_MEMO[k] = v\n")
    assert ef01("consensus_specs_tpu/stf/x.py", src) == []


def test_ef01_uninstrumented_modules_are_out_of_scope():
    src = ("_VERIFIED_MEMO = {}\n"
           "def risky(k, v):\n"
           "    _VERIFIED_MEMO[k] = v\n")
    assert ef01("consensus_specs_tpu/stf/x.py", src) == []


def test_ef01_follows_helper_inserts_across_files():
    helper = ("_VERIFIED_MEMO = {}\n"
              "def memo_put(k, v):\n"
              "    _VERIFIED_MEMO[k] = v\n")
    user = ("from consensus_specs_tpu import faults\n"
            "from consensus_specs_tpu.stf.helper import memo_put\n"
            "_SITE = faults.site('stf.x.probe')\n"
            "def risky(k, v):\n"
            "    _SITE()\n"
            "    memo_put(k, v)\n")
    files = {"consensus_specs_tpu/stf/helper.py": helper,
             "consensus_specs_tpu/stf/user.py": user}
    proj = build_project(files)
    found = ef01("consensus_specs_tpu/stf/user.py", user, project=proj)
    assert [f.line for f in found] == [6]
    assert "memo_put" in found[0].message


def test_ef01_staging_routed_helper_is_clean_across_files():
    helper = ("from consensus_specs_tpu.stf import staging\n"
              "_VERIFIED_MEMO = {}\n"
              "def memo_put(k, v):\n"
              "    staging.note_insert(_VERIFIED_MEMO, k)\n"
              "    _VERIFIED_MEMO[k] = v\n")
    user = ("from consensus_specs_tpu import faults\n"
            "from consensus_specs_tpu.stf.helper import memo_put\n"
            "_SITE = faults.site('stf.x.probe')\n"
            "def risky(k, v):\n"
            "    _SITE()\n"
            "    memo_put(k, v)\n")
    files = {"consensus_specs_tpu/stf/helper.py": helper,
             "consensus_specs_tpu/stf/user.py": user}
    proj = build_project(files)
    assert ef01("consensus_specs_tpu/stf/user.py", user, project=proj) == []


def test_ef01_speculated_memo_commit_outside_defer_stays_red():
    """ISSUE 10: the overlapped pipeline's verdict path must commit a
    speculated batch's triples THROUGH the block transaction
    (staging.defer -> commit_block), never directly — a direct insert at
    the drain seam would land keys for a block that may still roll back.
    The pipeline-shaped fixture below (probe at the drain, insert after
    the verdict) is exactly that bug, and EF01 keeps it gate-red."""
    src = ("from consensus_specs_tpu import faults\n"
           "from consensus_specs_tpu.stf import staging\n"
           "_SITE_DRAIN = faults.site('stf.x.drain')\n"
           "_VERIFIED_MEMO = {}\n"
           "def finish_speculation(handle, keys):\n"
           "    _SITE_DRAIN()\n"
           "    bad = handle.result()\n"
           "    if bad is None:\n"
           "        for k in keys:\n"
           "            _VERIFIED_MEMO[k] = True\n"
           "    return bad\n")
    found = ef01("consensus_specs_tpu/stf/x.py", src)
    assert [f.line for f in found] == [10]
    assert "strand" in found[0].message


def test_ef01_speculated_commit_through_defer_is_sanctioned():
    """The shipping shape: the drain path stages the commit with
    staging.defer and the deferred function inserts at settlement —
    clean, exactly like verify.stage_commit -> _commit_keys."""
    src = ("from consensus_specs_tpu import faults\n"
           "from consensus_specs_tpu.stf import staging\n"
           "_SITE_DRAIN = faults.site('stf.x.drain')\n"
           "_VERIFIED_MEMO = {}\n"
           "def _commit(keys):\n"
           "    _SITE_DRAIN()\n"
           "    for k in keys:\n"
           "        _VERIFIED_MEMO[k] = True\n"
           "def finish_speculation(handle, keys):\n"
           "    _SITE_DRAIN()\n"
           "    if handle.result() is None:\n"
           "        staging.defer(_commit, keys)\n")
    assert ef01("consensus_specs_tpu/stf/x.py", src) == []


# -- ISSUE 13: admission-pool inserts next to the node fault probes -----------


_NODE_HEADER = ("from consensus_specs_tpu import faults\n"
                "from consensus_specs_tpu.stf import staging\n"
                "_SITE = faults.site('node.x.probe')\n"
                "_ORPHANS = {}\n")


def test_ef01_flags_unrouted_orphan_pool_insert_next_to_probe():
    src = _NODE_HEADER + ("def pool(parent, item):\n"
                          "    _SITE()\n"
                          "    _ORPHANS[parent] = [item]\n")
    found = ef01("consensus_specs_tpu/node/x.py", src)
    assert [f.line for f in found] == [7]
    assert "strand" in found[0].message


def test_ef01_orphan_insert_with_handler_invalidation_is_clean():
    # the live admission.py shape: the insert carries its own undo
    src = _NODE_HEADER + ("def pool(parent, item):\n"
                          "    _SITE()\n"
                          "    try:\n"
                          "        _ORPHANS[parent] = [item]\n"
                          "    except BaseException:\n"
                          "        _ORPHANS.pop(parent, None)\n"
                          "        raise\n")
    assert ef01("consensus_specs_tpu/node/x.py", src) == []


def test_ef01_admission_side_tables_are_observational():
    # a stranded seen-key/parking entry is self-healing (re-admission
    # skips dedup; parking decays on the clock): EF01 skips them
    src = ("from consensus_specs_tpu import faults\n"
           "_SITE = faults.site('node.x.probe')\n"
           "_SEEN = {}\n"
           "def mark(key):\n"
           "    _SITE()\n"
           "    _SEEN[key] = True\n")
    assert ef01("consensus_specs_tpu/node/x.py", src) == []


def test_ef01_persist_index_unrouted_insert_is_flagged():
    # the durable checkpoint index (ISSUE 14) rides the same registry:
    # an insert next to a probe without staging routing is gate-red
    src = ("from consensus_specs_tpu import faults\n"
           "from consensus_specs_tpu.stf import staging\n"
           "_SITE = faults.site('persist.x.probe')\n"
           "_INDEX = {}\n"
           "def adopt(path, meta):\n"
           "    _SITE()\n"
           "    _INDEX[path] = meta\n")
    found = ef01("consensus_specs_tpu/persist/x.py", src)
    assert [f.line for f in found] == [7]
    assert "_INDEX" in found[0].message


def test_ef01_persist_index_routed_insert_is_clean():
    src = ("from consensus_specs_tpu import faults\n"
           "from consensus_specs_tpu.stf import staging\n"
           "_SITE = faults.site('persist.x.probe')\n"
           "_INDEX = {}\n"
           "def adopt(path, meta):\n"
           "    _SITE()\n"
           "    _INDEX[path] = meta\n"
           "    staging.note_insert(_INDEX, path)\n")
    assert ef01("consensus_specs_tpu/persist/x.py", src) == []
