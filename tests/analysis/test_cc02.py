"""CC02 key coverage: registered-memo lookups whose key omits a
parameter the cached computation reads — fixtures for the canonical memo
shape, the put-helper form, coverage through derived locals, and the
skip conditions (no insertion in scope, non-owner files, builder-form
RootKeyedCache gets)."""
from analysis import REPO_ROOT, analyze_text, run


def cc02(path, src):
    return [f for f in analyze_text(path, src) if f.code == "CC02"]


_OWNER = "consensus_specs_tpu/stf/sync.py"


_OMITTED_PARAM = """\
def sync_committee_rows(spec, state, period):
    key = (bytes(state.validators.hash_tree_root()),)
    hit = _SYNC_ROWS_CACHE.get(key)
    if hit is not None:
        return hit
    rows = resolve(state, period)
    _SYNC_ROWS_CACHE[key] = rows
    return rows
"""


def test_omitted_parameter_is_flagged():
    found = cc02(_OWNER, _OMITTED_PARAM)
    assert len(found) == 1
    assert "period" in found[0].message
    assert "_SYNC_ROWS_CACHE" in found[0].message


_PUT_HELPER = """\
def committee_context(spec, state, epoch):
    lookup_key = (bytes(state.validators.hash_tree_root()), int(epoch))
    ctx = _CTX_LOOKUP.get(lookup_key)
    if ctx is not None:
        return ctx
    seed = bytes(spec.get_seed(state, epoch))
    ctx = _fifo_put(_CTX_CACHE, (lookup_key[0], seed),
                    build_ctx(spec, state, epoch, seed))
    return _fifo_put(_CTX_LOOKUP, lookup_key, ctx)
"""


def test_put_helper_insertion_is_seen():
    """The committee-context shape that motivated the rule: the lookup
    layer's key binds registry/randao roots and the epoch but not the
    spec, while the stored context reads the spec's geometry."""
    found = cc02("consensus_specs_tpu/stf/attestations.py", _PUT_HELPER)
    assert any("_CTX_LOOKUP" in f.message and "spec" in f.message
               for f in found), found


_COVERED_TRANSITIVELY = """\
def sync_committee_rows(spec, state):
    root = bytes(state.validators.hash_tree_root())
    geometry = (int(spec.SYNC_COMMITTEE_SIZE),)
    key = (root, geometry)
    hit = _SYNC_ROWS_CACHE.get(key)
    if hit is not None:
        return hit
    rows = resolve(spec, state)
    _SYNC_ROWS_CACHE[key] = rows
    return rows
"""


def test_coverage_through_derived_locals():
    """A key built from locals derived from the parameters covers them —
    the rule follows assignment chains, not spellings."""
    assert cc02(_OWNER, _COVERED_TRANSITIVELY) == []


_SETDEFAULT_FORM = """\
def sync_committee_rows(spec, state, period):
    key = (bytes(state.hash_tree_root()),)
    hit = _SYNC_ROWS_CACHE.get(key)
    if hit is not None:
        return hit
    return _SYNC_ROWS_CACHE.setdefault(key, resolve(state, period))
"""


def test_setdefault_insertion_is_seen():
    found = cc02(_OWNER, _SETDEFAULT_FORM)
    assert len(found) == 1 and "period" in found[0].message


_NO_INSERTION = """\
def peek(spec, state, key):
    return _SYNC_ROWS_CACHE.get(key)
"""


def test_lookup_without_insertion_is_skipped():
    """No paired insertion in scope -> no evidence about the key/value
    contract -> no finding (read-only probes stay legal)."""
    assert cc02(_OWNER, _NO_INSERTION) == []


_BUILDER_FORM = """\
def cached_rows(state):
    return _SYNC_ROWS_CACHE.get(state.validators, build_rows)
"""


def test_two_arg_builder_get_is_skipped():
    """RootKeyedCache-style ``get(view, build)`` carries no inline key
    expression; its keying is the view's root by construction."""
    assert cc02(_OWNER, _BUILDER_FORM) == []


def test_non_owner_file_is_skipped():
    """CC02 is the owner's discipline (CC01 already polices outsiders):
    the same source outside stf/sync.py is someone else's dict."""
    assert cc02("consensus_specs_tpu/forkchoice/batch.py", _OMITTED_PARAM) == []


def test_noqa_suppresses():
    src = _OMITTED_PARAM.replace(
        "    hit = _SYNC_ROWS_CACHE.get(key)",
        "    hit = _SYNC_ROWS_CACHE.get(key)  # noqa: CC02")
    assert cc02(_OWNER, src) == []


_HELPER_KEY = """\
def _row_key(spec, state, period):
    return (bytes(state.validators.hash_tree_root()),
            {geometry}int(period))


def sync_committee_rows(spec, state, period):
    key = _row_key(spec, state, period)
    hit = _SYNC_ROWS_CACHE.get(key)
    if hit is not None:
        return hit
    rows = resolve(spec, state, period)
    _SYNC_ROWS_CACHE[key] = rows
    return rows
"""


def test_helper_built_key_is_transparent():
    """A key hoisted into a local builder function keeps the rule's
    power (ISSUE 8): only the callsite arguments the helper's RETURN
    actually reaches count as bound — naming ``spec`` in the call is not
    coverage when the helper drops it."""
    covered = _HELPER_KEY.format(geometry="int(spec.SYNC_COMMITTEE_SIZE), ")
    assert cc02(_OWNER, covered) == []
    dropped = _HELPER_KEY.format(geometry="")
    found = cc02(_OWNER, dropped)
    assert len(found) == 1 and "spec" in found[0].message, found


# -- the live tree, gate-shaped ----------------------------------------------


def test_cc02_mutation_turns_gate_red():
    """Dropping the spec-geometry component from the committee-context
    lookup key reintroduces exactly the staleness class the rule exists
    for — the full gate (baseline applied) must go red."""
    rel = "consensus_specs_tpu/stf/attestations.py"
    path = REPO_ROOT / rel
    text = path.read_text()
    mutated = text.replace(
        "        int(epoch),\n        _spec_geometry_key(spec),\n    )",
        "        int(epoch),\n    )")
    assert mutated != text, "mutation did not apply"
    result = run([path], overrides={rel: mutated}, use_cache=False)
    assert any(f.code == "CC02" and "spec" in f.message
               for f in result.findings), [f.render() for f in result.findings]
