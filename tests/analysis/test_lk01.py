"""LK01 lock discipline: registered locks taken with ``with``, no
blocking calls inside a critical section, no inverted acquisition
orders, and every lock construction declared in the concurrency
registry (ISSUE 15)."""
import pytest

from analysis import analyze_text
from analysis import concurrency_registry as creg
from analysis.concurrency_registry import LockSpec
from analysis.dataflow import build_project

MOD = "consensus_specs_tpu.stf.x"
PATH = "consensus_specs_tpu/stf/x.py"
MOD2 = "consensus_specs_tpu.node.y"
PATH2 = "consensus_specs_tpu/node/y.py"


@pytest.fixture
def registry(monkeypatch):
    monkeypatch.setattr(creg, "LOCKS", (
        LockSpec("a lock", MOD, frozenset({"_A"})),
        LockSpec("b lock", MOD, frozenset({"_B"})),
        LockSpec("box lock", MOD,
                 frozenset({"Box._lock", "Box._not_full"})),
        LockSpec("fence", MOD, frozenset({"fence"})),
        LockSpec("y lock", MOD2, frozenset({"_A"})),
        LockSpec("y other", MOD2, frozenset({"_B"})),
    ))
    monkeypatch.setattr(creg, "SHARED", ())
    monkeypatch.setattr(creg, "ROLE_SEEDS", ())


def lk01(path, src, project=None):
    return [f for f in analyze_text(path, src, project=project)
            if f.code == "LK01"]


def check(src, project=None):
    return lk01(PATH, src, project=project)


_HEADER = ("import threading\n"
           "_A = threading.Lock()\n"
           "_B = threading.Lock()\n")


# -- completeness: every lock construction declared ----------------------------

def test_undeclared_module_lock_flagged(registry):
    src = _HEADER + "_ROGUE = threading.Lock()\n"
    found = check(src)
    assert [f.line for f in found] == [4]
    assert "_ROGUE" in found[0].message
    assert "concurrency_registry" in found[0].message


def test_undeclared_instance_and_local_locks_flagged(registry):
    src = _HEADER + ("class Box:\n"
                     "    def __init__(self):\n"
                     "        self._cond = threading.Condition()\n"
                     "def run():\n"
                     "    gate = threading.Condition()\n"
                     "    return gate\n")
    found = check(src)
    assert [f.line for f in found] == [6, 8]
    assert "Box._cond" in found[0].message
    assert "gate" in found[1].message


def test_declared_constructions_are_clean(registry):
    src = _HEADER + ("class Box:\n"
                     "    def __init__(self):\n"
                     "        self._lock = threading.Lock()\n"
                     "        self._not_full = threading.Condition(self._lock)\n"
                     "def run():\n"
                     "    fence = threading.Condition()\n"
                     "    return fence\n")
    assert check(src) == []


# -- acquire outside with ------------------------------------------------------

def test_bare_acquire_on_registered_lock_flagged(registry):
    src = _HEADER + ("def grab():\n"
                     "    _A.acquire()\n"
                     "    try:\n"
                     "        pass\n"
                     "    finally:\n"
                     "        _A.release()\n")
    found = check(src)
    assert [f.line for f in found] == [5]
    assert "a lock" in found[0].message


def test_annotated_acquire_is_sanctioned(registry):
    src = _HEADER + (
        "def probe():\n"
        "    # thread-safe: non-blocking try-acquire, released in finally\n"
        "    return _A.acquire(blocking=False)\n")
    assert check(src) == []


def test_acquire_on_unregistered_receiver_ignored(registry):
    src = _HEADER + ("def grab(resource):\n"
                     "    resource.acquire()\n")
    assert check(src) == []


# -- blocking under a held lock ------------------------------------------------

def test_blocking_calls_under_lock_flagged(registry):
    src = _HEADER + ("import time\n"
                     "def bad(queue, worker, future):\n"
                     "    with _A:\n"
                     "        queue.put(1)\n"
                     "        worker.join()\n"
                     "        time.sleep(0.1)\n"
                     "        future.result()\n")
    found = check(src)
    assert [f.line for f in found] == [7, 8, 9, 10]
    assert all("a lock" in f.message for f in found)


def test_condition_wait_and_outside_calls_are_legal(registry):
    # wait RELEASES the lock (the idiom); blocking outside a lock is fine
    src = _HEADER + ("class Box:\n"
                     "    def __init__(self):\n"
                     "        self._lock = threading.Lock()\n"
                     "        self._not_full = threading.Condition(self._lock)\n"
                     "    def put(self, queue):\n"
                     "        with self._not_full:\n"
                     "            self._not_full.wait(1.0)\n"
                     "        queue.put(1)\n")
    assert check(src) == []


def test_nested_def_body_is_not_under_the_lock(registry):
    # a closure defined inside the critical section runs later
    src = _HEADER + ("def make(queue):\n"
                     "    with _A:\n"
                     "        def later():\n"
                     "            queue.put(1)\n"
                     "    return later\n")
    assert check(src) == []


def test_native_batch_entry_under_lock_flagged(registry):
    src = _HEADER + ("from consensus_specs_tpu.stf import verify\n"
                     "def bad(entries):\n"
                     "    with _B:\n"
                     "        return verify.first_invalid(entries)\n")
    found = check(src)
    assert [f.line for f in found] == [7]
    assert "first_invalid" in found[0].message


# -- acquisition-order inversions ----------------------------------------------

def test_order_inversion_across_files_flagged(registry):
    src_x = _HEADER + ("def f():\n"
                       "    with _A:\n"
                       "        with _B:\n"
                       "            pass\n")
    src_y = ("import threading\n"
             "_A = threading.Lock()\n"
             "_B = threading.Lock()\n"
             "def g():\n"
             "    with _B:\n"
             "        with _A:\n"
             "            pass\n")
    proj = build_project({PATH: src_x, PATH2: src_y})
    found = lk01(PATH2, src_y, project=proj)
    # y's B->A inverts x's A->B (identities are registry-canonical, so
    # the two files' distinct LockSpecs never collide by spelling)
    assert found == []  # different canonical locks: no shared pair
    # same-file inversion through the SAME locks does flag
    src_both = _HEADER + ("def f():\n"
                          "    with _A:\n"
                          "        with _B:\n"
                          "            pass\n"
                          "def g():\n"
                          "    with _B:\n"
                          "        with _A:\n"
                          "            pass\n")
    found = check(src_both)
    assert len(found) == 2  # each direction names the other site
    assert "deadlock" in found[0].message


def test_consistent_order_is_clean(registry):
    src = _HEADER + ("def f():\n"
                     "    with _A:\n"
                     "        with _B:\n"
                     "            pass\n"
                     "def g():\n"
                     "    with _A:\n"
                     "        with _B:\n"
                     "            pass\n")
    assert check(src) == []


def test_inversion_detected_through_condition_alias(registry):
    # f orders box-lock -> _A via the Lock spelling; g inverts it via
    # the CONDITION spelling of the same lock — one canonical identity
    src = _HEADER + ("class Box:\n"
                     "    def __init__(self):\n"
                     "        self._lock = threading.Lock()\n"
                     "        self._not_full = threading.Condition(self._lock)\n"
                     "    def f(self):\n"
                     "        with self._lock:\n"
                     "            with _A:\n"
                     "                pass\n"
                     "    def g(self):\n"
                     "        with _A:\n"
                     "            with self._not_full:\n"
                     "                pass\n")
    found = check(src)
    assert len(found) == 2
    assert "box lock" in found[0].message


def test_noqa_suppresses(registry):
    src = _HEADER + "_ROGUE = threading.Lock()  # noqa: LK01\n"
    assert check(src) == []


def test_tests_and_specs_are_exempt(registry):
    src = "import threading\n_ROGUE = threading.Lock()\n"
    assert lk01("tests/test_x.py", src) == []
    assert lk01("consensus_specs_tpu/specs/src/x.py", src) == []
