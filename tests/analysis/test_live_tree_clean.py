"""Tier-1 gate: the live tree carries ZERO unbaselined analyzer findings
— the engine invariants (cache coherence, rollback safety, jit purity,
Gwei dtype safety) plus the hygiene codes hold on every PR by
construction.

The seeded-mutation tests prove the gate has teeth: re-introducing each
class of bug the semantic rules exist for (a stray ``store.latest_messages``
write, a dropped ``dtype=np.uint64``, a cache poke from outside the
owner, a state write outside the rollback region, a ``print`` in a jitted
kernel) turns the same analysis red — via ``overrides``, which analyze
hypothetical file contents at their real tree paths without touching
disk.
"""
import pytest

from analysis import REPO_ROOT, run


@pytest.fixture(scope="module")
def gate(tmp_path_factory):
    cache = tmp_path_factory.mktemp("analysis") / "cache.json"
    result = run(cache_path=cache)
    result._cache_path = cache
    return result


def test_live_tree_has_zero_unbaselined_findings(gate):
    assert gate.findings == [], [f.render() for f in gate.findings]


def test_no_stale_baseline_entries(gate):
    assert gate.stale_baseline == [], gate.stale_baseline


def test_baselined_findings_still_fire(gate):
    # the baseline holds reviewed findings, not dead entries: every one
    # matched a live finding this run (the CC01 resident-merkle install)
    assert {f.code for f in gate.baselined} == {"CC01"}


def test_full_tree_scale_and_budget(gate):
    assert gate.n_files > 250  # the whole tree, not a subset
    # acceptance: < 5 s cold on the 1 vCPU CI box; allow CI-noise headroom
    assert gate.duration_s < 15, f"cold run took {gate.duration_s:.1f}s"


def test_warm_run_is_cached_and_fast(gate):
    warm = run(cache_path=gate._cache_path)
    assert warm.cache_hits == warm.n_files
    assert warm.findings == []
    # acceptance: < 1 s warm; allow CI-noise headroom
    assert warm.duration_s < 3, f"warm run took {warm.duration_s:.1f}s"


# -- seeded mutations: the gate must turn red --------------------------------

def _mutated(rel, mutate):
    """Analyze one live file with ``mutate(text)`` applied, full gate
    config (baseline included), returning unbaselined findings."""
    path = REPO_ROOT / rel
    text = path.read_text()
    mutated = mutate(text)
    assert mutated != text, "mutation did not apply"
    result = run([path], overrides={rel: mutated}, use_cache=False)
    return result.findings


def test_fc01_mutation_turns_red():
    rel = "consensus_specs_tpu/testing/helpers/fork_choice.py"
    found = _mutated(rel, lambda t: t + (
        "\n\ndef fast_vote(store, i, message):\n"
        "    store.latest_messages[i] = message\n"))
    assert any(f.code == "FC01" for f in found), found


def test_dt01_mutation_turns_red():
    rel = "consensus_specs_tpu/ops/epoch_jax.py"
    found = _mutated(rel, lambda t: t.replace(",\n                       dtype=np.uint64", ""))
    assert sum(f.code == "DT01" for f in found) == 2, found


def test_cc01_mutation_turns_red():
    rel = "consensus_specs_tpu/stf/attestations.py"
    found = _mutated(rel, lambda t: t + (
        "\n\ndef _prime_permutation(seed, n, rounds):\n"
        "    perm = compute_shuffle_permutation(seed, n, rounds)\n"
        "    perm[0] = 0\n"
        "    return perm\n"))
    assert any(f.code == "CC01" for f in found), found


def test_rb01_mutation_turns_red():
    rel = "consensus_specs_tpu/stf/verify.py"
    found = _mutated(rel, lambda t: t + (
        "\n\ndef settle_and_advance(state, slot):\n"
        "    state.slot = slot\n"))
    assert any(f.code == "RB01" for f in found), found


def test_jx01_mutation_turns_red():
    rel = "consensus_specs_tpu/ops/sha256_jax.py"
    found = _mutated(rel, lambda t: t + (
        "\n\n@jax.jit\n"
        "def _traced_debug(words):\n"
        "    print(words.shape)\n"
        "    return words\n"))
    assert any(f.code == "JX01" for f in found), found


def test_st01_mutation_turns_red():
    rel = "consensus_specs_tpu/testing/helpers/block_processing.py"
    found = _mutated(rel, lambda t: t + (
        "\n\ndef verify_each(bls, atts):\n"
        "    return [bls.FastAggregateVerify(a.pks, a.msg, a.sig)\n"
        "            for a in atts]\n"))
    assert any(f.code == "ST01" for f in found), found
