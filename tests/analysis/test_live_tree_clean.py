"""Tier-1 gate: the live tree carries ZERO unbaselined analyzer findings
— the engine invariants (cache coherence, rollback safety, jit purity,
Gwei dtype safety, host-sync boundaries, sharding contracts, effect
safety) plus the hygiene codes hold on every PR by construction.

The seeded-mutation tests prove the gate has teeth: re-introducing each
class of bug the semantic rules exist for (a stray ``store.latest_messages``
write, a dropped ``dtype=np.uint64``, a cache poke from outside the
owner, a state write outside the rollback region, a ``print`` in a jitted
kernel, an undeclared device pull-back, a spec-less ``shard_map``, an
unrouted cache insert next to a fault probe) turns the same analysis red
— via ``overrides``, which analyze hypothetical file contents at their
real tree paths without touching disk.  The battery runs full-tree (the
interprocedural rules need the project graph) against the gate's warm
cache, so each mutation only re-analyzes the mutated files plus their
call-graph dependents.
"""
import pytest

from analysis import REPO_ROOT, run
from analysis.core import REGISTRY


@pytest.fixture(scope="module")
def gate(tmp_path_factory):
    cache = tmp_path_factory.mktemp("analysis") / "cache.json"
    result = run(cache_path=cache)
    result._cache_path = cache
    return result


def test_live_tree_has_zero_unbaselined_findings(gate):
    assert gate.findings == [], [f.render() for f in gate.findings]


def test_no_stale_baseline_entries(gate):
    assert gate.stale_baseline == [], gate.stale_baseline


def test_baselined_findings_still_fire(gate):
    # the baseline holds reviewed findings, not dead entries: every one
    # matched a live finding this run (the CC01 resident-merkle install)
    assert {f.code for f in gate.baselined} == {"CC01"}


def test_full_tree_scale_and_budget(gate):
    assert gate.n_files > 250  # the whole tree, not a subset
    # acceptance: cold two-pass run on the 1 vCPU CI box with headroom
    assert gate.duration_s < 20, f"cold run took {gate.duration_s:.1f}s"


def test_warm_run_is_cached_and_fast(gate):
    warm = run(cache_path=gate._cache_path)
    assert warm.cache_hits == warm.n_files
    assert warm.findings == []
    # acceptance: <= 2 s warm on 1 vCPU; allow CI-noise headroom
    assert warm.duration_s < 2, f"warm run took {warm.duration_s:.1f}s"


def test_per_rule_budget_and_observability(gate):
    # every registered rule reports stats, and no single rule eats the
    # whole cold-run budget on the live tree (self-observability gate)
    from analysis import all_rules

    assert set(gate.rule_stats) == {r.code for r in all_rules()}
    for code, s in gate.rule_stats.items():
        assert s["time_s"] < 8.0, f"{code} took {s['time_s']:.2f}s"
        assert s["findings"] >= 0
    # the stats survive into the JSON report (make analyze -> ANALYSIS.json)
    report = gate.to_json()["rule_stats"]
    assert set(report) == set(gate.rule_stats)
    assert all("time_s" in v and "findings" in v for v in report.values())
    # the thread-role fixed point (ISSUE 15) reports its own wall time
    # and stays a rounding error of the run — it executes warm AND cold
    assert gate.to_json()["role_pass_s"] == round(gate.role_pass_s, 4)
    assert 0.0 <= gate.role_pass_s < 1.0, gate.role_pass_s


def test_warm_run_keeps_the_role_pass_cheap(gate):
    # the role pass is the one project-level pass a warm run cannot
    # skip; its budget is what keeps `make analyze` interactive
    warm = run(cache_path=gate._cache_path)
    assert warm.role_pass_s < 0.5, warm.role_pass_s


# -- seeded mutations: the gate must turn red --------------------------------

def _mutated(gate, mutations):
    """Analyze the live tree with ``mutations`` ({rel: mutate(text)})
    applied, full gate config (baseline included, project graph built,
    warm cache consulted read-only), returning unbaselined findings."""
    overrides = {}
    for rel, mutate in mutations.items():
        text = (REPO_ROOT / rel).read_text()
        mutated = mutate(text)
        assert mutated != text, f"mutation did not apply to {rel}"
        overrides[rel] = mutated
    return run(cache_path=gate._cache_path, overrides=overrides).findings


def test_fc01_mutation_turns_red(gate):
    rel = "consensus_specs_tpu/testing/helpers/fork_choice.py"
    found = _mutated(gate, {rel: lambda t: t + (
        "\n\ndef fast_vote(store, i, message):\n"
        "    store.latest_messages[i] = message\n")})
    assert any(f.code == "FC01" for f in found), found


def test_dt01_mutation_turns_red(gate):
    rel = "consensus_specs_tpu/ops/epoch_jax.py"
    found = _mutated(gate, {rel: lambda t: t.replace(
        ",\n                       dtype=np.uint64", "")})
    assert sum(f.code == "DT01" for f in found) == 2, found


def test_cc01_mutation_turns_red(gate):
    rel = "consensus_specs_tpu/stf/attestations.py"
    found = _mutated(gate, {rel: lambda t: t + (
        "\n\ndef _prime_permutation(seed, n, rounds):\n"
        "    perm = compute_shuffle_permutation(seed, n, rounds)\n"
        "    perm[0] = 0\n"
        "    return perm\n")})
    assert any(f.code == "CC01" for f in found), found


def test_rb01_mutation_turns_red(gate):
    rel = "consensus_specs_tpu/stf/verify.py"
    found = _mutated(gate, {rel: lambda t: t + (
        "\n\ndef settle_and_advance(state, slot):\n"
        "    state.slot = slot\n")})
    assert any(f.code == "RB01" for f in found), found


def test_jx01_mutation_turns_red(gate):
    rel = "consensus_specs_tpu/ops/sha256_jax.py"
    found = _mutated(gate, {rel: lambda t: t + (
        "\n\n@jax.jit\n"
        "def _traced_debug(words):\n"
        "    print(words.shape)\n"
        "    return words\n")})
    assert any(f.code == "JX01" for f in found), found


def test_st01_mutation_turns_red(gate):
    rel = "consensus_specs_tpu/testing/helpers/block_processing.py"
    found = _mutated(gate, {rel: lambda t: t + (
        "\n\ndef verify_each(bls, atts):\n"
        "    return [bls.FastAggregateVerify(a.pks, a.msg, a.sig)\n"
        "            for a in atts]\n")})
    assert any(f.code == "ST01" for f in found), found


def test_hd01_mutation_turns_red(gate):
    # un-declare the epoch kernel's staged-view boundary: the pull-back
    # the issue names (ops/epoch_jax.py) must be flagged again
    rel = "consensus_specs_tpu/ops/epoch_jax.py"
    found = _mutated(gate, {rel: lambda t: t.replace("# host-sync:",
                                                     "# host-off:")})
    assert sum(f.code == "HD01" for f in found) == 2, found


def test_sh01_mutation_turns_red(gate):
    # drop out_specs from the sharded pairing check's shard_map callsite
    rel = "consensus_specs_tpu/parallel/bls_sharded.py"
    found = _mutated(gate, {rel: lambda t: t.replace(
        "            out_specs=P(axis),\n", "")})
    assert any(f.code == "SH01" and "out_specs" in f.message
               for f in found), found


def test_ef01_mutation_turns_red(gate):
    # an unrouted insert into a registered memo right next to a fault
    # probe: PR 5's transactional discipline, machine-checked
    rel = "consensus_specs_tpu/stf/attestations.py"
    found = _mutated(gate, {rel: lambda t: t + (
        "\n\ndef _poke_ctx(key, value):\n"
        "    _SITE_RESOLVE()\n"
        "    _CTX_CACHE[key] = value\n")})
    assert any(f.code == "EF01" for f in found), found


def test_ob01_unclosed_span_mutation_turns_red(gate):
    # a leaky raw timeline.begin next to the pipeline's real probe sites
    # (ISSUE 11): no finally-end, no escape — the span-leak check fires
    rel = "consensus_specs_tpu/stf/pipeline.py"
    found = _mutated(gate, {rel: lambda t: t + (
        "\n\ndef leaky_probe(entries):\n"
        "    sid = timeline.begin('probe')\n"
        "    return verify.first_invalid(entries)\n")})
    assert any(f.code == "OB01" and "finally" in f.message
               for f in found), found


def test_io01_mutation_turns_red(gate):
    # a hand-rolled artifact promotion next to the real engine code
    # (ISSUE 14): the torn-write discipline lives in persist/atomic.py
    # ONLY — a bespoke os.replace outside it is gate-red
    rel = "consensus_specs_tpu/stf/columns.py"
    found = _mutated(gate, {rel: lambda t: t + (
        "\n\nimport os\n"
        "def _spill_column(tmp, path, col):\n"
        "    with open(tmp, 'wb') as f:\n"
        "        f.write(col.tobytes())\n"
        "    os.replace(tmp, path)\n")})
    assert any(f.code == "IO01" and "os.replace" in f.message
               for f in found), found
    assert any(f.code == "IO01" and "'wb'" in f.message
               for f in found), found


def test_cc01_cross_file_passthrough_mutation_turns_red(gate):
    # the call-graph-aware half of CC01: a helper in ANOTHER file passes
    # the registry-columns producer's cached dict through; mutating its
    # return value is flagged at the mutation site
    wrapper = "consensus_specs_tpu/ops/segment.py"
    user = "consensus_specs_tpu/stf/slot_roots.py"
    found = _mutated(gate, {
        wrapper: lambda t: t + (
            "\n\nfrom consensus_specs_tpu.ops.epoch_jax import "
            "registry_columns\n"
            "def cols_view(spec, state):\n"
            "    return registry_columns(spec, state)\n"),
        user: lambda t: t + (
            "\n\nfrom consensus_specs_tpu.ops.segment import cols_view\n"
            "def _corrupt(spec, state):\n"
            "    cols = cols_view(spec, state)\n"
            "    cols[\"effective_balance\"][0] = 0\n"
            "    return cols\n")})
    assert any(f.code == "CC01" and f.file == user for f in found), found


def test_dt01_cross_file_callsite_mutation_turns_red(gate):
    # the call-graph-aware half of DT01: the reducing helper carries no
    # hint in its own file; the hinted callsite lives a file away
    helper = "consensus_specs_tpu/ops/segment.py"
    user = "consensus_specs_tpu/forkchoice/batch.py"
    found = _mutated(gate, {
        helper: lambda t: t + (
            "\n\ndef total_of(values):\n"
            "    return np.sum(values)\n"),
        user: lambda t: t + (
            "\n\nfrom consensus_specs_tpu.ops.segment import total_of\n"
            "def _total_balance(balances):\n"
            "    return total_of(balances)\n")})
    assert any(f.code == "DT01" and f.file == user
               and "total_of" in f.message for f in found), found


def test_th01_pr9_span_stack_race_mutation_turns_red(gate):
    # PR 9's historical race, reintroduced: the span nesting stack as a
    # shared module global instead of thread-local — TH01 must flag the
    # mutation with the spawned roles that reach span() named
    rel = "consensus_specs_tpu/telemetry/metrics.py"
    found = _mutated(gate, {rel: lambda t: t.replace(
        "_tls = threading.local()  # per-thread span nesting stack",
        "_NEST: list = []\n"
        "_tls = threading.local()  # per-thread span nesting stack",
    ).replace("    stack = _stack()\n", "    stack = _NEST\n")})
    hits = [f for f in found if f.code == "TH01"]
    assert hits, found
    assert any("_NEST" in f.message and "pipeline-worker" in f.message
               and "metrics.span" in f.message for f in hits), hits


def test_th01_pr14_writer_staging_leak_mutation_turns_red(gate):
    # PR 14's historical race, reintroduced: the background checkpoint
    # writer riding the apply thread's open block transaction (the
    # _WRITER_THREAD gate and its justification removed)
    rel = "consensus_specs_tpu/persist/store.py"
    found = _mutated(gate, {rel: lambda t: t.replace(
        "    if not getattr(_WRITER_THREAD, \"active\", False):\n"
        "        # thread-safe: the _WRITER_THREAD.active flag above gates this\n"
        "        # off the background writer — only same-thread (synchronous)\n"
        "        # callers ride the apply thread's own open transaction\n"
        "        staging.note_insert(_INDEX, path)",
        "    staging.note_insert(_INDEX, path)")})
    hits = [f for f in found if f.code == "TH01"]
    assert hits, found
    assert any("block cache transaction" in f.message
               and "persist-writer" in f.message
               and "CheckpointStore._drain -> "
                   "persist.store.CheckpointStore.write_checkpoint"
                   in f.message for f in hits), hits


def test_th01_lock_free_requeue_front_mutation_turns_red(gate):
    # the ingest deque's registered lock dropped from requeue_front
    rel = "consensus_specs_tpu/node/ingest.py"
    found = _mutated(gate, {rel: lambda t: t.replace(
        "        with self._lock:\n"
        "            if len(self._items) >= self._cap:",
        "        if True:\n"
        "            if len(self._items) >= self._cap:")})
    assert any(f.code == "TH01" and "ingest queue deque" in f.message
               and "IngestQueue._lock" in f.message for f in found), found


def test_th01_unguarded_aggregation_buffer_write_mutation_turns_red(gate):
    # ISSUE 19's cross-role staging buffer: gossip producers write it,
    # the apply loop drains it — the admission lock dropped from the
    # producer-side staging write must turn the gate red
    rel = "consensus_specs_tpu/node/admission.py"
    found = _mutated(gate, {rel: lambda t: t.replace(
        '    item = WorkItem("attestations", payload, link, producer)\n'
        "    with _LOCK:\n"
        "        if producer in _QUARANTINED:",
        '    item = WorkItem("attestations", payload, link, producer)\n'
        "    if True:\n"
        "        if producer in _QUARANTINED:")})
    hits = [f for f in found if f.code == "TH01"]
    assert hits, found
    assert any("admission aggregation buffer" in f.message
               for f in hits), hits


def test_th01_undeclared_spawn_site_mutation_turns_red(gate):
    # registry completeness: a new production thread without a declared
    # role turns the gate red (the chaos COVERED_SITES pattern)
    rel = "consensus_specs_tpu/node/firehose.py"
    found = _mutated(gate, {rel: lambda t: t + (
        "\n\ndef _orphan_worker():\n"
        "    pass\n"
        "def _spawn_orphan():\n"
        "    threading.Thread(target=_orphan_worker).start()\n")})
    assert any(f.code == "TH01" and "no declared role" in f.message
               for f in found), found


def test_lk01_undeclared_lock_mutation_turns_red(gate):
    # registry completeness: a new production lock without a LockSpec
    rel = "consensus_specs_tpu/stf/pipeline.py"
    found = _mutated(gate, {rel: lambda t: t + (
        "\n\nimport threading\n"
        "_SIDE_LOCK = threading.Lock()\n")})
    assert any(f.code == "LK01" and "_SIDE_LOCK" in f.message
               for f in found), found


# -- spec-mirror parity (ISSUE 18): SP01/SP02/SP03 ---------------------------

def test_sp02_capella_fast_forks_mutation_turns_red(gate):
    # ROADMAP item 4's exact first step — widening FAST_FORKS to capella
    # — is gate-red until every reachable capella spec fn is declared
    rel = "consensus_specs_tpu/stf/engine.py"
    found = _mutated(gate, {rel: lambda t: t.replace(
        'FAST_FORKS = ("phase0", "altair", "bellatrix")',
        'FAST_FORKS = ("phase0", "altair", "bellatrix", "capella")')})
    hits = [f for f in found if f.code == "SP02"]
    assert hits, found
    assert all(f.file == rel for f in hits)
    assert all("fast-path fork 'capella'" in f.message for f in hits), hits
    # the coverage gaps are the capella additions themselves
    named = " ".join(f.message for f in hits)
    assert "process_withdrawals" in named, named
    assert "process_full_withdrawals" in named, named


def test_sp01_spec_body_edit_mutation_turns_red(gate):
    # a semantic edit to a pinned spec function names the mirror + fork
    rel = "consensus_specs_tpu/specs/src/phase0.py"
    found = _mutated(gate, {rel: lambda t: t.replace(
        "assert block.slot == state.slot",
        "assert block.slot >= state.slot", 1)})
    hits = [f for f in found if f.code == "SP01"]
    assert any(f.file == "consensus_specs_tpu/stf/engine.py"
               and "'_header'" in f.message
               and "process_block_header" in f.message
               and "phase0" in f.message for f in hits), found


def test_sp01_spec_comment_churn_stays_green(gate):
    # AST normalization: comment/docstring churn is not drift
    rel = "consensus_specs_tpu/specs/src/phase0.py"
    found = _mutated(gate, {rel: lambda t: t + "\n# annotated, no-op\n"})
    assert not [f for f in found if f.code.startswith("SP")], found


def test_sp03_guard_deletion_mutation_turns_red(gate):
    # deleting a mapped guard from a live mirror is red with the guard,
    # the spec twin, and the mirror named
    rel = "consensus_specs_tpu/stf/slot_roots.py"
    found = _mutated(gate, {rel: lambda t: t.replace(
        "    assert state.slot < slot", "    pass")})
    hits = [f for f in found if f.code == "SP03"]
    assert any("assert state.slot < slot" in f.message
               and "process_slots" in f.message for f in hits), found


def test_mirror_pass_budget_and_snapshot_report(gate):
    # the extraction pass reports its own wall time (ANALYSIS.json) and
    # stays within the warm per-rule budget; the per-fork snapshot
    # digests are the rows a pin bump is audited against
    js = gate.to_json()
    assert js["mirror_pass_s"] == round(gate.mirror_pass_s, 4)
    assert 0.0 <= gate.mirror_pass_s < 0.5, gate.mirror_pass_s
    assert set(js["spec_snapshot"]) == {
        "phase0", "altair", "bellatrix", "capella", "ssz"}
    assert all(len(d) == 64 for d in js["spec_snapshot"].values())


def test_warm_run_keeps_the_mirror_pass_cheap(gate):
    warm = run(cache_path=gate._cache_path)
    assert warm.mirror_pass_s < 0.5, warm.mirror_pass_s


def test_spec_edit_rederives_exactly_the_pinned_mirrors(gate):
    # cache correctness: a (semantically inert) spec-source edit shifts
    # the dependency digest of exactly the files whose registry pins
    # reach that source — every mirror file re-analyzes, nothing else
    from analysis import mirror_registry

    rel = "consensus_specs_tpu/specs/src/bellatrix.py"
    text = (REPO_ROOT / rel).read_text() + "\n# churn\n"
    res = run(cache_path=gate._cache_path, overrides={rel: text},
              changed_only=True)
    assert res.findings == [], [f.render() for f in res.findings]
    expected = {rel}
    for display, deps in mirror_registry.extra_file_deps().items():
        if rel in deps:
            expected.add(display)
    assert set(res.analyzed) == expected, (
        sorted(set(res.analyzed) ^ expected))


def test_changed_only_leaf_edit_stays_scoped(gate):
    # make analyze-changed: an untouched tree re-analyzes nothing; a
    # leaf edit re-analyzes the leaf (+ dependents — this leaf has none)
    clean = run(cache_path=gate._cache_path, changed_only=True)
    assert clean.analyzed == [] and clean.findings == []
    assert clean.stale_baseline == []
    leaf = "tests/analysis/test_noqa.py"
    text = (REPO_ROOT / leaf).read_text() + "\n# touched\n"
    res = run(cache_path=gate._cache_path, overrides={leaf: text},
              changed_only=True)
    assert set(res.analyzed) == {leaf}, sorted(res.analyzed)


def test_registry_covers_every_mutation_code():
    # every rule family proven red above is a registered plugin
    for code in ("FC01", "DT01", "CC01", "RB01", "JX01", "ST01",
                 "HD01", "SH01", "EF01", "OB01", "IO01", "TH01", "LK01",
                 "SP01", "SP02", "SP03"):
        assert code in REGISTRY, code
