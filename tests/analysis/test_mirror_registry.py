"""Structural audit of the spec-mirror registry: every declaration
resolves against the live tree (mirrors exist, guards present, pins match
the extracted digests) and the extraction pass's redeclared fork ladder
stays in lockstep with ``specs/builder.py``."""

import ast

from analysis import REPO_ROOT, mirror_registry, spec_extract


def test_registry_is_structurally_valid():
    assert mirror_registry.registry_errors() == []


def test_registry_scale_matches_the_fast_paths():
    # the tentpole floor: every production fast-path mirror is declared
    assert len(mirror_registry.MIRRORS) >= 25
    assert len(mirror_registry.mirror_files()) >= 6


def test_fork_chains_lockstep_with_builder_fork_parents():
    # spec_extract redeclares the ladder (importing builder pulls in jax);
    # pin it AST-for-AST against the authoritative FORK_PARENTS
    src = (REPO_ROOT / "consensus_specs_tpu/specs/builder.py").read_text()
    parents = None
    for node in ast.walk(ast.parse(src)):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "FORK_PARENTS"
                        for t in node.targets)):
            parents = ast.literal_eval(node.value)
    assert parents is not None
    for fork, chain in spec_extract.FORK_CHAINS.items():
        rebuilt, cur = [], fork
        while cur is not None:
            rebuilt.append(cur)
            cur = parents[cur]
        assert tuple(reversed(rebuilt)) == chain, fork


def test_every_mirror_resolves_with_its_guards_present():
    for m in mirror_registry.MIRRORS:
        path = REPO_ROOT / mirror_registry.mirror_display(m)
        assert path.exists(), m.name
        text = path.read_text()
        node = mirror_registry.find_def(ast.parse(text), m.qualname)
        assert node is not None, (m.name, m.qualname)
        seg = ast.get_source_segment(text, node)
        for pin in m.pins:
            for guard in pin.guards:
                if guard is not None:
                    assert guard in seg, (m.name, pin.fn, guard)


def test_live_pins_match_extracted_spec_facts():
    texts = {d: (REPO_ROOT / d).read_text()
             for d in spec_extract.spec_source_displays()}
    snap = spec_extract.snapshot(texts)
    for m in mirror_registry.MIRRORS:
        for pin in m.pins:
            for fork in pin.forks:
                fn = snap.get(fork, pin.fn)
                assert fn is not None, (m.name, pin.fn, fork)
                assert fn.digest == pin.digest, (m.name, pin.fn, fork)
                assert fn.raise_count == pin.raise_count, (m.name, pin.fn)
                assert fn.raise_digest == pin.raise_digest, (m.name, pin.fn)
                assert len(pin.guards) == pin.raise_count, (m.name, pin.fn)


def test_coverage_queries():
    assert mirror_registry.coverage(
        "process_slots", "phase0") == "mirror:slot-advance"
    assert mirror_registry.coverage("process_deposit", "phase0") == "literal"
    # capella is off the fast path: the ISSUE's seeded gap stays a gap
    assert mirror_registry.coverage("process_withdrawals", "capella") is None


def test_extra_file_deps_cover_pinned_chains():
    deps = mirror_registry.extra_file_deps()
    # SP02 reads every chain: the engine depends on all spec sources
    assert set(deps[mirror_registry.ENGINE_DISPLAY]) == set(
        spec_extract.spec_source_displays())
    # chain closure: an altair-pinned mirror also depends on phase0 (an
    # earlier-fork edit can move the later fork's effective definition)
    epoch = deps["consensus_specs_tpu/ops/epoch_altair.py"]
    assert "consensus_specs_tpu/specs/src/altair.py" in epoch
    assert "consensus_specs_tpu/specs/src/phase0.py" in epoch
    # every mirror file appears
    assert set(mirror_registry.mirror_files()) <= set(deps)


def test_find_def_resolves_nested_paths():
    tree = ast.parse(
        "class _Outer:\n"
        "    def inner(self):\n"
        "        pass\n"
        "def top():\n"
        "    pass\n")
    assert mirror_registry.find_def(tree, "top").name == "top"
    assert mirror_registry.find_def(tree, "_Outer.inner").name == "inner"
    assert mirror_registry.find_def(tree, "_Outer.gone") is None
    assert mirror_registry.find_def(tree, "missing") is None
