"""IO01 durable-artifact IO discipline: raw ``os.replace``/``os.rename``
promotions and binary open-for-write in production modules must route
through ``persist/atomic.py`` or declare a ``# durable-io: <why>``
boundary (ISSUE 14)."""
from analysis import analyze_text


def io01(path, src):
    return [f for f in analyze_text(path, src) if f.code == "IO01"]


def test_io01_flags_raw_os_replace():
    src = ("import os\n"
           "def promote(tmp, path):\n"
           "    os.replace(tmp, path)\n")
    found = io01("consensus_specs_tpu/stf/x.py", src)
    assert [f.line for f in found] == [3]
    assert "persist/atomic" in found[0].message


def test_io01_flags_raw_os_rename():
    src = ("import os\n"
           "def promote(tmp, path):\n"
           "    os.rename(tmp, path)\n")
    found = io01("consensus_specs_tpu/crypto/x.py", src)
    assert [f.line for f in found] == [3]


def test_io01_flags_binary_open_for_write():
    src = ("def save(path, table):\n"
           "    with open(path, 'wb') as f:\n"
           "        f.write(table)\n")
    found = io01("consensus_specs_tpu/crypto/x.py", src)
    assert [f.line for f in found] == [2]
    assert "'wb'" in found[0].message


def test_io01_flags_append_and_update_binary_modes():
    src = ("def patch(path):\n"
           "    a = open(path, 'ab')\n"
           "    b = open(path, 'r+b')\n"
           "    return a, b\n")
    assert [f.line for f in io01("consensus_specs_tpu/node/x.py",
                                 src)] == [2, 3]


def test_io01_text_writes_and_binary_reads_are_legal():
    # JSON reports (text mode) and artifact READS are not durable-write
    # hazards; deletions are invalidations
    src = ("import json, os\n"
           "def report(path, payload):\n"
           "    with open(path, 'w') as f:\n"
           "        json.dump(payload, f)\n"
           "    with open(path, 'rb') as f:\n"
           "        raw = f.read()\n"
           "    os.unlink(path)\n"
           "    return raw\n")
    assert io01("consensus_specs_tpu/stf/x.py", src) == []


def test_io01_durable_io_annotation_sanctions_the_line():
    src = ("import os\n"
           "def promote(tmp, path):\n"
           "    # durable-io: compiler output promoted whole\n"
           "    os.replace(tmp, path)\n")
    assert io01("consensus_specs_tpu/crypto/x.py", src) == []


def test_io01_bare_annotation_does_not_sanction():
    # the boundary needs a justification, exactly like host-sync
    src = ("import os\n"
           "def promote(tmp, path):\n"
           "    os.replace(tmp, path)  # durable-io:\n")
    assert [f.line for f in io01("consensus_specs_tpu/crypto/x.py",
                                 src)] == [3]


def test_io01_persist_and_tests_are_exempt():
    src = ("import os\n"
           "def promote(tmp, path):\n"
           "    os.replace(tmp, path)\n")
    assert io01("consensus_specs_tpu/persist/atomic.py", src) == []
    assert io01("tests/test_x.py", src) == []
    assert io01("tools/perf_doctor.py", src) == []


def test_io01_computed_mode_is_not_guessed():
    src = ("def save(path, mode, data):\n"
           "    with open(path, mode) as f:\n"
           "        f.write(data)\n")
    assert io01("consensus_specs_tpu/stf/x.py", src) == []


def test_io01_flags_binary_os_fdopen():
    # the pre-migration MSM-table shape: mkstemp + fdopen(fd, "wb")
    src = ("import os, tempfile\n"
           "def save(path, table):\n"
           "    fd, tmp = tempfile.mkstemp(dir='.')\n"
           "    with os.fdopen(fd, 'wb') as f:\n"
           "        f.write(table)\n")
    found = io01("consensus_specs_tpu/crypto/x.py", src)
    assert [f.line for f in found] == [4]
    assert "fdopen" in found[0].message
