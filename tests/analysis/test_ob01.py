"""OB01 observability-event discipline: the recorder/timeline rings are
written only through their APIs, a raw ``timeline.begin`` is closed on
every exit path (or escapes to an owner), and commit-class events in
fault-probed modules are never recorded inside a still-open block
transaction (a rolled-back block must not log a commit that never
happened)."""
from analysis import analyze_text


def ob01(path, src):
    return [f for f in analyze_text(path, src) if f.code == "OB01"]


_HEADER = ("from consensus_specs_tpu import faults, telemetry\n"
           "from consensus_specs_tpu.stf import staging\n"
           "from consensus_specs_tpu.telemetry import recorder, timeline\n"
           "_SITE = faults.site('stf.x.probe')\n")


def test_ob01_flags_direct_ring_append():
    src = _HEADER + ("def leak(event):\n"
                     "    recorder._EVENTS.append(event)\n")
    found = ob01("consensus_specs_tpu/stf/x.py", src)
    assert [f.line for f in found] == [6]
    assert "telemetry.record" in found[0].message


def test_ob01_ring_reads_and_invalidations_are_legal():
    src = _HEADER + ("def peek():\n"
                     "    recorder._EVENTS.clear()\n"
                     "    return list(recorder._EVENTS)\n")
    assert ob01("consensus_specs_tpu/stf/x.py", src) == []


def test_ob01_flags_commit_event_inside_open_transaction():
    src = _HEADER + ("def apply_one(spec, state, sb):\n"
                     "    with staging.block_transaction():\n"
                     "        _SITE()\n"
                     "        telemetry.record('cache_commit', n=1)\n")
    found = ob01("consensus_specs_tpu/stf/x.py", src)
    assert [f.line for f in found] == [8]
    assert "never happened" in found[0].message


def test_ob01_commit_event_after_the_with_block_is_clean():
    src = _HEADER + ("def apply_one(spec, state, sb):\n"
                     "    with staging.block_transaction():\n"
                     "        _SITE()\n"
                     "    telemetry.record('block_fast', slot=1)\n")
    assert ob01("consensus_specs_tpu/stf/x.py", src) == []


def test_ob01_noncommit_events_inside_transaction_are_legal():
    # progress/diagnostic events may fire mid-block: only commit-class
    # kinds assert settlement
    src = _HEADER + ("def apply_one(spec, state, sb):\n"
                     "    with staging.block_transaction():\n"
                     "        _SITE()\n"
                     "        telemetry.record('phase_start', phase='ops')\n")
    assert ob01("consensus_specs_tpu/stf/x.py", src) == []


def test_ob01_uninstrumented_modules_skip_the_transaction_check():
    src = ("from consensus_specs_tpu import telemetry\n"
           "from consensus_specs_tpu.stf import staging\n"
           "def apply_one():\n"
           "    with staging.block_transaction():\n"
           "        telemetry.record('cache_commit')\n")
    assert ob01("consensus_specs_tpu/stf/x.py", src) == []


def test_ob01_recorder_module_itself_is_exempt():
    src = ("import collections\n"
           "_EVENTS = collections.deque(maxlen=4)\n"
           "def record(kind):\n"
           "    _EVENTS.append({'kind': kind})\n")
    assert ob01("consensus_specs_tpu/telemetry/recorder.py", src) == []


def test_ob01_record_via_recorder_module_alias_is_also_judged():
    src = _HEADER + ("def apply_one(spec, state, sb):\n"
                     "    with staging.block_transaction():\n"
                     "        _SITE()\n"
                     "        recorder.record('memo_commit')\n")
    found = ob01("consensus_specs_tpu/stf/x.py", src)
    assert [f.line for f in found] == [8]


# -- ISSUE 11 extension: timeline ring + unclosed-span leak -------------------


def test_ob01_flags_direct_timeline_ring_append():
    src = _HEADER + ("def leak(event):\n"
                     "    timeline._EVENTS.append(event)\n")
    found = ob01("consensus_specs_tpu/stf/x.py", src)
    assert [f.line for f in found] == [6]
    assert "observability ring" in found[0].message


def test_ob01_timeline_ring_reads_and_invalidations_are_legal():
    src = _HEADER + ("def peek():\n"
                     "    timeline._EVENTS.clear()\n"
                     "    return list(timeline._EVENTS)\n")
    assert ob01("consensus_specs_tpu/stf/x.py", src) == []


def test_ob01_flags_unclosed_begin():
    # a begin whose end is NOT in a finally: the exception path leaks
    src = _HEADER + ("def phase(block):\n"
                     "    sid = timeline.begin('host/phase')\n"
                     "    do_work(block)\n"
                     "    timeline.end(sid)\n")
    found = ob01("consensus_specs_tpu/stf/x.py", src)
    assert [f.line for f in found] == [6]
    assert "finally" in found[0].message


def test_ob01_begin_with_finally_end_is_legal():
    src = _HEADER + ("def phase(block):\n"
                     "    sid = timeline.begin('host/phase')\n"
                     "    try:\n"
                     "        do_work(block)\n"
                     "    finally:\n"
                     "        timeline.end(sid)\n")
    assert ob01("consensus_specs_tpu/stf/x.py", src) == []


def test_ob01_span_context_manager_is_legal():
    src = _HEADER + ("def phase(block):\n"
                     "    with timeline.span('host/phase', link=1):\n"
                     "        do_work(block)\n")
    assert ob01("consensus_specs_tpu/stf/x.py", src) == []


def test_ob01_begin_escaping_to_owner_is_legal():
    # the engine's _Speculation shape: the id's lifetime belongs to an
    # owner object (closed at settle/drain, a scope this rule can't see)
    src = _HEADER + ("def start(self, block):\n"
                     "    self.sid = timeline.begin('host/phases')\n"
                     "\n"
                     "def opened(name):\n"
                     "    return timeline.begin(name)\n")
    assert ob01("consensus_specs_tpu/stf/x.py", src) == []


def test_ob01_telemetry_module_is_exempt_from_span_check():
    src = ("from . import timeline\n"
           "def span_impl(name):\n"
           "    sid = timeline.begin(name)\n"
           "    return sid\n")
    assert ob01("consensus_specs_tpu/telemetry/metrics.py", src) == []


def test_ob01_node_commit_kinds_inside_open_transaction_are_flagged():
    # ISSUE 12: node_block/node_gossip assert an item fully applied —
    # the same commit-class discipline as cache_commit/block_fast
    src = _HEADER + ("def apply_item(spec, state, sb):\n"
                     "    with staging.block_transaction():\n"
                     "        _SITE()\n"
                     "        telemetry.record('node_block', slot=1)\n")
    found = ob01("consensus_specs_tpu/node/x.py", src)
    assert [f.line for f in found] == [8]
    assert "never happened" in found[0].message


def test_ob01_node_gossip_after_the_with_block_is_clean():
    src = _HEADER + ("def apply_item(spec, state, batch):\n"
                     "    with staging.block_transaction():\n"
                     "        _SITE()\n"
                     "    telemetry.record('node_gossip', n=len(batch))\n")
    assert ob01("consensus_specs_tpu/node/x.py", src) == []


# -- ISSUE 13: containment commit-kinds ---------------------------------------


def test_ob01_node_quarantine_inside_open_transaction_is_flagged():
    # node_quarantine asserts the poison item LANDED in the dead-letter
    # ring; recorded before settlement, a fault would put a containment
    # action in the post-mortem that never happened
    src = _HEADER + ("def contain(item):\n"
                     "    with staging.block_transaction():\n"
                     "        _SITE()\n"
                     "        telemetry.record('node_quarantine', kind='x')\n")
    found = ob01("consensus_specs_tpu/node/x.py", src)
    assert [f.line for f in found] == [8]
    assert "never happened" in found[0].message


def test_ob01_node_recovered_inside_open_transaction_is_flagged():
    src = _HEADER + ("def recover(journal):\n"
                     "    with staging.block_transaction():\n"
                     "        _SITE()\n"
                     "        telemetry.record('node_recovered', items=1)\n")
    found = ob01("consensus_specs_tpu/node/x.py", src)
    assert [f.line for f in found] == [8]


def test_ob01_node_recovered_after_the_with_block_is_clean():
    src = _HEADER + ("def recover(journal):\n"
                     "    with staging.block_transaction():\n"
                     "        _SITE()\n"
                     "    telemetry.record('node_recovered', items=1)\n")
    assert ob01("consensus_specs_tpu/node/x.py", src) == []


def test_ob01_checkpoint_written_inside_open_transaction_is_flagged():
    # checkpoint_written asserts a durable artifact was atomically
    # promoted (ISSUE 14): recorded before settlement, a fault would
    # roll the block back with the timeline claiming bytes on disk
    src = _HEADER + ("def write_ckpt(payload):\n"
                     "    with staging.block_transaction():\n"
                     "        _SITE()\n"
                     "        telemetry.record('checkpoint_written', n=1)\n")
    found = ob01("consensus_specs_tpu/persist/x.py", src)
    assert [f.line for f in found] == [8]


def test_ob01_checkpoint_restored_inside_open_transaction_is_flagged():
    src = _HEADER + ("def restore(path):\n"
                     "    with staging.block_transaction():\n"
                     "        _SITE()\n"
                     "        telemetry.record('checkpoint_restored', n=1)\n")
    found = ob01("consensus_specs_tpu/persist/x.py", src)
    assert [f.line for f in found] == [8]


def test_ob01_checkpoint_events_after_the_with_block_are_clean():
    src = _HEADER + ("def write_ckpt(payload):\n"
                     "    with staging.block_transaction():\n"
                     "        _SITE()\n"
                     "    telemetry.record('checkpoint_written', n=1)\n"
                     "    telemetry.record('store_corrupt', path='x')\n")
    assert ob01("consensus_specs_tpu/persist/x.py", src) == []
