"""OB01 flight-recorder discipline: the ring is written only through
``telemetry.record``, and commit-class events in fault-probed modules
are never recorded inside a still-open block transaction (a rolled-back
block must not log a commit that never happened)."""
from analysis import analyze_text


def ob01(path, src):
    return [f for f in analyze_text(path, src) if f.code == "OB01"]


_HEADER = ("from consensus_specs_tpu import faults, telemetry\n"
           "from consensus_specs_tpu.stf import staging\n"
           "from consensus_specs_tpu.telemetry import recorder\n"
           "_SITE = faults.site('stf.x.probe')\n")


def test_ob01_flags_direct_ring_append():
    src = _HEADER + ("def leak(event):\n"
                     "    recorder._EVENTS.append(event)\n")
    found = ob01("consensus_specs_tpu/stf/x.py", src)
    assert [f.line for f in found] == [6]
    assert "telemetry.record" in found[0].message


def test_ob01_ring_reads_and_invalidations_are_legal():
    src = _HEADER + ("def peek():\n"
                     "    recorder._EVENTS.clear()\n"
                     "    return list(recorder._EVENTS)\n")
    assert ob01("consensus_specs_tpu/stf/x.py", src) == []


def test_ob01_flags_commit_event_inside_open_transaction():
    src = _HEADER + ("def apply_one(spec, state, sb):\n"
                     "    with staging.block_transaction():\n"
                     "        _SITE()\n"
                     "        telemetry.record('cache_commit', n=1)\n")
    found = ob01("consensus_specs_tpu/stf/x.py", src)
    assert [f.line for f in found] == [8]
    assert "never happened" in found[0].message


def test_ob01_commit_event_after_the_with_block_is_clean():
    src = _HEADER + ("def apply_one(spec, state, sb):\n"
                     "    with staging.block_transaction():\n"
                     "        _SITE()\n"
                     "    telemetry.record('block_fast', slot=1)\n")
    assert ob01("consensus_specs_tpu/stf/x.py", src) == []


def test_ob01_noncommit_events_inside_transaction_are_legal():
    # progress/diagnostic events may fire mid-block: only commit-class
    # kinds assert settlement
    src = _HEADER + ("def apply_one(spec, state, sb):\n"
                     "    with staging.block_transaction():\n"
                     "        _SITE()\n"
                     "        telemetry.record('phase_start', phase='ops')\n")
    assert ob01("consensus_specs_tpu/stf/x.py", src) == []


def test_ob01_uninstrumented_modules_skip_the_transaction_check():
    src = ("from consensus_specs_tpu import telemetry\n"
           "from consensus_specs_tpu.stf import staging\n"
           "def apply_one():\n"
           "    with staging.block_transaction():\n"
           "        telemetry.record('cache_commit')\n")
    assert ob01("consensus_specs_tpu/stf/x.py", src) == []


def test_ob01_recorder_module_itself_is_exempt():
    src = ("import collections\n"
           "_EVENTS = collections.deque(maxlen=4)\n"
           "def record(kind):\n"
           "    _EVENTS.append({'kind': kind})\n")
    assert ob01("consensus_specs_tpu/telemetry/recorder.py", src) == []


def test_ob01_record_via_recorder_module_alias_is_also_judged():
    src = _HEADER + ("def apply_one(spec, state, sb):\n"
                     "    with staging.block_transaction():\n"
                     "        _SITE()\n"
                     "        recorder.record('memo_commit')\n")
    found = ob01("consensus_specs_tpu/stf/x.py", src)
    assert [f.line for f in found] == [8]
