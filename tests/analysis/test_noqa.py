"""Targeted ``# noqa`` suppression: bare form silences everything on the
line (legacy behavior), coded form silences exactly the listed codes."""
from analysis import analyze_text

LONG = "x = 1  " + "# pad the line out to something past the limit " * 3


def codes(path, src):
    return sorted({f.code for f in analyze_text(path, src)})


def test_bare_noqa_suppresses_everything():
    src = f"{LONG}# noqa\n"
    assert len(src.splitlines()[0]) > 120
    assert codes("x.py", src) == []


def test_coded_noqa_suppresses_only_that_code():
    # line is both too long AND carries trailing whitespace
    src = f"{LONG}# noqa: E501   \n"
    assert codes("x.py", src) == ["W291"]


def test_code_list():
    src = f"{LONG}# noqa: E501, W291   \n"
    assert codes("x.py", src) == []


def test_trailing_prose_after_codes_is_ignored():
    src = f"{LONG}# noqa: E501 (pinned constant), W291\n"
    assert codes("x.py", src) == []


def test_unknown_codes_are_legal_but_do_not_blanket():
    # E402 is a flake8 code this analyzer does not implement: listing it
    # documents intent without silencing anything else on the line
    src = f"{LONG}# noqa: E402\n"
    assert "E501" in codes("x.py", src)


def test_prose_mentioning_a_code_does_not_suppress_it():
    # only the LEADING run of code tokens counts: naming FC01 in the
    # trailing prose of an E501 noqa must not silence FC01
    src = ("def f(s, m):\n"
           "    s.latest_messages[0] = m  # noqa: E501 see also FC01 docs\n")
    assert "FC01" in codes("x.py", src)


def test_case_insensitive_noqa_word():
    src = f"{LONG}# NOQA: E501\n"
    assert "E501" not in codes("x.py", src)


def test_noqa_on_other_line_does_not_leak():
    src = f"ok = 1  # noqa\n{LONG}\n"
    assert "E501" in codes("x.py", src)
