"""HD01 host-sync detection: implicit device->host transfers on
device-tainted values inside hot-path modules, with ``# host-sync:``
declared boundaries as the sanctioned escape hatch."""
from analysis import analyze_text
from analysis.dataflow import build_project


def hd01(path, src, project=None):
    return [f for f in analyze_text(path, src, project=project)
            if f.code == "HD01"]


_VIOLATIONS = """\
import numpy as np
import jax
import jax.numpy as jnp

_jit_kernel = jax.jit(lambda x: x * 2)

def pulls(x):
    dev = jnp.asarray(x)
    a = np.asarray(dev)            # np pull-back
    b = float(dev[0])              # scalar cast sync
    for row in dev:                # per-element sync
        pass
    c = dev.item()                 # .item()
    d = dev.tolist()               # .tolist()
    e = np.asarray(_jit_kernel(x))  # compiled-callable result
    return a, b, c, d, e

def unpacked(x):
    r, p = _jit_kernel(x)
    return np.asarray(r), np.asarray(p)   # both taints through unpack
"""


def test_hd01_flags_every_sync_shape_in_hot_dirs():
    lines = [f.line for f in hd01("consensus_specs_tpu/ops/k.py",
                                  _VIOLATIONS)]
    assert lines == [9, 10, 11, 13, 14, 15, 20, 20]


def test_hd01_only_polices_hot_path_modules():
    # the same code outside ops/stf/parallel/forkchoice is free to sync
    assert hd01("consensus_specs_tpu/testing/k.py", _VIOLATIONS) == []
    assert hd01("consensus_specs_tpu/crypto/k.py", _VIOLATIONS) == []
    assert hd01("tools/k.py", _VIOLATIONS) == []


def test_hd01_host_values_do_not_taint():
    src = ("import numpy as np\n"
           "def f(x):\n"
           "    a = np.square(x)\n"
           "    b = np.asarray(a)\n"        # numpy-to-numpy: no device
           "    return float(b[0]), b.tolist()\n")
    assert hd01("consensus_specs_tpu/ops/k.py", src) == []


def test_hd01_jax_host_returning_apis_are_not_seeds():
    src = ("import jax\n"
           "def f():\n"
           "    n = jax.device_count()\n"
           "    return float(n), [d for d in jax.devices()]\n")
    assert hd01("consensus_specs_tpu/ops/k.py", src) == []


def test_hd01_trailing_boundary_declaration_suppresses():
    src = ("import numpy as np\n"
           "import jax.numpy as jnp\n"
           "def f(x):\n"
           "    dev = jnp.asarray(x)\n"
           "    return np.asarray(dev)  # host-sync: staged epoch view\n")
    assert hd01("consensus_specs_tpu/ops/k.py", src) == []


def test_hd01_standalone_boundary_comment_covers_next_statement():
    src = ("import numpy as np\n"
           "import jax.numpy as jnp\n"
           "def f(x):\n"
           "    dev = jnp.asarray(x)\n"
           "    # host-sync: staged view — both outputs pulled once\n"
           "    # (second comment line keeps the block together)\n"
           "    return (np.asarray(dev),\n"
           "            np.asarray(dev))\n")
    assert hd01("consensus_specs_tpu/ops/k.py", src) == []


def test_hd01_bare_boundary_without_justification_does_not_suppress():
    src = ("import numpy as np\n"
           "import jax.numpy as jnp\n"
           "def f(x):\n"
           "    dev = jnp.asarray(x)\n"
           "    return np.asarray(dev)  # host-sync:\n")
    assert [f.line for f in hd01("consensus_specs_tpu/ops/k.py", src)] == [5]


def test_hd01_follows_device_residency_across_files():
    helper = ("import jax.numpy as jnp\n"
              "def device_cols(state):\n"
              "    return jnp.asarray(state.balances)\n")
    # passthrough: a second hop through another file still taints
    middle = ("from consensus_specs_tpu.ops.helper import device_cols\n"
              "def view(state):\n"
              "    return device_cols(state)\n")
    user = ("import numpy as np\n"
            "from consensus_specs_tpu.ops.middle import view\n"
            "def use(state):\n"
            "    cols = view(state)\n"
            "    return np.asarray(cols)\n")
    files = {"consensus_specs_tpu/ops/helper.py": helper,
             "consensus_specs_tpu/ops/middle.py": middle,
             "consensus_specs_tpu/stf/user.py": user}
    proj = build_project(files)
    assert [f.line for f in hd01("consensus_specs_tpu/stf/user.py", user,
                                 project=proj)] == [5]
    # without the project graph the same file has no cross-file facts
    assert hd01("consensus_specs_tpu/stf/user.py", user) == []


def test_hd01_respects_targeted_noqa():
    src = ("import numpy as np\n"
           "import jax.numpy as jnp\n"
           "def f(x):\n"
           "    return np.asarray(jnp.asarray(x))  # noqa: HD01\n")
    assert hd01("consensus_specs_tpu/ops/k.py", src) == []
