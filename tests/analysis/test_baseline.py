"""Baseline semantics: grandfathered findings match on (file, code,
snippet) — line-drift immune — and stale entries are reported so the
baseline cannot rot."""
import json

import pytest

from analysis import run
from analysis.baseline import Baseline


def _write_baseline(tmp_path, entries):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"entries": entries}))
    return p


def _run(tmp_path, baseline):
    return run([tmp_path], root=tmp_path, use_cache=False,
               baseline_path=baseline)


def test_baselined_finding_does_not_fail(tmp_path):
    (tmp_path / "a.py").write_text("import os\n")
    bl = _write_baseline(tmp_path, [{
        "file": "a.py", "code": "F401", "snippet": "import os",
        "justification": "kept for the doctest namespace"}])
    result = _run(tmp_path, bl)
    assert result.findings == []
    assert [f.code for f in result.baselined] == ["F401"]
    assert result.stale_baseline == []


def test_baseline_survives_line_drift(tmp_path):
    (tmp_path / "a.py").write_text("x = 1\ny = 2\n\nimport os\n")
    bl = _write_baseline(tmp_path, [{
        "file": "a.py", "code": "F401", "snippet": "import os",
        "justification": "kept"}])
    result = _run(tmp_path, bl)
    assert result.findings == [] and len(result.baselined) == 1


def test_stale_entry_is_reported(tmp_path):
    (tmp_path / "a.py").write_text("x = 1\n")  # clean: entry is stale
    bl = _write_baseline(tmp_path, [{
        "file": "a.py", "code": "F401", "snippet": "import os",
        "justification": "was needed once"}])
    result = _run(tmp_path, bl)
    assert result.findings == []
    assert [e["code"] for e in result.stale_baseline] == ["F401"]


def test_entry_consumes_at_most_one_finding(tmp_path):
    # a SECOND identical violation in the same file is new, unreviewed
    # code: only one of the two findings is absorbed by the entry
    (tmp_path / "a.py").write_text("x = 1   \ny = 2\nx = 1   \n")
    bl = _write_baseline(tmp_path, [{
        "file": "a.py", "code": "W291", "snippet": "x = 1",
        "justification": "the first one is reviewed"}])
    result = _run(tmp_path, bl)
    assert len(result.baselined) == 1
    assert [(f.line, f.code) for f in result.findings] == [(3, "W291")]
    assert result.stale_baseline == []


def test_deleted_file_entry_is_stale(tmp_path):
    (tmp_path / "a.py").write_text("x = 1\n")
    bl = _write_baseline(tmp_path, [{
        "file": "gone.py", "code": "F401", "snippet": "import os",
        "justification": "for a file that no longer exists"}])
    result = _run(tmp_path, bl)
    assert [e["file"] for e in result.stale_baseline] == ["gone.py"]


def test_out_of_scope_entry_is_not_stale(tmp_path):
    # the entry's file exists but is outside this run's roots: no verdict
    sub = tmp_path / "scanned"
    sub.mkdir()
    (sub / "a.py").write_text("x = 1\n")
    (tmp_path / "other.py").write_text("import os\n")
    bl = _write_baseline(tmp_path, [{
        "file": "other.py", "code": "F401", "snippet": "import os",
        "justification": "kept"}])
    result = run([sub], root=tmp_path, use_cache=False, baseline_path=bl)
    assert result.stale_baseline == []


def test_baseline_does_not_mask_other_findings(tmp_path):
    (tmp_path / "a.py").write_text("import os\nimport sys\n")
    bl = _write_baseline(tmp_path, [{
        "file": "a.py", "code": "F401", "snippet": "import os",
        "justification": "kept"}])
    result = _run(tmp_path, bl)
    assert [(f.line, f.code) for f in result.findings] == [(2, "F401")]


def test_malformed_baseline_entry_rejected(tmp_path):
    p = _write_baseline(tmp_path, [{"file": "a.py", "code": "F401"}])
    with pytest.raises(ValueError, match="snippet"):
        Baseline.load(p)


def test_prune_drops_exactly_the_stale_entries(tmp_path):
    # tools/lint.py --prune-baseline: one file's entry is consumed, one
    # same-file entry no longer fires, one entry's file is deleted — the
    # run reports the latter two stale and prune() rewrites without them
    from analysis.baseline import prune

    (tmp_path / "a.py").write_text("import os\n")
    consumed = {"file": "a.py", "code": "F401", "snippet": "import os",
                "justification": "kept"}
    fixed = {"file": "a.py", "code": "W291", "snippet": "x = 1",
             "justification": "was fixed since"}
    deleted = {"file": "gone.py", "code": "F401", "snippet": "import sys",
               "justification": "file was deleted since"}
    bl = _write_baseline(tmp_path, [consumed, fixed, deleted])
    result = _run(tmp_path, bl)
    assert {e["justification"] for e in result.stale_baseline} == {
        "was fixed since", "file was deleted since"}

    dropped = prune(bl, result.stale_baseline)
    assert {e["justification"] for e in dropped} == {
        "was fixed since", "file was deleted since"}
    kept = json.loads(bl.read_text())["entries"]
    assert kept == [consumed]
    # the pruned baseline round-trips clean: no findings, nothing stale
    again = _run(tmp_path, bl)
    assert again.findings == [] and again.stale_baseline == []


def test_prune_is_a_no_op_without_stale_entries(tmp_path):
    from analysis.baseline import prune

    entry = {"file": "a.py", "code": "F401", "snippet": "import os",
             "justification": "kept"}
    bl = _write_baseline(tmp_path, [entry])
    before = bl.read_text()
    assert prune(bl, []) == []
    assert bl.read_text() == before
    assert prune(tmp_path / "missing.json", [entry]) == []


def test_live_baseline_entries_all_have_justifications():
    from analysis.runner import DEFAULT_BASELINE

    bl = Baseline.load(DEFAULT_BASELINE)
    for e in bl.entries:
        assert e["justification"].strip(), e
