"""Registry contract: every rule is uniquely coded and documented, and
the legacy single-file-checker codes all survived the migration."""
from analysis import REGISTRY, all_rules

LEGACY_CODES = {"E501", "F401", "W291", "W191", "B001", "E999",
                "FC01", "ST01"}
SEMANTIC_CODES = {"CC01", "RB01", "JX01", "DT01"}
HYGIENE_ADDITIONS = {"W605", "B006"}


def test_all_expected_codes_registered():
    rules = {r.code for r in all_rules()}
    assert LEGACY_CODES <= rules
    assert SEMANTIC_CODES <= rules
    assert HYGIENE_ADDITIONS <= rules


def test_every_rule_has_unique_code_summary_and_docs():
    seen = set()
    for rule in all_rules():
        assert rule.code and rule.code not in seen, rule
        seen.add(rule.code)
        assert rule.summary, f"{rule.code} has no summary"
        assert type(rule).__doc__, f"{rule.code} has no docstring"
    assert seen == set(REGISTRY)


def test_rule_subset_selection():
    subset = all_rules(codes=["FC01", "DT01"])
    assert [r.code for r in subset] == ["FC01", "DT01"]
