"""Registry contract: every rule is uniquely coded and documented, and
the legacy single-file-checker codes all survived the migration."""
from analysis import REGISTRY, all_rules

LEGACY_CODES = {"E501", "F401", "W291", "W191", "B001", "E999",
                "FC01", "ST01"}
SEMANTIC_CODES = {"CC01", "RB01", "JX01", "DT01"}
HYGIENE_ADDITIONS = {"W605", "B006"}


def test_all_expected_codes_registered():
    rules = {r.code for r in all_rules()}
    assert LEGACY_CODES <= rules
    assert SEMANTIC_CODES <= rules
    assert HYGIENE_ADDITIONS <= rules


def test_every_rule_has_unique_code_summary_and_docs():
    seen = set()
    for rule in all_rules():
        assert rule.code and rule.code not in seen, rule
        seen.add(rule.code)
        assert rule.summary, f"{rule.code} has no summary"
        assert type(rule).__doc__, f"{rule.code} has no docstring"
    assert seen == set(REGISTRY)


def test_rule_subset_selection():
    subset = all_rules(codes=["FC01", "DT01"])
    assert [r.code for r in subset] == ["FC01", "DT01"]


def test_concurrency_registry_is_duplicate_free():
    from analysis.concurrency_registry import registry_errors

    assert registry_errors() == []


def test_concurrency_registry_duplicates_detected(monkeypatch):
    from analysis import concurrency_registry as creg
    from analysis.concurrency_registry import (LockSpec, RoleSeed,
                                               SharedSpec, registry_errors)

    monkeypatch.setattr(creg, "LOCKS", (
        LockSpec("dup", "m", frozenset({"_L"})),
        LockSpec("dup", "m", frozenset({"_L"})),      # name AND spelling
    ))
    monkeypatch.setattr(creg, "SHARED", (
        SharedSpec("s1", "m", module_globals=frozenset({"_G"})),
        SharedSpec("s2", "m", module_globals=frozenset({"_G"})),  # global
        SharedSpec("s3", "m", module_globals=frozenset({"_H"}),
                   lock="missing"),                   # unknown lock
    ))
    monkeypatch.setattr(creg, "ROLE_SEEDS", (
        RoleSeed("m.f", "producer"),
        RoleSeed("m.f", "producer"),                  # seed twice
        RoleSeed("m.g", "no-such-role"),              # unknown role
    ))
    errors = registry_errors()
    assert len(errors) == 6, errors
    joined = "\n".join(errors)
    for needle in ("'dup' declared twice", "spelling '_L'", "'_G'",
                   "unknown lock 'missing'", "seed 'm.f'",
                   "unknown role 'no-such-role'"):
        assert needle in joined, (needle, errors)


def test_lint_cli_refuses_duplicate_registry(monkeypatch, capsys):
    # `make analyze` (tools/lint.py) exits 2 before analyzing anything
    import lint
    from analysis import concurrency_registry as creg
    from analysis.concurrency_registry import LockSpec

    monkeypatch.setattr(creg, "LOCKS", (
        LockSpec("dup", "m", frozenset({"_L"})),
        LockSpec("dup", "m", frozenset({"_L"})),
    ))
    assert lint.main([]) == 2
    out = capsys.readouterr().out
    assert "concurrency registry error" in out
    assert "concurrency_registry.py" in out
