"""SH01 sharding contracts: every shard_map/pjit callsite binds its
partition specs, names only declared mesh axes, and lives in a module
with a sharded-dim divisibility guard."""
from analysis import analyze_text
from analysis.dataflow import build_project


def sh01(path, src, project=None):
    return [f for f in analyze_text(path, src, project=project)
            if f.code == "SH01"]


_CLEAN = """\
import jax
from jax.sharding import PartitionSpec as P

def launch(mesh, fn, xs):
    f = jax.shard_map(fn, mesh=mesh, in_specs=P("v"), out_specs=P("v"))
    assert xs.shape[0] % 8 == 0, "ragged batch"
    return f(xs)
"""


def test_sh01_contract_respecting_callsite_is_clean():
    assert sh01("consensus_specs_tpu/parallel/x.py", _CLEAN) == []


def test_sh01_missing_specs():
    src = ("import jax\n"
           "def launch(mesh, fn, xs):\n"
           "    assert xs.shape[0] % 8 == 0\n"
           "    g = jax.shard_map(fn, mesh=mesh)\n"
           "    h = jax.shard_map(fn, mesh=mesh, in_specs=None)\n"
           "    return g(xs), h(xs)\n")
    found = sh01("consensus_specs_tpu/parallel/x.py", src)
    assert [f.line for f in found] == [4, 5]
    assert "in_specs / out_specs" in found[0].message
    assert "out_specs" in found[1].message


def test_sh01_undeclared_mesh_axis():
    src = _CLEAN.replace('P("v"), out_specs=P("v")',
                         'P("v"), out_specs=P("rows")')
    found = sh01("consensus_specs_tpu/parallel/x.py", src)
    assert len(found) == 1 and "'rows'" in found[0].message


def test_sh01_axes_come_from_the_projects_mesh_module():
    mesh = ("from jax.sharding import Mesh\n"
            "def build_mesh(devices, axis='lanes', axis2='hosts'):\n"
            "    return Mesh(devices, (axis, axis2))\n")
    user = _CLEAN.replace('P("v"), out_specs=P("v")',
                          'P("lanes"), out_specs=P("hosts")')
    proj = build_project({"consensus_specs_tpu/parallel/mesh.py": mesh,
                          "consensus_specs_tpu/parallel/x.py": user})
    assert sh01("consensus_specs_tpu/parallel/x.py", user,
                project=proj) == []
    # "v" is not declared by THIS mesh module, so the default spelling
    # now fails — the declared vocabulary is the source of truth
    assert len(sh01("consensus_specs_tpu/parallel/x.py", _CLEAN,
                    project=proj)) == 1


def test_sh01_module_needs_divisibility_guard():
    src = ('import jax\n'
           'from jax.sharding import PartitionSpec as P\n'
           'def launch(mesh, fn, xs):\n'
           '    f = jax.shard_map(fn, mesh=mesh, in_specs=P("v"),\n'
           '                      out_specs=P("v"))\n'
           '    return f(xs)\n')
    found = sh01("consensus_specs_tpu/parallel/x.py", src)
    assert len(found) == 1 and "divisibility guard" in found[0].message
    # a pad-to-multiple helper is the other sanctioned guard shape
    assert sh01("consensus_specs_tpu/parallel/x.py",
                src.replace("return f(xs)",
                            "return f(pad_to_multiple(xs))")) == []


def test_sh01_pjit_uses_shardings_spelling():
    src = ("from jax.experimental.pjit import pjit\n"
           "def launch(fn, xs):\n"
           "    assert xs.shape[0] % 8 == 0\n"
           "    return pjit(fn)(xs)\n")
    found = sh01("consensus_specs_tpu/parallel/x.py", src)
    assert len(found) == 1
    assert "in_shardings / out_shardings" in found[0].message


def test_sh01_partial_decorator_form_is_seen():
    src = ("import functools\n"
           "import jax\n"
           "@functools.partial(jax.shard_map, mesh=None)\n"
           "def kernel(x):\n"
           "    return x\n")
    assert len(sh01("consensus_specs_tpu/parallel/x.py", src)) >= 1


def test_sh01_exempts_spec_sources():
    src = ("import jax\n"
           "def launch(mesh, fn, xs):\n"
           "    return jax.shard_map(fn, mesh=mesh)(xs)\n")
    assert sh01("consensus_specs_tpu/specs/src/phase0.py", src) == []


def test_sh01_live_mesh_vocabulary_matches_parallel_mesh():
    # the real tree's mesh.py declares exactly the "v" axis today; the
    # project pass must pick it up from the axis-parameter default
    import pathlib
    mesh_src = (pathlib.Path(__file__).resolve().parents[2]
                / "consensus_specs_tpu/parallel/mesh.py").read_text()
    proj = build_project({"consensus_specs_tpu/parallel/mesh.py": mesh_src})
    assert proj.mesh_axis_names() == {"v"}
