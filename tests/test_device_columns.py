"""Differential pin for the fused device flag-deltas path (ISSUE 8).

``ops/epoch_altair.rewards_and_penalties`` can run its per-flag
reward/penalty loop as ONE jit dispatch over the device-resident
participation column (``stf/columns.device_column``), gated by the
``CSTPU_DEVICE_COLUMNS`` policy.  Both paths must be bit-identical —
exact int64 on either side — so the policy can flip per backend without
a semantics question.  (On the CPU XLA backend the host path wins, which
is why the auto policy stays host-side; this test FORCES the device path
to pin parity regardless of backend.)
"""
import os

import numpy as np

from consensus_specs_tpu.ops import epoch_altair
from consensus_specs_tpu.ssz import bulk
from consensus_specs_tpu.stf import attestations as stf_attestations
from consensus_specs_tpu.testing.context import spec_state_test, with_phases
from consensus_specs_tpu.testing.helpers.attestations import (
    next_epoch_with_attestations,
)
from consensus_specs_tpu.testing.helpers.state import next_epoch


def _force_device_columns(value):
    prev = os.environ.get("CSTPU_DEVICE_COLUMNS")
    if value is None:
        os.environ.pop("CSTPU_DEVICE_COLUMNS", None)
    else:
        os.environ["CSTPU_DEVICE_COLUMNS"] = value
    return prev


def _participating_state(spec, state):
    """Two attestation-bearing epochs: both participation columns carry
    real flag spreads when rewards run."""
    next_epoch(spec, state)
    _, _, s = next_epoch_with_attestations(spec, state, True, True)
    _, _, s = next_epoch_with_attestations(spec, s, True, True)
    return s


@with_phases(["altair"])
@spec_state_test
def test_device_flag_deltas_bit_identical(spec, state):
    s = _participating_state(spec, state)
    s_host, s_dev = s.copy(), s.copy()
    stf_attestations.reset_caches()
    assert not epoch_altair._device_columns_policy()  # auto stays host
    epoch_altair.rewards_and_penalties(spec, s_host)
    prev = _force_device_columns("1")
    try:
        assert epoch_altair._device_columns_policy()
        epoch_altair.rewards_and_penalties(spec, s_dev)
    finally:
        _force_device_columns(prev)
    host_bal = bulk.packed_uint64_to_numpy(s_host.balances)
    dev_bal = bulk.packed_uint64_to_numpy(s_dev.balances)
    assert np.array_equal(host_bal, dev_bal)
    assert bytes(s_host.hash_tree_root()) == bytes(s_dev.hash_tree_root())
    yield None


@with_phases(["altair"])
@spec_state_test
def test_device_column_uploaded_once_per_version(spec, state):
    """The device buffer is keyed by the column's tree root: a second
    consumer of the same version gets the SAME device array back, and a
    flush (new root) re-uploads."""
    from consensus_specs_tpu.stf import columns

    s = _participating_state(spec, state)
    stf_attestations.reset_caches()
    first = columns.device_column(s, current=False)
    assert columns.device_column(s, current=False) is first
    # a flush registers a new version under the new root
    col = columns.staged_view(s, current=False)
    col[:] = 0
    columns.flush(s, current=False, col=col)
    assert columns.device_column(s, current=False) is not first
    yield None


@with_phases(["altair"])
@spec_state_test
def test_device_policy_off_forces_host(spec, state):
    s = _participating_state(spec, state)
    prev = _force_device_columns("0")
    try:
        assert not epoch_altair._device_columns_policy()
        # and the full epoch still runs (host loop) with flags present
        s2 = s.copy()
        epoch_altair.rewards_and_penalties(spec, s2)
    finally:
        _force_device_columns(prev)
    yield None
