"""Snappy block-format codec tests: roundtrips, wire-format cases, and
hand-built streams exercising every tag type."""
import random

import pytest

from consensus_specs_tpu.gen.snappy import compress, decompress


@pytest.mark.parametrize("data", [
    b"",
    b"a",
    b"abc",
    b"\x00" * 100,
    b"ab" * 5000,
    bytes(range(256)) * 10,
    b"the quick brown fox jumps over the lazy dog " * 50,
])
def test_roundtrip(data):
    assert decompress(compress(data)) == data


def test_roundtrip_random():
    rng = random.Random(5)
    for _ in range(20):
        n = rng.randint(0, 5000)
        # mixture of compressible runs and random bytes
        data = b"".join(
            bytes([rng.randrange(4)]) * rng.randint(1, 40) for _ in range(n // 20 + 1)
        )[:n]
        assert decompress(compress(data)) == data


def test_compression_actually_compresses():
    # runs compress to ~3 bytes per 64-byte copy element (same order as
    # reference snappy, which also caps copies at 64 bytes)
    data = b"\x00" * 10000
    assert len(compress(data)) < 600


def test_decompress_handcrafted_all_tags():
    # literal "abcd", copy1 (offset 4 len 4), copy2 (offset 2 len 5),
    # copy4 (offset 8 len 4)
    stream = bytearray()
    stream += bytes([17])  # varint uncompressed length = 4+4+5+4
    stream += bytes([(4 - 1) << 2]) + b"abcd"          # literal len 4
    stream += bytes([((4 - 4) << 2) | 0b01, 4])        # copy1: off 4 len 4
    stream += bytes([((5 - 1) << 2) | 0b10, 2, 0])     # copy2: off 2 len 5 (overlap)
    stream += bytes([((4 - 1) << 2) | 0b11, 8, 0, 0, 0])  # copy4: off 8 len 4
    out = decompress(bytes(stream))
    assert out[:8] == b"abcdabcd"
    assert out[8:13] == b"cdcdc"  # overlapping copy repeats the pair
    assert len(out) == 17


def test_decompress_long_literal_lengths():
    for n in (59, 60, 61, 300, 70000):
        data = bytes([7]) * n
        assert decompress(compress(data)) == data


def test_decompress_rejects_bad_streams():
    with pytest.raises(ValueError):
        decompress(b"")  # truncated varint? empty input
    with pytest.raises(ValueError):
        decompress(bytes([5, (4 - 1) << 2, 65]))  # truncated literal
    with pytest.raises(ValueError):
        # copy with offset beyond output
        decompress(bytes([4, ((4 - 1) << 2) | 0b10, 9, 0]))
    with pytest.raises(ValueError):
        # length mismatch vs header
        decompress(bytes([9, (4 - 1) << 2]) + b"abcd")
