"""Runtime YAML loader tests — including a full parity sweep: loading the
reference's own presets/configs YAML must reproduce this framework's
baked-in preset/config data for every shared var."""
from pathlib import Path

import pytest

from consensus_specs_tpu.config import get_config, get_preset
from consensus_specs_tpu.config.config_util import (
    load_config_file,
    load_preset,
    load_preset_dir,
    parse_config_vars,
)

REFERENCE = Path("/root/reference")


def test_parse_config_vars_types(tmp_path):
    parsed = parse_config_vars({
        "PRESET_BASE": "minimal",
        "CONFIG_NAME": "testnet",
        "SLOTS_PER_EPOCH": "8",
        "GENESIS_FORK_VERSION": "0x00000001",
        "SOME_LIST": ["1", "2", "x"],
    })
    assert parsed["PRESET_BASE"] == "minimal"
    assert parsed["SLOTS_PER_EPOCH"] == 8
    assert parsed["GENESIS_FORK_VERSION"] == b"\x00\x00\x00\x01"
    assert parsed["SOME_LIST"] == [1, 2, "x"]


def test_duplicate_preset_vars_fatal(tmp_path):
    a = tmp_path / "a.yaml"
    b = tmp_path / "b.yaml"
    a.write_text("SLOTS_PER_EPOCH: 8\n")
    b.write_text("SLOTS_PER_EPOCH: 16\n")
    with pytest.raises(Exception, match="duplicate"):
        load_preset([a, b])


def test_empty_files_skipped(tmp_path):
    a = tmp_path / "a.yaml"
    b = tmp_path / "b.yaml"
    a.write_text("")
    b.write_text("MAX_FOO: 4\n")
    assert load_preset([a, b]) == {"MAX_FOO": 4}


@pytest.mark.skipif(not REFERENCE.exists(), reason="reference not vendored")
@pytest.mark.parametrize("preset_name", ["minimal", "mainnet"])
def test_reference_preset_yaml_matches_baked_data(preset_name):
    """Every var in the reference's preset YAMLs must equal the baked-in
    preset data (the YAMLs are the normative source the reference's
    compiler consumes)."""
    loaded = load_preset_dir(REFERENCE / "presets" / preset_name)
    baked = get_preset(preset_name)
    # Documented deltas between the reference YAMLs and the baked data:
    # - MAX_CUSTODY_CHUNK_CHALLENGE_RESP: the YAML's abbreviation of the
    #   markdown's ..._RESPONSES name (values must still match);
    # - *_SAMPLES_PER_BLOCK: the YAML lags the markdown's *_PER_BLOB
    #   rename; minimal values deliberately shrunk here for NTT-test
    #   tractability (see config/presets.py) on this never-compiled fork
    renamed = {
        "MAX_CUSTODY_CHUNK_CHALLENGE_RESP":
            "MAX_CUSTODY_CHUNK_CHALLENGE_RESPONSES",
        "MAX_SAMPLES_PER_BLOCK": "MAX_SAMPLES_PER_BLOB",
        "TARGET_SAMPLES_PER_BLOCK": "TARGET_SAMPLES_PER_BLOB",
    }
    value_deviations = {"MAX_SAMPLES_PER_BLOB", "TARGET_SAMPLES_PER_BLOB"} \
        if preset_name == "minimal" else set()
    mismatches = {}
    for key, value in loaded.items():
        our_key = renamed.get(key, key)
        if our_key in value_deviations:
            assert baked[our_key] <= value  # shrunk, never enlarged
            continue
        if baked.get(our_key) != value:
            mismatches[key] = (value, baked.get(our_key))
    assert mismatches == {}


@pytest.mark.skipif(not REFERENCE.exists(), reason="reference not vendored")
@pytest.mark.parametrize("config_name", ["minimal", "mainnet"])
def test_reference_config_yaml_matches_baked_data(config_name):
    loaded = load_config_file(REFERENCE / "configs" / f"{config_name}.yaml")
    baked = get_config(config_name).to_dict()

    def norm(x):
        if isinstance(x, (bytes, bytearray)):
            return bytes(x)
        try:
            return int(x)
        except (TypeError, ValueError):
            return str(x)

    mismatches = {
        key: (value, baked.get(key, "<missing>"))
        for key, value in loaded.items()
        if norm(value) != norm(baked.get(key, "<missing>"))
    }
    assert mismatches == {}
