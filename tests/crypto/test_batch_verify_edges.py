"""BatchFastAggregateVerify edge cases — the failure modes the block
engine's bisection fallback leans on (stf/verify.py, crypto/bls/native.py).

Covered: the vacuous empty batch, duplicate messages across items (the
RLC scalars must keep the equations independent), a single tampered
signature hiding inside a 128-item batch (bisection must name exactly it),
deterministic-seed replay (same seed -> same verdict, byte-for-byte
reproducible batches for test vectors), the sync-aggregate entry shape
the altair lineage folds into the block batch (stf/sync.py: empty
participation, duplicate seats, bisection naming the sync entry, triple-
memo dedup of a re-carried aggregate), and the verified-triple memo's
FIFO bound."""
import hashlib

import pytest

from consensus_specs_tpu.crypto import bls as bls_facade
from consensus_specs_tpu.stf import verify as stf_verify

native = pytest.importorskip(
    "consensus_specs_tpu.crypto.bls.native",
    reason="native BLS backend unavailable on this host")


def _item(sks, msg):
    pks = [native.SkToPk(sk) for sk in sks]
    sig = native.Aggregate([native.Sign(sk, msg) for sk in sks])
    return pks, msg, sig


def _flat(pks, msg, sig):
    affines = b"".join(native.pubkey_affine(pk) for pk in pks)
    return (len(pks), affines, bytes(msg), bytes(sig))


@pytest.fixture(scope="module")
def batch128():
    """128 4-member aggregates over distinct messages."""
    return [_item(range(4 * i + 1, 4 * i + 5),
                  hashlib.sha256(bytes([i])).digest()) for i in range(128)]


def test_empty_batch_is_vacuously_true():
    assert native.BatchFastAggregateVerify([]) is True
    assert native.BatchFastAggregateVerifyFlat([], b"", [], []) is True
    assert stf_verify.settle([], []) is None


def test_duplicate_messages_across_items():
    """Same message signed by different key sets: every equation must be
    weighed independently (a naive shared-message merge would let one
    valid item mask another's tampered signature)."""
    msg = b"\x07" * 32
    a = _item((1, 2, 3), msg)
    b = _item((4, 5, 6), msg)
    assert native.BatchFastAggregateVerify([a, b])
    bad = (b[0], b[1], native.Aggregate(
        [native.Sign(sk, b"\x08" * 32) for sk in (4, 5, 6)]))
    assert not native.BatchFastAggregateVerify([a, bad])
    assert not native.BatchFastAggregateVerify([bad, a])


def test_single_tampered_signature_in_128_item_batch(batch128):
    for poison in (0, 77, 127):
        items = list(batch128)
        pks, msg, _ = items[poison]
        wrong = native.Aggregate([native.Sign(999, msg)])
        items[poison] = (pks, msg, wrong)
        assert not native.BatchFastAggregateVerify(items)
        # facade bisection names exactly the poisoned index
        entries = [(tuple(bytes(p) for p in pks_), bytes(m), bytes(s))
                   for pks_, m, s in items]
        assert bls_facade._first_invalid(entries) == poison
        # stf flat-path bisection agrees
        flat = [_flat(*it) for it in items]
        assert stf_verify.first_invalid(flat) == poison


def test_flat_path_matches_compressed_path(batch128):
    items = batch128[:8]
    flat = [_flat(*it) for it in items]
    counts, affines, msgs, sigs = zip(*flat)
    assert native.BatchFastAggregateVerifyFlat(
        counts, b"".join(affines), msgs, sigs)
    assert native.BatchFastAggregateVerify(items)


def test_deterministic_seed_replay(batch128):
    items = batch128[:16]
    seed = b"\x5a" * 32
    for _ in range(3):
        assert native.BatchFastAggregateVerify(items, seed=seed)
    tampered = list(items)
    pks, msg, _ = tampered[9]
    tampered[9] = (pks, msg, native.Aggregate([native.Sign(31337, msg)]))
    for _ in range(3):
        assert not native.BatchFastAggregateVerify(tampered, seed=seed)
    with pytest.raises(ValueError, match="32 bytes"):
        native.BatchFastAggregateVerify(items, seed=b"\x01" * 16)
    count, affines, msg, sig = _flat(*items[0])
    with pytest.raises(ValueError, match="32 bytes"):
        native.BatchFastAggregateVerifyFlat(
            [count], affines, [msg], [sig], seed=b"short")


def test_flat_input_validation(batch128):
    count, affines, msg, sig = _flat(*batch128[0])
    # inconsistent affine buffer size
    with pytest.raises(ValueError, match="inconsistent"):
        native.BatchFastAggregateVerifyFlat(
            [count + 1], affines, [msg], [sig])
    # zero-member item: invalid, not an error
    assert not native.BatchFastAggregateVerifyFlat([0], b"", [msg], [sig])
    # malformed signature length: invalid
    assert not native.BatchFastAggregateVerifyFlat(
        [count], affines, [msg], [sig[:95]])


def test_verified_triple_memo_roundtrip(batch128):
    stf_verify.reset_memo()
    entries = [_flat(*it) for it in batch128[:4]]
    keys = [stf_verify.triple_key(e[1], e[2], e[3]) for e in entries]
    assert not any(stf_verify.is_verified(k) for k in keys)
    assert stf_verify.settle(entries, keys) is None
    assert all(stf_verify.is_verified(k) for k in keys)
    stf_verify.reset_memo()
    assert not stf_verify.is_verified(keys[0])


def test_verified_triple_memo_fifo_bound(batch128, monkeypatch):
    """The memo is bounded: past the cap the OLDEST triples evict first
    (FIFO), eviction count and cap are visible in stats, and a replay can
    never grow the memo without limit."""
    monkeypatch.setattr(stf_verify, "_VERIFIED_MEMO_MAX", 4)
    stf_verify.reset_memo()
    stf_verify.reset_stats()
    assert stf_verify.stats["memo_cap"] == 4
    entries = [_flat(*it) for it in batch128[:6]]
    keys = [stf_verify.triple_key(e[1], e[2], e[3]) for e in entries]
    assert stf_verify.settle(entries[:4], keys[:4]) is None
    assert stf_verify.stats["memo_evictions"] == 0
    assert all(stf_verify.is_verified(k) for k in keys[:4])
    # two more distinct triples: the two oldest fall out
    assert stf_verify.settle(entries[4:], keys[4:]) is None
    assert stf_verify.stats["memo_evictions"] == 2
    assert len(stf_verify._VERIFIED_MEMO) == 4
    assert not stf_verify.is_verified(keys[0])
    assert not stf_verify.is_verified(keys[1])
    assert all(stf_verify.is_verified(k) for k in keys[2:])
    # re-settling an evicted triple re-inserts without double-counting
    assert stf_verify.settle(entries[:1], keys[:1]) is None
    assert stf_verify.stats["memo_evictions"] == 3
    stf_verify.reset_memo()
    stf_verify.reset_stats()
    assert stf_verify.stats["memo_cap"] == 4  # cap is a readout, not a counter


# -- sync-aggregate entries (the altair lineage's addition to the batch) ------


@pytest.fixture(scope="module")
def altair_env():
    """Minimal altair genesis + a collector matching the engine's
    per-block entry protocol (stf/engine.py collect)."""
    from consensus_specs_tpu.specs.builder import get_spec
    from consensus_specs_tpu.testing.context import (
        default_activation_threshold,
        default_balances,
    )
    from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state

    spec = get_spec("altair", "minimal")
    state = create_genesis_state(
        spec=spec,
        validator_balances=default_balances(spec),
        activation_threshold=default_activation_threshold(spec))
    # a couple of slots of history so the previous-slot block root the
    # sync message signs over exists (sync aggregates ride blocks >= 1)
    spec.process_slots(state, spec.Slot(2))
    return spec, state


def _collector():
    entries, keys = [], []

    def collect(members_id, count, flat, message, signature):
        key = stf_verify.triple_key(members_id, message, signature)
        if stf_verify.is_verified(key):
            return
        entries.append((count, flat(), message, signature))
        keys.append(key)

    return entries, keys, collect


def _signed_sync_aggregate(spec, state, participants, bits):
    from consensus_specs_tpu.testing.helpers.sync_committee import (
        compute_aggregate_sync_committee_signature,
    )

    return spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, int(state.slot) - 1, participants))


def test_sync_empty_participation_bitvector(altair_env):
    """Empty participation contributes NO batch entry when it carries the
    infinity signature (eth_fast_aggregate_verify's one non-pairing
    acceptance), and trips the fast path for any other signature."""
    from consensus_specs_tpu.stf import sync as stf_sync
    from consensus_specs_tpu.stf.attestations import FastPathViolation

    spec, state = altair_env
    size = int(spec.SYNC_COMMITTEE_SIZE)
    stf_verify.reset_memo()
    entries, keys, collect = _collector()
    stf_sync.process_sync_aggregate(
        spec, state.copy(),
        spec.SyncAggregate(sync_committee_bits=[False] * size,
                           sync_committee_signature=spec.G2_POINT_AT_INFINITY),
        collect, True)
    assert entries == [] and keys == []
    with pytest.raises(FastPathViolation, match="non-infinity"):
        stf_sync.process_sync_aggregate(
            spec, state.copy(),
            spec.SyncAggregate(
                sync_committee_bits=[False] * size,
                sync_committee_signature=spec.BLSSignature(b"\x01" * 96)),
            collect, True)


def test_sync_duplicate_participant_keys(altair_env):
    """A committee seating the SAME validator in every seat (duplicates
    are legal — sync sampling is with replacement): the entry carries the
    duplicated affine rows and the aggregate still verifies."""
    from consensus_specs_tpu.stf import sync as stf_sync

    spec, state = altair_env
    state = state.copy()
    size = int(spec.SYNC_COMMITTEE_SIZE)
    pk0 = state.validators[0].pubkey
    state.current_sync_committee = spec.SyncCommittee(
        pubkeys=[pk0] * size,
        aggregate_pubkey=spec.eth_aggregate_pubkeys([pk0] * size))
    aggregate = _signed_sync_aggregate(spec, state, [0] * size, [True] * size)
    stf_verify.reset_memo()
    entries, keys, collect = _collector()
    stf_sync.process_sync_aggregate(spec, state, aggregate, collect, True)
    assert len(entries) == 1
    count, flat, _, _ = entries[0]
    assert count == size and len(flat) == size * 96
    assert flat == flat[:96] * size  # every member row is validator 0's
    assert stf_verify.settle(entries, keys) is None


def test_sync_tampered_signature_bisected_to_sync_entry(altair_env, batch128):
    """A block-shaped batch (attestation entries first, the sync entry
    last) with a tampered sync signature: bisection must name exactly the
    sync entry."""
    from consensus_specs_tpu.stf import sync as stf_sync

    spec, state = altair_env
    size = int(spec.SYNC_COMMITTEE_SIZE)
    participants = list(range(size))
    aggregate = _signed_sync_aggregate(
        spec, state, participants[:-1], [True] * size)  # one signer short
    stf_verify.reset_memo()
    entries, keys, collect = _collector()
    stf_sync.process_sync_aggregate(spec, state.copy(), aggregate, collect, True)
    assert len(entries) == 1
    full = [_flat(*it) for it in batch128[:5]] + entries
    assert stf_verify.first_invalid(full) == 5


def test_sync_entry_deduped_across_blocks_via_triple_memo(altair_env):
    """A re-carried sync aggregate (same previous-slot root, same
    signature — gossip re-delivery, or the same block replayed on a fork
    sharing the parent) settles once: the second collection is dropped by
    the verified-triple memo."""
    from consensus_specs_tpu.stf import sync as stf_sync

    spec, state = altair_env
    size = int(spec.SYNC_COMMITTEE_SIZE)
    from consensus_specs_tpu.testing.helpers.sync_committee import (
        compute_committee_indices,
    )

    participants = compute_committee_indices(spec, state)
    aggregate = _signed_sync_aggregate(spec, state, participants, [True] * size)
    stf_verify.reset_memo()
    stf_verify.reset_stats()
    entries, keys, collect = _collector()
    stf_sync.process_sync_aggregate(spec, state.copy(), aggregate, collect, True)
    assert len(entries) == 1
    assert stf_verify.settle(entries, keys) is None
    entries2, keys2, collect2 = _collector()
    stf_sync.process_sync_aggregate(spec, state.copy(), aggregate, collect2, True)
    assert entries2 == [] and keys2 == []  # memoized: no second pairing
    assert stf_verify.stats["memo_hits"] >= 1
