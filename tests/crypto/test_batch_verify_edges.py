"""BatchFastAggregateVerify edge cases — the failure modes the block
engine's bisection fallback leans on (stf/verify.py, crypto/bls/native.py).

Covered: the vacuous empty batch, duplicate messages across items (the
RLC scalars must keep the equations independent), a single tampered
signature hiding inside a 128-item batch (bisection must name exactly it),
and deterministic-seed replay (same seed -> same verdict, byte-for-byte
reproducible batches for test vectors)."""
import hashlib

import pytest

from consensus_specs_tpu.crypto import bls as bls_facade
from consensus_specs_tpu.stf import verify as stf_verify

native = pytest.importorskip(
    "consensus_specs_tpu.crypto.bls.native",
    reason="native BLS backend unavailable on this host")


def _item(sks, msg):
    pks = [native.SkToPk(sk) for sk in sks]
    sig = native.Aggregate([native.Sign(sk, msg) for sk in sks])
    return pks, msg, sig


def _flat(pks, msg, sig):
    affines = b"".join(native.pubkey_affine(pk) for pk in pks)
    return (len(pks), affines, bytes(msg), bytes(sig))


@pytest.fixture(scope="module")
def batch128():
    """128 4-member aggregates over distinct messages."""
    return [_item(range(4 * i + 1, 4 * i + 5),
                  hashlib.sha256(bytes([i])).digest()) for i in range(128)]


def test_empty_batch_is_vacuously_true():
    assert native.BatchFastAggregateVerify([]) is True
    assert native.BatchFastAggregateVerifyFlat([], b"", [], []) is True
    assert stf_verify.settle([], []) is None


def test_duplicate_messages_across_items():
    """Same message signed by different key sets: every equation must be
    weighed independently (a naive shared-message merge would let one
    valid item mask another's tampered signature)."""
    msg = b"\x07" * 32
    a = _item((1, 2, 3), msg)
    b = _item((4, 5, 6), msg)
    assert native.BatchFastAggregateVerify([a, b])
    bad = (b[0], b[1], native.Aggregate(
        [native.Sign(sk, b"\x08" * 32) for sk in (4, 5, 6)]))
    assert not native.BatchFastAggregateVerify([a, bad])
    assert not native.BatchFastAggregateVerify([bad, a])


def test_single_tampered_signature_in_128_item_batch(batch128):
    for poison in (0, 77, 127):
        items = list(batch128)
        pks, msg, _ = items[poison]
        wrong = native.Aggregate([native.Sign(999, msg)])
        items[poison] = (pks, msg, wrong)
        assert not native.BatchFastAggregateVerify(items)
        # facade bisection names exactly the poisoned index
        entries = [(tuple(bytes(p) for p in pks_), bytes(m), bytes(s))
                   for pks_, m, s in items]
        assert bls_facade._first_invalid(entries) == poison
        # stf flat-path bisection agrees
        flat = [_flat(*it) for it in items]
        assert stf_verify.first_invalid(flat) == poison


def test_flat_path_matches_compressed_path(batch128):
    items = batch128[:8]
    flat = [_flat(*it) for it in items]
    counts, affines, msgs, sigs = zip(*flat)
    assert native.BatchFastAggregateVerifyFlat(
        counts, b"".join(affines), msgs, sigs)
    assert native.BatchFastAggregateVerify(items)


def test_deterministic_seed_replay(batch128):
    items = batch128[:16]
    seed = b"\x5a" * 32
    for _ in range(3):
        assert native.BatchFastAggregateVerify(items, seed=seed)
    tampered = list(items)
    pks, msg, _ = tampered[9]
    tampered[9] = (pks, msg, native.Aggregate([native.Sign(31337, msg)]))
    for _ in range(3):
        assert not native.BatchFastAggregateVerify(tampered, seed=seed)
    with pytest.raises(ValueError, match="32 bytes"):
        native.BatchFastAggregateVerify(items, seed=b"\x01" * 16)
    count, affines, msg, sig = _flat(*items[0])
    with pytest.raises(ValueError, match="32 bytes"):
        native.BatchFastAggregateVerifyFlat(
            [count], affines, [msg], [sig], seed=b"short")


def test_flat_input_validation(batch128):
    count, affines, msg, sig = _flat(*batch128[0])
    # inconsistent affine buffer size
    with pytest.raises(ValueError, match="inconsistent"):
        native.BatchFastAggregateVerifyFlat(
            [count + 1], affines, [msg], [sig])
    # zero-member item: invalid, not an error
    assert not native.BatchFastAggregateVerifyFlat([0], b"", [msg], [sig])
    # malformed signature length: invalid
    assert not native.BatchFastAggregateVerifyFlat(
        [count], affines, [msg], [sig[:95]])


def test_verified_triple_memo_roundtrip(batch128):
    stf_verify.reset_memo()
    entries = [_flat(*it) for it in batch128[:4]]
    keys = [stf_verify.triple_key(e[1], e[2], e[3]) for e in entries]
    assert not any(stf_verify.is_verified(k) for k in keys)
    assert stf_verify.settle(entries, keys) is None
    assert all(stf_verify.is_verified(k) for k in keys)
    stf_verify.reset_memo()
    assert not stf_verify.is_verified(keys[0])
