"""Differential pins for ``bls_g2_msm`` — the variable-base Pippenger
bucket machinery the batch verifier's signature fold runs on (ISSUE 7).

The native MSM is pinned against the pure-Python per-point scalar-mul
oracle (sum of ``Point.mul`` — the dumbest possible evaluation), across
random inputs, infinity/identity lanes, and off-subgroup rejection; the
same-message lane folding of the batch verifier is pinned to the
UNFOLDED verdict, including a tampered-entry case where bisection must
still name the same leftmost original entry the unfolded walk would.
"""
import hashlib
import random

import pytest

native = pytest.importorskip(
    "consensus_specs_tpu.crypto.bls.native",
    reason="native BLS backend unavailable on this host")

from consensus_specs_tpu.crypto import bls as bls_facade
from consensus_specs_tpu.crypto.bls.curve import (
    g2_generator,
    g2_to_bytes,
    signature_to_point,
)
from consensus_specs_tpu.crypto.bls.fields import R
from consensus_specs_tpu.stf import verify as stf_verify

G2_INF = bytes([0xC0]) + b"\x00" * 95


def _oracle_msm(points: bytes, scalars: bytes) -> bytes:
    """sum_i [s_i]Q_i the slow way: per-point double-and-add + point add."""
    n = len(points) // 96
    acc = None
    for i in range(n):
        q = signature_to_point(points[96 * i:96 * (i + 1)])
        s = int.from_bytes(scalars[32 * i:32 * (i + 1)], "big")
        term = q.mul(s)
        acc = term if acc is None else acc + term
    return g2_to_bytes(acc)


def _rand_inputs(rng, n):
    points = b"".join(
        bytes(native.Sign(rng.randrange(1, R), b"g2msm")) for _ in range(n))
    scalars = b"".join(
        rng.randrange(R).to_bytes(32, "big") for _ in range(n))
    return points, scalars


@pytest.mark.parametrize("n", [1, 2, 3, 7, 16])
def test_g2_msm_matches_per_point_oracle(n):
    rng = random.Random(1000 + n)
    points, scalars = _rand_inputs(rng, n)
    assert native.G2MSM(points, scalars) == _oracle_msm(points, scalars)


@pytest.mark.slow
def test_g2_msm_matches_per_point_oracle_deep():
    """Deep enough that multiple Pippenger windows and bucket-collision
    paths are exercised (several points per bucket)."""
    rng = random.Random(77)
    points, scalars = _rand_inputs(rng, 192)
    assert native.G2MSM(points, scalars) == _oracle_msm(points, scalars)


def test_g2_msm_identity_lanes():
    rng = random.Random(5)
    points, scalars = _rand_inputs(rng, 3)
    # zero scalars contribute nothing
    zeroed = scalars[:32] + b"\x00" * 32 + scalars[64:]
    assert native.G2MSM(points, zeroed) == _oracle_msm(points, zeroed)
    # infinity points contribute nothing
    with_inf = points[:96] + G2_INF + points[192:]
    assert native.G2MSM(with_inf, scalars) == _oracle_msm(with_inf, scalars)
    # all-infinity and empty inputs sum to the identity
    assert native.G2MSM(G2_INF * 2, scalars[:64]) == G2_INF
    assert native.G2MSM(b"", b"") == G2_INF
    # scalar == r folds to the identity (the oracle reduces mod r the
    # group-order way: [r]Q == inf)
    r_scalar = R.to_bytes(32, "big")
    assert native.G2MSM(points[:96], r_scalar) == G2_INF


def test_g2_msm_scalar_one_roundtrip():
    sig = bytes(native.Sign(42, b"roundtrip"))
    one = (1).to_bytes(32, "big")
    assert native.G2MSM(sig, one) == sig
    # [2]G2 via two lanes of the generator
    g = g2_to_bytes(g2_generator())
    assert native.G2MSM(g + g, one + one) == g2_to_bytes(
        g2_generator().mul(2))


def test_g2_msm_rejects_off_subgroup():
    """On-curve points outside the r-order subgroup must raise, exactly
    as load_signature rejects them everywhere else — a hole here would
    let a rogue fold input through the bucketed path."""
    from consensus_specs_tpu.crypto.bls.curve import Point
    from consensus_specs_tpu.crypto.bls.fields import Fq2, P

    rng = random.Random(99)
    b2 = Fq2(4, 4)
    while True:
        x = Fq2(rng.randrange(P), rng.randrange(P))
        y = (x.square() * x + b2).sqrt()
        if y is None:
            continue
        pt = Point(x, y, Fq2.one(), b2)
        if not pt.in_subgroup():
            break
    bad = g2_to_bytes(pt)
    one = (1).to_bytes(32, "big")
    with pytest.raises(ValueError, match="off-subgroup|malformed"):
        native.G2MSM(bad, one)
    # malformed shapes fail fast
    with pytest.raises(ValueError):
        native.G2MSM(b"\x00" * 95, one)
    with pytest.raises(ValueError):
        native.G2MSM(bytes(native.Sign(1, b"x")), one + one)


# ---------------------------------------------------------------------------
# folded-vs-unfolded batch verdict parity
# ---------------------------------------------------------------------------


def _item(sks, msg):
    pks = [native.SkToPk(sk) for sk in sks]
    sig = native.Aggregate([native.Sign(sk, msg) for sk in sks])
    return pks, msg, sig


def _flat(pks, msg, sig):
    affines = b"".join(native.pubkey_affine(pk) for pk in pks)
    return (len(pks), affines, bytes(msg), bytes(sig))


def _shared_msg_batch(n_msgs=4, lanes_per_msg=3):
    """The engine's real same-message shape: aggregates re-covering the
    SAME AttestationData with different committees — byte-identical
    messages across items, so the C side folds them into one Miller
    lane."""
    items = []
    for m in range(n_msgs):
        msg = hashlib.sha256(bytes([0xA0 + m])).digest()
        for lane in range(lanes_per_msg):
            base = 100 * m + 10 * lane + 1
            items.append(_item(range(base, base + 3), msg))
    return items


def test_folded_batch_accepts_what_unfolded_accepts():
    items = _shared_msg_batch()
    # folded path (shared messages collapse to n_msgs Miller lanes)
    assert native.BatchFastAggregateVerify(items, seed=b"\x11" * 32)
    # unfolded oracle: every item alone (k == 1 batches fold nothing)
    for it in items:
        assert native.BatchFastAggregateVerify([it], seed=b"\x12" * 32)


@pytest.mark.parametrize("poison", [0, 4, 11])
def test_folded_batch_bisects_to_same_leftmost_entry(poison):
    """Tampering one entry of a shared-message batch: the folded batch
    must fail, and the bisection walk must name the SAME leftmost
    original entry the per-item oracle identifies — folding may merge
    lanes inside one native call, but a sub-batch call re-folds within
    the subset it was handed, so descent stays exact (the BDLO12
    batch-forgery-identification contract)."""
    items = _shared_msg_batch()  # 12 items, 4 unique messages
    pks, msg, _ = items[poison]
    items[poison] = (pks, msg, native.Aggregate([native.Sign(999, msg)]))
    assert not native.BatchFastAggregateVerify(items, seed=b"\x13" * 32)
    # per-item oracle: which entries are actually bad?
    oracle_bad = [i for i, it in enumerate(items)
                  if not native.BatchFastAggregateVerify([it])]
    assert oracle_bad == [poison]
    entries = [(tuple(bytes(p) for p in pks_), bytes(m), bytes(s))
               for pks_, m, s in items]
    assert bls_facade._first_invalid(entries) == poison
    flat = [_flat(*it) for it in items]
    assert stf_verify.first_invalid(flat) == poison


def test_folded_batch_two_tampered_same_message_names_leftmost():
    """Both lanes of one folded message group tampered: bisection must
    still land on the LEFTMOST original entry, not the group."""
    items = _shared_msg_batch(n_msgs=2, lanes_per_msg=3)
    msg = items[2][1]
    assert items[2][1] == items[1][1]  # same message group
    for i in (1, 2):
        pks, m, _ = items[i]
        items[i] = (pks, m, native.Aggregate([native.Sign(998 + i, m)]))
    flat = [_flat(*it) for it in items]
    assert stf_verify.first_invalid(flat) == 1


@pytest.mark.slow
def test_folded_batch_parity_deep():
    """128-item batch with heavy message sharing, every verdict pinned
    both ways (tier-1 budget: slow-marked)."""
    items = _shared_msg_batch(n_msgs=8, lanes_per_msg=16)
    assert native.BatchFastAggregateVerify(items, seed=b"\x21" * 32)
    pks, msg, _ = items[100]
    items[100] = (pks, msg, native.Aggregate([native.Sign(997, msg)]))
    assert not native.BatchFastAggregateVerify(items, seed=b"\x22" * 32)
    flat = [_flat(*it) for it in items]
    assert stf_verify.first_invalid(flat) == 100
