"""KZG + Fr FFT + device MSM tests: host oracle self-consistency and the
batched JAX scalar-mult differential."""
import random

import pytest

from consensus_specs_tpu.crypto import fr, kzg
from consensus_specs_tpu.crypto.bls.curve import g1_generator, g1_to_bytes

rng = random.Random(1717)


def test_fft_roundtrip_and_convolution_theorem():
    vals = [rng.randrange(fr.R) for _ in range(128)]
    assert fr.ifft(fr.fft(vals)) == [v % fr.R for v in vals]
    # multiplication in evaluation form == poly_mul in coefficient form
    a = [rng.randrange(fr.R) for _ in range(8)] + [0] * 8
    b = [rng.randrange(fr.R) for _ in range(8)] + [0] * 8
    ea, eb = fr.fft(a), fr.fft(b)
    prod_evals = [x * y % fr.R for x, y in zip(ea, eb)]
    expected = fr.poly_mul(a[:8], b[:8])
    assert fr.ifft(prod_evals)[:15] == expected


def test_reverse_bit_order_involution():
    xs = list(range(16))
    assert fr.reverse_bit_order_list(fr.reverse_bit_order_list(xs)) == xs


@pytest.mark.parametrize("erased", [1, 16, 32])
def test_recover_polynomial(erased):
    evals = fr.fft([rng.randrange(fr.R) for _ in range(32)] + [0] * 32)
    samples = list(evals)
    for i in rng.sample(range(64), erased):
        samples[i] = None
    assert fr.recover_polynomial(samples) == evals


def test_recover_rejects_too_many_erasures():
    evals = fr.fft([1] * 8 + [0] * 8)
    samples = [None] * 9 + list(evals[9:])
    with pytest.raises(AssertionError):
        fr.recover_polynomial(samples)


def test_commitment_linearity():
    n = 16
    setup = kzg.setup_lagrange(n)
    blob_a = [rng.randrange(fr.R) for _ in range(n)]
    blob_b = [rng.randrange(fr.R) for _ in range(n)]
    blob_sum = [(a + b) % fr.R for a, b in zip(blob_a, blob_b)]
    ca = kzg.commitment_to_point(kzg.blob_to_kzg(blob_a, setup))
    cb = kzg.commitment_to_point(kzg.blob_to_kzg(blob_b, setup))
    csum = kzg.blob_to_kzg(blob_sum, setup)
    assert g1_to_bytes(ca + cb) == csum


def test_commitment_matches_secret_evaluation():
    n = 32
    setup = kzg.setup_lagrange(n)
    blob = [rng.randrange(fr.R) for _ in range(n)]
    c = kzg.blob_to_kzg(blob, setup)
    assert kzg.verify_commitment_matches_poly(c, blob)
    assert not kzg.verify_commitment_matches_poly(c, blob[::-1])


def test_device_batch_scalar_mul_differential():
    from consensus_specs_tpu.ops import kzg_jax

    g = g1_generator()
    points = [g.mul(i + 1) for i in range(8)]
    scalars = [
        0, 1, 2, fr.R - 1, rng.randrange(fr.R), rng.randrange(fr.R),
        (fr.R + 1) // 2, 3,
    ]
    got = kzg_jax.batch_scalar_mul(points, scalars)
    for p, s, out in zip(points, scalars, got):
        assert out == p.mul(s % fr.R), f"lane with scalar {s}"


def test_device_msm_matches_host_lincomb():
    from consensus_specs_tpu.ops import kzg_jax

    n = 8
    setup = kzg.setup_lagrange(n)
    blob = [rng.randrange(fr.R) for _ in range(n)]
    host = kzg.g1_lincomb(setup, blob)
    dev = kzg_jax.msm(setup, blob)
    assert dev == host


def test_pippenger_matches_naive_lincomb():
    g = g1_generator()
    points = [g.mul(i + 2) for i in range(40)]
    scalars = [rng.randrange(fr.R) for _ in range(38)] + [0, 1]
    naive = kzg.g1_lincomb(points, scalars)
    fast = kzg.g1_msm_pippenger(points, scalars)
    assert fast == naive
