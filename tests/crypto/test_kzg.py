"""KZG + Fr FFT + device MSM tests: host oracle self-consistency and the
batched JAX scalar-mult differential."""
import random

import pytest

from consensus_specs_tpu.crypto import fr, kzg
from consensus_specs_tpu.crypto.bls.curve import g1_generator, g1_to_bytes

rng = random.Random(1717)


def test_fft_roundtrip_and_convolution_theorem():
    vals = [rng.randrange(fr.R) for _ in range(128)]
    assert fr.ifft(fr.fft(vals)) == [v % fr.R for v in vals]
    # multiplication in evaluation form == poly_mul in coefficient form
    a = [rng.randrange(fr.R) for _ in range(8)] + [0] * 8
    b = [rng.randrange(fr.R) for _ in range(8)] + [0] * 8
    ea, eb = fr.fft(a), fr.fft(b)
    prod_evals = [x * y % fr.R for x, y in zip(ea, eb)]
    expected = fr.poly_mul(a[:8], b[:8])
    assert fr.ifft(prod_evals)[:15] == expected


def test_reverse_bit_order_involution():
    xs = list(range(16))
    assert fr.reverse_bit_order_list(fr.reverse_bit_order_list(xs)) == xs


@pytest.mark.parametrize("erased", [1, 16, 32])
def test_recover_polynomial(erased):
    evals = fr.fft([rng.randrange(fr.R) for _ in range(32)] + [0] * 32)
    samples = list(evals)
    for i in rng.sample(range(64), erased):
        samples[i] = None
    assert fr.recover_polynomial(samples) == evals


def test_recover_rejects_too_many_erasures():
    evals = fr.fft([1] * 8 + [0] * 8)
    samples = [None] * 9 + list(evals[9:])
    with pytest.raises(AssertionError):
        fr.recover_polynomial(samples)


def test_commitment_linearity():
    n = 16
    setup = kzg.setup_lagrange(n)
    blob_a = [rng.randrange(fr.R) for _ in range(n)]
    blob_b = [rng.randrange(fr.R) for _ in range(n)]
    blob_sum = [(a + b) % fr.R for a, b in zip(blob_a, blob_b)]
    ca = kzg.commitment_to_point(kzg.blob_to_kzg(blob_a, setup))
    cb = kzg.commitment_to_point(kzg.blob_to_kzg(blob_b, setup))
    csum = kzg.blob_to_kzg(blob_sum, setup)
    assert g1_to_bytes(ca + cb) == csum


def test_commitment_matches_secret_evaluation():
    n = 32
    setup = kzg.setup_lagrange(n)
    blob = [rng.randrange(fr.R) for _ in range(n)]
    c = kzg.blob_to_kzg(blob, setup)
    assert kzg.verify_commitment_matches_poly(c, blob)
    assert not kzg.verify_commitment_matches_poly(c, blob[::-1])


def test_device_batch_scalar_mul_differential():
    from consensus_specs_tpu.ops import kzg_jax

    g = g1_generator()
    points = [g.mul(i + 1) for i in range(8)]
    scalars = [
        0, 1, 2, fr.R - 1, rng.randrange(fr.R), rng.randrange(fr.R),
        (fr.R + 1) // 2, 3,
    ]
    got = kzg_jax.batch_scalar_mul(points, scalars)
    for p, s, out in zip(points, scalars, got):
        assert out == p.mul(s % fr.R), f"lane with scalar {s}"


def test_device_msm_matches_host_lincomb():
    from consensus_specs_tpu.ops import kzg_jax

    n = 8
    setup = kzg.setup_lagrange(n)
    blob = [rng.randrange(fr.R) for _ in range(n)]
    host = kzg.g1_lincomb(setup, blob)
    dev = kzg_jax.msm(setup, blob)
    assert dev == host


def test_pippenger_matches_naive_lincomb():
    g = g1_generator()
    points = [g.mul(i + 2) for i in range(40)]
    scalars = [rng.randrange(fr.R) for _ in range(38)] + [0, 1]
    naive = kzg.g1_lincomb(points, scalars)
    fast = kzg.g1_msm_pippenger(points, scalars)
    assert fast == naive


# --- native C++ MSM (bls_g1_msm / bls_g1_msm_fixed) ------------------------

def _native_available():
    return kzg._native_mod() is not None


@pytest.mark.skipif(not _native_available(), reason="native BLS backend absent")
def test_native_msm_matches_python_pippenger():
    g = g1_generator()
    points = [g.mul(i + 2) for i in range(65)]
    scalars = [rng.randrange(fr.R) for _ in range(63)] + [0, 1]
    expected = g1_to_bytes(kzg.g1_msm_pippenger(points, scalars))
    assert kzg.g1_msm_native(points, scalars) == expected
    assert kzg.g1_msm_native(points, scalars, fixed_base=True) == expected


@pytest.mark.skipif(not _native_available(), reason="native BLS backend absent")
def test_native_fixed_msm_edge_digits():
    # constant scalars exercise the deep single-bucket tree; the duplicate
    # point pair exercises the batch-affine doubling branch; P + (-P) the
    # cancellation branch (result: infinity)
    from consensus_specs_tpu.crypto.bls.curve import Point

    n = 128
    setup = kzg.setup_lagrange(n)
    for blob in ([4] * n, [0] * (n - 2) + [123, fr.R - 1]):
        expected = g1_to_bytes(kzg.g1_lincomb(setup, blob))
        assert kzg.g1_msm_native(setup, blob, fixed_base=True) == expected

    g = g1_generator()
    assert kzg.g1_msm_native([g, g], [5, 5], fixed_base=True) == \
        g1_to_bytes(kzg.g1_lincomb([g, g], [5, 5]))
    neg_g = Point(g.x, -g.y, g.z, g.b)
    inf = bytes([0xC0]) + b"\x00" * 47
    assert kzg.g1_msm_native([g, neg_g], [5, 5], fixed_base=True) == inf


@pytest.mark.skipif(not _native_available(), reason="native BLS backend absent")
def test_native_msm_rejects_off_curve_point():
    from consensus_specs_tpu.crypto.bls import native

    bad = (3).to_bytes(48, "big") + (5).to_bytes(48, "big")
    with pytest.raises(ValueError):
        native.G1MSM(bad, (1).to_bytes(32, "big"))


def test_blob_to_kzg_native_and_python_paths_agree():
    n = 128
    setup = kzg.setup_lagrange(n)
    blob = [rng.randrange(fr.R) for _ in range(n)]
    via_blob = kzg.blob_to_kzg(blob, setup)  # native fixed-base when present
    assert via_blob == g1_to_bytes(kzg.g1_msm_pippenger(setup, blob))


def test_msm_table_disk_cache_keys_on_abi(tmp_path, monkeypatch):
    """ADVICE r5 #1: the persisted MSM-table cache key folds in an ABI tag
    (byte order + pointer width + digest of the generator's serialized
    window table).  A table written by an incompatible build host lands at
    a different path, so it can never pass the integrity check — the
    loader sees a cache miss and rebuilds instead of feeding foreign
    Montgomery limbs to the C side."""
    nat = kzg._native_mod()
    if nat is None:
        pytest.skip("native backend unavailable")
    setup = kzg.setup_lagrange(4)
    flat = kzg._points_affine_bytes(setup)

    tag = kzg._msm_abi_tag(nat)
    assert len(tag) == 8
    path_here = kzg._fixed_table_path(nat, flat)
    assert f"_{tag}_" in path_here

    table = kzg._load_or_build_fixed_table(nat, flat)
    import os
    assert os.path.exists(path_here)

    # simulate loading on a host with a different ABI: the key changes, the
    # compatible-host table is invisible, and the rebuild round-trips
    monkeypatch.setattr(kzg, "_MSM_ABI_TAG", "00000000")
    path_other = kzg._fixed_table_path(nat, flat)
    assert path_other != path_here
    assert not os.path.exists(path_other)
    table2 = kzg._load_or_build_fixed_table(nat, flat)
    assert table2 == table  # deterministic rebuild on this (same) host
    assert os.path.exists(path_other)
    os.unlink(path_other)  # don't leave the fake-ABI artifact behind


def test_msm_abi_tag_tracks_table_serialization(monkeypatch):
    """The tag's behavioral probe is the serialized window table of the
    generator: a backend whose precompute emits different bytes (different
    limb layout) must produce a different tag."""
    nat = kzg._native_mod()
    if nat is None:
        pytest.skip("native backend unavailable")
    real = kzg._msm_abi_tag(nat)

    class _AlienABI:
        _source_digest = staticmethod(nat._source_digest)
        _MSM_FIXED_WINDOWS = nat._MSM_FIXED_WINDOWS

        @staticmethod
        def G1MSMPrecompute(xy):
            table = nat.G1MSMPrecompute(xy)
            return table[::-1]  # same data, alien byte order

    monkeypatch.setattr(kzg, "_MSM_ABI_TAG", None)
    alien = kzg._msm_abi_tag(_AlienABI)
    monkeypatch.setattr(kzg, "_MSM_ABI_TAG", None)
    assert kzg._msm_abi_tag(nat) == real  # cache rebuilt, stable
    assert alien != real


def _table_path_and_flat(tmp_path, monkeypatch):
    """(nat, flat, path): the disk-cache path for a tiny setup, redirected
    into ``tmp_path`` so corruption scenarios never touch the real tree."""
    nat = kzg._native_mod()
    if nat is None:
        pytest.skip("native backend unavailable")
    setup = kzg.setup_lagrange(4)
    flat = kzg._points_affine_bytes(setup)
    real_path = kzg._fixed_table_path(nat, flat)
    import os
    path = str(tmp_path / os.path.basename(real_path))
    monkeypatch.setattr(kzg, "_fixed_table_path", lambda _nat, _flat: path)
    return nat, flat, path


def test_msm_table_truncated_file_regenerates(tmp_path, monkeypatch):
    """ISSUE 5 satellite: a truncated cache file (torn write that made it
    to disk, process killed mid-write on a pre-atomic layout) fails the
    length check and is regenerated in place — never fed to the C side."""
    import os
    nat, flat, path = _table_path_and_flat(tmp_path, monkeypatch)
    table = kzg._load_or_build_fixed_table(nat, flat)
    assert os.path.exists(path)
    with open(path, "rb") as f:
        data = f.read()
    with open(path, "wb") as f:
        f.write(data[: len(data) // 2])  # truncate: torn write survivor
    again = kzg._load_or_build_fixed_table(nat, flat)
    assert again == table
    with open(path, "rb") as f:
        assert f.read() == data  # the damaged file was repaired on disk


def test_msm_table_corrupted_payload_regenerates(tmp_path, monkeypatch):
    """A right-sized file whose payload was damaged (bit rot, torn write
    across preallocated blocks) fails the trailing-SHA256 check and is
    regenerated; the rebuilt table round-trips through G1MSMFixed."""
    import os
    nat, flat, path = _table_path_and_flat(tmp_path, monkeypatch)
    table = kzg._load_or_build_fixed_table(nat, flat)
    with open(path, "r+b") as f:
        f.seek(7)
        byte = f.read(1)
        f.seek(7)
        f.write(bytes([byte[0] ^ 0xFF]))
    again = kzg._load_or_build_fixed_table(nat, flat)
    assert again == table
    # the repaired table feeds the C side (entry-0 on-curve backstop holds)
    n = len(flat) // 96
    scalars = b"".join(int(i + 1).to_bytes(32, "big") for i in range(n))
    assert nat.G1MSMFixed(again, n, scalars) == nat.G1MSM(flat, scalars)
    # no stray temp files left behind by the rebuild-and-replace path
    leftovers = [p for p in os.listdir(tmp_path) if p.endswith(".tmp")]
    assert leftovers == []
