"""Differential tests: JAX batched BLS pipeline vs the Python oracle.

Layered exactly like the implementation: limb arithmetic vs python ints,
tower ops vs crypto/bls/fields.py, Frobenius/HHT identities exactly, then
the full batched pairing-product check vs oracle verifications.

The pairing tests share ONE compiled batch shape (B=2, K=2) — compile is
the dominant cost and is persistently cached under .cache/jax.
"""
import random

import numpy as np
import pytest

from consensus_specs_tpu.crypto.bls import ciphersuite as py
from consensus_specs_tpu.crypto.bls.fields import Fq2, Fq6, Fq12, P, R, X_PARAM
from consensus_specs_tpu.ops import bls_jax
from consensus_specs_tpu.ops.bls_jax import limbs, tower

rng = random.Random(99)


def rand_fq() -> int:
    return rng.randrange(P)


def rand_fq12() -> Fq12:
    def f2():
        return Fq2(rand_fq(), rand_fq())

    return Fq12(Fq6(f2(), f2(), f2()), Fq6(f2(), f2(), f2()))


# --- limb layer -------------------------------------------------------------


def test_limb_roundtrip():
    for _ in range(20):
        x = rand_fq()
        assert limbs.limbs_to_int(limbs.int_to_limbs(x)) == x


def test_limb_mont_mul_differential():
    import jax.numpy as jnp

    xs = [rand_fq() for _ in range(8)]
    ys = [rand_fq() for _ in range(8)]
    a = jnp.asarray(np.stack([limbs.host_to_mont(x) for x in xs]))
    b = jnp.asarray(np.stack([limbs.host_to_mont(y) for y in ys]))
    out = limbs.mul(a, b)
    for i in range(8):
        assert limbs.host_from_mont(np.asarray(out[i])) == (xs[i] * ys[i]) % P


def test_limb_lazy_add_sub_then_mul():
    import jax.numpy as jnp

    xs = [rand_fq() for _ in range(4)]
    a = jnp.asarray(np.stack([limbs.host_to_mont(x) for x in xs]))
    # (8a - 3a) * a == 5a^2
    acc = a + a + a + a + a + a + a + a - (a + a + a)
    out = limbs.mul(acc, a)
    for i in range(4):
        assert limbs.host_from_mont(np.asarray(out[i])) == (5 * xs[i] * xs[i]) % P


def test_limb_inv():
    import jax.numpy as jnp

    xs = [rand_fq() for _ in range(4)]
    a = jnp.asarray(np.stack([limbs.host_to_mont(x) for x in xs]))
    out = limbs.inv(a)
    for i in range(4):
        assert limbs.host_from_mont(np.asarray(out[i])) == pow(xs[i], P - 2, P)


def test_limb_canonical_and_cond_sub():
    import jax.numpy as jnp

    for x in [0, 1, P - 1, P // 2, rand_fq()]:
        a = jnp.asarray(limbs.host_to_mont(x))[None, :]
        c = limbs.canonical(a)
        assert limbs.limbs_to_int(np.asarray(c[0])) == (x * limbs.R_INT) % P


# --- tower layer ------------------------------------------------------------


def _to12(x: Fq12) -> np.ndarray:
    return tower.host_fq12_from_oracle(x)


def _from12(a) -> Fq12:
    return tower.host_fq12_to_oracle(np.asarray(a))


def test_fq12_mul_square_differential():
    import jax.numpy as jnp

    for _ in range(3):
        x, y = rand_fq12(), rand_fq12()
        got = _from12(tower.fq12_mul(jnp.asarray(_to12(x)), jnp.asarray(_to12(y))))
        assert got == x * y
        got_sq = _from12(tower.fq12_square(jnp.asarray(_to12(x))))
        assert got_sq == x.square()


def test_fq12_inv_conj_differential():
    import jax.numpy as jnp

    x = rand_fq12()
    assert _from12(tower.fq12_inv(jnp.asarray(_to12(x)))) == x.inv()
    assert _from12(tower.fq12_conj(jnp.asarray(_to12(x)))) == x.conjugate()


def test_fq12_frobenius_differential():
    import jax.numpy as jnp

    x = rand_fq12()
    assert _from12(tower.fq12_frob1(jnp.asarray(_to12(x)))) == x.pow(P)
    assert _from12(tower.fq12_frob2(jnp.asarray(_to12(x)))) == x.pow(P * P)


def test_fq12_mul_line_matches_full_mul():
    import jax.numpy as jnp

    x = rand_fq12()
    l0, l3, l5 = Fq2(rand_fq(), rand_fq()), Fq2(rand_fq(), rand_fq()), Fq2(
        rand_fq(), rand_fq()
    )
    # sparse element with w-slots {0, 3, 5}
    sparse = Fq12(
        Fq6(l0, Fq2(0, 0), Fq2(0, 0)), Fq6(Fq2(0, 0), l3, l5)
    )

    def h2(v: Fq2):
        return jnp.asarray(
            np.stack([limbs.host_to_mont(v.c0), limbs.host_to_mont(v.c1)])
        )

    got = _from12(
        tower.fq12_mul_line(jnp.asarray(_to12(x)), h2(l0), h2(l3), h2(l5))
    )
    assert got == x * sparse


def test_hht_hard_part_identity():
    """3 * (p^4 - p^2 + 1)/r  ==  (x-1)^2 (x+p) (x^2 + p^2 - 1) + 3, exactly."""
    x = X_PARAM
    lhs = 3 * ((P**4 - P**2 + 1) // R)
    rhs = (x - 1) ** 2 * (x + P) * (x**2 + P**2 - 1) + 3
    assert lhs == rhs


# --- full pipeline (shares one compiled shape: K=2, B=2) --------------------


@pytest.fixture(scope="module")
def signed_fixture():
    msg = b"jax batch attestation"
    sks = [11, 22, 33]
    pks = [py.SkToPk(sk) for sk in sks]
    sigs = [py.Sign(sk, msg) for sk in sks]
    agg = py.Aggregate(sigs)
    return msg, sks, pks, sigs, agg


def test_batch_fast_aggregate_verify_differential(signed_fixture):
    msg, sks, pks, sigs, agg = signed_fixture
    got = bls_jax.batch_fast_aggregate_verify(
        [pks, pks], [msg, b"wrong message"], [agg, agg]
    )
    assert got == [True, False]
    expected = [
        py.FastAggregateVerify(pks, msg, agg),
        py.FastAggregateVerify(pks, b"wrong message", agg),
    ]
    assert got == expected


def test_batch_verify_mixed(signed_fixture):
    msg, sks, pks, sigs, agg = signed_fixture
    got = bls_jax.batch_verify(
        [pks[0], pks[1]], [msg, msg], [sigs[0], sigs[0]]
    )
    assert got == [True, False]


def test_batch_malformed_inputs_are_false(signed_fixture):
    msg, sks, pks, sigs, agg = signed_fixture
    got = bls_jax.batch_fast_aggregate_verify(
        [[], [b"\xff" * 48]], [msg, msg], [agg, agg]
    )
    assert got == [False, False]


def test_scalar_api_matches_backend_contract(signed_fixture):
    msg, sks, pks, sigs, agg = signed_fixture
    assert bls_jax.FastAggregateVerify(pks, msg, agg)
    assert not bls_jax.Verify(pks[0], msg, sigs[1])
    assert bls_jax.Verify(pks[0], msg, sigs[0])
    # infinity signature takes the host fallback path
    assert not bls_jax.Verify(pks[0], msg, bls_jax.G2_POINT_AT_INFINITY)
    # distinct-message AggregateVerify delegates to the host backend
    msgs = [b"m1", b"m2", b"m3"]
    agg2 = py.Aggregate([py.Sign(sk, m) for sk, m in zip(sks, msgs)])
    assert bls_jax.AggregateVerify(pks, msgs, agg2)
    assert not bls_jax.AggregateVerify(pks, list(reversed(msgs)), agg2)


def test_selector_use_jax_roundtrip(signed_fixture):
    from consensus_specs_tpu.crypto import bls

    msg, sks, pks, sigs, agg = signed_fixture
    prev = bls.backend_name()
    try:
        bls.use_jax()
        assert bls.backend_name() == "jax"
        assert bls.FastAggregateVerify(pks, msg, agg)
        assert not bls.Verify(pks[0], b"nope", sigs[0])
        assert bls.Sign(sks[0], msg) == sigs[0]
    finally:
        bls.use_backend(prev)
