"""Differential tests: native C++ BLS backend vs the pure-Python oracle.

The oracle (crypto/bls/*.py) is itself pinned by RFC 9380 vectors and PoP
semantics tests from round 1; these tests pin the native backend to it
bit-for-bit, including edge cases the Verify-family contract requires
(reference behavior: eth2spec/utils/bls.py:47-74 — malformed input is
invalid, never fatal).
"""
import hashlib

import pytest

try:
    from consensus_specs_tpu.crypto.bls import native
except ImportError as exc:  # toolchain missing — report, don't hide
    pytest.skip(f"native BLS unavailable: {exc}", allow_module_level=True)

from consensus_specs_tpu.crypto.bls import ciphersuite as py
from consensus_specs_tpu.crypto.bls.curve import (
    g1_generator,
    g2_generator,
    g1_to_bytes,
    g2_to_bytes,
)
from consensus_specs_tpu.crypto.bls.hash_to_curve import DST_G2_POP, hash_to_g2
from consensus_specs_tpu.crypto.bls.pairing import pairing

SKS = [1, 2, 3, 0x1234, 0xDEADBEEF, 2**200 + 17]
MSGS = [b"", b"a", b"hello consensus", b"\x00" * 32, bytes(range(100))]


def fq12_to_bytes(f) -> bytes:
    coeffs = [f.c0.c0, f.c0.c1, f.c0.c2, f.c1.c0, f.c1.c1, f.c1.c2]
    out = b""
    for c in coeffs:
        out += c.c0.to_bytes(48, "big") + c.c1.to_bytes(48, "big")
    return out


def test_sha256_matches_hashlib():
    for probe in [b"", b"abc", b"x" * 1000, bytes(range(256)) * 3]:
        assert native.sha256(probe) == hashlib.sha256(probe).digest()


def test_sk_to_pk_matches_oracle():
    for sk in SKS:
        assert native.SkToPk(sk) == py.SkToPk(sk)


def test_sk_range_rejected():
    from consensus_specs_tpu.crypto.bls.fields import R

    for bad in [0, R, R + 5]:
        with pytest.raises(ValueError):
            native.SkToPk(bad)


def test_hash_to_g2_matches_oracle():
    for msg in MSGS:
        expected = g2_to_bytes(hash_to_g2(msg, DST_G2_POP))
        assert native.hash_to_g2_compressed(msg, DST_G2_POP) == expected


def test_hash_to_g2_rfc9380_vector():
    # RFC 9380 §J.10.1 (BLS12381G2_XMD:SHA-256_SSWU_RO_), msg="abc"
    dst = b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_"
    expected = g2_to_bytes(hash_to_g2(b"abc", dst))
    assert native.hash_to_g2_compressed(b"abc", dst) == expected


def test_sign_matches_oracle():
    for sk in SKS[:3]:
        for msg in MSGS[:3]:
            assert native.Sign(sk, msg) == py.Sign(sk, msg)


def test_pairing_matches_oracle():
    p = g1_generator()
    q = g2_generator()
    expected = fq12_to_bytes(pairing(p, q))
    got = native.pairing_bytes(g1_to_bytes(p), g2_to_bytes(q))
    assert got == expected


def test_pairing_bilinear_native():
    # e(2P, Q) == e(P, 2Q) without any oracle in the loop
    p, q = g1_generator(), g2_generator()
    lhs = native.pairing_bytes(g1_to_bytes(p.mul(2)), g2_to_bytes(q))
    rhs = native.pairing_bytes(g1_to_bytes(p), g2_to_bytes(q.mul(2)))
    assert lhs == rhs


def test_verify_roundtrip():
    sk = 777
    pk = native.SkToPk(sk)
    msg = b"attestation data root"
    sig = native.Sign(sk, msg)
    assert native.Verify(pk, msg, sig)
    assert not native.Verify(pk, b"tampered", sig)
    assert not native.Verify(pk, msg, native.Sign(778, msg))


def test_key_validate():
    assert native.KeyValidate(native.SkToPk(42))
    assert not native.KeyValidate(b"\xc0" + b"\x00" * 47)  # infinity
    assert not native.KeyValidate(b"\x00" * 48)  # no compression flag
    assert not native.KeyValidate(b"\xff" * 48)  # x >= p
    assert not native.KeyValidate(b"\x99" * 48)  # junk


def test_verify_malformed_inputs_false_not_fatal():
    sk = 9
    pk = native.SkToPk(sk)
    sig = native.Sign(sk, b"m")
    assert not native.Verify(b"\x00" * 48, b"m", sig)
    assert not native.Verify(pk, b"m", b"\x00" * 96)
    assert not native.Verify(pk, b"m", b"\xff" * 96)
    assert not native.Verify(b"", b"m", sig)
    # infinity pubkey is rejected even with an infinity signature
    assert not native.Verify(b"\xc0" + b"\x00" * 47, b"m", native.G2_POINT_AT_INFINITY)


def test_aggregate_matches_oracle():
    msg = b"same message"
    sigs = [native.Sign(sk, msg) for sk in SKS[:4]]
    assert native.Aggregate(sigs) == py.Aggregate(sigs)
    with pytest.raises(ValueError):
        native.Aggregate([])


def test_aggregate_pks_matches_oracle():
    pks = [native.SkToPk(sk) for sk in SKS[:4]]
    assert native.AggregatePKs(pks) == py.AggregatePKs(pks)
    with pytest.raises(ValueError):
        native.AggregatePKs([])


def test_fast_aggregate_verify():
    msg = b"sync committee root"
    sks = SKS[:4]
    pks = [native.SkToPk(sk) for sk in sks]
    agg = native.Aggregate([native.Sign(sk, msg) for sk in sks])
    assert native.FastAggregateVerify(pks, msg, agg)
    assert not native.FastAggregateVerify(pks, b"other", agg)
    assert not native.FastAggregateVerify(pks[:3], msg, agg)
    assert not native.FastAggregateVerify([], msg, agg)
    # infinity signature with empty-sum pubkeys is still rejected on n=0
    assert not native.FastAggregateVerify([], msg, native.G2_POINT_AT_INFINITY)


def test_aggregate_verify_distinct_messages():
    sks = SKS[:3]
    msgs = [b"m1", b"m2-longer", b""]
    pks = [native.SkToPk(sk) for sk in sks]
    agg = native.Aggregate([native.Sign(sk, m) for sk, m in zip(sks, msgs)])
    assert native.AggregateVerify(pks, msgs, agg)
    assert not native.AggregateVerify(pks, [b"m1", b"m2-longer", b"x"], agg)
    assert not native.AggregateVerify(pks, msgs[:2], agg)
    assert not native.AggregateVerify([], [], agg)


def test_cross_backend_verify():
    """Signatures produced by either backend verify under the other."""
    sk, msg = 31337, b"cross-check"
    assert py.Verify(py.SkToPk(sk), msg, native.Sign(sk, msg))
    assert native.Verify(native.SkToPk(sk), msg, py.Sign(sk, msg))


def test_non_subgroup_g2_rejected():
    """The psi-based fast subgroup test must reject curve points outside the
    r-order subgroup exactly as the [r]P == inf test did."""
    import random

    from consensus_specs_tpu.crypto.bls.curve import Point
    from consensus_specs_tpu.crypto.bls.fields import Fq2, P

    rng = random.Random(99)
    b2 = Fq2(4, 4)
    found = 0
    while found < 3:
        x = Fq2(rng.randrange(P), rng.randrange(P))
        y = (x.square() * x + b2).sqrt()
        if y is None:
            continue
        pt = Point(x, y, Fq2.one(), b2)
        if pt.in_subgroup():  # astronomically unlikely
            continue
        found += 1
        encoded = g2_to_bytes(pt)
        pk = native.SkToPk(5)
        # used as a signature: load_signature must reject -> False, not crash
        assert not native.Verify(pk, b"msg", encoded)
        assert not native.FastAggregateVerify([pk], b"msg", encoded)
        assert not native.BatchFastAggregateVerify(
            [([pk], b"msg", encoded)], seed=b"\x07" * 32)


def test_non_subgroup_g1_pubkey_rejected():
    """The endomorphism-based fast G1 membership test (load_pubkey /
    bls_decompress_pubkey) must reject on-curve points outside the r-order
    subgroup exactly as the generic [r]P == inf test did — a regression
    here silently accepts rogue pubkeys, so it gets the same pin as the
    G2 analogue above."""
    import random

    from consensus_specs_tpu.crypto.bls.curve import Point
    from consensus_specs_tpu.crypto.bls.fields import Fq, P

    rng = random.Random(1117)
    b1 = Fq(4)
    found = 0
    while found < 3:
        x = Fq(rng.randrange(P))
        y = (x.square() * x + b1).sqrt()
        if y is None:
            continue
        pt = Point(x, y, Fq.one(), b1)
        if pt.in_subgroup():  # astronomically unlikely
            continue
        found += 1
        encoded = g1_to_bytes(pt)
        assert not py.KeyValidate(encoded)
        assert not native.KeyValidate(encoded)
        assert native.pubkey_affine(encoded) is None
        sig = native.Sign(5, b"msg")
        assert not native.Verify(encoded, b"msg", sig)
        assert not native.FastAggregateVerify([encoded], b"msg", sig)
    # positives stay positive: real pubkeys pass both paths
    for sk in (1, 7, 2**200):
        pk = native.SkToPk(sk)
        assert native.KeyValidate(pk) and py.KeyValidate(pk)
        assert native.pubkey_affine(pk) is not None


def test_batch_fast_aggregate_verify_matches_sequential():
    """Differential: for random valid/invalid mixes, the batch answer equals
    the AND of the individual FastAggregateVerify answers."""
    import random

    from consensus_specs_tpu.crypto.bls.curve import R

    rng = random.Random(4242)
    sks = [rng.randrange(1, R) for _ in range(12)]
    pks = [native.SkToPk(sk) for sk in sks]

    def item(members, msg, good=True):
        agg_sk = sum(sks[m] for m in members) % R
        sig = native.Sign(agg_sk, msg if good else msg + b"!")
        return ([pks[m] for m in members], msg, sig)

    for trial in range(6):
        items = []
        expected = True
        for i in range(rng.randrange(1, 6)):
            members = rng.sample(range(12), rng.randrange(1, 6))
            good = rng.random() < 0.7
            items.append(item(members, b"msg%d-%d" % (trial, i), good))
            expected = expected and good
        seed = bytes([trial]) * 32
        assert native.BatchFastAggregateVerify(items, seed=seed) == expected
        seq = all(native.FastAggregateVerify(*it) for it in items)
        assert seq == expected


def test_batch_empty_and_invalid_shapes():
    assert native.BatchFastAggregateVerify([])
    msg = b"m"
    sig = native.Sign(7, msg)
    # zero pubkeys in an item -> that item invalid -> batch False
    assert not native.BatchFastAggregateVerify([([], msg, sig)])
    # malformed signature length
    assert not native.BatchFastAggregateVerify([([native.SkToPk(7)], msg, sig[:-1])])
    # malformed pubkey -> invalid item
    assert not native.BatchFastAggregateVerify([([b"\x00" * 48], msg, sig)])


def test_batch_deterministic_seed():
    """Same seed -> same RLC scalars -> identical (deterministic) outcome."""
    msg = b"det"
    sks = SKS[:3]
    pks = [native.SkToPk(sk) for sk in sks]
    agg = native.Aggregate([native.Sign(sk, msg) for sk in sks])
    items = [(pks, msg, agg)] * 4
    for seed in (b"\x00" * 32, b"\xff" * 32):
        assert native.BatchFastAggregateVerify(items, seed=seed)
        assert native.BatchFastAggregateVerify(items, seed=seed)


def test_deferred_scope_bisects_to_first_culprit():
    """Selector-level deferred scope: the AssertionError names the FIRST
    failing entry in sequential call order (bisection over sub-batches)."""
    from consensus_specs_tpu.crypto import bls

    prev = bls.backend_name()
    bls.use_native()
    try:
        msg = b"deferred"
        sks = SKS[:3]
        pks = [native.SkToPk(sk) for sk in sks]
        good = native.Aggregate([native.Sign(sk, msg) for sk in sks])
        bad = native.Sign(999, msg)

        # all good -> clean exit
        with bls.deferred_fast_aggregate_verify():
            for _ in range(5):
                assert bls.FastAggregateVerify(pks, msg, good)

        # failures at 2 and 4 -> reported culprit is 2 (the first)
        with pytest.raises(AssertionError, match=r"batch entry 2 of 6"):
            with bls.deferred_fast_aggregate_verify():
                for i in range(6):
                    sig = bad if i in (2, 4) else good
                    assert bls.FastAggregateVerify(pks, msg, sig)

        # structural exception with a PRIOR bad signature: signature wins
        # (sequential order: the bad signature was checked first)
        with pytest.raises(AssertionError, match=r"batch entry 0 of 1"):
            with bls.deferred_fast_aggregate_verify():
                bls.FastAggregateVerify(pks, msg, bad)
                raise IndexError("later structural failure")

        # structural exception with all prior signatures good: propagates
        with pytest.raises(IndexError):
            with bls.deferred_fast_aggregate_verify():
                bls.FastAggregateVerify(pks, msg, good)
                raise IndexError("real structural failure")
    finally:
        # restore the PREVIOUS backend: leaving "python" active would make
        # every later BLS-on spec test pay the pure-Python pairing (~10x)
        bls.use_backend(prev)


def test_deferred_scope_inactive_when_bls_off():
    from consensus_specs_tpu.crypto import bls

    prev = bls.backend_name()
    bls.use_native()
    was = bls.bls_active
    bls.bls_active = False
    try:
        with bls.deferred_fast_aggregate_verify() as scope:
            assert bls.FastAggregateVerify([b"\x00" * 48], b"m", b"\x00" * 96)
            assert scope.entries == []  # only_with_bls short-circuits first
    finally:
        bls.bls_active = was
        bls.use_backend(prev)


# --- fixed-base MSM table integrity + window-count pins (ADVICE r5 #2, #4) --

def _gen_xy():
    g = g1_generator()
    return g.x.n.to_bytes(48, "big") + g.y.n.to_bytes(48, "big")


def test_msm_fixed_rejects_corrupted_table():
    """The 'corrupted MSM table' ValueError must be a REAL failure mode:
    the C side sanity-checks the first table entry against the curve, so a
    byte flipped anywhere in entry 0 (either coordinate) is rejected
    instead of silently producing a wrong commitment."""
    xy = _gen_xy()
    table = native.G1MSMPrecompute(xy)
    scalar = (7).to_bytes(32, "big")
    ok = native.G1MSMFixed(table, 1, scalar)
    assert ok == native.G1MSM(xy, scalar)
    for byte_off in (0, 17, 48, 95):  # x limbs and y limbs of entry 0
        bad = bytearray(table)
        bad[byte_off] ^= 0x5A
        with pytest.raises(ValueError, match="corrupted MSM table"):
            native.G1MSMFixed(bytes(bad), 1, scalar)


def _g1_affine_xy(p):
    """Canonical affine x||y of a Jacobian point."""
    from consensus_specs_tpu.crypto.bls.fields import P as FQ_P
    zi = pow(p.z.n, FQ_P - 2, FQ_P)
    zi2 = zi * zi % FQ_P
    x = p.x.n * zi2 % FQ_P
    y = p.y.n * zi2 % FQ_P * zi % FQ_P
    return x.to_bytes(48, "big") + y.to_bytes(48, "big")


def test_msm_fixed_corruption_check_result_still_correct():
    """The sanity probe must not perturb results: a multi-point fixed-base
    MSM still matches the variable-base Pippenger bit-for-bit."""
    from consensus_specs_tpu.crypto.bls.curve import g1_from_bytes

    sks = [3, 2**254 + 11, 0x123456789ABCDEF]
    flat = b"".join(
        _g1_affine_xy(g1_from_bytes(native.SkToPk(sk))) for sk in sks)
    scalars = b"".join(((s * 31) % (2**255)).to_bytes(32, "big") for s in sks)
    table = native.G1MSMPrecompute(flat)
    assert native.G1MSMFixed(table, len(sks), scalars) == \
        native.G1MSM(flat, scalars)


def test_msm_window_counts_use_ceil():
    """ADVICE r5 #4: both the Pippenger cost model and every n_windows
    computation must use ceil(255/c) — the (255+c)/c form pays an always-
    empty top window whenever c divides 255 (c = 3, 5, 15)."""
    import math
    import os
    # the exported fixed-base window count is the C side's own layout
    assert native._MSM_FIXED_WINDOWS == math.ceil(255 / 12)
    # source pin: no remaining biased-window forms
    src_path = os.path.join(os.path.dirname(native.__file__), "native",
                            "bls12_381.cpp")
    with open(src_path) as f:
        src = f.read()
    assert "(255 + t) / t" not in src
    assert "(255 + c) / c" not in src
    assert "(255 + MSM_FIXED_C) / MSM_FIXED_C" not in src
