"""Differential tests: native C++ BLS backend vs the pure-Python oracle.

The oracle (crypto/bls/*.py) is itself pinned by RFC 9380 vectors and PoP
semantics tests from round 1; these tests pin the native backend to it
bit-for-bit, including edge cases the Verify-family contract requires
(reference behavior: eth2spec/utils/bls.py:47-74 — malformed input is
invalid, never fatal).
"""
import hashlib

import pytest

try:
    from consensus_specs_tpu.crypto.bls import native
except ImportError as exc:  # toolchain missing — report, don't hide
    pytest.skip(f"native BLS unavailable: {exc}", allow_module_level=True)

from consensus_specs_tpu.crypto.bls import ciphersuite as py
from consensus_specs_tpu.crypto.bls.curve import (
    g1_generator,
    g2_generator,
    g1_to_bytes,
    g2_to_bytes,
)
from consensus_specs_tpu.crypto.bls.hash_to_curve import DST_G2_POP, hash_to_g2
from consensus_specs_tpu.crypto.bls.pairing import pairing

SKS = [1, 2, 3, 0x1234, 0xDEADBEEF, 2**200 + 17]
MSGS = [b"", b"a", b"hello consensus", b"\x00" * 32, bytes(range(100))]


def fq12_to_bytes(f) -> bytes:
    coeffs = [f.c0.c0, f.c0.c1, f.c0.c2, f.c1.c0, f.c1.c1, f.c1.c2]
    out = b""
    for c in coeffs:
        out += c.c0.to_bytes(48, "big") + c.c1.to_bytes(48, "big")
    return out


def test_sha256_matches_hashlib():
    for probe in [b"", b"abc", b"x" * 1000, bytes(range(256)) * 3]:
        assert native.sha256(probe) == hashlib.sha256(probe).digest()


def test_sk_to_pk_matches_oracle():
    for sk in SKS:
        assert native.SkToPk(sk) == py.SkToPk(sk)


def test_sk_range_rejected():
    from consensus_specs_tpu.crypto.bls.fields import R

    for bad in [0, R, R + 5]:
        with pytest.raises(ValueError):
            native.SkToPk(bad)


def test_hash_to_g2_matches_oracle():
    for msg in MSGS:
        expected = g2_to_bytes(hash_to_g2(msg, DST_G2_POP))
        assert native.hash_to_g2_compressed(msg, DST_G2_POP) == expected


def test_hash_to_g2_rfc9380_vector():
    # RFC 9380 §J.10.1 (BLS12381G2_XMD:SHA-256_SSWU_RO_), msg="abc"
    dst = b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_"
    expected = g2_to_bytes(hash_to_g2(b"abc", dst))
    assert native.hash_to_g2_compressed(b"abc", dst) == expected


def test_sign_matches_oracle():
    for sk in SKS[:3]:
        for msg in MSGS[:3]:
            assert native.Sign(sk, msg) == py.Sign(sk, msg)


def test_pairing_matches_oracle():
    p = g1_generator()
    q = g2_generator()
    expected = fq12_to_bytes(pairing(p, q))
    got = native.pairing_bytes(g1_to_bytes(p), g2_to_bytes(q))
    assert got == expected


def test_pairing_bilinear_native():
    # e(2P, Q) == e(P, 2Q) without any oracle in the loop
    p, q = g1_generator(), g2_generator()
    lhs = native.pairing_bytes(g1_to_bytes(p.mul(2)), g2_to_bytes(q))
    rhs = native.pairing_bytes(g1_to_bytes(p), g2_to_bytes(q.mul(2)))
    assert lhs == rhs


def test_verify_roundtrip():
    sk = 777
    pk = native.SkToPk(sk)
    msg = b"attestation data root"
    sig = native.Sign(sk, msg)
    assert native.Verify(pk, msg, sig)
    assert not native.Verify(pk, b"tampered", sig)
    assert not native.Verify(pk, msg, native.Sign(778, msg))


def test_key_validate():
    assert native.KeyValidate(native.SkToPk(42))
    assert not native.KeyValidate(b"\xc0" + b"\x00" * 47)  # infinity
    assert not native.KeyValidate(b"\x00" * 48)  # no compression flag
    assert not native.KeyValidate(b"\xff" * 48)  # x >= p
    assert not native.KeyValidate(b"\x99" * 48)  # junk


def test_verify_malformed_inputs_false_not_fatal():
    sk = 9
    pk = native.SkToPk(sk)
    sig = native.Sign(sk, b"m")
    assert not native.Verify(b"\x00" * 48, b"m", sig)
    assert not native.Verify(pk, b"m", b"\x00" * 96)
    assert not native.Verify(pk, b"m", b"\xff" * 96)
    assert not native.Verify(b"", b"m", sig)
    # infinity pubkey is rejected even with an infinity signature
    assert not native.Verify(b"\xc0" + b"\x00" * 47, b"m", native.G2_POINT_AT_INFINITY)


def test_aggregate_matches_oracle():
    msg = b"same message"
    sigs = [native.Sign(sk, msg) for sk in SKS[:4]]
    assert native.Aggregate(sigs) == py.Aggregate(sigs)
    with pytest.raises(ValueError):
        native.Aggregate([])


def test_aggregate_pks_matches_oracle():
    pks = [native.SkToPk(sk) for sk in SKS[:4]]
    assert native.AggregatePKs(pks) == py.AggregatePKs(pks)
    with pytest.raises(ValueError):
        native.AggregatePKs([])


def test_fast_aggregate_verify():
    msg = b"sync committee root"
    sks = SKS[:4]
    pks = [native.SkToPk(sk) for sk in sks]
    agg = native.Aggregate([native.Sign(sk, msg) for sk in sks])
    assert native.FastAggregateVerify(pks, msg, agg)
    assert not native.FastAggregateVerify(pks, b"other", agg)
    assert not native.FastAggregateVerify(pks[:3], msg, agg)
    assert not native.FastAggregateVerify([], msg, agg)
    # infinity signature with empty-sum pubkeys is still rejected on n=0
    assert not native.FastAggregateVerify([], msg, native.G2_POINT_AT_INFINITY)


def test_aggregate_verify_distinct_messages():
    sks = SKS[:3]
    msgs = [b"m1", b"m2-longer", b""]
    pks = [native.SkToPk(sk) for sk in sks]
    agg = native.Aggregate([native.Sign(sk, m) for sk, m in zip(sks, msgs)])
    assert native.AggregateVerify(pks, msgs, agg)
    assert not native.AggregateVerify(pks, [b"m1", b"m2-longer", b"x"], agg)
    assert not native.AggregateVerify(pks, msgs[:2], agg)
    assert not native.AggregateVerify([], [], agg)


def test_cross_backend_verify():
    """Signatures produced by either backend verify under the other."""
    sk, msg = 31337, b"cross-check"
    assert py.Verify(py.SkToPk(sk), msg, native.Sign(sk, msg))
    assert native.Verify(native.SkToPk(sk), msg, py.Sign(sk, msg))
