"""Differential test: the VENDORED deposit-contract BYTECODE, executed by
the from-scratch EVM, against the transcribed twin and the SSZ deposit
list root (round-4 VERDICT item 6; reference analogue:
solidity_deposit_contract/web3_tester/tests/test_deposit.py:1-194).

This is the test that fails if bytecode and twin ever disagree: the same
deposit sequence is pushed through both, and root/count/logs must match
at every step.
"""
import json
import os

import pytest

from consensus_specs_tpu.deposit_contract import DepositTree
from consensus_specs_tpu.evm import EvmRevert, decode_abi, deploy, selector
from consensus_specs_tpu.specs.builder import get_spec

ART = os.path.join(os.path.dirname(__file__), "..", "consensus_specs_tpu",
                   "vendor", "deposit_contract", "deposit_contract.json")

GWEI = 10**9
ETHER = 10**18

DEPOSIT_SIG = "deposit(bytes,bytes,bytes,bytes32)"
DEPOSIT_TYPES = ["bytes", "bytes", "bytes", "bytes32"]


@pytest.fixture(scope="module")
def spec():
    return get_spec("phase0", "minimal")


@pytest.fixture()
def contract():
    art = json.load(open(ART))
    return deploy(bytes.fromhex(art["bytecode"][2:]))


def _deposit_data(spec, i, amount_gwei):
    return spec.DepositData(
        pubkey=bytes([i + 1]) * 48,
        withdrawal_credentials=bytes([i + 0x20]) * 32,
        amount=amount_gwei,
        signature=bytes([i + 0x60]) * 96,
    )


def _do_deposit(contract, spec, data):
    return contract.call(
        DEPOSIT_SIG, DEPOSIT_TYPES,
        [bytes(data.pubkey), bytes(data.withdrawal_credentials),
         bytes(data.signature), bytes(data.hash_tree_root())],
        value=int(data.amount) * GWEI,
    )


def _evm_root(contract) -> bytes:
    return contract.call("get_deposit_root()", [], [], static=True)


def _evm_count(contract) -> int:
    raw = decode_abi(["bytes"], contract.call(
        "get_deposit_count()", [], [], static=True))[0]
    return int.from_bytes(raw, "little")


def test_empty_tree_root_matches_twin(contract):
    assert _evm_root(contract) == DepositTree().get_root()
    assert _evm_count(contract) == 0


def test_deposit_sequence_bytecode_vs_twin_vs_ssz(contract, spec):
    """The core differential: every step, bytecode root == twin root ==
    SSZ List[DepositData] root path."""
    twin = DepositTree()
    datas = []
    for i in range(5):
        data = _deposit_data(spec, i, 32 * 10**9)  # 32 ETH in gwei
        _do_deposit(contract, spec, data)
        twin.push_leaf(bytes(data.hash_tree_root()))
        datas.append(data)

        assert _evm_root(contract) == twin.get_root(), f"diverged at {i}"
        assert _evm_count(contract) == twin.deposit_count == i + 1

    # and against the SSZ list root's deposit-tree form: the contract root
    # mixes count into the 2^32-deep tree exactly like the SSZ hash tree
    # root of List[DepositData, 2**32]
    from consensus_specs_tpu.ssz.types import List as SSZList

    lst = SSZList[spec.DepositData, 2**32](datas)
    assert _evm_root(contract) == bytes(lst.hash_tree_root())


def test_deposit_event_log_fields(contract, spec):
    data = _deposit_data(spec, 7, 32 * 10**9)
    _do_deposit(contract, spec, data)
    assert len(contract.logs) == 1
    log = contract.logs[0]
    # DepositEvent(bytes,bytes,bytes,bytes,bytes) — ABI-decode the payload
    pk, wc, amount_le, sig, index_le = decode_abi(
        ["bytes"] * 5, log.data)
    assert pk == bytes(data.pubkey)
    assert wc == bytes(data.withdrawal_credentials)
    assert int.from_bytes(amount_le, "little") == int(data.amount)
    assert sig == bytes(data.signature)
    assert int.from_bytes(index_le, "little") == 0


def test_low_value_deposit_reverts(contract, spec):
    data = _deposit_data(spec, 1, 10**8)  # 0.1 ETH < 1 ETH minimum
    with pytest.raises(EvmRevert):
        _do_deposit(contract, spec, data)
    assert _evm_count(contract) == 0


def test_non_gwei_multiple_reverts(contract, spec):
    data = _deposit_data(spec, 1, 32 * 10**9)
    with pytest.raises(EvmRevert):
        contract.call(
            DEPOSIT_SIG, DEPOSIT_TYPES,
            [bytes(data.pubkey), bytes(data.withdrawal_credentials),
             bytes(data.signature), bytes(data.hash_tree_root())],
            value=int(data.amount) * GWEI + 1,  # not a gwei multiple
        )


def test_wrong_data_root_reverts(contract, spec):
    data = _deposit_data(spec, 2, 32 * 10**9)
    with pytest.raises(EvmRevert):
        contract.call(
            DEPOSIT_SIG, DEPOSIT_TYPES,
            [bytes(data.pubkey), bytes(data.withdrawal_credentials),
             bytes(data.signature), b"\xbe" * 32],  # tampered root
            value=int(data.amount) * GWEI,
        )


def test_malformed_pubkey_length_reverts(contract, spec):
    data = _deposit_data(spec, 3, 32 * 10**9)
    with pytest.raises(EvmRevert):
        contract.call(
            DEPOSIT_SIG, DEPOSIT_TYPES,
            [b"\x01" * 47, bytes(data.withdrawal_credentials),
             bytes(data.signature), bytes(data.hash_tree_root())],
            value=int(data.amount) * GWEI,
        )


def test_supports_interface(contract):
    erc165 = selector("supportsInterface(bytes4)")
    deposit_iface = selector(DEPOSIT_SIG)  # not the ERC-165 id; expect False
    out = contract.call("supportsInterface(bytes4)", ["bytes4"],
                        [bytes.fromhex("01ffc9a7")], static=True)
    assert decode_abi(["bool"], out)[0] is True
    out = contract.call("supportsInterface(bytes4)", ["bytes4"],
                        [b"\xde\xad\xbe\xef"], static=True)
    assert decode_abi(["bool"], out)[0] is False
    assert erc165 != deposit_iface


def test_reverted_call_discards_storage_effects(contract, spec):
    """EVM revert semantics: a failed call leaves NO state behind, even if
    the bytecode wrote storage before the failing require."""
    data = _deposit_data(spec, 4, 32 * 10**9)
    _do_deposit(contract, spec, data)  # one committed deposit
    pre_storage = dict(contract.storage)
    pre_root = _evm_root(contract)
    with pytest.raises(EvmRevert):
        contract.call(
            DEPOSIT_SIG, DEPOSIT_TYPES,
            [bytes(data.pubkey), bytes(data.withdrawal_credentials),
             bytes(data.signature), b"\xaa" * 32],  # wrong root -> revert
            value=int(data.amount) * GWEI,
        )
    assert contract.storage == pre_storage
    assert _evm_root(contract) == pre_root
    assert _evm_count(contract) == 1


def test_static_call_cannot_mutate(contract, spec):
    """deposit() through a static context must fail (SSTORE/LOG guarded by
    explicit EvmRevert, not strippable asserts)."""
    data = _deposit_data(spec, 5, 32 * 10**9)
    with pytest.raises(EvmRevert):
        contract.call(
            DEPOSIT_SIG, DEPOSIT_TYPES,
            [bytes(data.pubkey), bytes(data.withdrawal_credentials),
             bytes(data.signature), bytes(data.hash_tree_root())],
            value=int(data.amount) * GWEI, static=True,
        )
    assert _evm_count(contract) == 0
