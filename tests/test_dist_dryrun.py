"""CI hook for the process-fabric dryrun (tools/dist_dryrun.py): the
epoch/merkle/pairing capability checks over the 2-worker supervised pool,
bit-identical to the in-process twins, plus the injected worker-kill leg
with recovery (ISSUE 20 satellite; ``make dist-dryrun``)."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_process_fabric_dryrun():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dist_dryrun.py")],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    report = json.loads(
        open(os.path.join(REPO, "DCN_DRYRUN.json")).read())
    assert report["ok"]
    assert report["path"] == "process-fabric"
    assert report["n_processes"] == 2
    assert report["checks"] == {
        "epoch_balances_bitexact": True,
        "merkle_root_matches_ssz": True,
        "pairing_lanes_verdicts_exact": True,
        "clean_run_no_redispatch": True,
    }
    # the failure-domain leg: the kill really happened AND the run
    # recovered on the fabric with a bit-identical root
    assert report["kill"]["root_parity"]
    assert report["kill"]["recovered_on_fabric"]
    assert report["kill"]["redispatched_chunks"] > 0
    assert report["kill"]["workers_lost"] >= 1
