"""Experimental-fork suites: eip4844 (KZG blobs), sharding (headers, fees,
shard work), das (extension/sampling/recovery), custody_game (custody-bit
math and period machinery).  References: specs/{eip4844,sharding,das,
custody_game}/ of the reference snapshot."""
import random

import pytest

from consensus_specs_tpu.crypto import fr, kzg
from consensus_specs_tpu.crypto.bls.curve import g1_to_bytes
from consensus_specs_tpu.specs.builder import get_spec

rng = random.Random(808)


@pytest.fixture(scope="module")
def eip4844():
    return get_spec("eip4844", "minimal")


@pytest.fixture(scope="module")
def sharding():
    return get_spec("sharding", "minimal")


@pytest.fixture(scope="module")
def das():
    return get_spec("das", "minimal")


@pytest.fixture(scope="module")
def custody():
    return get_spec("custody_game", "minimal")


# --- eip4844 ----------------------------------------------------------------


def test_blob_commitment_and_versioned_hash(eip4844):
    spec = eip4844
    blob = spec.Blob([rng.randrange(int(spec.BLS_MODULUS))
                      for _ in range(int(spec.FIELD_ELEMENTS_PER_BLOB))])
    c = spec.blob_to_kzg(blob)
    assert kzg.verify_commitment_matches_poly(bytes(c), [int(v) for v in blob])
    vh = spec.kzg_to_versioned_hash(c)
    assert vh[0] == 1 and len(vh) == 32


def _mock_blob_tx(spec, versioned_hashes):
    """SSZ-shaped SignedBlobTransaction mock: 1-byte type + 4-byte message
    offset + message whose bytes 156:160 hold the hashes' position (the
    draft reads that offset as an absolute index into the opaque tx)."""
    message_offset = 5
    hashes_abs = message_offset + 160  # right after the offset field
    message = bytearray(b"\x00" * 156)
    message += int(hashes_abs).to_bytes(4, "little")
    message += b"".join(versioned_hashes)
    tx = bytes([int(spec.BLOB_TX_TYPE)]) + int(message_offset - 1).to_bytes(4, "little") + bytes(message)
    return spec.Transaction(tx)


def test_tx_peek_and_kzg_verification(eip4844):
    spec = eip4844
    blob = spec.Blob([3] * int(spec.FIELD_ELEMENTS_PER_BLOB))
    commitment = spec.blob_to_kzg(blob)
    vh = spec.kzg_to_versioned_hash(commitment)
    tx = _mock_blob_tx(spec, [bytes(vh)])
    assert list(spec.tx_peek_blob_versioned_hashes(tx)) == [vh]
    assert spec.verify_kzgs_against_transactions([tx], [commitment])
    other = spec.blob_to_kzg(spec.Blob([4] * int(spec.FIELD_ELEMENTS_PER_BLOB)))
    assert not spec.verify_kzgs_against_transactions([tx], [other])


def test_blobs_sidecar_verification(eip4844):
    spec = eip4844
    blob = spec.Blob([rng.randrange(int(spec.BLS_MODULUS))
                      for _ in range(int(spec.FIELD_ELEMENTS_PER_BLOB))])
    c = spec.blob_to_kzg(blob)
    sidecar = spec.BlobsSidecar(
        beacon_block_root=b"\x22" * 32, beacon_block_slot=7, blobs=[blob])
    spec.verify_blobs_sidecar(7, b"\x22" * 32, [c], sidecar)
    with pytest.raises(AssertionError):
        spec.verify_blobs_sidecar(8, b"\x22" * 32, [c], sidecar)


def test_eip4844_block_body_has_blob_kzgs(eip4844):
    body = eip4844.BeaconBlockBody()
    assert len(body.blob_kzgs) == 0
    assert "blob_kzgs" in type(body)._field_names


def test_is_data_available_retrieve_and_verify_roundtrip(eip4844):
    """The availability gate end-to-end over the from-scratch KZG: install a
    blob store behind the retrieve seam, gate a (slot, root, kzgs) triple,
    and check both the unavailable and the wrong-commitment paths fail
    (eip4844/validator.md:49-55)."""
    spec = eip4844
    blob = spec.Blob([rng.randrange(int(spec.BLS_MODULUS))
                      for _ in range(int(spec.FIELD_ELEMENTS_PER_BLOB))])
    commitment = spec.blob_to_kzg(blob)
    root, slot = b"\x77" * 32, 9
    sidecar = spec.BlobsSidecar(
        beacon_block_root=root, beacon_block_slot=slot, blobs=[blob])

    # nothing retrievable: the block must not be considered valid
    with pytest.raises(spec.BlobsSidecarUnavailable):
        spec.is_data_available(slot, root, [commitment])

    store = {(slot, root): sidecar}
    original = spec.retrieve_blobs_sidecar

    def retrieve(s, r):
        try:
            return store[(int(s), bytes(r))]
        except KeyError:
            raise spec.BlobsSidecarUnavailable()

    spec.retrieve_blobs_sidecar = retrieve
    try:
        spec.is_data_available(slot, root, [commitment])  # passes

        wrong = spec.blob_to_kzg(
            spec.Blob([5] * int(spec.FIELD_ELEMENTS_PER_BLOB)))
        with pytest.raises(AssertionError):
            spec.is_data_available(slot, root, [wrong])
        with pytest.raises(spec.BlobsSidecarUnavailable):
            spec.is_data_available(slot + 1, root, [commitment])
    finally:
        spec.retrieve_blobs_sidecar = original


# --- sharding ---------------------------------------------------------------


def test_sample_price_updates(sharding):
    spec = sharding
    target = int(spec.TARGET_SAMPLES_PER_BLOB)
    price = spec.Gwei(1000)
    up = spec.compute_updated_sample_price(price, spec.uint64(target * 2), spec.uint64(2))
    down = spec.compute_updated_sample_price(price, spec.uint64(0), spec.uint64(2))
    flat = spec.compute_updated_sample_price(price, spec.uint64(target), spec.uint64(2))
    assert int(up) > 1000 and int(down) < 1000 and int(flat) <= 1000
    # bounds respected
    assert int(spec.compute_updated_sample_price(
        spec.MAX_SAMPLE_PRICE, spec.uint64(target * 2), spec.uint64(1))) <= int(spec.MAX_SAMPLE_PRICE)


def test_shard_committee_index_roundtrip(sharding):
    spec = sharding
    from consensus_specs_tpu.testing.context import (
        default_activation_threshold,
        default_balances,
    )
    from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state

    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec))
    slot = spec.Slot(3)
    count = int(spec.get_committee_count_per_slot(state, spec.compute_epoch_at_slot(slot)))
    for index in range(count):
        shard = spec.compute_shard_from_committee_index(state, slot, spec.CommitteeIndex(index))
        back = spec.compute_committee_index_from_shard(state, slot, shard)
        assert int(back) == index


def test_reset_and_confirm_pending_shard_work(sharding):
    spec = sharding
    from consensus_specs_tpu.testing.context import (
        default_activation_threshold,
        default_balances,
    )
    from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state

    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec))
    spec.reset_pending_shard_work(state)
    # next epoch's slots now carry PENDING work for shards with committees
    next_start = int(spec.compute_start_slot_at_epoch(spec.get_current_epoch(state) + 1))
    buffer_index = next_start % int(spec.SHARD_STATE_MEMORY_SLOTS)
    statuses = [int(w.status.selector) for w in state.shard_buffer[buffer_index]]
    assert spec.SHARD_WORK_PENDING in statuses


def test_degree_proof_pairing_identity(sharding):
    """The process_shard_header degree check: D = commit(B(X) * X^(N-l))
    satisfies e(D, H) == e(commit(B), s^(N-l) H) iff deg(B) < l."""
    spec = sharding
    g1_setup, g2_setup = spec._kzg_setups()
    n = len(g1_setup)
    l = 4
    coeffs = [rng.randrange(fr.R) for _ in range(l)]  # deg < l
    commitment = kzg.g1_lincomb(g1_setup[:l], coeffs)
    degree_proof = kzg.g1_lincomb(g1_setup[n - l:], coeffs)
    from consensus_specs_tpu.crypto import bls

    assert bls.Pairing(degree_proof, g2_setup[0]) == bls.Pairing(commitment, g2_setup[-l])
    # a degree-l polynomial (one too high) must fail against the same slot
    bad = coeffs + [1]
    bad_commit = kzg.g1_lincomb(g1_setup[:l + 1], bad)
    bad_proof = kzg.g1_lincomb(g1_setup[n - l - 1:], bad)  # honest shift for deg l+1
    assert bls.Pairing(bad_proof, g2_setup[0]) != bls.Pairing(bad_commit, g2_setup[-l])


def test_upgrade_to_sharding(sharding):
    spec = sharding
    bella = get_spec("bellatrix", "minimal")
    from consensus_specs_tpu.testing.context import (
        default_activation_threshold,
        default_balances,
    )
    from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state

    pre = create_genesis_state(
        bella, default_balances(bella), default_activation_threshold(bella))
    post = spec.upgrade_to_sharding(pre)
    assert post.fork.current_version == spec.config.SHARDING_FORK_VERSION
    assert int(post.shard_sample_price) == int(spec.MIN_SAMPLE_PRICE)
    assert post.validators.hash_tree_root() == pre.validators.hash_tree_root()


# --- das --------------------------------------------------------------------


def test_das_extend_unextend_roundtrip(das):
    spec = das
    pps = int(spec.POINTS_PER_SAMPLE)
    data = [rng.randrange(fr.R) for _ in range(2 * pps)]
    ext = spec.extend_data(data)
    assert len(ext) == 2 * len(data)
    assert list(spec.unextend_data(ext)) == data


def test_das_sample_verify_and_reconstruct(das):
    spec = das
    pps = int(spec.POINTS_PER_SAMPLE)
    data = [rng.randrange(fr.R) for _ in range(2 * pps)]
    ext = spec.extend_data(data)
    poly = spec.inverse_fft(spec.reverse_bit_order_list([int(v) for v in ext]))
    assert all(v == 0 for v in poly[len(poly) // 2:])
    commitment = spec.BLSCommitment(g1_to_bytes(
        kzg.g1_lincomb(kzg.setup_monomial(len(poly)), poly)))

    samples = spec.sample_data(spec.Slot(3), spec.Shard(1), ext)
    for s in samples:
        spec.verify_sample(s, len(samples), commitment)

    # tampered data is rejected
    bad = samples[0].copy()
    bad.data[0] = int(bad.data[0]) ^ 1
    with pytest.raises(AssertionError):
        spec.verify_sample(bad, len(samples), commitment)

    # half the samples reconstruct everything
    partial = [None if i % 2 == 0 else s for i, s in enumerate(samples)]
    rec = spec.reconstruct_extended_data(partial)
    assert rec == [int(v) for v in ext]


# --- custody game -----------------------------------------------------------


def test_custody_bit_is_deterministic(custody):
    spec = custody
    from consensus_specs_tpu.crypto.bls import ciphersuite

    sig = spec.BLSSignature(ciphersuite.Sign(99, b"reveal"))
    data = b"shard data " * 100
    assert spec.compute_custody_bit(sig, data) == spec.compute_custody_bit(sig, data)
    secrets = spec.get_custody_secrets(sig)
    assert len(secrets) == 3 and all(isinstance(s, int) for s in secrets)


def test_custody_period_machinery(custody):
    spec = custody
    period = spec.get_custody_period_for_validator(spec.ValidatorIndex(5), spec.Epoch(0))
    randao_epoch = spec.get_randao_epoch_for_custody_period(period, spec.ValidatorIndex(5))
    assert int(randao_epoch) > 0
    # later epochs map to same-or-later periods
    later = spec.get_custody_period_for_validator(
        spec.ValidatorIndex(5), spec.Epoch(int(spec.EPOCHS_PER_CUSTODY_PERIOD) * 3))
    assert int(later) > int(period)


def test_replace_empty_or_append(custody):
    spec = custody
    records = spec.List[spec.CustodyChunkChallengeRecord, 8]()
    r1 = spec.CustodyChunkChallengeRecord(challenge_index=1)
    idx = spec.replace_empty_or_append(records, r1)
    assert idx == 0 and len(records) == 1
    r2 = spec.CustodyChunkChallengeRecord(challenge_index=2)
    idx = spec.replace_empty_or_append(records, r2)
    assert idx == 1 and len(records) == 2
    # clearing the first slot makes it reusable
    records[0] = spec.CustodyChunkChallengeRecord()
    r3 = spec.CustodyChunkChallengeRecord(challenge_index=3)
    idx = spec.replace_empty_or_append(records, r3)
    assert idx == 0 and len(records) == 2


def test_custody_state_and_body_fields(custody):
    spec = custody
    state = spec.BeaconState()
    assert int(state.custody_chunk_challenge_index) == 0
    body = spec.BeaconBlockBody()
    for field in ("chunk_challenges", "chunk_challenge_responses",
                  "custody_key_reveals", "early_derived_secret_reveals",
                  "custody_slashings", "shard_headers"):
        assert field in type(body)._field_names, field
