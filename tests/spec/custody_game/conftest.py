"""Shared custody-game fixtures: one minimal custody spec and a
16-validator mock-genesis state per test."""
import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.specs.builder import get_spec
from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state


@pytest.fixture(scope="package")
def spec():
    return get_spec("custody_game", "minimal")


@pytest.fixture()
def state(spec):
    old = bls.bls_active
    bls.bls_active = False
    st = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * 16, spec.MAX_EFFECTIVE_BALANCE)
    bls.bls_active = old
    return st
