"""Custody-game operation suites (reference suites:
test/custody_game/block_processing/): key reveals, early derived secret
reveals, chunk challenges and responses, against a real custody-fork
state built by the mock-genesis helper."""
import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.ssz.merkle_minimal import (
    calc_merkle_tree_from_leaves,
    get_merkle_proof,
)
from consensus_specs_tpu.testing.helpers.attestations import (
    get_valid_attestation,
)
from consensus_specs_tpu.testing.helpers.keys import privkeys
from consensus_specs_tpu.testing.helpers.state import next_slots, transition_to


@pytest.fixture(autouse=True)
def _bls_on():
    # custody operations verify real signatures (reveal signatures ARE the
    # custody secrets); run with the fast native backend active
    old = bls.bls_active
    bls.bls_active = True
    yield
    bls.bls_active = old


def _valid_key_reveal(spec, state, index):
    revealer = state.validators[index]
    epoch_to_sign = spec.get_randao_epoch_for_custody_period(
        revealer.next_custody_secret_to_reveal, spec.ValidatorIndex(index))
    domain = spec.get_domain(state, spec.DOMAIN_RANDAO, epoch_to_sign)
    signing_root = spec.compute_signing_root(epoch_to_sign, domain)
    return spec.CustodyKeyReveal(
        revealer_index=index,
        reveal=bls.Sign(privkeys[index], signing_root),
    )


def _advance_one_custody_period(spec, state):
    transition_to(
        spec, state,
        int(spec.EPOCHS_PER_CUSTODY_PERIOD) * int(spec.SLOTS_PER_EPOCH) + 1)


def test_custody_key_reveal_valid(spec, state):
    _advance_one_custody_period(spec, state)
    reveal = _valid_key_reveal(spec, state, 0)
    pre_next = int(state.validators[0].next_custody_secret_to_reveal)
    spec.process_custody_key_reveal(state, reveal)
    assert int(state.validators[0].next_custody_secret_to_reveal) == pre_next + 1


def test_custody_key_reveal_too_early(spec, state):
    # genesis epoch: no custody period has elapsed yet
    reveal = _valid_key_reveal(spec, state, 0)
    with pytest.raises(AssertionError):
        spec.process_custody_key_reveal(state, reveal)


def test_custody_key_reveal_wrong_signature(spec, state):
    _advance_one_custody_period(spec, state)
    reveal = _valid_key_reveal(spec, state, 0)
    reveal = spec.CustodyKeyReveal(
        revealer_index=0,
        reveal=bls.Sign(privkeys[1], b"\x33" * 32),
    )
    with pytest.raises(AssertionError):
        spec.process_custody_key_reveal(state, reveal)


def test_custody_key_reveal_double_reveal_rejected(spec, state):
    _advance_one_custody_period(spec, state)
    spec.process_custody_key_reveal(state, _valid_key_reveal(spec, state, 0))
    # the next secret is not yet revealable within the same period
    with pytest.raises(AssertionError):
        spec.process_custody_key_reveal(state, _valid_key_reveal(spec, state, 0))


def _early_reveal(spec, state, revealed_index, masker_index, epoch):
    domain = spec.get_domain(state, spec.DOMAIN_RANDAO, epoch)
    mask = b"\x11" * 32
    sigs = [
        bls.Sign(privkeys[revealed_index], spec.compute_signing_root(
            spec.Epoch(epoch), domain)),
        bls.Sign(privkeys[masker_index], spec.compute_signing_root(
            spec.Bytes32(mask), domain)),
    ]
    return spec.EarlyDerivedSecretReveal(
        revealed_index=revealed_index,
        epoch=epoch,
        reveal=bls.Aggregate(sigs),
        masker_index=masker_index,
        mask=mask,
    )


def test_early_derived_secret_reveal_minor_penalty(spec, state):
    epoch = int(spec.get_current_epoch(state)) + int(spec.RANDAO_PENALTY_EPOCHS)
    reveal = _early_reveal(spec, state, 1, 2, epoch)
    pre_balance = int(state.balances[1])
    spec.process_early_derived_secret_reveal(state, reveal)
    assert int(state.balances[1]) < pre_balance          # penalized
    assert not state.validators[1].slashed               # but not slashed
    loc = epoch % int(spec.EARLY_DERIVED_SECRET_PENALTY_MAX_FUTURE_EPOCHS)
    assert 1 in [int(i) for i in state.exposed_derived_secrets[loc]]


def test_early_derived_secret_reveal_full_slash(spec, state):
    epoch = int(spec.get_current_epoch(state)) + \
        int(spec.CUSTODY_PERIOD_TO_RANDAO_PADDING)
    reveal = _early_reveal(spec, state, 3, 2, epoch)
    spec.process_early_derived_secret_reveal(state, reveal)
    assert state.validators[3].slashed


def test_early_derived_secret_reveal_double_rejected(spec, state):
    epoch = int(spec.get_current_epoch(state)) + int(spec.RANDAO_PENALTY_EPOCHS)
    spec.process_early_derived_secret_reveal(
        state, _early_reveal(spec, state, 1, 2, epoch))
    with pytest.raises(AssertionError):
        spec.process_early_derived_secret_reveal(
            state, _early_reveal(spec, state, 1, 2, epoch))


# -- chunk challenges -------------------------------------------------------


def _challengeable_attestation(spec, state):
    """Attestation (unsigned; BLS switched off around validation) whose
    shard_transition_root commits to a one-block shard transition."""
    data_bytes = b"\x22" * 300
    chunk_count = 2
    shard_transition = spec.ShardTransition(
        start_slot=1,
        shard_block_lengths=[int(spec.BYTES_PER_CUSTODY_CHUNK) * chunk_count],
        shard_data_roots=[b"\x00" * 32],
    )
    attestation = get_valid_attestation(spec, state, signed=False)
    attestation.data.shard_transition_root = \
        spec.hash_tree_root(shard_transition)
    return attestation, shard_transition, data_bytes


def test_chunk_challenge_records_and_response(spec, state):
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY) + 1)
    old = bls.bls_active
    bls.bls_active = False  # unsigned attestation; structure under test
    try:
        attestation, shard_transition, _ = _challengeable_attestation(spec, state)

        # build the chunked data tree the response must open into
        depth = int(spec.CUSTODY_RESPONSE_DEPTH)
        chunk = spec.ByteVector[spec.BYTES_PER_CUSTODY_CHUNK](
            b"\x07" * int(spec.BYTES_PER_CUSTODY_CHUNK))
        leaves = [bytes(chunk.hash_tree_root()), bytes(chunk.hash_tree_root())]
        tree = calc_merkle_tree_from_leaves(leaves, depth)
        length_leaf = (2).to_bytes(32, "little")
        data_root = spec.hash(tree[-1][0] + length_leaf)
        shard_transition.shard_data_roots[0] = data_root
        attestation.data.shard_transition_root = \
            spec.hash_tree_root(shard_transition)

        # min(): the attester set is LRU-cached inside the spec — never
        # mutate it (pop() would eat the responder out of the cache)
        responder = int(min(spec.get_attesting_indices(
            state, attestation.data, attestation.aggregation_bits)))
        challenge = spec.CustodyChunkChallenge(
            responder_index=responder,
            shard_transition=shard_transition,
            attestation=attestation,
            data_index=0,
            chunk_index=1,
        )
        pre_index = int(state.custody_chunk_challenge_index)
        spec.process_chunk_challenge(state, challenge)
        assert int(state.custody_chunk_challenge_index) == pre_index + 1
        record = state.custody_chunk_challenge_records[0]
        assert int(record.responder_index) == responder
        assert bytes(record.data_root) == bytes(data_root)

        # duplicate challenge rejected
        with pytest.raises(AssertionError):
            spec.process_chunk_challenge(state, challenge)

        # valid response clears the record and rewards the proposer
        branch = get_merkle_proof(tree, 1, depth) + [length_leaf]
        response = spec.CustodyChunkResponse(
            challenge_index=record.challenge_index,
            chunk_index=1,
            chunk=chunk,
            branch=branch,
        )
        proposer = int(spec.get_beacon_proposer_index(state))
        pre_balance = int(state.balances[proposer])
        spec.process_chunk_challenge_response(state, response)
        assert int(state.balances[proposer]) > pre_balance
        cleared = state.custody_chunk_challenge_records[0]
        assert int(cleared.challenge_index) == 0
        assert bytes(cleared.data_root) == b"\x00" * 32

        # responding again must fail.  The cleared sentinel record has
        # challenge_index=0 == the first real challenge's index, so pin
        # the rejection to a record-lookup failure by using an index no
        # record (real or sentinel) carries.
        gone = spec.CustodyChunkResponse(
            challenge_index=int(record.challenge_index) + 100,
            chunk_index=1, chunk=chunk, branch=branch)
        with pytest.raises(AssertionError):
            spec.process_chunk_challenge_response(state, gone)
        # and the sentinel-matching replay also fails (chunk mismatch)
        with pytest.raises(AssertionError):
            spec.process_chunk_challenge_response(state, response)
    finally:
        bls.bls_active = old


def test_chunk_challenge_wrong_chunk_index_rejected(spec, state):
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY) + 1)
    old = bls.bls_active
    bls.bls_active = False
    try:
        attestation, shard_transition, _ = _challengeable_attestation(spec, state)
        # min(): the attester set is LRU-cached inside the spec — never
        # mutate it (pop() would eat the responder out of the cache)
        responder = int(min(spec.get_attesting_indices(
            state, attestation.data, attestation.aggregation_bits)))
        challenge = spec.CustodyChunkChallenge(
            responder_index=responder,
            shard_transition=shard_transition,
            attestation=attestation,
            data_index=0,
            chunk_index=99,  # beyond transition_chunks
        )
        with pytest.raises(AssertionError):
            spec.process_chunk_challenge(state, challenge)
    finally:
        bls.bls_active = old
