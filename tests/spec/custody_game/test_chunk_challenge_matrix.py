"""Chunk-challenge scenario matrix (reference suite:
test/custody_game/block_processing/test_process_chunk_challenge.py —
appended/replaced/duplicate/second/multi-epoch/off-chain/response
variants), built on this repo's mock-genesis custody state and
merkle_minimal proof machinery."""
import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.ssz.merkle_minimal import (
    calc_merkle_tree_from_leaves,
    get_merkle_proof,
)
from consensus_specs_tpu.testing.helpers.attestations import get_valid_attestation
from consensus_specs_tpu.testing.helpers.state import next_slots


@pytest.fixture(autouse=True)
def _bls_off():
    # structure under test; attestations are unsigned
    old = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = old


CHUNK_COUNT = 2


def _chunked_transition(spec, fill: bytes):
    """(shard_transition, chunks, tree, length_leaf) with a data root the
    response proofs can open into."""
    depth = int(spec.CUSTODY_RESPONSE_DEPTH)
    chunk = spec.ByteVector[spec.BYTES_PER_CUSTODY_CHUNK](
        fill * int(spec.BYTES_PER_CUSTODY_CHUNK))
    leaves = [bytes(chunk.hash_tree_root())] * CHUNK_COUNT
    tree = calc_merkle_tree_from_leaves(leaves, depth)
    length_leaf = CHUNK_COUNT.to_bytes(32, "little")
    data_root = spec.hash(tree[-1][0] + length_leaf)
    shard_transition = spec.ShardTransition(
        start_slot=1,
        shard_block_lengths=[int(spec.BYTES_PER_CUSTODY_CHUNK) * CHUNK_COUNT],
        shard_data_roots=[data_root],
    )
    return shard_transition, chunk, tree, length_leaf


def _attested_challenge(spec, state, chunk_index=0, fill=b"\x07"):
    """A fully consistent (attestation, challenge, chunk, tree, length_leaf)
    bundle for the current state."""
    shard_transition, chunk, tree, length_leaf = _chunked_transition(spec, fill)
    attestation = get_valid_attestation(spec, state, signed=False)
    attestation.data.shard_transition_root = spec.hash_tree_root(shard_transition)
    responder = int(min(spec.get_attesting_indices(
        state, attestation.data, attestation.aggregation_bits)))
    challenge = spec.CustodyChunkChallenge(
        responder_index=responder,
        shard_transition=shard_transition,
        attestation=attestation,
        data_index=0,
        chunk_index=chunk_index,
    )
    return challenge, chunk, tree, length_leaf


def _response(spec, challenge_index, chunk_index, chunk, tree, length_leaf):
    branch = get_merkle_proof(tree, chunk_index,
                              int(spec.CUSTODY_RESPONSE_DEPTH)) + [length_leaf]
    return spec.CustodyChunkResponse(
        challenge_index=challenge_index,
        chunk_index=chunk_index,
        chunk=chunk,
        branch=branch,
    )


def _ready(spec, state, extra_slots=0):
    next_slots(spec, state,
               int(spec.MIN_ATTESTATION_INCLUSION_DELAY) + 1 + extra_slots)


def test_challenge_appended(spec, state):
    _ready(spec, state)
    challenge, *_ = _attested_challenge(spec, state)
    spec.process_chunk_challenge(state, challenge)
    record = state.custody_chunk_challenge_records[0]
    assert int(record.responder_index) == int(challenge.responder_index)
    assert int(record.chunk_index) == 0
    assert int(state.custody_chunk_challenge_index) == 1


def test_challenge_empty_element_replaced(spec, state):
    """A cleared (all-default) record slot is reused before the list grows."""
    _ready(spec, state)
    state.custody_chunk_challenge_records.append(
        spec.CustodyChunkChallengeRecord())  # an empty slot
    challenge, *_ = _attested_challenge(spec, state)
    spec.process_chunk_challenge(state, challenge)
    assert len(state.custody_chunk_challenge_records) == 1  # replaced, not appended
    assert int(state.custody_chunk_challenge_records[0].responder_index) == \
        int(challenge.responder_index)


def test_duplicate_challenge_rejected(spec, state):
    _ready(spec, state)
    challenge, *_ = _attested_challenge(spec, state)
    spec.process_chunk_challenge(state, challenge)
    with pytest.raises(AssertionError):
        spec.process_chunk_challenge(state, challenge)


def test_second_challenge_different_chunk(spec, state):
    """Same attestation, different chunk index: both records must coexist."""
    _ready(spec, state)
    challenge0, *_ = _attested_challenge(spec, state, chunk_index=0)
    spec.process_chunk_challenge(state, challenge0)
    challenge1 = spec.CustodyChunkChallenge(
        responder_index=challenge0.responder_index,
        shard_transition=challenge0.shard_transition,
        attestation=challenge0.attestation,
        data_index=0,
        chunk_index=1,
    )
    spec.process_chunk_challenge(state, challenge1)
    records = state.custody_chunk_challenge_records
    assert len(records) == 2
    assert {int(r.chunk_index) for r in records} == {0, 1}
    assert int(records[1].challenge_index) == 1


def test_challenge_multiple_epochs_custody(spec, state):
    """An attestation a few epochs old is still challengeable (the custody
    window spans EPOCHS_PER_CUSTODY_PERIOD)."""
    _ready(spec, state)
    challenge, *_ = _attested_challenge(spec, state)
    next_slots(spec, state, 3 * int(spec.SLOTS_PER_EPOCH))
    spec.process_chunk_challenge(state, challenge)
    assert int(state.custody_chunk_challenge_index) == 1


def test_challenge_stale_attestation_rejected(spec, state):
    """Beyond target.epoch + MAX_CHUNK_CHALLENGE_DELAY the attestation is
    too old to challenge.  The clock is set directly (as the epoch suites
    do) — transitioning through that many custody epochs would cascade the
    reveal-deadline sweep first."""
    _ready(spec, state)
    challenge, *_ = _attested_challenge(spec, state)
    horizon = int(spec.MAX_CHUNK_CHALLENGE_DELAY) + 2
    state.slot = spec.Slot(horizon * int(spec.SLOTS_PER_EPOCH))
    with pytest.raises(AssertionError):
        spec.process_chunk_challenge(state, challenge)


def test_off_chain_attestation_challengeable(spec, state):
    """The challenge carries its own attestation — it need not have been
    included in any block, only validate against the state."""
    _ready(spec, state)
    # never run process_attestation; straight to the challenge
    challenge, *_ = _attested_challenge(spec, state)
    spec.process_chunk_challenge(state, challenge)
    assert int(state.custody_chunk_challenge_index) == 1


def test_custody_response_chunk_index_0(spec, state):
    """Response opening chunk 0 (the existing suite covers index 1)."""
    _ready(spec, state)
    challenge, chunk, tree, length_leaf = _attested_challenge(
        spec, state, chunk_index=0, fill=b"\x09")
    spec.process_chunk_challenge(state, challenge)
    record = state.custody_chunk_challenge_records[0]
    response = _response(spec, int(record.challenge_index), 0, chunk, tree,
                         length_leaf)
    proposer = int(spec.get_beacon_proposer_index(state))
    pre = int(state.balances[proposer])
    spec.process_chunk_challenge_response(state, response)
    assert int(state.balances[proposer]) > pre
    assert bytes(state.custody_chunk_challenge_records[0].data_root) == b"\x00" * 32


def test_custody_response_wrong_chunk_rejected(spec, state):
    _ready(spec, state)
    challenge, chunk, tree, length_leaf = _attested_challenge(spec, state)
    spec.process_chunk_challenge(state, challenge)
    record = state.custody_chunk_challenge_records[0]
    bad_chunk = spec.ByteVector[spec.BYTES_PER_CUSTODY_CHUNK](
        b"\x55" * int(spec.BYTES_PER_CUSTODY_CHUNK))
    response = _response(spec, int(record.challenge_index), 0, bad_chunk,
                         tree, length_leaf)
    with pytest.raises(AssertionError):
        spec.process_chunk_challenge_response(state, response)


def test_custody_response_wrong_branch_rejected(spec, state):
    _ready(spec, state)
    challenge, chunk, tree, length_leaf = _attested_challenge(spec, state)
    spec.process_chunk_challenge(state, challenge)
    record = state.custody_chunk_challenge_records[0]
    response = _response(spec, int(record.challenge_index), 0, chunk, tree,
                         length_leaf)
    tampered = list(response.branch)
    tampered[0] = b"\xde" * 32
    response.branch = tampered
    with pytest.raises(AssertionError):
        spec.process_chunk_challenge_response(state, response)


def test_custody_response_multiple_epochs_later(spec, state):
    """A response landing several epochs after the challenge, still before
    the deadline, clears the record."""
    _ready(spec, state)
    challenge, chunk, tree, length_leaf = _attested_challenge(spec, state)
    spec.process_chunk_challenge(state, challenge)
    record = state.custody_chunk_challenge_records[0]
    next_slots(spec, state, 2 * int(spec.SLOTS_PER_EPOCH))
    response = _response(spec, int(record.challenge_index), 0, chunk, tree,
                         length_leaf)
    spec.process_chunk_challenge_response(state, response)
    assert bytes(state.custody_chunk_challenge_records[0].data_root) == b"\x00" * 32
