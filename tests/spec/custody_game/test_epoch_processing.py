"""Custody-game epoch-processing suites (reference suites:
test/custody_game/epoch_processing/): reveal deadlines, challenge
deadlines, final updates."""

from consensus_specs_tpu.testing.helpers.state import transition_to


def test_reveal_deadlines_slash_laggards(spec, state):
    # set the clock directly: custody process_epoch runs the deadline
    # sweep itself, so *transitioning* here would cascade-slash validators
    # one custody-period-offset at a time mid-transition.  At epoch
    # 2*PERIOD every validator's staggered period index is 2 > deadline 1.
    state.slot = spec.Slot(
        2 * int(spec.EPOCHS_PER_CUSTODY_PERIOD) * int(spec.SLOTS_PER_EPOCH))
    assert not any(v.slashed for v in state.validators)
    spec.process_reveal_deadlines(state)
    assert all(v.slashed for v in state.validators)


def test_reveal_deadlines_no_slash_within_grace(spec, state):
    # epoch 8: every staggered period index is 0, deadline 1 not exceeded
    state.slot = spec.Slot(8 * int(spec.SLOTS_PER_EPOCH))
    spec.process_reveal_deadlines(state)
    assert not any(v.slashed for v in state.validators)


def test_challenge_deadlines_slash_unanswered(spec, state):
    transition_to(spec, state, int(spec.SLOTS_PER_EPOCH))
    record = spec.CustodyChunkChallengeRecord(
        challenge_index=0,
        challenger_index=1,
        responder_index=2,
        inclusion_epoch=spec.get_current_epoch(state),
        data_root=b"\x42" * 32,
        chunk_index=0,
    )
    spec.replace_empty_or_append(state.custody_chunk_challenge_records, record)
    # deadline is EPOCHS_PER_CUSTODY_PERIOD after inclusion
    slots = (int(spec.get_current_epoch(state))
             + int(spec.EPOCHS_PER_CUSTODY_PERIOD) + 2) * int(spec.SLOTS_PER_EPOCH)
    transition_to(spec, state, slots)
    spec.process_challenge_deadlines(state)
    assert state.validators[2].slashed
    # record cleared
    assert int(state.custody_chunk_challenge_records[0].challenge_index) == 0
    assert bytes(state.custody_chunk_challenge_records[0].data_root) == b"\x00" * 32


def test_final_updates_clears_secrets_and_delays_withdrawal(spec, state):
    current = int(spec.get_current_epoch(state))
    loc = current % int(spec.EARLY_DERIVED_SECRET_PENALTY_MAX_FUTURE_EPOCHS)
    state.exposed_derived_secrets[loc].append(5)

    # exited validator with unrevealed secrets gets its withdrawal delayed
    validator = state.validators[4]
    validator.exit_epoch = spec.Epoch(current)
    validator.withdrawable_epoch = spec.Epoch(current + 1)
    assert int(validator.all_custody_secrets_revealed_epoch) == \
        int(spec.FAR_FUTURE_EPOCH)

    spec.process_custody_final_updates(state)
    assert len(state.exposed_derived_secrets[loc]) == 0
    assert int(state.validators[4].withdrawable_epoch) == \
        int(spec.FAR_FUTURE_EPOCH)


def test_final_updates_releases_fully_revealed(spec, state):
    current = int(spec.get_current_epoch(state))
    validator = state.validators[6]
    validator.exit_epoch = spec.Epoch(current)
    validator.withdrawable_epoch = spec.Epoch(current + 7)
    validator.all_custody_secrets_revealed_epoch = spec.Epoch(current)
    spec.process_custody_final_updates(state)
    # no challenge records, all secrets revealed: withdrawal stands
    assert int(state.validators[6].withdrawable_epoch) == current + 7


def test_final_updates_suspends_withdrawal_under_open_challenge(spec, state):
    """An exited responder with an OPEN chunk-challenge record must have its
    withdrawal suspended (reference scenario:
    test_validator_withdrawal_suspend_after_chunk_challenge)."""
    current = int(spec.get_current_epoch(state))
    responder = 3
    validator = state.validators[responder]
    validator.exit_epoch = spec.Epoch(current)
    validator.withdrawable_epoch = spec.Epoch(current + 4)
    validator.all_custody_secrets_revealed_epoch = spec.Epoch(current)
    spec.replace_empty_or_append(
        state.custody_chunk_challenge_records,
        spec.CustodyChunkChallengeRecord(
            challenge_index=7,
            challenger_index=1,
            responder_index=responder,
            inclusion_epoch=spec.Epoch(current),
            data_root=b"\x42" * 32,
            chunk_index=0,
        ))
    spec.process_custody_final_updates(state)
    assert int(state.validators[responder].withdrawable_epoch) == \
        int(spec.FAR_FUTURE_EPOCH)


def test_final_updates_resume_after_challenge_response(spec, state):
    """Once the record is cleared (answered) and all secrets are revealed,
    the next sweep re-enables withdrawal at revealed_epoch + delay
    (reference scenario:
    test_validator_withdrawal_resume_after_chunk_challenge_response)."""
    current = int(spec.get_current_epoch(state))
    responder = 3
    validator = state.validators[responder]
    validator.exit_epoch = spec.Epoch(current)
    validator.all_custody_secrets_revealed_epoch = spec.Epoch(current)
    validator.withdrawable_epoch = spec.FAR_FUTURE_EPOCH  # suspended earlier
    # an empty (cleared) record only
    spec.replace_empty_or_append(
        state.custody_chunk_challenge_records,
        spec.CustodyChunkChallengeRecord())
    spec.process_custody_final_updates(state)
    assert int(state.validators[responder].withdrawable_epoch) == \
        current + int(spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)


def test_final_updates_reenable_after_custody_reveal(spec, state):
    """A withdrawal delayed for unrevealed secrets resumes once
    all_custody_secrets_revealed_epoch is set (reference scenario:
    test_validator_withdrawal_reenable_after_custody_reveal)."""
    current = int(spec.get_current_epoch(state))
    validator = state.validators[5]
    validator.exit_epoch = spec.Epoch(current)
    validator.withdrawable_epoch = spec.FAR_FUTURE_EPOCH
    spec.process_custody_final_updates(state)  # still unrevealed: stays FAR
    assert int(state.validators[5].withdrawable_epoch) == \
        int(spec.FAR_FUTURE_EPOCH)
    validator = state.validators[5]
    validator.all_custody_secrets_revealed_epoch = spec.Epoch(current)
    spec.process_custody_final_updates(state)
    assert int(state.validators[5].withdrawable_epoch) == \
        current + int(spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)
