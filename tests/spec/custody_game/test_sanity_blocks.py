"""Custody-game sanity: full signed blocks carrying custody operations
through ``state_transition`` (reference suite:
test/custody_game/sanity/test_blocks.py, adapted to this snapshot's
ShardBlob-era sharding layout)."""
import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.ssz.merkle_minimal import (
    calc_merkle_tree_from_leaves,
    get_merkle_proof,
)
from consensus_specs_tpu.testing.helpers.attestations import get_valid_attestation
from consensus_specs_tpu.testing.helpers.block import build_empty_block_for_next_slot
from consensus_specs_tpu.testing.helpers.keys import privkeys
from consensus_specs_tpu.testing.helpers.state import (
    next_slots,
    state_transition_and_sign_block,
    transition_to,
)


@pytest.fixture(autouse=True)
def _bls_on():
    old = bls.bls_active
    bls.bls_active = True
    yield
    bls.bls_active = old


def _signed_key_reveal(spec, state, index):
    revealer = state.validators[index]
    epoch_to_sign = spec.get_randao_epoch_for_custody_period(
        revealer.next_custody_secret_to_reveal, spec.ValidatorIndex(index))
    domain = spec.get_domain(state, spec.DOMAIN_RANDAO, epoch_to_sign)
    return spec.CustodyKeyReveal(
        revealer_index=index,
        reveal=bls.Sign(privkeys[index],
                        spec.compute_signing_root(epoch_to_sign, domain)),
    )


def test_block_with_custody_key_reveal(spec, state):
    transition_to(
        spec, state,
        int(spec.EPOCHS_PER_CUSTODY_PERIOD) * int(spec.SLOTS_PER_EPOCH) + 1)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.custody_key_reveals.append(_signed_key_reveal(spec, state, 0))

    pre_next = int(state.validators[0].next_custody_secret_to_reveal)
    state_transition_and_sign_block(spec, state, block)
    assert int(state.validators[0].next_custody_secret_to_reveal) == pre_next + 1


def test_block_with_premature_key_reveal_rejected(spec, state):
    # No custody period has elapsed: the reveal (and thus the block) fails.
    block = build_empty_block_for_next_slot(spec, state)
    block.body.custody_key_reveals.append(_signed_key_reveal(spec, state, 0))
    state_transition_and_sign_block(spec, state, block, expect_fail=True)


def test_block_with_early_derived_secret_reveal(spec, state):
    epoch = int(spec.get_current_epoch(state)) + int(spec.RANDAO_PENALTY_EPOCHS)
    domain = spec.get_domain(state, spec.DOMAIN_RANDAO, epoch)
    mask = b"\x11" * 32
    reveal = spec.EarlyDerivedSecretReveal(
        revealed_index=1,
        epoch=epoch,
        reveal=bls.Aggregate([
            bls.Sign(privkeys[1], spec.compute_signing_root(spec.Epoch(epoch), domain)),
            bls.Sign(privkeys[2], spec.compute_signing_root(spec.Bytes32(mask), domain)),
        ]),
        masker_index=2,
        mask=mask,
    )
    block = build_empty_block_for_next_slot(spec, state)
    block.body.early_derived_secret_reveals.append(reveal)

    pre_balance = int(state.balances[1])
    state_transition_and_sign_block(spec, state, block)
    assert int(state.balances[1]) < pre_balance
    assert not state.validators[1].slashed


def test_block_with_chunk_challenge_and_response(spec, state):
    """Two blocks: one carrying a chunk challenge against an included-era
    attestation, the next carrying the winning response."""
    bls.bls_active = False  # structure under test; attestation is unsigned
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY) + 1)

    # chunked shard data the response must open into
    depth = int(spec.CUSTODY_RESPONSE_DEPTH)
    chunk = spec.ByteVector[spec.BYTES_PER_CUSTODY_CHUNK](
        b"\x07" * int(spec.BYTES_PER_CUSTODY_CHUNK))
    leaves = [bytes(chunk.hash_tree_root())] * 2
    tree = calc_merkle_tree_from_leaves(leaves, depth)
    length_leaf = (2).to_bytes(32, "little")
    data_root = spec.hash(tree[-1][0] + length_leaf)

    shard_transition = spec.ShardTransition(
        start_slot=1,
        shard_block_lengths=[int(spec.BYTES_PER_CUSTODY_CHUNK) * 2],
        shard_data_roots=[data_root],
    )
    attestation = get_valid_attestation(spec, state, signed=False)
    attestation.data.shard_transition_root = spec.hash_tree_root(shard_transition)
    responder = int(min(spec.get_attesting_indices(
        state, attestation.data, attestation.aggregation_bits)))

    challenge_block = build_empty_block_for_next_slot(spec, state)
    challenge_block.body.chunk_challenges.append(spec.CustodyChunkChallenge(
        responder_index=responder,
        shard_transition=shard_transition,
        attestation=attestation,
        data_index=0,
        chunk_index=1,
    ))
    state_transition_and_sign_block(spec, state, challenge_block)
    record = state.custody_chunk_challenge_records[0]
    assert int(record.responder_index) == responder

    response_block = build_empty_block_for_next_slot(spec, state)
    response_block.body.chunk_challenge_responses.append(spec.CustodyChunkResponse(
        challenge_index=record.challenge_index,
        chunk_index=1,
        chunk=chunk,
        branch=get_merkle_proof(tree, 1, depth) + [length_leaf],
    ))
    state_transition_and_sign_block(spec, state, response_block)
    cleared = state.custody_chunk_challenge_records[0]
    assert bytes(cleared.data_root) == b"\x00" * 32
