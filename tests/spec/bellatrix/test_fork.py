"""Bellatrix fork-upgrade tests (reference capability:
test/bellatrix/fork/test_bellatrix_fork_basic.py)."""
from consensus_specs_tpu.testing.context import (
    low_balances,
    misc_balances,
    spec_test,
    with_custom_state,
    with_phases,
    with_state,
)
from consensus_specs_tpu.testing.helpers.bellatrix.fork import (
    BELLATRIX_FORK_TEST_META_TAGS,
    run_fork_test,
)
from consensus_specs_tpu.testing.helpers.constants import ALTAIR, BELLATRIX
from consensus_specs_tpu.testing.helpers.state import next_epoch, next_epoch_via_block
from consensus_specs_tpu.testing.utils import with_meta_tags


@with_phases(phases=[ALTAIR], other_phases=[BELLATRIX])
@spec_test
@with_state
@with_meta_tags(BELLATRIX_FORK_TEST_META_TAGS)
def test_fork_base_state(spec, phases, state):
    yield from run_fork_test(phases[BELLATRIX], state)


@with_phases(phases=[ALTAIR], other_phases=[BELLATRIX])
@spec_test
@with_state
@with_meta_tags(BELLATRIX_FORK_TEST_META_TAGS)
def test_fork_next_epoch(spec, phases, state):
    next_epoch(spec, state)
    yield from run_fork_test(phases[BELLATRIX], state)


@with_phases(phases=[ALTAIR], other_phases=[BELLATRIX])
@spec_test
@with_state
@with_meta_tags(BELLATRIX_FORK_TEST_META_TAGS)
def test_fork_next_epoch_with_block(spec, phases, state):
    next_epoch_via_block(spec, state)
    yield from run_fork_test(phases[BELLATRIX], state)


@with_phases(phases=[ALTAIR], other_phases=[BELLATRIX])
@with_custom_state(balances_fn=low_balances, threshold_fn=lambda spec: spec.config.EJECTION_BALANCE)
@spec_test
@with_meta_tags(BELLATRIX_FORK_TEST_META_TAGS)
def test_fork_random_low_balances(spec, phases, state):
    yield from run_fork_test(phases[BELLATRIX], state)


@with_phases(phases=[ALTAIR], other_phases=[BELLATRIX])
@with_custom_state(balances_fn=misc_balances, threshold_fn=lambda spec: spec.config.EJECTION_BALANCE)
@spec_test
@with_meta_tags(BELLATRIX_FORK_TEST_META_TAGS)
def test_fork_random_misc_balances(spec, phases, state):
    yield from run_fork_test(phases[BELLATRIX], state)
