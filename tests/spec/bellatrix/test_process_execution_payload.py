"""process_execution_payload operation suite (spec rules:
bellatrix/beacon-chain.md process_execution_payload; reference suite:
test/bellatrix/block_processing/test_process_execution_payload.py)."""
from consensus_specs_tpu.testing.context import (
    expect_assertion_error,
    spec_state_test,
    with_bellatrix_and_later,
)
from consensus_specs_tpu.testing.helpers.execution_payload import (
    build_empty_execution_payload,
    build_state_with_complete_transition,
    build_state_with_incomplete_transition,
    get_execution_payload_header,
)
from consensus_specs_tpu.testing.helpers.state import next_slot


def run_execution_payload_processing(spec, state, payload, valid=True,
                                     execution_valid=True):
    """Yield operation parts; process under an engine stub returning
    ``execution_valid``."""
    yield "pre", state
    yield "execution", {"execution_valid": execution_valid}
    yield "execution_payload", payload

    class TestEngine(spec.NoopExecutionEngine):
        def notify_new_payload(self, new_payload) -> bool:
            assert new_payload == payload
            return execution_valid

    if not valid:
        expect_assertion_error(
            lambda: spec.process_execution_payload(state, payload, TestEngine())
        )
        yield "post", None
        return
    spec.process_execution_payload(state, payload, TestEngine())
    yield "post", state
    assert state.latest_execution_payload_header == get_execution_payload_header(
        spec, payload)


@with_bellatrix_and_later
@spec_state_test
def test_success_first_payload(spec, state):
    state = build_state_with_incomplete_transition(spec, state)
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    yield from run_execution_payload_processing(spec, state, payload)


@with_bellatrix_and_later
@spec_state_test
def test_success_regular_payload(spec, state):
    state = build_state_with_complete_transition(spec, state)
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    yield from run_execution_payload_processing(spec, state, payload)


@with_bellatrix_and_later
@spec_state_test
def test_invalid_bad_parent_hash_regular_payload(spec, state):
    state = build_state_with_complete_transition(spec, state)
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = b"\x55" * 32
    yield from run_execution_payload_processing(spec, state, payload, valid=False)


@with_bellatrix_and_later
@spec_state_test
def test_invalid_bad_prev_randao_regular_payload(spec, state):
    state = build_state_with_complete_transition(spec, state)
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.prev_randao = b"\x42" * 32
    yield from run_execution_payload_processing(spec, state, payload, valid=False)


@with_bellatrix_and_later
@spec_state_test
def test_invalid_future_timestamp_regular_payload(spec, state):
    state = build_state_with_complete_transition(spec, state)
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.timestamp = int(payload.timestamp) + 1
    yield from run_execution_payload_processing(spec, state, payload, valid=False)


@with_bellatrix_and_later
@spec_state_test
def test_invalid_execution_engine_rejects_payload(spec, state):
    state = build_state_with_complete_transition(spec, state)
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    yield from run_execution_payload_processing(
        spec, state, payload, valid=False, execution_valid=False)


@with_bellatrix_and_later
@spec_state_test
def test_invalid_bad_timestamp_first_payload(spec, state):
    # the timestamp rule holds even before the merge transition completes
    state = build_state_with_incomplete_transition(spec, state)
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.timestamp = int(payload.timestamp) + 1
    yield from run_execution_payload_processing(spec, state, payload, valid=False)


@with_bellatrix_and_later
@spec_state_test
def test_success_first_payload_with_gap_slot(spec, state):
    state = build_state_with_incomplete_transition(spec, state)
    next_slot(spec, state)
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    yield from run_execution_payload_processing(spec, state, payload)


@with_bellatrix_and_later
@spec_state_test
def test_non_empty_extra_data(spec, state):
    state = build_state_with_complete_transition(spec, state)
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.extra_data = b"\x45" * 12
    yield from run_execution_payload_processing(spec, state, payload)
    assert bytes(state.latest_execution_payload_header.extra_data) == b"\x45" * 12


@with_bellatrix_and_later
@spec_state_test
def test_bad_parent_hash_first_payload_is_valid(spec, state):
    # before the merge transition completes, parent_hash is unconstrained
    state = build_state_with_incomplete_transition(spec, state)
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = b"\x55" * 32
    yield from run_execution_payload_processing(spec, state, payload)
