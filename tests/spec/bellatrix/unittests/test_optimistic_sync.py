"""Optimistic-sync predicate suite (reference surface: sync/optimistic.md
compiled into the bellatrix spec — OptimisticStore, is_optimistic,
latest_verified_ancestor, is_optimistic_candidate_block — plus
fork_choice/safe-block.md's get_safe_* helpers).  Round 3 pinned these
AST-for-AST; this suite executes them, through the shared test DSL."""
from consensus_specs_tpu.testing.context import (
    spec_configured_state_test,
    spec_state_test,
    with_bellatrix_and_later,
)


def _chain(spec, n, with_payload=()):
    """n linked blocks; indices in ``with_payload`` get a non-empty
    execution payload (an 'execution block')."""
    blocks = []
    parent_root = spec.Root()
    for i in range(n):
        block = spec.BeaconBlock(slot=i + 1, parent_root=parent_root)
        if i in with_payload:
            block.body.execution_payload.block_hash = bytes([i + 1]) * 32
            block.body.execution_payload.timestamp = 1  # non-default payload
        blocks.append(block)
        parent_root = spec.hash_tree_root(block)
    return blocks


def _opt_store(spec, blocks, optimistic_indices):
    roots = [spec.hash_tree_root(b) for b in blocks]
    return spec.OptimisticStore(
        optimistic_roots=set(roots[i] for i in optimistic_indices),
        head_block_root=roots[-1],
        blocks={r: b for r, b in zip(roots, blocks)},
        block_states={},
    )


@with_bellatrix_and_later
@spec_state_test
def test_is_optimistic_membership(spec, state):
    blocks = _chain(spec, 3)
    opt = _opt_store(spec, blocks, {2})
    assert spec.is_optimistic(opt, blocks[2])
    assert not spec.is_optimistic(opt, blocks[0])
    assert not spec.is_optimistic(opt, blocks[1])
    yield "post", None


@with_bellatrix_and_later
@spec_state_test
def test_latest_verified_ancestor_walks_optimistic_suffix(spec, state):
    """Blocks 2..4 optimistic: the latest verified ancestor of the head is
    block 1, regardless of where the walk starts in the suffix."""
    blocks = _chain(spec, 5)
    opt = _opt_store(spec, blocks, {2, 3, 4})
    for start in (2, 3, 4):
        got = spec.latest_verified_ancestor(opt, blocks[start])
        assert spec.hash_tree_root(got) == spec.hash_tree_root(blocks[1])
    # a fully verified block is its own answer
    got = spec.latest_verified_ancestor(opt, blocks[1])
    assert spec.hash_tree_root(got) == spec.hash_tree_root(blocks[1])
    yield "post", None


@with_bellatrix_and_later
@spec_state_test
def test_latest_verified_ancestor_stops_at_genesis_boundary(spec, state):
    """Every block optimistic: the walk terminates at the chain's first
    block (whose parent_root is the zero root)."""
    blocks = _chain(spec, 3)
    opt = _opt_store(spec, blocks, {0, 1, 2})
    got = spec.latest_verified_ancestor(opt, blocks[2])
    assert spec.hash_tree_root(got) == spec.hash_tree_root(blocks[0])
    yield "post", None


@with_bellatrix_and_later
@spec_state_test
def test_is_execution_block(spec, state):
    blocks = _chain(spec, 2, with_payload={1})
    assert not spec.is_execution_block(blocks[0])
    assert spec.is_execution_block(blocks[1])
    yield "post", None


@with_bellatrix_and_later
@spec_state_test
def test_candidate_when_parent_is_execution_block(spec, state):
    blocks = _chain(spec, 3, with_payload={0})
    opt = _opt_store(spec, blocks, set())
    # parent (block 0) carries a payload: optimistic import allowed NOW
    assert spec.is_optimistic_candidate_block(
        opt, current_slot=blocks[1].slot, block=blocks[1])
    yield "post", None


@with_bellatrix_and_later
@spec_state_test
def test_candidate_requires_safe_slot_distance_otherwise(spec, state):
    blocks = _chain(spec, 3)  # no payloads anywhere
    opt = _opt_store(spec, blocks, set())
    safe = int(spec.SAFE_SLOTS_TO_IMPORT_OPTIMISTICALLY)
    block = blocks[1]
    # too recent: not a candidate
    assert not spec.is_optimistic_candidate_block(
        opt, current_slot=spec.Slot(int(block.slot) + safe - 1), block=block)
    # old enough: candidate
    assert spec.is_optimistic_candidate_block(
        opt, current_slot=spec.Slot(int(block.slot) + safe), block=block)
    yield "post", None


@with_bellatrix_and_later
@spec_state_test
def test_safe_block_root_is_justified_root(spec, state):
    anchor = spec.BeaconBlock(state_root=state.hash_tree_root())
    store = spec.get_forkchoice_store(state, anchor)
    assert bytes(spec.get_safe_beacon_block_root(store)) == \
        bytes(store.justified_checkpoint.root)
    yield "post", None


@with_bellatrix_and_later
@spec_state_test
def test_safe_execution_hash_zero_before_fork_epoch(spec, state):
    """Default config: BELLATRIX_FORK_EPOCH is far-future, so the justified
    block predates it and the safe execution hash must be Hash32()."""
    anchor = spec.BeaconBlock(state_root=state.hash_tree_root())
    store = spec.get_forkchoice_store(state, anchor)
    assert int(spec.config.BELLATRIX_FORK_EPOCH) > 0
    assert bytes(spec.get_safe_execution_payload_hash(store)) == b"\x00" * 32
    yield "post", None


@with_bellatrix_and_later
@spec_configured_state_test({"BELLATRIX_FORK_EPOCH": 0})
def test_safe_execution_hash_is_justified_payload_post_fork(spec, state):
    """Fork at genesis: the justified block's epoch reaches
    BELLATRIX_FORK_EPOCH, so the hash must be the justified block's OWN
    payload hash — a non-zero value, so a branch inversion in
    get_safe_execution_payload_hash cannot slip through."""
    payload_hash = b"\x5a" * 32
    anchor = spec.BeaconBlock(state_root=state.hash_tree_root())
    anchor.body.execution_payload.block_hash = payload_hash
    store = spec.get_forkchoice_store(state, anchor)
    assert int(spec.compute_epoch_at_slot(
        store.blocks[spec.get_safe_beacon_block_root(store)].slot)) >= \
        int(spec.config.BELLATRIX_FORK_EPOCH)
    assert bytes(spec.get_safe_execution_payload_hash(store)) == payload_hash
    yield "post", None
