"""validate_merge_block matrix: TTD crossing, PoW-chain lookups, and the
TERMINAL_BLOCK_HASH override path (reference suite:
test/bellatrix/unittests/test_validate_merge_block.py; spec:
bellatrix/fork-choice.md validate_merge_block)."""
from contextlib import contextmanager

from consensus_specs_tpu.testing.context import (
    spec_configured_state_test,
    spec_state_test,
    with_bellatrix_and_later,
)
from consensus_specs_tpu.testing.helpers.block import build_empty_block_for_next_slot
from consensus_specs_tpu.testing.helpers.pow_block import prepare_random_pow_chain

_TBH_HEX = "0x" + "00" * 31 + "01"
_TBH = bytes.fromhex(_TBH_HEX[2:])


@contextmanager
def _pow_chain_visible(spec, pow_chain):
    """Temporarily route spec.get_pow_block through the mock chain."""
    by_hash = pow_chain.to_dict()
    original = spec.get_pow_block

    def get_pow_block(block_hash):
        return by_hash.get(bytes(block_hash))

    spec.get_pow_block = get_pow_block
    try:
        yield
    finally:
        spec.get_pow_block = original


def _check_validate_merge_block(spec, pow_chain, beacon_block, valid=True):
    with _pow_chain_visible(spec, pow_chain):
        try:
            spec.validate_merge_block(beacon_block)
            aborted = False
        except AssertionError:
            aborted = True
    assert aborted != valid


def _chain_crossing_ttd(spec, length=2, head_excess=0, parent_gap=1):
    """A chain whose head sits at TTD + head_excess, parent at
    TTD - parent_gap (clamped at zero)."""
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    chain = prepare_random_pow_chain(spec, length)
    if length > 1:
        chain.head(-1).total_difficulty = max(0, ttd - parent_gap)
    chain.head().total_difficulty = ttd + head_excess
    return chain


def _block_on_pow_head(spec, state, pow_chain):
    block = build_empty_block_for_next_slot(spec, state)
    block.body.execution_payload.parent_hash = pow_chain.head().block_hash
    return block


@with_bellatrix_and_later
@spec_state_test
def test_validate_merge_block_success(spec, state):
    pow_chain = _chain_crossing_ttd(spec)
    _check_validate_merge_block(
        spec, pow_chain, _block_on_pow_head(spec, state, pow_chain))
    yield from ()


@with_bellatrix_and_later
@spec_state_test
def test_validate_merge_block_fail_block_lookup(spec, state):
    pow_chain = _chain_crossing_ttd(spec)
    # payload parent hash left at default: not in the PoW chain
    block = build_empty_block_for_next_slot(spec, state)
    _check_validate_merge_block(spec, pow_chain, block, valid=False)
    yield from ()


@with_bellatrix_and_later
@spec_state_test
def test_validate_merge_block_fail_parent_block_lookup(spec, state):
    # single-block chain: the terminal block's parent is unknown
    pow_chain = _chain_crossing_ttd(spec, length=1)
    _check_validate_merge_block(
        spec, pow_chain, _block_on_pow_head(spec, state, pow_chain), valid=False)
    yield from ()


@with_bellatrix_and_later
@spec_state_test
def test_validate_merge_block_fail_after_terminal(spec, state):
    # both head and parent are at/past TTD: the head is not terminal
    pow_chain = _chain_crossing_ttd(spec, head_excess=1, parent_gap=0)
    _check_validate_merge_block(
        spec, pow_chain, _block_on_pow_head(spec, state, pow_chain), valid=False)
    yield from ()


@with_bellatrix_and_later
@spec_configured_state_test({
    "TERMINAL_BLOCK_HASH": _TBH_HEX,
    "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": "0",
})
def test_validate_merge_block_tbh_override_success(spec, state):
    # TTD deliberately NOT reached: only the hash override validates this
    pow_chain = _chain_crossing_ttd(spec, head_excess=-1, parent_gap=2)
    pow_chain.head().block_hash = _TBH
    _check_validate_merge_block(
        spec, pow_chain, _block_on_pow_head(spec, state, pow_chain))
    yield from ()


@with_bellatrix_and_later
@spec_configured_state_test({
    "TERMINAL_BLOCK_HASH": _TBH_HEX,
    "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": "0",
})
def test_validate_merge_block_fail_parent_hash_is_not_tbh(spec, state):
    # TTD reached, but with a hash override configured only the TBH counts
    pow_chain = _chain_crossing_ttd(spec)
    _check_validate_merge_block(
        spec, pow_chain, _block_on_pow_head(spec, state, pow_chain), valid=False)
    yield from ()


@with_bellatrix_and_later
@spec_configured_state_test({
    "TERMINAL_BLOCK_HASH": _TBH_HEX,
    "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": "1",
})
def test_validate_merge_block_terminal_block_hash_fail_activation_not_reached(spec, state):
    # correct TBH, but the activation epoch is still in the future
    pow_chain = _chain_crossing_ttd(spec)
    pow_chain.head().block_hash = _TBH
    _check_validate_merge_block(
        spec, pow_chain, _block_on_pow_head(spec, state, pow_chain), valid=False)
    yield from ()


@with_bellatrix_and_later
@spec_configured_state_test({
    "TERMINAL_BLOCK_HASH": _TBH_HEX,
    "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": "1",
})
def test_validate_merge_block_fail_activation_not_reached_parent_hash_is_not_tbh(spec, state):
    pow_chain = _chain_crossing_ttd(spec)
    _check_validate_merge_block(
        spec, pow_chain, _block_on_pow_head(spec, state, pow_chain), valid=False)
    yield from ()
