"""Bellatrix randomized block scenarios (reference capability:
test/bellatrix/random/): post-merge states through seeded random walks
(sync aggregates and operations on top of payload-bearing states)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.random_scenarios import run_random_scenario


def _make(seed, with_leak=False, stages=6):
    @spec_state_test
    def case(spec, state):
        yield from run_random_scenario(
            spec, state, seed=seed, stages=stages, with_leak=with_leak)

    return with_phases(["bellatrix"])(case)


test_random_0 = _make(120)
test_random_1 = _make(221)
test_random_leak_0 = _make(524, with_leak=True, stages=4)
