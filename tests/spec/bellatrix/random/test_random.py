"""Bellatrix randomized block scenarios (reference capability:
test/bellatrix/random/): post-merge states through seeded random walks
(sync aggregates and operations on top of payload-bearing states)."""
from functools import partial

from consensus_specs_tpu.testing.random_scenarios import make_random_case

_make = partial(make_random_case, "bellatrix")


test_random_0 = _make(120)
test_random_1 = _make(221)
test_random_leak_0 = _make(524, with_leak=True, stages=4)
