"""Bellatrix sanity block scenarios (reference suite:
test/bellatrix/sanity/test_blocks.py): blocks with execution payloads
pre- and post-merge, and the merge-transition predicate surface."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.block import (
    build_empty_block_for_next_slot,
)
from consensus_specs_tpu.testing.helpers.execution_payload import (
    build_empty_execution_payload,
)
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    state_transition_and_sign_block,
)

BELLATRIX_AND_LATER = ["bellatrix", "capella"]


def _payload_for_block(spec, state, block):
    """Payload built against a copy advanced to the block's slot (the
    builder assumes a same-slot pre-state)."""
    advanced = state.copy()
    spec.process_slots(advanced, block.slot)
    return build_empty_execution_payload(spec, advanced)


@with_phases(BELLATRIX_AND_LATER)
@spec_state_test
def test_empty_block_transition_post_merge(spec, state):
    # mock genesis seeds a payload header: merge already complete
    assert spec.is_merge_transition_complete(state)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed]
    yield "post", state
    assert bytes(state.latest_block_header.body_root) == \
        bytes(block.body.hash_tree_root())


@with_phases(BELLATRIX_AND_LATER)
@spec_state_test
def test_block_with_execution_payload(spec, state):
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    payload = _payload_for_block(spec, state, block)
    block.body.execution_payload = payload
    signed = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed]
    yield "post", state
    assert bytes(state.latest_execution_payload_header.block_hash) == \
        bytes(payload.block_hash)


@with_phases(BELLATRIX_AND_LATER)
@spec_state_test
def test_payloads_across_epoch_boundary(spec, state):
    yield "pre", state
    blocks = []
    next_epoch(spec, state)
    for _ in range(3):
        block = build_empty_block_for_next_slot(spec, state)
        block.body.execution_payload = _payload_for_block(spec, state, block)
        blocks.append(state_transition_and_sign_block(spec, state, block))
    yield "blocks", blocks
    yield "post", state
    assert int(state.slot) > int(spec.SLOTS_PER_EPOCH)


@with_phases(BELLATRIX_AND_LATER)
@spec_state_test
def test_invalid_payload_timestamp(spec, state):
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    payload = _payload_for_block(spec, state, block)
    payload.timestamp = int(payload.timestamp) + 1
    block.body.execution_payload = payload
    signed = state_transition_and_sign_block(
        spec, state, block, expect_fail=True)
    yield "blocks", [signed]
    yield "post", None
