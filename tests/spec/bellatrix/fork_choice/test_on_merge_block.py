"""on_block at the merge transition: terminal-block validation against a
mocked PoW chain (reference suite:
test/bellatrix/fork_choice/test_on_merge_block.py; spec:
bellatrix/fork-choice.md on_block + validate_merge_block)."""
from consensus_specs_tpu.testing.context import spec_state_test, with_phases
from consensus_specs_tpu.testing.exceptions import BlockNotFoundException
from consensus_specs_tpu.testing.helpers.block import build_empty_block_for_next_slot
from consensus_specs_tpu.testing.helpers.execution_payload import (
    build_state_with_incomplete_transition,
)
from consensus_specs_tpu.testing.helpers.fork_choice import (
    add_pow_block,
    get_genesis_forkchoice_store_and_block,
    on_tick_and_append_step,
    tick_and_add_block,
)
from consensus_specs_tpu.testing.helpers.pow_block import prepare_random_pow_block
from consensus_specs_tpu.testing.helpers.state import state_transition_and_sign_block


def _merge_scenario(spec, state, parent_gap, head_excess, chain_length=2):
    """Common driver: pre-merge anchor state, a mocked PoW chain with the
    head at TTD + head_excess (parent at TTD - parent_gap), and one beacon
    block claiming the PoW head as payload parent.

    Returns a generator to be yield-driven by the test; the ``expect``
    kwargs of _deliver control validity.
    """
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    pow_blocks = []
    pow_head = prepare_random_pow_block(spec)
    pow_head.total_difficulty = ttd + head_excess
    pow_blocks.append(pow_head)
    if chain_length > 1:
        pow_parent = prepare_random_pow_block(spec)
        pow_parent.total_difficulty = max(0, ttd - parent_gap)
        pow_head.parent_hash = pow_parent.block_hash
        pow_blocks.append(pow_parent)
    return pow_blocks


def _run_merge_case(spec, state, pow_blocks, valid, block_not_found=False,
                    expect_head=False):
    test_steps = []
    state = build_state_with_incomplete_transition(spec, state)
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)
    yield "anchor_state", state
    yield "anchor_block", anchor_block
    on_tick_and_append_step(
        spec, store,
        int(store.genesis_time) + int(state.slot) * int(spec.config.SECONDS_PER_SLOT),
        test_steps)

    for pow_block in pow_blocks:
        yield from add_pow_block(spec, store, pow_block, test_steps)

    by_hash = {bytes(b.block_hash): b for b in pow_blocks}
    original = spec.get_pow_block

    def get_pow_block(block_hash):
        try:
            return by_hash[bytes(block_hash)]
        except KeyError:
            raise BlockNotFoundException()

    spec.get_pow_block = get_pow_block
    try:
        block = build_empty_block_for_next_slot(spec, state)
        block.body.execution_payload.parent_hash = pow_blocks[0].block_hash
        signed = state_transition_and_sign_block(spec, state, block)
        yield from tick_and_add_block(
            spec, store, signed, test_steps, valid=valid,
            merge_block=True, block_not_found=block_not_found)
        if expect_head:
            assert spec.get_head(store) == signed.message.hash_tree_root()
    finally:
        spec.get_pow_block = original
    yield "steps", "data", test_steps


@with_phases(["bellatrix"])
@spec_state_test
def test_all_valid(spec, state):
    pow_blocks = _merge_scenario(spec, state, parent_gap=1, head_excess=0)
    yield from _run_merge_case(spec, state, pow_blocks, valid=True, expect_head=True)


@with_phases(["bellatrix"])
@spec_state_test
def test_block_lookup_failed(spec, state):
    # single sub-TTD PoW block: the parent lookup raises BlockNotFound
    pow_blocks = _merge_scenario(spec, state, parent_gap=1, head_excess=-1,
                                 chain_length=1)
    yield from _run_merge_case(
        spec, state, pow_blocks, valid=False, block_not_found=True)


@with_phases(["bellatrix"])
@spec_state_test
def test_too_early_for_merge(spec, state):
    # head one short of TTD: not terminal yet
    pow_blocks = _merge_scenario(spec, state, parent_gap=2, head_excess=-1)
    yield from _run_merge_case(spec, state, pow_blocks, valid=False)


@with_phases(["bellatrix"])
@spec_state_test
def test_too_late_for_merge(spec, state):
    # parent already at TTD: the head is past the terminal block
    pow_blocks = _merge_scenario(spec, state, parent_gap=0, head_excess=1)
    yield from _run_merge_case(spec, state, pow_blocks, valid=False)
