"""Bellatrix terminal PoW block validity tests via the pow_block helpers
(reference capability: test/bellatrix/unittests/test_validate_merge_block.py
family)."""
from random import Random

from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.pow_block import (
    prepare_random_pow_block,
    prepare_random_pow_chain,
)


@with_phases(["bellatrix", "capella"])
@spec_state_test
def test_is_valid_terminal_pow_block_success(spec, state):
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    rng = Random(11)
    parent = prepare_random_pow_block(spec, rng)
    parent.total_difficulty = ttd - 1
    block = prepare_random_pow_block(spec, rng)
    block.parent_hash = parent.block_hash
    block.total_difficulty = ttd
    assert spec.is_valid_terminal_pow_block(block, parent)
    yield from ()


@with_phases(["bellatrix", "capella"])
@spec_state_test
def test_is_valid_terminal_pow_block_fails_before_ttd(spec, state):
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    rng = Random(12)
    parent = prepare_random_pow_block(spec, rng)
    parent.total_difficulty = max(0, ttd - 2)
    block = prepare_random_pow_block(spec, rng)
    block.parent_hash = parent.block_hash
    block.total_difficulty = max(0, ttd - 1)
    assert not spec.is_valid_terminal_pow_block(block, parent)
    yield from ()


@with_phases(["bellatrix", "capella"])
@spec_state_test
def test_is_valid_terminal_pow_block_fails_parent_at_ttd(spec, state):
    # parent already reached TTD: the child is not the terminal block
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    rng = Random(13)
    parent = prepare_random_pow_block(spec, rng)
    parent.total_difficulty = ttd
    block = prepare_random_pow_block(spec, rng)
    block.parent_hash = parent.block_hash
    block.total_difficulty = ttd + 1
    assert not spec.is_valid_terminal_pow_block(block, parent)
    yield from ()


@with_phases(["bellatrix", "capella"])
@spec_state_test
def test_pow_chain_linkage(spec, state):
    chain = prepare_random_pow_chain(spec, 5, Random(14))
    blocks = list(chain)
    for parent, child in zip(blocks, blocks[1:]):
        assert child.parent_hash == parent.block_hash
    assert chain.head() == blocks[-1]
    assert chain.to_dict()[blocks[2].block_hash] == blocks[2]
    yield from ()
