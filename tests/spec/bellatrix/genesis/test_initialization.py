"""Bellatrix genesis suite (reference suite:
test/bellatrix/genesis/test_initialization.py): the testing-variant
``initialize_beacon_state_from_eth1`` seeds an execution payload header
(reference: setup.py BellatrixSpecBuilder sundry preparations)."""
from consensus_specs_tpu.testing.context import (
    with_presets,
    single_phase,
    spec_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.deposits import (
    prepare_full_genesis_deposits,
)
from consensus_specs_tpu.testing.helpers.genesis import (
    get_sample_genesis_execution_payload_header,
)

GENESIS_TIME = 1578009600


@with_phases(["bellatrix"])
@spec_test
@single_phase
@with_presets(["minimal"], reason="mainnet genesis means 16384 signed deposits per case")
def test_initialize_pre_transition_empty_payload(spec):
    deposit_count = spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
    deposits, deposit_root, _ = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE, deposit_count, signed=True,
    )
    eth1_block_hash = b"\x12" * 32
    state = spec.initialize_beacon_state_from_eth1(
        eth1_block_hash, GENESIS_TIME, deposits
    )
    assert len(state.validators) == deposit_count
    # default (empty) payload header: the merge is NOT complete
    assert not spec.is_merge_transition_complete(state)
    yield "eth1_block_hash", eth1_block_hash
    yield "deposits", deposits
    yield "state", state


@with_phases(["bellatrix"])
@spec_test
@single_phase
@with_presets(["minimal"], reason="mainnet genesis means 16384 signed deposits per case")
def test_initialize_post_transition_with_payload_header(spec):
    deposit_count = spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
    deposits, _, _ = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE, deposit_count, signed=True,
    )
    eth1_block_hash = b"\x12" * 32
    header = get_sample_genesis_execution_payload_header(spec, eth1_block_hash)
    state = spec.initialize_beacon_state_from_eth1(
        eth1_block_hash, GENESIS_TIME, deposits,
        execution_payload_header=header,
    )
    yield "eth1_block_hash", eth1_block_hash
    yield "deposits", deposits
    # seeded payload header: genesis is post-merge
    assert spec.is_merge_transition_complete(state)
    assert bytes(state.latest_execution_payload_header.hash_tree_root()) == \
        bytes(header.hash_tree_root())
    yield "execution_payload_header", header
    yield "state", state
