"""Capella withdrawals tests (reference: test/capella/, early-draft
full-withdrawals queue semantics)."""
from consensus_specs_tpu.testing.context import (
    expect_assertion_error,
    spec_state_test,
    with_capella_and_later,
)
from consensus_specs_tpu.testing.helpers.execution_payload import (
    build_empty_execution_payload,
    build_state_with_complete_transition,
)
from consensus_specs_tpu.testing.helpers.state import next_epoch


def _make_validator_withdrawable(spec, state, index):
    validator = state.validators[index]
    validator.withdrawal_credentials = (
        bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX) + bytes(validator.withdrawal_credentials[1:])
    )
    validator.withdrawable_epoch = spec.get_current_epoch(state)
    assert spec.is_fully_withdrawable_validator(
        state.validators[index], spec.get_current_epoch(state))


@with_capella_and_later
@spec_state_test
def test_process_withdrawals_dequeues_queue(spec, state):
    state = build_state_with_complete_transition(spec, state)
    index = 0
    _make_validator_withdrawable(spec, state, index)
    next_epoch(spec, state)  # enqueue the withdrawal
    assert len(state.withdrawals_queue) == 1

    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 1

    yield "pre", state
    yield "execution_payload", payload
    spec.process_withdrawals(state, payload)
    yield "post", state

    assert len(state.withdrawals_queue) == 0


@with_capella_and_later
@spec_state_test
def test_process_withdrawals_wrong_payload_fails(spec, state):
    state = build_state_with_complete_transition(spec, state)
    index = 0
    _make_validator_withdrawable(spec, state, index)
    next_epoch(spec, state)
    assert len(state.withdrawals_queue) == 1

    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals[0].amount += 1  # mismatch vs queue

    yield "pre", state
    yield "execution_payload", payload
    expect_assertion_error(lambda: spec.process_withdrawals(state, payload))
    yield "post", None


@with_capella_and_later
@spec_state_test
def test_get_expected_withdrawals_caps_at_payload_max(spec, state):
    """capella/validator.md get_expected_withdrawals: the next payload
    carries at most MAX_WITHDRAWALS_PER_PAYLOAD queue entries, in order."""
    state = build_state_with_complete_transition(spec, state)
    for index in range(int(spec.MAX_WITHDRAWALS_PER_PAYLOAD) + 2):
        state.withdrawals_queue.append(spec.Withdrawal(
            index=index, address=b"\x42" * 20, amount=1000 + index))
    yield "meta", {"bls_setting": 2}
    expected = spec.get_expected_withdrawals(state)
    assert len(expected) == int(spec.MAX_WITHDRAWALS_PER_PAYLOAD)
    assert [int(w.index) for w in expected] == \
        list(range(int(spec.MAX_WITHDRAWALS_PER_PAYLOAD)))


@with_capella_and_later
@spec_state_test
def test_prepare_execution_payload_includes_withdrawals(spec, state):
    """capella/validator.md prepare_execution_payload: post-merge, the
    payload attributes handed to the engine carry the expected
    withdrawals."""
    state = build_state_with_complete_transition(spec, state)
    state.withdrawals_queue.append(spec.Withdrawal(
        index=0, address=b"\x42" * 20, amount=777))
    yield "meta", {"bls_setting": 2}

    captured = {}

    class RecordingEngine(spec.NoopExecutionEngine):
        def notify_forkchoice_updated(self, head_block_hash, safe_block_hash,
                                      finalized_block_hash, payload_attributes):
            captured["attrs"] = payload_attributes
            captured["head"] = head_block_hash
            return None

    spec.prepare_execution_payload(
        state, {}, spec.Hash32(), spec.Hash32(),
        spec.ExecutionAddress(b"\x11" * 20), RecordingEngine())

    attrs = captured["attrs"]
    assert bytes(captured["head"]) == \
        bytes(state.latest_execution_payload_header.block_hash)
    assert [int(w.amount) for w in attrs.withdrawals] == [777]
    assert int(attrs.timestamp) == \
        int(spec.compute_timestamp_at_slot(state, state.slot))
