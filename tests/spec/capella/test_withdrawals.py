"""Capella withdrawals tests (reference: test/capella/, early-draft
full-withdrawals queue semantics)."""
from consensus_specs_tpu.testing.context import (
    expect_assertion_error,
    spec_state_test,
    with_capella_and_later,
)
from consensus_specs_tpu.testing.helpers.execution_payload import (
    build_empty_execution_payload,
    build_state_with_complete_transition,
)
from consensus_specs_tpu.testing.helpers.state import next_epoch


def _make_validator_withdrawable(spec, state, index):
    validator = state.validators[index]
    validator.withdrawal_credentials = (
        bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX) + bytes(validator.withdrawal_credentials[1:])
    )
    validator.withdrawable_epoch = spec.get_current_epoch(state)
    assert spec.is_fully_withdrawable_validator(
        state.validators[index], spec.get_current_epoch(state))


@with_capella_and_later
@spec_state_test
def test_process_withdrawals_dequeues_queue(spec, state):
    state = build_state_with_complete_transition(spec, state)
    index = 0
    _make_validator_withdrawable(spec, state, index)
    next_epoch(spec, state)  # enqueue the withdrawal
    assert len(state.withdrawals_queue) == 1

    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 1

    yield "pre", state
    yield "execution_payload", payload
    spec.process_withdrawals(state, payload)
    yield "post", state

    assert len(state.withdrawals_queue) == 0


@with_capella_and_later
@spec_state_test
def test_process_withdrawals_wrong_payload_fails(spec, state):
    state = build_state_with_complete_transition(spec, state)
    index = 0
    _make_validator_withdrawable(spec, state, index)
    next_epoch(spec, state)
    assert len(state.withdrawals_queue) == 1

    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals[0].amount += 1  # mismatch vs queue

    yield "pre", state
    yield "execution_payload", payload
    expect_assertion_error(lambda: spec.process_withdrawals(state, payload))
    yield "post", None
