"""Capella withdrawals tests (reference: test/capella/, early-draft
full-withdrawals queue semantics)."""
from consensus_specs_tpu.testing.context import (
    expect_assertion_error,
    spec_state_test,
    with_capella_and_later,
)
from consensus_specs_tpu.testing.helpers.execution_payload import (
    build_empty_execution_payload,
    build_state_with_complete_transition,
)
from consensus_specs_tpu.testing.helpers.state import next_epoch


def _make_validator_withdrawable(spec, state, index):
    validator = state.validators[index]
    validator.withdrawal_credentials = (
        bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX) + bytes(validator.withdrawal_credentials[1:])
    )
    validator.withdrawable_epoch = spec.get_current_epoch(state)
    assert spec.is_fully_withdrawable_validator(
        state.validators[index], spec.get_current_epoch(state))


@with_capella_and_later
@spec_state_test
def test_process_withdrawals_dequeues_queue(spec, state):
    state = build_state_with_complete_transition(spec, state)
    index = 0
    _make_validator_withdrawable(spec, state, index)
    next_epoch(spec, state)  # enqueue the withdrawal
    assert len(state.withdrawals_queue) == 1

    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 1

    yield "pre", state
    yield "execution_payload", payload
    spec.process_withdrawals(state, payload)
    yield "post", state

    assert len(state.withdrawals_queue) == 0


@with_capella_and_later
@spec_state_test
def test_process_withdrawals_wrong_payload_fails(spec, state):
    state = build_state_with_complete_transition(spec, state)
    index = 0
    _make_validator_withdrawable(spec, state, index)
    next_epoch(spec, state)
    assert len(state.withdrawals_queue) == 1

    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals[0].amount += 1  # mismatch vs queue

    yield "pre", state
    yield "execution_payload", payload
    expect_assertion_error(lambda: spec.process_withdrawals(state, payload))
    yield "post", None


@with_capella_and_later
@spec_state_test
def test_process_withdrawals_empty_queue_empty_payload(spec, state):
    state = build_state_with_complete_transition(spec, state)
    assert len(state.withdrawals_queue) == 0
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 0

    yield "pre", state
    yield "execution_payload", payload
    spec.process_withdrawals(state, payload)
    yield "post", state
    assert len(state.withdrawals_queue) == 0


@with_capella_and_later
@spec_state_test
def test_process_withdrawals_multiple_dequeued_in_order(spec, state):
    state = build_state_with_complete_transition(spec, state)
    for index in (0, 1, 2):
        _make_validator_withdrawable(spec, state, index)
    next_epoch(spec, state)
    assert len(state.withdrawals_queue) == 3
    queued = [w.copy() for w in state.withdrawals_queue]

    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 3

    yield "pre", state
    yield "execution_payload", payload
    spec.process_withdrawals(state, payload)
    yield "post", state
    assert len(state.withdrawals_queue) == 0
    # FIFO: payload order matched the queue's
    for want, got in zip(queued, payload.withdrawals):
        assert want == got


@with_capella_and_later
@spec_state_test
def test_process_withdrawals_caps_at_max_per_payload(spec, state):
    state = build_state_with_complete_transition(spec, state)
    count = int(spec.MAX_WITHDRAWALS_PER_PAYLOAD) + 2
    for index in range(count):
        _make_validator_withdrawable(spec, state, index)
    next_epoch(spec, state)
    assert len(state.withdrawals_queue) == count

    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == int(spec.MAX_WITHDRAWALS_PER_PAYLOAD)

    yield "pre", state
    yield "execution_payload", payload
    spec.process_withdrawals(state, payload)
    yield "post", state
    assert len(state.withdrawals_queue) == 2  # the overflow stays queued


@with_capella_and_later
@spec_state_test
def test_process_withdrawals_extra_payload_withdrawal_fails(spec, state):
    state = build_state_with_complete_transition(spec, state)
    _make_validator_withdrawable(spec, state, 0)
    next_epoch(spec, state)

    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals.append(payload.withdrawals[0])  # uncovenanted extra

    yield "pre", state
    yield "execution_payload", payload
    expect_assertion_error(lambda: spec.process_withdrawals(state, payload))
    yield "post", None


@with_capella_and_later
@spec_state_test
def test_process_withdrawals_wrong_order_fails(spec, state):
    state = build_state_with_complete_transition(spec, state)
    for index in (0, 1):
        _make_validator_withdrawable(spec, state, index)
    next_epoch(spec, state)
    assert len(state.withdrawals_queue) == 2

    payload = build_empty_execution_payload(spec, state)
    w0, w1 = payload.withdrawals[0].copy(), payload.withdrawals[1].copy()
    payload.withdrawals[0] = w1
    payload.withdrawals[1] = w0

    yield "pre", state
    yield "execution_payload", payload
    expect_assertion_error(lambda: spec.process_withdrawals(state, payload))
    yield "post", None


@with_capella_and_later
@spec_state_test
def test_withdraw_balance_enqueues_and_decrements(spec, state):
    index = 0
    pre_balance = int(state.balances[index])
    amount = pre_balance // 4
    pre_queue_len = len(state.withdrawals_queue)

    spec.withdraw_balance(state, index, amount)

    assert int(state.balances[index]) == pre_balance - amount
    assert len(state.withdrawals_queue) == pre_queue_len + 1
    entry = state.withdrawals_queue[-1]
    assert int(entry.amount) == amount
    assert int(entry.index) == pre_queue_len  # monotone withdrawal index
    # recipient address comes from the eth1 withdrawal credentials tail
    assert bytes(entry.address) == bytes(
        state.validators[index].withdrawal_credentials[12:])
    yield from ()


@with_capella_and_later
@spec_state_test
def test_full_withdrawals_epoch_processing_skips_bls_credentialed(spec, state):
    # BLS-prefixed credentials are NOT withdrawable in the early draft
    index = 0
    validator = state.validators[index]
    validator.withdrawable_epoch = spec.get_current_epoch(state)
    assert bytes(validator.withdrawal_credentials)[:1] == bytes(
        spec.BLS_WITHDRAWAL_PREFIX)
    assert not spec.is_fully_withdrawable_validator(
        validator, spec.get_current_epoch(state))
    pre_queue_len = len(state.withdrawals_queue)
    next_epoch(spec, state)
    assert len(state.withdrawals_queue) == pre_queue_len
    yield from ()
