"""Capella randomized block scenarios (reference capability:
test/capella random coverage via the transition suites): withdrawal-era
states through seeded random walks."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.random_scenarios import run_random_scenario


def _make(seed, with_leak=False, stages=6):
    @spec_state_test
    def case(spec, state):
        yield from run_random_scenario(
            spec, state, seed=seed, stages=stages, with_leak=with_leak)

    return with_phases(["capella"])(case)


test_random_0 = _make(130)
test_random_1 = _make(231)
test_random_leak_0 = _make(534, with_leak=True, stages=4)
