"""Capella randomized block scenarios (reference capability:
test/capella random coverage via the transition suites): withdrawal-era
states through seeded random walks."""
from functools import partial

from consensus_specs_tpu.testing.random_scenarios import make_random_case

_make = partial(make_random_case, "capella")


test_random_0 = _make(130)
test_random_1 = _make(231)
test_random_leak_0 = _make(534, with_leak=True, stages=4)
