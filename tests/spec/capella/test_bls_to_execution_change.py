"""BLSToExecutionChange operation tests (reference: test/capella/block_processing)."""
from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.testing.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test,
    with_capella_and_later,
)
from consensus_specs_tpu.testing.helpers.keys import pubkeys, pubkey_to_privkey


def _signed_address_change(spec, state, validator_index):
    withdrawal_pubkey = pubkeys[-1 - int(validator_index)]
    privkey = pubkey_to_privkey[withdrawal_pubkey]
    address_change = spec.BLSToExecutionChange(
        validator_index=validator_index,
        from_bls_pubkey=withdrawal_pubkey,
        to_execution_address=b"\x42" * 20,
    )
    domain = spec.get_domain(state, spec.DOMAIN_BLS_TO_EXECUTION_CHANGE)
    signing_root = spec.compute_signing_root(address_change, domain)
    return spec.SignedBLSToExecutionChange(
        message=address_change,
        signature=bls.Sign(privkey, signing_root),
    )


@with_capella_and_later
@spec_state_test
@always_bls
def test_valid_bls_to_execution_change(spec, state):
    signed_change = _signed_address_change(spec, state, 0)
    yield "pre", state
    yield "address_change", signed_change
    spec.process_bls_to_execution_change(state, signed_change)
    yield "post", state

    creds = state.validators[0].withdrawal_credentials
    assert creds[:1] == spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX
    assert creds[12:] == b"\x42" * 20


@with_capella_and_later
@spec_state_test
@always_bls
def test_invalid_signature_rejected(spec, state):
    signed_change = _signed_address_change(spec, state, 0)
    signed_change.signature = spec.BLSSignature(b"\x01" + bytes(signed_change.signature[1:]))
    yield "pre", state
    yield "address_change", signed_change
    expect_assertion_error(lambda: spec.process_bls_to_execution_change(state, signed_change))
    yield "post", None


@with_capella_and_later
@spec_state_test
def test_wrong_pubkey_rejected(spec, state):
    signed_change = _signed_address_change(spec, state, 0)
    signed_change.message.from_bls_pubkey = pubkeys[5]  # wrong withdrawal key
    yield "pre", state
    yield "address_change", signed_change
    expect_assertion_error(lambda: spec.process_bls_to_execution_change(state, signed_change))
    yield "post", None


@with_capella_and_later
@spec_state_test
def test_out_of_range_validator_index(spec, state):
    signed_change = _signed_address_change(spec, state, 0)
    signed_change.message.validator_index = len(state.validators)
    yield "pre", state
    yield "address_change", signed_change
    expect_assertion_error(lambda: spec.process_bls_to_execution_change(state, signed_change))
    yield "post", None
