"""Capella validator-duty unittests (capella/validator.md): expected
withdrawals and payload preparation — pure helpers, no vector parts (kept
out of the operations-reflected modules)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_capella_and_later,
)
from consensus_specs_tpu.testing.helpers.execution_payload import (
    build_state_with_complete_transition,
)


@with_capella_and_later
@spec_state_test
def test_get_expected_withdrawals_caps_at_payload_max(spec, state):
    """capella/validator.md get_expected_withdrawals: the next payload
    carries at most MAX_WITHDRAWALS_PER_PAYLOAD queue entries, in order."""
    state = build_state_with_complete_transition(spec, state)
    for index in range(int(spec.MAX_WITHDRAWALS_PER_PAYLOAD) + 2):
        state.withdrawals_queue.append(spec.Withdrawal(
            index=index, address=b"\x42" * 20, amount=1000 + index))
    yield "meta", {"bls_setting": 2}
    expected = spec.get_expected_withdrawals(state)
    assert len(expected) == int(spec.MAX_WITHDRAWALS_PER_PAYLOAD)
    assert [int(w.index) for w in expected] == \
        list(range(int(spec.MAX_WITHDRAWALS_PER_PAYLOAD)))


@with_capella_and_later
@spec_state_test
def test_prepare_execution_payload_includes_withdrawals(spec, state):
    """capella/validator.md prepare_execution_payload: post-merge, the
    payload attributes handed to the engine carry the expected
    withdrawals."""
    state = build_state_with_complete_transition(spec, state)
    state.withdrawals_queue.append(spec.Withdrawal(
        index=0, address=b"\x42" * 20, amount=777))
    yield "meta", {"bls_setting": 2}

    captured = {}

    class RecordingEngine(spec.NoopExecutionEngine):
        def notify_forkchoice_updated(self, head_block_hash, safe_block_hash,
                                      finalized_block_hash, payload_attributes):
            captured["attrs"] = payload_attributes
            captured["head"] = head_block_hash
            return None

    spec.prepare_execution_payload(
        state, {}, spec.Hash32(), spec.Hash32(),
        spec.ExecutionAddress(b"\x11" * 20), RecordingEngine())

    attrs = captured["attrs"]
    assert bytes(captured["head"]) == \
        bytes(state.latest_execution_payload_header.block_hash)
    assert [int(w.amount) for w in attrs.withdrawals] == [777]
    assert int(attrs.timestamp) == \
        int(spec.compute_timestamp_at_slot(state, state.slot))
