"""process_full_withdrawals suite (spec: capella/beacon-chain.md:311;
reference suite: test/capella/epoch_processing/test_process_full_withdrawals.py).
This snapshot is the early-capella draft: fully withdrawable validators'
balances move to the in-state withdrawals queue."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.epoch_processing import (
    run_epoch_processing_to,
    run_epoch_processing_with,
)
from consensus_specs_tpu.testing.helpers.state import next_epoch


def _make_fully_withdrawable(spec, state, index, epoch=None):
    if epoch is None:
        epoch = spec.get_current_epoch(state)
    validator = state.validators[index]
    validator.withdrawal_credentials = (
        bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX)
        + bytes(validator.withdrawal_credentials)[1:]
    )
    validator.withdrawable_epoch = epoch
    assert spec.is_fully_withdrawable_validator(
        validator, spec.get_current_epoch(state))


@with_phases(["capella"])
@spec_state_test
def test_no_withdrawable_validators(spec, state):
    next_epoch(spec, state)
    pre_queue_len = len(state.withdrawals_queue)
    yield from run_epoch_processing_with(spec, state, "process_full_withdrawals")
    assert len(state.withdrawals_queue) == pre_queue_len


@with_phases(["capella"])
@spec_state_test
def test_single_full_withdrawal(spec, state):
    next_epoch(spec, state)
    _make_fully_withdrawable(spec, state, 0)
    # advance through the prior sub-transitions, then capture the balance
    # the sweep will actually withdraw
    run_epoch_processing_to(spec, state, "process_full_withdrawals")
    pre_balance = int(state.balances[0])
    assert pre_balance > 0
    pre_queue_len = len(state.withdrawals_queue)
    yield "pre", state
    spec.process_full_withdrawals(state)
    yield "post", state
    assert int(state.balances[0]) == 0
    assert len(state.withdrawals_queue) == pre_queue_len + 1
    withdrawal = state.withdrawals_queue[-1]
    assert int(withdrawal.amount) == pre_balance
    # withdrawal address = eth1 credential tail of validator 0
    assert bytes(withdrawal.address) == \
        bytes(state.validators[0].withdrawal_credentials)[12:]
    # marked withdrawn at this epoch: not withdrawable again next pass
    assert int(state.validators[0].fully_withdrawn_epoch) == \
        int(spec.get_current_epoch(state))
    assert not spec.is_fully_withdrawable_validator(
        state.validators[0], spec.get_current_epoch(state))


@with_phases(["capella"])
@spec_state_test
def test_multiple_full_withdrawals_ordered(spec, state):
    next_epoch(spec, state)
    targets = [2, 5, 9]
    for index in targets:
        _make_fully_withdrawable(spec, state, index)
    run_epoch_processing_to(spec, state, "process_full_withdrawals")
    balances = {index: int(state.balances[index]) for index in targets}
    start_index = int(state.next_withdrawal_index) \
        if hasattr(state, "next_withdrawal_index") else None
    pre_queue_len = len(state.withdrawals_queue)
    yield "pre", state
    spec.process_full_withdrawals(state)
    yield "post", state
    queued = list(state.withdrawals_queue)[pre_queue_len:]
    # swept in validator-index order, amounts as-of the sweep
    assert [bytes(w.address) for w in queued] == [
        bytes(state.validators[i].withdrawal_credentials)[12:] for i in targets]
    assert [int(w.amount) for w in queued] == [balances[i] for i in targets]
    if start_index is not None:
        assert [int(w.index) for w in queued] == \
            [start_index + i for i in range(len(targets))]
    for index in targets:
        assert int(state.balances[index]) == 0


@with_phases(["capella"])
@spec_state_test
def test_bls_credentials_not_withdrawable(spec, state):
    """Validators still on BLS withdrawal credentials must not be swept
    even when their withdrawable epoch has passed."""
    next_epoch(spec, state)
    validator = state.validators[1]
    assert bytes(validator.withdrawal_credentials)[:1] == \
        bytes(spec.BLS_WITHDRAWAL_PREFIX)
    validator.withdrawable_epoch = spec.get_current_epoch(state)
    run_epoch_processing_to(spec, state, "process_full_withdrawals")
    pre_balance = int(state.balances[1])
    pre_queue_len = len(state.withdrawals_queue)
    yield "pre", state
    spec.process_full_withdrawals(state)
    yield "post", state
    assert int(state.balances[1]) == pre_balance
    assert len(state.withdrawals_queue) == pre_queue_len
