"""Slashed-but-active validators crossing the fork boundary (reference
suite: test/altair/transition/test_slashing.py)."""
import random

from consensus_specs_tpu.testing.context import (
    ForkMeta,
    with_fork_metas,
    with_presets,
)
from consensus_specs_tpu.testing.helpers.constants import (
    ALL_PRE_POST_FORKS,
    MINIMAL,
)
from consensus_specs_tpu.testing.helpers.fork_transition import (
    do_fork,
    transition_to_next_epoch_and_append_blocks,
    transition_until_fork,
)
from consensus_specs_tpu.testing.helpers.random import slash_random_validators


@with_fork_metas([ForkMeta(pre_fork_name=pre, post_fork_name=post, fork_epoch=1)
                  for pre, post in ALL_PRE_POST_FORKS])
@with_presets([MINIMAL], reason="needs a registry larger than one sync committee")
def test_transition_with_one_fourth_slashed_active_validators_pre_fork(
        state, fork_epoch, spec, post_spec, pre_tag, post_tag):
    """A quarter of the registry is slashed (still active) at the fork.
    Slashed validators keep their sync-committee eligibility but cannot
    propose, so post-fork blocks must dodge slashed proposers."""
    slashed = slash_random_validators(
        spec, state, rng=random.Random(5566), fraction=0.25)
    assert slashed
    now = spec.get_current_epoch(state)
    for index in slashed:
        v = state.validators[index]
        assert v.slashed
        assert spec.is_active_validator(v, now)
    assert not spec.is_in_inactivity_leak(state)

    transition_until_fork(spec, state, fork_epoch)
    assert spec.get_current_epoch(state) < fork_epoch

    yield "pre", state

    state, _ = do_fork(state, spec, post_spec, fork_epoch, with_block=False)

    slashed_keys = {bytes(state.validators[i].pubkey) for i in slashed}
    committee_keys = {bytes(pk) for pk in state.current_sync_committee.pubkeys}
    assert slashed_keys & committee_keys
    assert slashed_keys - committee_keys

    blocks = []
    transition_to_next_epoch_and_append_blocks(
        post_spec, state, post_tag, blocks, only_last_block=True,
        ignoring_proposers=set(slashed))

    now = post_spec.get_current_epoch(state)
    for v in state.validators:
        assert post_spec.is_active_validator(v, now)
    assert not post_spec.is_in_inactivity_leak(state)

    yield "blocks", blocks
    yield "post", state
