"""Inactivity leak straddling the fork boundary (reference suite:
test/altair/transition/test_leaking.py).  Minimal-preset leak onset is
MIN_EPOCHS_TO_INACTIVITY_PENALTY + 2 = epoch 6 with no attestations."""
from consensus_specs_tpu.testing.context import ForkMeta, with_fork_metas
from consensus_specs_tpu.testing.helpers.constants import ALL_PRE_POST_FORKS
from consensus_specs_tpu.testing.helpers.fork_transition import (
    do_fork,
    transition_to_next_epoch_and_append_blocks,
    transition_until_fork,
)


def _run_leak_transition(state, fork_epoch, spec, post_spec, post_tag,
                         leaking_pre_fork):
    transition_until_fork(spec, state, fork_epoch)
    assert spec.is_in_inactivity_leak(state) == leaking_pre_fork

    yield "pre", state

    blocks = []
    state, fork_block = do_fork(state, spec, post_spec, fork_epoch)
    blocks.append(post_tag(fork_block))
    assert spec.is_in_inactivity_leak(state)

    transition_to_next_epoch_and_append_blocks(
        post_spec, state, post_tag, blocks, only_last_block=True)

    yield "blocks", blocks
    yield "post", state


@with_fork_metas([ForkMeta(pre_fork_name=pre, post_fork_name=post, fork_epoch=7)
                  for pre, post in ALL_PRE_POST_FORKS])
def test_transition_with_leaking_pre_fork(state, fork_epoch, spec, post_spec,
                                          pre_tag, post_tag):
    """The chain is already leaking when the fork hits (onset epoch 6 <
    fork epoch 7)."""
    yield from _run_leak_transition(
        state, fork_epoch, spec, post_spec, post_tag, leaking_pre_fork=True)


@with_fork_metas([ForkMeta(pre_fork_name=pre, post_fork_name=post, fork_epoch=6)
                  for pre, post in ALL_PRE_POST_FORKS])
def test_transition_with_leaking_at_fork(state, fork_epoch, spec, post_spec,
                                         pre_tag, post_tag):
    """Leak onset coincides with the fork epoch itself."""
    yield from _run_leak_transition(
        state, fork_epoch, spec, post_spec, post_tag, leaking_pre_fork=False)
