"""Registry lifecycle straddling the fork boundary: exits initiated
pre-fork that land after/at the fork, and activation queues crossing it
(reference suite: test/altair/transition/test_activations_and_exits.py)."""
import random

from consensus_specs_tpu.testing.context import (
    ForkMeta,
    with_fork_metas,
    with_presets,
)
from consensus_specs_tpu.testing.helpers.constants import (
    ALL_PRE_POST_FORKS,
    ALTAIR,
    MINIMAL,
)
from consensus_specs_tpu.testing.helpers.fork_transition import (
    do_fork,
    transition_to_next_epoch_and_append_blocks,
    transition_until_fork,
)
from consensus_specs_tpu.testing.helpers.random import (
    exit_random_validators,
    set_some_activations,
    set_some_new_deposits,
)

_AT_FORK_2 = [ForkMeta(pre_fork_name=pre, post_fork_name=post, fork_epoch=2)
              for pre, post in ALL_PRE_POST_FORKS]


def _exit_quarter(spec, state, exit_epoch):
    exited = exit_random_validators(
        spec, state, rng=random.Random(5566), fraction=0.25,
        exit_epoch=exit_epoch, from_epoch=spec.get_current_epoch(state))
    assert exited
    return exited


@with_fork_metas(_AT_FORK_2)
@with_presets([MINIMAL], reason="needs a registry larger than one sync committee")
def test_transition_with_one_fourth_exiting_validators_exit_post_fork(
        state, fork_epoch, spec, post_spec, pre_tag, post_tag):
    """Exits initiated pre-fork take effect only after the transition; the
    exiting validators are still active on both sides."""
    exited = _exit_quarter(spec, state, exit_epoch=10)

    transition_until_fork(spec, state, fork_epoch)
    now = spec.get_current_epoch(state)
    for index in exited:
        v = state.validators[index]
        assert not v.slashed
        assert fork_epoch < v.exit_epoch < spec.FAR_FUTURE_EPOCH
        assert spec.is_active_validator(v, now)
    assert not spec.is_in_inactivity_leak(state)

    yield "pre", state

    blocks = []
    state, fork_block = do_fork(state, spec, post_spec, fork_epoch)
    blocks.append(post_tag(fork_block))

    # still-active exiting validators remain sync-committee eligible, so
    # some (but not all) committee seats belong to them
    exiting_keys = {bytes(state.validators[i].pubkey) for i in exited}
    committee_keys = {bytes(pk) for pk in state.current_sync_committee.pubkeys}
    assert exiting_keys & committee_keys
    assert exiting_keys - committee_keys

    transition_to_next_epoch_and_append_blocks(
        post_spec, state, post_tag, blocks, only_last_block=True)

    now = post_spec.get_current_epoch(state)
    for index in exited:
        v = state.validators[index]
        assert not v.slashed
        assert post_spec.is_active_validator(v, now)
    assert not post_spec.is_in_inactivity_leak(state)

    yield "blocks", blocks
    yield "post", state


@with_fork_metas(_AT_FORK_2)
def test_transition_with_one_fourth_exiting_validators_exit_at_fork(
        state, fork_epoch, spec, post_spec, pre_tag, post_tag):
    """Exits land exactly on the fork epoch: active before, inactive after.
    The altair upgrade builds its first sync committee from active
    validators only, so none of the exited may hold a seat."""
    exited = _exit_quarter(spec, state, exit_epoch=fork_epoch)

    transition_until_fork(spec, state, fork_epoch)
    now = spec.get_current_epoch(state)
    for index in exited:
        v = state.validators[index]
        assert not v.slashed
        assert v.exit_epoch == fork_epoch
        assert spec.is_active_validator(v, now)

    yield "pre", state

    blocks = []
    state, fork_block = do_fork(state, spec, post_spec, fork_epoch)
    blocks.append(post_tag(fork_block))

    now = post_spec.get_current_epoch(state)
    for index in exited:
        v = state.validators[index]
        assert not v.slashed
        assert not post_spec.is_active_validator(v, now)
    assert not post_spec.is_in_inactivity_leak(state)

    exited_keys = {bytes(state.validators[i].pubkey) for i in exited}
    committee_keys = {bytes(pk) for pk in state.current_sync_committee.pubkeys}
    if post_spec.fork == ALTAIR:
        # the upgrade itself samples the committee from active validators
        assert not (exited_keys & committee_keys)
    else:
        # later upgrades inherit the committee assembled pre-fork
        assert exited_keys & committee_keys

    transition_to_next_epoch_and_append_blocks(
        post_spec, state, post_tag, blocks, only_last_block=True)

    yield "blocks", blocks
    yield "post", state


@with_fork_metas(_AT_FORK_2)
def test_transition_with_non_empty_activation_queue(
        state, fork_epoch, spec, post_spec, pre_tag, post_tag):
    """Pending (not yet activated) deposits ride through the upgrade."""
    transition_until_fork(spec, state, fork_epoch)
    queued = set_some_new_deposits(spec, state, rng=random.Random(5566))
    assert queued
    now = spec.get_current_epoch(state)
    for index in queued:
        assert not spec.is_active_validator(state.validators[index], now)

    yield "pre", state

    blocks = []
    state, fork_block = do_fork(state, spec, post_spec, fork_epoch)
    blocks.append(post_tag(fork_block))
    transition_to_next_epoch_and_append_blocks(
        post_spec, state, post_tag, blocks, only_last_block=True)

    yield "blocks", blocks
    yield "post", state


@with_fork_metas(_AT_FORK_2)
def test_transition_with_activation_at_fork_epoch(
        state, fork_epoch, spec, post_spec, pre_tag, post_tag):
    """Validators scheduled to activate exactly at the fork epoch must be
    active right after the upgrade."""
    transition_until_fork(spec, state, fork_epoch)
    pending = set_some_activations(
        spec, state, rng=random.Random(5566), activation_epoch=fork_epoch)
    assert pending
    now = spec.get_current_epoch(state)
    for index in pending:
        v = state.validators[index]
        assert not spec.is_active_validator(v, now)
        assert v.activation_epoch == fork_epoch

    yield "pre", state

    blocks = []
    state, fork_block = do_fork(state, spec, post_spec, fork_epoch)
    blocks.append(post_tag(fork_block))
    transition_to_next_epoch_and_append_blocks(
        post_spec, state, post_tag, blocks, only_last_block=True)

    now = post_spec.get_current_epoch(state)
    for index in pending:
        assert post_spec.is_active_validator(state.validators[index], now)

    yield "blocks", blocks
    yield "post", state
