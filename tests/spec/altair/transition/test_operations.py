"""Operations carried across the fork boundary — included in the last
pre-fork block or the fork block itself (reference suite:
test/altair/transition/test_operations.py)."""
from consensus_specs_tpu.testing.context import (
    ForkMeta,
    always_bls,
    with_fork_metas,
    with_presets,
)
from consensus_specs_tpu.testing.helpers.constants import (
    ALL_PRE_POST_FORKS,
    MINIMAL,
)
from consensus_specs_tpu.testing.helpers.fork_transition import (
    OperationType,
    run_transition_with_operation,
)

_AT_FORK_2 = [ForkMeta(pre_fork_name=pre, post_fork_name=post, fork_epoch=2)
              for pre, post in ALL_PRE_POST_FORKS]
# Voluntary exits need SHARD_COMMITTEE_PERIOD (64 epochs on minimal) of
# validator age, so those metas fork at epoch 66.
_AT_FORK_66 = [ForkMeta(pre_fork_name=pre, post_fork_name=post, fork_epoch=66)
               for pre, post in ALL_PRE_POST_FORKS]


def _run(state, fork_epoch, spec, post_spec, pre_tag, post_tag,
         operation_type, offset):
    yield from run_transition_with_operation(
        state, fork_epoch, spec, post_spec, pre_tag, post_tag,
        operation_type=operation_type,
        operation_at_slot=fork_epoch * spec.SLOTS_PER_EPOCH + offset)


@with_fork_metas(_AT_FORK_2)
@always_bls
def test_transition_with_proposer_slashing_right_after_fork(
        state, fork_epoch, spec, post_spec, pre_tag, post_tag):
    yield from _run(state, fork_epoch, spec, post_spec, pre_tag, post_tag,
                    OperationType.PROPOSER_SLASHING, 0)


@with_fork_metas(_AT_FORK_2)
@always_bls
def test_transition_with_proposer_slashing_right_before_fork(
        state, fork_epoch, spec, post_spec, pre_tag, post_tag):
    yield from _run(state, fork_epoch, spec, post_spec, pre_tag, post_tag,
                    OperationType.PROPOSER_SLASHING, -1)


@with_fork_metas(_AT_FORK_2)
@always_bls
def test_transition_with_attester_slashing_right_after_fork(
        state, fork_epoch, spec, post_spec, pre_tag, post_tag):
    yield from _run(state, fork_epoch, spec, post_spec, pre_tag, post_tag,
                    OperationType.ATTESTER_SLASHING, 0)


@with_fork_metas(_AT_FORK_2)
@always_bls
def test_transition_with_attester_slashing_right_before_fork(
        state, fork_epoch, spec, post_spec, pre_tag, post_tag):
    yield from _run(state, fork_epoch, spec, post_spec, pre_tag, post_tag,
                    OperationType.ATTESTER_SLASHING, -1)


@with_fork_metas(_AT_FORK_2)
def test_transition_with_deposit_right_after_fork(
        state, fork_epoch, spec, post_spec, pre_tag, post_tag):
    yield from _run(state, fork_epoch, spec, post_spec, pre_tag, post_tag,
                    OperationType.DEPOSIT, 0)


@with_fork_metas(_AT_FORK_2)
def test_transition_with_deposit_right_before_fork(
        state, fork_epoch, spec, post_spec, pre_tag, post_tag):
    yield from _run(state, fork_epoch, spec, post_spec, pre_tag, post_tag,
                    OperationType.DEPOSIT, -1)


@with_fork_metas(_AT_FORK_66)
@with_presets([MINIMAL], reason="too slow")
def test_transition_with_voluntary_exit_right_after_fork(
        state, fork_epoch, spec, post_spec, pre_tag, post_tag):
    # age validator 0 past the shard committee period first
    state.slot = spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    yield from _run(state, fork_epoch, spec, post_spec, pre_tag, post_tag,
                    OperationType.VOLUNTARY_EXIT, 0)


@with_fork_metas(_AT_FORK_66)
@with_presets([MINIMAL], reason="too slow")
def test_transition_with_voluntary_exit_right_before_fork(
        state, fork_epoch, spec, post_spec, pre_tag, post_tag):
    state.slot = spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    yield from _run(state, fork_epoch, spec, post_spec, pre_tag, post_tag,
                    OperationType.VOLUNTARY_EXIT, -1)
