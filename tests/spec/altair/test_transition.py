"""Cross-fork transition scenarios using the fork_transition machinery
(reference capability: test/altair/transition/test_transition.py over
helpers/fork_transition.py): blocks before, at, and after the fork
boundary, including skipped-slot gaps."""
from consensus_specs_tpu.testing.context import (
    spec_test,
    with_phases,
    with_state,
)
from consensus_specs_tpu.testing.helpers.constants import ALTAIR, PHASE0
from consensus_specs_tpu.testing.helpers.fork_transition import (
    do_fork,
    skip_slots,
    state_transition_across_slots,
    transition_to_next_epoch_and_append_blocks,
)
from consensus_specs_tpu.testing.utils import with_meta_tags

FORK_EPOCH = 2
META = {"fork": ALTAIR, "fork_epoch": FORK_EPOCH}


def _pre_tag(b):
    return b


def _post_tag(b):
    return b


@with_phases(phases=[PHASE0], other_phases=[ALTAIR])
@spec_test
@with_state
@with_meta_tags(META)
def test_normal_transition(spec, phases, state):
    """Blocks every slot up to, across, and past the fork boundary."""
    post_spec = phases[ALTAIR]
    yield "pre", state

    blocks = []
    target = FORK_EPOCH * spec.SLOTS_PER_EPOCH - 1
    blocks.extend(
        _pre_tag(b) for b in state_transition_across_slots(spec, state, target)
    )
    assert spec.compute_epoch_at_slot(state.slot + 1) == FORK_EPOCH

    state, fork_block = do_fork(state, spec, post_spec, FORK_EPOCH)
    blocks.append(_post_tag(fork_block))

    transition_to_next_epoch_and_append_blocks(post_spec, state, _post_tag, blocks)

    yield "blocks", blocks
    yield "post", state
    assert state.fork.current_version == post_spec.config.ALTAIR_FORK_VERSION
    # participation flags replaced pending attestations
    assert len(state.previous_epoch_participation) == len(state.validators)


@with_phases(phases=[PHASE0], other_phases=[ALTAIR])
@spec_test
@with_state
@with_meta_tags(META)
def test_transition_with_leading_blocks(spec, phases, state):
    """Pre-fork epoch full of blocks, then the fork."""
    post_spec = phases[ALTAIR]
    yield "pre", state

    blocks = []
    target = FORK_EPOCH * spec.SLOTS_PER_EPOCH - 1
    blocks.extend(
        _pre_tag(b)
        for b in state_transition_across_slots(spec, state, target)
    )
    state, fork_block = do_fork(state, spec, post_spec, FORK_EPOCH)
    blocks.append(_post_tag(fork_block))

    yield "blocks", blocks
    yield "post", state


@with_phases(phases=[PHASE0], other_phases=[ALTAIR])
@spec_test
@with_state
@with_meta_tags(META)
def test_transition_with_skipped_slots_around_fork(spec, phases, state):
    post_spec = phases[ALTAIR]
    yield "pre", state

    blocks = []
    target = FORK_EPOCH * spec.SLOTS_PER_EPOCH - 1
    # skip the last two pre-fork proposals
    blocks.extend(
        _pre_tag(b)
        for b in state_transition_across_slots(
            spec, state, target, block_filter=skip_slots(target - 1, target))
    )
    state, fork_block = do_fork(state, spec, post_spec, FORK_EPOCH)
    blocks.append(_post_tag(fork_block))
    transition_to_next_epoch_and_append_blocks(
        post_spec, state, _post_tag, blocks, only_last_block=True)

    yield "blocks", blocks
    yield "post", state
    assert state.fork.current_version == post_spec.config.ALTAIR_FORK_VERSION
