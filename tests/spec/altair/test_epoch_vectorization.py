"""Differential tests: altair+ vectorized epoch substitutions vs their
sequential ``__wrapped__`` originals — flag rewards (incl. leak and the
per-pair floor-at-zero order), inactivity scores, participation rotation.
Scenarios force mixed participation flags, slashed validators, and
nonzero inactivity scores."""
import random

from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_altair_and_later as with_altair_family,
)
from consensus_specs_tpu.testing.helpers.state import next_epoch


def unwrap(fn):
    while hasattr(fn, "__wrapped__"):
        fn = fn.__wrapped__
    return fn


def _mixed_participation_state(spec, state, seed=4242):
    """Scatter participation flags, slashes, scores over a mid-chain state."""
    rng = random.Random(seed)
    next_epoch(spec, state)
    next_epoch(spec, state)
    n = len(state.validators)
    for i in range(n):
        flags = 0
        for flag in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
            if rng.random() < 0.6:
                flags |= 1 << flag
        state.previous_epoch_participation[i] = flags
        state.current_epoch_participation[i] = rng.randrange(
            1 << len(spec.PARTICIPATION_FLAG_WEIGHTS))
        state.inactivity_scores[i] = rng.randrange(0, 100)
    for i in rng.sample(range(n), max(1, n // 16)):
        state.validators[i].slashed = True
    return state


def _assert_same_mutation(spec, state, name):
    vec_state = state.copy()
    seq_state = state.copy()
    getattr(spec, name)(vec_state)
    unwrap(getattr(spec, name))(seq_state)
    assert vec_state.hash_tree_root() == seq_state.hash_tree_root(), name


@with_altair_family
@spec_state_test
def test_rewards_and_penalties_differential(spec, state):
    _mixed_participation_state(spec, state)
    _assert_same_mutation(spec, state, "process_rewards_and_penalties")
    yield from ()


@with_altair_family
@spec_state_test
def test_rewards_and_penalties_differential_in_leak(spec, state):
    _mixed_participation_state(spec, state)
    for _ in range(int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 2):
        next_epoch(spec, state)
    assert spec.is_in_inactivity_leak(state)
    # scores past the 2^27 int64-exactness guard force the big-int penalty
    # fallback while staying inside the spec's uint64 numerator range
    for i in range(0, len(state.validators), 3):
        state.inactivity_scores[i] = 2**28 + 12345
    _assert_same_mutation(spec, state, "process_rewards_and_penalties")
    yield from ()


@with_altair_family
@spec_state_test
def test_justification_differential(spec, state):
    _mixed_participation_state(spec, state)
    _assert_same_mutation(spec, state, "process_justification_and_finalization")
    yield from ()


@with_altair_family
@spec_state_test
def test_inactivity_updates_differential(spec, state):
    _mixed_participation_state(spec, state)
    _assert_same_mutation(spec, state, "process_inactivity_updates")
    yield from ()


@with_altair_family
@spec_state_test
def test_inactivity_updates_differential_in_leak(spec, state):
    _mixed_participation_state(spec, state)
    for _ in range(int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 2):
        next_epoch(spec, state)
    assert spec.is_in_inactivity_leak(state)
    _assert_same_mutation(spec, state, "process_inactivity_updates")
    yield from ()


@with_altair_family
@spec_state_test
def test_participation_flag_rotation_differential(spec, state):
    _mixed_participation_state(spec, state)
    _assert_same_mutation(spec, state, "process_participation_flag_updates")
    yield from ()


@with_altair_family
@spec_state_test
def test_full_epoch_differential(spec, state):
    """Whole process_epoch through both pipelines on a flag-scattered
    state: every altair substitution at once."""
    _mixed_participation_state(spec, state)
    vec_state = state.copy()
    seq_state = state.copy()
    spec.process_epoch(vec_state)
    g = spec.__dict__
    names = (
        "process_justification_and_finalization",
        "process_rewards_and_penalties", "process_inactivity_updates",
        "process_participation_flag_updates", "process_registry_updates",
        "process_slashings", "process_effective_balance_updates",
    )
    saved = {k: g[k] for k in names}
    try:
        for k in names:
            g[k] = unwrap(saved[k])
        spec.process_epoch(seq_state)
    finally:
        g.update(saved)
    assert vec_state.hash_tree_root() == seq_state.hash_tree_root()
    yield from ()
