"""Randomized sync-aggregate scenarios (reference capability:
test/altair/block_processing/sync_aggregate/test_process_sync_aggregate_random.py):
seeded participation patterns through the real process_sync_aggregate."""
import random

from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_altair_and_later,
)
from consensus_specs_tpu.testing.helpers.sync_committee import (
    compute_committee_indices,
    run_successful_sync_committee_test,
)


def _run_random_participation(spec, state, rng, fraction):
    """Reuses the shared runner, which validates every participant's
    reward and every absentee's penalty."""
    committee_indices = compute_committee_indices(spec, state)
    size = len(committee_indices)
    participate = set(rng.sample(range(size), int(size * fraction)))
    bits = [i in participate for i in range(size)]
    yield from run_successful_sync_committee_test(
        spec, state, committee_indices, bits)


@with_altair_and_later
@spec_state_test
def test_random_participation_three_quarters(spec, state):
    yield from _run_random_participation(spec, state, random.Random(41), 0.75)


@with_altair_and_later
@spec_state_test
def test_random_participation_half(spec, state):
    yield from _run_random_participation(spec, state, random.Random(42), 0.5)


@with_altair_and_later
@spec_state_test
def test_random_participation_low(spec, state):
    yield from _run_random_participation(spec, state, random.Random(43), 0.25)


@with_altair_and_later
@spec_state_test
def test_empty_participation(spec, state):
    yield from _run_random_participation(spec, state, random.Random(44), 0.0)
