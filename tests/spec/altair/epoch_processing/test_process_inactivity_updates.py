"""process_inactivity_updates suite (spec: altair/beacon-chain.md
process_inactivity_updates; reference suite:
test/altair/epoch_processing/test_process_inactivity_updates.py)."""
from random import Random

from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.epoch_processing import (
    run_epoch_processing_with,
)
from consensus_specs_tpu.testing.helpers.rewards import leaking
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    set_empty_participation,
    set_full_participation,
)

ALTAIR_AND_LATER = ["altair", "bellatrix", "capella"]


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_genesis_epoch_no_op(spec, state):
    assert spec.get_current_epoch(state) == spec.GENESIS_EPOCH
    pre = [int(x) for x in state.inactivity_scores]
    yield from run_epoch_processing_with(spec, state, "process_inactivity_updates")
    assert [int(x) for x in state.inactivity_scores] == pre


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_all_zero_scores_full_participation(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    set_full_participation(spec, state)
    yield from run_epoch_processing_with(spec, state, "process_inactivity_updates")
    assert all(int(x) == 0 for x in state.inactivity_scores)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_leak_increments_nonparticipants(spec, state):
    set_empty_participation(spec, state)
    pre = [int(x) for x in state.inactivity_scores]
    yield from run_epoch_processing_with(spec, state, "process_inactivity_updates")
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    for index in [int(i) for i in spec.get_eligible_validator_indices(state)]:
        assert int(state.inactivity_scores[index]) == pre[index] + bias


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_leak_participants_decrement_by_one(spec, state):
    """During a leak, target-participating validators shed exactly 1
    (the unconditional ``-= min(1, score)``); the recovery-rate decrement
    is leak-gated and must NOT apply."""
    set_full_participation(spec, state)
    for index in range(len(state.validators)):
        state.inactivity_scores[index] = 10
    yield from run_epoch_processing_with(spec, state, "process_inactivity_updates")
    for index in [int(i) for i in spec.get_eligible_validator_indices(state)]:
        assert int(state.inactivity_scores[index]) == 9


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_recovery_decrements_when_not_leaking(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    assert not spec.is_in_inactivity_leak(state)
    set_full_participation(spec, state)
    rng = Random(3030)
    pre = []
    for index in range(len(state.validators)):
        score = rng.randrange(0, 30)
        state.inactivity_scores[index] = score
        pre.append(score)
    yield from run_epoch_processing_with(spec, state, "process_inactivity_updates")
    rate = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)
    for index in [int(i) for i in spec.get_eligible_validator_indices(state)]:
        # participant: -= min(1, s), then leak-free recovery -= min(rate, s)
        s = pre[index]
        s -= min(1, s)
        s -= min(rate, s)
        assert int(state.inactivity_scores[index]) == s


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_nonparticipant_bias_then_floor(spec, state):
    """Not leaking: non-participants gain bias then recover by the rate
    in the same pass (net effect per the spec's two-step update)."""
    next_epoch(spec, state)
    next_epoch(spec, state)
    assert not spec.is_in_inactivity_leak(state)
    set_empty_participation(spec, state)
    for index in range(len(state.validators)):
        state.inactivity_scores[index] = 7
    yield from run_epoch_processing_with(spec, state, "process_inactivity_updates")
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    rate = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)
    expected = max(7 + bias - rate, 0)
    for index in [int(i) for i in spec.get_eligible_validator_indices(state)]:
        assert int(state.inactivity_scores[index]) == expected
