"""process_inactivity_updates suite (spec: altair/beacon-chain.md
process_inactivity_updates; reference suite:
test/altair/epoch_processing/test_process_inactivity_updates.py)."""
from random import Random

from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.epoch_processing import (
    run_epoch_processing_with,
)
from consensus_specs_tpu.testing.helpers.rewards import leaking
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    set_empty_participation,
    set_full_participation,
)

ALTAIR_AND_LATER = ["altair", "bellatrix", "capella"]


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_genesis_epoch_no_op(spec, state):
    assert spec.get_current_epoch(state) == spec.GENESIS_EPOCH
    pre = [int(x) for x in state.inactivity_scores]
    yield from run_epoch_processing_with(spec, state, "process_inactivity_updates")
    assert [int(x) for x in state.inactivity_scores] == pre


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_all_zero_scores_full_participation(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    set_full_participation(spec, state)
    yield from run_epoch_processing_with(spec, state, "process_inactivity_updates")
    assert all(int(x) == 0 for x in state.inactivity_scores)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_leak_increments_nonparticipants(spec, state):
    set_empty_participation(spec, state)
    pre = [int(x) for x in state.inactivity_scores]
    yield from run_epoch_processing_with(spec, state, "process_inactivity_updates")
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    for index in [int(i) for i in spec.get_eligible_validator_indices(state)]:
        assert int(state.inactivity_scores[index]) == pre[index] + bias


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_leak_participants_decrement_by_one(spec, state):
    """During a leak, target-participating validators shed exactly 1
    (the unconditional ``-= min(1, score)``); the recovery-rate decrement
    is leak-gated and must NOT apply."""
    set_full_participation(spec, state)
    for index in range(len(state.validators)):
        state.inactivity_scores[index] = 10
    yield from run_epoch_processing_with(spec, state, "process_inactivity_updates")
    for index in [int(i) for i in spec.get_eligible_validator_indices(state)]:
        assert int(state.inactivity_scores[index]) == 9


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_recovery_decrements_when_not_leaking(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    assert not spec.is_in_inactivity_leak(state)
    set_full_participation(spec, state)
    rng = Random(3030)
    pre = []
    for index in range(len(state.validators)):
        score = rng.randrange(0, 30)
        state.inactivity_scores[index] = score
        pre.append(score)
    yield from run_epoch_processing_with(spec, state, "process_inactivity_updates")
    rate = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)
    for index in [int(i) for i in spec.get_eligible_validator_indices(state)]:
        # participant: -= min(1, s), then leak-free recovery -= min(rate, s)
        s = pre[index]
        s -= min(1, s)
        s -= min(rate, s)
        assert int(state.inactivity_scores[index]) == s


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_nonparticipant_bias_then_floor(spec, state):
    """Not leaking: non-participants gain bias then recover by the rate
    in the same pass (net effect per the spec's two-step update)."""
    next_epoch(spec, state)
    next_epoch(spec, state)
    assert not spec.is_in_inactivity_leak(state)
    set_empty_participation(spec, state)
    for index in range(len(state.validators)):
        state.inactivity_scores[index] = 7
    yield from run_epoch_processing_with(spec, state, "process_inactivity_updates")
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    rate = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)
    expected = max(7 + bias - rate, 0)
    for index in [int(i) for i in spec.get_eligible_validator_indices(state)]:
        assert int(state.inactivity_scores[index]) == expected


# -- scores x participation x leak matrix ------------------------------------
#
# Shared runner (reference capability: the run_inactivity_scores_test matrix
# of test_process_inactivity_updates.py): seed the scores and participation
# shape, run the sub-transition, and verify the spec formula per validator.


def _seed_scores(spec, state, rng=None):
    for index in range(len(state.validators)):
        state.inactivity_scores[index] = (
            0 if rng is None else rng.randint(0, 100))


def _expected_score(spec, state, index, pre_score, participated_target):
    score = pre_score
    if participated_target:
        score -= min(1, score)
    else:
        score += int(spec.config.INACTIVITY_SCORE_BIAS)
    if not spec.is_in_inactivity_leak(state):
        score -= min(int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE), score)
    return score


def _run_inactivity_matrix_case(spec, state, participation_fn, rng=None):
    next_epoch(spec, state)
    _seed_scores(spec, state, rng)
    participation_fn(spec, state)
    pre_scores = [int(x) for x in state.inactivity_scores]
    eligible = {int(i) for i in spec.get_eligible_validator_indices(state)}
    on_target = {int(i) for i in spec.get_unslashed_participating_indices(
        state, int(spec.TIMELY_TARGET_FLAG_INDEX), spec.get_previous_epoch(state))}

    yield from run_epoch_processing_with(spec, state, "process_inactivity_updates")

    for index in range(len(state.validators)):
        if index not in eligible:
            assert int(state.inactivity_scores[index]) == pre_scores[index]
        else:
            assert int(state.inactivity_scores[index]) == _expected_score(
                spec, state, index, pre_scores[index], index in on_target)


def _random_participation(spec, state):
    from consensus_specs_tpu.testing.helpers.random import (
        randomize_attestation_participation,
    )
    randomize_attestation_participation(spec, state, rng=Random(5522))


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_all_zero_inactivity_scores_empty_participation(spec, state):
    yield from _run_inactivity_matrix_case(spec, state, set_empty_participation)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_all_zero_inactivity_scores_empty_participation_leaking(spec, state):
    yield from _run_inactivity_matrix_case(spec, state, set_empty_participation)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_all_zero_inactivity_scores_random_participation(spec, state):
    yield from _run_inactivity_matrix_case(spec, state, _random_participation)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_all_zero_inactivity_scores_random_participation_leaking(spec, state):
    yield from _run_inactivity_matrix_case(spec, state, _random_participation)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_random_inactivity_scores_empty_participation(spec, state):
    yield from _run_inactivity_matrix_case(
        spec, state, set_empty_participation, rng=Random(10101))


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_random_inactivity_scores_empty_participation_leaking(spec, state):
    yield from _run_inactivity_matrix_case(
        spec, state, set_empty_participation, rng=Random(10102))


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_random_inactivity_scores_random_participation(spec, state):
    yield from _run_inactivity_matrix_case(
        spec, state, _random_participation, rng=Random(10103))


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_random_inactivity_scores_random_participation_leaking(spec, state):
    yield from _run_inactivity_matrix_case(
        spec, state, _random_participation, rng=Random(10104))


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_random_inactivity_scores_full_participation(spec, state):
    yield from _run_inactivity_matrix_case(
        spec, state, set_full_participation, rng=Random(10105))


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_random_inactivity_scores_full_participation_leaking(spec, state):
    yield from _run_inactivity_matrix_case(
        spec, state, set_full_participation, rng=Random(10106))


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_some_slashed_zero_scores_full_participation(spec, state):
    from consensus_specs_tpu.testing.helpers.random import slash_random_validators

    slash_random_validators(spec, state, rng=Random(10107), fraction=0.25)
    yield from _run_inactivity_matrix_case(spec, state, set_full_participation)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_some_slashed_zero_scores_full_participation_leaking(spec, state):
    from consensus_specs_tpu.testing.helpers.random import slash_random_validators

    slash_random_validators(spec, state, rng=Random(10108), fraction=0.25)
    yield from _run_inactivity_matrix_case(spec, state, set_full_participation)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_some_exited_full_random_leaking(spec, state):
    from consensus_specs_tpu.testing.helpers.random import exit_random_validators

    exit_random_validators(spec, state, rng=Random(10109), fraction=0.25,
                           exit_epoch=spec.get_current_epoch(state))
    yield from _run_inactivity_matrix_case(
        spec, state, _random_participation, rng=Random(10110))
