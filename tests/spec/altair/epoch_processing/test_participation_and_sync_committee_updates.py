"""process_participation_flag_updates + process_sync_committee_updates
suites (spec: altair/beacon-chain.md:570-583; reference suites:
test/altair/epoch_processing/test_process_participation_flag_updates.py,
test_process_sync_committee_updates.py)."""
from random import Random

from consensus_specs_tpu.testing.context import (
    always_bls,
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.epoch_processing import (
    run_epoch_processing_with,
)
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    transition_to,
)

ALTAIR_AND_LATER = ["altair", "bellatrix", "capella"]


def _randomize_flags(spec, state, rng):
    for index in range(len(state.validators)):
        state.current_epoch_participation[index] = spec.ParticipationFlags(
            rng.randrange(0, 8))
        state.previous_epoch_participation[index] = spec.ParticipationFlags(
            rng.randrange(0, 8))


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_participation_flag_rotation(spec, state):
    next_epoch(spec, state)
    _randomize_flags(spec, state, Random(4040))
    current = [int(x) for x in state.current_epoch_participation]
    yield from run_epoch_processing_with(
        spec, state, "process_participation_flag_updates")
    assert [int(x) for x in state.previous_epoch_participation] == current
    assert all(int(x) == 0 for x in state.current_epoch_participation)
    assert len(state.current_epoch_participation) == len(state.validators)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@always_bls
def test_sync_committee_rotation_at_period_boundary(spec, state):
    # advance to the final epoch of a sync-committee period
    period_epochs = int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)
    transition_to(spec, state, (period_epochs - 1) * int(spec.SLOTS_PER_EPOCH))
    next_ = state.next_sync_committee.copy()
    yield from run_epoch_processing_with(
        spec, state, "process_sync_committee_updates")
    # boundary crossed: current <- old next; next recomputed for the new
    # period (state is unchanged since the handler, so recomputing now
    # must reproduce exactly what it stored)
    assert bytes(state.current_sync_committee.hash_tree_root()) == \
        bytes(next_.hash_tree_root())
    assert bytes(state.next_sync_committee.hash_tree_root()) == \
        bytes(spec.get_next_sync_committee(state).hash_tree_root())


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_sync_committee_no_rotation_mid_period(spec, state):
    next_epoch(spec, state)
    assert (int(spec.get_current_epoch(state)) + 1) % \
        int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD) != 0
    current = state.current_sync_committee.copy()
    next_ = state.next_sync_committee.copy()
    yield from run_epoch_processing_with(
        spec, state, "process_sync_committee_updates")
    assert bytes(state.current_sync_committee.hash_tree_root()) == \
        bytes(current.hash_tree_root())
    assert bytes(state.next_sync_committee.hash_tree_root()) == \
        bytes(next_.hash_tree_root())
