"""Altair randomized block scenarios (reference capability:
test/altair/random/): seeded walks with random attestations, proposer
slashings, and partially-participating signed sync aggregates."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.random_scenarios import run_random_scenario


def _make(seed, with_leak=False, stages=6):
    @spec_state_test
    def case(spec, state):
        yield from run_random_scenario(
            spec, state, seed=seed, stages=stages, with_leak=with_leak)

    return with_phases(["altair"])(case)


test_random_0 = _make(110)
test_random_1 = _make(211)
test_random_2 = _make(312)
test_random_leak_0 = _make(514, with_leak=True, stages=4)
