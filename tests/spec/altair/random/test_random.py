"""Altair randomized block scenarios (reference capability:
test/altair/random/): seeded walks with random attestations, proposer
slashings, and partially-participating signed sync aggregates."""
from functools import partial

from consensus_specs_tpu.testing.random_scenarios import make_random_case

_make = partial(make_random_case, "altair")


test_random_0 = _make(110)
test_random_1 = _make(211)
test_random_2 = _make(312)
test_random_leak_0 = _make(514, with_leak=True, stages=4)
