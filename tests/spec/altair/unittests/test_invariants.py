"""Altair unittests: incentivization-weight and helper invariants
(reference suite: test/altair/unittests/test_config_invariants.py,
test_helpers.py)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)

ALTAIR_AND_LATER = ["altair", "bellatrix", "capella"]


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_weight_denominator(spec, state):
    yield "meta", {"bls_setting": 2}
    assert (
        int(spec.TIMELY_HEAD_WEIGHT)
        + int(spec.TIMELY_SOURCE_WEIGHT)
        + int(spec.TIMELY_TARGET_WEIGHT)
        + int(spec.SYNC_REWARD_WEIGHT)
        + int(spec.PROPOSER_WEIGHT)
    ) == int(spec.WEIGHT_DENOMINATOR)
    assert [int(w) for w in spec.PARTICIPATION_FLAG_WEIGHTS] == [
        int(spec.TIMELY_SOURCE_WEIGHT),
        int(spec.TIMELY_TARGET_WEIGHT),
        int(spec.TIMELY_HEAD_WEIGHT),
    ]


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_flag_indices_distinct(spec, state):
    yield "meta", {"bls_setting": 2}
    indices = [
        int(spec.TIMELY_SOURCE_FLAG_INDEX),
        int(spec.TIMELY_TARGET_FLAG_INDEX),
        int(spec.TIMELY_HEAD_FLAG_INDEX),
    ]
    assert sorted(indices) == [0, 1, 2]


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_add_has_flag_roundtrip(spec, state):
    yield "meta", {"bls_setting": 2}
    for flag_index in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
        flags = spec.add_flag(spec.ParticipationFlags(0), flag_index)
        for other in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
            assert spec.has_flag(flags, other) == (other == flag_index)
    # all flags set
    flags = spec.ParticipationFlags(0)
    for flag_index in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
        flags = spec.add_flag(flags, flag_index)
    assert all(
        spec.has_flag(flags, i)
        for i in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)))


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_next_sync_committee_structure(spec, state):
    yield "meta", {"bls_setting": 2}
    committee = spec.get_next_sync_committee(state)
    assert len(committee.pubkeys) == int(spec.SYNC_COMMITTEE_SIZE)
    # aggregate pubkey matches eth_aggregate_pubkeys over the members
    # (pinned separately under BLS-on tests; structural check here)
    indices = spec.get_next_sync_committee_indices(state)
    assert len(indices) == int(spec.SYNC_COMMITTEE_SIZE)
    active = set(int(i) for i in spec.get_active_validator_indices(
        state, spec.get_current_epoch(state)))
    assert all(int(i) in active for i in indices)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_sync_subcommittee_pubkeys_partition(spec, state):
    yield "meta", {"bls_setting": 2}
    size = int(spec.SYNC_COMMITTEE_SIZE)
    count = int(spec.SYNC_COMMITTEE_SUBNET_COUNT)
    seen = []
    for subcommittee_index in range(count):
        pubkeys = spec.get_sync_subcommittee_pubkeys(state, subcommittee_index)
        assert len(pubkeys) == size // count
        seen.extend(bytes(pk) for pk in pubkeys)
    assert seen == [bytes(pk) for pk in state.current_sync_committee.pubkeys]
