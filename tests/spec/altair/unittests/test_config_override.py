"""Config-override spec rebuild (reference suite:
test/altair/unittests/test_config_override.py): a per-test config must
produce a fresh spec module whose containers and genesis state reflect
the overridden fork versions."""
from consensus_specs_tpu.testing.context import (
    spec_configured_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.constants import ALTAIR


@with_phases([ALTAIR])
@spec_configured_state_test({
    "GENESIS_FORK_VERSION": "0x12345678",
    "ALTAIR_FORK_VERSION": "0x11111111",
    "ALTAIR_FORK_EPOCH": 4,
})
def test_config_override(spec, state):
    assert spec.config.ALTAIR_FORK_EPOCH == 4
    assert spec.config.GENESIS_FORK_VERSION != spec.Version(b"\x00" * 4)
    assert spec.config.GENESIS_FORK_VERSION == spec.Version(bytes.fromhex("12345678"))
    assert spec.config.ALTAIR_FORK_VERSION == spec.Version(bytes.fromhex("11111111"))
    # the mock-genesis state is built against the overridden config
    assert state.fork.current_version == spec.Version(bytes.fromhex("11111111"))
    yield from ()
