"""Altair validator-duty unittests (reference suite:
test/altair/unittests/validator/test_validator.py): sync-committee
assignment, subnet computation, selection proofs, aggregator selection,
and contribution-and-proof construction."""
from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.testing.context import (
    always_bls,
    spec_state_test,
    with_altair_and_later,
)
from consensus_specs_tpu.testing.helpers.keys import pubkey_to_privkey


@with_altair_and_later
@spec_state_test
def test_sync_committee_assignment_matches_membership(spec, state):
    yield "meta", {"bls_setting": 2}
    epoch = spec.get_current_epoch(state)
    members = {bytes(pk) for pk in state.current_sync_committee.pubkeys}
    for index in range(len(state.validators)):
        assigned = spec.is_assigned_to_sync_committee(
            state, epoch, spec.ValidatorIndex(index))
        assert assigned == (bytes(state.validators[index].pubkey) in members)


@with_altair_and_later
@spec_state_test
def test_subnets_cover_all_member_positions(spec, state):
    yield "meta", {"bls_setting": 2}
    size = int(spec.SYNC_COMMITTEE_SIZE)
    per_subnet = size // int(spec.SYNC_COMMITTEE_SUBNET_COUNT)
    pubkeys = [bytes(pk) for pk in state.current_sync_committee.pubkeys]
    for index in range(len(state.validators)):
        subnets = spec.compute_subnets_for_sync_committee(
            state, spec.ValidatorIndex(index))
        expected = {
            position // per_subnet
            for position, pk in enumerate(pubkeys)
            if pk == bytes(state.validators[index].pubkey)
        }
        assert {int(s) for s in subnets} == expected


@with_altair_and_later
@spec_state_test
@always_bls
def test_selection_proof_and_aggregator_determinism(spec, state):
    slot = state.slot
    subcommittee_index = 0
    member_pubkey = bytes(state.current_sync_committee.pubkeys[0])
    privkey = pubkey_to_privkey[member_pubkey]
    proof = spec.get_sync_committee_selection_proof(
        state, slot, subcommittee_index, privkey)
    # deterministic: same inputs, same proof, same aggregator decision
    proof2 = spec.get_sync_committee_selection_proof(
        state, slot, subcommittee_index, privkey)
    assert bytes(proof) == bytes(proof2)
    # aggregator selection is a pure function of the proof bytes; exercise
    # it and pin the expected minimal-preset behavior (modulo 1: every
    # member aggregates).  Mainnet's 1-in-8 draw is probabilistic, so no
    # existence sweep — that would flake (~(7/8)^n) on large presets.
    decision = spec.is_sync_committee_aggregator(proof)
    modulo = max(1, int(spec.SYNC_COMMITTEE_SIZE)
                 // int(spec.SYNC_COMMITTEE_SUBNET_COUNT)
                 // int(spec.TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE))
    if modulo == 1:
        assert decision


@with_altair_and_later
@spec_state_test
@always_bls
def test_contribution_and_proof_roundtrip(spec, state):
    subcommittee_index = 0
    member_pubkey = bytes(state.current_sync_committee.pubkeys[0])
    privkey = pubkey_to_privkey[member_pubkey]
    contribution = spec.SyncCommitteeContribution(
        slot=state.slot,
        beacon_block_root=(
            spec.get_block_root_at_slot(state, state.slot - 1)
            if int(state.slot) > 0 else spec.Root()),
        subcommittee_index=subcommittee_index,
        aggregation_bits=[True] + [False] * (
            int(spec.SYNC_COMMITTEE_SIZE)
            // int(spec.SYNC_COMMITTEE_SUBNET_COUNT) - 1),
        signature=spec.BLSSignature(b"\xc0" + b"\x00" * 95),
    )
    # the aggregator is whichever validator owns the committee's first slot
    member_index = next(
        i for i, v in enumerate(state.validators)
        if bytes(v.pubkey) == member_pubkey)
    aggregator_index = spec.ValidatorIndex(member_index)
    cap = spec.get_contribution_and_proof(
        state, aggregator_index, contribution, privkey)
    assert int(cap.aggregator_index) == member_index
    assert bytes(cap.contribution.hash_tree_root()) == \
        bytes(contribution.hash_tree_root())
    # the embedded selection proof verifies under the aggregator's key
    domain = spec.get_domain(
        state, spec.DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
        spec.compute_epoch_at_slot(contribution.slot))
    signing_root = spec.compute_signing_root(
        spec.SyncAggregatorSelectionData(
            slot=contribution.slot,
            subcommittee_index=subcommittee_index,
        ), domain)
    assert bls.Verify(member_pubkey, signing_root, cap.selection_proof)
    # and the signature over the envelope verifies
    sig = spec.get_contribution_and_proof_signature(state, cap, privkey)
    domain = spec.get_domain(state, spec.DOMAIN_CONTRIBUTION_AND_PROOF,
                             spec.compute_epoch_at_slot(contribution.slot))
    signing_root = spec.compute_signing_root(cap, domain)
    assert bls.Verify(member_pubkey, signing_root, sig)
