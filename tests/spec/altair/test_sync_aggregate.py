"""Sync aggregate processing tests (reference:
test/altair/block_processing/sync_aggregate/test_process_sync_aggregate.py,
representative subset)."""
import random

from consensus_specs_tpu.testing.context import (
    always_bls,
    spec_state_test,
    with_altair_and_later,
)
from consensus_specs_tpu.testing.helpers.block import build_empty_block_for_next_slot
from consensus_specs_tpu.testing.helpers.state import transition_to
from consensus_specs_tpu.testing.helpers.sync_committee import (
    compute_aggregate_sync_committee_signature,
    compute_committee_indices,
    run_successful_sync_committee_test,
    run_sync_committee_processing,
)


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_bad_domain(spec, state):
    committee_indices = compute_committee_indices(spec, state)

    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec,
            state,
            block.slot - 1,
            committee_indices,  # full committee signs
            block_root=block.parent_root,
            domain_type=spec.DOMAIN_BEACON_ATTESTER,  # Incorrect domain
        ),
    )
    yield from run_sync_committee_processing(spec, state, block, expect_exception=True)


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_missing_participant(spec, state):
    committee_indices = compute_committee_indices(spec, state)
    rng = random.Random(2020)
    random_participant = rng.choice(committee_indices)

    block = build_empty_block_for_next_slot(spec, state)
    # Exclude one participant whose signature was included.
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[index != random_participant for index in committee_indices],
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec,
            state,
            block.slot - 1,
            committee_indices,  # full committee signs
            block_root=block.parent_root,
        ),
    )
    yield from run_sync_committee_processing(spec, state, block, expect_exception=True)


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_extra_participant(spec, state):
    committee_indices = compute_committee_indices(spec, state)
    rng = random.Random(3030)
    random_participant = rng.choice(committee_indices)

    block = build_empty_block_for_next_slot(spec, state)
    # Exclude one signature even though the block claims the entire committee participated.
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec,
            state,
            block.slot - 1,
            [index for index in committee_indices if index != random_participant],
            block_root=block.parent_root,
        ),
    )
    yield from run_sync_committee_processing(spec, state, block, expect_exception=True)


@with_altair_and_later
@spec_state_test
@always_bls
def test_sync_committee_rewards_empty_participants(spec, state):
    committee_indices = compute_committee_indices(spec, state)
    committee_bits = [False] * len(committee_indices)

    yield from run_successful_sync_committee_test(spec, state, committee_indices, committee_bits)


@with_altair_and_later
@spec_state_test
@always_bls
def test_sync_committee_rewards_not_full_participants(spec, state):
    committee_indices = compute_committee_indices(spec, state)
    rng = random.Random(1010)
    committee_bits = [rng.choice([True, False]) for _ in committee_indices]

    yield from run_successful_sync_committee_test(spec, state, committee_indices, committee_bits)


@with_altair_and_later
@spec_state_test
@always_bls
def test_sync_committee_rewards_nonduplicate_committee(spec, state):
    committee_indices = compute_committee_indices(spec, state)
    committee_bits = [True] * len(committee_indices)

    yield from run_successful_sync_committee_test(spec, state, committee_indices, committee_bits)


@with_altair_and_later
@spec_state_test
@always_bls
def test_proposer_in_committee_without_participation(spec, state):
    state.slot = state.slot + 1  # skip one slot to roll proposers

    # find a slot where the proposer is in the sync committee
    committee_indices = compute_committee_indices(spec, state)
    for _ in range(spec.SLOTS_PER_EPOCH):
        block = build_empty_block_for_next_slot(spec, state)
        proposer_index = block.proposer_index
        if proposer_index in committee_indices:
            committee_bits = [index != proposer_index for index in committee_indices]
            participants = [index for index in committee_indices if index != proposer_index]
            block.body.sync_aggregate = spec.SyncAggregate(
                sync_committee_bits=committee_bits,
                sync_committee_signature=compute_aggregate_sync_committee_signature(
                    spec, state, block.slot - 1, participants, block_root=block.parent_root,
                ),
            )
            yield from run_sync_committee_processing(spec, state, block)
            return
        else:
            transition_to(spec, state, state.slot + 1)
    raise AssertionError("no proposer in committee found within an epoch")
