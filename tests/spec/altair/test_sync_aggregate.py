"""Sync aggregate processing tests (reference:
test/altair/block_processing/sync_aggregate/test_process_sync_aggregate.py,
representative subset)."""
import random

from consensus_specs_tpu.testing.context import (
    always_bls,
    spec_state_test,
    with_altair_and_later,
)
from consensus_specs_tpu.testing.helpers.block import build_empty_block_for_next_slot
from consensus_specs_tpu.testing.helpers.state import transition_to
from consensus_specs_tpu.testing.helpers.sync_committee import (
    compute_aggregate_sync_committee_signature,
    compute_committee_indices,
    run_successful_sync_committee_test,
    run_sync_committee_processing,
)


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_bad_domain(spec, state):
    committee_indices = compute_committee_indices(spec, state)

    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec,
            state,
            block.slot - 1,
            committee_indices,  # full committee signs
            block_root=block.parent_root,
            domain_type=spec.DOMAIN_BEACON_ATTESTER,  # Incorrect domain
        ),
    )
    yield from run_sync_committee_processing(spec, state, block, expect_exception=True)


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_missing_participant(spec, state):
    committee_indices = compute_committee_indices(spec, state)
    rng = random.Random(2020)
    random_participant = rng.choice(committee_indices)

    block = build_empty_block_for_next_slot(spec, state)
    # Exclude one participant whose signature was included.
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[index != random_participant for index in committee_indices],
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec,
            state,
            block.slot - 1,
            committee_indices,  # full committee signs
            block_root=block.parent_root,
        ),
    )
    yield from run_sync_committee_processing(spec, state, block, expect_exception=True)


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_extra_participant(spec, state):
    committee_indices = compute_committee_indices(spec, state)
    rng = random.Random(3030)
    random_participant = rng.choice(committee_indices)

    block = build_empty_block_for_next_slot(spec, state)
    # Exclude one signature even though the block claims the entire committee participated.
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec,
            state,
            block.slot - 1,
            [index for index in committee_indices if index != random_participant],
            block_root=block.parent_root,
        ),
    )
    yield from run_sync_committee_processing(spec, state, block, expect_exception=True)


@with_altair_and_later
@spec_state_test
@always_bls
def test_sync_committee_rewards_empty_participants(spec, state):
    committee_indices = compute_committee_indices(spec, state)
    committee_bits = [False] * len(committee_indices)

    yield from run_successful_sync_committee_test(spec, state, committee_indices, committee_bits)


@with_altair_and_later
@spec_state_test
@always_bls
def test_sync_committee_rewards_not_full_participants(spec, state):
    committee_indices = compute_committee_indices(spec, state)
    rng = random.Random(1010)
    committee_bits = [rng.choice([True, False]) for _ in committee_indices]

    yield from run_successful_sync_committee_test(spec, state, committee_indices, committee_bits)


@with_altair_and_later
@spec_state_test
@always_bls
def test_sync_committee_rewards_nonduplicate_committee(spec, state):
    committee_indices = compute_committee_indices(spec, state)
    committee_bits = [True] * len(committee_indices)

    yield from run_successful_sync_committee_test(spec, state, committee_indices, committee_bits)


@with_altair_and_later
@spec_state_test
@always_bls
def test_proposer_in_committee_without_participation(spec, state):
    state.slot = state.slot + 1  # skip one slot to roll proposers

    # find a slot where the proposer is in the sync committee
    committee_indices = compute_committee_indices(spec, state)
    for _ in range(spec.SLOTS_PER_EPOCH):
        block = build_empty_block_for_next_slot(spec, state)
        proposer_index = block.proposer_index
        if proposer_index in committee_indices:
            committee_bits = [index != proposer_index for index in committee_indices]
            participants = [index for index in committee_indices if index != proposer_index]
            block.body.sync_aggregate = spec.SyncAggregate(
                sync_committee_bits=committee_bits,
                sync_committee_signature=compute_aggregate_sync_committee_signature(
                    spec, state, block.slot - 1, participants, block_root=block.parent_root,
                ),
            )
            yield from run_sync_committee_processing(spec, state, block)
            return
        else:
            transition_to(spec, state, state.slot + 1)
    raise AssertionError("no proposer in committee found within an epoch")


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_no_participants(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    # no participants, but a random (non-infinity) signature
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[False] * len(block.body.sync_aggregate.sync_committee_bits),
        sync_committee_signature=b"\x55" * 96,
    )
    yield from run_sync_committee_processing(spec, state, block, expect_exception=True)


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_infinite_signature_with_all_participants(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    # G2 infinity only verifies for the EMPTY participant set
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(block.body.sync_aggregate.sync_committee_bits),
        sync_committee_signature=spec.G2_POINT_AT_INFINITY,
    )
    yield from run_sync_committee_processing(spec, state, block, expect_exception=True)


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_infinite_signature_with_single_participant(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    bits = [False] * len(block.body.sync_aggregate.sync_committee_bits)
    bits[0] = True
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=spec.G2_POINT_AT_INFINITY,
    )
    yield from run_sync_committee_processing(spec, state, block, expect_exception=True)


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_past_block(spec, state):
    from consensus_specs_tpu.testing.helpers.state import (
        state_transition_and_sign_block,
    )

    committee_indices = compute_committee_indices(spec, state)
    for _ in range(2):  # build some history
        block = build_empty_block_for_next_slot(spec, state)
        block.body.sync_aggregate = spec.SyncAggregate(
            sync_committee_bits=[True] * len(committee_indices),
            sync_committee_signature=compute_aggregate_sync_committee_signature(
                spec, state, block.slot - 1, committee_indices,
                block_root=block.parent_root))
        state_transition_and_sign_block(spec, state, block)

    # aggregate signs a TWO-slots-old root: wrong message for this slot
    invalid_block = build_empty_block_for_next_slot(spec, state)
    invalid_block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, invalid_block.slot - 2, committee_indices),
    )
    yield from run_sync_committee_processing(
        spec, state, invalid_block, expect_exception=True)


def _sync_member_in_lifecycle_stage(spec, state, committee_indices, mutate):
    """Apply ``mutate`` to one committee member picked deterministically."""
    victim = committee_indices[0]
    mutate(state.validators[victim])
    return victim


def _aged_state_with_committee(spec, state):
    from consensus_specs_tpu.testing.helpers.state import next_epoch_via_block

    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    for _ in range(2):
        next_epoch_via_block(spec, state)
    return compute_committee_indices(spec, state)


@with_altair_and_later
@spec_state_test
@always_bls
def test_sync_committee_with_participating_exited_member(spec, state):
    committee_indices = _aged_state_with_committee(spec, state)
    victim = _sync_member_in_lifecycle_stage(
        spec, state, committee_indices,
        lambda v: spec.initiate_validator_exit(
            state, committee_indices[0]))
    # past the exit epoch but not yet withdrawable: still a valid signer
    from consensus_specs_tpu.testing.helpers.state import transition_to as _tt
    _tt(spec, state, int(spec.compute_start_slot_at_epoch(
        state.validators[victim].exit_epoch + 1)))
    assert spec.get_current_epoch(state) < state.validators[victim].withdrawable_epoch
    assert not spec.is_active_validator(
        state.validators[victim], spec.get_current_epoch(state))

    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, committee_indices,
            block_root=block.parent_root))
    yield from run_sync_committee_processing(spec, state, block)


@with_altair_and_later
@spec_state_test
@always_bls
def test_sync_committee_with_nonparticipating_exited_member(spec, state):
    committee_indices = _aged_state_with_committee(spec, state)
    victim = committee_indices[0]
    spec.initiate_validator_exit(state, victim)
    from consensus_specs_tpu.testing.helpers.state import transition_to as _tt
    _tt(spec, state, int(spec.compute_start_slot_at_epoch(
        state.validators[victim].exit_epoch + 1)))

    # the exited seat abstains; everyone else signs
    victim_pubkey = state.validators[victim].pubkey
    seat = list(state.current_sync_committee.pubkeys).index(victim_pubkey)
    bits = [i != seat for i in range(len(committee_indices))]
    participants = [idx for i, idx in enumerate(committee_indices) if i != seat]

    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, participants,
            block_root=block.parent_root))
    yield from run_sync_committee_processing(spec, state, block)


@with_altair_and_later
@spec_state_test
@always_bls
def test_sync_committee_with_participating_withdrawable_member(spec, state):
    committee_indices = _aged_state_with_committee(spec, state)
    victim = committee_indices[0]
    # fully withdrawable, yet the committee seat still signs validly
    state.validators[victim].exit_epoch = spec.get_current_epoch(state) - 2
    state.validators[victim].withdrawable_epoch = spec.get_current_epoch(state) - 1

    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, committee_indices,
            block_root=block.parent_root))
    yield from run_sync_committee_processing(spec, state, block)


@with_altair_and_later
@spec_state_test
@always_bls
def test_sync_committee_with_nonparticipating_withdrawable_member(spec, state):
    committee_indices = _aged_state_with_committee(spec, state)
    victim = committee_indices[0]
    state.validators[victim].exit_epoch = spec.get_current_epoch(state) - 2
    state.validators[victim].withdrawable_epoch = spec.get_current_epoch(state) - 1

    victim_pubkey = state.validators[victim].pubkey
    seat = list(state.current_sync_committee.pubkeys).index(victim_pubkey)
    bits = [i != seat for i in range(len(committee_indices))]
    participants = [idx for i, idx in enumerate(committee_indices) if i != seat]

    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, participants,
            block_root=block.parent_root))
    yield from run_sync_committee_processing(spec, state, block)
