"""Altair+ rewards suite — flag-based deltas across participation
patterns (reference suite: test/altair/rewards/test_basic.py).  Every
case also pins the installed vectorized flag-rewards kernel to the
sequential spec components via ``run_flag_deltas``."""
from random import Random

from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.rewards import leaking, run_flag_deltas
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    set_empty_participation,
    set_full_participation,
)

ALTAIR_AND_LATER = ["altair", "bellatrix", "capella"]


def _advance(spec, state, epochs=2):
    for _ in range(epochs):
        next_epoch(spec, state)


def _set_partial_participation(spec, state, rng, fraction=0.5):
    """Randomly give ``fraction`` of validators full previous-epoch flags
    and clear everyone else."""
    full = spec.ParticipationFlags(0)
    for flag_index in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
        full = spec.add_flag(full, flag_index)
    for index in range(len(state.validators)):
        flags = full if rng.random() < fraction else spec.ParticipationFlags(0)
        state.previous_epoch_participation[index] = flags
        state.current_epoch_participation[index] = spec.ParticipationFlags(0)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_empty_participation(spec, state):
    _advance(spec, state)
    set_empty_participation(spec, state)
    yield from run_flag_deltas(spec, state)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_full_participation(spec, state):
    _advance(spec, state)
    set_full_participation(spec, state)
    yield from run_flag_deltas(spec, state)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_half_participation(spec, state):
    _advance(spec, state)
    _set_partial_participation(spec, state, Random(1010), 0.5)
    yield from run_flag_deltas(spec, state)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_one_participant(spec, state):
    _advance(spec, state)
    set_empty_participation(spec, state)
    full = spec.ParticipationFlags(0)
    for flag_index in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
        full = spec.add_flag(full, flag_index)
    state.previous_epoch_participation[0] = full
    yield from run_flag_deltas(spec, state)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_target_only_participation(spec, state):
    _advance(spec, state)
    set_empty_participation(spec, state)
    for index in range(len(state.validators)):
        state.previous_epoch_participation[index] = spec.add_flag(
            spec.ParticipationFlags(0), int(spec.TIMELY_TARGET_FLAG_INDEX))
    yield from run_flag_deltas(spec, state)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_full_participation_with_slashed(spec, state):
    _advance(spec, state)
    set_full_participation(spec, state)
    for index in (0, 3, 7):
        state.validators[index].slashed = True
    yield from run_flag_deltas(spec, state)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_empty_participation_leak(spec, state):
    set_empty_participation(spec, state)
    assert spec.is_in_inactivity_leak(state)
    yield from run_flag_deltas(spec, state)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_full_participation_leak(spec, state):
    set_full_participation(spec, state)
    assert spec.is_in_inactivity_leak(state)
    yield from run_flag_deltas(spec, state)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_half_participation_leak_with_scores(spec, state):
    """Leaking state with nonzero inactivity scores: the quadratic
    inactivity penalty must hit exactly the non-target-participating."""
    rng = Random(2020)
    _set_partial_participation(spec, state, rng, 0.5)
    for index in range(len(state.validators)):
        state.inactivity_scores[index] = rng.randrange(0, 50)
    yield from run_flag_deltas(spec, state)


# -- inactivity-score-focused scenarios (reference suite:
#    test/altair/rewards/test_inactivity_scores.py) ---------------------------


def _seed_inactivity_scores(spec, state, rng=None, uniform=None):
    for index in range(len(state.validators)):
        state.inactivity_scores[index] = (
            uniform if uniform is not None else rng.randint(0, 1000))


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_random_inactivity_scores_full_participation(spec, state):
    _advance(spec, state)
    set_full_participation(spec, state)
    _seed_inactivity_scores(spec, state, Random(9001))
    yield from run_flag_deltas(spec, state)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_random_inactivity_scores_full_participation_leaking(spec, state):
    set_full_participation(spec, state)
    _seed_inactivity_scores(spec, state, Random(9002))
    yield from run_flag_deltas(spec, state)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_random_inactivity_scores_empty_participation(spec, state):
    _advance(spec, state)
    set_empty_participation(spec, state)
    _seed_inactivity_scores(spec, state, Random(9003))
    yield from run_flag_deltas(spec, state)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_random_inactivity_scores_empty_participation_leaking(spec, state):
    set_empty_participation(spec, state)
    _seed_inactivity_scores(spec, state, Random(9004))
    yield from run_flag_deltas(spec, state)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_maximal_inactivity_scores_leaking(spec, state):
    """Quadratic penalties at the score ceiling must not overflow or go
    negative through the balance floor."""
    set_empty_participation(spec, state)
    _seed_inactivity_scores(
        spec, state, uniform=int(spec.config.INACTIVITY_SCORE_BIAS) * 100)
    yield from run_flag_deltas(spec, state)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking()
def test_zero_inactivity_scores_leaking(spec, state):
    set_empty_participation(spec, state)
    _seed_inactivity_scores(spec, state, uniform=0)
    yield from run_flag_deltas(spec, state)


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@leaking(epochs_extra=6)
def test_random_scores_deep_leak_partial_participation(spec, state):
    _set_partial_participation(spec, state, Random(9005), fraction=0.3)
    _seed_inactivity_scores(spec, state, Random(9006))
    yield from run_flag_deltas(spec, state)
