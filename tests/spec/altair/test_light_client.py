"""Altair light-client sync protocol tests using the light_client and
merkle helpers (reference capability: test/altair/unittests/test_sync_protocol.py)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.block import (
    build_empty_block_for_next_slot,
)
from consensus_specs_tpu.testing.helpers.light_client import (
    get_sync_aggregate,
    initialize_light_client_store,
)
from consensus_specs_tpu.testing.helpers.merkle import build_proof
from consensus_specs_tpu.testing.helpers.state import state_transition_and_sign_block


@with_phases(["altair"])
@spec_state_test
def test_initialize_store(spec, state):
    store = initialize_light_client_store(spec, state)
    assert store.current_sync_committee == state.current_sync_committee
    assert store.next_sync_committee == state.next_sync_committee
    assert store.best_valid_update is None
    yield from ()


@with_phases(["altair"])
@spec_state_test
def test_sync_aggregate_helper_is_block_valid(spec, state):
    """get_sync_aggregate output passes the real process_sync_aggregate."""
    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = get_sync_aggregate(
        spec, state, block,
        block_root=block.parent_root,
    )
    state_transition_and_sign_block(spec, state, block)
    yield from ()


@with_phases(["altair"])
@spec_state_test
def test_next_sync_committee_merkle_proof(spec, state):
    """build_proof produces a branch is_valid_merkle_branch accepts for
    NEXT_SYNC_COMMITTEE_INDEX — the exact proof light-client updates carry."""
    proof = build_proof(state, int(spec.NEXT_SYNC_COMMITTEE_INDEX))
    assert len(proof) == int(spec.floorlog2(spec.NEXT_SYNC_COMMITTEE_INDEX))
    assert spec.is_valid_merkle_branch(
        leaf=state.next_sync_committee.hash_tree_root(),
        branch=proof,
        depth=int(spec.floorlog2(spec.NEXT_SYNC_COMMITTEE_INDEX)),
        index=int(spec.get_subtree_index(spec.NEXT_SYNC_COMMITTEE_INDEX)),
        root=state.hash_tree_root(),
    )
    yield from ()


@with_phases(["altair"])
@spec_state_test
def test_finalized_root_merkle_proof(spec, state):
    proof = build_proof(state, int(spec.FINALIZED_ROOT_INDEX))
    assert spec.is_valid_merkle_branch(
        leaf=state.finalized_checkpoint.root,
        branch=proof,
        depth=int(spec.floorlog2(spec.FINALIZED_ROOT_INDEX)),
        index=int(spec.get_subtree_index(spec.FINALIZED_ROOT_INDEX)),
        root=state.hash_tree_root(),
    )
    yield from ()


def _header_for(spec, block):
    return spec.BeaconBlockHeader(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=block.state_root,
        body_root=spec.hash_tree_root(block.body),
    )


def _same_period_update(spec, state, header):
    """Non-finality, same-period update attested by the full committee."""
    return spec.LightClientUpdate(
        attested_header=header,
        next_sync_committee=state.next_sync_committee,
        finalized_header=spec.BeaconBlockHeader(),
        sync_aggregate=get_sync_aggregate(
            spec, state, header,
            block_root=spec.hash_tree_root(header)),
        fork_version=state.fork.current_version,
    )


@with_phases(["altair"])
@spec_state_test
def test_process_light_client_update_sets_optimistic_and_best(spec, state):
    """A valid non-finality update becomes best_valid_update and advances
    the optimistic header, but not the finalized one
    (spec: altair/sync-protocol.md process_light_client_update)."""
    store = initialize_light_client_store(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    header = _header_for(spec, signed.message)

    update = _same_period_update(spec, state, header)
    spec.process_light_client_update(
        store, update, state.slot, state.genesis_validators_root)

    assert store.best_valid_update is not None
    assert spec.hash_tree_root(store.best_valid_update.attested_header) == \
        spec.hash_tree_root(header)
    assert spec.hash_tree_root(store.optimistic_header) == \
        spec.hash_tree_root(header)
    # no finality data: the finalized header must not advance
    assert int(store.finalized_header.slot) == 0
    assert int(store.current_max_active_participants) == \
        int(spec.SYNC_COMMITTEE_SIZE)


@with_phases(["altair"])
@spec_state_test
def test_process_light_client_update_bad_signature_rejected(spec, state):
    store = initialize_light_client_store(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    header = _header_for(spec, signed.message)

    update = _same_period_update(spec, state, header)
    tampered = spec.BeaconBlockHeader(
        slot=header.slot, proposer_index=header.proposer_index,
        parent_root=header.parent_root, state_root=header.state_root,
        body_root=b"\x13" * 32)
    update.attested_header = tampered
    try:
        spec.process_light_client_update(
            store, update, state.slot, state.genesis_validators_root)
        from consensus_specs_tpu.crypto import bls as _bls
        assert not _bls.bls_active  # only passes when verification is stubbed
    except AssertionError:
        assert store.best_valid_update is None


@with_phases(["altair"])
@spec_state_test
def test_light_client_forced_update_on_timeout(spec, state):
    """With a pending best_valid_update and no finality for a whole
    UPDATE_TIMEOUT window, the store force-applies the best update
    (spec: altair/sync-protocol.md process_slot_for_light_client_store)."""
    store = initialize_light_client_store(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    header = _header_for(spec, signed.message)

    update = _same_period_update(spec, state, header)
    spec.process_light_client_update(
        store, update, state.slot, state.genesis_validators_root)
    assert store.best_valid_update is not None
    assert int(store.finalized_header.slot) == 0

    timeout_slot = int(header.slot) + int(spec.UPDATE_TIMEOUT) + 1
    spec.process_slot_for_light_client_store(store, spec.Slot(timeout_slot))
    # forced apply: the best update's header became the finalized header
    assert spec.hash_tree_root(store.finalized_header) == \
        spec.hash_tree_root(header)
    assert store.best_valid_update is None
