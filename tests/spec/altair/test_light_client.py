"""Altair light-client sync protocol tests using the light_client and
merkle helpers (reference capability: test/altair/unittests/test_sync_protocol.py)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.block import (
    build_empty_block_for_next_slot,
)
from consensus_specs_tpu.testing.helpers.light_client import (
    get_sync_aggregate,
    initialize_light_client_store,
)
from consensus_specs_tpu.testing.helpers.merkle import build_proof
from consensus_specs_tpu.testing.helpers.state import state_transition_and_sign_block


@with_phases(["altair"])
@spec_state_test
def test_initialize_store(spec, state):
    store = initialize_light_client_store(spec, state)
    assert store.current_sync_committee == state.current_sync_committee
    assert store.next_sync_committee == state.next_sync_committee
    assert store.best_valid_update is None
    yield from ()


@with_phases(["altair"])
@spec_state_test
def test_sync_aggregate_helper_is_block_valid(spec, state):
    """get_sync_aggregate output passes the real process_sync_aggregate."""
    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = get_sync_aggregate(
        spec, state, block,
        block_root=block.parent_root,
    )
    state_transition_and_sign_block(spec, state, block)
    yield from ()


@with_phases(["altair"])
@spec_state_test
def test_next_sync_committee_merkle_proof(spec, state):
    """build_proof produces a branch is_valid_merkle_branch accepts for
    NEXT_SYNC_COMMITTEE_INDEX — the exact proof light-client updates carry."""
    proof = build_proof(state, int(spec.NEXT_SYNC_COMMITTEE_INDEX))
    assert len(proof) == int(spec.floorlog2(spec.NEXT_SYNC_COMMITTEE_INDEX))
    assert spec.is_valid_merkle_branch(
        leaf=state.next_sync_committee.hash_tree_root(),
        branch=proof,
        depth=int(spec.floorlog2(spec.NEXT_SYNC_COMMITTEE_INDEX)),
        index=int(spec.get_subtree_index(spec.NEXT_SYNC_COMMITTEE_INDEX)),
        root=state.hash_tree_root(),
    )
    yield from ()


@with_phases(["altair"])
@spec_state_test
def test_finalized_root_merkle_proof(spec, state):
    proof = build_proof(state, int(spec.FINALIZED_ROOT_INDEX))
    assert spec.is_valid_merkle_branch(
        leaf=state.finalized_checkpoint.root,
        branch=proof,
        depth=int(spec.floorlog2(spec.FINALIZED_ROOT_INDEX)),
        index=int(spec.get_subtree_index(spec.FINALIZED_ROOT_INDEX)),
        root=state.hash_tree_root(),
    )
    yield from ()
