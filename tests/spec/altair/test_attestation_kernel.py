"""Differential: the block-scoped vectorized process_attestation
(specs/builder.py _install_altair_attestation_kernel) must mutate state
identically to the sequential altair spec path — participation flags,
proposer reward, and assert behavior."""
import pytest

from consensus_specs_tpu.specs import builder
from consensus_specs_tpu.specs.builder import get_spec
from consensus_specs_tpu.ssz import bulk
from consensus_specs_tpu.testing.context import (
    default_activation_threshold,
    default_balances,
)
from consensus_specs_tpu.testing.helpers.attestations import (
    get_valid_attestation,
)
from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state
from consensus_specs_tpu.testing.helpers.state import next_slots


@pytest.fixture(scope="module")
def env():
    spec = get_spec("altair", "minimal")
    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec))
    next_slots(spec, state, 3)
    att = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    return spec, state, att


def _run_scoped(spec, state, att):
    """Run the substituted path under a participation scope, flushing the
    mirror — exactly what the process_block wrapper does."""
    scope = builder._ParticipationBlockScope(state)
    token = builder._part_scope.set(scope)
    try:
        spec.process_attestation(state, att)
        scope.flush(state)
    finally:
        builder._part_scope.reset(token)


def test_scoped_matches_sequential(env):
    spec, state, att = env
    seq, vec = state.copy(), state.copy()
    spec.process_attestation.__wrapped__(seq, att)
    _run_scoped(spec, vec, att)
    assert bytes(vec.hash_tree_root()) == bytes(seq.hash_tree_root())
    assert (bulk.packed_uint8_to_numpy(vec.current_epoch_participation)
            == bulk.packed_uint8_to_numpy(seq.current_epoch_participation)).all()
    assert vec.balances == seq.balances


def test_second_inclusion_gives_no_double_reward(env):
    """Flags already set -> zero new numerator on both paths."""
    spec, state, att = env
    seq, vec = state.copy(), state.copy()
    spec.process_attestation.__wrapped__(seq, att)
    spec.process_attestation.__wrapped__(seq, att)
    scope = builder._ParticipationBlockScope(vec)
    token = builder._part_scope.set(scope)
    try:
        spec.process_attestation(vec, att)
        spec.process_attestation(vec, att)  # dedup against the mirror
        scope.flush(vec)
    finally:
        builder._part_scope.reset(token)
    assert bytes(vec.hash_tree_root()) == bytes(seq.hash_tree_root())


def test_validation_asserts_match(env):
    spec, state, att = env
    bad = att.copy()
    bad.data.index = spec.get_committee_count_per_slot(
        state, bad.data.target.epoch) + 10
    for runner in (
        lambda st: _run_scoped(spec, st, bad),
        lambda st: spec.process_attestation.__wrapped__(st, bad),
    ):
        st = state.copy()
        with pytest.raises(AssertionError):
            runner(st)


def test_outside_scope_falls_back_to_sequential(env):
    spec, state, att = env
    seq, direct = state.copy(), state.copy()
    spec.process_attestation.__wrapped__(seq, att)
    spec.process_attestation(direct, att)  # no scope: must be sequential
    assert bytes(direct.hash_tree_root()) == bytes(seq.hash_tree_root())


def test_sync_aggregate_substitution_matches_sequential(env):
    """process_sync_aggregate with the cached pubkey reverse index must
    mutate balances identically to the spec's all-validators list.index
    scan, for full, partial, and empty participation."""
    from consensus_specs_tpu.testing.helpers.sync_committee import (
        compute_aggregate_sync_committee_signature,
        compute_committee_indices,
    )

    spec, state, _ = env
    committee = compute_committee_indices(spec, state)
    size = int(spec.SYNC_COMMITTEE_SIZE)
    for bits in ([True] * size,
                 [i % 2 == 0 for i in range(size)],
                 [False] * size):
        participants = [i for i, b in zip(committee, bits) if b]
        agg = spec.SyncAggregate(
            sync_committee_bits=bits,
            sync_committee_signature=compute_aggregate_sync_committee_signature(
                spec, state, state.slot - 1, participants))
        seq, vec = state.copy(), state.copy()
        spec.process_sync_aggregate.__wrapped__(seq, agg)
        spec.process_sync_aggregate(vec, agg)
        assert bytes(vec.hash_tree_root()) == bytes(seq.hash_tree_root())
