"""Light-client single-leaf merkle proofs over BeaconState gindices
(reference capability: test/altair/merkle/test_single_proof.py; format:
docs/formats/merkle/single_proof.md)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_altair_and_later,
)
from consensus_specs_tpu.testing.helpers.merkle import build_proof


def _run_single_proof(spec, state, gindex, leaf_root):
    """Yield the state + proof parts and verify the branch both ways."""
    yield "state", state
    branch = build_proof(state.get_backing(), gindex)
    yield "proof", {
        "leaf": "0x" + bytes(leaf_root).hex(),
        "leaf_index": int(gindex),
        "branch": ["0x" + bytes(node).hex() for node in branch],
    }
    assert spec.is_valid_merkle_branch(
        leaf=leaf_root,
        branch=branch,
        depth=spec.floorlog2(gindex),
        index=spec.get_subtree_index(gindex),
        root=state.hash_tree_root(),
    )


@with_altair_and_later
@spec_state_test
def test_next_sync_committee_merkle_proof(spec, state):
    yield from _run_single_proof(
        spec, state, spec.NEXT_SYNC_COMMITTEE_INDEX,
        state.next_sync_committee.hash_tree_root())


@with_altair_and_later
@spec_state_test
def test_finality_root_merkle_proof(spec, state):
    yield from _run_single_proof(
        spec, state, spec.FINALIZED_ROOT_INDEX,
        state.finalized_checkpoint.root)
