"""Altair+ sanity block scenarios (reference suite:
test/altair/sanity/test_blocks.py): full blocks with sync aggregates,
attestations setting participation flags, and epoch rollover."""
from consensus_specs_tpu.testing.context import (
    always_bls,
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.attestations import (
    get_valid_attestation,
    next_epoch_with_attestations,
)
from consensus_specs_tpu.testing.helpers.block import (
    build_empty_block_for_next_slot,
)
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    next_slots,
    state_transition_and_sign_block,
)
from consensus_specs_tpu.testing.helpers.sync_committee import (
    compute_aggregate_sync_committee_signature,
    compute_committee_indices,
)

ALTAIR_AND_LATER = ["altair", "bellatrix", "capella"]


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_block_with_full_sync_aggregate(spec, state):
    next_epoch(spec, state)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    committee_indices = compute_committee_indices(spec, state)
    assert len(committee_indices) == int(spec.SYNC_COMMITTEE_SIZE)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, committee_indices),
    )
    signed = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed]
    yield "post", state


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
@always_bls
def test_block_with_partial_sync_aggregate_bls(spec, state):
    next_epoch(spec, state)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    committee_indices = compute_committee_indices(spec, state)
    bits = [i % 2 == 0 for i in range(len(committee_indices))]
    participants = [
        v for i, v in enumerate(committee_indices) if bits[i]]
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, participants),
    )
    signed = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed]
    yield "post", state


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_attestation_sets_participation_flags(spec, state):
    next_epoch(spec, state)
    next_slots(spec, state, 1)
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.attestations.append(attestation)
    signed = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed]
    yield "post", state
    flagged = [
        i for i in range(len(state.validators))
        if int(state.current_epoch_participation[i]) != 0
        or int(state.previous_epoch_participation[i]) != 0
    ]
    assert len(flagged) > 0


@with_phases(ALTAIR_AND_LATER)
@spec_state_test
def test_two_epochs_full_attestations(spec, state):
    next_epoch(spec, state)
    yield "pre", state
    _, blocks, state = next_epoch_with_attestations(spec, state, True, False)
    _, blocks2, state = next_epoch_with_attestations(spec, state, True, True)
    yield "blocks", blocks + blocks2
    yield "post", state
    # full participation must have justified the chain
    assert int(state.current_justified_checkpoint.epoch) > 0
