"""Differential suite: the batched block-transition engine's ALTAIR
LINEAGE fast path vs the literal ``spec.state_transition``.

Same three-layer contract as the phase0 suite
(tests/spec/phase0/sanity/test_stf_engine_differential.py), now covering
the altair-specific state application — participation-flag scatter, sync
aggregates folded into the per-block signature batch, net-delta sync
rewards — and bellatrix's execution payload run literally inside the
snapshot region:

* **Sanity replays** — every altair and bellatrix sanity-blocks scenario
  re-runs under ``engine_mode()`` with post-state parity (or shared
  rejection) asserted after every helper-driven transition;

* **BLS-on chains** — a 2-epoch attestation-bearing altair chain, sync-
  aggregate-bearing blocks (full and partial participation), a seeded
  random-operation walk, and a bellatrix payload chain, each with
  per-block root parity and the no-silent-fallback counter assert;

* **Failure behavior** — an exception-parity battery for the new
  surfaces: invalid sync-committee signature, empty participation with a
  non-infinity signature, tampered attestation signatures (bisection
  path), bad state roots, and an invalid execution payload — each must
  raise the literal spec's exact exception and leave the state
  byte-identically poisoned.  A wrong-length participation bitvector is
  unrepresentable by construction (Bitvector[SYNC_COMMITTEE_SIZE]
  rejects it at the SSZ layer) and is pinned as such.
"""
import pytest

from consensus_specs_tpu import stf
from consensus_specs_tpu.testing.context import spec_state_test, with_phases
from consensus_specs_tpu.testing.helpers.attestations import (
    next_slots_with_attestations,
)
from consensus_specs_tpu.testing.helpers.block import (
    build_empty_block_for_next_slot,
)
from consensus_specs_tpu.testing.helpers.block_processing import engine_mode
from consensus_specs_tpu.testing.helpers.execution_payload import (
    build_empty_execution_payload,
)
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    state_transition_and_sign_block,
)
from consensus_specs_tpu.testing.helpers.sync_committee import (
    compute_aggregate_sync_committee_signature,
    compute_committee_indices,
)
from consensus_specs_tpu.testing.random_scenarios import run_random_scenario

from . import test_blocks as _altair_blocks

# -- adversarial sanity replays ----------------------------------------------

from ...bellatrix.sanity import test_blocks as _bellatrix_blocks

_ALTAIR_REPLAYS = [name for name in sorted(dir(_altair_blocks))
                   if name.startswith("test_")]
_BELLATRIX_REPLAYS = [name for name in sorted(dir(_bellatrix_blocks))
                      if name.startswith("test_")]


@pytest.mark.parametrize("phase", ["altair", "bellatrix"])
@pytest.mark.parametrize("name", _ALTAIR_REPLAYS)
def test_replay_altair_sanity_scenario_through_engine(name, phase):
    """Re-run the altair+ sanity scenarios (sync aggregates, participation
    flags, epoch rollover) with the engine mirror attached — for both the
    altair and bellatrix builds of the scenario."""
    with engine_mode():
        getattr(_altair_blocks, name)(phase=phase, bls_active=False)


@pytest.mark.parametrize("name", _BELLATRIX_REPLAYS)
def test_replay_bellatrix_sanity_scenario_through_engine(name):
    """Execution-payload sanity scenarios (pre/post merge) mirrored
    through the engine: process_execution_payload runs literally inside
    the snapshot-protected region."""
    with engine_mode():
        getattr(_bellatrix_blocks, name)(phase="bellatrix", bls_active=False)


# -- BLS-on chains ------------------------------------------------------------


def _per_block_differential(spec, state, signed_blocks):
    """Replay block-by-block through both paths, roots compared at every
    block boundary; the engine must take its fast path on every block."""
    s_spec, s_eng = state.copy(), state.copy()
    stf.reset_stats()
    for i, sb in enumerate(signed_blocks):
        spec.state_transition(s_spec, sb, True)
        stf.apply_signed_blocks(spec, s_eng, [sb], True)
        assert bytes(s_spec.hash_tree_root()) == bytes(s_eng.hash_tree_root()), \
            f"post-state diverged at block {i}"
    assert stf.stats["replayed_blocks"] == 0 and \
        stf.stats["fast_blocks"] == len(signed_blocks), \
        f"engine silently replayed {stf.stats['replayed_blocks']} blocks"
    return s_eng


@with_phases(["altair"])
@spec_state_test
def test_stf_differential_full_epochs_bls_altair(spec, state):
    """Two attestation-bearing altair epochs, BLS ON: participation-flag
    scatter + proposer rewards against the literal kernel, every block's
    signatures settled in one engine batch."""
    next_epoch(spec, state)
    _, signed_blocks, _ = next_slots_with_attestations(
        spec, state.copy(), int(spec.SLOTS_PER_EPOCH) * 2, True, True)
    _per_block_differential(spec, state, signed_blocks)
    yield None


def _sync_block(spec, state, participation):
    """A signed block carrying a sync aggregate with the given per-seat
    participation filter (state is advanced through the block)."""
    block = build_empty_block_for_next_slot(spec, state)
    committee_indices = compute_committee_indices(spec, state)
    bits = [participation(i) for i in range(len(committee_indices))]
    participants = [v for i, v in enumerate(committee_indices) if bits[i]]
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, participants))
    return block, state_transition_and_sign_block(spec, state, block)


@with_phases(["altair"])
@spec_state_test
def test_stf_differential_sync_aggregates_bls(spec, state):
    """Full, partial, and empty sync participation, BLS ON: the sync
    entry joins the block batch (or the infinity-signature acceptance
    short-circuits it) and net-delta rewards land byte-identical."""
    next_epoch(spec, state)
    pre = state.copy()
    signed = []
    for participation in (lambda i: True, lambda i: i % 2 == 0,
                          lambda i: False):
        signed.append(_sync_block(spec, state, participation)[1])
    _per_block_differential(spec, pre, signed)
    yield None


@pytest.mark.parametrize("seed", [13])
def test_stf_differential_random_scenario_altair(seed):
    """Seeded randomized-operation walk (random sync aggregates included)
    mirrored through the engine by the helper hook; BLS on."""
    @with_phases(["altair"])
    @spec_state_test
    def case(spec, state):
        with engine_mode():
            yield from run_random_scenario(spec, state, seed=seed, stages=4)

    case(phase="altair", bls_active=True)


@with_phases(["bellatrix"])
@spec_state_test
def test_stf_differential_bellatrix_payload_chain(spec, state):
    """A post-merge payload-bearing chain, BLS ON: the literal
    process_execution_payload runs inside the snapshot region and the
    header caching lands byte-identical."""
    pre = state.copy()
    assert spec.is_merge_transition_complete(state)
    signed = []
    for _ in range(4):
        block = build_empty_block_for_next_slot(spec, state)
        advanced = state.copy()
        spec.process_slots(advanced, block.slot)
        block.body.execution_payload = build_empty_execution_payload(
            spec, advanced)
        signed.append(state_transition_and_sign_block(spec, state, block))
    _per_block_differential(spec, pre, signed)
    yield None


# -- identical failure behavior ----------------------------------------------


def _exception_parity(spec, state, signed_block):
    """Both paths must raise the same exception type/message and leave the
    state byte-identically (partially) mutated."""
    exc_spec = exc_eng = None
    s_spec, s_eng = state.copy(), state.copy()
    try:
        spec.state_transition(s_spec, signed_block, True)
    except Exception as e:  # noqa: B001 - parity harness captures anything
        exc_spec = e
    try:
        stf.apply_signed_blocks(spec, s_eng, [signed_block], True)
    except Exception as e:  # noqa: B001
        exc_eng = e
    assert exc_spec is not None, "scenario was supposed to be invalid"
    assert type(exc_spec) is type(exc_eng), (exc_spec, exc_eng)
    assert str(exc_spec) == str(exc_eng), (exc_spec, exc_eng)
    assert bytes(s_spec.hash_tree_root()) == bytes(s_eng.hash_tree_root()), \
        "poisoned post-states diverged"


@with_phases(["altair"])
@spec_state_test
def test_stf_invalid_altair_blocks_fail_identically(spec, state):
    next_epoch(spec, state)
    pre = state.copy()
    _, signed = _sync_block(spec, state, lambda i: True)

    def tamper(fn):
        sb = signed.copy()
        fn(sb)
        return sb

    size = int(spec.SYNC_COMMITTEE_SIZE)
    cases = [
        # invalid sync-committee signature (bisected to the sync entry,
        # then replayed to the spec's process_sync_aggregate assert)
        tamper(lambda sb: setattr(sb.message.body.sync_aggregate,
                                  "sync_committee_signature",
                                  spec.BLSSignature(b"\x42" * 96))),
        # empty participation must carry the infinity signature
        tamper(lambda sb: setattr(sb.message.body, "sync_aggregate",
                                  spec.SyncAggregate(
                                      sync_committee_bits=[False] * size,
                                      sync_committee_signature=spec.BLSSignature(
                                          b"\x01" * 96)))),
        # flipped participation bit under the full-participation signature
        tamper(lambda sb: setattr(
            sb.message.body.sync_aggregate, "sync_committee_bits",
            [i != 0 for i in range(size)])),
        tamper(lambda sb: setattr(sb, "signature", b"\x11" * 96)),
        tamper(lambda sb: setattr(sb.message, "state_root",
                                  spec.Root(b"\x44" * 32))),
    ]
    for sb in cases:
        _exception_parity(spec, pre, sb)
    yield None


@with_phases(["altair"])
@spec_state_test
def test_stf_invalid_altair_attestation_fails_identically(spec, state):
    """A tampered aggregate signature inside an attestation-bearing altair
    block: the engine's batch fails, bisects, rolls back, and the literal
    replay raises at the spec's is_valid_indexed_attestation assert."""
    next_epoch(spec, state)
    _, signed_blocks, _ = next_slots_with_attestations(
        spec, state.copy(), int(spec.SLOTS_PER_EPOCH), True, False)
    sb = signed_blocks[0].copy()
    sb.message.body.attestations[0].signature = spec.BLSSignature(b"\x33" * 96)
    _exception_parity(spec, state, sb)
    yield None


@with_phases(["bellatrix"])
@spec_state_test
def test_stf_invalid_execution_payload_fails_identically(spec, state):
    """An invalid execution payload (wrong timestamp) must raise the
    spec's process_execution_payload assert from inside the snapshot
    region with identical partial state."""
    pre = state.copy()
    block = build_empty_block_for_next_slot(spec, state)
    advanced = state.copy()
    spec.process_slots(advanced, block.slot)
    payload = build_empty_execution_payload(spec, advanced)
    payload.timestamp = payload.timestamp + 1
    block.body.execution_payload = payload
    signed = state_transition_and_sign_block(
        spec, state, block, expect_fail=True)
    _exception_parity(spec, pre, signed)
    yield None


@with_phases(["altair"])
@spec_state_test
def test_wrong_length_sync_bits_unrepresentable(spec, state):
    """The 'wrong participation bitvector length' failure class is closed
    off at the SSZ layer: Bitvector[SYNC_COMMITTEE_SIZE] rejects any other
    length at construction, so neither path can ever see such a block."""
    size = int(spec.SYNC_COMMITTEE_SIZE)
    for bad in (size - 1, size + 1, 1):
        with pytest.raises(Exception):
            spec.SyncAggregate(sync_committee_bits=[True] * bad)
    # the empty literal is the type's DEFAULT fill (all-false, full size),
    # not a zero-length vector — still never a length mismatch
    sa = spec.SyncAggregate(sync_committee_bits=[])
    assert len(sa.sync_committee_bits) == size
    yield None


# -- exception parity, pipeline ON vs OFF (ISSUE 10) --------------------------

from ...phase0.sanity.test_stf_engine_differential import (  # noqa: E402
    _PIPELINE_BATTERY,
    _pipeline_exception_battery,
)


@pytest.mark.parametrize("pipeline_mode", ["0", "1"],
                         ids=["pipeline-off", "pipeline-on"])
@pytest.mark.parametrize("scenario", _PIPELINE_BATTERY)
def test_exception_parity_pipeline_battery_altair(scenario, pipeline_mode,
                                                  monkeypatch, recwarn):
    """The ON/OFF exception-parity battery over the ALTAIR corpus: the
    speculated invalid block rides sync-aggregate-bearing predecessors,
    so the drain unwinds participation mirror flushes and sync seat
    memos too (same shared harness as the phase0 suite)."""
    _pipeline_exception_battery("altair", scenario, pipeline_mode,
                                monkeypatch)
