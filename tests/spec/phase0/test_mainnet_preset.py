"""Mainnet-preset execution smoke tests — the mainnet spec module runs
end-to-end, not just compiles (VERDICT weak #6: 'mainnet preset is never
executed').  Uses a small synthetic registry (mainnet committee math
degrades gracefully via max(1, ...)) so the default suite stays fast; the
full 400k path is bench.py's job."""
import numpy as np

from consensus_specs_tpu.specs.builder import get_spec
from consensus_specs_tpu.ssz import bulk
from consensus_specs_tpu.ssz.node import (
    BranchNode,
    subtree_fill_to_contents,
    uint_to_leaf,
)

FAR_FUTURE = 2**64 - 1


def _synthetic_state(spec, n):
    state = spec.BeaconState()
    state.slot = 2 * spec.SLOTS_PER_EPOCH
    vnode = spec.Validator(
        effective_balance=spec.MAX_EFFECTIVE_BALANCE,
        activation_epoch=0,
        exit_epoch=FAR_FUTURE,
        withdrawable_epoch=FAR_FUTURE,
    ).get_backing()
    vt = type(state.validators)
    state.validators = vt.view_from_backing(
        BranchNode(subtree_fill_to_contents([vnode] * n, vt.contents_depth()),
                   uint_to_leaf(n)))
    bulk.set_packed_uint64_from_numpy(
        state.balances,
        np.full(n, int(spec.MAX_EFFECTIVE_BALANCE), dtype=np.int64))
    return state


def _fill_prev_epoch_attestations(spec, state):
    prev = spec.get_previous_epoch(state)
    start = int(spec.compute_start_slot_at_epoch(prev))
    for slot in range(start, start + int(spec.SLOTS_PER_EPOCH)):
        for index in range(int(spec.get_committee_count_per_slot(state, prev))):
            committee = spec.get_beacon_committee(state, slot, index)
            data = spec.AttestationData(
                slot=slot, index=index,
                beacon_block_root=spec.get_block_root_at_slot(state, slot),
                source=state.previous_justified_checkpoint,
                target=spec.Checkpoint(
                    epoch=prev, root=spec.get_block_root(state, prev)),
            )
            state.previous_epoch_attestations.append(spec.PendingAttestation(
                aggregation_bits=[True] * len(committee),
                data=data, inclusion_delay=1, proposer_index=0,
            ))


def test_mainnet_phase0_epoch_transition_runs():
    spec = get_spec("phase0", "mainnet")
    assert int(spec.SLOTS_PER_EPOCH) == 32
    assert spec.config.PRESET_BASE == "mainnet"
    state = _synthetic_state(spec, 2048)
    _fill_prev_epoch_attestations(spec, state)
    pre_balance = int(state.balances[0])
    spec.process_epoch(state)
    # full participation at mainnet constants: everyone earns rewards
    assert int(state.balances[0]) > pre_balance
    assert int(state.current_justified_checkpoint.epoch) == 1


def test_mainnet_matches_sequential_pipeline():
    """The vectorized substitutions hold bit-for-bit under mainnet
    constants too, not just minimal."""
    spec = get_spec("phase0", "mainnet")
    state = _synthetic_state(spec, 1024)
    _fill_prev_epoch_attestations(spec, state)
    seq_state = state.copy()
    spec.process_epoch(state)
    g = spec.__dict__
    names = ("process_rewards_and_penalties", "process_registry_updates",
             "process_slashings", "process_effective_balance_updates")
    saved = {k: g[k] for k in names}
    try:
        for k in names:
            fn = saved[k]
            while hasattr(fn, "__wrapped__"):
                fn = fn.__wrapped__
            g[k] = fn
        spec.process_epoch(seq_state)
    finally:
        g.update(saved)
    assert state.hash_tree_root() == seq_state.hash_tree_root()


def test_mainnet_capella_spec_builds_and_upgrades():
    spec = get_spec("capella", "mainnet")
    assert int(spec.MAX_WITHDRAWALS_PER_PAYLOAD) == 16
    assert spec.config.CAPELLA_FORK_VERSION == bytes.fromhex("03000000")
