"""Differential tests: vectorized registry-phase substitutions vs their
sequential ``__wrapped__`` originals (installed by specs/builder.py
_install_registry_vectorization / _install_phase0_epoch_kernel).

Every scenario mutates a copy of the state through BOTH paths and demands
bit-identical results — state root for the process_* functions, exact
values for the accessors.  Scenarios are chosen to hit each vectorized
branch: activation queue, ejections, dequeue ordering, slashing windows,
hysteresis in both directions, and FAR_FUTURE saturation.
"""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_all_phases,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.attestations import (
    prepare_state_with_attestations,
)
from consensus_specs_tpu.testing.helpers.state import next_epoch

FAR_FUTURE = 2**64 - 1


def unwrap(fn):
    while hasattr(fn, "__wrapped__"):
        fn = fn.__wrapped__
    return fn


def _assert_same_mutation(spec, state, name):
    """Run vectorized spec.<name> and sequential original on copies; roots
    must match bit-for-bit."""
    vec_state = state.copy()
    seq_state = state.copy()
    getattr(spec, name)(vec_state)
    unwrap(getattr(spec, name))(seq_state)
    assert vec_state.hash_tree_root() == seq_state.hash_tree_root(), name


@with_all_phases
@spec_state_test
def test_registry_updates_differential(spec, state):
    n = len(state.validators)
    # queue-eligible: fresh-deposit shape
    state.validators[1].activation_eligibility_epoch = FAR_FUTURE
    state.validators[1].activation_epoch = FAR_FUTURE
    # ejectable: active with balance at the ejection line
    state.validators[2].effective_balance = spec.config.EJECTION_BALANCE
    # dequeue candidates with distinct eligibility epochs (order matters)
    state.finalized_checkpoint.epoch = 5
    state.validators[3].activation_eligibility_epoch = 4
    state.validators[3].activation_epoch = FAR_FUTURE
    state.validators[4].activation_eligibility_epoch = 2
    state.validators[4].activation_epoch = FAR_FUTURE
    if n > 5:
        state.validators[5].activation_eligibility_epoch = 2
        state.validators[5].activation_epoch = FAR_FUTURE
    _assert_same_mutation(spec, state, "process_registry_updates")
    yield from ()


@with_all_phases
@spec_state_test
def test_slashings_differential(spec, state):
    epoch = spec.get_current_epoch(state)
    window = epoch + spec.EPOCHS_PER_SLASHINGS_VECTOR // 2
    for i in (0, 3):
        state.validators[i].slashed = True
        state.validators[i].withdrawable_epoch = window
    # one slashed validator OUTSIDE the window: must not be penalized
    state.validators[4].slashed = True
    state.validators[4].withdrawable_epoch = window + 1
    state.slashings[0] = spec.Gwei(3 * 10**9)
    state.slashings[1] = spec.Gwei(10**9)
    _assert_same_mutation(spec, state, "process_slashings")
    yield from ()


@with_all_phases
@spec_state_test
def test_effective_balance_updates_differential(spec, state):
    ebi = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    # downward past hysteresis, upward past hysteresis, and inside the band
    state.balances[0] = state.validators[0].effective_balance - ebi
    state.balances[1] = int(state.validators[1].effective_balance) + 2 * ebi
    state.validators[1].effective_balance = spec.Gwei(
        int(state.validators[1].effective_balance) - 2 * ebi
    )
    state.balances[2] = int(state.validators[2].effective_balance) + ebi // 8
    _assert_same_mutation(spec, state, "process_effective_balance_updates")
    yield from ()


@with_all_phases
@spec_state_test
def test_active_accessors_differential(spec, state):
    # mix of exited / future-activation / slashed validators
    epoch = spec.get_current_epoch(state)
    state.validators[0].exit_epoch = epoch  # no longer active
    state.validators[1].activation_epoch = epoch + 2  # not yet active
    state.validators[2].slashed = True

    vec_idx = spec.get_active_validator_indices(state, epoch)
    seq_idx = unwrap(spec.get_active_validator_indices)(state, epoch)
    assert [int(i) for i in vec_idx] == [int(i) for i in seq_idx]

    vec_total = spec.get_total_active_balance(state)
    seq_total = unwrap(spec.get_total_active_balance)(state)
    assert int(vec_total) == int(seq_total)
    yield from ()


@with_phases(["phase0"])
@spec_state_test
def test_attesting_balance_differential(spec, state):
    prepare_state_with_attestations(
        spec, state,
        participation_fn=lambda slot, index, comm: set(list(comm)[::2]),
    )
    state.validators[0].slashed = True  # unslashed filter must apply
    atts = spec.get_matching_target_attestations(
        state, spec.get_previous_epoch(state)
    )
    vec = spec.get_attesting_balance(state, atts)
    seq = unwrap(spec.get_attesting_balance)(state, atts)
    assert int(vec) == int(seq)
    yield from ()


@with_phases(["phase0"])
@spec_state_test
def test_full_epoch_differential_after_activity(spec, state):
    """Several epochs of activity, then one full process_epoch through both
    pipelines — the integration check over every substitution at once."""
    next_epoch(spec, state)
    prepare_state_with_attestations(spec, state)
    # ejection-eligible validator (effective balance does not perturb the
    # already-built attestations' committees, unlike activation changes)
    state.validators[2].effective_balance = spec.config.EJECTION_BALANCE

    vec_state = state.copy()
    seq_state = state.copy()
    spec.process_epoch(vec_state)
    g = spec.__dict__
    names = (
        "process_rewards_and_penalties", "process_registry_updates",
        "process_slashings", "process_effective_balance_updates",
    )
    saved = {k: g[k] for k in names}
    try:
        for k in names:
            g[k] = unwrap(saved[k])
        spec.process_epoch(seq_state)
    finally:
        g.update(saved)
    assert vec_state.hash_tree_root() == seq_state.hash_tree_root()
    yield from ()
