"""Fork-choice tests (reference: test/phase0/fork_choice/test_on_block.py,
test_get_head.py — representative subset)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_all_phases,
    with_presets,
)
from consensus_specs_tpu.testing.helpers.attestations import (
    get_valid_attestation,
    next_epoch_with_attestations,
)
from consensus_specs_tpu.testing.helpers.block import (
    build_empty_block_for_next_slot,
)
from consensus_specs_tpu.testing.helpers.constants import MINIMAL
from consensus_specs_tpu.testing.helpers.fork_choice import (
    add_attestation,
    add_block,
    apply_next_epoch_with_attestations,
    get_anchor_root,
    get_genesis_forkchoice_store,
    get_genesis_forkchoice_store_and_block,
    on_tick_and_append_step,
    tick_and_add_block,
    tick_and_run_on_attestation,
)
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    state_transition_and_sign_block,
)


@with_all_phases
@spec_state_test
def test_genesis_head(spec, state):
    test_steps = []
    store = get_genesis_forkchoice_store(spec, state)
    anchor_root = get_anchor_root(spec, state)
    assert spec.get_head(store) == anchor_root
    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
def test_on_block_checks(spec, state):
    test_steps = []
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)
    yield "anchor_state", state
    yield "anchor_block", anchor_block

    # On receiving a block of `GENESIS_SLOT + 1` slot
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield from tick_and_add_block(spec, store, signed_block, test_steps)
    assert spec.get_head(store) == signed_block.message.hash_tree_root()

    # block from the future is not added
    future_block = build_empty_block_for_next_slot(spec, state)
    future_signed = state_transition_and_sign_block(spec, state.copy(), future_block)
    # do NOT tick forward: current slot < block slot
    yield from add_block(spec, store, future_signed, test_steps, valid=False)

    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
def test_on_attestation_updates_latest_messages(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    test_steps = []

    # advance a slot with a block, then attest to it
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield from tick_and_add_block(spec, store, signed_block, test_steps)

    attestation = get_valid_attestation(spec, state, slot=block.slot, signed=True)
    yield from tick_and_run_on_attestation(spec, store, attestation, test_steps)

    attesting = spec.get_attesting_indices(state, attestation.data, attestation.aggregation_bits)
    for i in attesting:
        assert i in store.latest_messages
        assert store.latest_messages[i].root == attestation.data.beacon_block_root
    yield "steps", "data", test_steps


@with_all_phases
@with_presets([MINIMAL], reason="epoch-long walks; too slow at mainnet size")
@spec_state_test
def test_on_block_finalization_updates(spec, state):
    """Full epochs with attestations drive justification+finality into the store."""
    test_steps = []
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)
    yield "anchor_state", state
    yield "anchor_block", anchor_block
    current_time = state.genesis_time + spec.config.SECONDS_PER_SLOT
    on_tick_and_append_step(spec, store, current_time, test_steps)

    next_epoch(spec, state)
    on_tick_and_append_step(
        spec, store,
        store.genesis_time + state.slot * spec.config.SECONDS_PER_SLOT, test_steps)

    for _ in range(4):
        state, store, _ = yield from apply_next_epoch_with_attestations(
            spec, state, store, True, True, test_steps=test_steps)

    assert store.finalized_checkpoint.epoch > 0
    assert store.justified_checkpoint.epoch > store.finalized_checkpoint.epoch

    yield "steps", "data", test_steps
