"""Fork-choice tests (reference: test/phase0/fork_choice/test_on_block.py,
test_get_head.py — representative subset)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_all_phases,
    with_presets,
)
from consensus_specs_tpu.testing.helpers.attestations import (
    get_valid_attestation,
)
from consensus_specs_tpu.testing.helpers.block import (
    build_empty_block_for_next_slot,
)
from consensus_specs_tpu.testing.helpers.attester_slashings import (
    get_valid_attester_slashing_by_indices,
)
from consensus_specs_tpu.testing.helpers.constants import MINIMAL
from consensus_specs_tpu.testing.helpers.fork_choice import (
    add_attester_slashing,
    add_block,
    apply_next_epoch_with_attestations,
    get_anchor_root,
    get_genesis_forkchoice_store_and_block,
    on_tick_and_append_step,
    tick_and_add_block,
    tick_and_run_on_attestation,
)
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    state_transition_and_sign_block,
)


@with_all_phases
@spec_state_test
def test_genesis_head(spec, state):
    test_steps = []
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)
    yield "anchor_state", state
    yield "anchor_block", anchor_block
    anchor_root = get_anchor_root(spec, state)
    assert spec.get_head(store) == anchor_root
    test_steps.append({"checks": {
        "head": {"slot": int(state.slot), "root": "0x" + bytes(anchor_root).hex()},
    }})
    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
def test_on_block_checks(spec, state):
    test_steps = []
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)
    yield "anchor_state", state
    yield "anchor_block", anchor_block

    # On receiving a block of `GENESIS_SLOT + 1` slot
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield from tick_and_add_block(spec, store, signed_block, test_steps)
    assert spec.get_head(store) == signed_block.message.hash_tree_root()

    # block from the future is not added
    future_block = build_empty_block_for_next_slot(spec, state)
    future_signed = state_transition_and_sign_block(spec, state.copy(), future_block)
    # do NOT tick forward: current slot < block slot
    yield from add_block(spec, store, future_signed, test_steps, valid=False)

    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
def test_on_attestation_updates_latest_messages(spec, state):
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)
    test_steps = []
    yield "anchor_state", state
    yield "anchor_block", anchor_block

    # advance a slot with a block, then attest to it
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield from tick_and_add_block(spec, store, signed_block, test_steps)

    attestation = get_valid_attestation(spec, state, slot=block.slot, signed=True)
    yield from tick_and_run_on_attestation(spec, store, attestation, test_steps)

    attesting = spec.get_attesting_indices(state, attestation.data, attestation.aggregation_bits)
    for i in attesting:
        assert i in store.latest_messages
        assert store.latest_messages[i].root == attestation.data.beacon_block_root
    # trailing checks pin the attestation's head effect for vector replay
    head = spec.get_head(store)
    test_steps.append({"checks": {
        "head": {"slot": int(store.blocks[head].slot),
                 "root": "0x" + bytes(head).hex()},
        "time": int(store.time),
    }})
    yield "steps", "data", test_steps


@with_all_phases
@with_presets([MINIMAL], reason="epoch-long walks; too slow at mainnet size")
@spec_state_test
def test_on_block_finalization_updates(spec, state):
    """Full epochs with attestations drive justification+finality into the store."""
    test_steps = []
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)
    yield "anchor_state", state
    yield "anchor_block", anchor_block
    current_time = state.genesis_time + spec.config.SECONDS_PER_SLOT
    on_tick_and_append_step(spec, store, current_time, test_steps)

    next_epoch(spec, state)
    on_tick_and_append_step(
        spec, store,
        store.genesis_time + state.slot * spec.config.SECONDS_PER_SLOT, test_steps)

    for _ in range(4):
        state, store, _ = yield from apply_next_epoch_with_attestations(
            spec, state, store, True, True, test_steps=test_steps)

    assert store.finalized_checkpoint.epoch > 0
    assert store.justified_checkpoint.epoch > store.finalized_checkpoint.epoch

    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
def test_proposer_boost_wins_head(spec, state):
    """A timely block gets the proposer-score boost and outweighs an
    equal-weight sibling (reference scenario family:
    fork_choice/test_get_head.py proposer-boost cases)."""
    test_steps = []
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)
    yield "anchor_state", state
    yield "anchor_block", anchor_block

    # two competing blocks at the same slot from the same parent
    next_slot_state = state.copy()
    block_a = build_empty_block_for_next_slot(spec, next_slot_state)
    block_a.body.graffiti = b"\x11" * 32
    signed_a = state_transition_and_sign_block(spec, next_slot_state, block_a)

    state_b = state.copy()
    block_b = build_empty_block_for_next_slot(spec, state_b)
    block_b.body.graffiti = b"\x22" * 32
    signed_b = state_transition_and_sign_block(spec, state_b, block_b)

    # arrive early in the slot: block A lands first and earns the boost
    time = (store.genesis_time + int(block_a.slot) * spec.config.SECONDS_PER_SLOT
            + spec.config.SECONDS_PER_SLOT // spec.INTERVALS_PER_SLOT - 1)
    on_tick_and_append_step(spec, store, time, test_steps)
    yield from add_block(spec, store, signed_a, test_steps)
    assert store.proposer_boost_root == signed_a.message.hash_tree_root()
    # B arrives after the attesting interval: no boost for it
    late = (store.genesis_time + int(block_a.slot) * spec.config.SECONDS_PER_SLOT
            + spec.config.SECONDS_PER_SLOT // spec.INTERVALS_PER_SLOT + 1)
    on_tick_and_append_step(spec, store, late, test_steps)
    yield from add_block(spec, store, signed_b, test_steps)
    assert store.proposer_boost_root == signed_a.message.hash_tree_root()

    # boost breaks the tie in favor of A regardless of root ordering
    assert spec.get_head(store) == signed_a.message.hash_tree_root()
    yield "steps", test_steps


@with_all_phases
@spec_state_test
def test_proposer_boost_expires_next_slot(spec, state):
    """The boost is transient: after the next on_tick the sibling with the
    lexicographically-higher root wins the tie again."""
    test_steps = []
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)
    yield "anchor_state", state
    yield "anchor_block", anchor_block

    sa = state.copy()
    block_a = build_empty_block_for_next_slot(spec, sa)
    block_a.body.graffiti = b"\x11" * 32
    signed_a = state_transition_and_sign_block(spec, sa, block_a)
    sb = state.copy()
    block_b = build_empty_block_for_next_slot(spec, sb)
    block_b.body.graffiti = b"\x22" * 32
    signed_b = state_transition_and_sign_block(spec, sb, block_b)

    time = (store.genesis_time + int(block_a.slot) * spec.config.SECONDS_PER_SLOT
            + spec.config.SECONDS_PER_SLOT // spec.INTERVALS_PER_SLOT - 1)
    on_tick_and_append_step(spec, store, time, test_steps)
    yield from add_block(spec, store, signed_a, test_steps)
    yield from add_block(spec, store, signed_b, test_steps)

    # move into the next slot: boost resets
    on_tick_and_append_step(
        spec, store,
        store.genesis_time + (int(block_a.slot) + 1) * spec.config.SECONDS_PER_SLOT,
        test_steps)
    assert store.proposer_boost_root == spec.Root()
    expected = max(
        signed_a.message.hash_tree_root(), signed_b.message.hash_tree_root())
    assert spec.get_head(store) == expected
    yield "steps", test_steps


@with_all_phases
@spec_state_test
def test_ex_ante_attestation_flips_head(spec, state):
    """Ex-ante reorg scenario: a sibling that arrives late but carries an
    attestation outweighs the boosted-but-unattested first block once the
    boost expires (reference family: test_ex_ante.py)."""
    test_steps = []
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)
    yield "anchor_state", state
    yield "anchor_block", anchor_block

    sa = state.copy()
    block_a = build_empty_block_for_next_slot(spec, sa)
    block_a.body.graffiti = b"\x11" * 32
    signed_a = state_transition_and_sign_block(spec, sa, block_a)
    sb = state.copy()
    block_b = build_empty_block_for_next_slot(spec, sb)
    block_b.body.graffiti = b"\x22" * 32
    signed_b = state_transition_and_sign_block(spec, sb, block_b)

    time = store.genesis_time + (int(block_a.slot) + 1) * spec.config.SECONDS_PER_SLOT
    on_tick_and_append_step(spec, store, time, test_steps)
    yield from add_block(spec, store, signed_a, test_steps)
    yield from add_block(spec, store, signed_b, test_steps)

    weaker = min(signed_a, signed_b, key=lambda s: s.message.hash_tree_root())
    weaker_state = sa if weaker is signed_a else sb
    # an attestation for the tie-losing block flips the head to it
    attestation = get_valid_attestation(
        spec, weaker_state, slot=weaker.message.slot, signed=True)
    next_time = time + spec.config.SECONDS_PER_SLOT
    on_tick_and_append_step(spec, store, next_time, test_steps)
    yield from tick_and_run_on_attestation(spec, store, attestation, test_steps)
    assert spec.get_head(store) == weaker.message.hash_tree_root()
    yield "steps", test_steps


@with_all_phases
@spec_state_test
def test_on_attester_slashing_discounts_equivocators(spec, state):
    """Fork-choice handler on_attester_slashing: equivocating indices are
    recorded AND their latest messages stop counting toward head weight —
    the attestation-flipped head reverts once its attesters equivocate
    (reference family: test_on_attester_slashing.py)."""
    test_steps = []
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)
    yield "anchor_state", state
    yield "anchor_block", anchor_block

    # two sibling blocks; no boost (tick is past the slot)
    forks = []
    for graffiti in (b"\x41" * 32, b"\x42" * 32):
        fork_state = state.copy()
        block = build_empty_block_for_next_slot(spec, fork_state)
        block.body.graffiti = graffiti
        forks.append(
            (state_transition_and_sign_block(spec, fork_state, block), fork_state))
    time = store.genesis_time + \
        (int(forks[0][0].message.slot) + 1) * spec.config.SECONDS_PER_SLOT
    on_tick_and_append_step(spec, store, time, test_steps)
    for signed, _ in forks:
        yield from add_block(spec, store, signed, test_steps)

    strong = max(s.message.hash_tree_root() for s, _ in forks)
    weaker, weaker_state = min(forks, key=lambda f: f[0].message.hash_tree_root())
    assert spec.get_head(store) == strong

    # one committee attests the tie-losing sibling: head flips to it
    attestation = get_valid_attestation(
        spec, weaker_state, slot=weaker.message.slot, signed=True)
    yield from tick_and_run_on_attestation(spec, store, attestation, test_steps)
    assert spec.get_head(store) == weaker.message.hash_tree_root()

    # slash exactly those attesters: their latest messages stop counting
    attesters = sorted(int(i) for i in spec.get_attesting_indices(
        weaker_state, attestation.data, attestation.aggregation_bits))
    slashing = get_valid_attester_slashing_by_indices(
        spec, state, attesters, signed_1=True, signed_2=True)
    yield from add_attester_slashing(spec, store, slashing, test_steps)
    for index in attesters:
        assert index in [int(i) for i in store.equivocating_indices]
    assert spec.get_head(store) == strong
    yield "steps", test_steps


@with_all_phases
@with_presets([MINIMAL], reason="epoch-long walks; too slow at mainnet size")
@spec_state_test
def test_justified_checkpoint_updates_via_blocks(spec, state):
    """Four epochs of full attestations through on_block update the
    store's justified and finalized checkpoints (reference family:
    test_on_block.py justification cases)."""
    test_steps = []
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)
    yield "anchor_state", state
    yield "anchor_block", anchor_block

    assert int(store.justified_checkpoint.epoch) == 0
    for round_ in range(4):
        state, store, _ = yield from apply_next_epoch_with_attestations(
            spec, state, store, True, round_ > 0, test_steps=test_steps)
    assert int(store.justified_checkpoint.epoch) > 0
    assert int(store.finalized_checkpoint.epoch) > 0
    # the head actually descends from the finalized checkpoint
    head = spec.get_head(store)
    finalized_slot = spec.compute_start_slot_at_epoch(
        store.finalized_checkpoint.epoch)
    assert spec.get_ancestor(store, head, finalized_slot) == \
        store.finalized_checkpoint.root
    yield "steps", test_steps


@with_all_phases
@spec_state_test
def test_get_head_tie_break_is_lexicographic(spec, state):
    """With equal weights and no boost, get_head picks the
    lexicographically greatest root (the spec's max() tie-break)."""
    test_steps = []
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)
    yield "anchor_state", state
    yield "anchor_block", anchor_block

    siblings = []
    for graffiti in (b"\x31" * 32, b"\x32" * 32):
        fork_state = state.copy()
        block = build_empty_block_for_next_slot(spec, fork_state)
        block.body.graffiti = graffiti
        siblings.append(state_transition_and_sign_block(spec, fork_state, block))

    # tick PAST the block slot so neither sibling gets the proposer boost
    time = store.genesis_time + \
        (int(siblings[0].message.slot) + 1) * spec.config.SECONDS_PER_SLOT
    on_tick_and_append_step(spec, store, time, test_steps)
    for signed in siblings:
        yield from add_block(spec, store, signed, test_steps)

    expected = max(s.message.hash_tree_root() for s in siblings)
    assert spec.get_head(store) == expected
    yield "steps", test_steps
