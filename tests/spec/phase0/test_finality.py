"""Finality rule suite: multi-epoch block-driven scenarios exercising the
four FFG finalization rules (spec: phase0/beacon-chain.md
weigh_justification_and_finalization; reference suite:
test/phase0/finality/test_finality.py)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.attestations import (
    next_epoch_with_attestations,
)
from consensus_specs_tpu.testing.helpers.state import next_epoch


def check_finality(spec, state, prev_state,
                   current_justified_changed,
                   previous_justified_changed,
                   finalized_changed):
    if current_justified_changed:
        assert state.current_justified_checkpoint.epoch > prev_state.current_justified_checkpoint.epoch
        assert state.current_justified_checkpoint.root != prev_state.current_justified_checkpoint.root
    else:
        assert state.current_justified_checkpoint == prev_state.current_justified_checkpoint
    if previous_justified_changed:
        assert state.previous_justified_checkpoint.epoch > prev_state.previous_justified_checkpoint.epoch
    else:
        assert state.previous_justified_checkpoint == prev_state.previous_justified_checkpoint
    if finalized_changed:
        assert state.finalized_checkpoint.epoch > prev_state.finalized_checkpoint.epoch
    else:
        assert state.finalized_checkpoint == prev_state.finalized_checkpoint


@with_phases(["phase0"])
@spec_state_test
def test_finality_no_updates_at_genesis(spec, state):
    assert spec.get_current_epoch(state) == spec.GENESIS_EPOCH
    yield "pre", state
    blocks = []
    for _ in range(2):
        prev_state, new_blocks, state = next_epoch_with_attestations(
            spec, state, True, False)
        blocks += new_blocks
        # FFG is frozen for the first two epochs
        check_finality(spec, state, prev_state, False, False, False)
    yield "blocks", blocks
    yield "post", state


@with_phases(["phase0"])
@spec_state_test
def test_finality_rule_4(spec, state):
    # two consecutive justified epochs: 2nd-newest finalizes (rule 4: 12)
    yield "pre", state
    blocks = []
    for epoch in range(4):
        prev_state, new_blocks, state = next_epoch_with_attestations(
            spec, state, True, False)
        blocks += new_blocks
        if epoch == 2:
            check_finality(spec, state, prev_state, True, False, False)
        elif epoch == 3:
            check_finality(spec, state, prev_state, True, True, True)
            assert state.finalized_checkpoint == prev_state.current_justified_checkpoint
    yield "blocks", blocks
    yield "post", state


@with_phases(["phase0"])
@spec_state_test
def test_finality_rule_1(spec, state):
    # previous-epoch attestations justify; rule 1 (234) finalizes
    next_epoch(spec, state)
    next_epoch(spec, state)
    yield "pre", state
    blocks = []
    for epoch in range(3):
        prev_state, new_blocks, state = next_epoch_with_attestations(
            spec, state, False, True)
        blocks += new_blocks
        if epoch == 2:
            check_finality(spec, state, prev_state, True, True, True)
            assert state.finalized_checkpoint == prev_state.previous_justified_checkpoint
    yield "blocks", blocks
    yield "post", state


@with_phases(["phase0"])
@spec_state_test
def test_finality_rule_2(spec, state):
    # justify with previous-epoch attestations only after a skipped epoch
    next_epoch(spec, state)
    next_epoch(spec, state)
    yield "pre", state
    blocks = []
    for epoch in range(3):
        if epoch == 0:
            prev_state, new_blocks, state = next_epoch_with_attestations(
                spec, state, True, False)
            check_finality(spec, state, prev_state, True, False, False)
        elif epoch == 1:
            prev_state, new_blocks, state = next_epoch_with_attestations(
                spec, state, False, False)
            check_finality(spec, state, prev_state, False, True, False)
        elif epoch == 2:
            prev_state, new_blocks, state = next_epoch_with_attestations(
                spec, state, False, True)
            # rule 2 (23): previous justified finalizes over the gap;
            # previous_justified itself was already rotated during the
            # attestation-free epoch, so it does not move again here
            check_finality(spec, state, prev_state, True, False, True)
        blocks += new_blocks
    yield "blocks", blocks
    yield "post", state


@with_phases(["phase0"])
@spec_state_test
def test_no_finality_without_justification(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    yield "pre", state
    blocks = []
    for _ in range(2):
        prev_state, new_blocks, state = next_epoch_with_attestations(
            spec, state, False, False)
        blocks += new_blocks
        check_finality(spec, state, prev_state, False, False, False)
    yield "blocks", blocks
    yield "post", state
