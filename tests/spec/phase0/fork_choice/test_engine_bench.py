"""The ≥100k-attestation ingest benchmark, pytest-side (slow tier).

Drives the same corpus builder as bench.py's ``forkchoice_batch_ingest``
row at a reduced registry (32k validators; the bench row runs 400k) but
the full ≥100k-attestation load: one epoch of unaggregated single-bit
attestations tiled to the target count, ingested by the per-attestation
spec loop and by the engine's batched path, asserting head + latest-
message parity.  The tier-1 differential suite pins correctness on small
scenarios; this pins it — plus the batched-path speedup — at traffic
scale.  (Re-delivered attestations are ignored by both paths per the
strict-epoch rule, so the tiling changes load, not semantics.)
"""
import time

import pytest

pytestmark = pytest.mark.slow

N_VALIDATORS = 32_768
N_ATTESTATIONS = 100_000


def test_engine_ingest_100k_attestations_head_parity():
    import bench
    from consensus_specs_tpu.crypto import bls
    from consensus_specs_tpu.specs.builder import get_spec

    spec = get_spec("phase0", "mainnet")
    was_active = bls.bls_active
    bls.bls_active = False
    try:
        state = bench.build_state(spec, N_VALIDATORS)
        store_seq, engine, atts, _ = bench.build_forkchoice_ingest_inputs(
            spec, state, N_ATTESTATIONS)
        while len(atts) < N_ATTESTATIONS:
            atts = atts + atts[:N_ATTESTATIONS - len(atts)]
        assert len(atts) >= N_ATTESTATIONS

        t0 = time.perf_counter()
        for att in atts:
            spec.on_attestation(store_seq, att)
        t_seq = time.perf_counter() - t0

        t0 = time.perf_counter()
        engine.on_attestations(atts)
        t_batch = time.perf_counter() - t0

        assert bytes(engine.get_head()) == bytes(spec.get_head(store_seq))
        assert engine.store.latest_messages == store_seq.latest_messages
        # the hard ≥10x gate lives in bench.py (dedicated, uncontended
        # runs); a pytest worker sharing the host still must see a
        # decisive win or the batched path has regressed badly
        assert t_batch * 3 < t_seq, (
            f"batched ingest {t_batch:.2f}s vs spec loop {t_seq:.2f}s")
    finally:
        bls.bls_active = was_active
