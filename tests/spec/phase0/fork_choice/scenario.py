"""Shared scaffolding for step-scripted fork-choice scenarios.

Every adversarial case in this package is "build a small block DAG off a
common base, deliver pieces in a chosen order, assert the head after each
delivery".  The builders here keep the per-test bodies down to the
scenario script itself (reference capability: the repeated inline setup of
test/phase0/fork_choice/test_ex_ante.py et al.).
"""
from consensus_specs_tpu.testing.helpers.attestations import (
    get_valid_attestation,
    sign_attestation,
)
from consensus_specs_tpu.testing.helpers.block import build_empty_block
from consensus_specs_tpu.testing.helpers.fork_choice import (
    get_genesis_forkchoice_store_and_block,
    on_tick_and_append_step,
)
from consensus_specs_tpu.testing.helpers.state import (
    state_transition_and_sign_block,
)


def slot_time(spec, store, slot) -> int:
    return int(store.genesis_time) + int(slot) * int(spec.config.SECONDS_PER_SLOT)


def begin_forkchoice(spec, state, test_steps):
    """Yield anchor parts, tick to the anchor's wall time, return the store."""
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)
    yield "anchor_state", state
    yield "anchor_block", anchor_block
    on_tick_and_append_step(spec, store, slot_time(spec, store, state.slot), test_steps)
    assert store.time == slot_time(spec, store, state.slot)
    return store


def make_branch_block(spec, base_state, slot):
    """(signed block, its post-state) at ``slot`` branching off ``base_state``."""
    post = base_state.copy()
    block = build_empty_block(spec, post, slot=slot)
    return state_transition_and_sign_block(spec, post, block), post


def head_of(spec, store):
    return spec.get_head(store)


def root_of(signed_block):
    return signed_block.message.hash_tree_root()


def vote_for(spec, state, signed_block, participants=1):
    """An attestation at ``state.slot`` by the first ``participants``
    committee members, pointed at ``signed_block``."""
    attestation = get_valid_attestation(
        spec, state, slot=state.slot, signed=False,
        filter_participant_set=lambda comm: set(sorted(comm)[:participants]))
    attestation.data.beacon_block_root = root_of(signed_block)
    assert sum(1 for bit in attestation.aggregation_bits if bit) == participants
    sign_attestation(spec, state, attestation)
    return attestation


def min_attesters_to_beat_boost(spec, store, state, boosted_root, target_root):
    """Smallest single-slot attester count whose LMD weight exceeds the
    proposer boost credited to ``boosted_root`` (all balances equal in the
    mock registry, so weight = count * effective balance)."""
    block = store.blocks[target_root]
    boost_score = 0
    if spec.get_ancestor(store, target_root, block.slot) == boosted_root:
        active = len(spec.get_active_validator_indices(state, spec.get_current_epoch(state)))
        avg_balance = spec.get_total_active_balance(state) // active
        committee_weight = (active // spec.SLOTS_PER_EPOCH) * avg_balance
        boost_score = committee_weight * spec.config.PROPOSER_SCORE_BOOST // 100
    return int(boost_score // state.validators[0].effective_balance) + 1
