"""Differential suite: the node serving pipeline vs the literal spec
``Store`` (ISSUE 12).

The engine differential suite pins the proto-array engine; this suite
pins the NODE — the same adversarial scenarios replayed with a
``Node``-backed mirror, so every helper-driven store mutation runs
through the engine-backed ``on_block`` (fork choice + batched stf
transition as one pipeline) with head + justified/finalized parity
asserted after every step.  What this adds over the engine suite: the
spec-handler reimplementation in ``node/service.py``
(``engine_backed_on_block``) is held to the spec's exact accept/reject
behavior — boost timing, finality-descendant checks, future-block
rejection — across every scenario in the get_head / ex_ante / on_block
suites, plus a finalizing multi-epoch chain (justified refresh + prune
through the node path).

The full enumeration runs on phase0; altair replays a representative
subset (the node handler is fork-agnostic — the stf engine owns the
fork dispatch, and the engine suite already drives both phases through
the identical mirror machinery — so the altair leg guards the
composition, not the scenarios; tier-1 stays within budget).
"""
import pytest

from consensus_specs_tpu.node import Node
from consensus_specs_tpu.testing.context import spec_state_test, with_phases
from consensus_specs_tpu.testing.helpers.fork_choice import (
    apply_next_epoch_with_attestations,
    assert_engine_parity,
    engine_mode,
    get_genesis_forkchoice_store_and_block,
    on_tick_and_append_step,
)
from consensus_specs_tpu.testing.helpers.state import next_epoch

from . import test_ex_ante as _ex_ante
from . import test_get_head as _get_head
from . import test_on_block as _on_block
from .scenario import slot_time


def _node_mirror(spec, genesis_state, anchor):
    """The shadow: a full Node (engine-backed on_block, journal off —
    scenario replays don't need the parity script)."""
    return Node(spec, genesis_state, anchor, journal=False)


_REPLAY_CASES = [
    (mod, name)
    for mod in (_get_head, _ex_ante, _on_block)
    for name in sorted(dir(mod))
    if name.startswith("test_")
]

# altair spot set: one scenario per suite, covering a head walk under
# votes, an ex-ante boost interaction, and an on_block reject path
_ALTAIR_SPOT = {"test_shorter_chain_but_heavier_weight",
                "test_ex_ante_vanilla",
                "test_on_block_future_block"}


@pytest.mark.parametrize(
    "mod,name", _REPLAY_CASES,
    ids=[f"{m.__name__.rsplit('.', 1)[-1]}::{n}" for m, n in _REPLAY_CASES])
def test_replay_scenario_through_node(mod, name):
    """Re-run an existing adversarial fork-choice scenario with a Node
    mirror attached: every handler call replays through the node's
    single-writer surface (engine-backed on_block included) expecting
    the same validity verdict, with parity asserted after each step."""
    with engine_mode(mirror_factory=_node_mirror):
        getattr(mod, name)(phase="phase0", bls_active=False)


@pytest.mark.parametrize("name", sorted(_ALTAIR_SPOT))
def test_replay_altair_scenario_through_node(name):
    mod = next(m for m, n in _REPLAY_CASES if n == name)
    with engine_mode(mirror_factory=_node_mirror):
        getattr(mod, name)(phase="altair", bls_active=False)


@with_phases(["phase0"])
@spec_state_test
def test_node_finalizing_chain(spec, state):
    """Full-participation epochs through the node until finalization
    advances: the engine-backed on_block carries justified refresh,
    finalized movement, and the proto-array prune, with per-step parity
    (the helpers assert it after every handler call)."""
    test_steps = []
    with engine_mode(mirror_factory=_node_mirror):
        store, _anchor = get_genesis_forkchoice_store_and_block(
            spec, state.copy())
        next_epoch(spec, state)
        on_tick_and_append_step(
            spec, store, slot_time(spec, store, state.slot), test_steps)
        for _ in range(3):
            state, store, _last = yield from \
                apply_next_epoch_with_attestations(
                    spec, state, store, True, True, test_steps=test_steps)
            assert_engine_parity(spec, store)
        assert store.finalized_checkpoint.epoch > 0
    yield "steps", "data", test_steps


@with_phases(["phase0"])
@spec_state_test
def test_node_on_block_stf_stats_engaged(spec, state):
    """The composition proof at unit scale: a block applied through
    ``Node.on_block`` lands in ``stf.stats`` as a fast block, not a
    literal replay (the acceptance bar the firehose holds at 100k
    scale)."""
    from consensus_specs_tpu import stf
    from consensus_specs_tpu.testing.helpers.block import build_empty_block
    from consensus_specs_tpu.testing.helpers.state import (
        state_transition_and_sign_block,
    )

    anchor = state.copy()
    block = build_empty_block(spec, state, slot=int(state.slot) + 1)
    signed = state_transition_and_sign_block(spec, state, block)

    node = Node(spec, anchor)
    stf.reset_stats()
    node.on_tick(int(anchor.genesis_time)
                 + (int(block.slot) + 1) * int(spec.config.SECONDS_PER_SLOT))
    node.on_block(signed)
    assert stf.stats["fast_blocks"] == 1
    assert stf.stats["replayed_blocks"] == 0
    assert bytes(node.get_head()) == bytes(block.hash_tree_root())
    yield None
