"""Differential suite: the node serving pipeline vs the literal spec
``Store`` (ISSUE 12).

The engine differential suite pins the proto-array engine; this suite
pins the NODE — the same adversarial scenarios replayed with a
``Node``-backed mirror, so every helper-driven store mutation runs
through the engine-backed ``on_block`` (fork choice + batched stf
transition as one pipeline) with head + justified/finalized parity
asserted after every step.  What this adds over the engine suite: the
spec-handler reimplementation in ``node/service.py``
(``engine_backed_on_block``) is held to the spec's exact accept/reject
behavior — boost timing, finality-descendant checks, future-block
rejection — across every scenario in the get_head / ex_ante / on_block
suites, plus a finalizing multi-epoch chain (justified refresh + prune
through the node path).

The full enumeration runs on phase0; altair replays a representative
subset (the node handler is fork-agnostic — the stf engine owns the
fork dispatch, and the engine suite already drives both phases through
the identical mirror machinery — so the altair leg guards the
composition, not the scenarios; tier-1 stays within budget).
"""
import pytest

from consensus_specs_tpu.node import Node
from consensus_specs_tpu.testing.context import spec_state_test, with_phases
from consensus_specs_tpu.testing.helpers.fork_choice import (
    apply_next_epoch_with_attestations,
    assert_engine_parity,
    engine_mode,
    get_genesis_forkchoice_store_and_block,
    on_tick_and_append_step,
)
from consensus_specs_tpu.testing.helpers.state import next_epoch

from . import test_ex_ante as _ex_ante
from . import test_get_head as _get_head
from . import test_on_block as _on_block
from .scenario import slot_time


def _node_mirror(spec, genesis_state, anchor):
    """The shadow: a full Node (engine-backed on_block, journal off —
    scenario replays don't need the parity script)."""
    return Node(spec, genesis_state, anchor, journal=False)


_REPLAY_CASES = [
    (mod, name)
    for mod in (_get_head, _ex_ante, _on_block)
    for name in sorted(dir(mod))
    if name.startswith("test_")
]

# altair spot set: one scenario per suite, covering a head walk under
# votes, an ex-ante boost interaction, and an on_block reject path
_ALTAIR_SPOT = {"test_shorter_chain_but_heavier_weight",
                "test_ex_ante_vanilla",
                "test_on_block_future_block"}


@pytest.mark.parametrize(
    "mod,name", _REPLAY_CASES,
    ids=[f"{m.__name__.rsplit('.', 1)[-1]}::{n}" for m, n in _REPLAY_CASES])
def test_replay_scenario_through_node(mod, name):
    """Re-run an existing adversarial fork-choice scenario with a Node
    mirror attached: every handler call replays through the node's
    single-writer surface (engine-backed on_block included) expecting
    the same validity verdict, with parity asserted after each step."""
    with engine_mode(mirror_factory=_node_mirror):
        getattr(mod, name)(phase="phase0", bls_active=False)


@pytest.mark.parametrize("name", sorted(_ALTAIR_SPOT))
def test_replay_altair_scenario_through_node(name):
    mod = next(m for m, n in _REPLAY_CASES if n == name)
    with engine_mode(mirror_factory=_node_mirror):
        getattr(mod, name)(phase="altair", bls_active=False)


@with_phases(["phase0"])
@spec_state_test
def test_node_finalizing_chain(spec, state):
    """Full-participation epochs through the node until finalization
    advances: the engine-backed on_block carries justified refresh,
    finalized movement, and the proto-array prune, with per-step parity
    (the helpers assert it after every handler call)."""
    test_steps = []
    with engine_mode(mirror_factory=_node_mirror):
        store, _anchor = get_genesis_forkchoice_store_and_block(
            spec, state.copy())
        next_epoch(spec, state)
        on_tick_and_append_step(
            spec, store, slot_time(spec, store, state.slot), test_steps)
        for _ in range(3):
            state, store, _last = yield from \
                apply_next_epoch_with_attestations(
                    spec, state, store, True, True, test_steps=test_steps)
            assert_engine_parity(spec, store)
        assert store.finalized_checkpoint.epoch > 0
    yield "steps", "data", test_steps


# -- orphan-pool differential (ISSUE 13 satellite): out-of-order delivery
# through the Node's admission gate converges to the in-order literal
# spec store — the pool changes WHEN a block applies, never WHAT the
# store ends up holding


def _literal_in_order(spec, state, anchor, chain, final_time):
    """The reference: a literal spec store, clock advanced first (same
    arrival times as the node leg), blocks applied in chain order."""
    ref = spec.get_forkchoice_store(state, anchor)
    spec.on_tick(ref, final_time)
    for sb in chain:
        spec.on_block(ref, sb)
    return ref


def _delivery_case(build_delivery):
    """Shared scaffold: one minimal epoch of full blocks; the node leg
    delivers per ``build_delivery``, the reference leg applies in order;
    head + checkpoint parity is byte-exact."""
    from consensus_specs_tpu.crypto import bls
    from consensus_specs_tpu.node import admission, firehose
    from consensus_specs_tpu.specs.builder import get_spec
    from consensus_specs_tpu.testing.context import (
        default_activation_threshold,
        default_balances,
    )
    from consensus_specs_tpu.testing.helpers.genesis import (
        create_genesis_state,
    )

    spec = get_spec("phase0", "minimal")
    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec))
    corpus = firehose.build_corpus(spec, state, n_epochs=1, gossip_target=8)
    was_active = bls.bls_active
    bls.bls_active = False  # unsigned corpus, both legs (the firehose shape)
    try:
        admission.reset_stats()
        node = Node(spec, state, corpus.anchor_block, retry_backoff_s=0.0)
        last = int(corpus.chain[-1].message.slot)
        final_time = (int(state.genesis_time)
                      + (last + 1) * int(spec.config.SECONDS_PER_SLOT))
        node.enqueue_tick(final_time)
        applied_chain = build_delivery(spec, node, corpus)
        node.queue.close()
        node.run_apply_loop()

        ref = _literal_in_order(spec, state, corpus.anchor_block,
                                applied_chain, final_time)
        assert bytes(node.get_head()) == bytes(spec.get_head(ref))
        head = bytes(node.get_head())
        assert bytes(node.store.block_states[head].hash_tree_root()) == \
            bytes(ref.block_states[head].hash_tree_root())
        assert node.store.justified_checkpoint == ref.justified_checkpoint
        assert node.store.finalized_checkpoint == ref.finalized_checkpoint
        return spec, node, corpus, admission
    finally:
        bls.bls_active = was_active


def test_node_child_before_parent_converges_to_in_order():
    """The whole epoch delivered in REVERSE: every block but the first
    orphans, then one cascade re-links the chain — end state identical
    to the literal spec fed in order."""
    def deliver(spec, node, corpus):
        for sb in reversed(corpus.chain):
            node.enqueue_block(sb)
        return corpus.chain

    _spec, _node, corpus, admission = _delivery_case(deliver)
    assert admission.stats["orphaned"] == len(corpus.chain) - 1
    assert admission.stats["orphans_relinked"] == len(corpus.chain) - 1


def test_node_duplicate_redelivery_converges_to_once_each():
    """Every block delivered twice (the second a fresh wire decode): the
    duplicates suppress at admission and the store matches the literal
    spec that saw each block once."""
    def deliver(spec, node, corpus):
        for sb in corpus.chain:
            node.enqueue_block(sb)
            node.enqueue_block(
                spec.SignedBeaconBlock.decode_bytes(sb.encode_bytes()))
        return corpus.chain

    _spec, _node, corpus, admission = _delivery_case(deliver)
    assert admission.stats["duplicates"] == len(corpus.chain)


def test_node_expired_orphan_converges_to_chain_without_it():
    """A child whose parent is withheld expires out of the pool; the
    node's store matches the literal spec that never saw the orphan (or
    its withheld parent) at all."""
    def deliver(spec, node, corpus):
        # withhold block 4; its child (block 5) orphans (the default
        # window is far wider than the corpus) and must expire below
        node.enqueue_block(corpus.chain[4])
        for sb in corpus.chain[:3]:
            node.enqueue_block(sb)
        return corpus.chain[:3]

    spec, node, corpus, admission = _delivery_case(deliver)
    assert admission.stats["orphaned"] == 1
    # housekeeping far past the window drops it
    admission.expire_orphans(int(corpus.chain[-1].message.slot)
                             + admission.ORPHAN_EXPIRY_SLOTS + 64)
    assert admission.stats["orphans_expired"] == 1
    assert admission.snapshot()["orphan_pool_depth"] == 0


@with_phases(["phase0"])
@spec_state_test
def test_node_on_block_stf_stats_engaged(spec, state):
    """The composition proof at unit scale: a block applied through
    ``Node.on_block`` lands in ``stf.stats`` as a fast block, not a
    literal replay (the acceptance bar the firehose holds at 100k
    scale)."""
    from consensus_specs_tpu import stf
    from consensus_specs_tpu.testing.helpers.block import build_empty_block
    from consensus_specs_tpu.testing.helpers.state import (
        state_transition_and_sign_block,
    )

    anchor = state.copy()
    block = build_empty_block(spec, state, slot=int(state.slot) + 1)
    signed = state_transition_and_sign_block(spec, state, block)

    node = Node(spec, anchor)
    stf.reset_stats()
    node.on_tick(int(anchor.genesis_time)
                 + (int(block.slot) + 1) * int(spec.config.SECONDS_PER_SLOT))
    node.on_block(signed)
    assert stf.stats["fast_blocks"] == 1
    assert stf.stats["replayed_blocks"] == 0
    assert bytes(node.get_head()) == bytes(block.hash_tree_root())
    yield None
