"""get_head scenarios: tie breaking, weight vs length, viability filtering,
proposer-boost lifecycle and equivocation discard (reference suite:
test/phase0/fork_choice/test_get_head.py)."""
import random

from consensus_specs_tpu.testing.context import (
    is_post_altair,
    spec_state_test,
    with_all_phases,
    with_presets,
)
from consensus_specs_tpu.testing.helpers.attestations import (
    get_valid_attestation,
    next_epoch_with_attestations,
)
from consensus_specs_tpu.testing.helpers.attester_slashings import (
    get_indexed_attestation_participants,
)
from consensus_specs_tpu.testing.helpers.block import (
    apply_empty_block,
    build_empty_block_for_next_slot,
)
from consensus_specs_tpu.testing.helpers.constants import MINIMAL
from consensus_specs_tpu.testing.helpers.fork_choice import (
    add_attestation,
    add_attester_slashing,
    add_block,
    get_anchor_root,
    get_formatted_head_output,
    on_tick_and_append_step,
    tick_and_add_block,
    tick_and_run_on_attestation,
)
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    next_slots,
    state_transition_and_sign_block,
)

from .scenario import begin_forkchoice, head_of, root_of, slot_time

_rng = random.Random(1001)


def _check_head(spec, store, test_steps):
    test_steps.append({"checks": {"head": get_formatted_head_output(spec, store)}})


def _distinct_block_with_larger_root(spec, base_state, other_root):
    """A next-slot block whose root exceeds ``other_root`` (graffiti-ground
    until the tie-breaker ordering is deterministic for the test)."""
    block = build_empty_block_for_next_slot(spec, base_state)
    signed = state_transition_and_sign_block(spec, base_state.copy(), block)
    while root_of(signed) <= other_root:
        block.body.graffiti = spec.Bytes32(
            _rng.getrandbits(256).to_bytes(32, "big"))
        signed = state_transition_and_sign_block(spec, base_state.copy(), block)
    return signed


@with_all_phases
@spec_state_test
def test_genesis(spec, state):
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)
    assert head_of(spec, store) == get_anchor_root(spec, state)
    test_steps.append({"checks": {
        "genesis_time": int(store.genesis_time),
        "head": get_formatted_head_output(spec, store),
    }})
    yield "steps", "data", test_steps
    if is_post_altair(spec):
        yield "description", "meta", \
            f"Although it's not phase 0, we may use {spec.fork} spec to start testnets."


@with_all_phases
@spec_state_test
def test_chain_no_attestations(spec, state):
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)
    _check_head(spec, store, test_steps)

    last = None
    for _ in range(2):
        block = build_empty_block_for_next_slot(spec, state)
        last = state_transition_and_sign_block(spec, state, block)
        yield from tick_and_add_block(spec, store, last, test_steps)

    assert head_of(spec, store) == root_of(last)
    _check_head(spec, store, test_steps)
    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
def test_split_tie_breaker_no_attestations(spec, state):
    test_steps = []
    genesis_state = state.copy()
    store = yield from begin_forkchoice(spec, state, test_steps)
    _check_head(spec, store, test_steps)

    # Two competing blocks in the same slot; no votes, no boost (delivered
    # a slot late), so lexicographically-largest root must win.
    side_a = genesis_state.copy()
    signed_a = state_transition_and_sign_block(
        spec, side_a, build_empty_block_for_next_slot(spec, side_a))
    side_b = genesis_state.copy()
    block_b = build_empty_block_for_next_slot(spec, side_b)
    block_b.body.graffiti = b"\x42" * 32
    signed_b = state_transition_and_sign_block(spec, side_b, block_b)

    on_tick_and_append_step(
        spec, store, slot_time(spec, store, signed_b.message.slot + 1), test_steps)
    yield from add_block(spec, store, signed_a, test_steps)
    yield from add_block(spec, store, signed_b, test_steps)

    assert head_of(spec, store) == max(root_of(signed_a), root_of(signed_b))
    _check_head(spec, store, test_steps)
    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
def test_shorter_chain_but_heavier_weight(spec, state):
    test_steps = []
    genesis_state = state.copy()
    store = yield from begin_forkchoice(spec, state, test_steps)
    _check_head(spec, store, test_steps)

    # Three-block chain vs a one-block fork.
    long_state = genesis_state.copy()
    long_signed = None
    for _ in range(3):
        long_signed = state_transition_and_sign_block(
            spec, long_state, build_empty_block_for_next_slot(spec, long_state))
        yield from tick_and_add_block(spec, store, long_signed, test_steps)

    short_state = genesis_state.copy()
    short_block = build_empty_block_for_next_slot(spec, short_state)
    short_block.body.graffiti = b"\x42" * 32
    short_signed = state_transition_and_sign_block(spec, short_state, short_block)
    yield from tick_and_add_block(spec, store, short_signed, test_steps)
    assert head_of(spec, store) == root_of(long_signed)

    # One attestation on the short fork outweighs the longer empty chain.
    short_vote = get_valid_attestation(
        spec, short_state, short_block.slot, signed=True)
    yield from tick_and_run_on_attestation(spec, store, short_vote, test_steps)
    assert head_of(spec, store) == root_of(short_signed)
    _check_head(spec, store, test_steps)
    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
@with_presets([MINIMAL], reason="too slow")
def test_filtered_block_tree(spec, state):
    """A branch carrying votes but descending from a non-viable (unjustified
    in its own chain) ancestor must be filtered out of the head walk
    (phase0/fork-choice.md filter_block_tree)."""
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)
    _check_head(spec, store, test_steps)

    # Justify an epoch on the honest branch.
    next_epoch(spec, state)
    next_epoch(spec, state)
    prev_state, signed_blocks, state = next_epoch_with_attestations(
        spec, state, True, False)
    assert (state.current_justified_checkpoint.epoch
            > prev_state.current_justified_checkpoint.epoch)

    on_tick_and_append_step(
        spec, store, slot_time(spec, store, state.slot), test_steps)
    for signed_block in signed_blocks:
        yield from add_block(spec, store, signed_block, test_steps)
    assert store.justified_checkpoint == state.current_justified_checkpoint

    viable_head = root_of(signed_blocks[-1])
    assert head_of(spec, store) == viable_head
    test_steps.append({"checks": {
        "head": get_formatted_head_output(spec, store),
        "justified_checkpoint_root":
            "0x" + bytes(store.justified_checkpoint.root).hex(),
    }})

    # Rogue branch: grows from the justified block but never justifies it
    # on-chain, then soaks up a whole epoch of votes.
    rogue_state = store.block_states[store.justified_checkpoint.root].copy()
    for _ in range(3):
        next_epoch(spec, rogue_state)
    assert spec.get_current_epoch(rogue_state) > store.justified_checkpoint.epoch

    rogue_block = build_empty_block_for_next_slot(spec, rogue_state)
    signed_rogue = state_transition_and_sign_block(spec, rogue_state, rogue_block)

    next_epoch(spec, rogue_state)
    rogue_votes = []
    for offset in range(spec.SLOTS_PER_EPOCH):
        slot = rogue_block.slot + offset
        for index in range(spec.get_committee_count_per_slot(
                rogue_state, spec.compute_epoch_at_slot(slot))):
            rogue_votes.append(get_valid_attestation(
                spec, rogue_state, slot, index, signed=True))

    on_tick_and_append_step(
        spec, store,
        slot_time(spec, store, rogue_votes[-1].data.slot + 1), test_steps)
    yield from add_block(spec, store, signed_rogue, test_steps)
    for vote in rogue_votes:
        yield from tick_and_run_on_attestation(spec, store, vote, test_steps)

    # All those votes must not move the head off the viable branch.
    assert head_of(spec, store) == viable_head
    _check_head(spec, store, test_steps)
    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
def test_proposer_boost_correct_head(spec, state):
    """Boost wins the head only during the boosted slot; the next on_tick
    clears proposer_boost_root and the head reverts."""
    test_steps = []
    genesis_state = state.copy()
    store = yield from begin_forkchoice(spec, state, test_steps)
    _check_head(spec, store, test_steps)

    timely_state = genesis_state.copy()
    next_slots(spec, timely_state, 3)
    timely_block = build_empty_block_for_next_slot(spec, timely_state)
    signed_timely = state_transition_and_sign_block(spec, timely_state, timely_block)

    rival_state = genesis_state.copy()
    next_slots(spec, rival_state, 2)
    signed_rival = _distinct_block_with_larger_root(
        spec, rival_state, root_of(signed_timely))

    on_tick_and_append_step(
        spec, store, slot_time(spec, store, timely_block.slot), test_steps)
    yield from add_block(spec, store, signed_rival, test_steps)
    assert store.proposer_boost_root == spec.Root()
    assert head_of(spec, store) == root_of(signed_rival)

    yield from add_block(spec, store, signed_timely, test_steps)
    assert store.proposer_boost_root == root_of(signed_timely)
    assert head_of(spec, store) == root_of(signed_timely)

    on_tick_and_append_step(
        spec, store, slot_time(spec, store, timely_block.slot + 1), test_steps)
    assert store.proposer_boost_root == spec.Root()
    assert head_of(spec, store) == root_of(signed_rival)
    _check_head(spec, store, test_steps)
    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
def test_discard_equivocations(spec, state):
    """An attester slashing delivered to the store must erase the equivocating
    validators' latest messages from the head walk."""
    test_steps = []
    genesis_state = state.copy()
    store = yield from begin_forkchoice(spec, state, test_steps)
    _check_head(spec, store, test_steps)

    fork_state = genesis_state.copy()
    next_slots(spec, fork_state, 3)
    fork_block = build_empty_block_for_next_slot(spec, fork_state)
    signed_fork = state_transition_and_sign_block(spec, fork_state, fork_block)

    # Two slashable votes for the same target slot from the same committee.
    eqv_state = fork_state.copy()
    eqv_block = apply_empty_block(spec, eqv_state, eqv_state.slot + 1)
    vote_eqv = get_valid_attestation(spec, eqv_state, slot=eqv_block.slot, signed=True)
    next_slots(spec, fork_state, 1)
    vote = get_valid_attestation(spec, fork_state, slot=eqv_block.slot, signed=True)
    assert spec.is_slashable_attestation_data(vote.data, vote_eqv.data)
    slashing = spec.AttesterSlashing(
        attestation_1=spec.get_indexed_attestation(fork_state, vote),
        attestation_2=spec.get_indexed_attestation(eqv_state, vote_eqv))
    assert get_indexed_attestation_participants(spec, slashing.attestation_1)

    rival_state = genesis_state.copy()
    next_slots(spec, rival_state, 2)
    signed_rival = _distinct_block_with_larger_root(
        spec, rival_state, root_of(signed_fork))

    on_tick_and_append_step(
        spec, store, slot_time(spec, store, eqv_block.slot + 2), test_steps)
    yield from add_block(spec, store, signed_rival, test_steps)
    assert store.proposer_boost_root == spec.Root()
    assert head_of(spec, store) == root_of(signed_rival)

    yield from add_block(spec, store, signed_fork, test_steps)
    assert head_of(spec, store) == root_of(signed_rival)

    # The vote flips the head to the fork...
    yield from add_attestation(spec, store, vote, test_steps)
    assert head_of(spec, store) == root_of(signed_fork)

    # ...until the slashing discards those attesters' messages.
    yield from add_attester_slashing(spec, store, slashing, test_steps)
    assert head_of(spec, store) == root_of(signed_rival)
    _check_head(spec, store, test_steps)
    yield "steps", "data", test_steps
