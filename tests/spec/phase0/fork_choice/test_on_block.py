"""on_block handler scenarios: arrival-time rules, finalized-ancestry
checks, justified-checkpoint update policy (safe-slots window), and the
proposer-boost set/clear lifecycle (reference suite:
test/phase0/fork_choice/test_on_block.py)."""
import random

from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_all_phases,
    with_presets,
)
from consensus_specs_tpu.testing.helpers.attestations import (
    next_epoch_with_attestations,
    next_slots_with_attestations,
    state_transition_with_full_attestations_block,
    state_transition_with_full_block,
)
from consensus_specs_tpu.testing.helpers.block import (
    build_empty_block,
    build_empty_block_for_next_slot,
    sign_block,
    transition_unsigned_block,
)
from consensus_specs_tpu.testing.helpers.constants import MINIMAL
from consensus_specs_tpu.testing.helpers.fork_choice import (
    add_block,
    apply_next_epoch_with_attestations,
    apply_next_slots_with_attestations,
    on_tick_and_append_step,
    tick_and_add_block,
)
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    next_slots,
    state_transition_and_sign_block,
)

from .scenario import begin_forkchoice, head_of, root_of, slot_time

_rng = random.Random(2020)


def _drop_random_third(_slot, _index, indices):
    keep = len(indices) - len(indices) // 3
    assert len(indices) >= 3
    return _rng.sample(sorted(indices), keep)


def _tick_to_state_slot(spec, store, state, test_steps):
    on_tick_and_append_step(
        spec, store, slot_time(spec, store, state.slot), test_steps)


@with_all_phases
@spec_state_test
def test_basic(spec, state):
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)

    signed = state_transition_and_sign_block(
        spec, state, build_empty_block_for_next_slot(spec, state))
    yield from tick_and_add_block(spec, store, signed, test_steps)
    assert head_of(spec, store) == root_of(signed)

    # A whole-epoch gap before the next block is fine.  (The reference
    # mutates store.time directly here; we tick through the recorded-step
    # API so the emitted vector stays replayable by a step-only client.)
    signed = state_transition_and_sign_block(
        spec, state, build_empty_block(spec, state, state.slot + spec.SLOTS_PER_EPOCH))
    on_tick_and_append_step(
        spec, store, slot_time(spec, store, signed.message.slot), test_steps)
    yield from tick_and_add_block(spec, store, signed, test_steps)
    assert head_of(spec, store) == root_of(signed)

    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
@with_presets([MINIMAL], reason="too slow")
def test_on_block_checkpoints(spec, state):
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)

    next_epoch(spec, state)
    _tick_to_state_slot(spec, store, state, test_steps)
    state, store, last_signed = yield from apply_next_epoch_with_attestations(
        spec, state, store, True, False, test_steps=test_steps)
    last_root = root_of(last_signed)
    assert head_of(spec, store) == last_root

    next_epoch(spec, state)
    _tick_to_state_slot(spec, store, state, test_steps)

    # Pretend the last block's justified checkpoint got finalized, and show
    # a block built on that view is accepted.
    mocked = store.block_states[last_root].copy()
    mocked.finalized_checkpoint = mocked.current_justified_checkpoint.copy()
    signed = state_transition_and_sign_block(
        spec, mocked.copy(), build_empty_block_for_next_slot(spec, mocked))
    yield from tick_and_add_block(spec, store, signed, test_steps)
    assert head_of(spec, store) == root_of(signed)
    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
def test_on_block_future_block(spec, state):
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)
    # Clock stays at genesis: a block for the next slot is from the future
    # and must be rejected.
    signed = state_transition_and_sign_block(
        spec, state, build_empty_block_for_next_slot(spec, state))
    yield from add_block(spec, store, signed, test_steps, valid=False)
    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
def test_on_block_bad_parent_root(spec, state):
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)

    block = build_empty_block_for_next_slot(spec, state)
    transition_unsigned_block(spec, state, block)
    block.state_root = state.hash_tree_root()
    block.parent_root = b"\x45" * 32  # nonexistent parent
    signed = sign_block(spec, state, block)
    yield from add_block(spec, store, signed, test_steps, valid=False)
    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
@with_presets([MINIMAL], reason="too slow")
def test_on_block_before_finalized(spec, state):
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)
    stale_state = state.copy()

    for _ in range(4):
        state, store, _ = yield from apply_next_epoch_with_attestations(
            spec, state, store, True, False, test_steps=test_steps)
    assert store.finalized_checkpoint.epoch == 2

    # A genesis-descended block below the finalized slot must be rejected.
    block = build_empty_block_for_next_slot(spec, stale_state)
    block.body.graffiti = b"\x12" * 32
    signed = state_transition_and_sign_block(spec, stale_state, block)
    assert root_of(signed) not in store.blocks
    yield from tick_and_add_block(spec, store, signed, test_steps, valid=False)
    yield "steps", "data", test_steps


def _finalize_epoch_2_with_skipped_boundary(spec, state, store, test_steps):
    """Shared ladder: fill epoch 0 + first slot of epoch 1, skip one epoch
    (making the finalized epoch's start slot a skipped slot), fill two more
    epochs -> finalized epoch 2 whose start slot is empty."""
    state, store, _ = yield from apply_next_slots_with_attestations(
        spec, state, store, spec.SLOTS_PER_EPOCH, True, False, test_steps)
    next_slots(spec, state, spec.SLOTS_PER_EPOCH)
    target_state = state.copy()
    for _ in range(2):
        state, store, _ = yield from apply_next_epoch_with_attestations(
            spec, state, store, True, True, test_steps=test_steps)
    assert state.finalized_checkpoint.epoch == store.finalized_checkpoint.epoch == 2
    assert store.finalized_checkpoint.root == spec.get_block_root(state, 1) \
        == spec.get_block_root(state, 2)
    assert state.current_justified_checkpoint.epoch == store.justified_checkpoint.epoch == 3
    return state, target_state


@with_all_phases
@spec_state_test
@with_presets([MINIMAL], reason="too slow")
def test_on_block_finalized_skip_slots(spec, state):
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)
    state, target_state = yield from _finalize_epoch_2_with_skipped_boundary(
        spec, state, store, test_steps)

    # Build through the skipped slots ON the finalized chain: accepted.
    signed = state_transition_and_sign_block(
        spec, target_state, build_empty_block_for_next_slot(spec, target_state))
    yield from tick_and_add_block(spec, store, signed, test_steps)
    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
@with_presets([MINIMAL], reason="too slow")
def test_on_block_finalized_skip_slots_not_in_skip_chain(spec, state):
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)
    state, _ = yield from _finalize_epoch_2_with_skipped_boundary(
        spec, state, store, test_steps)

    # Build from the finalized root itself (one epoch BELOW the finalized
    # slot, since the boundary slot was skipped): must be rejected.
    stale = store.block_states[store.finalized_checkpoint.root].copy()
    assert stale.slot == spec.compute_start_slot_at_epoch(
        store.finalized_checkpoint.epoch - 1)
    signed = state_transition_and_sign_block(
        spec, stale, build_empty_block_for_next_slot(spec, stale))
    yield from tick_and_add_block(spec, store, signed, test_steps, valid=False)
    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
@with_presets([MINIMAL], reason="needs more pre-generated keys than mainnet config allows")
def test_on_block_update_justified_checkpoint_within_safe_slots(spec, state):
    """Inside SAFE_SLOTS_TO_UPDATE_JUSTIFIED, a block with a newer justified
    checkpoint updates store.justified_checkpoint immediately."""
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)

    for _ in range(2):
        next_epoch(spec, state)
    state, store, _ = yield from apply_next_epoch_with_attestations(
        spec, state, store, True, False, test_steps=test_steps)
    assert store.justified_checkpoint.epoch == 2
    for _ in range(2):
        next_epoch(spec, state)
    state, store, _ = yield from apply_next_epoch_with_attestations(
        spec, state, store, True, False,
        participation_fn=_drop_random_third, test_steps=test_steps)
    assert store.justified_checkpoint.epoch == 2

    next_epoch(spec, state)
    pre_finalized_epoch = int(state.finalized_checkpoint.epoch)

    signed = state_transition_with_full_block(spec, state, True, True)
    assert state.current_justified_checkpoint.epoch == 5
    assert state.current_justified_checkpoint.epoch > store.justified_checkpoint.epoch
    assert (spec.get_current_slot(store) % spec.SLOTS_PER_EPOCH
            < spec.SAFE_SLOTS_TO_UPDATE_JUSTIFIED)
    yield from tick_and_add_block(spec, store, signed, test_steps)

    assert store.justified_checkpoint.epoch == 5
    assert store.justified_checkpoint == state.current_justified_checkpoint
    assert int(store.finalized_checkpoint.epoch) == pre_finalized_epoch == 0
    yield "steps", "data", test_steps


@with_all_phases
@with_presets([MINIMAL], reason="assumes MAX_ATTESTATIONS >= 2/3 of an epoch")
@spec_state_test
def test_on_block_outside_safe_slots_but_finality(spec, state):
    """Outside the safe-slots window, the update still happens when the new
    justified checkpoint does not conflict (finality advanced)."""
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)

    next_epoch(spec, state)
    for _ in range(3):
        state, store, _ = yield from apply_next_epoch_with_attestations(
            spec, state, store, True, False, test_steps=test_steps)
    assert store.finalized_checkpoint.epoch == 2
    assert store.justified_checkpoint.epoch == 3

    for _ in range(3):
        next_epoch(spec, state)
    state, store, _ = yield from apply_next_epoch_with_attestations(
        spec, state, store, True, True, test_steps=test_steps)
    assert state.current_justified_checkpoint.epoch == 7

    state, store, _ = yield from apply_next_slots_with_attestations(
        spec, state, store, 5, True, True, test_steps)
    assert store.justified_checkpoint.epoch == 7

    # Block at epoch 9 slot 5 carrying the full backlog.
    next_epoch(spec, state)
    next_slots(spec, state, 4)
    signed = state_transition_with_full_attestations_block(spec, state, True, True)
    yield from tick_and_add_block(spec, store, signed, test_steps)
    assert store.justified_checkpoint.epoch == 7

    # Empty block late in epoch 10, past the safe window, advancing finality.
    next_epoch(spec, state)
    next_slots(spec, state, 4)
    signed = state_transition_and_sign_block(
        spec, state, build_empty_block_for_next_slot(spec, state))
    assert state.finalized_checkpoint.epoch == 7
    assert state.current_justified_checkpoint.epoch == 8
    if store.time < spec.compute_time_at_slot(state, signed.message.slot):
        on_tick_and_append_step(
            spec, store, slot_time(spec, store, signed.message.slot), test_steps)
    assert (spec.get_current_slot(store) % spec.SLOTS_PER_EPOCH
            >= spec.SAFE_SLOTS_TO_UPDATE_JUSTIFIED)
    yield from add_block(spec, store, signed, test_steps)

    assert store.finalized_checkpoint == state.finalized_checkpoint
    assert store.justified_checkpoint == state.current_justified_checkpoint
    yield "steps", "data", test_steps


@with_all_phases
@with_presets([MINIMAL], reason="assumes MAX_ATTESTATIONS >= 2/3 of an epoch")
@spec_state_test
def test_new_justified_is_later_than_store_justified(spec, state):
    """Three competing forks: one parks a later checkpoint in
    best_justified (outside safe slots), another later supersedes the
    store's justified checkpoint via finality."""
    fork_1 = state.copy()
    fork_3 = state.copy()
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)

    # Fork 1: justify epoch 3.
    next_epoch(spec, fork_1)
    fork_1, store, _ = yield from apply_next_epoch_with_attestations(
        spec, fork_1, store, False, True, test_steps=test_steps)
    fork_2 = fork_1.copy()
    assert spec.get_current_epoch(fork_2) == 2
    next_epoch(spec, fork_1)
    for _ in range(2):
        fork_1, store, _ = yield from apply_next_epoch_with_attestations(
            spec, fork_1, store, False, True, test_steps=test_steps)
    assert store.justified_checkpoint.epoch == 3
    assert store.finalized_checkpoint.epoch == 0

    # Fork 2: deliver a justified-epoch-5 block outside safe slots — only
    # best_justified_checkpoint moves.
    signed = state_transition_and_sign_block(
        spec, fork_2, build_empty_block_for_next_slot(spec, fork_2))
    yield from tick_and_add_block(spec, store, signed, test_steps)
    for _ in range(2):
        next_epoch(spec, fork_2)
    for _ in range(2):
        next_epoch(spec, fork_2)
        next_slots(spec, fork_2, 4)
        signed = state_transition_with_full_attestations_block(spec, fork_2, True, True)
        yield from tick_and_add_block(spec, store, signed, test_steps)
        assert fork_2.current_justified_checkpoint.epoch == 0
    next_epoch(spec, fork_2)
    next_slots(spec, fork_2, spec.SAFE_SLOTS_TO_UPDATE_JUSTIFIED + 2)
    signed = state_transition_with_full_attestations_block(spec, fork_2, True, True)
    assert fork_2.current_justified_checkpoint.epoch == 5
    on_tick_and_append_step(
        spec, store, slot_time(spec, store, fork_2.slot), test_steps)
    assert (spec.compute_slots_since_epoch_start(spec.get_current_slot(store))
            >= spec.SAFE_SLOTS_TO_UPDATE_JUSTIFIED)
    yield from add_block(spec, store, signed, test_steps)
    assert store.justified_checkpoint.epoch == 3
    assert store.best_justified_checkpoint.epoch == 5

    # Fork 3: finality-driven update replaces the store's justified
    # checkpoint with its own (later than 3, distinct from fork 2's).
    blocks = []
    for _ in range(3):
        next_epoch(spec, fork_3)
    _, signed_blocks, fork_3 = next_epoch_with_attestations(spec, fork_3, True, True)
    blocks += signed_blocks
    _, signed_blocks, fork_3 = next_slots_with_attestations(spec, fork_3, 5, True, True)
    blocks += signed_blocks.copy()
    for _ in range(2):
        next_epoch(spec, fork_3)
        next_slots(spec, fork_3, 4)
        blocks.append(state_transition_with_full_block(spec, fork_3, True, True).copy())
    assert fork_3.finalized_checkpoint.epoch == 3
    assert fork_3.current_justified_checkpoint.epoch == 4

    for signed_block in blocks:
        if store.time < spec.compute_time_at_slot(fork_2, signed_block.message.slot):
            on_tick_and_append_step(
                spec, store, slot_time(spec, store, signed_block.message.slot),
                test_steps)
        yield from add_block(spec, store, signed_block, test_steps)

    assert store.finalized_checkpoint == fork_3.finalized_checkpoint
    assert store.justified_checkpoint == fork_3.current_justified_checkpoint
    assert store.justified_checkpoint != store.best_justified_checkpoint
    assert store.best_justified_checkpoint == fork_2.current_justified_checkpoint
    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
@with_presets([MINIMAL], reason="too slow")
def test_new_finalized_slot_is_not_justified_checkpoint_ancestor(spec, state):
    """Competing fork finalizes an epoch whose boundary is NOT an ancestor
    of the store's justified root: both checkpoints must be replaced."""
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)

    next_epoch(spec, state)
    rival = state.copy()

    state, store, _ = yield from apply_next_epoch_with_attestations(
        spec, state, store, False, True, test_steps=test_steps)
    next_epoch(spec, state)
    for _ in range(2):
        state, store, _ = yield from apply_next_epoch_with_attestations(
            spec, state, store, False, True, test_steps=test_steps)
    assert store.finalized_checkpoint.epoch == 0
    assert store.justified_checkpoint.epoch == 3

    blocks = []
    for _ in range(3):
        _, signed_blocks, rival = next_epoch_with_attestations(spec, rival, True, True)
        blocks += signed_blocks
    assert rival.finalized_checkpoint.epoch == 2
    assert rival.current_justified_checkpoint.epoch == 3
    assert state.current_justified_checkpoint != rival.current_justified_checkpoint

    old_justified_root = store.justified_checkpoint.root
    for signed_block in blocks:  # no on_tick: arrivals are all "late"
        yield from add_block(spec, store, signed_block, test_steps)

    finalized_slot = spec.compute_start_slot_at_epoch(store.finalized_checkpoint.epoch)
    assert spec.get_ancestor(store, old_justified_root, finalized_slot) \
        != store.finalized_checkpoint.root
    assert store.finalized_checkpoint == rival.finalized_checkpoint
    assert store.justified_checkpoint == rival.current_justified_checkpoint
    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
@with_presets([MINIMAL], reason="too slow")
def test_new_finalized_slot_is_justified_checkpoint_ancestor(spec, state):
    """Competing fork finalizes a boundary that IS an ancestor of the
    store's justified root; justified updates via the non-conflict path."""
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)

    next_epoch(spec, state)
    state, store, _ = yield from apply_next_epoch_with_attestations(
        spec, state, store, False, True, test_steps=test_steps)
    state, store, _ = yield from apply_next_epoch_with_attestations(
        spec, state, store, True, False, test_steps=test_steps)
    next_epoch(spec, state)
    for _ in range(2):
        state, store, _ = yield from apply_next_epoch_with_attestations(
            spec, state, store, False, True, test_steps=test_steps)
    assert store.finalized_checkpoint.epoch == 2
    assert store.justified_checkpoint.epoch == 4

    rival = store.block_states[
        spec.get_block_root_at_slot(state, spec.compute_start_slot_at_epoch(3))].copy()
    blocks = []
    for _ in range(2):
        _, signed_blocks, rival = next_epoch_with_attestations(spec, rival, True, True)
        blocks += signed_blocks
    assert rival.finalized_checkpoint.epoch == 3
    assert rival.current_justified_checkpoint.epoch == 4

    old_justified_root = store.justified_checkpoint.root
    for signed_block in blocks:
        yield from tick_and_add_block(spec, store, signed_block, test_steps)

    finalized_slot = spec.compute_start_slot_at_epoch(store.finalized_checkpoint.epoch)
    assert spec.get_ancestor(store, old_justified_root, finalized_slot) \
        == store.finalized_checkpoint.root
    assert store.finalized_checkpoint == rival.finalized_checkpoint
    assert store.justified_checkpoint == rival.current_justified_checkpoint
    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
def test_proposer_boost(spec, state):
    """Boost is granted on arrival inside the attesting interval (at its
    edge and at its start) and cleared by the next-slot tick."""
    test_steps = []
    genesis_state = state.copy()
    store = yield from begin_forkchoice(spec, state, test_steps)

    state = genesis_state.copy()
    next_slots(spec, state, 3)
    interval = int(spec.config.SECONDS_PER_SLOT) // int(spec.INTERVALS_PER_SLOT)

    for arrival_offset in (interval - 1, 0):  # edge of interval, then start
        block = build_empty_block_for_next_slot(spec, state)
        signed = state_transition_and_sign_block(spec, state, block)
        on_tick_and_append_step(
            spec, store,
            slot_time(spec, store, block.slot) + arrival_offset, test_steps)
        yield from add_block(spec, store, signed, test_steps)
        assert store.proposer_boost_root == root_of(signed)
        assert spec.get_latest_attesting_balance(store, root_of(signed)) > 0

        on_tick_and_append_step(
            spec, store, slot_time(spec, store, block.slot + 1), test_steps)
        assert store.proposer_boost_root == spec.Root()
        assert spec.get_latest_attesting_balance(store, root_of(signed)) == 0
        next_slots(spec, state, 2)

    test_steps.append({"checks": {
        "proposer_boost_root": "0x" + bytes(store.proposer_boost_root).hex()}})
    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
def test_proposer_boost_root_same_slot_untimely_block(spec, state):
    """Arrival one interval into the slot is untimely: no boost."""
    test_steps = []
    genesis_state = state.copy()
    store = yield from begin_forkchoice(spec, state, test_steps)

    state = genesis_state.copy()
    next_slots(spec, state, 3)
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)

    interval = int(spec.config.SECONDS_PER_SLOT) // int(spec.INTERVALS_PER_SLOT)
    on_tick_and_append_step(
        spec, store, slot_time(spec, store, block.slot) + interval, test_steps)
    yield from add_block(spec, store, signed, test_steps)
    assert store.proposer_boost_root == spec.Root()

    test_steps.append({"checks": {
        "proposer_boost_root": "0x" + bytes(store.proposer_boost_root).hex()}})
    yield "steps", "data", test_steps
