"""Ex-ante re-org attack scenarios (reference suite:
test/phase0/fork_choice/test_ex_ante.py).

The attacker withholds a block (and possibly a small attestation set) to
displace an honest block; PROPOSER_SCORE_BOOST is the defense under test
(phase0/fork-choice.md get_latest_attesting_balance proposer-boost term).
"""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_all_phases,
    with_presets,
)
from consensus_specs_tpu.testing.helpers.fork_choice import (
    add_attestation,
    add_block,
    on_tick_and_append_step,
    tick_and_add_block,
)
from consensus_specs_tpu.testing.helpers.constants import MAINNET

from .scenario import (
    begin_forkchoice,
    head_of,
    make_branch_block,
    min_attesters_to_beat_boost,
    root_of,
    slot_time,
    vote_for,
)


def _base_plus_forks(spec, state, store, test_steps, with_d=False):
    """Common DAG: A at N+1 (delivered, head), then withheld B (N+2, parent
    A) and honest C (N+3, parent A); optionally D (N+4, parent B)."""
    signed_a, state_a = make_branch_block(spec, state, state.slot + 1)
    yield from tick_and_add_block(spec, store, signed_a, test_steps)
    assert head_of(spec, store) == root_of(signed_a)

    signed_b, state_b = make_branch_block(spec, state_a, state_a.slot + 1)
    signed_c, state_c = make_branch_block(spec, state_a, state_a.slot + 2)
    out = [signed_a, state_a, signed_b, state_b, signed_c, state_c]
    if with_d:
        signed_d, state_d = make_branch_block(spec, state_b, state_a.slot + 3)
        out += [signed_d, state_d]
    return out


@with_all_phases
@spec_state_test
def test_ex_ante_vanilla(spec, state):
    """One adversarial attestation is not enough against the boost:
    deliver C at its slot (head), then late B (C keeps head via boost),
    then a single vote for B (C still head)."""
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)
    (_, _, signed_b, state_b,
     signed_c, state_c) = yield from _base_plus_forks(spec, state, store, test_steps)

    withheld_vote = vote_for(spec, state_b, signed_b, participants=1)

    on_tick_and_append_step(
        spec, store, slot_time(spec, store, state_c.slot), test_steps)
    yield from add_block(spec, store, signed_c, test_steps)
    assert head_of(spec, store) == root_of(signed_c)

    yield from add_block(spec, store, signed_b, test_steps)
    assert head_of(spec, store) == root_of(signed_c)

    yield from add_attestation(spec, store, withheld_vote, test_steps)
    assert head_of(spec, store) == root_of(signed_c)

    yield "steps", "data", test_steps


@with_all_phases
@with_presets([MAINNET], reason="needs non-duplicate committees across slots")
@spec_state_test
def test_ex_ante_attestations_is_greater_than_proposer_boost_with_boost(spec, state):
    """Enough adversarial attestations DO beat the boost: B flips the head
    once its single-slot vote weight exceeds C's proposer score."""
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)
    (_, _, signed_b, state_b,
     signed_c, state_c) = yield from _base_plus_forks(spec, state, store, test_steps)

    on_tick_and_append_step(
        spec, store, slot_time(spec, store, state_c.slot), test_steps)
    yield from add_block(spec, store, signed_c, test_steps)
    assert head_of(spec, store) == root_of(signed_c)

    yield from add_block(spec, store, signed_b, test_steps)
    assert head_of(spec, store) == root_of(signed_c)

    needed = min_attesters_to_beat_boost(
        spec, store, state, root_of(signed_b), root_of(signed_b))
    attack = vote_for(spec, state_b, signed_b, participants=needed)
    yield from add_attestation(spec, store, attack, test_steps)
    assert head_of(spec, store) == root_of(signed_b)

    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
def test_ex_ante_sandwich_without_attestations(spec, state):
    """Boost-only sandwich: C is boosted over late B, then D (child of B)
    arrives on time and takes the head with its own boost."""
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)
    (_, _, signed_b, _, signed_c, state_c,
     signed_d, state_d) = yield from _base_plus_forks(
        spec, state, store, test_steps, with_d=True)

    on_tick_and_append_step(
        spec, store, slot_time(spec, store, state_c.slot), test_steps)
    yield from add_block(spec, store, signed_c, test_steps)
    assert head_of(spec, store) == root_of(signed_c)

    yield from add_block(spec, store, signed_b, test_steps)
    assert head_of(spec, store) == root_of(signed_c)

    on_tick_and_append_step(
        spec, store, slot_time(spec, store, state_d.slot), test_steps)
    yield from add_block(spec, store, signed_d, test_steps)
    assert head_of(spec, store) == root_of(signed_d)

    yield "steps", "data", test_steps


@with_all_phases
@spec_state_test
def test_ex_ante_sandwich_with_honest_attestation(spec, state):
    """An honest vote for C alone cannot stop the D-boost sandwich (one
    vote < boost), so D still becomes head."""
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)
    (_, _, signed_b, _, signed_c, state_c,
     signed_d, state_d) = yield from _base_plus_forks(
        spec, state, store, test_steps, with_d=True)

    honest_vote = vote_for(spec, state_c, signed_c, participants=1)

    on_tick_and_append_step(
        spec, store, slot_time(spec, store, state_c.slot), test_steps)
    yield from add_block(spec, store, signed_c, test_steps)
    assert head_of(spec, store) == root_of(signed_c)

    yield from add_block(spec, store, signed_b, test_steps)
    assert head_of(spec, store) == root_of(signed_c)

    on_tick_and_append_step(
        spec, store, slot_time(spec, store, state_d.slot), test_steps)
    yield from add_attestation(spec, store, honest_vote, test_steps)
    assert head_of(spec, store) == root_of(signed_c)

    yield from add_block(spec, store, signed_d, test_steps)
    assert head_of(spec, store) == root_of(signed_d)

    yield "steps", "data", test_steps


@with_all_phases
@with_presets([MAINNET], reason="needs non-duplicate committees across slots")
@spec_state_test
def test_ex_ante_sandwich_with_boost_not_sufficient(spec, state):
    """Once C has boost-beating honest votes, D's proposer boost is not
    enough to complete the sandwich — C keeps the head."""
    test_steps = []
    store = yield from begin_forkchoice(spec, state, test_steps)
    (_, _, signed_b, _, signed_c, state_c,
     signed_d, state_d) = yield from _base_plus_forks(
        spec, state, store, test_steps, with_d=True)

    on_tick_and_append_step(
        spec, store, slot_time(spec, store, state_c.slot), test_steps)
    yield from add_block(spec, store, signed_c, test_steps)
    assert head_of(spec, store) == root_of(signed_c)

    yield from add_block(spec, store, signed_b, test_steps)
    assert head_of(spec, store) == root_of(signed_c)

    needed = min_attesters_to_beat_boost(
        spec, store, state, root_of(signed_c), root_of(signed_c))
    honest_votes = vote_for(spec, state_c, signed_c, participants=needed)

    on_tick_and_append_step(
        spec, store, slot_time(spec, store, state_d.slot), test_steps)
    yield from add_attestation(spec, store, honest_votes, test_steps)
    assert head_of(spec, store) == root_of(signed_c)

    yield from add_block(spec, store, signed_d, test_steps)
    assert head_of(spec, store) == root_of(signed_c)

    yield "steps", "data", test_steps
