"""Differential suite: proto-array engine vs the literal spec ``Store``.

Two layers of pinning:

* **Adversarial replays** — every scenario in this package's get_head /
  ex_ante / on_block suites re-runs under ``engine_mode()``: each helper-
  driven store mutation is mirrored into a shadow ``ForkChoiceEngine``
  and head + justified/finalized parity is asserted after every step
  (testing/helpers/fork_choice.py), so the existing adversarial scripts
  double as engine differentials.

* **Random chains** — seeded random block DAGs (forks off random known
  tips, skip slots, scattered LMD votes, full-participation epochs deep
  enough to move justified/finalized and trigger pruning) driven through
  both paths with parity asserted at every delivery; plus unit pins for
  the batched latest-message fold against the sequential spec fold and
  for the two segment-sum backends.
"""
import random

import numpy as np
import pytest

from consensus_specs_tpu.ops.segment import segment_sum
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
    with_presets,
)
from consensus_specs_tpu.testing.helpers.attestations import (
    get_valid_attestation,
    sign_attestation,
)
from consensus_specs_tpu.testing.helpers.constants import MINIMAL
from consensus_specs_tpu.testing.helpers.fork_choice import (
    apply_next_epoch_with_attestations,
    assert_engine_parity,
    engine_mode,
    get_genesis_forkchoice_store_and_block,
    on_tick_and_append_step,
    run_on_attestation,
    tick_and_add_block,
    tick_and_run_on_attestation,
)
from consensus_specs_tpu.testing.helpers.state import next_epoch

from .scenario import begin_forkchoice, make_branch_block, root_of, slot_time

# -- adversarial replays ------------------------------------------------------

from . import test_ex_ante as _ex_ante
from . import test_get_head as _get_head
from . import test_on_block as _on_block

_REPLAY_CASES = [
    (mod, name)
    for mod in (_get_head, _ex_ante, _on_block)
    for name in sorted(dir(mod))
    if name.startswith("test_")
]

# the engine wraps a spec ``Store``, so later forks are parametrization,
# not new engine code: the altair leg drives the same adversarial scripts
# through an altair store (participation-flag states, altair justification
# pipeline) with the identical mirror parity contract
_REPLAY_PHASES = ["phase0", "altair"]


@pytest.mark.parametrize("phase", _REPLAY_PHASES)
@pytest.mark.parametrize(
    "mod,name", _REPLAY_CASES,
    ids=[f"{m.__name__.rsplit('.', 1)[-1]}::{n}" for m, n in _REPLAY_CASES])
def test_replay_scenario_through_engine(mod, name, phase):
    """Re-run an existing adversarial fork-choice scenario with the engine
    mirror attached: parity is asserted after every store mutation.  BLS
    off: the originals already pin signature handling, and this exercises
    the batch path's vectorized no-BLS validation residue (the random
    cases below keep BLS on)."""
    with engine_mode():
        getattr(mod, name)(phase=phase, bls_active=False)


# -- random-chain differential ------------------------------------------------


def _vote_for_block(spec, rng, post, signed):
    """A partial-committee attestation at the block's slot voting for it."""
    att = get_valid_attestation(
        spec, post, slot=post.slot, signed=False,
        filter_participant_set=lambda comm: set(
            sorted(comm)[:rng.randint(1, max(1, len(comm) // 2))]))
    att.data.beacon_block_root = root_of(signed)
    sign_attestation(spec, post, att)
    return att


def _deliver_vote(spec, store, att, test_steps):
    """Mature the clock past the attested slot, then deliver the vote with
    the validity verdict the spec's epoch-window check implies — random
    DAGs legitimately produce votes whose target epoch has aged out, and
    the engine must reject those exactly like the spec."""
    mature = slot_time(spec, store, int(att.data.slot) + 1)
    if store.time < mature:
        on_tick_and_append_step(spec, store, mature, test_steps)
    current_epoch = spec.compute_epoch_at_slot(spec.get_current_slot(store))
    previous_epoch = max(int(current_epoch) - 1, int(spec.GENESIS_EPOCH))
    valid = int(att.data.target.epoch) in (int(current_epoch), previous_epoch)
    run_on_attestation(spec, store, att, valid=valid)
    return valid


def _run_random_forkchoice(spec, state, seed):
    """Seeded random DAG: blocks fork off random known tips with random
    skip distances; votes land on random blocks (sometimes long-stale,
    exercising the rejection path); every delivery asserts engine parity
    (helpers mirror)."""
    rng = random.Random(seed)
    test_steps = []
    genesis_state = state.copy()
    store = yield from begin_forkchoice(spec, state, test_steps)

    blocks = []          # [(signed block, post state)]
    base_states = [genesis_state]

    for round_ in range(3):
        # grow the DAG: a few blocks off random known states
        for _ in range(rng.randint(2, 4)):
            base = rng.choice(base_states)
            slot = int(base.slot) + rng.randint(1, 3)
            signed, post = make_branch_block(spec, base, slot)
            blocks.append((signed, post))
            base_states.append(post)
            yield from tick_and_add_block(spec, store, signed, test_steps)
            assert_engine_parity(spec, store)
        # scatter LMD votes over random known blocks
        for _ in range(rng.randint(1, 3)):
            signed, post = rng.choice(blocks)
            att = _vote_for_block(spec, rng, post, signed)
            _deliver_vote(spec, store, att, test_steps)
            assert_engine_parity(spec, store)
    yield "steps", "data", test_steps


def _make_random_case(seed):
    @with_phases(["phase0"])
    @spec_state_test
    def case(spec, state):
        with engine_mode():
            yield from _run_random_forkchoice(spec, state, seed)

    return case


for _seed in range(20):
    globals()[f"test_engine_differential_random_{_seed}"] = \
        _make_random_case(_seed)
del _seed


# -- deep-chain differential (justified/finalized movement + pruning) --------


def _make_deep_case(seed):
    @with_phases(["phase0"])
    @spec_state_test
    @with_presets([MINIMAL], reason="too slow")
    def case(spec, state):
        """Full-participation epochs through the store until finalization
        advances: exercises balance refresh on justified change and
        proto-array pruning on finalized change, with a competing fork
        plus votes afterwards."""
        rng = random.Random(seed)
        test_steps = []
        with engine_mode():
            store = yield from begin_forkchoice(spec, state, test_steps)
            next_epoch(spec, state)
            on_tick_and_append_step(
                spec, store, slot_time(spec, store, state.slot), test_steps)
            for _ in range(3):
                state, store, last_block = yield from \
                    apply_next_epoch_with_attestations(
                        spec, state, store, True, True, test_steps=test_steps)
                assert_engine_parity(spec, store)
            assert store.finalized_checkpoint.epoch > 0
            # competing fork off the head, then votes for it
            base = store.block_states[spec.get_head(store)].copy()
            signed, post = make_branch_block(
                spec, base, int(base.slot) + rng.randint(1, 2))
            yield from tick_and_add_block(spec, store, signed, test_steps)
            assert_engine_parity(spec, store)
            att = get_valid_attestation(
                spec, post, slot=post.slot, signed=False)
            att.data.beacon_block_root = root_of(signed)
            sign_attestation(spec, post, att)
            yield from tick_and_run_on_attestation(
                spec, store, att, test_steps)
            assert_engine_parity(spec, store)
        yield "steps", "data", test_steps

    return case


for _seed in (100,):
    globals()[f"test_engine_differential_deep_{_seed}"] = _make_deep_case(_seed)
del _seed


# -- unit pins ----------------------------------------------------------------


@with_phases(["phase0"])
@spec_state_test
def test_batched_latest_message_fold_matches_sequential(spec, state):
    """The batch reduction (earliest entry of the max epoch, strict-epoch
    gate) must leave ``store.latest_messages`` byte-identical to the
    spec's sequential fold for a batch with repeated validators across
    two target epochs and varying LMD roots."""
    from consensus_specs_tpu.forkchoice import ForkChoiceEngine

    store, _ = get_genesis_forkchoice_store_and_block(spec, state.copy())
    engine = ForkChoiceEngine(
        spec, get_genesis_forkchoice_store_and_block(spec, state.copy())[0])

    # linear chain through epochs 1-2 so two target epochs exist
    st = state.copy()
    blocks, posts = [], []
    spe = int(spec.SLOTS_PER_EPOCH)
    for _ in range(2 * spe):
        signed, st = make_branch_block(spec, st, int(st.slot) + 1)
        blocks.append(signed)
        posts.append(st)
    for signed in blocks:
        t = slot_time(spec, store, signed.message.slot)
        if store.time < t:
            spec.on_tick(store, t)
            engine.on_tick(t)
        spec.on_block(store, signed)
        engine.on_block(signed)
    # clock one slot past the tip: epoch 2 is current, epoch 1 previous —
    # every target below stays inside the spec's ingestion window
    t = slot_time(spec, store, int(blocks[-1].message.slot) + 1)
    spec.on_tick(store, t)
    engine.on_tick(t)

    # attestations at random slots of epochs 1-2, each voting a random
    # block between its target's epoch start and its own slot
    rng = random.Random(7)
    atts = []
    for _ in range(10):
        i = rng.randint(spe - 1, 2 * spe - 1)   # block index; slot = i + 1
        slot = i + 1
        epoch_start_idx = (slot // spe) * spe - 1
        att = get_valid_attestation(
            spec, posts[i], slot=slot, signed=False,
            filter_participant_set=lambda comm: set(
                rng.sample(sorted(comm), max(1, len(comm) // 2))))
        att.data.beacon_block_root = \
            blocks[rng.randint(epoch_start_idx, i)].message.hash_tree_root()
        sign_attestation(spec, posts[i], att)
        atts.append(att)
    rng.shuffle(atts)
    for att in atts:
        spec.on_attestation(store, att)
    engine.on_attestations(atts)
    assert dict(store.latest_messages) == dict(engine.store.latest_messages)
    assert bytes(spec.get_head(store)) == bytes(engine.get_head())


def test_segment_sum_backends_agree():
    rng = np.random.default_rng(3)
    values = rng.integers(0, 32_000_000_000, 5000)
    ids = rng.integers(0, 37, 5000)
    host = segment_sum(values, ids, 37, backend="numpy")
    dev = segment_sum(values, ids, 37, backend="jax")
    assert host.dtype == np.int64
    assert np.array_equal(host, dev)


@with_phases(["phase0"])
@spec_state_test
def test_engine_wraps_warm_store_with_standing_votes(spec, state):
    """Constructing the engine around a store that already carries latest
    messages must seed the proto-array votes — parity from the very first
    ``get_head``, not just for stores the engine saw grow."""
    from consensus_specs_tpu.forkchoice import ForkChoiceEngine

    store, _ = get_genesis_forkchoice_store_and_block(spec, state.copy())
    base = state.copy()
    side_a = base.copy()
    signed_a, post_a = make_branch_block(spec, side_a, int(base.slot) + 1)
    side_b = base.copy()
    signed_b, post_b = make_branch_block(spec, side_b, int(base.slot) + 1)
    if bytes(root_of(signed_a)) > bytes(root_of(signed_b)):
        signed_a, post_a, signed_b, post_b = signed_b, post_b, signed_a, post_a
    # deliver both, then vote for the lexicographically SMALLER root so
    # the head depends on the standing vote, not the tie-break
    t = slot_time(spec, store, int(spec.SLOTS_PER_EPOCH) + 2)
    spec.on_tick(store, t)
    spec.on_block(store, signed_a)
    spec.on_block(store, signed_b)
    att = get_valid_attestation(spec, post_a, slot=post_a.slot, signed=False)
    att.data.beacon_block_root = root_of(signed_a)
    sign_attestation(spec, post_a, att)
    spec.on_attestation(store, att)
    assert bytes(spec.get_head(store)) == bytes(root_of(signed_a))

    engine = ForkChoiceEngine(spec, store)  # wrap the WARM store
    assert bytes(engine.get_head()) == bytes(spec.get_head(store))
