"""Differential: the pubkey-column substitution of
is_valid_indexed_attestation (specs/builder.py
_install_attestation_pubkey_column) must be behaviorally identical to the
sequential spec path, including failure semantics."""
import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.specs.builder import get_spec
from consensus_specs_tpu.ssz import bulk
from consensus_specs_tpu.testing.context import (
    default_activation_threshold,
    default_balances,
)
from consensus_specs_tpu.testing.helpers.attestations import (
    get_valid_attestation,
)
from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state


@pytest.fixture(scope="module")
def env():
    spec = get_spec("phase0", "minimal")
    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec))
    spec.process_slots(state, state.slot + 2)
    att = get_valid_attestation(spec, state, signed=True)
    spec.process_slots(
        state, att.data.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)
    return spec, state, spec.get_indexed_attestation(state, att)


def _both(spec, state, indexed):
    new = spec.is_valid_indexed_attestation(state, indexed)
    old = spec.is_valid_indexed_attestation.__wrapped__(state, indexed)
    assert new == old, f"substitution diverged: {new} vs {old}"
    return new


def test_valid_attestation_accepted_by_both(env):
    spec, state, indexed = env
    was = bls.bls_active
    bls.bls_active = True
    try:
        assert _both(spec, state, indexed) is True
    finally:
        bls.bls_active = was


def test_bad_signature_rejected_by_both(env):
    spec, state, indexed = env
    bad = indexed.copy()
    bad.signature = spec.BLSSignature(b"\x01" * 96)
    was = bls.bls_active
    bls.bls_active = True
    try:
        assert _both(spec, state, bad) is False
    finally:
        bls.bls_active = was


def test_structural_gates_match(env):
    spec, state, indexed = env
    empty = indexed.copy()
    empty.attesting_indices = []
    assert _both(spec, state, empty) is False

    if len(indexed.attesting_indices) >= 2:
        unsorted = indexed.copy()
        ids = [int(i) for i in indexed.attesting_indices]
        unsorted.attesting_indices = [ids[1], ids[0]] + ids[2:]
        assert _both(spec, state, unsorted) is False

    dup = indexed.copy()
    first = int(indexed.attesting_indices[0])
    dup.attesting_indices = [first, first]
    assert _both(spec, state, dup) is False


def test_out_of_range_index_raises_in_both(env):
    spec, state, indexed = env
    bad = indexed.copy()
    bad.attesting_indices = [len(state.validators) + 5]
    with pytest.raises(IndexError):
        spec.is_valid_indexed_attestation(state, bad)
    with pytest.raises(IndexError):
        spec.is_valid_indexed_attestation.__wrapped__(state, bad)


def test_column_matches_view_reads_and_tracks_mutation(env):
    spec, state, _ = env
    column = bulk.cached_validator_pubkeys(state.validators)
    assert len(column) == len(state.validators)
    for i in (0, 1, len(column) - 1):
        assert column[i] == bytes(state.validators[i].pubkey)
    # registry mutation -> new root -> fresh column (same pubkeys)
    st2 = state.copy()
    st2.validators[0].effective_balance = int(
        st2.validators[0].effective_balance) - 10**9
    column2 = bulk.cached_validator_pubkeys(st2.validators)
    assert column2[0] == column[0]
    assert len(column2) == len(column)
