"""Genesis initialization suite (spec: phase0/beacon-chain.md
initialize_beacon_state_from_eth1; reference suite:
test/phase0/genesis/test_initialization.py)."""
from consensus_specs_tpu.testing.context import (
    with_presets,
    single_phase,
    spec_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.deposits import (
    prepare_full_genesis_deposits,
)

GENESIS_TIME = 1578009600


@with_phases(["phase0"])
@spec_test
@single_phase
@with_presets(["minimal"], reason="mainnet genesis means 16384 signed deposits per case")
def test_initialize_beacon_state_from_eth1(spec):
    deposit_count = spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
    deposits, deposit_root, _ = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE, deposit_count, signed=True,
    )
    eth1_block_hash = b"\x12" * 32
    state = spec.initialize_beacon_state_from_eth1(
        eth1_block_hash, GENESIS_TIME, deposits
    )
    assert state.genesis_time == GENESIS_TIME + spec.config.GENESIS_DELAY
    assert len(state.validators) == deposit_count
    assert state.eth1_data.deposit_root == deposit_root
    assert state.eth1_data.deposit_count == deposit_count
    assert state.eth1_data.block_hash == eth1_block_hash
    assert spec.get_total_active_balance(state) == (
        deposit_count * spec.MAX_EFFECTIVE_BALANCE
    )
    yield "eth1_block_hash", eth1_block_hash
    yield "deposits", deposits
    yield "state", state


@with_phases(["phase0"])
@spec_test
@single_phase
@with_presets(["minimal"], reason="mainnet genesis means 16384 signed deposits per case")
def test_initialize_beacon_state_some_small_balances(spec):
    main_count = spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
    main_deposits, _, deposit_data_list = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE, main_count, signed=True,
    )
    # additional deposits below the activation threshold
    small_deposits, deposit_root, _ = prepare_full_genesis_deposits(
        spec, spec.MIN_DEPOSIT_AMOUNT, 2,
        min_pubkey_index=main_count, signed=True,
        deposit_data_list=deposit_data_list,
    )
    deposits = main_deposits + small_deposits
    state = spec.initialize_beacon_state_from_eth1(
        b"\x12" * 32, GENESIS_TIME, deposits
    )
    assert len(state.validators) == main_count + 2
    # only the full-balance validators are active at genesis
    assert len(spec.get_active_validator_indices(state, 0)) == main_count
    yield "eth1_block_hash", b"\x12" * 32
    yield "deposits", deposits
    yield "state", state


@with_phases(["phase0"])
@spec_test
@single_phase
@with_presets(["minimal"], reason="mainnet genesis means 16384 signed deposits per case")
def test_initialize_beacon_state_one_topup_activation(spec):
    count = spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
    # validator 0 deposits in two halves; the top-up must activate it
    half = spec.MAX_EFFECTIVE_BALANCE // 2
    first_deposits, _, deposit_data_list = prepare_full_genesis_deposits(
        spec, half, 1, signed=True,
    )
    rest_deposits, _, deposit_data_list = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE, count - 1,
        min_pubkey_index=1, signed=True, deposit_data_list=deposit_data_list,
    )
    topup_deposits, _, _ = prepare_full_genesis_deposits(
        spec, half, 1, signed=True, deposit_data_list=deposit_data_list,
    )
    deposits = first_deposits + rest_deposits + topup_deposits
    state = spec.initialize_beacon_state_from_eth1(
        b"\x12" * 32, GENESIS_TIME, deposits
    )
    assert len(spec.get_active_validator_indices(state, 0)) == count
    yield "eth1_block_hash", b"\x12" * 32
    yield "deposits", deposits
    yield "state", state
