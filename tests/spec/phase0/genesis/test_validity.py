"""Genesis validity suite (spec: phase0/beacon-chain.md
is_valid_genesis_state; reference suite:
test/phase0/genesis/test_validity.py)."""
from consensus_specs_tpu.testing.context import (
    with_presets,
    single_phase,
    spec_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.deposits import (
    prepare_full_genesis_deposits,
)


def create_valid_beacon_state(spec):
    deposits, _, _ = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE,
        spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT, signed=True,
    )
    return spec.initialize_beacon_state_from_eth1(
        b"\x12" * 32, spec.config.MIN_GENESIS_TIME, deposits
    )


def run_is_valid_genesis_state(spec, state, valid=True):
    yield "genesis", state
    assert spec.is_valid_genesis_state(state) == valid
    yield "is_valid", "meta", valid


@with_phases(["phase0"])
@spec_test
@single_phase
@with_presets(["minimal"], reason="mainnet genesis means 16384 signed deposits per case")
def test_full_genesis_deposits_valid(spec):
    state = create_valid_beacon_state(spec)
    yield from run_is_valid_genesis_state(spec, state)


@with_phases(["phase0"])
@spec_test
@single_phase
@with_presets(["minimal"], reason="mainnet genesis means 16384 signed deposits per case")
def test_invalid_before_genesis_time(spec):
    state = create_valid_beacon_state(spec)
    state.genesis_time = spec.config.MIN_GENESIS_TIME - 3
    yield from run_is_valid_genesis_state(spec, state, valid=False)


@with_phases(["phase0"])
@spec_test
@single_phase
@with_presets(["minimal"], reason="mainnet genesis means 16384 signed deposits per case")
def test_invalid_too_few_validators(spec):
    state = create_valid_beacon_state(spec)
    for index in range(2):
        v = state.validators[index]
        v.activation_epoch = spec.FAR_FUTURE_EPOCH  # not active at genesis
    assert len(spec.get_active_validator_indices(state, 0)) < (
        spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
    )
    yield from run_is_valid_genesis_state(spec, state, valid=False)


@with_phases(["phase0"])
@spec_test
@single_phase
@with_presets(["minimal"], reason="mainnet genesis means 16384 signed deposits per case")
def test_exactly_min_validator_count(spec):
    state = create_valid_beacon_state(spec)
    assert len(spec.get_active_validator_indices(state, 0)) == (
        spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
    )
    yield from run_is_valid_genesis_state(spec, state)
