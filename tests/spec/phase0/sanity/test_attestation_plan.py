"""Unit pins for the epoch-scoped attestation plan cache (ISSUE 8).

The cache (``stf/attestations._PLAN_CACHE``) memoizes whole-aggregate
resolution — committee gather + bits unpack + attester sort — on
(committee-geometry lookup key, attestation-data root, aggregation-bits
root).  These tests pin the contract edges the differential suites can't
isolate: content-addressed hits, bits-digest misses, FIFO eviction,
rollback under the block cache transaction, and geometry-keyed
invalidation (a state whose committees could differ can never consume
another state's plan).
"""
import numpy as np
import pytest

from consensus_specs_tpu.stf import attestations as atts_mod
from consensus_specs_tpu.stf import staging
from consensus_specs_tpu.testing.context import spec_state_test, with_phases
from consensus_specs_tpu.testing.helpers.attestations import (
    next_slots_with_attestations,
)
from consensus_specs_tpu.testing.helpers.state import next_epoch


def _attesting_block_position(spec, state):
    """(state at the last block's slot, that block's attestations): one
    attestation-bearing resolve position built with the sanity helpers."""
    next_epoch(spec, state)
    _, signed_blocks, _ = next_slots_with_attestations(
        spec, state.copy(), int(spec.SLOTS_PER_EPOCH) + 2, True, False)
    s = state.copy()
    for sb in signed_blocks[:-1]:
        spec.state_transition(s, sb, True)
    last = signed_blocks[-1].message
    spec.process_slots(s, last.slot)
    atts = list(last.body.attestations)
    assert atts, "corpus position carries no attestations"
    return s, atts


def _resolve(spec, s, atts):
    return atts_mod.resolve_block_attestations(spec, s).resolve(atts)


@with_phases(["phase0"])
@spec_state_test
def test_plan_hit_serves_recarried_aggregate(spec, state):
    """A re-resolved aggregate is served the SAME plan object — the
    re-carried-aggregate corpus shape (sig_memo_hits 1920/2048) never
    re-gathers, re-unpacks, or re-sorts."""
    s, atts = _attesting_block_position(spec, state)
    atts_mod.reset_caches()
    plans = _resolve(spec, s, atts)
    n_unique = len(atts_mod._PLAN_CACHE)
    assert n_unique == len(atts)  # corpus carries distinct aggregates
    again = _resolve(spec, s, atts)
    assert all(a is b for a, b in zip(plans, again))
    assert len(atts_mod._PLAN_CACHE) == n_unique
    # a DECODED copy of the same aggregate (fresh SSZ objects, same
    # content) hits too: the key is content-addressed roots, not ids
    copies = [type(a).decode_bytes(a.encode_bytes()) for a in atts]
    assert all(a is b for a, b in zip(plans, _resolve(spec, s, copies)))
    yield None


@with_phases(["phase0"])
@spec_state_test
def test_plan_miss_on_bits_digest(spec, state):
    """Same attestation data, different aggregation bits -> different
    plan (the bits-root key half), with the attester set tracking the
    flipped bit exactly."""
    s, atts = _attesting_block_position(spec, state)
    atts_mod.reset_caches()
    base = _resolve(spec, s, atts)[0]
    att2 = atts[0].copy()
    flip = next(i for i, b in enumerate(att2.aggregation_bits) if b)
    if sum(att2.aggregation_bits) == 1:
        # keep the attesting set non-empty: set another bit instead
        flip = next(i for i, b in enumerate(att2.aggregation_bits) if not b)
        att2.aggregation_bits[flip] = True
    else:
        att2.aggregation_bits[flip] = False
    size_before = len(atts_mod._PLAN_CACHE)
    plan2 = _resolve(spec, s, [att2])[0]
    assert len(atts_mod._PLAN_CACHE) == size_before + 1  # miss, new entry
    assert plan2.data_root == base.data_root  # data half unchanged
    assert not np.array_equal(plan2.attesters, base.attesters)
    yield None


@with_phases(["phase0"])
@spec_state_test
def test_plan_fifo_eviction(spec, state):
    """At capacity the OLDEST plan leaves first (insertion-ordered dict
    pop), and an evicted plan simply re-resolves — no correctness edge."""
    s, atts = _attesting_block_position(spec, state)
    # three unique plans: the original plus two bit-variants (distinct
    # bits digests) — block width doesn't matter, key uniqueness does
    base = atts[0]
    assert sum(base.aggregation_bits) >= 3
    variants = [base]
    set_bits = [j for j, b in enumerate(base.aggregation_bits) if b]
    for i in range(2):
        v = base.copy()
        v.aggregation_bits[set_bits[i]] = False
        variants.append(v)
    atts_mod.reset_caches()
    old_cap = atts_mod._PLAN_CACHE_MAX
    atts_mod._PLAN_CACHE_MAX = 2
    try:
        _resolve(spec, s, variants[:1])
        first_key = next(iter(atts_mod._PLAN_CACHE))
        _resolve(spec, s, variants[1:])  # second fills, third evicts first
        assert len(atts_mod._PLAN_CACHE) == 2
        assert first_key not in atts_mod._PLAN_CACHE
        re_resolved = _resolve(spec, s, variants[:1])[0]
        assert len(re_resolved.attesters) > 0
    finally:
        atts_mod._PLAN_CACHE_MAX = old_cap
        atts_mod.reset_caches()
    yield None


@with_phases(["phase0"])
@spec_state_test
def test_plan_rollback_pops_transactional_inserts(spec, state):
    """Plans inserted inside a failing block's cache transaction roll
    back with it — a poisoned plan can never outlive its block (the
    chaos case's unit-level half)."""
    s, atts = _attesting_block_position(spec, state)
    atts_mod.reset_caches()
    with pytest.raises(RuntimeError, match="mid-block fault"):
        with staging.block_transaction():
            _resolve(spec, s, atts)
            assert len(atts_mod._PLAN_CACHE) == len(atts)  # visible inserts
            raise RuntimeError("mid-block fault")
    assert len(atts_mod._PLAN_CACHE) == 0
    # and a clean transaction commits them
    with staging.block_transaction():
        _resolve(spec, s, atts)
    assert len(atts_mod._PLAN_CACHE) == len(atts)
    yield None


@with_phases(["phase0"])
@spec_state_test
def test_plan_stale_geometry_never_reused(spec, state):
    """A state whose committee geometry inputs differ (here: every randao
    mix mutated, so the attester seed changes) MISSES on every plan the
    original state built — the context half of the key makes stale reuse
    structurally impossible."""
    s, atts = _attesting_block_position(spec, state)
    atts_mod.reset_caches()
    plans = _resolve(spec, s, atts)
    size_before = len(atts_mod._PLAN_CACHE)
    s2 = s.copy()
    for i in range(len(s2.randao_mixes)):
        s2.randao_mixes[i] = b"\xfe" * 32  # every seed input differs
    plans2 = _resolve(spec, s2, atts)
    assert len(atts_mod._PLAN_CACHE) == size_before + len(atts)
    assert all(a is not b for a, b in zip(plans, plans2))
    yield None


@with_phases(["phase0"])
@spec_state_test
def test_plan_survives_randao_progress(spec, state):
    """The ctx half keys on the attester SEED, not the full randao_mixes
    root: a state differing only in a mix the seed does not read (the
    current epoch's, which process_randao rewrites every block) HITS —
    this is what makes plans live across the blocks that re-carry an
    aggregate."""
    s, atts = _attesting_block_position(spec, state)
    atts_mod.reset_caches()
    plans = _resolve(spec, s, atts)
    s2 = s.copy()
    # the mix process_randao touches: current epoch % EPOCHS_PER_VECTOR
    ix = int(spec.get_current_epoch(s2)) % len(s2.randao_mixes)
    s2.randao_mixes[ix] = b"\xab" * 32
    plans2 = _resolve(spec, s2, atts)
    assert all(a is b for a, b in zip(plans, plans2))
    yield None
