"""Slot-advance sanity tests (reference: test/phase0/sanity/test_slots.py)."""
from consensus_specs_tpu.testing.context import (
    spec_configured_state_test,
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.state import get_state_root


@with_all_phases
@spec_state_test
def test_slots_1(spec, state):
    pre_slot = state.slot
    pre_root = state.hash_tree_root()
    yield "pre", state

    slots = 1
    yield "slots", "meta", int(slots)
    spec.process_slots(state, state.slot + slots)

    yield "post", state
    assert state.slot == pre_slot + 1
    assert get_state_root(spec, state, pre_slot) == pre_root


@with_all_phases
@spec_state_test
def test_slots_2(spec, state):
    yield "pre", state
    slots = 2
    yield "slots", "meta", int(slots)
    spec.process_slots(state, state.slot + slots)
    yield "post", state


@with_all_phases
@spec_state_test
def test_empty_epoch(spec, state):
    pre_slot = state.slot
    yield "pre", state
    slots = spec.SLOTS_PER_EPOCH
    yield "slots", "meta", int(slots)
    spec.process_slots(state, state.slot + slots)
    yield "post", state
    assert state.slot == pre_slot + spec.SLOTS_PER_EPOCH


@with_all_phases
@spec_state_test
def test_double_empty_epoch(spec, state):
    pre_slot = state.slot
    yield "pre", state
    slots = spec.SLOTS_PER_EPOCH * 2
    yield "slots", "meta", int(slots)
    spec.process_slots(state, state.slot + slots)
    yield "post", state
    assert state.slot == pre_slot + slots


@with_all_phases
@spec_state_test
def test_over_epoch_boundary(spec, state):
    if spec.SLOTS_PER_EPOCH > 1:
        spec.process_slots(state, state.slot + spec.SLOTS_PER_EPOCH // 2)
    pre_slot = state.slot
    yield "pre", state
    slots = spec.SLOTS_PER_EPOCH
    yield "slots", "meta", int(slots)
    spec.process_slots(state, state.slot + slots)
    yield "post", state
    assert state.slot == pre_slot + slots


@with_all_phases
@spec_state_test
def test_historical_accumulator(spec, state):
    pre_historical_roots = state.historical_roots.copy()

    yield "pre", state
    slots = spec.SLOTS_PER_HISTORICAL_ROOT
    yield "slots", "meta", int(slots)
    spec.process_slots(state, state.slot + slots)
    yield "post", state

    assert len(state.historical_roots) == len(pre_historical_roots) + 1


@with_all_phases
@spec_configured_state_test({"EJECTION_BALANCE": 32_000_000_000})
def test_epoch_ejections_under_raised_ejection_balance(spec, state):
    """Config-override vector: with EJECTION_BALANCE raised to the max
    effective balance, the epoch's registry sweep ejects every active
    validator — a post state only reproducible by consumers that honor
    the recorded config.yaml (reference capability: with_config_overrides
    yielding the effective config into vectors)."""
    assert int(spec.config.EJECTION_BALANCE) == 32_000_000_000
    yield "pre", state

    slots = int(spec.SLOTS_PER_EPOCH)
    yield "slots", "meta", slots
    spec.process_slots(state, state.slot + slots)

    yield "post", state
    assert all(
        int(v.exit_epoch) != int(spec.FAR_FUTURE_EPOCH)
        for v in state.validators
    )
