"""Differential suite: the batched block-transition engine
(``stf.apply_signed_blocks``) vs the literal ``spec.state_transition``.

Three layers of pinning:

* **Sanity replays** — every scenario in this package's sanity-blocks and
  multi-operations suites re-runs under
  ``testing/helpers/block_processing.engine_mode()``: each helper-driven
  signed-block transition is mirrored through the engine on a shadow
  pre-state and post-state ``hash_tree_root`` parity (or shared
  rejection) is asserted after every block — the existing adversarial
  scripts double as engine differentials.

* **Seeded random epochs** — multi-block attestation-bearing epochs and
  randomized-operation walks driven through both paths with per-block
  root parity and a no-silent-fallback assertion (a fast path that
  quietly degrades to spec replay would still pass root parity, so the
  engine's own counters are part of the contract).

* **Failure behavior** — invalid blocks must raise the literal spec's
  exception type and message at the spec's point in processing AND leave
  the state byte-identically as poisoned (the engine's rollback + spec
  replay makes this exact, including the bisection-located signature
  failures).
"""
import contextlib

import pytest

from consensus_specs_tpu import stf
from consensus_specs_tpu.stf import slot_roots
from consensus_specs_tpu.testing.context import spec_state_test, with_phases
from consensus_specs_tpu.testing.helpers.attestations import (
    next_slots_with_attestations,
)
from consensus_specs_tpu.testing.helpers.block_processing import engine_mode
from consensus_specs_tpu.testing.helpers.state import next_epoch
from consensus_specs_tpu.testing.random_scenarios import run_random_scenario

from . import test_blocks as _blocks
from . import test_multi_operations as _multi

# -- adversarial sanity replays ----------------------------------------------

_REPLAY_CASES = [
    (mod, name)
    for mod in (_blocks, _multi)
    for name in sorted(dir(mod))
    if name.startswith("test_")
]


@pytest.mark.parametrize(
    "mod,name", _REPLAY_CASES,
    ids=[f"{m.__name__.rsplit('.', 1)[-1]}::{n}" for m, n in _REPLAY_CASES])
def test_replay_sanity_scenario_through_engine(mod, name):
    """Re-run an existing sanity scenario with the engine mirror attached.
    BLS off for speed (``always_bls`` scenarios force it back on, so the
    signature-batch path is exercised where the original demanded it);
    structural parity and shared-rejection behavior is what the replays
    pin — the BLS-on cases below cover the batch itself."""
    with engine_mode():
        getattr(mod, name)(phase="phase0", bls_active=False)


# -- seeded random multi-block epochs ----------------------------------------


def _per_block_differential(spec, state, signed_blocks):
    """Replay block-by-block through both paths, roots compared at every
    block boundary; the engine must take its fast path on every block."""
    s_spec, s_eng = state.copy(), state.copy()
    stf.reset_stats()
    for i, sb in enumerate(signed_blocks):
        spec.state_transition(s_spec, sb, True)
        stf.apply_signed_blocks(spec, s_eng, [sb], True)
        assert bytes(s_spec.hash_tree_root()) == bytes(s_eng.hash_tree_root()), \
            f"post-state diverged at block {i}"
    assert stf.stats["fast_blocks"] == len(signed_blocks), \
        f"engine silently replayed {stf.stats['replayed_blocks']} blocks"
    return s_eng


@with_phases(["phase0"])
@spec_state_test
def test_stf_differential_full_epochs_bls(spec, state):
    """Two attestation-bearing epochs, BLS ON: every block settles its
    proposer + RANDAO + aggregate signatures in one engine batch."""
    next_epoch(spec, state)
    _, signed_blocks, _ = next_slots_with_attestations(
        spec, state.copy(), int(spec.SLOTS_PER_EPOCH) * 2, True, True)
    _per_block_differential(spec, state, signed_blocks)
    yield None


@pytest.mark.parametrize("seed", [11, 23])
def test_stf_differential_random_scenario(seed):
    """Seeded randomized-operation walks (slashings, skips, epoch jumps)
    mirrored through the engine by the helper hook; BLS on."""
    @with_phases(["phase0"])
    @spec_state_test
    def case(spec, state):
        with engine_mode():
            yield from run_random_scenario(spec, state, seed=seed, stages=4)

    case(phase="phase0", bls_active=True)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [31, 47, 59])
def test_stf_differential_random_scenario_deep(seed):
    """Longer random walks (leak epochs included) — the heavy tail of the
    same contract."""
    @with_phases(["phase0"])
    @spec_state_test
    def case(spec, state):
        with engine_mode():
            yield from run_random_scenario(
                spec, state, seed=seed, stages=8, with_leak=True)

    case(phase="phase0", bls_active=True)


@pytest.mark.slow
def test_engine_vs_literal_parity_1m_validators():
    """Validator-count axis of the differential contract (ISSUE 8): a
    short full-block walk at 2^20 validators, engine vs literal with
    per-block byte-identical roots and no silent fallback — the
    scale-bench row's correctness story, pinned in the suite.  BLS off:
    what scales with validator count is committee geometry, the
    attestation plan, and the participation/balance writes, and those
    are exactly the parity surface here."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))))
    import bench
    from consensus_specs_tpu.crypto import bls
    from consensus_specs_tpu.specs.builder import get_spec
    from consensus_specs_tpu.stf import attestations as stf_attestations

    n = 1 << 20
    spec = get_spec("phase0", "mainnet")
    was_active = bls.bls_active
    bls.bls_active = False
    try:
        state = bench.build_state(spec, n)
        bench._install_real_pubkeys(spec, state, n)
        signed_blocks = bench._build_epoch_blocks(spec, state, n_slots=4)
        stf_attestations.reset_caches()
        _per_block_differential(spec, state, signed_blocks)
    finally:
        bls.bls_active = was_active
        stf_attestations.reset_caches()  # don't leak 1M-sized columns


# -- identical failure behavior ----------------------------------------------


def _exception_parity(spec, state, signed_block):
    """Both paths must raise the same exception type/message and leave the
    state byte-identically (partially) mutated."""
    exc_spec = exc_eng = None
    s_spec, s_eng = state.copy(), state.copy()
    try:
        spec.state_transition(s_spec, signed_block, True)
    except Exception as e:  # noqa: B001 - parity harness captures anything
        exc_spec = e
    try:
        stf.apply_signed_blocks(spec, s_eng, [signed_block], True)
    except Exception as e:  # noqa: B001
        exc_eng = e
    assert exc_spec is not None, "scenario was supposed to be invalid"
    assert type(exc_spec) is type(exc_eng), (exc_spec, exc_eng)
    assert str(exc_spec) == str(exc_eng), (exc_spec, exc_eng)
    assert bytes(s_spec.hash_tree_root()) == bytes(s_eng.hash_tree_root()), \
        "poisoned post-states diverged"


@with_phases(["phase0"])
@spec_state_test
def test_stf_invalid_blocks_fail_identically(spec, state):
    next_epoch(spec, state)
    _, signed_blocks, _ = next_slots_with_attestations(
        spec, state.copy(), int(spec.SLOTS_PER_EPOCH), True, False)
    base = signed_blocks[0]

    def tamper(fn):
        sb = base.copy()
        fn(sb)
        return sb

    cases = [
        tamper(lambda sb: setattr(sb, "signature", b"\x11" * 96)),
        tamper(lambda sb: setattr(sb.message.body, "randao_reveal",
                                  spec.BLSSignature(b"\x22" * 96))),
        tamper(lambda sb: setattr(sb.message.body.attestations[0], "signature",
                                  spec.BLSSignature(b"\x33" * 96))),
        tamper(lambda sb: setattr(sb.message, "slot", sb.message.slot + 1)),
        tamper(lambda sb: setattr(sb.message, "proposer_index",
                                  sb.message.proposer_index + 1)),
        tamper(lambda sb: setattr(sb.message.body.attestations[0].data,
                                  "index", 2 ** 32)),
        tamper(lambda sb: setattr(sb.message, "state_root",
                                  spec.Root(b"\x44" * 32))),
    ]
    for sb in cases:
        _exception_parity(spec, state, sb)
    yield None


# -- exception parity, pipeline ON vs OFF (ISSUE 10) --------------------------

# the overlapped pipeline speculates block N+1 while block N's verdict
# is outstanding; this battery pins that a genuinely-invalid block —
# speculated or not, breaker open or not, native degraded or not —
# still raises the literal spec's exact exception with the state
# byte-identically poisoned, with the pipeline ON and OFF.

_PIPELINE_BATTERY = ["tampered-sig-speculated", "breaker-trip-mid-pipeline",
                     "degradation-drain"]


def _pipeline_exception_battery(fork, scenario, pipeline_mode, monkeypatch):
    from consensus_specs_tpu import faults
    from consensus_specs_tpu.crypto import bls
    from tests.chaos.test_stf_chaos import _corpus, _fresh_engine_env

    spec, pre, blocks, _roots = _corpus(fork)
    monkeypatch.setenv("CSTPU_PIPELINE", pipeline_mode)

    # tamper an aggregate signature on a block that carries attestations
    # and has predecessors to speculate across
    tamper_idx = next(i for i, sb in enumerate(blocks)
                      if i >= 2 and len(sb.message.body.attestations))
    bad = blocks[tamper_idx].copy()
    bad.message.body.attestations[0].signature = \
        spec.BLSSignature(b"\x33" * 96)
    walk = list(blocks[:tamper_idx]) + [bad]

    plan_faults = []
    if scenario == "breaker-trip-mid-pipeline":
        plan_faults = [faults.Fault("stf.engine.operations", nth=n)
                       for n in (1, 2, 3)]
    elif scenario == "degradation-drain":
        plan_faults = [faults.Fault("stf.verify.native_call", nth=1,
                                    kind="crash")]

    prev = bls.bls_active
    bls.bls_active = True
    try:
        # oracle: the sequential literal spec over the same walk
        s_spec = pre.copy()
        for sb in walk[:-1]:
            spec.state_transition(s_spec, sb, True)
        exc_spec = _capture_exc(spec.state_transition, s_spec, walk[-1], True)

        _fresh_engine_env()
        s_eng = pre.copy()
        ctx = (faults.inject(faults.FaultPlan(plan_faults))
               if plan_faults else contextlib.nullcontext())
        with ctx:
            # ONE call: the tampered block IS speculated (pipeline ON)
            exc_eng = _capture_exc(
                stf.apply_signed_blocks, spec, s_eng, walk, True)
    finally:
        bls.bls_active = prev
        from consensus_specs_tpu.stf import verify as stf_verify

        stf_verify.reset_degraded()  # don't leak degradation to later tests

    assert exc_spec is not None, "scenario was supposed to be invalid"
    assert type(exc_spec) is type(exc_eng), (exc_spec, exc_eng)
    assert str(exc_spec) == str(exc_eng), (exc_spec, exc_eng)
    assert bytes(s_spec.hash_tree_root()) == bytes(s_eng.hash_tree_root()), \
        "poisoned post-states diverged"


def _capture_exc(fn, *args):
    try:
        fn(*args)
    except Exception as e:  # noqa: B001 - parity harness captures anything
        return e
    return None


@pytest.mark.parametrize("pipeline_mode", ["0", "1"],
                         ids=["pipeline-off", "pipeline-on"])
@pytest.mark.parametrize("scenario", _PIPELINE_BATTERY)
def test_exception_parity_pipeline_battery(scenario, pipeline_mode,
                                           monkeypatch, recwarn):
    _pipeline_exception_battery("phase0", scenario, pipeline_mode,
                                monkeypatch)


# -- per-slot roots (stf/slot_roots vs spec.process_slots) --------------------


@with_phases(["phase0"])
@spec_state_test
def test_slot_roots_process_slots_differential(spec, state):
    """Empty-slot advancement across an epoch boundary: the resident-
    routed replica must land byte-identical states at every boundary."""
    for jump in (1, 3, int(spec.SLOTS_PER_EPOCH) + 2):
        s_spec, s_eng = state.copy(), state.copy()
        target = s_spec.slot + jump
        spec.process_slots(s_spec, target)
        slot_roots.process_slots(spec, s_eng, target)
        assert bytes(s_spec.hash_tree_root()) == bytes(s_eng.hash_tree_root())
        state = s_spec
    # same assert on an already-reached slot
    with pytest.raises(AssertionError):
        slot_roots.process_slots(spec, state.copy(), state.slot)
    yield None
