"""Full-random-operations block test (reference capability:
test/helpers/multi_operations.py driving sanity blocks)."""
import random

from consensus_specs_tpu.testing.context import spec_state_test, with_phases
from consensus_specs_tpu.testing.helpers.multi_operations import (
    run_test_full_random_operations,
)


@with_phases(["phase0"])
@spec_state_test
def test_full_random_operations(spec, state):
    yield from run_test_full_random_operations(spec, state, random.Random(77))
