"""Block-processing sanity tests (reference: test/phase0/sanity/test_blocks.py,
representative subset)."""
from consensus_specs_tpu.testing.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.attestations import get_valid_attestation
from consensus_specs_tpu.testing.helpers.attester_slashings import (
    get_valid_attester_slashing_by_indices,
)
from consensus_specs_tpu.testing.helpers.proposer_slashings import (
    get_valid_proposer_slashing,
)
from consensus_specs_tpu.testing.helpers.block import (
    build_empty_block,
    build_empty_block_for_next_slot,
    sign_block,
)
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    next_slot,
    state_transition_and_sign_block,
)


@with_all_phases
@spec_state_test
def test_prev_slot_block_transition(spec, state):
    # Go to clean slot
    spec.process_slots(state, state.slot + 1)
    # Make a block for it
    block = build_empty_block(spec, state, slot=state.slot)
    proposer_index = spec.get_beacon_proposer_index(state)
    # Transition to next slot, above block will not be invalid on top of new state.
    spec.process_slots(state, state.slot + 1)

    yield "pre", state
    # State is beyond block slot, but the block can still be realistic when invalid.
    # Try the transition, and update the state root to where it is halted. Then sign with the supposed proposer.
    expect_assertion_error(lambda: spec.process_block(state, block))
    block.state_root = state.hash_tree_root()
    signed_block = sign_block(spec, state, block, proposer_index=proposer_index)
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_same_slot_block_transition(spec, state):
    # Same slot on top of pre-state, but move out of slot 0 first.
    spec.process_slots(state, state.slot + 1)

    block = build_empty_block(spec, state, slot=state.slot)

    yield "pre", state

    assert state.slot == block.slot

    spec.process_block(state, block)
    block.state_root = state.hash_tree_root()

    signed_block = sign_block(spec, state, block)

    yield "blocks", [signed_block]
    yield "post", state


@with_all_phases
@spec_state_test
def test_empty_block_transition(spec, state):
    pre_slot = state.slot
    pre_eth1_votes = len(state.eth1_data_votes)
    pre_mix = spec.get_randao_mix(state, spec.get_current_epoch(state))

    yield "pre", state

    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)

    yield "blocks", [signed_block]
    yield "post", state

    assert len(state.eth1_data_votes) == pre_eth1_votes + 1
    assert spec.get_block_root_at_slot(state, pre_slot) == block.parent_root
    assert spec.get_randao_mix(state, spec.get_current_epoch(state)) != pre_mix


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_state_root(spec, state):
    yield "pre", state

    block = build_empty_block_for_next_slot(spec, state)
    block.state_root = b"\xaa" * 32
    signed_block = sign_block(spec, state, block)

    expect_assertion_error(
        lambda: spec.state_transition(state, signed_block, validate_result=True))

    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
@always_bls
def test_zero_block_sig(spec, state):
    yield "pre", state

    block = build_empty_block_for_next_slot(spec, state)
    invalid_signed_block = spec.SignedBeaconBlock(message=block)
    expect_assertion_error(
        lambda: spec.state_transition(state, invalid_signed_block))

    yield "blocks", [invalid_signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_block_sig(spec, state):
    yield "pre", state

    block = build_empty_block_for_next_slot(spec, state)

    domain = spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER, spec.compute_epoch_at_slot(block.slot))
    signing_root = spec.compute_signing_root(block, domain)
    invalid_signed_block = spec.SignedBeaconBlock(
        message=block,
        signature=spec.bls.Sign(123456, signing_root),
    )
    expect_assertion_error(
        lambda: spec.state_transition(state, invalid_signed_block))

    yield "blocks", [invalid_signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_proposer_index_sig_from_expected_proposer(spec, state):
    yield "pre", state

    block = build_empty_block_for_next_slot(spec, state)
    expect_proposer_index = block.proposer_index

    # Set invalid proposer index but correct signature wrt expected proposer
    active_indices = spec.get_active_validator_indices(state, spec.get_current_epoch(state))
    active_indices = [i for i in active_indices if i != block.proposer_index]
    block.proposer_index = active_indices[0]  # invalid proposer index

    invalid_signed_block = sign_block(spec, state, block, expect_proposer_index)

    expect_assertion_error(
        lambda: spec.state_transition(state, invalid_signed_block))

    yield "blocks", [invalid_signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_skipped_slots(spec, state):
    pre_slot = state.slot
    yield "pre", state

    block = build_empty_block(spec, state, state.slot + 4)
    signed_block = state_transition_and_sign_block(spec, state, block)

    yield "blocks", [signed_block]
    yield "post", state

    assert state.slot == block.slot
    assert spec.get_randao_mix(state, spec.get_current_epoch(state)) != spec.Bytes32()
    for slot in range(pre_slot, state.slot):
        assert spec.get_block_root_at_slot(state, slot) == block.parent_root


@with_all_phases
@spec_state_test
def test_empty_epoch_transition(spec, state):
    pre_slot = state.slot
    yield "pre", state

    block = build_empty_block(spec, state, state.slot + spec.SLOTS_PER_EPOCH)
    signed_block = state_transition_and_sign_block(spec, state, block)

    yield "blocks", [signed_block]
    yield "post", state

    assert state.slot == block.slot
    for slot in range(pre_slot, state.slot):
        assert spec.get_block_root_at_slot(state, slot) == block.parent_root


@with_all_phases
@spec_state_test
def test_high_proposer_index(spec, state):
    # disable a good amount of validators to make the active count lower, for a faster test
    current_epoch = spec.get_current_epoch(state)
    for i in range(len(state.validators) // 3):
        state.validators[i].exit_epoch = current_epoch

    # skip forward, get brand new proposers
    state.slot = spec.SLOTS_PER_EPOCH * 2
    block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, block)

    active_count = len(spec.get_active_validator_indices(state, current_epoch))
    while True:
        proposer_index = spec.get_beacon_proposer_index(state)
        if proposer_index >= active_count:
            # found a proposer that has a higher index than the active validator count
            yield "pre", state
            # test if the proposer can be recognized correctly, even while it has a high index
            signed_block = state_transition_and_sign_block(
                spec, state, build_empty_block_for_next_slot(spec, state))
            yield "blocks", [signed_block]
            yield "post", state
            break
        next_slot(spec, state)


@with_all_phases
@spec_state_test
def test_attestation(spec, state):
    next_epoch(spec, state)

    yield "pre", state

    attestation_block = build_empty_block(
        spec, state, state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)

    index = 0
    attestation = get_valid_attestation(spec, state, index=index, signed=True)

    # Add to state via block transition
    pre_current_attestations_len = (
        len(state.current_epoch_attestations) if spec.fork == "phase0" else None
    )
    attestation_block.body.attestations.append(attestation)
    signed_attestation_block = state_transition_and_sign_block(spec, state, attestation_block)

    if spec.fork == "phase0":
        assert len(state.current_epoch_attestations) == pre_current_attestations_len + 1
        # Epoch transition should move to previous_epoch_attestations
        pre_current_attestations_root = spec.hash_tree_root(state.current_epoch_attestations)
    else:
        pre_current_epoch_participation_root = spec.hash_tree_root(state.current_epoch_participation)

    epoch_block = build_empty_block(spec, state, state.slot + spec.SLOTS_PER_EPOCH)
    signed_epoch_block = state_transition_and_sign_block(spec, state, epoch_block)

    yield "blocks", [signed_attestation_block, signed_epoch_block]
    yield "post", state

    if spec.fork == "phase0":
        assert len(state.current_epoch_attestations) == 0
        assert spec.hash_tree_root(state.previous_epoch_attestations) == pre_current_attestations_root
    else:
        for index in range(len(state.validators)):
            assert state.current_epoch_participation[index] == spec.ParticipationFlags(0b0000_0000)
        assert spec.hash_tree_root(state.previous_epoch_participation) == pre_current_epoch_participation_root


@with_all_phases
@spec_state_test
def test_balance_driven_status_transitions(spec, state):
    current_epoch = spec.get_current_epoch(state)
    validator_index = spec.get_active_validator_indices(state, current_epoch)[-1]

    assert state.validators[validator_index].exit_epoch == spec.FAR_FUTURE_EPOCH

    # set validator balance to below ejection threshold
    state.validators[validator_index].effective_balance = spec.config.EJECTION_BALANCE

    yield "pre", state

    # trigger epoch transition
    block = build_empty_block(spec, state, state.slot + spec.SLOTS_PER_EPOCH)
    signed_block = state_transition_and_sign_block(spec, state, block)

    yield "blocks", [signed_block]
    yield "post", state

    assert state.validators[validator_index].exit_epoch < spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_eth1_data_votes_consensus(spec, state):
    voting_period_slots = spec.EPOCHS_PER_ETH1_VOTING_PERIOD * spec.SLOTS_PER_EPOCH

    offset_block = build_empty_block(spec, state, slot=voting_period_slots - 1)
    state_transition_and_sign_block(spec, state, offset_block)
    yield "pre", state

    a = b"\xaa" * 32
    b = b"\xbb" * 32
    c = b"\xcc" * 32

    blocks = []

    for i in range(0, voting_period_slots):
        block = build_empty_block_for_next_slot(spec, state)
        # wait for over 50% for A, then start voting B
        block.body.eth1_data.block_hash = b if i * 2 > voting_period_slots else a
        signed_block = state_transition_and_sign_block(spec, state, block)
        blocks.append(signed_block)

    assert len(state.eth1_data_votes) == voting_period_slots
    assert state.eth1_data.block_hash == a

    # transition to next eth1 voting period
    block = build_empty_block_for_next_slot(spec, state)
    block.body.eth1_data.block_hash = c
    signed_block = state_transition_and_sign_block(spec, state, block)
    blocks.append(signed_block)

    yield "blocks", blocks
    yield "post", state

    assert state.eth1_data.block_hash == a
    assert state.slot % voting_period_slots == 0
    assert len(state.eth1_data_votes) == 1
    assert state.eth1_data_votes[0].block_hash == c


@with_all_phases
@spec_state_test
def test_proposal_for_genesis_slot(spec, state):
    assert state.slot == spec.GENESIS_SLOT
    yield "pre", state
    block = build_empty_block(spec, state, spec.GENESIS_SLOT)
    block.parent_root = state.latest_block_header.hash_tree_root()

    # a block for the genesis slot can never transition (slot must advance)
    expect_assertion_error(
        lambda: spec.state_transition(
            state, spec.SignedBeaconBlock(message=block), validate_result=False))
    yield "blocks", [spec.SignedBeaconBlock(message=block)]
    yield "post", None


@with_all_phases
@spec_state_test
def test_parent_from_same_slot(spec, state):
    yield "pre", state

    parent_block = build_empty_block_for_next_slot(spec, state)
    signed_parent = state_transition_and_sign_block(spec, state, parent_block)

    # sibling claiming a parent in its own slot
    child_block = parent_block.copy()
    child_block.parent_root = state.latest_block_header.hash_tree_root()

    failed_state = state.copy()
    expect_assertion_error(
        lambda: spec.state_transition(
            failed_state, spec.SignedBeaconBlock(message=child_block),
            validate_result=False))
    yield "blocks", [signed_parent, spec.SignedBeaconBlock(message=child_block)]
    yield "post", None


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_proposer_index_sig_from_proposer_index(spec, state):
    invalid_block = build_empty_block_for_next_slot(spec, state)
    # steal the slot from the expected proposer, sign with the thief's key
    expected_proposer = invalid_block.proposer_index
    active = spec.get_active_validator_indices(state, spec.get_current_epoch(state))
    thief = next(i for i in active if i != expected_proposer)
    invalid_block.proposer_index = thief

    yield "pre", state
    invalid_signed = sign_block(spec, state, invalid_block, proposer_index=thief)
    expect_assertion_error(
        lambda: spec.state_transition(state, invalid_signed))
    yield "blocks", [invalid_signed]
    yield "post", None


@with_all_phases
@spec_state_test
def test_empty_epoch_transition_not_finalizing(spec, state):
    if spec.preset_name == "mainnet":
        return  # minimal-only: four empty epochs are cheap there
    yield "pre", state
    block = build_empty_block(
        spec, state, state.slot + spec.SLOTS_PER_EPOCH * 5)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert state.slot == block.slot
    assert state.finalized_checkpoint.epoch < spec.get_current_epoch(state) - 4
    for index in range(len(state.validators)):
        assert state.balances[index] < spec.MAX_EFFECTIVE_BALANCE


@with_all_phases
@spec_state_test
def test_proposer_self_slashing(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    assert not state.validators[block.proposer_index].slashed
    slashing = get_valid_proposer_slashing(
        spec, state, slashed_index=block.proposer_index,
        signed_1=True, signed_2=True)
    block.body.proposer_slashings.append(slashing)

    yield "pre", state
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert state.validators[block.proposer_index].slashed


@with_all_phases
@spec_state_test
def test_proposer_slashing(spec, state):
    slashing = get_valid_proposer_slashing(spec, state, signed_1=True, signed_2=True)
    victim = slashing.signed_header_1.message.proposer_index
    assert not state.validators[victim].slashed

    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.proposer_slashings.append(slashing)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert state.validators[victim].slashed


@with_all_phases
@spec_state_test
def test_double_same_proposer_slashings_same_block(spec, state):
    slashing = get_valid_proposer_slashing(spec, state, signed_1=True, signed_2=True)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.proposer_slashings = [slashing, slashing]
    signed_block = state_transition_and_sign_block(spec, state, block, expect_fail=True)
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_double_similar_proposer_slashings_same_block(spec, state):
    # same proposer, two distinct evidence pairs: second must fail (already slashed)
    victim = spec.get_active_validator_indices(state, spec.get_current_epoch(state))[-1]
    slashing_1 = get_valid_proposer_slashing(
        spec, state, slashed_index=victim, random_root=b"\x66" * 32,
        signed_1=True, signed_2=True)
    slashing_2 = get_valid_proposer_slashing(
        spec, state, slashed_index=victim, random_root=b"\x77" * 32,
        signed_1=True, signed_2=True)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.proposer_slashings = [slashing_1, slashing_2]
    signed_block = state_transition_and_sign_block(spec, state, block, expect_fail=True)
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_multiple_different_proposer_slashings_same_block(spec, state):
    active = spec.get_active_validator_indices(state, spec.get_current_epoch(state))
    proposer = spec.get_beacon_proposer_index(state)
    victims = [i for i in active if i != proposer][:3]
    slashings = [
        get_valid_proposer_slashing(
            spec, state, slashed_index=victim, signed_1=True, signed_2=True)
        for victim in victims
    ]
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.proposer_slashings = slashings
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    for victim in victims:
        assert state.validators[victim].slashed


@with_all_phases
@spec_state_test
def test_attester_slashing(spec, state):
    slashing = get_valid_attester_slashing_by_indices(
        spec, state, sorted(spec.get_active_validator_indices(
            state, spec.get_current_epoch(state))[:2]),
        signed_1=True, signed_2=True)
    victims = slashing.attestation_1.attesting_indices

    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.attester_slashings.append(slashing)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    for victim in victims:
        assert state.validators[victim].slashed


@with_all_phases
@spec_state_test
def test_duplicate_attester_slashing(spec, state):
    slashing = get_valid_attester_slashing_by_indices(
        spec, state, [0, 1], signed_1=True, signed_2=True)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.attester_slashings = [slashing, slashing]
    signed_block = state_transition_and_sign_block(spec, state, block, expect_fail=True)
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_multiple_attester_slashings_no_overlap(spec, state):
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.attester_slashings = [
        get_valid_attester_slashing_by_indices(
            spec, state, [0, 1], signed_1=True, signed_2=True),
        get_valid_attester_slashing_by_indices(
            spec, state, [2, 3], signed_1=True, signed_2=True),
    ]
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    for victim in range(4):
        assert state.validators[victim].slashed


@with_all_phases
@spec_state_test
def test_multiple_attester_slashings_partial_overlap(spec, state):
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.attester_slashings = [
        get_valid_attester_slashing_by_indices(
            spec, state, [0, 1, 2], signed_1=True, signed_2=True),
        get_valid_attester_slashing_by_indices(
            spec, state, [1, 2, 3], signed_1=True, signed_2=True),
    ]
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    for victim in range(4):
        assert state.validators[victim].slashed


@with_all_phases
@spec_state_test
def test_proposer_after_inactive_index(spec, state):
    # exit a low index and skip ahead until it would have proposed
    inactive_index = 10
    spec.initiate_validator_exit(state, inactive_index)
    exit_epoch = state.validators[inactive_index].exit_epoch
    from consensus_specs_tpu.testing.helpers.state import transition_to
    transition_to(spec, state, spec.compute_start_slot_at_epoch(exit_epoch))

    yield "pre", state
    for _ in range(spec.SLOTS_PER_EPOCH):
        proposer = spec.get_beacon_proposer_index(state)
        assert proposer != inactive_index
        next_slot(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state


@with_all_phases
@spec_state_test
def test_expected_deposit_in_block(spec, state):
    # state advertises one pending deposit the block fails to deliver
    state.eth1_data.deposit_count = state.eth1_deposit_index + 1
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block, expect_fail=True)
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_deposit_in_block(spec, state):
    from consensus_specs_tpu.testing.helpers.deposits import prepare_state_and_deposit

    new_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(spec, state, new_index, amount, signed=True)

    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.deposits.append(deposit)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert len(state.validators) == new_index + 1
    assert state.balances[new_index] == amount
    from consensus_specs_tpu.testing.helpers.keys import pubkeys
    assert state.validators[new_index].pubkey == pubkeys[new_index]


@with_all_phases
@spec_state_test
def test_deposit_top_up(spec, state):
    from consensus_specs_tpu.testing.helpers.deposits import prepare_state_and_deposit

    index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(spec, state, index, amount)
    pre_balance = int(state.balances[index])
    pre_count = len(state.validators)

    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.deposits.append(deposit)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert len(state.validators) == pre_count
    expected = pre_balance + int(amount)
    from consensus_specs_tpu.testing.context import is_post_altair
    if is_post_altair(spec):
        # an empty sync aggregate penalizes every absent committee seat
        from consensus_specs_tpu.testing.helpers.sync_committee import (
            compute_committee_indices,
            compute_sync_committee_participant_reward_and_penalty,
        )
        reward, penalty = compute_sync_committee_participant_reward_and_penalty(
            spec, state, index,
            compute_committee_indices(spec, state, state.current_sync_committee),
            block.body.sync_aggregate.sync_committee_bits)
        expected += int(reward) - int(penalty)
    assert int(state.balances[index]) == expected


def _age_for_exits(spec, state):
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH


@with_all_phases
@spec_state_test
def test_voluntary_exit(spec, state):
    from consensus_specs_tpu.testing.helpers.voluntary_exits import prepare_signed_exits

    _age_for_exits(spec, state)
    index = spec.get_active_validator_indices(state, spec.get_current_epoch(state))[-1]
    signed_exit = prepare_signed_exits(spec, state, [index])[0]

    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.voluntary_exits.append(signed_exit)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert state.validators[index].exit_epoch < spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_double_validator_exit_same_block(spec, state):
    from consensus_specs_tpu.testing.helpers.voluntary_exits import prepare_signed_exits

    _age_for_exits(spec, state)
    index = spec.get_active_validator_indices(state, spec.get_current_epoch(state))[-1]
    signed_exit = prepare_signed_exits(spec, state, [index])[0]

    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.voluntary_exits = [signed_exit, signed_exit]
    signed_block = state_transition_and_sign_block(spec, state, block, expect_fail=True)
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_multiple_different_validator_exits_same_block(spec, state):
    from consensus_specs_tpu.testing.helpers.voluntary_exits import prepare_signed_exits

    _age_for_exits(spec, state)
    indices = spec.get_active_validator_indices(
        state, spec.get_current_epoch(state))[-3:]
    exits = prepare_signed_exits(spec, state, indices)

    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.voluntary_exits = exits
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    for index in indices:
        assert state.validators[index].exit_epoch < spec.FAR_FUTURE_EPOCH


def _run_slash_and_exit(spec, state, slash_index, exit_index, valid):
    from consensus_specs_tpu.testing.helpers.voluntary_exits import prepare_signed_exits

    _age_for_exits(spec, state)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.attester_slashings.append(get_valid_attester_slashing_by_indices(
        spec, state, [slash_index], signed_1=True, signed_2=True))
    block.body.voluntary_exits.append(
        prepare_signed_exits(spec, state, [exit_index])[0])
    signed_block = state_transition_and_sign_block(
        spec, state, block, expect_fail=not valid)
    yield "blocks", [signed_block]
    yield "post", state if valid else None


@with_all_phases
@spec_state_test
def test_slash_and_exit_same_index(spec, state):
    # slashing sets an exit epoch, so the voluntary exit's
    # exit_epoch==FAR_FUTURE precondition fails
    index = spec.get_active_validator_indices(state, spec.get_current_epoch(state))[-1]
    yield from _run_slash_and_exit(spec, state, index, index, valid=False)


@with_all_phases
@spec_state_test
def test_slash_and_exit_diff_index(spec, state):
    active = spec.get_active_validator_indices(state, spec.get_current_epoch(state))
    yield from _run_slash_and_exit(spec, state, active[-1], active[-2], valid=True)


@with_all_phases
@spec_state_test
def test_historical_batch(spec, state):
    # park one slot short of a historical-root boundary
    state.slot += spec.SLOTS_PER_HISTORICAL_ROOT - (
        state.slot % spec.SLOTS_PER_HISTORICAL_ROOT) - 1
    pre_historical_len = len(state.historical_roots)

    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert state.slot == block.slot
    assert len(state.historical_roots) == pre_historical_len + 1


@with_all_phases
@spec_state_test
def test_eth1_data_votes_no_consensus(spec, state):
    voting_period_slots = spec.EPOCHS_PER_ETH1_VOTING_PERIOD * spec.SLOTS_PER_EPOCH

    offset_block = build_empty_block(spec, state, slot=voting_period_slots - 1)
    state_transition_and_sign_block(spec, state, offset_block)
    yield "pre", state

    pre_eth1_hash = state.eth1_data.block_hash
    a, b = b"\xaa" * 32, b"\xbb" * 32
    blocks = []
    for i in range(voting_period_slots):
        block = build_empty_block_for_next_slot(spec, state)
        # a 50/50 split never reaches the strict-majority threshold
        block.body.eth1_data.block_hash = b if i * 2 >= voting_period_slots else a
        blocks.append(state_transition_and_sign_block(spec, state, block))

    assert len(state.eth1_data_votes) == voting_period_slots
    assert state.eth1_data.block_hash == pre_eth1_hash
    yield "blocks", blocks
    yield "post", state


@with_all_phases
@spec_state_test
def test_full_random_operations_1(spec, state):
    import random as _random

    from consensus_specs_tpu.testing.helpers.multi_operations import (
        run_test_full_random_operations,
    )
    yield from run_test_full_random_operations(spec, state, _random.Random(2080))


@with_all_phases
@spec_state_test
def test_full_random_operations_2(spec, state):
    import random as _random

    from consensus_specs_tpu.testing.helpers.multi_operations import (
        run_test_full_random_operations,
    )
    yield from run_test_full_random_operations(spec, state, _random.Random(2090))
