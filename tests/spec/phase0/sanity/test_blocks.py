"""Block-processing sanity tests (reference: test/phase0/sanity/test_blocks.py,
representative subset)."""
from consensus_specs_tpu.testing.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.attestations import get_valid_attestation
from consensus_specs_tpu.testing.helpers.block import (
    build_empty_block,
    build_empty_block_for_next_slot,
    sign_block,
)
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    next_slot,
    state_transition_and_sign_block,
)


@with_all_phases
@spec_state_test
def test_prev_slot_block_transition(spec, state):
    # Go to clean slot
    spec.process_slots(state, state.slot + 1)
    # Make a block for it
    block = build_empty_block(spec, state, slot=state.slot)
    proposer_index = spec.get_beacon_proposer_index(state)
    # Transition to next slot, above block will not be invalid on top of new state.
    spec.process_slots(state, state.slot + 1)

    yield "pre", state
    # State is beyond block slot, but the block can still be realistic when invalid.
    # Try the transition, and update the state root to where it is halted. Then sign with the supposed proposer.
    expect_assertion_error(lambda: spec.process_block(state, block))
    block.state_root = state.hash_tree_root()
    signed_block = sign_block(spec, state, block, proposer_index=proposer_index)
    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_same_slot_block_transition(spec, state):
    # Same slot on top of pre-state, but move out of slot 0 first.
    spec.process_slots(state, state.slot + 1)

    block = build_empty_block(spec, state, slot=state.slot)

    yield "pre", state

    assert state.slot == block.slot

    spec.process_block(state, block)
    block.state_root = state.hash_tree_root()

    signed_block = sign_block(spec, state, block)

    yield "blocks", [signed_block]
    yield "post", state


@with_all_phases
@spec_state_test
def test_empty_block_transition(spec, state):
    pre_slot = state.slot
    pre_eth1_votes = len(state.eth1_data_votes)
    pre_mix = spec.get_randao_mix(state, spec.get_current_epoch(state))

    yield "pre", state

    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)

    yield "blocks", [signed_block]
    yield "post", state

    assert len(state.eth1_data_votes) == pre_eth1_votes + 1
    assert spec.get_block_root_at_slot(state, pre_slot) == block.parent_root
    assert spec.get_randao_mix(state, spec.get_current_epoch(state)) != pre_mix


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_state_root(spec, state):
    yield "pre", state

    block = build_empty_block_for_next_slot(spec, state)
    block.state_root = b"\xaa" * 32
    signed_block = sign_block(spec, state, block)

    expect_assertion_error(
        lambda: spec.state_transition(state, signed_block, validate_result=True))

    yield "blocks", [signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
@always_bls
def test_zero_block_sig(spec, state):
    yield "pre", state

    block = build_empty_block_for_next_slot(spec, state)
    invalid_signed_block = spec.SignedBeaconBlock(message=block)
    expect_assertion_error(
        lambda: spec.state_transition(state, invalid_signed_block))

    yield "blocks", [invalid_signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_block_sig(spec, state):
    yield "pre", state

    block = build_empty_block_for_next_slot(spec, state)

    domain = spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER, spec.compute_epoch_at_slot(block.slot))
    signing_root = spec.compute_signing_root(block, domain)
    invalid_signed_block = spec.SignedBeaconBlock(
        message=block,
        signature=spec.bls.Sign(123456, signing_root),
    )
    expect_assertion_error(
        lambda: spec.state_transition(state, invalid_signed_block))

    yield "blocks", [invalid_signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_proposer_index_sig_from_expected_proposer(spec, state):
    yield "pre", state

    block = build_empty_block_for_next_slot(spec, state)
    expect_proposer_index = block.proposer_index

    # Set invalid proposer index but correct signature wrt expected proposer
    active_indices = spec.get_active_validator_indices(state, spec.get_current_epoch(state))
    active_indices = [i for i in active_indices if i != block.proposer_index]
    block.proposer_index = active_indices[0]  # invalid proposer index

    invalid_signed_block = sign_block(spec, state, block, expect_proposer_index)

    expect_assertion_error(
        lambda: spec.state_transition(state, invalid_signed_block))

    yield "blocks", [invalid_signed_block]
    yield "post", None


@with_all_phases
@spec_state_test
def test_skipped_slots(spec, state):
    pre_slot = state.slot
    yield "pre", state

    block = build_empty_block(spec, state, state.slot + 4)
    signed_block = state_transition_and_sign_block(spec, state, block)

    yield "blocks", [signed_block]
    yield "post", state

    assert state.slot == block.slot
    assert spec.get_randao_mix(state, spec.get_current_epoch(state)) != spec.Bytes32()
    for slot in range(pre_slot, state.slot):
        assert spec.get_block_root_at_slot(state, slot) == block.parent_root


@with_all_phases
@spec_state_test
def test_empty_epoch_transition(spec, state):
    pre_slot = state.slot
    yield "pre", state

    block = build_empty_block(spec, state, state.slot + spec.SLOTS_PER_EPOCH)
    signed_block = state_transition_and_sign_block(spec, state, block)

    yield "blocks", [signed_block]
    yield "post", state

    assert state.slot == block.slot
    for slot in range(pre_slot, state.slot):
        assert spec.get_block_root_at_slot(state, slot) == block.parent_root


@with_all_phases
@spec_state_test
def test_high_proposer_index(spec, state):
    # disable a good amount of validators to make the active count lower, for a faster test
    current_epoch = spec.get_current_epoch(state)
    for i in range(len(state.validators) // 3):
        state.validators[i].exit_epoch = current_epoch

    # skip forward, get brand new proposers
    state.slot = spec.SLOTS_PER_EPOCH * 2
    block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, block)

    active_count = len(spec.get_active_validator_indices(state, current_epoch))
    while True:
        proposer_index = spec.get_beacon_proposer_index(state)
        if proposer_index >= active_count:
            # found a proposer that has a higher index than the active validator count
            yield "pre", state
            # test if the proposer can be recognized correctly, even while it has a high index
            signed_block = state_transition_and_sign_block(
                spec, state, build_empty_block_for_next_slot(spec, state))
            yield "blocks", [signed_block]
            yield "post", state
            break
        next_slot(spec, state)


@with_all_phases
@spec_state_test
def test_attestation(spec, state):
    next_epoch(spec, state)

    yield "pre", state

    attestation_block = build_empty_block(
        spec, state, state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)

    index = 0
    attestation = get_valid_attestation(spec, state, index=index, signed=True)

    # Add to state via block transition
    pre_current_attestations_len = (
        len(state.current_epoch_attestations) if spec.fork == "phase0" else None
    )
    attestation_block.body.attestations.append(attestation)
    signed_attestation_block = state_transition_and_sign_block(spec, state, attestation_block)

    if spec.fork == "phase0":
        assert len(state.current_epoch_attestations) == pre_current_attestations_len + 1
        # Epoch transition should move to previous_epoch_attestations
        pre_current_attestations_root = spec.hash_tree_root(state.current_epoch_attestations)
    else:
        pre_current_epoch_participation_root = spec.hash_tree_root(state.current_epoch_participation)

    epoch_block = build_empty_block(spec, state, state.slot + spec.SLOTS_PER_EPOCH)
    signed_epoch_block = state_transition_and_sign_block(spec, state, epoch_block)

    yield "blocks", [signed_attestation_block, signed_epoch_block]
    yield "post", state

    if spec.fork == "phase0":
        assert len(state.current_epoch_attestations) == 0
        assert spec.hash_tree_root(state.previous_epoch_attestations) == pre_current_attestations_root
    else:
        for index in range(len(state.validators)):
            assert state.current_epoch_participation[index] == spec.ParticipationFlags(0b0000_0000)
        assert spec.hash_tree_root(state.previous_epoch_participation) == pre_current_epoch_participation_root


@with_all_phases
@spec_state_test
def test_balance_driven_status_transitions(spec, state):
    current_epoch = spec.get_current_epoch(state)
    validator_index = spec.get_active_validator_indices(state, current_epoch)[-1]

    assert state.validators[validator_index].exit_epoch == spec.FAR_FUTURE_EPOCH

    # set validator balance to below ejection threshold
    state.validators[validator_index].effective_balance = spec.config.EJECTION_BALANCE

    yield "pre", state

    # trigger epoch transition
    block = build_empty_block(spec, state, state.slot + spec.SLOTS_PER_EPOCH)
    signed_block = state_transition_and_sign_block(spec, state, block)

    yield "blocks", [signed_block]
    yield "post", state

    assert state.validators[validator_index].exit_epoch < spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_eth1_data_votes_consensus(spec, state):
    voting_period_slots = spec.EPOCHS_PER_ETH1_VOTING_PERIOD * spec.SLOTS_PER_EPOCH

    offset_block = build_empty_block(spec, state, slot=voting_period_slots - 1)
    state_transition_and_sign_block(spec, state, offset_block)
    yield "pre", state

    a = b"\xaa" * 32
    b = b"\xbb" * 32
    c = b"\xcc" * 32

    blocks = []

    for i in range(0, voting_period_slots):
        block = build_empty_block_for_next_slot(spec, state)
        # wait for over 50% for A, then start voting B
        block.body.eth1_data.block_hash = b if i * 2 > voting_period_slots else a
        signed_block = state_transition_and_sign_block(spec, state, block)
        blocks.append(signed_block)

    assert len(state.eth1_data_votes) == voting_period_slots
    assert state.eth1_data.block_hash == a

    # transition to next eth1 voting period
    block = build_empty_block_for_next_slot(spec, state)
    block.body.eth1_data.block_hash = c
    signed_block = state_transition_and_sign_block(spec, state, block)
    blocks.append(signed_block)

    yield "blocks", blocks
    yield "post", state

    assert state.eth1_data.block_hash == a
    assert state.slot % voting_period_slots == 0
    assert len(state.eth1_data_votes) == 1
    assert state.eth1_data_votes[0].block_hash == c
