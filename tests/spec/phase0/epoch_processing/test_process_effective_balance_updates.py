"""process_effective_balance_updates suite: hysteresis thresholds in both
directions (spec: phase0/beacon-chain.md process_effective_balance_updates;
reference suite: test/phase0/epoch_processing/test_process_effective_balance_updates.py)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.epoch_processing import (
    run_epoch_processing_with,
)


@with_all_phases
@spec_state_test
def test_effective_balance_hysteresis(spec, state):
    # laid out as (balance, pre-effective, post-effective) probes around the
    # hysteresis thresholds
    max_eff = int(spec.MAX_EFFECTIVE_BALANCE)
    min_dep = int(spec.config.EJECTION_BALANCE)  # just a small anchor
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    half_inc = inc // 2
    quarter_inc = inc // 4

    # change happens iff balance + DOWNWARD(inc/4) < eff, or
    # eff + UPWARD(5*inc/4) < balance; new eff = min(floor(balance), MAX)
    cases = [
        (max_eff, max_eff, max_eff, "as-is"),
        (max_eff, max_eff - 1, max_eff - 1, "tiny drift inside upward band: unchanged"),
        (max_eff + 1, max_eff, max_eff, "above max: unchanged"),
        (max_eff - quarter_inc, max_eff, max_eff, "inside downward band"),
        (max_eff - half_inc - 1, max_eff, max_eff - inc, "outside downward band"),
        (max_eff + inc, max_eff, max_eff, "upward inside band (capped anyway)"),
        (max_eff - inc - half_inc - 1, max_eff, max_eff - 2 * inc, "two increments down"),
        (max_eff - inc + quarter_inc, max_eff - inc, max_eff - inc, "inside band from below"),
        (max_eff + quarter_inc + 1, max_eff - inc, max_eff, "outside upward band: rises"),
        (min_dep, max_eff, min_dep - min_dep % inc, "collapse to floor"),
    ]
    assert len(state.validators) >= len(cases)
    for i, (balance, pre_eff, _, _) in enumerate(cases):
        state.balances[i] = balance
        state.validators[i].effective_balance = pre_eff

    yield from run_epoch_processing_with(
        spec, state, "process_effective_balance_updates"
    )

    for i, (_, _, post_eff, label) in enumerate(cases):
        assert int(state.validators[i].effective_balance) == post_eff, label
