"""process_slashings suite: correlated-penalty application at the
half-way-to-withdrawable epoch (spec: phase0/beacon-chain.md
process_slashings; reference suite:
test/phase0/epoch_processing/test_process_slashings.py)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.epoch_processing import (
    run_epoch_processing_to,
    run_epoch_processing_with,
)
from consensus_specs_tpu.testing.helpers.state import get_balance


def _slash_validators(spec, state, indices, out_epochs):
    total_slashed_balance = 0
    for index, out_epoch in zip(indices, out_epochs):
        v = state.validators[index]
        v.slashed = True
        spec.initiate_validator_exit(state, index)
        v.withdrawable_epoch = out_epoch
        total_slashed_balance += int(v.effective_balance)
    state.slashings[
        int(spec.get_current_epoch(state)) % int(spec.EPOCHS_PER_SLASHINGS_VECTOR)
    ] = total_slashed_balance


def _in_window(spec, state):
    return int(spec.get_current_epoch(state) + spec.EPOCHS_PER_SLASHINGS_VECTOR // 2)


@with_all_phases
@spec_state_test
def test_max_penalties(spec, state):
    # slash enough stake that the proportional multiplier saturates
    slashed_count = len(state.validators) // 3 + 1
    # the sub-transition runs at the boundary slot, current epoch unchanged
    out_epoch = _in_window(spec, state)
    indices = list(range(slashed_count))
    _slash_validators(spec, state, indices, [out_epoch] * slashed_count)

    total_balance = int(spec.get_total_active_balance(state))
    total_penalties = sum(int(x) for x in state.slashings)
    assert total_balance // 3 <= total_penalties

    run_epoch_processing_to(spec, state, "process_slashings")
    pre_balances = [int(state.balances[i]) for i in indices]
    yield "pre", state
    spec.process_slashings(state)
    yield "post", state
    # per-fork proportional multiplier: reuse the builder's single
    # fork->constant mapping (all fork constants are preset-injected
    # globals, so presence probing would pick the wrong one)
    from consensus_specs_tpu.specs.builder import _SLASHING_MULT

    mult = getattr(spec, _SLASHING_MULT[spec.fork])
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    adjusted = min(total_penalties * int(mult), total_balance)
    for i, pre in zip(indices, pre_balances):
        eff = int(state.validators[i].effective_balance)
        expected_penalty = eff // inc * adjusted // total_balance * inc
        assert int(state.balances[i]) == max(0, pre - expected_penalty)


@with_all_phases
@spec_state_test
def test_low_penalty(spec, state):
    # one slashed validator out of many: penalty is proportional — and
    # preset-dependent (on mainnet-sized registries the integer division
    # legitimately floors to zero), so pin the exact spec formula
    _slash_validators(spec, state, [5], [_in_window(spec, state)])
    pre = get_balance(state, 5)
    yield from run_epoch_processing_with(spec, state, "process_slashings")
    post = get_balance(state, 5)

    increment = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    total = int(spec.get_total_active_balance(state))
    eff = int(state.validators[5].effective_balance)
    from consensus_specs_tpu.specs.builder import _SLASHING_MULT

    mult = int(getattr(spec, _SLASHING_MULT[spec.fork]))
    adjusted = min(sum(int(x) for x in state.slashings) * mult, total)
    expected = eff // increment * adjusted // total * increment
    assert post == pre - expected


@with_all_phases
@spec_state_test
def test_no_penalty_outside_window(spec, state):
    # withdrawable epoch NOT at the halfway point: no penalty this epoch
    out_epoch = _in_window(spec, state) + 10
    _slash_validators(spec, state, [3], [out_epoch])
    run_epoch_processing_to(spec, state, "process_slashings")
    pre = get_balance(state, 3)
    yield "pre", state
    spec.process_slashings(state)
    yield "post", state
    assert get_balance(state, 3) == pre


@with_all_phases
@spec_state_test
def test_empty_slashings(spec, state):
    pre_balances = [int(b) for b in state.balances]
    yield from run_epoch_processing_with(spec, state, "process_slashings")
    assert [int(b) for b in state.balances] == pre_balances
