"""process_registry_updates suite: activation queueing/dequeueing under
the churn limit and ejections (spec: phase0/beacon-chain.md
process_registry_updates; reference suite:
test/phase0/epoch_processing/test_process_registry_updates.py)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.deposits import mock_deposit
from consensus_specs_tpu.testing.helpers.epoch_processing import (
    run_epoch_processing_with,
)
from consensus_specs_tpu.testing.helpers.state import next_epoch


def run_process_registry_updates(spec, state):
    yield from run_epoch_processing_with(spec, state, "process_registry_updates")


@with_all_phases
@spec_state_test
def test_add_to_activation_queue(spec, state):
    index = 0
    mock_deposit(spec, state, index)
    yield from run_process_registry_updates(spec, state)
    # queued but not yet eligible for activation (not finalized yet)
    assert state.validators[index].activation_eligibility_epoch != spec.FAR_FUTURE_EPOCH
    assert state.validators[index].activation_epoch == spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_activation_queue_to_activated_if_finalized(spec, state):
    # advance so a finalized epoch > eligibility epoch is coherent (the
    # rewards pass computes prev_epoch - finalized_epoch in uint64)
    for _ in range(5):
        next_epoch(spec, state)
    index = 0
    mock_deposit(spec, state, index)
    state.validators[index].activation_eligibility_epoch = 1
    state.finalized_checkpoint.epoch = 3
    assert not spec.is_active_validator(
        state.validators[index], spec.get_current_epoch(state))
    yield from run_process_registry_updates(spec, state)
    v = state.validators[index]
    assert v.activation_epoch != spec.FAR_FUTURE_EPOCH
    assert spec.is_active_validator(v, v.activation_epoch)


@with_all_phases
@spec_state_test
def test_activation_queue_not_finalized_stays_queued(spec, state):
    index = 0
    mock_deposit(spec, state, index)
    state.validators[index].activation_eligibility_epoch = (
        state.finalized_checkpoint.epoch + 1
    )
    yield from run_process_registry_updates(spec, state)
    assert state.validators[index].activation_epoch == spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_activation_queue_sorted_by_eligibility_then_index(spec, state):
    churn = int(spec.get_validator_churn_limit(state))
    n_candidates = churn + 2
    assert len(state.validators) > n_candidates
    state.finalized_checkpoint.epoch = 10
    # later indices get EARLIER eligibility epochs: they must win the queue
    for i in range(n_candidates):
        mock_deposit(spec, state, i)
        state.validators[i].activation_eligibility_epoch = n_candidates - i
    yield from run_process_registry_updates(spec, state)
    activated = [
        i for i in range(n_candidates)
        if state.validators[i].activation_epoch != spec.FAR_FUTURE_EPOCH
    ]
    # the churn-many validators with smallest (eligibility, index) activate
    expected = sorted(
        range(n_candidates),
        key=lambda i: (int(state.validators[i].activation_eligibility_epoch), i),
    )[:churn]
    assert sorted(activated) == sorted(expected)


@with_all_phases
@spec_state_test
def test_ejection(spec, state):
    index = 0
    assert spec.is_active_validator(state.validators[index],
                                    spec.get_current_epoch(state))
    state.validators[index].effective_balance = spec.config.EJECTION_BALANCE
    yield from run_process_registry_updates(spec, state)
    assert state.validators[index].exit_epoch != spec.FAR_FUTURE_EPOCH
    assert not spec.is_active_validator(
        state.validators[index],
        spec.compute_activation_exit_epoch(spec.get_current_epoch(state)),
    )
