"""process_justification_and_finalization suite: the four FFG finality
rules driven by crafted checkpoint/bit patterns (spec:
phase0/beacon-chain.md weigh_justification_and_finalization; reference
suite: test/phase0/epoch_processing/test_process_justification_and_finalization.py)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_all_phases,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.epoch_processing import (
    run_epoch_processing_with,
)
from consensus_specs_tpu.testing.helpers.state import transition_to


def _skip_to_epoch(spec, state, epoch):
    transition_to(spec, state, epoch * spec.SLOTS_PER_EPOCH)


def _fill_prev_epoch_target_attestations(spec, state):
    """Craft full-weight previous-epoch target attestations directly (no
    slot transitions, so justification state is untouched until the
    handler under test runs)."""
    prev = spec.get_previous_epoch(state)
    start = int(spec.compute_start_slot_at_epoch(prev))
    for slot in range(start, start + int(spec.SLOTS_PER_EPOCH)):
        for index in range(int(spec.get_committee_count_per_slot(state, prev))):
            committee = spec.get_beacon_committee(state, slot, index)
            data = spec.AttestationData(
                slot=slot, index=index,
                beacon_block_root=spec.get_block_root_at_slot(state, slot),
                source=state.previous_justified_checkpoint,
                target=spec.Checkpoint(
                    epoch=prev, root=spec.get_block_root(state, prev)),
            )
            state.previous_epoch_attestations.append(spec.PendingAttestation(
                aggregation_bits=[True] * len(committee),
                data=data, inclusion_delay=1, proposer_index=0,
            ))


@with_phases(["phase0"])
@spec_state_test
def test_full_participation_justifies_previous_epoch(spec, state):
    _skip_to_epoch(spec, state, 3)
    _fill_prev_epoch_target_attestations(spec, state)
    prev = spec.get_previous_epoch(state)
    assert int(state.current_justified_checkpoint.epoch) < prev
    yield from run_epoch_processing_with(
        spec, state, "process_justification_and_finalization"
    )
    assert int(state.current_justified_checkpoint.epoch) == int(prev)


@with_all_phases
@spec_state_test
def test_no_attestations_no_justification(spec, state):
    _skip_to_epoch(spec, state, 3)
    pre_cp = state.current_justified_checkpoint.copy()
    yield from run_epoch_processing_with(
        spec, state, "process_justification_and_finalization"
    )
    assert state.current_justified_checkpoint == pre_cp
    assert int(state.finalized_checkpoint.epoch) == 0


@with_all_phases
@spec_state_test
def test_first_two_epochs_skip_ffg(spec, state):
    # current epoch <= GENESIS_EPOCH + 1: checkpoints/bits must not move
    pre_bits = state.justification_bits.encode_bytes()
    pre_cp = state.current_justified_checkpoint.copy()
    yield from run_epoch_processing_with(
        spec, state, "process_justification_and_finalization"
    )
    assert state.justification_bits.encode_bytes() == pre_bits
    assert state.current_justified_checkpoint == pre_cp
    assert int(state.finalized_checkpoint.epoch) == 0


@with_phases(["phase0"])
@spec_state_test
def test_sustained_participation_finalizes(spec, state):
    """Two consecutively-justified epochs finalize the older one (rule 23):
    justify epochs 2 and 3 by hand, fill epoch-3-target attestations, and
    the handler must finalize epoch 2."""
    _skip_to_epoch(spec, state, 4)
    b = state.justification_bits
    b[0] = True  # epoch 3 justified (bit 0 = previous epoch slot)
    b[1] = True  # epoch 2 justified
    state.previous_justified_checkpoint = spec.Checkpoint(
        epoch=2, root=spec.get_block_root(state, 2))
    state.current_justified_checkpoint = spec.Checkpoint(
        epoch=3, root=spec.get_block_root(state, 3))
    _fill_prev_epoch_target_attestations(spec, state)
    yield from run_epoch_processing_with(
        spec, state, "process_justification_and_finalization"
    )
    assert int(state.current_justified_checkpoint.epoch) == 3
    assert int(state.finalized_checkpoint.epoch) >= 2
