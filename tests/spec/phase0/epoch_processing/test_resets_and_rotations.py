"""Small epoch sub-transitions: eth1 vote reset, slashings vector reset,
randao mix rotation, historical roots accumulator, participation record
rotation (spec: phase0/beacon-chain.md process_* final updates; reference
suites: test/phase0/epoch_processing/test_process_{eth1_data_reset,
slashings_reset,randao_mixes_reset,historical_roots_update,
participation_record_updates}.py)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_all_phases,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.epoch_processing import (
    run_epoch_processing_with,
)
from consensus_specs_tpu.testing.helpers.state import transition_to


@with_all_phases
@spec_state_test
def test_eth1_vote_no_reset(spec, state):
    assert spec.EPOCHS_PER_ETH1_VOTING_PERIOD > 1
    # skip ahead to the epoch BEFORE the voting period boundary
    transition_to(
        spec, state,
        spec.SLOTS_PER_EPOCH * (spec.EPOCHS_PER_ETH1_VOTING_PERIOD - 2),
    )
    for i in range(state.slot + 1):
        state.eth1_data_votes.append(spec.Eth1Data(deposit_count=i))
    pre_count = len(state.eth1_data_votes)
    yield from run_epoch_processing_with(spec, state, "process_eth1_data_reset")
    assert len(state.eth1_data_votes) == pre_count


@with_all_phases
@spec_state_test
def test_eth1_vote_reset(spec, state):
    transition_to(
        spec, state,
        spec.SLOTS_PER_EPOCH * spec.EPOCHS_PER_ETH1_VOTING_PERIOD - spec.SLOTS_PER_EPOCH,
    )
    for i in range(3):
        state.eth1_data_votes.append(spec.Eth1Data(deposit_count=i))
    yield from run_epoch_processing_with(spec, state, "process_eth1_data_reset")
    assert len(state.eth1_data_votes) == 0


@with_all_phases
@spec_state_test
def test_slashings_reset(spec, state):
    next_epoch_slot_index = (
        int(spec.get_current_epoch(state)) + 1
    ) % int(spec.EPOCHS_PER_SLASHINGS_VECTOR)
    state.slashings[next_epoch_slot_index] = spec.Gwei(5 * 10**9)
    yield from run_epoch_processing_with(spec, state, "process_slashings_reset")
    assert int(state.slashings[next_epoch_slot_index]) == 0


@with_all_phases
@spec_state_test
def test_randao_mixes_rotation(spec, state):
    current_epoch = int(spec.get_current_epoch(state))
    vector_len = int(spec.EPOCHS_PER_HISTORICAL_VECTOR)
    mix = spec.get_randao_mix(state, current_epoch)
    yield from run_epoch_processing_with(spec, state, "process_randao_mixes_reset")
    assert state.randao_mixes[(current_epoch + 1) % vector_len] == mix


@with_all_phases
@spec_state_test
def test_historical_roots_accumulator(spec, state):
    from consensus_specs_tpu.testing.helpers.epoch_processing import (
        run_epoch_processing_to,
    )

    period_slots = int(spec.SLOTS_PER_HISTORICAL_ROOT)
    transition_to(spec, state, period_slots - 2)
    pre_len = len(state.historical_roots)
    # snapshot the roots AFTER the runner's slot processing, right before
    # the sub-transition itself
    run_epoch_processing_to(spec, state, "process_historical_roots_update")
    expected = spec.hash_tree_root(spec.HistoricalBatch(
        block_roots=state.block_roots,
        state_roots=state.state_roots,
    ))
    yield "sub_transition", "meta", "process_historical_roots_update"
    yield "pre", state
    spec.process_historical_roots_update(state)
    yield "post", state
    assert len(state.historical_roots) == pre_len + 1
    assert state.historical_roots[-1] == expected


@with_phases(["phase0"])
@spec_state_test
def test_participation_record_rotation(spec, state):
    from consensus_specs_tpu.testing.helpers.attestations import (
        prepare_state_with_attestations,
    )

    prepare_state_with_attestations(spec, state)
    current = [a.copy() for a in state.current_epoch_attestations]
    assert len(state.previous_epoch_attestations) > 0
    yield from run_epoch_processing_with(
        spec, state, "process_participation_record_updates"
    )
    assert len(state.current_epoch_attestations) == 0
    assert [a.hash_tree_root() for a in state.previous_epoch_attestations] == [
        a.hash_tree_root() for a in current
    ]
