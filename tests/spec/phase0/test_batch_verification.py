"""Differential tests for the deferred (batched) block signature path.

The sanctioned substitution wraps ``process_block`` in
``bls.deferred_fast_aggregate_verify`` (specs/builder.py), collapsing a
block's aggregate checks into one RLC pairing product with a single final
exponentiation.  These tests pin the substitution to the sequential spec
path: identical post-states on valid blocks, identical rejection (with the
first failing check attributed) on invalid ones.  Reference analogue for
the substitution pattern: setup.py:488-492.
"""
import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.testing.context import (
    always_bls,
    spec_state_test,
    with_all_phases,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.attestations import get_valid_attestation
from consensus_specs_tpu.testing.helpers.block import build_empty_block
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    state_transition_and_sign_block,
)


def _block_with_attestations(spec, state, n_atts=2, tamper_index=None):
    next_epoch(spec, state)
    block = build_empty_block(
        spec, state, state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)
    for i in range(n_atts):
        att = get_valid_attestation(spec, state, index=i, signed=True)
        if tamper_index is not None and i == tamper_index:
            att.signature = spec.BLSSignature(b"\x11" + bytes(att.signature)[1:])
        block.body.attestations.append(att)
    return block


@with_all_phases
@spec_state_test
@always_bls
def test_batched_block_equals_sequential(spec, state):
    """Valid attestation-bearing block: the deferred path and the sequential
    (__wrapped__) path must produce byte-identical post-states."""
    seq_state = state.copy()

    block = _block_with_attestations(spec, state, n_atts=2)
    seq_block = block.copy()

    signed = state_transition_and_sign_block(spec, state, block)

    # replay through the unwrapped sequential process_block
    batched = spec.process_block
    assert hasattr(batched, "__wrapped__"), "substitution must be installed"
    spec.process_block = batched.__wrapped__
    try:
        seq_signed = state_transition_and_sign_block(spec, seq_state, seq_block)
    finally:
        spec.process_block = batched

    assert signed.hash_tree_root() == seq_signed.hash_tree_root()
    assert state.hash_tree_root() == seq_state.hash_tree_root()
    yield "post", state


@with_all_phases
@spec_state_test
@always_bls
def test_batched_block_rejects_bad_signature(spec, state):
    """One tampered attestation signature: state_transition must reject the
    block (AssertionError) through the deferred path."""
    block = _block_with_attestations(spec, state, n_atts=2, tamper_index=1)
    with pytest.raises(AssertionError):
        state_transition_and_sign_block(spec, state, block)
    yield "post", None


@with_phases(["phase0"])
@spec_state_test
@always_bls
def test_deferred_scope_collects_block_checks(spec, state):
    """The substitution actually engages: FastAggregateVerify calls made
    during process_block are deferred, verified once as a batch."""
    calls = []
    orig_batch = bls._batch_verify

    def counting_batch(entries):
        calls.append(len(entries))
        return orig_batch(entries)

    block = _block_with_attestations(spec, state, n_atts=2)
    bls._batch_verify = counting_batch
    try:
        state_transition_and_sign_block(spec, state, block)
    finally:
        bls._batch_verify = orig_batch

    assert calls == [2], f"expected one batch of 2 attestation checks, got {calls}"
    yield "post", state
