"""Config/preset invariants (reference suite:
test/phase0/unittests/test_config_invariants.py): the cross-constant
relations every fork×preset build must satisfy."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_all_phases,
)


def _check_unique(values):
    as_bytes = [bytes(v) for v in values]
    assert len(set(as_bytes)) == len(as_bytes)


@with_all_phases
@spec_state_test
def test_time(spec, state):
    yield "meta", {"bls_setting": 2}
    assert int(spec.SLOTS_PER_EPOCH) <= int(spec.SLOTS_PER_HISTORICAL_ROOT)
    assert int(spec.MIN_SEED_LOOKAHEAD) < int(spec.MAX_SEED_LOOKAHEAD)
    assert int(spec.SLOTS_PER_HISTORICAL_ROOT) % int(spec.SLOTS_PER_EPOCH) == 0
    assert int(spec.SLOTS_PER_HISTORICAL_ROOT) <= \
        int(spec.HISTORICAL_ROOTS_LIMIT) * int(spec.SLOTS_PER_EPOCH)
    assert int(spec.MIN_ATTESTATION_INCLUSION_DELAY) <= int(spec.SLOTS_PER_EPOCH)


@with_all_phases
@spec_state_test
def test_balances(spec, state):
    yield "meta", {"bls_setting": 2}
    assert int(spec.MIN_DEPOSIT_AMOUNT) <= int(spec.MAX_EFFECTIVE_BALANCE)
    assert int(spec.MAX_EFFECTIVE_BALANCE) % int(spec.EFFECTIVE_BALANCE_INCREMENT) == 0
    assert int(spec.config.EJECTION_BALANCE) < int(spec.MAX_EFFECTIVE_BALANCE)
    assert int(spec.HYSTERESIS_QUOTIENT) > 0
    assert int(spec.HYSTERESIS_UPWARD_MULTIPLIER) > \
        int(spec.HYSTERESIS_DOWNWARD_MULTIPLIER)


@with_all_phases
@spec_state_test
def test_containers_and_committees(spec, state):
    yield "meta", {"bls_setting": 2}
    assert int(spec.TARGET_COMMITTEE_SIZE) <= int(spec.MAX_VALIDATORS_PER_COMMITTEE)
    assert int(spec.MAX_COMMITTEES_PER_SLOT) >= 1
    assert int(spec.SHUFFLE_ROUND_COUNT) > 0
    # the justification bitvector must cover the FFG lookback
    assert int(spec.JUSTIFICATION_BITS_LENGTH) == 4
    # registry limit fits the effective-balance cache assumptions
    assert int(spec.VALIDATOR_REGISTRY_LIMIT) >= \
        int(spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT)


@with_all_phases
@spec_state_test
def test_domain_types_unique(spec, state):
    yield "meta", {"bls_setting": 2}
    domains = [
        spec.DOMAIN_BEACON_PROPOSER,
        spec.DOMAIN_BEACON_ATTESTER,
        spec.DOMAIN_RANDAO,
        spec.DOMAIN_DEPOSIT,
        spec.DOMAIN_VOLUNTARY_EXIT,
        spec.DOMAIN_SELECTION_PROOF,
        spec.DOMAIN_AGGREGATE_AND_PROOF,
    ]
    if hasattr(spec, "DOMAIN_SYNC_COMMITTEE"):
        domains += [
            spec.DOMAIN_SYNC_COMMITTEE,
            spec.DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
            spec.DOMAIN_CONTRIBUTION_AND_PROOF,
        ]
    _check_unique(domains)


@with_all_phases
@spec_state_test
def test_fork_versions_unique(spec, state):
    yield "meta", {"bls_setting": 2}
    versions = [
        spec.config.GENESIS_FORK_VERSION,
        spec.config.ALTAIR_FORK_VERSION,
        spec.config.BELLATRIX_FORK_VERSION,
        spec.config.CAPELLA_FORK_VERSION,
    ]
    _check_unique(versions)


@with_all_phases
@spec_state_test
def test_incentives_denominators(spec, state):
    yield "meta", {"bls_setting": 2}
    assert int(spec.WHISTLEBLOWER_REWARD_QUOTIENT) > 0
    assert int(spec.MIN_SLASHING_PENALTY_QUOTIENT) > 0
    assert int(spec.BASE_REWARD_FACTOR) > 0
    if hasattr(spec, "INACTIVITY_PENALTY_QUOTIENT_ALTAIR"):
        assert int(spec.INACTIVITY_PENALTY_QUOTIENT_ALTAIR) > 0
    if hasattr(spec, "INACTIVITY_PENALTY_QUOTIENT_BELLATRIX"):
        # the merge tightens the leak (full penalty, spec rationale)
        assert int(spec.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX) <= \
            int(spec.INACTIVITY_PENALTY_QUOTIENT_ALTAIR)
