"""on_tick justified-checkpoint promotion rules (reference suite:
test/phase0/unittests/fork_choice/test_on_tick.py): best_justified is
promoted only at an epoch-boundary tick, only when newer, and only when
its chain contains the store's finalized checkpoint."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.block import build_empty_block_for_next_slot
from consensus_specs_tpu.testing.helpers.fork_choice import (
    get_genesis_forkchoice_store,
)
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    state_transition_and_sign_block,
    transition_to,
)


def _tick_and_check(spec, store, time, expect_promotion=False):
    before = store.justified_checkpoint
    spec.on_tick(store, time)
    assert store.time == time
    if expect_promotion:
        assert store.justified_checkpoint == store.best_justified_checkpoint
        assert store.justified_checkpoint.epoch > before.epoch
        assert store.justified_checkpoint.root != before.root
    else:
        assert store.justified_checkpoint == before


def _register(spec, store, block, state):
    store.blocks[block.hash_tree_root()] = block.copy()
    store.block_states[block.hash_tree_root()] = state.copy()


def _mock_best_justified_chain(spec, state, store):
    """Grow a chain whose epoch-2 block claims an epoch-1 justified
    checkpoint, and point store.best_justified_checkpoint at it."""
    next_epoch(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, block)
    _register(spec, store, block, state)
    anchor_block = block.copy()

    # park at the last slot of the epoch so the next tick is a boundary
    transition_to(
        spec, state,
        state.slot + spec.SLOTS_PER_EPOCH - state.slot % spec.SLOTS_PER_EPOCH - 1)
    block = build_empty_block_for_next_slot(spec, state)
    state.current_justified_checkpoint = spec.Checkpoint(
        epoch=spec.compute_epoch_at_slot(anchor_block.slot),
        root=anchor_block.hash_tree_root())
    state_transition_and_sign_block(spec, state, block)
    _register(spec, store, block, state)
    store.best_justified_checkpoint = state.current_justified_checkpoint.copy()
    return state


@with_all_phases
@spec_state_test
def test_basic(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    _tick_and_check(spec, store, int(store.time) + 1)


@with_all_phases
@spec_state_test
def test_update_justified_single_on_store_finalized_chain(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    state = _mock_best_justified_chain(spec, state, store)
    _tick_and_check(
        spec, store,
        int(store.genesis_time) + int(state.slot) * int(spec.config.SECONDS_PER_SLOT),
        expect_promotion=True)


@with_all_phases
@spec_state_test
def test_update_justified_single_not_on_store_finalized_chain(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    init_state = state.copy()

    # Finalize a block on a DIFFERENT branch than the best-justified chain.
    next_epoch(spec, state)
    rival_block = build_empty_block_for_next_slot(spec, state)
    rival_block.body.graffiti = b"\x11" * 32
    state_transition_and_sign_block(spec, state, rival_block)
    _register(spec, store, rival_block, state)
    store.finalized_checkpoint = spec.Checkpoint(
        epoch=spec.compute_epoch_at_slot(rival_block.slot),
        root=rival_block.hash_tree_root())

    # Best-justified chain grows from genesis, NOT through rival_block.
    state = init_state.copy()
    next_epoch(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.graffiti = b"\x22" * 32
    state_transition_and_sign_block(spec, state, block)
    _register(spec, store, block, state)
    anchor_block = block.copy()
    transition_to(
        spec, state,
        state.slot + spec.SLOTS_PER_EPOCH - state.slot % spec.SLOTS_PER_EPOCH - 1)
    block = build_empty_block_for_next_slot(spec, state)
    state.current_justified_checkpoint = spec.Checkpoint(
        epoch=spec.compute_epoch_at_slot(anchor_block.slot),
        root=anchor_block.hash_tree_root())
    state_transition_and_sign_block(spec, state, block)
    _register(spec, store, block, state)
    store.best_justified_checkpoint = state.current_justified_checkpoint.copy()

    # Boundary tick, but the candidate's chain misses the finalized block.
    _tick_and_check(
        spec, store,
        int(store.genesis_time) + int(state.slot) * int(spec.config.SECONDS_PER_SLOT))


@with_all_phases
@spec_state_test
def test_no_update_same_slot_at_epoch_boundary(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    store.best_justified_checkpoint = spec.Checkpoint(
        epoch=store.justified_checkpoint.epoch + 1, root=b"\x55" * 32)
    # clock already sits exactly on the boundary; +1s is not a new boundary
    store.time = int(spec.config.SECONDS_PER_SLOT) * int(spec.SLOTS_PER_EPOCH)
    _tick_and_check(spec, store, int(store.time) + 1)


@with_all_phases
@spec_state_test
def test_no_update_not_epoch_boundary(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    store.best_justified_checkpoint = spec.Checkpoint(
        epoch=store.justified_checkpoint.epoch + 1, root=b"\x55" * 32)
    _tick_and_check(
        spec, store, int(store.time) + int(spec.config.SECONDS_PER_SLOT))


@with_all_phases
@spec_state_test
def test_no_update_new_justified_equal_epoch(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    per_epoch = int(spec.config.SECONDS_PER_SLOT) * int(spec.SLOTS_PER_EPOCH)
    store.best_justified_checkpoint = spec.Checkpoint(
        epoch=store.justified_checkpoint.epoch + 1, root=b"\x55" * 32)
    store.justified_checkpoint = spec.Checkpoint(
        epoch=store.best_justified_checkpoint.epoch, root=b"\x44" * 32)
    _tick_and_check(spec, store, int(store.time) + per_epoch)


@with_all_phases
@spec_state_test
def test_no_update_new_justified_later_epoch(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    per_epoch = int(spec.config.SECONDS_PER_SLOT) * int(spec.SLOTS_PER_EPOCH)
    store.best_justified_checkpoint = spec.Checkpoint(
        epoch=store.justified_checkpoint.epoch + 1, root=b"\x55" * 32)
    store.justified_checkpoint = spec.Checkpoint(
        epoch=store.best_justified_checkpoint.epoch + 1, root=b"\x44" * 32)
    _tick_and_check(spec, store, int(store.time) + per_epoch)
