"""on_block best-justified bookkeeping when multiple better justifications
arrive outside the safe-slots window (reference suite:
test/phase0/unittests/fork_choice/test_on_block.py)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.block import build_empty_block_for_next_slot
from consensus_specs_tpu.testing.helpers.fork_choice import (
    apply_next_epoch_with_attestations,
    get_genesis_forkchoice_store,
    run_on_block,
)
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    state_transition_and_sign_block,
)


@with_all_phases
@spec_state_test
def test_on_block_outside_safe_slots_and_multiple_better_justified(spec, state):
    """Outside the safe window with a conflicting store.justified_checkpoint,
    each better block only raises best_justified_checkpoint — justified and
    finalized stay put until the next boundary tick."""
    store = get_genesis_forkchoice_store(spec, state)

    next_epoch(spec, state)
    spec.on_tick(store, int(store.genesis_time) + int(state.slot) * int(spec.config.SECONDS_PER_SLOT))
    state, store, last_signed_block = yield from apply_next_epoch_with_attestations(
        spec, state, store, True, False)
    last_block_root = last_signed_block.message.hash_tree_root()

    # Fictitious justified checkpoint that no real chain contains.
    store.justified_checkpoint = spec.Checkpoint(
        epoch=spec.compute_epoch_at_slot(last_signed_block.message.slot),
        root=spec.Root(b"JUSTIFIED".ljust(32, b"\x00")))

    next_epoch(spec, state)
    spec.on_tick(store, int(store.genesis_time) + int(state.slot) * int(spec.config.SECONDS_PER_SLOT))

    # The would-be better justified root, registered but chain-less.
    just_block = build_empty_block_for_next_slot(spec, state)
    store.blocks[just_block.hash_tree_root()] = just_block

    spec.on_tick(store, int(store.time)
                 + int(spec.SAFE_SLOTS_TO_UPDATE_JUSTIFIED) * int(spec.config.SECONDS_PER_SLOT))
    assert (spec.get_current_slot(store) % spec.SLOTS_PER_EPOCH
            >= spec.SAFE_SLOTS_TO_UPDATE_JUSTIFIED)

    finalized_before = store.finalized_checkpoint
    justified_before = store.justified_checkpoint

    best_seen = spec.Checkpoint(epoch=0)
    for bump in range(3, 0, -1):
        parent_state = store.block_states[last_block_root]
        candidate = spec.Checkpoint(
            epoch=justified_before.epoch + bump,
            root=just_block.hash_tree_root())
        if candidate.epoch > best_seen.epoch:
            best_seen = candidate
        parent_state.current_justified_checkpoint = candidate

        block = build_empty_block_for_next_slot(spec, parent_state)
        signed = state_transition_and_sign_block(spec, parent_state.copy(), block)

        # Re-root the parent so the mutated state is reachable from the block.
        patched_parent = store.blocks[last_block_root].copy()
        patched_parent.state_root = parent_state.hash_tree_root()
        store.blocks[block.parent_root] = patched_parent
        store.block_states[block.parent_root] = parent_state.copy()

        run_on_block(spec, store, signed)

    assert store.finalized_checkpoint == finalized_before
    assert store.justified_checkpoint == justified_before
    assert store.best_justified_checkpoint == best_seen
