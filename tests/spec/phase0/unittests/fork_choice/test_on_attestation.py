"""on_attestation validation matrix (reference suite:
test/phase0/unittests/fork_choice/test_on_attestation.py): epoch-window
rules, target/head store-membership and consistency rules, LMD message
recording (phase0/fork-choice.md validate_on_attestation)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.attestations import (
    get_valid_attestation,
    sign_attestation,
)
from consensus_specs_tpu.testing.helpers.block import build_empty_block_for_next_slot
from consensus_specs_tpu.testing.helpers.fork_choice import (
    get_genesis_forkchoice_store,
)
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    next_slot,
    state_transition_and_sign_block,
    transition_to,
)


def _check_on_attestation(spec, state, store, attestation, valid=True):
    """Feed on_attestation; valid deliveries must record the attesters'
    latest LMD message, invalid ones must abort."""
    if not valid:
        try:
            spec.on_attestation(store, attestation)
        except AssertionError:
            return
        raise AssertionError("on_attestation accepted an invalid attestation")

    indexed = spec.get_indexed_attestation(state, attestation)
    spec.on_attestation(store, attestation)
    probe = indexed.attesting_indices[0]
    assert store.latest_messages[probe] == spec.LatestMessage(
        epoch=attestation.data.target.epoch,
        root=attestation.data.beacon_block_root,
    )


def _tick_slots(spec, store, slots):
    spec.on_tick(store, int(store.time) + int(spec.config.SECONDS_PER_SLOT) * int(slots))


def _block_into_store(spec, state, store):
    signed = state_transition_and_sign_block(
        spec, state, build_empty_block_for_next_slot(spec, state))
    spec.on_block(store, signed)
    return signed.message


@with_all_phases
@spec_state_test
def test_on_attestation_current_epoch(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    _tick_slots(spec, store, 2)
    block = _block_into_store(spec, state, store)

    attestation = get_valid_attestation(spec, state, slot=block.slot, signed=True)
    assert attestation.data.target.epoch == spec.GENESIS_EPOCH
    assert spec.compute_epoch_at_slot(spec.get_current_slot(store)) == spec.GENESIS_EPOCH
    _check_on_attestation(spec, state, store, attestation)


@with_all_phases
@spec_state_test
def test_on_attestation_previous_epoch(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    _tick_slots(spec, store, spec.SLOTS_PER_EPOCH)
    block = _block_into_store(spec, state, store)

    attestation = get_valid_attestation(spec, state, slot=block.slot, signed=True)
    assert attestation.data.target.epoch == spec.GENESIS_EPOCH
    assert spec.compute_epoch_at_slot(spec.get_current_slot(store)) == spec.GENESIS_EPOCH + 1
    _check_on_attestation(spec, state, store, attestation)


@with_all_phases
@spec_state_test
def test_on_attestation_past_epoch(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    _tick_slots(spec, store, 2 * spec.SLOTS_PER_EPOCH)
    _block_into_store(spec, state, store)

    # Clock is 2 epochs ahead of the attestation's target: out of window.
    attestation = get_valid_attestation(spec, state, slot=state.slot, signed=True)
    assert attestation.data.target.epoch == spec.GENESIS_EPOCH
    _check_on_attestation(spec, state, store, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_on_attestation_mismatched_target_and_slot(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    _tick_slots(spec, store, spec.SLOTS_PER_EPOCH)
    block = _block_into_store(spec, state, store)

    attestation = get_valid_attestation(spec, state, slot=block.slot)
    attestation.data.target.epoch += 1  # target epoch != slot's epoch
    sign_attestation(spec, state, attestation)
    assert spec.compute_epoch_at_slot(attestation.data.slot) == spec.GENESIS_EPOCH
    _check_on_attestation(spec, state, store, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_on_attestation_inconsistent_target_and_head(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    _tick_slots(spec, store, 2 * spec.SLOTS_PER_EPOCH)

    # Chain 1: empty through the first epoch boundary.
    chain_1 = state.copy()
    next_epoch(spec, chain_1)
    # Chain 2: contains one distinct block, then crosses the boundary.
    chain_2 = state.copy()
    signed_diff = state_transition_and_sign_block(
        spec, chain_2, build_empty_block_for_next_slot(spec, chain_2))
    spec.on_block(store, signed_diff)
    next_epoch(spec, chain_2)
    next_slot(spec, chain_2)

    # Head on chain 1, target checkpoint taken from chain 2: inconsistent.
    head_block = build_empty_block_for_next_slot(spec, chain_1)
    spec.on_block(store, state_transition_and_sign_block(spec, chain_1, head_block))
    attestation = get_valid_attestation(spec, chain_1, slot=head_block.slot, signed=False)
    epoch = spec.compute_epoch_at_slot(attestation.data.slot)
    attestation.data.target = spec.Checkpoint(
        epoch=epoch, root=spec.get_block_root(chain_2, epoch))
    sign_attestation(spec, chain_1, attestation)
    assert spec.get_block_root(chain_1, epoch) != attestation.data.target.root
    _check_on_attestation(spec, state, store, attestation, valid=False)


def _target_block_near_epoch_boundary(spec, state, store, slots_before_boundary):
    _tick_slots(spec, store, spec.SLOTS_PER_EPOCH + 1)
    boundary = spec.compute_start_slot_at_epoch(spec.get_current_epoch(state) + 1)
    transition_to(spec, state, boundary - slots_before_boundary)
    target_block = build_empty_block_for_next_slot(spec, state)
    return target_block, state_transition_and_sign_block(spec, state, target_block)


@with_all_phases
@spec_state_test
def test_on_attestation_target_block_not_in_store(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    target_block, _ = _target_block_near_epoch_boundary(spec, state, store, 1)
    # deliberately NOT delivered to the store
    attestation = get_valid_attestation(spec, state, slot=target_block.slot, signed=True)
    assert attestation.data.target.root == target_block.hash_tree_root()
    _check_on_attestation(spec, state, store, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_on_attestation_target_checkpoint_not_in_store(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    target_block, signed = _target_block_near_epoch_boundary(spec, state, store, 1)
    spec.on_block(store, signed)
    # checkpoint state not yet materialized in store: must be derived
    attestation = get_valid_attestation(spec, state, slot=target_block.slot, signed=True)
    assert attestation.data.target.root == target_block.hash_tree_root()
    _check_on_attestation(spec, state, store, attestation)


@with_all_phases
@spec_state_test
def test_on_attestation_target_checkpoint_not_in_store_diff_slot(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    target_block, signed = _target_block_near_epoch_boundary(spec, state, store, 2)
    spec.on_block(store, signed)
    # attest one (empty) slot after the target block
    attestation_slot = target_block.slot + 1
    transition_to(spec, state, attestation_slot)
    attestation = get_valid_attestation(spec, state, slot=attestation_slot, signed=True)
    assert attestation.data.target.root == target_block.hash_tree_root()
    _check_on_attestation(spec, state, store, attestation)


@with_all_phases
@spec_state_test
def test_on_attestation_beacon_block_not_in_store(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    target_block, signed = _target_block_near_epoch_boundary(spec, state, store, 1)
    spec.on_block(store, signed)

    head_block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, head_block)
    # head block withheld from the store
    attestation = get_valid_attestation(spec, state, slot=head_block.slot, signed=True)
    assert attestation.data.target.root == target_block.hash_tree_root()
    assert attestation.data.beacon_block_root == head_block.hash_tree_root()
    _check_on_attestation(spec, state, store, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_on_attestation_future_epoch(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    _tick_slots(spec, store, 3)
    _block_into_store(spec, state, store)
    next_epoch(spec, state)  # state leaves the store's clock behind
    attestation = get_valid_attestation(spec, state, slot=state.slot, signed=True)
    _check_on_attestation(spec, state, store, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_on_attestation_future_block(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    _tick_slots(spec, store, 5)
    signed = state_transition_and_sign_block(
        spec, state, build_empty_block_for_next_slot(spec, state))
    spec.on_block(store, signed)
    # attestation dated before the block it points at
    attestation = get_valid_attestation(
        spec, state, slot=signed.message.slot - 1, signed=False)
    attestation.data.beacon_block_root = signed.message.hash_tree_root()
    sign_attestation(spec, state, attestation)
    _check_on_attestation(spec, state, store, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_on_attestation_same_slot(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    _tick_slots(spec, store, 1)
    block = _block_into_store(spec, state, store)
    # same-slot delivery violates the one-slot propagation delay
    attestation = get_valid_attestation(spec, state, slot=block.slot, signed=True)
    _check_on_attestation(spec, state, store, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_on_attestation_invalid_attestation(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    _tick_slots(spec, store, 3)
    block = _block_into_store(spec, state, store)
    attestation = get_valid_attestation(spec, state, slot=block.slot, signed=True)
    # out-of-range committee index makes the attestation itself invalid
    attestation.data.index = spec.MAX_COMMITTEES_PER_SLOT * spec.SLOTS_PER_EPOCH
    _check_on_attestation(spec, state, store, attestation, valid=False)
