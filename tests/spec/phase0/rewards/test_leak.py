"""Rewards suite under the inactivity leak (reference suite:
test/phase0/rewards/test_leak.py): every basic scenario re-run after
advancing past MIN_EPOCHS_TO_INACTIVITY_PENALTY, where the component
formulas switch shape (full-base-reward compensation + quadratic
inactivity penalties — phase0/beacon-chain.md get_attestation_component_
deltas / get_inactivity_penalty_deltas)."""
from random import Random

from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.rewards import (
    leaking,
    run_test_all_balances_too_low_for_reward,
    run_test_empty,
    run_test_full_all_correct,
    run_test_full_fraction_incorrect,
    run_test_full_random,
    run_test_low_balances,
    run_test_one_attestation_one_correct,
    run_test_partial,
    run_test_with_exited_validators,
    run_test_with_not_yet_activated_validators,
    run_test_with_slashed_validators,
)

phase0 = with_phases(["phase0"])


@phase0
@spec_state_test
@leaking()
def test_empty_leak(spec, state):
    yield from run_test_empty(spec, state)


@phase0
@spec_state_test
@leaking()
def test_full_leak(spec, state):
    yield from run_test_full_all_correct(spec, state)


@phase0
@spec_state_test
@leaking()
def test_half_full_leak(spec, state):
    yield from run_test_partial(spec, state, 0.5)


@phase0
@spec_state_test
@leaking()
def test_quarter_full_leak(spec, state):
    yield from run_test_partial(spec, state, 0.25)


@phase0
@spec_state_test
@leaking()
def test_one_attestation_one_correct_leak(spec, state):
    yield from run_test_one_attestation_one_correct(spec, state)


@phase0
@spec_state_test
@leaking()
def test_full_but_partial_participation_leak(spec, state):
    yield from run_test_partial(spec, state, 0.7)


@phase0
@spec_state_test
@leaking()
def test_with_not_yet_activated_validators_leak(spec, state):
    yield from run_test_with_not_yet_activated_validators(spec, state)


@phase0
@spec_state_test
@leaking()
def test_with_exited_validators_leak(spec, state):
    yield from run_test_with_exited_validators(spec, state)


@phase0
@spec_state_test
@leaking()
def test_with_slashed_validators_leak(spec, state):
    yield from run_test_with_slashed_validators(spec, state)


@phase0
@spec_state_test
@leaking()
def test_some_very_low_effective_balances_that_attested_leak(spec, state):
    yield from run_test_low_balances(spec, state, attested=True)


@phase0
@spec_state_test
@leaking()
def test_some_very_low_effective_balances_that_did_not_attest_leak(spec, state):
    yield from run_test_low_balances(spec, state, attested=False)


@phase0
@spec_state_test
@leaking()
def test_all_balances_too_low_for_reward_leak(spec, state):
    yield from run_test_all_balances_too_low_for_reward(spec, state)


@phase0
@spec_state_test
@leaking()
def test_full_half_incorrect_target_leak(spec, state):
    yield from run_test_full_fraction_incorrect(
        spec, state, correct_target=False, correct_head=True,
        fraction_incorrect=0.5)


@phase0
@spec_state_test
@leaking()
def test_full_half_incorrect_head_leak(spec, state):
    yield from run_test_full_fraction_incorrect(
        spec, state, correct_target=True, correct_head=False,
        fraction_incorrect=0.5)


@phase0
@spec_state_test
@leaking()
def test_full_all_incorrect_target_and_head_leak(spec, state):
    yield from run_test_full_fraction_incorrect(
        spec, state, correct_target=False, correct_head=False,
        fraction_incorrect=1.0)


@phase0
@spec_state_test
@leaking(epochs_extra=4)
def test_full_deep_leak(spec, state):
    yield from run_test_full_all_correct(spec, state)


@phase0
@spec_state_test
@leaking(epochs_extra=8)
def test_empty_very_deep_leak(spec, state):
    yield from run_test_empty(spec, state)


@phase0
@spec_state_test
@leaking()
def test_full_random_leak_seed_3(spec, state):
    yield from run_test_full_random(spec, state, Random(3))
