"""Rewards suite — basic participation patterns (reference suite:
test/phase0/rewards/test_basic.py); every case is simultaneously a
differential test of the installed deltas kernel (helpers/rewards.py
pins component sums against spec.get_attestation_deltas)."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.attestations import (
    prepare_state_with_attestations,
)
from consensus_specs_tpu.testing.helpers.rewards import leaking, run_deltas
from consensus_specs_tpu.testing.helpers.state import next_epoch


@with_phases(["phase0"])
@spec_state_test
def test_empty(spec, state):
    next_epoch(spec, state)
    yield from run_deltas(spec, state)


@with_phases(["phase0"])
@spec_state_test
def test_full_all_correct(spec, state):
    prepare_state_with_attestations(spec, state)
    yield from run_deltas(spec, state)


@with_phases(["phase0"])
@spec_state_test
def test_half_full(spec, state):
    prepare_state_with_attestations(
        spec, state,
        participation_fn=lambda slot, index, comm: set(list(comm)[: len(comm) // 2]),
    )
    yield from run_deltas(spec, state)


@with_phases(["phase0"])
@spec_state_test
def test_one_attestation_one_correct(spec, state):
    prepare_state_with_attestations(
        spec, state,
        participation_fn=lambda slot, index, comm: (
            set(list(comm)[:1]) if (slot == 0 and index == 0) else set()
        ),
    )
    yield from run_deltas(spec, state)


@with_phases(["phase0"])
@spec_state_test
def test_with_slashed_validators(spec, state):
    prepare_state_with_attestations(spec, state)
    for index in (0, 5, 10):
        state.validators[index].slashed = True
    yield from run_deltas(spec, state)


@with_phases(["phase0"])
@spec_state_test
def test_some_very_low_effective_balances(spec, state):
    prepare_state_with_attestations(spec, state)
    for index in (0, 1, 2):
        state.validators[index].effective_balance = spec.EFFECTIVE_BALANCE_INCREMENT
    yield from run_deltas(spec, state)


@with_phases(["phase0"])
@spec_state_test
@leaking()
def test_empty_leak(spec, state):
    yield from run_deltas(spec, state)


@with_phases(["phase0"])
@spec_state_test
@leaking()
def test_full_leak(spec, state):
    prepare_state_with_attestations(spec, state)
    yield from run_deltas(spec, state)


@with_phases(["phase0"])
@spec_state_test
@leaking(epochs_extra=4)
def test_half_full_deep_leak(spec, state):
    prepare_state_with_attestations(
        spec, state,
        participation_fn=lambda slot, index, comm: set(list(comm)[: len(comm) // 2]),
    )
    yield from run_deltas(spec, state)
