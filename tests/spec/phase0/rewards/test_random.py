"""Rewards suite — randomized registry + participation shapes (reference
suite: test/phase0/rewards/test_random.py).  Each seed drives random exits,
slashings and per-committee participation through the full component
triangulation in helpers/rewards.py."""
from random import Random

from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.rewards import (
    leaking,
    run_test_full_random,
)

phase0 = with_phases(["phase0"])


@phase0
@spec_state_test
def test_full_random_0(spec, state):
    yield from run_test_full_random(spec, state, Random(1010))


@phase0
@spec_state_test
def test_full_random_1(spec, state):
    yield from run_test_full_random(spec, state, Random(2020))


@phase0
@spec_state_test
def test_full_random_2(spec, state):
    yield from run_test_full_random(spec, state, Random(3030))


@phase0
@spec_state_test
def test_full_random_3(spec, state):
    yield from run_test_full_random(spec, state, Random(4040))


@phase0
@spec_state_test
def test_full_random_4(spec, state):
    yield from run_test_full_random(spec, state, Random(5050))


@phase0
@spec_state_test
@leaking()
def test_full_random_leak_0(spec, state):
    yield from run_test_full_random(spec, state, Random(6060))


@phase0
@spec_state_test
@leaking()
def test_full_random_leak_1(spec, state):
    yield from run_test_full_random(spec, state, Random(7070))


@phase0
@spec_state_test
@leaking(epochs_extra=4)
def test_full_random_deep_leak(spec, state):
    yield from run_test_full_random(spec, state, Random(8080))


@phase0
@spec_state_test
def test_full_random_low_balances(spec, state):
    rng = Random(9090)
    for index in rng.sample(range(len(state.validators)), 4):
        state.validators[index].effective_balance = spec.EFFECTIVE_BALANCE_INCREMENT
    yield from run_test_full_random(spec, state, rng)


@phase0
@spec_state_test
def test_full_random_five_epoch_history(spec, state):
    from consensus_specs_tpu.testing.helpers.state import next_epoch

    rng = Random(111)
    for _ in range(5):
        next_epoch(spec, state)
    yield from run_test_full_random(spec, state, rng)
