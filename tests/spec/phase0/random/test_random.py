"""Randomized block scenarios (reference capability: the code-generated
test/phase0/random/test_random.py suite): seeded random walks through
time skips, empty and operation-bearing blocks, with and without the
inactivity leak."""
from functools import partial

from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_phases,
)
from consensus_specs_tpu.testing.random_scenarios import (
    make_random_case,
    run_random_scenario,
)

_make = partial(make_random_case, "phase0")


test_random_0 = _make(100)
test_random_1 = _make(201)
test_random_2 = _make(302)
test_random_3 = _make(403)
test_random_leak_0 = _make(504, with_leak=True, stages=4)
test_random_leak_1 = _make(605, with_leak=True, stages=4)


@with_phases(["phase0"])
@spec_state_test
def test_randomized_state_scenario(spec, state):
    """Compound state randomizer (helpers/random.py) feeding the scenario
    engine: exits, slashings and balance drift survive full transitions."""
    from random import Random

    from consensus_specs_tpu.testing.helpers.random import (
        patch_state_to_non_leaking,
        randomize_state,
    )
    from consensus_specs_tpu.testing.helpers.state import next_epoch

    next_epoch(spec, state)
    randomize_state(spec, state, Random(909), exit_fraction=0.1, slash_fraction=0.05)
    patch_state_to_non_leaking(spec, state)
    yield from run_random_scenario(spec, state, seed=909, stages=4)
