"""Validator-duty unit tests (reference capability:
test/phase0/unittests/validator/test_validator_unittest.py subset):
committee assignment, proposal, aggregation selection, subnets, and the
eth1 vote window."""
from consensus_specs_tpu.testing.context import (
    spec_state_test,
    with_all_phases,
    with_phases,
)
from consensus_specs_tpu.testing.helpers.keys import privkeys


@with_all_phases
@spec_state_test
def test_committee_assignment_covers_every_active_validator_once(spec, state):
    epoch = spec.get_current_epoch(state)
    seen = {}
    for index in spec.get_active_validator_indices(state, epoch):
        assignment = spec.get_committee_assignment(state, epoch, index)
        assert assignment is not None
        committee, committee_index, slot = assignment
        assert index in committee
        assert spec.compute_epoch_at_slot(slot) == epoch
        assert committee_index < spec.get_committee_count_per_slot(state, epoch)
        # each validator attests exactly once per epoch
        assert index not in seen
        seen[index] = (committee_index, slot)
        # the assignment reproduces get_beacon_committee
        assert list(committee) == list(
            spec.get_beacon_committee(state, slot, committee_index))
    yield from ()


@with_all_phases
@spec_state_test
def test_committee_assignment_next_epoch_only(spec, state):
    epoch = spec.get_current_epoch(state)
    index = spec.get_active_validator_indices(state, epoch)[0]
    # assignments are computable for current and next epoch, not beyond
    assert spec.get_committee_assignment(state, epoch, index) is not None
    assert spec.get_committee_assignment(state, epoch + 1, index) is not None
    try:
        spec.get_committee_assignment(state, epoch + 2, index)
        raised = False
    except AssertionError:
        raised = True
    assert raised
    yield from ()


@with_all_phases
@spec_state_test
def test_is_proposer_matches_proposer_index(spec, state):
    proposer = spec.get_beacon_proposer_index(state)
    assert spec.is_proposer(state, proposer)
    epoch = spec.get_current_epoch(state)
    others = [
        i for i in spec.get_active_validator_indices(state, epoch)
        if i != proposer
    ]
    assert not spec.is_proposer(state, others[0])
    yield from ()


@with_all_phases
@spec_state_test
def test_attestation_subnet_is_stable_and_bounded(spec, state):
    epoch = spec.get_current_epoch(state)
    committees_per_slot = spec.get_committee_count_per_slot(state, epoch)
    subnets = set()
    for slot in range(int(spec.SLOTS_PER_EPOCH)):
        for index in range(int(committees_per_slot)):
            subnet = spec.compute_subnet_for_attestation(
                committees_per_slot, spec.Slot(slot), spec.CommitteeIndex(index))
            assert int(subnet) < int(spec.ATTESTATION_SUBNET_COUNT)
            subnets.add(int(subnet))
    assert len(subnets) > 1  # assignments spread across subnets
    yield from ()


@with_all_phases
@spec_state_test
def test_aggregator_selection_is_signature_determined(spec, state):
    slot = state.slot
    epoch = spec.get_current_epoch(state)
    committee_index = spec.CommitteeIndex(0)
    committee = spec.get_beacon_committee(state, slot, committee_index)
    # at minimal committee sizes the aggregation modulo is 1: everyone
    # aggregates, which still exercises signature-domain separation
    decisions = set()
    for index in list(committee)[:4]:
        sig = spec.get_slot_signature(state, slot, privkeys[index])
        decisions.add(bool(spec.is_aggregator(state, slot, committee_index, sig)))
    assert True in decisions or False in decisions
    modulo = max(1, len(committee) // int(spec.TARGET_AGGREGATORS_PER_COMMITTEE))
    if modulo == 1:
        assert decisions == {True}
    yield from ()


@with_phases(["altair"])
@spec_state_test
def test_sync_committee_subnets_bounded(spec, state):
    pubkeys = [v.pubkey for v in state.validators]
    member = pubkeys.index(state.current_sync_committee.pubkeys[0])
    subnets = spec.compute_subnets_for_sync_committee(
        state, spec.ValidatorIndex(member))
    assert len(subnets) >= 1
    for subnet in subnets:
        assert int(subnet) < int(spec.SYNC_COMMITTEE_SUBNET_COUNT)
    yield from ()


@with_phases(["phase0"])
@spec_state_test
def test_eth1_vote_period_boundaries(spec, state):
    period_slots = int(spec.EPOCHS_PER_ETH1_VOTING_PERIOD) * int(spec.SLOTS_PER_EPOCH)
    # voting_period_start_time maths stays consistent across the period
    state.genesis_time = 100
    for slot in (0, 1, period_slots - 1, period_slots):
        state.slot = slot
        start = spec.voting_period_start_time(state)
        expected_start_slot = slot - slot % period_slots
        assert int(start) == 100 + expected_start_slot * int(spec.config.SECONDS_PER_SLOT)
    yield from ()


@with_phases(["phase0"])
@spec_state_test
def test_weak_subjectivity_period_grows_with_balance_churn(spec, state):
    """compute_weak_subjectivity_period: at least
    MIN_VALIDATOR_WITHDRAWABILITY_DELAY, growing with validator count
    (reference: weak-subjectivity.md)."""
    ws = spec.compute_weak_subjectivity_period(state)
    assert int(ws) >= int(spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)

    # a store at the checkpoint is inside the period; one past it is not
    from consensus_specs_tpu.testing.helpers.fork_choice import (
        get_genesis_forkchoice_store,
    )

    ws_state = state.copy()
    ws_checkpoint = spec.Checkpoint(
        epoch=spec.get_current_epoch(ws_state),
        root=ws_state.latest_block_header.state_root,
    )
    store = get_genesis_forkchoice_store(spec, state)
    assert spec.is_within_weak_subjectivity_period(store, ws_state, ws_checkpoint)
    store.time = store.genesis_time + int(spec.config.SECONDS_PER_SLOT) * int(
        spec.SLOTS_PER_EPOCH) * (int(ws) + 2)
    assert not spec.is_within_weak_subjectivity_period(store, ws_state, ws_checkpoint)
    yield from ()
