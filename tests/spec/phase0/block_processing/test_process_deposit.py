"""process_deposit operation suite (spec rules: phase0/beacon-chain.md
process_deposit incl. merkle proof validation, top-ups, invalid-signature
tolerance; reference suite:
test/phase0/block_processing/test_process_deposit.py)."""
from consensus_specs_tpu.testing.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.deposits import prepare_state_and_deposit
from consensus_specs_tpu.testing.helpers.state import get_balance


def run_deposit_processing(spec, state, deposit, validator_index, valid=True,
                           effective=True):
    pre_validator_count = len(state.validators)
    pre_balance = 0
    is_top_up = validator_index < pre_validator_count
    if is_top_up:
        pre_balance = get_balance(state, validator_index)

    yield "pre", state
    yield "deposit", deposit
    if not valid:
        expect_assertion_error(lambda: spec.process_deposit(state, deposit))
        yield "post", None
        return

    spec.process_deposit(state, deposit)
    yield "post", state

    if not effective:
        # invalid signature on a NEW deposit: no-op, never a failure
        assert len(state.validators) == pre_validator_count
        assert len(state.balances) == pre_validator_count
        if is_top_up:
            assert get_balance(state, validator_index) == pre_balance
    else:
        if is_top_up:
            assert len(state.validators) == pre_validator_count
            assert get_balance(state, validator_index) == (
                pre_balance + deposit.data.amount
            )
        else:
            assert len(state.validators) == pre_validator_count + 1
            assert len(state.balances) == pre_validator_count + 1
            assert get_balance(state, validator_index) == deposit.data.amount
    assert state.eth1_deposit_index == state.eth1_data.deposit_count


@with_all_phases
@spec_state_test
def test_new_deposit_under_max(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE - 1
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_new_deposit_max(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_new_deposit_over_max(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE + 1
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_top_up__max_effective_balance(spec, state):
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    state.balances[validator_index] = spec.MAX_EFFECTIVE_BALANCE
    yield from run_deposit_processing(spec, state, deposit, validator_index)
    assert state.validators[validator_index].effective_balance == spec.MAX_EFFECTIVE_BALANCE


@with_all_phases
@spec_state_test
def test_top_up__zero_balance(spec, state):
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    state.balances[validator_index] = 0
    state.validators[validator_index].effective_balance = 0
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
@always_bls
def test_new_deposit_invalid_sig_is_noop(spec, state):
    # unsigned new deposit: proof checks out, signature doesn't -> skipped,
    # but processing itself MUST succeed
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount)
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, effective=False
    )


@with_all_phases
@spec_state_test
@always_bls
def test_top_up_invalid_sig_still_effective(spec, state):
    # top-ups skip signature verification entirely
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_invalid_wrong_deposit_for_deposit_count(spec, state):
    # two deposits prepared; contract count points at the first, second given
    deposit_1 = prepare_state_and_deposit(spec, state, len(state.validators),
                                          spec.MAX_EFFECTIVE_BALANCE, signed=True)
    root_1 = state.eth1_data.deposit_root
    deposit_2 = prepare_state_and_deposit(spec, state, len(state.validators) + 1,
                                          spec.MAX_EFFECTIVE_BALANCE, signed=True)
    state.eth1_data.deposit_root = root_1
    state.eth1_data.deposit_count = 1
    yield from run_deposit_processing(
        spec, state, deposit_2, len(state.validators), valid=False
    )


@with_all_phases
@spec_state_test
def test_invalid_bad_merkle_proof(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    deposit.proof[5] = b"\x66" * 32
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, valid=False
    )
