"""process_deposit operation suite (spec rules: phase0/beacon-chain.md
process_deposit incl. merkle proof validation, top-ups, invalid-signature
tolerance; reference suite:
test/phase0/block_processing/test_process_deposit.py)."""
from consensus_specs_tpu.testing.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.deposits import prepare_state_and_deposit
from consensus_specs_tpu.testing.helpers.state import get_balance


def run_deposit_processing(spec, state, deposit, validator_index, valid=True,
                           effective=True):
    pre_validator_count = len(state.validators)
    pre_balance = 0
    is_top_up = validator_index < pre_validator_count
    if is_top_up:
        pre_balance = get_balance(state, validator_index)

    yield "pre", state
    yield "deposit", deposit
    if not valid:
        expect_assertion_error(lambda: spec.process_deposit(state, deposit))
        yield "post", None
        return

    spec.process_deposit(state, deposit)
    yield "post", state

    if not effective:
        # invalid signature on a NEW deposit: no-op, never a failure
        assert len(state.validators) == pre_validator_count
        assert len(state.balances) == pre_validator_count
        if is_top_up:
            assert get_balance(state, validator_index) == pre_balance
    else:
        if is_top_up:
            assert len(state.validators) == pre_validator_count
            assert get_balance(state, validator_index) == (
                pre_balance + deposit.data.amount
            )
        else:
            assert len(state.validators) == pre_validator_count + 1
            assert len(state.balances) == pre_validator_count + 1
            assert get_balance(state, validator_index) == deposit.data.amount
    assert state.eth1_deposit_index == state.eth1_data.deposit_count


@with_all_phases
@spec_state_test
def test_new_deposit_under_max(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE - 1
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_new_deposit_max(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_new_deposit_over_max(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE + 1
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_top_up__max_effective_balance(spec, state):
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    state.balances[validator_index] = spec.MAX_EFFECTIVE_BALANCE
    yield from run_deposit_processing(spec, state, deposit, validator_index)
    assert state.validators[validator_index].effective_balance == spec.MAX_EFFECTIVE_BALANCE


@with_all_phases
@spec_state_test
def test_top_up__zero_balance(spec, state):
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    state.balances[validator_index] = 0
    state.validators[validator_index].effective_balance = 0
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
@always_bls
def test_new_deposit_invalid_sig_is_noop(spec, state):
    # unsigned new deposit: proof checks out, signature doesn't -> skipped,
    # but processing itself MUST succeed
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount)
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, effective=False
    )


@with_all_phases
@spec_state_test
@always_bls
def test_top_up_invalid_sig_still_effective(spec, state):
    # top-ups skip signature verification entirely
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_invalid_wrong_deposit_for_deposit_count(spec, state):
    # two deposits prepared; contract count points at the first, second given
    deposit_1 = prepare_state_and_deposit(spec, state, len(state.validators),
                                          spec.MAX_EFFECTIVE_BALANCE, signed=True)
    root_1 = state.eth1_data.deposit_root
    deposit_2 = prepare_state_and_deposit(spec, state, len(state.validators) + 1,
                                          spec.MAX_EFFECTIVE_BALANCE, signed=True)
    state.eth1_data.deposit_root = root_1
    state.eth1_data.deposit_count = 1
    yield from run_deposit_processing(
        spec, state, deposit_2, len(state.validators), valid=False
    )


@with_all_phases
@spec_state_test
def test_invalid_bad_merkle_proof(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(spec, state, validator_index, amount, signed=True)
    deposit.proof[5] = b"\x66" * 32
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, valid=False
    )


@with_all_phases
@spec_state_test
def test_new_deposit_eth1_withdrawal_credentials(spec, state):
    validator_index = len(state.validators)
    creds = (bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX)
             + b"\x00" * 11  # specified padding
             + b"\x59" * 20)  # a 20-byte eth1 address
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, spec.MAX_EFFECTIVE_BALANCE,
        withdrawal_credentials=creds, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)
    assert bytes(state.validators[validator_index].withdrawal_credentials) == creds


@with_all_phases
@spec_state_test
def test_new_deposit_non_versioned_withdrawal_credentials(spec, state):
    # process_deposit does NOT validate the credentials prefix
    validator_index = len(state.validators)
    creds = b"\xff" + b"\x02" * 31
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, spec.MAX_EFFECTIVE_BALANCE,
        withdrawal_credentials=creds, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)
    assert bytes(state.validators[validator_index].withdrawal_credentials) == creds


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_sig_other_version(spec, state):
    """A signature over the right message but the wrong domain fork version
    is a no-op new deposit (not a failure)."""
    from consensus_specs_tpu.crypto import bls
    from consensus_specs_tpu.testing.helpers.deposits import (
        build_deposit,
        default_withdrawal_credentials,
    )
    from consensus_specs_tpu.testing.helpers.keys import privkeys, pubkeys

    validator_index = len(state.validators)
    pubkey = pubkeys[validator_index]
    creds = default_withdrawal_credentials(spec, pubkey)
    deposit, root, _ = build_deposit(
        spec, [], pubkey, privkeys[validator_index],
        spec.MAX_EFFECTIVE_BALANCE, creds, signed=False)
    # sign under a bogus fork version
    message = spec.DepositMessage(
        pubkey=pubkey, withdrawal_credentials=creds,
        amount=spec.MAX_EFFECTIVE_BALANCE)
    domain = spec.compute_domain(
        spec.DOMAIN_DEPOSIT, fork_version=b"\xab\xcd\xef\xff")
    deposit.data.signature = bls.Sign(
        privkeys[validator_index], spec.compute_signing_root(message, domain))
    # re-derive the proof for the mutated data
    from consensus_specs_tpu.testing.helpers.deposits import deposit_from_context
    deposit, root, _ = deposit_from_context(spec, [deposit.data], 0)
    state.eth1_deposit_index = 0
    state.eth1_data.deposit_root = root
    state.eth1_data.deposit_count = 1
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, effective=False)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_withdrawal_credentials_top_up(spec, state):
    """Top-ups ignore the deposit's credentials entirely."""
    validator_index = 0
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, spec.MAX_EFFECTIVE_BALANCE // 4,
        withdrawal_credentials=b"\xff" * 32)
    pre_creds = bytes(state.validators[validator_index].withdrawal_credentials)
    yield from run_deposit_processing(spec, state, deposit, validator_index)
    assert bytes(
        state.validators[validator_index].withdrawal_credentials) == pre_creds
