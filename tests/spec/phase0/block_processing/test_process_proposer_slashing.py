"""process_proposer_slashing operation suite (spec rules:
phase0/beacon-chain.md process_proposer_slashing; reference suite:
test/phase0/block_processing/test_process_proposer_slashing.py)."""
from consensus_specs_tpu.testing.context import (
    always_bls,
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.block_header import sign_block_header
from consensus_specs_tpu.testing.helpers.keys import pubkey_to_privkey
from consensus_specs_tpu.testing.helpers.proposer_slashings import (
    check_proposer_slashing_effect,
    get_valid_proposer_slashing,
)
from consensus_specs_tpu.testing.helpers.state import next_epoch


def run_proposer_slashing_processing(spec, state, proposer_slashing, valid=True):
    """Yield the operation vector parts; apply or expect rejection."""
    from consensus_specs_tpu.testing.context import expect_assertion_error

    pre_state = state.copy()
    yield "pre", state
    yield "proposer_slashing", proposer_slashing
    if not valid:
        expect_assertion_error(
            lambda: spec.process_proposer_slashing(state, proposer_slashing)
        )
        yield "post", None
        return
    spec.process_proposer_slashing(state, proposer_slashing)
    yield "post", state
    slashed_index = proposer_slashing.signed_header_1.message.proposer_index
    check_proposer_slashing_effect(spec, pre_state, state, slashed_index)


@with_all_phases
@spec_state_test
def test_basic(spec, state):
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, signed_1=True, signed_2=True
    )
    yield from run_proposer_slashing_processing(spec, state, proposer_slashing)


@with_all_phases
@spec_state_test
def test_slashed_and_proposer_index_the_same(spec, state):
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, slashed_index=spec.get_beacon_proposer_index(state),
        signed_1=True, signed_2=True,
    )
    yield from run_proposer_slashing_processing(spec, state, proposer_slashing)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_sig_1(spec, state):
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, signed_1=False, signed_2=True
    )
    yield from run_proposer_slashing_processing(spec, state, proposer_slashing, False)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_sig_2(spec, state):
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, signed_1=True, signed_2=False
    )
    yield from run_proposer_slashing_processing(spec, state, proposer_slashing, False)


@with_all_phases
@spec_state_test
def test_invalid_headers_are_same(spec, state):
    proposer_slashing = get_valid_proposer_slashing(spec, state, signed_1=True)
    proposer_slashing.signed_header_2 = proposer_slashing.signed_header_1
    yield from run_proposer_slashing_processing(spec, state, proposer_slashing, False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_index_mismatch(spec, state):
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, signed_1=True, signed_2=False
    )
    header = proposer_slashing.signed_header_2.message
    header.proposer_index = int(header.proposer_index) - 1
    privkey = pubkey_to_privkey[
        state.validators[proposer_slashing.signed_header_1.message.proposer_index].pubkey
    ]
    proposer_slashing.signed_header_2 = sign_block_header(spec, state, header, privkey)
    yield from run_proposer_slashing_processing(spec, state, proposer_slashing, False)


@with_all_phases
@spec_state_test
def test_invalid_slots_mismatch(spec, state):
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, signed_1=True, signed_2=False
    )
    header = proposer_slashing.signed_header_2.message
    header.slot = int(header.slot) + 1
    privkey = pubkey_to_privkey[state.validators[header.proposer_index].pubkey]
    proposer_slashing.signed_header_2 = sign_block_header(spec, state, header, privkey)
    yield from run_proposer_slashing_processing(spec, state, proposer_slashing, False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_is_not_activated(spec, state):
    index = spec.get_active_validator_indices(state, spec.get_current_epoch(state))[-1]
    state.validators[index].activation_epoch = spec.get_current_epoch(state) + 1
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, slashed_index=index, signed_1=True, signed_2=True
    )
    yield from run_proposer_slashing_processing(spec, state, proposer_slashing, False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_is_slashed(spec, state):
    index = spec.get_active_validator_indices(state, spec.get_current_epoch(state))[-1]
    state.validators[index].slashed = True
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, slashed_index=index, signed_1=True, signed_2=True
    )
    yield from run_proposer_slashing_processing(spec, state, proposer_slashing, False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_is_withdrawn(spec, state):
    next_epoch(spec, state)
    index = spec.get_active_validator_indices(state, spec.get_current_epoch(state))[-1]
    state.validators[index].withdrawable_epoch = spec.get_current_epoch(state) - 1
    proposer_slashing = get_valid_proposer_slashing(
        spec, state, slashed_index=index, signed_1=True, signed_2=True
    )
    yield from run_proposer_slashing_processing(spec, state, proposer_slashing, False)
