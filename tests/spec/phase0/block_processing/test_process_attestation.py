"""process_attestation operation suite (spec conformance scenarios:
phase0/beacon-chain.md process_attestation validity rules; reference
suite: test/phase0/block_processing/test_process_attestation.py)."""
from consensus_specs_tpu.testing.context import (
    always_bls,
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.attestations import (
    get_valid_attestation,
    run_attestation_processing,
    sign_attestation,
)
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    next_slots,
    transition_to,
)


@with_all_phases
@spec_state_test
def test_one_basic_attestation(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation)


@with_all_phases
@spec_state_test
def test_previous_epoch_attestation(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    next_epoch(spec, state)
    yield from run_attestation_processing(spec, state, attestation)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_attestation_signature(spec, state):
    attestation = get_valid_attestation(spec, state)  # unsigned: zero sig
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_before_inclusion_delay(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    # state.slot == attestation slot: inclusion delay not yet satisfied
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_after_epoch_slots(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    transition_to(spec, state, state.slot + spec.SLOTS_PER_EPOCH + 1)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_wrong_source_checkpoint(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    attestation = get_valid_attestation(spec, state)
    attestation.data.source.epoch += 10
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_bad_source_root(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    attestation = get_valid_attestation(spec, state)
    attestation.data.source.root = b"\x77" * 32
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_mismatched_target_and_slot(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    attestation = get_valid_attestation(spec, state, slot=state.slot - spec.SLOTS_PER_EPOCH)
    attestation.data.slot = attestation.data.slot + spec.SLOTS_PER_EPOCH
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_future_target_epoch(spec, state):
    attestation = get_valid_attestation(spec, state)
    attestation.data.target.epoch = spec.get_current_epoch(state) + 1
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_committee_index(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    # committee count is per-slot; an index at the count is out of range
    attestation.data.index = spec.get_committee_count_per_slot(
        state, spec.get_current_epoch(state)
    )
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_too_few_aggregation_bits(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    attestation.aggregation_bits = type(attestation.aggregation_bits)(
        list(attestation.aggregation_bits)[:-1]
    )
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_too_many_aggregation_bits(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    attestation.aggregation_bits = type(attestation.aggregation_bits)(
        list(attestation.aggregation_bits) + [False]
    )
    yield from run_attestation_processing(spec, state, attestation, valid=False)
