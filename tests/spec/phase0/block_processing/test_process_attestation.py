"""process_attestation operation suite (spec conformance scenarios:
phase0/beacon-chain.md process_attestation validity rules; reference
suite: test/phase0/block_processing/test_process_attestation.py)."""
from consensus_specs_tpu.testing.context import (
    always_bls,
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.attestations import (
    get_valid_attestation,
    run_attestation_processing,
    sign_attestation,
)
from consensus_specs_tpu.testing.helpers.state import (
    next_epoch,
    next_slots,
    transition_to,
)


@with_all_phases
@spec_state_test
def test_one_basic_attestation(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation)


@with_all_phases
@spec_state_test
def test_previous_epoch_attestation(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    next_epoch(spec, state)
    yield from run_attestation_processing(spec, state, attestation)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_attestation_signature(spec, state):
    attestation = get_valid_attestation(spec, state)  # unsigned: zero sig
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_before_inclusion_delay(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    # state.slot == attestation slot: inclusion delay not yet satisfied
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_after_epoch_slots(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    transition_to(spec, state, state.slot + spec.SLOTS_PER_EPOCH + 1)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_wrong_source_checkpoint(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    attestation = get_valid_attestation(spec, state)
    attestation.data.source.epoch += 10
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_bad_source_root(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    attestation = get_valid_attestation(spec, state)
    attestation.data.source.root = b"\x77" * 32
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_mismatched_target_and_slot(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    attestation = get_valid_attestation(spec, state, slot=state.slot - spec.SLOTS_PER_EPOCH)
    attestation.data.slot = attestation.data.slot + spec.SLOTS_PER_EPOCH
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_future_target_epoch(spec, state):
    attestation = get_valid_attestation(spec, state)
    attestation.data.target.epoch = spec.get_current_epoch(state) + 1
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_committee_index(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    # committee count is per-slot; an index at the count is out of range
    attestation.data.index = spec.get_committee_count_per_slot(
        state, spec.get_current_epoch(state)
    )
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_too_few_aggregation_bits(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    attestation.aggregation_bits = type(attestation.aggregation_bits)(
        list(attestation.aggregation_bits)[:-1]
    )
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_too_many_aggregation_bits(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    attestation.aggregation_bits = type(attestation.aggregation_bits)(
        list(attestation.aggregation_bits) + [False]
    )
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_success_multi_proposer_index_iterations(spec, state):
    # several slots in: proposer lookup iterates past empty preceding slots
    next_slots(spec, state, spec.SLOTS_PER_EPOCH * 2)
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation)


@with_all_phases
@spec_state_test
@always_bls
def test_empty_participants_zeroes_sig(spec, state):
    attestation = get_valid_attestation(
        spec, state, filter_participant_set=lambda comm: set())
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_empty_participants_seemingly_valid_sig(spec, state):
    from consensus_specs_tpu.crypto import bls as bls_mod
    from consensus_specs_tpu.testing.helpers.keys import privkeys

    attestation = get_valid_attestation(
        spec, state, filter_participant_set=lambda comm: set())
    # a real signature over the data, but from nobody in the (empty) set
    attestation.signature = bls_mod.Sign(
        privkeys[0], spec.compute_signing_root(
            attestation.data,
            spec.get_domain(state, spec.DOMAIN_BEACON_ATTESTER,
                            attestation.data.target.epoch)))
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


def _justification_backdrop(spec, state):
    """Fast-forward to epoch 5 with distinct justified checkpoint roots."""
    next_slots(spec, state, spec.SLOTS_PER_EPOCH * 5)
    state.finalized_checkpoint.epoch = 2
    state.previous_justified_checkpoint = spec.Checkpoint(
        epoch=3, root=b"\x01" * 32)
    state.current_justified_checkpoint = spec.Checkpoint(
        epoch=4, root=b"\x32" * 32)


@with_all_phases
@spec_state_test
def test_old_source_epoch(spec, state):
    _justification_backdrop(spec, state)
    attestation = get_valid_attestation(
        spec, state, slot=spec.SLOTS_PER_EPOCH * 3 + 1)
    assert attestation.data.source.epoch == state.previous_justified_checkpoint.epoch
    # point the source below the oldest admissible epoch
    attestation.data.source.epoch = state.finalized_checkpoint.epoch
    sign_attestation(spec, state, attestation)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_new_source_epoch(spec, state):
    attestation = get_valid_attestation(spec, state)
    attestation.data.source.epoch += 1
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_source_root_is_target_root(spec, state):
    # target-root correctness is a rewards concern, not a validity rule
    attestation = get_valid_attestation(spec, state)
    attestation.data.target.root = attestation.data.source.root
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation)


@with_all_phases
@spec_state_test
def test_invalid_current_source_root(spec, state):
    _justification_backdrop(spec, state)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    # current-epoch attestation must cite the CURRENT justified root
    attestation = get_valid_attestation(
        spec, state, slot=spec.SLOTS_PER_EPOCH * 5)
    assert attestation.data.target.epoch == spec.get_current_epoch(state)
    assert attestation.data.source.root == state.current_justified_checkpoint.root
    attestation.data.source.root = state.previous_justified_checkpoint.root
    sign_attestation(spec, state, attestation)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_previous_source_root(spec, state):
    _justification_backdrop(spec, state)
    # previous-epoch attestation must cite the PREVIOUS justified root
    attestation = get_valid_attestation(
        spec, state, slot=spec.SLOTS_PER_EPOCH * 4 + 1)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    assert attestation.data.target.epoch == spec.get_previous_epoch(state)
    assert attestation.data.source.root == state.previous_justified_checkpoint.root
    attestation.data.source.root = state.current_justified_checkpoint.root
    sign_attestation(spec, state, attestation)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_wrong_index_for_committee_signature(spec, state):
    # signature belongs to committee `index`; shifting the index breaks it
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    attestation.data.index += 1
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_all_phases
@spec_state_test
def test_old_target_epoch(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    # age the state beyond the attestation's whole target-epoch window
    next_slots(spec, state, spec.SLOTS_PER_EPOCH * 2)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


# -- inclusion-delay x head/target correctness matrix ------------------------
#
# Validity only depends on the delay (<= SLOTS_PER_EPOCH); wrong head or
# target roots stay *valid* and exercise the reduced-credit paths (altair
# participation-flag branches in particular).

def _run_delay_matrix_case(spec, state, delay, wrong_head=False, wrong_target=False):
    attestation = get_valid_attestation(spec, state, signed=False)
    if wrong_head:
        attestation.data.beacon_block_root = b"\x42" * 32
    if wrong_target:
        attestation.data.target.root = b"\x33" * 32
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, delay)
    yield from run_attestation_processing(
        spec, state, attestation, valid=delay <= spec.SLOTS_PER_EPOCH)


def _sqrt_epoch(spec):
    return int(spec.integer_squareroot(spec.uint64(int(spec.SLOTS_PER_EPOCH))))


@with_all_phases
@spec_state_test
def test_correct_min_inclusion_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))


@with_all_phases
@spec_state_test
def test_correct_sqrt_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(spec, state, _sqrt_epoch(spec))


@with_all_phases
@spec_state_test
def test_correct_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(spec, state, int(spec.SLOTS_PER_EPOCH))


@with_all_phases
@spec_state_test
def test_correct_after_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(spec, state, int(spec.SLOTS_PER_EPOCH) + 1)


@with_all_phases
@spec_state_test
def test_incorrect_head_min_inclusion_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY), wrong_head=True)


@with_all_phases
@spec_state_test
def test_incorrect_head_sqrt_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(spec, state, _sqrt_epoch(spec), wrong_head=True)


@with_all_phases
@spec_state_test
def test_incorrect_head_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, int(spec.SLOTS_PER_EPOCH), wrong_head=True)


@with_all_phases
@spec_state_test
def test_incorrect_head_after_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, int(spec.SLOTS_PER_EPOCH) + 1, wrong_head=True)


@with_all_phases
@spec_state_test
def test_incorrect_head_and_target_min_inclusion_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY),
        wrong_head=True, wrong_target=True)


@with_all_phases
@spec_state_test
def test_incorrect_head_and_target_sqrt_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, _sqrt_epoch(spec), wrong_head=True, wrong_target=True)


@with_all_phases
@spec_state_test
def test_incorrect_head_and_target_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, int(spec.SLOTS_PER_EPOCH), wrong_head=True, wrong_target=True)


@with_all_phases
@spec_state_test
def test_incorrect_head_and_target_after_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, int(spec.SLOTS_PER_EPOCH) + 1,
        wrong_head=True, wrong_target=True)


@with_all_phases
@spec_state_test
def test_incorrect_target_min_inclusion_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY), wrong_target=True)


@with_all_phases
@spec_state_test
def test_incorrect_target_sqrt_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, _sqrt_epoch(spec), wrong_target=True)


@with_all_phases
@spec_state_test
def test_incorrect_target_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, int(spec.SLOTS_PER_EPOCH), wrong_target=True)


@with_all_phases
@spec_state_test
def test_incorrect_target_after_epoch_delay(spec, state):
    yield from _run_delay_matrix_case(
        spec, state, int(spec.SLOTS_PER_EPOCH) + 1, wrong_target=True)
