"""process_voluntary_exit operation suite (spec rules:
phase0/beacon-chain.md process_voluntary_exit; reference suite:
test/phase0/block_processing/test_process_voluntary_exit.py)."""
from consensus_specs_tpu.testing.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.keys import pubkey_to_privkey
from consensus_specs_tpu.testing.helpers.voluntary_exits import sign_voluntary_exit


def run_voluntary_exit_processing(spec, state, signed_exit, valid=True):
    validator_index = signed_exit.message.validator_index
    yield "pre", state
    yield "voluntary_exit", signed_exit
    if not valid:
        expect_assertion_error(
            lambda: spec.process_voluntary_exit(state, signed_exit)
        )
        yield "post", None
        return
    pre_exit_epoch = state.validators[validator_index].exit_epoch
    spec.process_voluntary_exit(state, signed_exit)
    yield "post", state
    assert pre_exit_epoch == spec.FAR_FUTURE_EPOCH
    assert state.validators[validator_index].exit_epoch < spec.FAR_FUTURE_EPOCH


def _eligible_state(spec, state):
    """Fast-forward past the PERSISTENT shard committee period so exits
    are admissible."""
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH


def _signed_exit(spec, state, index, epoch=None):
    exit = spec.VoluntaryExit(
        epoch=spec.get_current_epoch(state) if epoch is None else epoch,
        validator_index=index,
    )
    privkey = pubkey_to_privkey[state.validators[index].pubkey]
    return sign_voluntary_exit(spec, state, exit, privkey)


@with_all_phases
@spec_state_test
def test_basic_exit(spec, state):
    _eligible_state(spec, state)
    index = spec.get_active_validator_indices(state, spec.get_current_epoch(state))[0]
    yield from run_voluntary_exit_processing(spec, state, _signed_exit(spec, state, index))


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_signature(spec, state):
    _eligible_state(spec, state)
    index = spec.get_active_validator_indices(state, spec.get_current_epoch(state))[0]
    signed = _signed_exit(spec, state, index)
    wrong_key_holder = spec.get_active_validator_indices(
        state, spec.get_current_epoch(state))[1]
    signed = sign_voluntary_exit(
        spec, state, signed.message,
        pubkey_to_privkey[state.validators[wrong_key_holder].pubkey],
    )
    yield from run_voluntary_exit_processing(spec, state, signed, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_not_active(spec, state):
    _eligible_state(spec, state)
    index = spec.get_active_validator_indices(state, spec.get_current_epoch(state))[0]
    state.validators[index].activation_epoch = spec.FAR_FUTURE_EPOCH
    yield from run_voluntary_exit_processing(
        spec, state, _signed_exit(spec, state, index), valid=False
    )


@with_all_phases
@spec_state_test
def test_invalid_already_exited(spec, state):
    _eligible_state(spec, state)
    index = spec.get_active_validator_indices(state, spec.get_current_epoch(state))[0]
    state.validators[index].exit_epoch = spec.get_current_epoch(state) + 3
    yield from run_voluntary_exit_processing(
        spec, state, _signed_exit(spec, state, index), valid=False
    )


@with_all_phases
@spec_state_test
def test_invalid_future_exit_epoch(spec, state):
    _eligible_state(spec, state)
    index = spec.get_active_validator_indices(state, spec.get_current_epoch(state))[0]
    signed = _signed_exit(
        spec, state, index, epoch=spec.get_current_epoch(state) + 1
    )
    yield from run_voluntary_exit_processing(spec, state, signed, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_before_shard_committee_period(spec, state):
    # fresh validator: active for fewer than SHARD_COMMITTEE_PERIOD epochs
    index = spec.get_active_validator_indices(state, spec.get_current_epoch(state))[0]
    yield from run_voluntary_exit_processing(
        spec, state, _signed_exit(spec, state, index), valid=False
    )


@with_all_phases
@spec_state_test
def test_success_exit_queue_ordering(spec, state):
    """Churn-limit worth of exits in one epoch share the exit epoch; one
    more spills into the next."""
    _eligible_state(spec, state)
    current_epoch = spec.get_current_epoch(state)
    indices = spec.get_active_validator_indices(state, current_epoch)
    churn = spec.get_validator_churn_limit(state)
    first = list(indices[:churn])
    for index in first:
        spec.process_voluntary_exit(state, _signed_exit(spec, state, index))
    overflow_index = indices[churn]
    signed = _signed_exit(spec, state, overflow_index)
    yield from run_voluntary_exit_processing(spec, state, signed)
    assert state.validators[overflow_index].exit_epoch == (
        state.validators[first[0]].exit_epoch + 1
    )


@with_all_phases
@spec_state_test
def test_invalid_validator_index(spec, state):
    _eligible_state(spec, state)
    index = spec.get_active_validator_indices(
        state, spec.get_current_epoch(state))[0]
    signed = _signed_exit(spec, state, index)
    signed.message.validator_index = len(state.validators) + 100
    yield from run_voluntary_exit_processing(spec, state, signed, valid=False)


@with_all_phases
@spec_state_test
def test_default_exit_epoch_subsequent_exit(spec, state):
    """A later exit inherits the furthest pending exit epoch, not the
    computed activation-queue epoch."""
    _eligible_state(spec, state)
    current_epoch = spec.get_current_epoch(state)
    indices = spec.get_active_validator_indices(state, current_epoch)

    # park an earlier exit far in the future
    state.validators[indices[0]].exit_epoch = current_epoch + 30

    signed = _signed_exit(spec, state, indices[1])
    yield from run_voluntary_exit_processing(spec, state, signed)
    assert state.validators[indices[1]].exit_epoch == current_epoch + 30
