"""process_block_header suite (spec rules: phase0/beacon-chain.md
process_block_header; reference suite:
test/phase0/block_processing/test_process_block_header.py)."""
from consensus_specs_tpu.testing.context import (
    expect_assertion_error,
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.block import build_empty_block_for_next_slot


def _prepare(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    spec.process_slots(state, block.slot)
    return block


def run_block_header_processing(spec, state, block, valid=True):
    yield "pre", state
    yield "block", block
    if not valid:
        expect_assertion_error(lambda: spec.process_block_header(state, block))
        yield "post", None
        return
    spec.process_block_header(state, block)
    yield "post", state


@with_all_phases
@spec_state_test
def test_basic_block_header(spec, state):
    block = _prepare(spec, state)
    yield from run_block_header_processing(spec, state, block)


@with_all_phases
@spec_state_test
def test_invalid_slot_block_header(spec, state):
    block = _prepare(spec, state)
    block.slot = state.slot + 2  # mismatched slot
    yield from run_block_header_processing(spec, state, block, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_index(spec, state):
    block = _prepare(spec, state)
    active = spec.get_active_validator_indices(state, spec.get_current_epoch(state))
    wrong = next(i for i in active if i != block.proposer_index)
    block.proposer_index = wrong
    yield from run_block_header_processing(spec, state, block, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_parent_root(spec, state):
    block = _prepare(spec, state)
    block.parent_root = b"\x12" * 32
    yield from run_block_header_processing(spec, state, block, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_multiple_blocks_single_slot(spec, state):
    block = _prepare(spec, state)
    spec.process_block_header(state, block)
    child_block = block.copy()
    child_block.parent_root = state.latest_block_header.hash_tree_root()
    yield from run_block_header_processing(spec, state, child_block, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_slashed(spec, state):
    block = _prepare(spec, state)
    state.validators[block.proposer_index].slashed = True
    yield from run_block_header_processing(spec, state, block, valid=False)
