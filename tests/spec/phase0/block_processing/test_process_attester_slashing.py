"""process_attester_slashing operation suite (spec rules:
phase0/beacon-chain.md process_attester_slashing / is_slashable_attestation_data;
reference suite: test/phase0/block_processing/test_process_attester_slashing.py)."""
from consensus_specs_tpu.testing.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.attestations import sign_indexed_attestation
from consensus_specs_tpu.testing.helpers.attester_slashings import (
    get_indexed_attestation_participants,
    get_valid_attester_slashing,
    get_valid_attester_slashing_by_indices,
)
from consensus_specs_tpu.testing.helpers.state import get_balance, next_epoch


def run_attester_slashing_processing(spec, state, attester_slashing, valid=True):
    yield "pre", state
    yield "attester_slashing", attester_slashing
    if not valid:
        expect_assertion_error(
            lambda: spec.process_attester_slashing(state, attester_slashing)
        )
        yield "post", None
        return

    # only the intersection of the two attestations' participants is slashed
    slashed_indices = sorted(
        set(get_indexed_attestation_participants(spec, attester_slashing.attestation_1))
        & set(get_indexed_attestation_participants(spec, attester_slashing.attestation_2))
    )
    proposer_index = spec.get_beacon_proposer_index(state)
    pre_proposer_balance = get_balance(state, proposer_index)
    pre_balances = {i: get_balance(state, i) for i in slashed_indices}

    spec.process_attester_slashing(state, attester_slashing)
    yield "post", state

    for i in slashed_indices:
        assert state.validators[i].slashed
        if i != proposer_index:
            assert get_balance(state, i) < pre_balances[i]
    assert get_balance(state, proposer_index) > pre_proposer_balance - (
        pre_balances.get(proposer_index, 0) // spec.MIN_SLASHING_PENALTY_QUOTIENT
    )


@with_all_phases
@spec_state_test
def test_basic_double(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True
    )
    yield from run_attester_slashing_processing(spec, state, attester_slashing)


@with_all_phases
@spec_state_test
def test_basic_surround(spec, state):
    next_epoch(spec, state)
    attester_slashing = get_valid_attester_slashing(
        spec, state, slot=state.slot - spec.SLOTS_PER_EPOCH, signed_1=False
    )
    # surround: att_1 strictly surrounds att_2 (source_1 < source_2 and
    # target_2 < target_1), built by nudging epochs upward only
    att_1 = attester_slashing.attestation_1
    att_2 = attester_slashing.attestation_2
    att_2.data.source.epoch = att_1.data.source.epoch + 1
    att_1.data.target.epoch = att_2.data.target.epoch + 1
    sign_indexed_attestation(spec, state, att_1)
    sign_indexed_attestation(spec, state, att_2)
    yield from run_attester_slashing_processing(spec, state, attester_slashing)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_sig_1(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=False, signed_2=True
    )
    yield from run_attester_slashing_processing(spec, state, attester_slashing, False)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_sig_2(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=False
    )
    yield from run_attester_slashing_processing(spec, state, attester_slashing, False)


@with_all_phases
@spec_state_test
def test_invalid_same_data(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=False
    )
    indexed_att_2 = attester_slashing.attestation_2
    indexed_att_2.data = attester_slashing.attestation_1.data
    sign_indexed_attestation(spec, state, indexed_att_2)
    yield from run_attester_slashing_processing(spec, state, attester_slashing, False)


@with_all_phases
@spec_state_test
def test_invalid_no_double_or_surround(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=False
    )
    attester_slashing.attestation_2.data.target.epoch += 1  # disjoint
    sign_indexed_attestation(spec, state, attester_slashing.attestation_2)
    yield from run_attester_slashing_processing(spec, state, attester_slashing, False)


@with_all_phases
@spec_state_test
def test_invalid_participants_already_slashed(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True
    )
    validator_indices = get_indexed_attestation_participants(
        spec, attester_slashing.attestation_1
    )
    for index in validator_indices:
        state.validators[index].slashed = True
    yield from run_attester_slashing_processing(spec, state, attester_slashing, False)


@with_all_phases
@spec_state_test
def test_invalid_unsorted_att_1(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=False, signed_2=True
    )
    indices = get_indexed_attestation_participants(
        spec, attester_slashing.attestation_1
    )
    assert len(indices) >= 3
    indices[1], indices[2] = indices[2], indices[1]  # break sorting
    attester_slashing.attestation_1.attesting_indices = indices
    sign_indexed_attestation(spec, state, attester_slashing.attestation_1)
    yield from run_attester_slashing_processing(spec, state, attester_slashing, False)


@with_all_phases
@spec_state_test
def test_invalid_empty_indices(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=False, signed_2=True
    )
    attester_slashing.attestation_1.attesting_indices = []
    attester_slashing.attestation_1.signature = spec.bls.G2_POINT_AT_INFINITY
    yield from run_attester_slashing_processing(spec, state, attester_slashing, False)


@with_all_phases
@spec_state_test
def test_partially_overlapping_participants(spec, state):
    # slash only the overlap of two differently-filtered attestations
    indices = sorted(
        get_indexed_attestation_participants(
            spec,
            get_valid_attester_slashing(spec, state).attestation_1,
        )
    )
    assert len(indices) >= 4
    half = len(indices) // 2
    attester_slashing = get_valid_attester_slashing_by_indices(
        spec, state,
        indices_1=indices[: half + 1],
        indices_2=indices[half - 1:],
        signed_1=True, signed_2=True,
    )
    yield from run_attester_slashing_processing(spec, state, attester_slashing)
