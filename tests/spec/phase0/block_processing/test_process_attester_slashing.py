"""process_attester_slashing operation suite (spec rules:
phase0/beacon-chain.md process_attester_slashing / is_slashable_attestation_data;
reference suite: test/phase0/block_processing/test_process_attester_slashing.py)."""
from consensus_specs_tpu.testing.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test,
    with_all_phases,
)
from consensus_specs_tpu.testing.helpers.attestations import sign_indexed_attestation
from consensus_specs_tpu.testing.helpers.attester_slashings import (
    get_indexed_attestation_participants,
    get_valid_attester_slashing,
    get_valid_attester_slashing_by_indices,
)
from consensus_specs_tpu.testing.helpers.state import get_balance, next_epoch


def run_attester_slashing_processing(spec, state, attester_slashing, valid=True):
    from consensus_specs_tpu.testing.helpers.proposer_slashings import (
        get_min_slashing_penalty_quotient,
    )

    yield "pre", state
    yield "attester_slashing", attester_slashing
    if not valid:
        expect_assertion_error(
            lambda: spec.process_attester_slashing(state, attester_slashing)
        )
        yield "post", None
        return

    # only the intersection of the two attestations' participants is slashed
    slashed_indices = sorted(
        set(get_indexed_attestation_participants(spec, attester_slashing.attestation_1))
        & set(get_indexed_attestation_participants(spec, attester_slashing.attestation_2))
    )
    proposer_index = spec.get_beacon_proposer_index(state)
    pre_proposer_balance = int(get_balance(state, proposer_index))
    pre_balances = {i: int(get_balance(state, i)) for i in slashed_indices}
    pre_effectives = {
        i: int(state.validators[i].effective_balance) for i in slashed_indices}
    pre_withdrawables = {
        i: int(state.validators[i].withdrawable_epoch) for i in slashed_indices}
    whistleblower_total = sum(
        eff // int(spec.WHISTLEBLOWER_REWARD_QUOTIENT)
        for eff in pre_effectives.values())

    spec.process_attester_slashing(state, attester_slashing)
    yield "post", state

    for i in slashed_indices:
        slashed_validator = state.validators[i]
        assert slashed_validator.slashed
        assert slashed_validator.exit_epoch < spec.FAR_FUTURE_EPOCH
        if pre_withdrawables[i] < int(spec.FAR_FUTURE_EPOCH):
            # already-exiting validators only ever extend their window
            assert int(slashed_validator.withdrawable_epoch) == max(
                pre_withdrawables[i],
                int(spec.get_current_epoch(state)) + int(spec.EPOCHS_PER_SLASHINGS_VECTOR))
        else:
            assert slashed_validator.withdrawable_epoch < spec.FAR_FUTURE_EPOCH
        if i != proposer_index:
            # the proposer's whistleblower income can outweigh their penalty
            assert int(get_balance(state, i)) < pre_balances[i]

    expected_proposer = pre_proposer_balance + whistleblower_total
    if proposer_index in slashed_indices:
        expected_proposer -= (
            pre_effectives[proposer_index] // int(get_min_slashing_penalty_quotient(spec)))
    assert int(get_balance(state, proposer_index)) == expected_proposer


@with_all_phases
@spec_state_test
def test_basic_double(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True
    )
    yield from run_attester_slashing_processing(spec, state, attester_slashing)


@with_all_phases
@spec_state_test
def test_basic_surround(spec, state):
    next_epoch(spec, state)
    attester_slashing = get_valid_attester_slashing(
        spec, state, slot=state.slot - spec.SLOTS_PER_EPOCH, signed_1=False
    )
    # surround: att_1 strictly surrounds att_2 (source_1 < source_2 and
    # target_2 < target_1), built by nudging epochs upward only
    att_1 = attester_slashing.attestation_1
    att_2 = attester_slashing.attestation_2
    att_2.data.source.epoch = att_1.data.source.epoch + 1
    att_1.data.target.epoch = att_2.data.target.epoch + 1
    sign_indexed_attestation(spec, state, att_1)
    sign_indexed_attestation(spec, state, att_2)
    yield from run_attester_slashing_processing(spec, state, attester_slashing)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_sig_1(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=False, signed_2=True
    )
    yield from run_attester_slashing_processing(spec, state, attester_slashing, False)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_sig_2(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=False
    )
    yield from run_attester_slashing_processing(spec, state, attester_slashing, False)


@with_all_phases
@spec_state_test
def test_invalid_same_data(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=False
    )
    indexed_att_2 = attester_slashing.attestation_2
    indexed_att_2.data = attester_slashing.attestation_1.data
    sign_indexed_attestation(spec, state, indexed_att_2)
    yield from run_attester_slashing_processing(spec, state, attester_slashing, False)


@with_all_phases
@spec_state_test
def test_invalid_no_double_or_surround(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=False
    )
    attester_slashing.attestation_2.data.target.epoch += 1  # disjoint
    sign_indexed_attestation(spec, state, attester_slashing.attestation_2)
    yield from run_attester_slashing_processing(spec, state, attester_slashing, False)


@with_all_phases
@spec_state_test
def test_invalid_participants_already_slashed(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=True
    )
    validator_indices = get_indexed_attestation_participants(
        spec, attester_slashing.attestation_1
    )
    for index in validator_indices:
        state.validators[index].slashed = True
    yield from run_attester_slashing_processing(spec, state, attester_slashing, False)


@with_all_phases
@spec_state_test
def test_invalid_unsorted_att_1(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=False, signed_2=True
    )
    indices = get_indexed_attestation_participants(
        spec, attester_slashing.attestation_1
    )
    assert len(indices) >= 3
    indices[1], indices[2] = indices[2], indices[1]  # break sorting
    attester_slashing.attestation_1.attesting_indices = indices
    sign_indexed_attestation(spec, state, attester_slashing.attestation_1)
    yield from run_attester_slashing_processing(spec, state, attester_slashing, False)


@with_all_phases
@spec_state_test
def test_invalid_empty_indices(spec, state):
    attester_slashing = get_valid_attester_slashing(
        spec, state, signed_1=False, signed_2=True
    )
    attester_slashing.attestation_1.attesting_indices = []
    attester_slashing.attestation_1.signature = spec.bls.G2_POINT_AT_INFINITY
    yield from run_attester_slashing_processing(spec, state, attester_slashing, False)


@with_all_phases
@spec_state_test
def test_partially_overlapping_participants(spec, state):
    # slash only the overlap of two differently-filtered attestations
    indices = sorted(
        get_indexed_attestation_participants(
            spec,
            get_valid_attester_slashing(spec, state).attestation_1,
        )
    )
    assert len(indices) >= 4
    half = len(indices) // 2
    attester_slashing = get_valid_attester_slashing_by_indices(
        spec, state,
        indices_1=indices[: half + 1],
        indices_2=indices[half - 1:],
        signed_1=True, signed_2=True,
    )
    yield from run_attester_slashing_processing(spec, state, attester_slashing)


@with_all_phases
@spec_state_test
def test_already_exited_recent(spec, state):
    slashing = get_valid_attester_slashing(spec, state, signed_1=True, signed_2=True)
    victims = get_indexed_attestation_participants(spec, slashing.attestation_1)
    # initiated exit, still within the slashable window
    spec.initiate_validator_exit(state, victims[0])
    yield from run_attester_slashing_processing(spec, state, slashing)


@with_all_phases
@spec_state_test
def test_already_exited_long_ago(spec, state):
    # every participant is deep in the exit queue (withdrawable soon, but
    # still inside the slashable window): the withdrawable-epoch max() path
    slashing = get_valid_attester_slashing(spec, state, signed_1=True, signed_2=True)
    victims = get_indexed_attestation_participants(spec, slashing.attestation_1)
    for index in victims:
        spec.initiate_validator_exit(state, index)
        state.validators[index].withdrawable_epoch = (
            spec.get_current_epoch(state) + 2)
    yield from run_attester_slashing_processing(spec, state, slashing)


@with_all_phases
@spec_state_test
def test_proposer_index_slashed(spec, state):
    from consensus_specs_tpu.testing.helpers.state import next_epoch_via_block

    # past genesis slot so a real proposer exists, then self-slash them
    next_epoch_via_block(spec, state)
    proposer = spec.get_beacon_proposer_index(state)
    slashing = get_valid_attester_slashing_by_indices(
        spec, state, [proposer], signed_1=True, signed_2=True)
    yield from run_attester_slashing_processing(spec, state, slashing)


@with_all_phases
@spec_state_test
def test_attestation_from_future(spec, state):
    from consensus_specs_tpu.testing.helpers.state import next_epoch_via_block

    # evidence dated past the state's slot is still slashable evidence
    future_state = state.copy()
    next_epoch_via_block(spec, future_state)
    slashing = get_valid_attester_slashing(
        spec, future_state, slot=state.slot + 5, signed_1=True, signed_2=True)
    yield from run_attester_slashing_processing(spec, state, slashing)


@with_all_phases
@spec_state_test
def test_with_effective_balance_disparity(spec, state):
    # nudge balances so effective balances and balances disagree
    for i in range(len(state.validators)):
        state.balances[i] = int(state.balances[i]) - i * 1000
    slashing = get_valid_attester_slashing(spec, state, signed_1=True, signed_2=True)
    yield from run_attester_slashing_processing(spec, state, slashing)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_sig_1_and_2(spec, state):
    slashing = get_valid_attester_slashing(spec, state, signed_1=False, signed_2=False)
    yield from run_attester_slashing_processing(spec, state, slashing, valid=False)


def _tamper_indices(spec, state, which, mutate):
    """Build a signed slashing, corrupt one side's indices WITHOUT re-signing."""
    slashing = get_valid_attester_slashing(spec, state, signed_1=True, signed_2=True)
    side = slashing.attestation_1 if which == 1 else slashing.attestation_2
    indices = list(side.attesting_indices)
    mutate(indices)
    side.attesting_indices = indices
    return slashing


@with_all_phases
@spec_state_test
def test_att1_high_index(spec, state):
    slashing = _tamper_indices(
        spec, state, 1, lambda ix: ix.append(len(state.validators)))
    yield from run_attester_slashing_processing(spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_att2_high_index(spec, state):
    slashing = _tamper_indices(
        spec, state, 2, lambda ix: ix.append(len(state.validators)))
    yield from run_attester_slashing_processing(spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_att1_empty_indices(spec, state):
    slashing = _tamper_indices(spec, state, 1, lambda ix: ix.clear())
    slashing.attestation_1.signature = spec.bls.G2_POINT_AT_INFINITY \
        if hasattr(spec, "bls") else slashing.attestation_1.signature
    yield from run_attester_slashing_processing(spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_all_empty_indices(spec, state):
    slashing = _tamper_indices(spec, state, 1, lambda ix: ix.clear())
    slashing.attestation_2.attesting_indices = []
    yield from run_attester_slashing_processing(spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_att1_bad_extra_index(spec, state):
    # extra index not covered by the aggregate signature
    slashing = get_valid_attester_slashing(spec, state, signed_1=True, signed_2=True)
    indices = list(slashing.attestation_1.attesting_indices)
    options = [i for i in range(len(state.validators)) if i not in indices]
    slashing.attestation_1.attesting_indices = sorted(indices + options[:1])
    yield from run_attester_slashing_processing(spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_att1_bad_replaced_index(spec, state):
    slashing = get_valid_attester_slashing(spec, state, signed_1=True, signed_2=True)
    indices = list(slashing.attestation_1.attesting_indices)
    options = [i for i in range(len(state.validators)) if i not in indices]
    indices[0] = options[0]
    slashing.attestation_1.attesting_indices = sorted(indices)
    yield from run_attester_slashing_processing(spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_att2_bad_extra_index(spec, state):
    slashing = get_valid_attester_slashing(spec, state, signed_1=True, signed_2=True)
    indices = list(slashing.attestation_2.attesting_indices)
    options = [i for i in range(len(state.validators)) if i not in indices]
    slashing.attestation_2.attesting_indices = sorted(indices + options[:1])
    yield from run_attester_slashing_processing(spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_att2_bad_replaced_index(spec, state):
    slashing = get_valid_attester_slashing(spec, state, signed_1=True, signed_2=True)
    indices = list(slashing.attestation_2.attesting_indices)
    options = [i for i in range(len(state.validators)) if i not in indices]
    indices[0] = options[0]
    slashing.attestation_2.attesting_indices = sorted(indices)
    yield from run_attester_slashing_processing(spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_att1_duplicate_index_normal_signed(spec, state):
    # drop one participant, duplicate another, re-sign: indices not sorted-unique
    slashing = get_valid_attester_slashing(spec, state, signed_1=False, signed_2=True)
    indices = list(slashing.attestation_1.attesting_indices)
    indices.pop(1)
    indices.append(indices[0])  # duplicate, list still "sorted"
    slashing.attestation_1.attesting_indices = indices
    sign_indexed_attestation(spec, state, slashing.attestation_1)
    yield from run_attester_slashing_processing(spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_att2_duplicate_index_double_signed(spec, state):
    # the duplicated participant double-signs: still invalid (not unique)
    slashing = get_valid_attester_slashing(spec, state, signed_1=True, signed_2=False)
    indices = list(slashing.attestation_2.attesting_indices)
    indices.insert(1, indices[0])
    slashing.attestation_2.attesting_indices = indices
    sign_indexed_attestation(spec, state, slashing.attestation_2)
    yield from run_attester_slashing_processing(spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_unsorted_att_2(spec, state):
    slashing = get_valid_attester_slashing(spec, state, signed_1=True, signed_2=False)
    indices = list(slashing.attestation_2.attesting_indices)
    assert len(indices) >= 3
    indices[1], indices[2] = indices[2], indices[1]
    slashing.attestation_2.attesting_indices = indices
    sign_indexed_attestation(spec, state, slashing.attestation_2)
    yield from run_attester_slashing_processing(spec, state, slashing, valid=False)
