"""Differential test: JAX attestation-deltas kernel vs the sequential spec."""
import numpy as np

from consensus_specs_tpu.ops.epoch_jax import attestation_deltas_for_state
from consensus_specs_tpu.testing.context import spec_state_test, with_phases
from consensus_specs_tpu.testing.helpers.attestations import (
    prepare_state_with_attestations,
)
from consensus_specs_tpu.testing.helpers.state import next_epoch


def _assert_deltas_match(spec, state):
    spec_rewards, spec_penalties = spec.get_attestation_deltas(state)
    k_rewards, k_penalties = attestation_deltas_for_state(spec, state)
    assert [int(x) for x in spec_rewards] == k_rewards.tolist()
    assert [int(x) for x in spec_penalties] == k_penalties.tolist()


@with_phases(["phase0"])
@spec_state_test
def test_deltas_kernel_full_participation(spec, state):
    prepare_state_with_attestations(spec, state)
    _assert_deltas_match(spec, state)
    yield from ()


@with_phases(["phase0"])
@spec_state_test
def test_deltas_kernel_partial_participation(spec, state):
    prepare_state_with_attestations(
        spec, state,
        participation_fn=lambda slot, index, comm: set(list(comm)[: len(comm) // 2]),
    )
    _assert_deltas_match(spec, state)
    yield from ()


@with_phases(["phase0"])
@spec_state_test
def test_deltas_kernel_empty_participation(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    _assert_deltas_match(spec, state)
    yield from ()


@with_phases(["phase0"])
@spec_state_test
def test_deltas_kernel_inactivity_leak(spec, state):
    # skip enough epochs with no finality to enter the leak
    for _ in range(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY + 3):
        next_epoch(spec, state)
    prepare_state_with_attestations(
        spec, state,
        participation_fn=lambda slot, index, comm: set(list(comm)[: len(comm) // 3]),
    )
    assert spec.is_in_inactivity_leak(state)
    _assert_deltas_match(spec, state)
    yield from ()


@with_phases(["phase0"])
@spec_state_test
def test_deltas_kernel_with_slashed_validators(spec, state):
    prepare_state_with_attestations(spec, state)
    # slash a few attesters directly
    for index in (0, 3, 7):
        state.validators[index].slashed = True
    _assert_deltas_match(spec, state)
    yield from ()