"""Differential test: JAX attestation-deltas kernel vs the sequential spec."""

from consensus_specs_tpu.ops.epoch_jax import attestation_deltas_for_state
from consensus_specs_tpu.testing.context import spec_state_test, with_phases
from consensus_specs_tpu.testing.helpers.attestations import (
    prepare_state_with_attestations,
)
from consensus_specs_tpu.testing.helpers.state import next_epoch


def _assert_deltas_match(spec, state):
    # the installed get_attestation_deltas IS the kernel; the sequential
    # original survives as __wrapped__ — that's the differential oracle
    sequential = spec.get_attestation_deltas.__wrapped__
    spec_rewards, spec_penalties = sequential(state)
    k_rewards, k_penalties = attestation_deltas_for_state(spec, state)
    assert [int(x) for x in spec_rewards] == k_rewards.tolist()
    assert [int(x) for x in spec_penalties] == k_penalties.tolist()
    # and the substituted spec function returns the same values
    s_rewards, s_penalties = spec.get_attestation_deltas(state)
    assert [int(x) for x in s_rewards] == k_rewards.tolist()
    assert [int(x) for x in s_penalties] == k_penalties.tolist()


@with_phases(["phase0"])
@spec_state_test
def test_deltas_kernel_full_participation(spec, state):
    prepare_state_with_attestations(spec, state)
    _assert_deltas_match(spec, state)
    yield from ()


@with_phases(["phase0"])
@spec_state_test
def test_deltas_kernel_partial_participation(spec, state):
    prepare_state_with_attestations(
        spec, state,
        participation_fn=lambda slot, index, comm: set(list(comm)[: len(comm) // 2]),
    )
    _assert_deltas_match(spec, state)
    yield from ()


@with_phases(["phase0"])
@spec_state_test
def test_deltas_kernel_empty_participation(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    _assert_deltas_match(spec, state)
    yield from ()


@with_phases(["phase0"])
@spec_state_test
def test_deltas_kernel_inactivity_leak(spec, state):
    # skip enough epochs with no finality to enter the leak
    for _ in range(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY + 3):
        next_epoch(spec, state)
    prepare_state_with_attestations(
        spec, state,
        participation_fn=lambda slot, index, comm: set(list(comm)[: len(comm) // 3]),
    )
    assert spec.is_in_inactivity_leak(state)
    _assert_deltas_match(spec, state)
    yield from ()


@with_phases(["phase0"])
@spec_state_test
def test_deltas_kernel_with_slashed_validators(spec, state):
    prepare_state_with_attestations(spec, state)
    # slash a few attesters directly
    for index in (0, 3, 7):
        state.validators[index].slashed = True
    _assert_deltas_match(spec, state)
    yield from ()

@with_phases(["phase0"])
@spec_state_test
def test_substituted_rewards_and_penalties_state_root(spec, state):
    """The substituted process_rewards_and_penalties (kernel + bulk balance
    write) must produce a bit-identical post-state vs the sequential spec."""
    prepare_state_with_attestations(spec, state)
    ref_state = state.copy()
    spec.process_rewards_and_penalties.__wrapped__(ref_state)
    spec.process_rewards_and_penalties(state)
    assert [int(b) for b in state.balances] == [int(b) for b in ref_state.balances]
    assert state.hash_tree_root() == ref_state.hash_tree_root()
    yield from ()


@with_phases(["phase0"])
@spec_state_test
def test_substituted_rewards_genesis_epoch_noop(spec, state):
    root_before = state.hash_tree_root()
    assert spec.get_current_epoch(state) == spec.GENESIS_EPOCH
    spec.process_rewards_and_penalties(state)
    assert state.hash_tree_root() == root_before
    yield from ()


@with_phases(["phase0"])
@spec_state_test
def test_matching_scans(spec, state):
    """The shared-pass get_matching_{target,head}_attestations twins
    (ISSUE 10): same elements, same order, same assert points as the
    sequential originals, served off one memoized scan."""
    import pytest

    prepare_state_with_attestations(spec, state)
    for epoch in (spec.get_previous_epoch(state),
                  spec.get_current_epoch(state)):
        for name in ("get_matching_target_attestations",
                     "get_matching_head_attestations"):
            ours = getattr(spec, name)(state, epoch)
            seq = getattr(spec, name).__wrapped__(state, epoch)
            assert [bytes(a.hash_tree_root()) for a in ours] == \
                [bytes(a.hash_tree_root()) for a in seq], (name, int(epoch))
        # repeat call serves the same scan (memoized, content-addressed)
        again = spec.get_matching_target_attestations(state, epoch)
        assert again is spec.get_matching_target_attestations(state, epoch)
    # the source precondition is preserved verbatim
    with pytest.raises(AssertionError):
        spec.get_matching_target_attestations(
            state, spec.get_current_epoch(state) + 1)
    yield from ()
