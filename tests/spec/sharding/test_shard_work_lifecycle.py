"""Sharding unittests (reference suite: test/sharding/unittests/): the
shard-work status lifecycle across epoch processing, and the
participation-flag batch application the shard attestation path uses."""
import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.specs.builder import get_spec
from consensus_specs_tpu.testing.context import (
    default_activation_threshold,
    default_balances,
)
from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state
from consensus_specs_tpu.testing.helpers.state import next_epoch


@pytest.fixture(scope="module")
def spec():
    return get_spec("sharding", "minimal")


@pytest.fixture()
def state(spec):
    old = bls.bls_active
    bls.bls_active = False
    st = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec))
    bls.bls_active = old
    return st


def _buffer_index(spec, slot):
    return int(slot) % int(spec.SHARD_STATE_MEMORY_SLOTS)


def _seed_pending_header(spec, state, slot, shard_index, weight,
                         committed=True):
    """Install a PENDING shard-work entry carrying one header vote."""
    buffer_index = _buffer_index(spec, slot)
    commitment = spec.AttestedDataCommitment(
        commitment=spec.DataCommitment(point=b"\xc0" + b"\x00" * 47,
                                       samples_count=4),
        root=b"\x77" * 32,
        includer_index=1,
    ) if committed else spec.AttestedDataCommitment()
    header = spec.PendingShardHeader(
        attested=commitment,
        votes=[False] * 4,
        weight=weight,
        update_slot=slot,
    )
    row = state.shard_buffer[buffer_index]
    while len(row) <= shard_index:  # genesis rows are empty
        row.append(spec.ShardWork())
    work = state.shard_buffer[buffer_index][shard_index]
    work.status.change(
        selector=spec.SHARD_WORK_PENDING,
        value=spec.List[
            spec.PendingShardHeader,
            spec.MAX_SHARD_HEADERS_PER_SHARD]([header]),
    )
    return buffer_index


def test_pending_confirmation_picks_winning_header(spec, state):
    next_epoch(spec, state)
    prev = spec.get_previous_epoch(state)
    slot = spec.compute_start_slot_at_epoch(prev)
    buffer_index = _seed_pending_header(spec, state, slot, 0, weight=7)
    spec.process_pending_shard_confirmations(state)
    work = state.shard_buffer[buffer_index][0]
    assert int(work.status.selector) == int(spec.SHARD_WORK_CONFIRMED)
    assert bytes(work.status.value.root) == b"\x77" * 32


def test_pending_confirmation_empty_commitment_unconfirmed(spec, state):
    next_epoch(spec, state)
    prev = spec.get_previous_epoch(state)
    slot = spec.compute_start_slot_at_epoch(prev)
    buffer_index = _seed_pending_header(
        spec, state, slot, 0, weight=7, committed=False)
    spec.process_pending_shard_confirmations(state)
    work = state.shard_buffer[buffer_index][0]
    assert int(work.status.selector) == int(spec.SHARD_WORK_UNCONFIRMED)


def test_pending_confirmation_genesis_noop(spec, state):
    assert spec.get_current_epoch(state) == spec.GENESIS_EPOCH
    before = bytes(state.shard_buffer.hash_tree_root())
    spec.process_pending_shard_confirmations(state)
    assert bytes(state.shard_buffer.hash_tree_root()) == before


def test_reset_pending_shard_work_schedules_next_epoch(spec, state):
    spec.reset_pending_shard_work(state)
    next_epoch_num = spec.get_current_epoch(state) + 1
    buffer_index = _buffer_index(
        spec, spec.compute_start_slot_at_epoch(next_epoch_num))
    statuses = [int(w.status.selector)
                for w in state.shard_buffer[buffer_index]]
    assert int(spec.SHARD_WORK_PENDING) in statuses
    # pending entries start with exactly the empty-commitment header
    pending = [w for w in state.shard_buffer[buffer_index]
               if int(w.status.selector) == int(spec.SHARD_WORK_PENDING)]
    for work in pending:
        headers = work.status.value
        assert len(headers) == 1
        assert bytes(headers[0].attested.hash_tree_root()) == \
            bytes(spec.AttestedDataCommitment().hash_tree_root())


def test_batch_apply_participation_flag(spec, state):
    next_epoch(spec, state)
    committee = [2, 5, 9, 11]
    bits = [True, False, True, True]
    flag = int(spec.TIMELY_SOURCE_FLAG_INDEX)
    spec.batch_apply_participation_flag(
        state, spec.Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE](bits),
        spec.get_current_epoch(state), committee, flag)
    for bit, index in zip(bits, committee):
        assert bool(spec.has_flag(
            state.current_epoch_participation[index], flag)) == bit
    # previous-epoch routing
    spec.batch_apply_participation_flag(
        state, spec.Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE]([True]),
        spec.get_previous_epoch(state), [0], flag)
    assert spec.has_flag(state.previous_epoch_participation[0], flag)
    assert not spec.has_flag(state.current_epoch_participation[0], flag)
