"""Shard blob header / proposer-slashing processing sanity (reference
capability: the sharding fork's operation surface, sharding/beacon-chain.md
process_shard_header + process_shard_proposer_slashing).  Uses an
empty-commitment blob (samples_count=0), whose degree proof is the setup's
own first G1 point — so the full signature + fee + pending-list pipeline
runs with real BLS but no polynomial work."""
import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.crypto.bls.curve import g1_to_bytes
from consensus_specs_tpu.specs.builder import get_spec
from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state
from consensus_specs_tpu.testing.helpers.keys import privkeys, pubkeys
from consensus_specs_tpu.testing.helpers.state import next_slots


@pytest.fixture(scope="module")
def spec():
    return get_spec("sharding", "minimal")


def _seed_pending(spec, state, slot, shard):
    """Install the PENDING shard-work entry with the dummy empty header, the
    way reset_pending_shard_work initializes a committee-backed shard."""
    buffer_index = int(slot) % int(spec.SHARD_STATE_MEMORY_SLOTS)
    active = int(spec.get_active_shard_count(
        state, spec.compute_epoch_at_slot(spec.Slot(slot))))
    row = state.shard_buffer[buffer_index]
    while len(row) < active:
        row.append(spec.ShardWork())
    index = spec.compute_committee_index_from_shard(
        state, spec.Slot(slot), spec.Shard(shard))
    committee_length = len(spec.get_beacon_committee(
        state, spec.Slot(slot), index))
    state.shard_buffer[buffer_index][shard].status.change(
        selector=spec.SHARD_WORK_PENDING,
        value=spec.List[spec.PendingShardHeader,
                        spec.MAX_SHARD_HEADERS_PER_SHARD]([
            spec.PendingShardHeader(
                attested=spec.AttestedDataCommitment(),
                votes=spec.Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE](
                    [0] * committee_length),
                weight=0,
                update_slot=slot,
            )
        ]),
    )


@pytest.fixture()
def state(spec):
    old = bls.bls_active
    bls.bls_active = False
    st = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * 32, spec.MAX_EFFECTIVE_BALANCE)
    # one funded blob builder (key index 40 — outside the validator range)
    st.blob_builders.append(spec.Builder(pubkey=pubkeys[40]))
    st.blob_builder_balances.append(spec.Gwei(10**9))
    st.shard_sample_price = 8
    next_slots(spec, st, 1)
    # the committee-backed shard for the current slot is the start shard
    _seed_pending(spec, st, int(st.slot),
                  int(spec.get_start_shard(st, st.slot)))
    bls.bls_active = old
    return st


BUILDER_SK_INDEX = 40


def _empty_commitment_header(spec, state, slot=None, shard=None):
    """A SignedShardBlobHeader over an empty blob, co-signed builder+proposer."""
    g1_setup, _ = spec._kzg_setups()
    slot = int(state.slot) if slot is None else slot
    if shard is None:
        shard = int(spec.get_start_shard(state, spec.Slot(slot)))
    proposer = int(spec.get_shard_proposer_index(
        state, spec.Slot(slot), spec.Shard(shard)))
    header = spec.ShardBlobHeader(
        slot=slot,
        shard=shard,
        body_summary=spec.ShardBlobBodySummary(
            commitment=spec.DataCommitment(
                point=g1_to_bytes(g1_setup[0]), samples_count=0),
            degree_proof=g1_to_bytes(g1_setup[0]),
            max_fee_per_sample=16,
            max_priority_fee_per_sample=2,
        ),
        proposer_index=proposer,
        builder_index=0,
    )
    root = spec.compute_signing_root(
        header, spec.get_domain(state, spec.DOMAIN_SHARD_BLOB))
    sig = bls.Aggregate([
        bls.Sign(privkeys[BUILDER_SK_INDEX], root),
        bls.Sign(privkeys[proposer], root),
    ])
    return spec.SignedShardBlobHeader(message=header, signature=sig)


@pytest.fixture(autouse=True)
def _bls_on():
    old = bls.bls_active
    bls.bls_active = True
    yield
    bls.bls_active = old


def test_shard_header_accepted_and_pending(spec, state):
    signed = _empty_commitment_header(spec, state)
    header = signed.message
    pre_builder = int(state.blob_builder_balances[0])
    spec.process_shard_header(state, signed)
    # empty blob: zero samples, zero fees charged
    assert int(state.blob_builder_balances[0]) == pre_builder
    work = state.shard_buffer[
        int(header.slot) % int(spec.SHARD_STATE_MEMORY_SLOTS)][int(header.shard)]
    pending = work.status.value
    # the dummy "empty" header from initialization plus the new one
    assert len(pending) == 2
    assert bytes(pending[1].attested.root) == bytes(spec.hash_tree_root(header))
    assert int(pending[1].weight) == 0


def test_shard_header_duplicate_rejected(spec, state):
    signed = _empty_commitment_header(spec, state)
    spec.process_shard_header(state, signed)
    with pytest.raises(AssertionError):
        spec.process_shard_header(state, signed)


def test_shard_header_wrong_proposer_rejected(spec, state):
    signed = _empty_commitment_header(spec, state)
    wrong = (int(signed.message.proposer_index) + 1) % 32
    signed.message.proposer_index = wrong
    with pytest.raises(AssertionError):
        spec.process_shard_header(state, signed)


def test_shard_header_bad_signature_rejected(spec, state):
    signed = _empty_commitment_header(spec, state)
    signed.signature = spec.BLSSignature(
        b"\x11" + bytes(signed.signature)[1:])
    with pytest.raises(AssertionError):
        spec.process_shard_header(state, signed)


def test_shard_header_future_slot_rejected(spec, state):
    signed = _empty_commitment_header(spec, state, slot=int(state.slot) + 1)
    with pytest.raises(AssertionError):
        spec.process_shard_header(state, signed)


def test_shard_header_invalid_shard_rejected(spec, state):
    active = int(spec.get_active_shard_count(
        state, spec.get_current_epoch(state)))
    signed = _empty_commitment_header(spec, state)
    signed.message.shard = active  # out of range
    with pytest.raises(AssertionError):
        spec.process_shard_header(state, signed)


def _proposer_slashing(spec, state, same_reference=False):
    slot = spec.Slot(int(state.slot))
    shard = spec.Shard(0)
    proposer = int(spec.get_shard_proposer_index(state, slot, shard))
    domain = spec.get_domain(
        state, spec.DOMAIN_SHARD_PROPOSER, spec.compute_epoch_at_slot(slot))

    def signed_ref(body_root):
        ref = spec.ShardBlobReference(
            slot=slot, shard=shard, proposer_index=proposer,
            builder_index=0, body_root=body_root)
        root = spec.compute_signing_root(ref, domain)
        return bls.Aggregate([
            bls.Sign(privkeys[BUILDER_SK_INDEX], root),
            bls.Sign(privkeys[proposer], root),
        ])

    root_1 = b"\x01" * 32
    root_2 = root_1 if same_reference else b"\x02" * 32
    return spec.ShardProposerSlashing(
        slot=slot, shard=shard, proposer_index=proposer,
        builder_index_1=0, builder_index_2=0,
        body_root_1=root_1, body_root_2=root_2,
        signature_1=signed_ref(root_1),
        signature_2=signed_ref(root_2),
    )


def test_shard_proposer_slashing(spec, state):
    slashing = _proposer_slashing(spec, state)
    proposer = int(slashing.proposer_index)
    assert not state.validators[proposer].slashed
    spec.process_shard_proposer_slashing(state, slashing)
    assert state.validators[proposer].slashed


def test_shard_proposer_slashing_same_reference_rejected(spec, state):
    slashing = _proposer_slashing(spec, state, same_reference=True)
    with pytest.raises(AssertionError):
        spec.process_shard_proposer_slashing(state, slashing)


def test_shard_proposer_slashing_bad_signature_rejected(spec, state):
    slashing = _proposer_slashing(spec, state)
    slashing.signature_2 = spec.BLSSignature(
        b"\x11" + bytes(slashing.signature_2)[1:])
    with pytest.raises(AssertionError):
        spec.process_shard_proposer_slashing(state, slashing)
