"""Start-shard / committee-index algebra unittests (reference suite:
test/sharding/unittests/test_get_start_shard.py; this spec snapshot's
``get_start_shard`` is the closed-form committee_count*slot formula from
the vendored sharding/beacon-chain.md, so the scenarios cover the same
surface — current/next/previous slot and far epochs — against it)."""
import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.specs.builder import get_spec
from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state


@pytest.fixture(scope="module")
def spec():
    return get_spec("sharding", "minimal")


@pytest.fixture()
def state(spec):
    old = bls.bls_active
    bls.bls_active = False
    st = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * 32, spec.MAX_EFFECTIVE_BALANCE)
    bls.bls_active = old
    return st


def _expected(spec, state, slot):
    epoch = spec.compute_epoch_at_slot(spec.Slot(slot))
    committees = int(spec.get_committee_count_per_slot(state, epoch))
    active = int(spec.get_active_shard_count(state, epoch))
    return committees * slot % active


def test_start_shard_current_slot(spec, state):
    slot = int(state.slot)
    got = int(spec.get_start_shard(state, spec.Slot(slot)))
    assert got == _expected(spec, state, slot)
    assert got < int(spec.get_active_shard_count(
        state, spec.get_current_epoch(state)))


def test_start_shard_next_and_previous_slot(spec, state):
    state.slot = spec.Slot(int(spec.SLOTS_PER_EPOCH) * 3)
    for delta in (-1, 0, 1):
        slot = int(state.slot) + delta
        assert int(spec.get_start_shard(state, spec.Slot(slot))) == \
            _expected(spec, state, slot)


def test_start_shard_far_future_epoch_slot(spec, state):
    slot = int(spec.SLOTS_PER_EPOCH) * 128 + 3
    assert int(spec.get_start_shard(state, spec.Slot(slot))) == \
        _expected(spec, state, slot)


def test_shard_from_committee_index_consistent_with_start_shard(spec, state):
    state.slot = spec.Slot(int(spec.SLOTS_PER_EPOCH) * 2 + 5)
    slot = spec.Slot(int(state.slot))
    epoch = spec.compute_epoch_at_slot(slot)
    active = int(spec.get_active_shard_count(state, epoch))
    start = int(spec.get_start_shard(state, slot))
    committees = int(spec.get_committee_count_per_slot(state, epoch))
    for index in range(committees):
        shard = int(spec.compute_shard_from_committee_index(
            state, slot, spec.CommitteeIndex(index)))
        assert shard == (index + start) % active
        back = int(spec.compute_committee_index_from_shard(
            state, slot, spec.Shard(shard)))
        assert back == index


def test_shard_index_out_of_range_rejected(spec, state):
    slot = spec.Slot(int(state.slot))
    epoch = spec.compute_epoch_at_slot(slot)
    active = int(spec.get_active_shard_count(state, epoch))
    with pytest.raises(AssertionError):
        spec.compute_shard_from_committee_index(
            state, slot, spec.CommitteeIndex(active))
