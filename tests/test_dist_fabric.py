"""Dist fabric units (ISSUE 20): the codec's torn-frame ladder, the
2-worker echo path, reply ordering/dedup, hedging, and the deterministic
chunk math — the fault-free half of the contract (the failure schedules
live in tests/chaos/test_dist_chaos.py)."""
import hashlib
import io
import threading

import pytest

from consensus_specs_tpu.dist import codec, dispatch, fabric as fabmod
from consensus_specs_tpu.dist.dispatch import TaskSpec
from consensus_specs_tpu.dist.fabric import Fabric
from consensus_specs_tpu.dist.workloads import _chunk_bounds
from consensus_specs_tpu.persist import atomic


@pytest.fixture(autouse=True)
def _fresh_stats():
    dispatch.reset_stats()
    fabmod.reset_stats()
    yield


# -- codec ---------------------------------------------------------------------


def test_codec_roundtrip():
    buf = io.BytesIO()
    codec.write_frame(buf, "task", {"id": "t0", "kind": "echo"}, b"payload")
    codec.write_frame(buf, "reply", {"ok": True}, b"")
    buf.seek(0)
    assert codec.read_frame(buf) == ("task", {"id": "t0", "kind": "echo"},
                                     b"payload")
    assert codec.read_frame(buf) == ("reply", {"ok": True}, b"")
    assert codec.read_frame(buf) is None  # clean EOF at a frame boundary


def test_codec_torn_frame_is_detected():
    raw = codec.encode_frame("task", {"id": "t0"}, b"x" * 100)
    for cut in (2, 5, len(raw) - 1):  # mid-prefix, mid-header, mid-digest
        with pytest.raises(atomic.ArtifactCorrupt):
            codec.read_frame(io.BytesIO(raw[:cut]))


def test_codec_flipped_bit_is_detected():
    raw = bytearray(codec.encode_frame("task", {"id": "t0"}, b"x" * 64))
    raw[len(raw) // 2] ^= 0x01
    with pytest.raises(atomic.ArtifactCorrupt):
        codec.read_frame(io.BytesIO(bytes(raw)))


def test_codec_foreign_protocol_tag_is_stale():
    env = atomic.envelope(b'{"a":1}\x00body', "task", "dist-v0")
    import struct
    raw = struct.pack("<I", len(env)) + env
    with pytest.raises(atomic.ArtifactStaleTag):
        codec.read_frame(io.BytesIO(raw))


def test_codec_insane_length_prefix_is_corrupt():
    import struct
    raw = struct.pack("<I", codec.MAX_FRAME + 1) + b"zzzz"
    with pytest.raises(atomic.ArtifactCorrupt):
        codec.read_frame(io.BytesIO(raw))


# -- the 2-worker echo path ----------------------------------------------------


def _echo_expect(i):
    body = f"chunk-{i}".encode()
    return hashlib.sha256(body).digest() + body


def test_two_worker_echo_batch():
    with Fabric(n_workers=2, heartbeat_interval=0.1) as fab:
        tasks = [TaskSpec("echo", {}, f"chunk-{i}".encode())
                 for i in range(6)]
        out = dispatch.run_tasks(fab, tasks, deadline_s=20.0)
    assert [body for _, body in out] == [_echo_expect(i) for i in range(6)]
    assert all(meta["ok"] for meta, _ in out)
    snap = dispatch.snapshot()
    # fault-free: nothing re-dispatched, nothing hedged, nothing lost
    assert snap["redispatched_chunks"] == 0
    assert snap["hedged_tasks"] == 0
    assert snap["worker_losses"] == 0
    assert snap["replies"] == 6
    fsnap = fabmod.snapshot()
    assert fsnap["spawned"] == 2
    assert fsnap["corrupt_replies"] == 0


def test_results_come_back_in_task_order():
    """Replies arrive out of order (task 0 is the slowest) but the merge
    surface is task-ordered — the fixed-merge-order contract every
    workload builds on."""
    with Fabric(n_workers=2, heartbeat_interval=0.1) as fab:
        tasks = [TaskSpec("sleep_echo", {"seconds": 0.4}, b"slow"),
                 TaskSpec("echo", {}, b"fast-1"),
                 TaskSpec("echo", {}, b"fast-2")]
        out = dispatch.run_tasks(fab, tasks, deadline_s=20.0)
    bodies = [body[32:] for _, body in out]
    assert bodies == [b"slow", b"fast-1", b"fast-2"]


def test_worker_scope_reaches_the_worker_process():
    """Each worker reports its CSTPU_DIST_PROC scope back in replies —
    the addressing a scoped chaos plan relies on."""
    with Fabric(n_workers=2, heartbeat_interval=0.1) as fab:
        tasks = [TaskSpec("echo", {}, bytes([i])) for i in range(4)]
        out = dispatch.run_tasks(fab, tasks, deadline_s=20.0)
    procs = {meta["proc"] for meta, _ in out}
    assert procs == {"proc1", "proc2"}  # round-robin touched both


def test_coordinator_wears_proc0_scope_inside_fabric_extent():
    from consensus_specs_tpu import faults

    assert faults.process_scope() is None
    with Fabric(n_workers=1, heartbeat_interval=0.1):
        assert faults.process_scope() == "proc0"
    assert faults.process_scope() is None


def test_hedge_duplicates_a_straggler():
    """A chunk in flight past hedge_s gets one duplicate on the second
    worker; the hedge is NOT a re-dispatched chunk (the fault-free gate
    keys on that distinction)."""
    with Fabric(n_workers=2, heartbeat_interval=0.1) as fab:
        tasks = [TaskSpec("sleep_echo", {"seconds": 0.6}, b"straggler")]
        out = dispatch.run_tasks(fab, tasks, deadline_s=30.0, hedge_s=0.15)
    assert out[0][1][32:] == b"straggler"
    snap = dispatch.snapshot()
    assert snap["hedged_tasks"] == 1
    assert snap["redispatched_chunks"] == 0


def test_duplicate_replies_are_discarded_by_task_id():
    """Unit-level dedup: a second reply for a settled task id is counted
    and dropped, never merged."""
    run = dispatch._DispatchRun.__new__(dispatch._DispatchRun)
    run.fabric = None
    pending = dispatch._Pending("r0.t0", 0, TaskSpec("echo", {}, b""))
    pending.workers = {"proc1"}
    run._inflight = {"r0.t0": pending}
    run._results = {}
    run._done = set()
    run._n = 1

    class _NoFabric:
        def worker(self, proc):
            return None

    run.fabric = _NoFabric()
    first = fabmod.Event("reply", "proc1", {"id": "r0.t0", "ok": True}, b"a")
    dupe = fabmod.Event("reply", "proc2", {"id": "r0.t0", "ok": True}, b"b")
    run._on_reply(first)
    run._on_reply(dupe)
    assert run._results[0][1] == b"a"  # first valid reply won
    assert dispatch.snapshot()["duplicate_replies"] == 1


def test_shutdown_is_clean():
    fab = Fabric(n_workers=2, heartbeat_interval=0.1).start()
    procs = [w.popen for w in fab.alive_workers()]
    fab.close()
    assert all(p.poll() is not None for p in procs)
    # close() is idempotent
    fab.close()


# -- deterministic chunk math --------------------------------------------------


def test_chunk_bounds_cover_and_are_deterministic():
    for n in (1, 2, 7, 16, 100):
        for k in (1, 2, 3, 8):
            bounds = _chunk_bounds(n, k)
            assert bounds == _chunk_bounds(n, k)
            assert bounds[0][0] == 0 and bounds[-1][1] == n
            for (a, b), (c, d) in zip(bounds, bounds[1:]):
                assert b == c and b > a
            sizes = [hi - lo for lo, hi in bounds]
            assert max(sizes) - min(sizes) <= 1


def test_chunk_bounds_degenerate():
    assert _chunk_bounds(3, 10) == [(0, 1), (1, 2), (2, 3)]
    assert _chunk_bounds(5, 1) == [(0, 5)]


# -- telemetry surface ---------------------------------------------------------


def test_snapshots_ride_the_telemetry_bus():
    from consensus_specs_tpu import telemetry

    tree = telemetry.snapshot()["providers"]
    assert "redispatched_chunks" in tree["dist.dispatch"]
    assert "corrupt_replies" in tree["dist.fabric"]


def test_stats_are_lock_guarded():
    """Counter bumps from many threads never lose increments (the reader
    threads and the dispatch loop all write these)."""
    def spin():
        for _ in range(1000):
            dispatch._bump("replies")

    threads = [threading.Thread(target=spin) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert dispatch.snapshot()["replies"] == 4000
