"""Device-resident merkleization parity: the resident subtree root and the
spliced full-state root must be bit-identical to the SSZ host path
(ops/merkle_resident.py; reference seam: ssz_impl.hash_tree_root)."""
import numpy as np
import pytest

from consensus_specs_tpu.ops.merkle_resident import (
    ResidentPackedU64List,
    replace_field_subtree,
)
from consensus_specs_tpu.ssz.impl import hash_tree_root
from consensus_specs_tpu.ssz.node import merkle_root
from consensus_specs_tpu.ssz.types import List, uint64

LIMIT = 2**40


@pytest.mark.parametrize("n", [1, 3, 4, 5, 63, 1024])
def test_resident_root_matches_ssz(n):
    rng = np.random.default_rng(n)
    values = rng.integers(0, 2**63, n, dtype=np.uint64)
    resident = ResidentPackedU64List(LIMIT)
    resident.upload(values)
    expected = bytes(hash_tree_root(List[uint64, LIMIT](*map(int, values))))
    assert resident.root() == expected


def test_resident_apply_add_scalar_and_vector():
    rng = np.random.default_rng(99)
    values = rng.integers(0, 2**62, 200, dtype=np.uint64)
    resident = ResidentPackedU64List(LIMIT)
    resident.upload(values)

    resident.apply_add(7)
    values = values + np.uint64(7)
    assert (resident.to_numpy() == values).all()

    deltas = rng.integers(-1000, 1000, 200)
    resident.apply_add(deltas)
    values = (values.astype(np.int64) + deltas).astype(np.uint64)
    assert (resident.to_numpy() == values).all()
    assert resident.root() == bytes(
        hash_tree_root(List[uint64, LIMIT](*map(int, values))))


def test_resident_splice_into_state_root():
    from consensus_specs_tpu.specs.builder import get_spec
    from consensus_specs_tpu.ssz import bulk
    from consensus_specs_tpu.testing.context import (
        default_activation_threshold,
        default_balances,
    )
    from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state

    spec = get_spec("phase0", "minimal")
    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec))
    cls = type(state)

    balances = bulk.packed_uint64_to_numpy(state.balances).astype(np.uint64)
    resident = ResidentPackedU64List(type(state.balances).LENGTH)
    resident.upload(balances)
    resident.apply_add(5)

    clean = state.get_backing()
    spliced = replace_field_subtree(
        clean, cls._field_index["balances"], cls._depth,
        resident.as_backing_node())

    host = state.copy()
    bulk.set_packed_uint64_from_numpy(host.balances, balances + np.uint64(5))
    assert merkle_root(spliced) == bytes(host.hash_tree_root())
