"""Device-resident merkleization parity: the resident subtree root and the
spliced full-state root must be bit-identical to the SSZ host path
(ops/merkle_resident.py; reference seam: ssz_impl.hash_tree_root)."""
import numpy as np
import pytest

from consensus_specs_tpu.ops.merkle_resident import (
    ResidentPackedU64List,
    replace_field_subtree,
)
from consensus_specs_tpu.ssz.impl import hash_tree_root
from consensus_specs_tpu.ssz.node import merkle_root
from consensus_specs_tpu.ssz.types import List, uint64

LIMIT = 2**40


@pytest.mark.parametrize("n", [1, 3, 4, 5, 63, 1024])
def test_resident_root_matches_ssz(n):
    rng = np.random.default_rng(n)
    values = rng.integers(0, 2**63, n, dtype=np.uint64)
    resident = ResidentPackedU64List(LIMIT)
    resident.upload(values)
    expected = bytes(hash_tree_root(List[uint64, LIMIT](*map(int, values))))
    assert resident.root() == expected


def test_resident_apply_add_scalar_and_vector():
    rng = np.random.default_rng(99)
    values = rng.integers(0, 2**62, 200, dtype=np.uint64)
    resident = ResidentPackedU64List(LIMIT)
    resident.upload(values)

    resident.apply_add(7)
    values = values + np.uint64(7)
    assert (resident.to_numpy() == values).all()

    deltas = rng.integers(-1000, 1000, 200)
    resident.apply_add(deltas)
    values = (values.astype(np.int64) + deltas).astype(np.uint64)
    assert (resident.to_numpy() == values).all()
    assert resident.root() == bytes(
        hash_tree_root(List[uint64, LIMIT](*map(int, values))))


@pytest.mark.parametrize("n", [5, 64, 100, 256])
def test_memoize_contents_root_matches_host(n):
    """memoize_packed_u64_contents_root installs a root the host hasher
    would have produced — pinned across pow2 and ragged lengths."""
    from consensus_specs_tpu.ops import merkle_resident
    from consensus_specs_tpu.ssz import bulk

    rng = np.random.default_rng(n)
    values = rng.integers(0, 2**63, n, dtype=np.uint64)
    resident = ResidentPackedU64List(LIMIT)
    resident.upload(values)
    padded_root = resident.contents_subtree_root()

    lst = List[uint64, LIMIT]()
    bulk.set_packed_uint64_from_numpy(lst, values)
    merkle_resident.memoize_packed_u64_contents_root(lst, padded_root)
    backing = lst.get_backing()
    assert backing.left._root is not None, "root was not memoized"
    expected = bytes(hash_tree_root(List[uint64, LIMIT](*map(int, values))))
    assert bytes(hash_tree_root(lst)) == expected


def test_fused_epoch_update_is_root_identical_to_host_path(monkeypatch):
    """The SHIPPING integration: process_rewards_and_penalties routed
    through the fused deltas+merkle program (forced on, threshold lowered)
    must leave a state whose full hash_tree_root is bit-identical to the
    host kernel path — the VERDICT 'residency composes' contract."""
    import jax

    from consensus_specs_tpu.ops import merkle_resident
    from consensus_specs_tpu.specs.builder import get_spec
    from consensus_specs_tpu.testing.context import (
        default_activation_threshold,
        default_balances,
    )
    from consensus_specs_tpu.testing.helpers.attestations import (
        next_epoch_with_attestations,
    )
    from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state

    spec = get_spec("phase0", "minimal")
    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec))
    # a previous epoch of attestations so the deltas kernel has real work
    _, _, state = next_epoch_with_attestations(spec, state, True, False)

    host_state = state.copy()
    dev_state = state.copy()

    monkeypatch.setenv("CSTPU_RESIDENT_MERKLE", "0")
    spec.process_rewards_and_penalties(host_state)

    monkeypatch.setenv("CSTPU_RESIDENT_MERKLE", "1")
    monkeypatch.setattr(merkle_resident, "RESIDENT_MIN", 1)
    before = merkle_resident.stats["fused_epoch_updates"]
    spec.process_rewards_and_penalties(dev_state)
    assert merkle_resident.stats["fused_epoch_updates"] == before + 1, \
        "fused path did not engage"
    assert merkle_resident.stats["roots_memoized"] > 0

    assert bytes(dev_state.hash_tree_root()) == bytes(host_state.hash_tree_root())
    # values identical too, not just roots
    from consensus_specs_tpu.ssz import bulk

    assert (bulk.packed_uint64_to_numpy(dev_state.balances)
            == bulk.packed_uint64_to_numpy(host_state.balances)).all()


def test_resident_device_policy(monkeypatch):
    from consensus_specs_tpu.ops import merkle_resident

    monkeypatch.setenv("CSTPU_RESIDENT_MERKLE", "0")
    assert merkle_resident.resident_device() is None
    monkeypatch.setenv("CSTPU_RESIDENT_MERKLE", "1")
    assert merkle_resident.resident_device() is not None
    # auto on the CPU test backend: host hashing wins, stay off
    monkeypatch.setenv("CSTPU_RESIDENT_MERKLE", "auto")
    import jax

    expected_off = jax.devices()[0].platform == "cpu"
    assert (merkle_resident.resident_device() is None) == expected_off


def test_resident_splice_into_state_root():
    from consensus_specs_tpu.specs.builder import get_spec
    from consensus_specs_tpu.ssz import bulk
    from consensus_specs_tpu.testing.context import (
        default_activation_threshold,
        default_balances,
    )
    from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state

    spec = get_spec("phase0", "minimal")
    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec))
    cls = type(state)

    balances = bulk.packed_uint64_to_numpy(state.balances).astype(np.uint64)
    resident = ResidentPackedU64List(type(state.balances).LENGTH)
    resident.upload(balances)
    resident.apply_add(5)

    clean = state.get_backing()
    spliced = replace_field_subtree(
        clean, cls._field_index["balances"], cls._depth,
        resident.as_backing_node())

    host = state.copy()
    bulk.set_packed_uint64_from_numpy(host.balances, balances + np.uint64(5))
    assert merkle_root(spliced) == bytes(host.hash_tree_root())
