"""Proof-serving differential at registry scale (ISSUE 16 satellite).

A mainnet-shape synthetic state (bench's ``build_state``) goes through
the REAL serving pipeline — checkpoint payload, on-disk artifact, mmap'd
``QueryEngine`` — and single-validator proofs for seeded random indices
must verify against ``spec.hash_tree_root(state)`` computed on the
materialized state.  The engine must serve every proof WITHOUT
materializing the state (``state_materializations`` stays 0): the whole
point of the read path is that proofs are offset walks, not decodes.
The 16k tier runs in tier-1; the 400k tier is ``slow`` (bench-scale).
"""
import os
import random
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from bench import build_state  # noqa: E402

from consensus_specs_tpu import query  # noqa: E402
from consensus_specs_tpu.node.service import default_anchor_block  # noqa: E402
from consensus_specs_tpu.persist import store as persist_store  # noqa: E402
from consensus_specs_tpu.persist.store import CheckpointStore  # noqa: E402
from consensus_specs_tpu.query.engine import QueryEngine  # noqa: E402
from consensus_specs_tpu.query.streamproof import verify_proof  # noqa: E402


def _engine_over_artifact(spec, state, directory):
    """The real pipeline: payload -> synchronous write -> fresh engine
    over the store's mmap'd artifact."""
    anchor_block = default_anchor_block(spec, state)
    root = bytes(anchor_block.hash_tree_root())
    payload = persist_store.CheckpointPayload(
        journal_pos=1, trigger=("tick", 0),
        time=int(state.genesis_time),
        justified=(0, root), best_justified=(0, root), finalized=(0, root),
        proposer_boost_root=b"\x00" * 32,
        latest_messages={}, equivocating=frozenset(),
        anchor_root=root,
        window=((root, anchor_block, state),),
        head_state_root=bytes(state.hash_tree_root()))
    store = CheckpointStore(directory, asynchronous=False)
    store.write_checkpoint(spec, payload)
    return QueryEngine(spec, store)


def _differential(n, tmp_path, n_samples=24, seed=0xC0FFEE):
    from consensus_specs_tpu.specs.builder import get_spec

    spec = get_spec("phase0", "mainnet")
    state = build_state(spec, n)
    root = bytes(spec.hash_tree_root(state))
    engine = _engine_over_artifact(spec, state, str(tmp_path))

    query.reset_stats()
    indices = random.Random(seed).sample(range(n), n_samples)
    indices += [0, n - 1]  # the boundary chunks
    for i in indices:
        pr = engine.proof_of_validator(i)
        assert pr is not None, i
        assert pr["state_root"] == root
        assert verify_proof(pr["leaf"], pr["branch"], pr["gindex"], root), i
        # cross-check a served field against the materialized state
        st = engine.validator_status(i)
        assert st["exit_epoch"] == int(state.validators[i].exit_epoch)
        assert engine.balance_of(i) == int(state.balances[i])

    # every proof was an offset walk off the mmap — the state was NEVER
    # rebuilt on the serving path
    assert query.stats["state_materializations"] == 0
    assert query.stats["proofs_served"] == len(indices)

    # tampered-leaf negative at this scale too
    pr = engine.proof_of_validator(indices[0])
    bad = bytes([pr["leaf"][0] ^ 1]) + pr["leaf"][1:]
    assert not verify_proof(bad, pr["branch"], pr["gindex"], root)
    engine.reset()


def test_proof_differential_16k(tmp_path):
    _differential(16_384, tmp_path)


@pytest.mark.slow
def test_proof_differential_400k(tmp_path):
    _differential(400_000, tmp_path)
