"""The historical query engine over a really-served node (ISSUE 16).

A three-epoch corpus runs through a ``Node`` with a synchronous
checkpoint store; the engine then serves summaries, balances, statuses,
proofs, votes and full states straight off the newest mmap'd artifact.
Every answer is differentially checked against the node's OWN copy of
the checkpoint-head state (artifacts lag the live head — they are
written at epoch fences), and walking every historical root through the
cap-2 resident set exercises spill + re-fault coherence."""
import pytest

from consensus_specs_tpu import query
from consensus_specs_tpu.node import firehose, service
from consensus_specs_tpu.persist.store import CheckpointStore
from consensus_specs_tpu.query import streamproof
from consensus_specs_tpu.testing.context import (
    default_activation_threshold,
    default_balances,
)
from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state


@pytest.fixture(autouse=True)
def _bls_off():
    from consensus_specs_tpu.crypto import bls

    prev = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = prev


_SCAFFOLD = {}


def _corpus():
    if not _SCAFFOLD:
        from consensus_specs_tpu.specs.builder import get_spec

        spec = get_spec("phase0", "minimal")
        state = create_genesis_state(
            spec, default_balances(spec), default_activation_threshold(spec))
        corpus = firehose.build_corpus(
            spec, state, n_epochs=3, gossip_target=120)
        _SCAFFOLD["phase0"] = (spec, state, corpus)
    return _SCAFFOLD["phase0"]


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """(spec, node): the corpus fully applied with a synchronous store —
    the engine is live and artifact-fed by the time the fixture yields."""
    from consensus_specs_tpu.crypto import bls

    prev = bls.bls_active
    bls.bls_active = False
    try:
        spec, state, corpus = _corpus()
        store = CheckpointStore(
            str(tmp_path_factory.mktemp("query_store")), asynchronous=False)
        service.reset_stats()
        query.reset_stats()
        node = service.Node(spec, state, corpus.anchor_block,
                            checkpoint_store=store)
        assert node.query_engine is not None
        for signed in corpus.chain:
            s = int(signed.message.slot)
            node.enqueue_tick(int(state.genesis_time)
                              + s * int(spec.config.SECONDS_PER_SLOT))
            node.enqueue_block(signed)
            for att in corpus.gossip.get(s - 1, ()):
                node.enqueue_attestations([att])
        last = int(corpus.chain[-1].message.slot)
        node.enqueue_tick(int(state.genesis_time)
                          + (last + 1) * int(spec.config.SECONDS_PER_SLOT))
        node.queue.close()
        node.run_apply_loop()
        yield spec, node
        store.close()
    finally:
        bls.bls_active = prev


def _checkpoint_head(node):
    """The artifact's head state — the node's own copy of it, the
    differential reference for everything the engine serves."""
    summ = node.query_engine.summary()
    assert summ is not None, "no artifact served"
    ref = node.store.block_states[bytes.fromhex(summ["head_block_root"])]
    assert bytes.fromhex(summ["head_state_root"]) == \
        bytes(ref.hash_tree_root())
    return summ, ref


def test_summary_serves_the_checkpoint_world(served):
    _spec, node = served
    summ, _ref = _checkpoint_head(node)
    assert summ["window_depth"] >= 1
    assert summ["journal_pos"] > 0
    assert summ["n_latest_messages"] >= 0


def test_point_queries_differential_vs_the_nodes_state(served):
    _spec, node = served
    eng = node.query_engine
    _summ, ref = _checkpoint_head(node)
    hsr = bytes(ref.hash_tree_root())
    for i in (0, 3, 17, 63):
        assert eng.balance_of(i) == int(ref.balances[i]), i
        st = eng.validator_status(i)
        assert st["exit_epoch"] == int(ref.validators[i].exit_epoch)
        assert st["effective_balance"] == \
            int(ref.validators[i].effective_balance)
        assert st["slashed"] == bool(ref.validators[i].slashed)
        pr = eng.proof_of_validator(i)
        assert pr["state_root"] == hsr
        assert streamproof.verify_proof(pr["leaf"], pr["branch"],
                                        pr["gindex"], hsr)
        v = eng.vote_of(i)  # votes as of checkpoint time; shape-check
        assert v is None or (isinstance(v["epoch"], int)
                             and len(v["root"]) == 32)


def test_state_at_root_serves_head_and_history(served):
    _spec, node = served
    eng = node.query_engine
    _summ, ref = _checkpoint_head(node)
    hsr = bytes(ref.hash_tree_root())
    assert bytes(eng.state_at_root().hash_tree_root()) == hsr
    hist = eng.historical_roots()
    assert hsr in hist
    oldest = hist[0]
    assert bytes(eng.state_at_root(oldest).hash_tree_root()) == oldest


def test_resident_eviction_spills_and_refaults_coherently(served):
    _spec, node = served
    eng = node.query_engine
    query.reset_stats()
    hist = eng.historical_roots()
    # two passes over every root through the cap-bounded resident set:
    # the second pass re-faults whatever the first evicted
    for _ in range(2):
        for r in hist:
            assert bytes(eng.state_at_root(r).hash_tree_root()) == r
    gauges = eng.cache_gauges()
    assert 0 < gauges["resident_size"] <= gauges["resident_cap"]
    if len(hist) > gauges["resident_cap"]:
        assert query.stats["spills"] > 0
        assert query.stats["refaults"] > 0
    assert query.stats["queries_served"] == 2 * len(hist)


def test_cache_gauges_stay_bounded(served):
    _spec, node = served
    g = node.query_engine.cache_gauges()
    assert g["artifact_index_size"] <= g["artifact_index_cap"]
    assert g["proof_cache_size"] <= g["proof_cache_cap"]
    assert g["resident_size"] <= g["resident_cap"]


def test_unknown_root_and_unknown_validator_are_clean_misses(served):
    _spec, node = served
    eng = node.query_engine
    query.reset_stats()
    assert eng.state_at_root(b"\xee" * 32) is None
    assert eng.balance_of(10 ** 9) is None
    assert eng.validator_status(10 ** 9) is None
    assert eng.proof_of_validator(10 ** 9) is None
    assert query.stats["queries_unserved"] == 4
    assert query.stats["queries_served"] == 0
