"""Streaming Merkle proofs off the encoded subtree format (ISSUE 16).

Differential ground truth is the ssz layer's own ``build_proof`` over
the materialized backing: for every validator and a spread of paths
(container, basic list element, field-within-container, list length
mixin) the offset-walking ``proof_at`` must produce byte-identical
branches, and every proof must verify against the state root.  A
tampered leaf must NOT verify — the negative that keeps ``verify_proof``
honest."""
import pytest

from consensus_specs_tpu.persist.store import encode_tree
from consensus_specs_tpu.query import streamproof
from consensus_specs_tpu.ssz.gindex import (
    build_proof,
    get_generalized_index,
    get_subtree_at_gindex,
)
from consensus_specs_tpu.ssz.node import merkle_root
from consensus_specs_tpu.testing.context import (
    default_activation_threshold,
    default_balances,
)
from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state


@pytest.fixture(scope="module")
def scaffold():
    """(spec, state, buf, entries, eid, root): a minimal genesis state
    run through the checkpoint codec's ``encode_tree``, then indexed by
    the streaming parser — the exact shape the engine serves from."""
    from consensus_specs_tpu.specs.builder import get_spec

    spec = get_spec("phase0", "minimal")
    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec))
    root = bytes(state.hash_tree_root())
    out = bytearray()
    encode_tree(state.get_backing(), out, {})
    buf = bytes(out)
    entries = []
    eid, off = streamproof.parse_tree(buf, 0, entries)
    assert off == len(buf)
    return spec, state, buf, entries, eid, root


def test_entry_root_matches_the_state_root(scaffold):
    _spec, _state, buf, entries, eid, root = scaffold
    assert streamproof.entry_root(buf, entries, eid) == root


def test_proofs_differential_vs_build_proof_all_validators(scaffold):
    spec, state, buf, entries, eid, root = scaffold
    backing = state.get_backing()
    n = len(state.validators)
    assert n >= 64
    for i in range(n):
        for path in (("validators", i), ("balances", i),
                     ("validators", i, "exit_epoch"),
                     ("balances", "__len__")):
            g = get_generalized_index(spec.BeaconState, *path)
            ref = build_proof(backing, g)
            leaf, branch = streamproof.proof_at(buf, entries, eid, g)
            assert branch == ref, (path, "branch mismatch")
            assert streamproof.verify_proof(leaf, branch, g, root), path
            assert streamproof.node_root_at(buf, entries, eid, g) == \
                merkle_root(get_subtree_at_gindex(backing, g))


def test_leaf_chunks_carry_the_actual_content(scaffold):
    spec, state, buf, entries, eid, _root = scaffold
    n = len(state.validators)
    g = get_generalized_index(spec.BeaconState, "balances", 3)
    chunk = streamproof.node_root_at(buf, entries, eid, g)
    bal = int.from_bytes(chunk[(3 % 4) * 8:(3 % 4) * 8 + 8], "little")
    assert bal == int(state.balances[3])
    g = get_generalized_index(spec.BeaconState, "balances", "__len__")
    ln = int.from_bytes(streamproof.node_root_at(buf, entries, eid, g)[:8],
                        "little")
    assert ln == n


def test_tampered_leaf_does_not_verify(scaffold):
    spec, _state, buf, entries, eid, root = scaffold
    g = get_generalized_index(spec.BeaconState, "validators", 0)
    leaf, branch = streamproof.proof_at(buf, entries, eid, g)
    assert streamproof.verify_proof(leaf, branch, g, root)
    bad = bytes([leaf[0] ^ 1]) + leaf[1:]
    assert not streamproof.verify_proof(bad, branch, g, root)
    # a tampered branch node fails too
    bad_branch = [branch[0]] if len(branch) == 1 else list(branch)
    bad_branch[0] = bytes([bad_branch[0][0] ^ 1]) + bad_branch[0][1:]
    assert not streamproof.verify_proof(leaf, type(branch)(bad_branch), g,
                                        root)
