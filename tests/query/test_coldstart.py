"""Checkpoint-sync cold starts (ISSUE 16): ``restore_or_build`` is the
universal state-build seam — first call builds and snapshots, the next
process restores byte-identically (verified once per artifact), the
``CSTPU_NO_CHECKPOINT_SYNC=1`` escape hatch forces the literal build,
and a rotted snapshot quarantines and falls back."""
import os

import pytest

from consensus_specs_tpu.query import coldstart, reset_stats, stats
from consensus_specs_tpu.testing.context import (
    default_activation_threshold,
    default_balances,
)
from consensus_specs_tpu.testing.helpers.genesis import create_genesis_state


@pytest.fixture()
def scaffold():
    from consensus_specs_tpu.specs.builder import get_spec

    spec = get_spec("phase0", "minimal")
    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec))
    reset_stats()
    coldstart.forget_verified()
    return spec, state


def test_build_then_restore_is_byte_identical(scaffold, tmp_path):
    spec, state = scaffold
    root = bytes(state.hash_tree_root())
    calls = []

    def build():
        calls.append(1)
        return state

    s1 = coldstart.restore_or_build(spec, len(state.validators), build,
                                    label="t", cache_dir=str(tmp_path))
    assert len(calls) == 1
    assert stats["coldstart_builds"] == 1
    assert stats["coldstart_writes"] == 1
    assert bytes(s1.hash_tree_root()) == root

    # a fresh process (the verified-memo forgotten) restores, not rebuilds
    coldstart.forget_verified()
    s2 = coldstart.restore_or_build(spec, len(state.validators), build,
                                    label="t", cache_dir=str(tmp_path))
    assert len(calls) == 1, "should restore, not rebuild"
    assert stats["coldstart_restores"] == 1
    assert bytes(s2.hash_tree_root()) == root


def test_opt_out_env_forces_the_literal_build(scaffold, tmp_path,
                                              monkeypatch):
    spec, state = scaffold
    calls = []

    def build():
        calls.append(1)
        return state

    coldstart.restore_or_build(spec, len(state.validators), build,
                               label="t", cache_dir=str(tmp_path))
    monkeypatch.setenv("CSTPU_NO_CHECKPOINT_SYNC", "1")
    coldstart.forget_verified()
    coldstart.restore_or_build(spec, len(state.validators), build,
                               label="t", cache_dir=str(tmp_path))
    assert len(calls) == 2, "opt-out must bypass the snapshot entirely"
    assert stats["coldstart_restores"] == 0


def test_corrupt_snapshot_quarantines_and_rebuilds(scaffold, tmp_path):
    spec, state = scaffold
    root = bytes(state.hash_tree_root())
    calls = []

    def build():
        calls.append(1)
        return state

    coldstart.restore_or_build(spec, len(state.validators), build,
                               label="t", cache_dir=str(tmp_path))
    path = coldstart.snapshot_path(spec, len(state.validators), "t",
                                   str(tmp_path))
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))

    coldstart.forget_verified()
    s = coldstart.restore_or_build(spec, len(state.validators), build,
                                   label="t", cache_dir=str(tmp_path))
    assert len(calls) == 2, "damage must fall back to the literal build"
    assert stats["coldstart_corrupt"] == 1
    assert os.path.exists(path + ".corrupt")
    assert bytes(s.hash_tree_root()) == root

    # the rebuild re-snapshotted: the NEXT cold start restores again
    reset_stats()
    coldstart.forget_verified()
    again = coldstart.restore_or_build(spec, len(state.validators), build,
                                       label="t", cache_dir=str(tmp_path))
    assert stats["coldstart_restores"] == 1
    assert len(calls) == 2
    assert bytes(again.hash_tree_root()) == root


def test_label_and_count_key_distinct_snapshots(scaffold, tmp_path):
    spec, state = scaffold
    p1 = coldstart.snapshot_path(spec, len(state.validators), "a",
                                 str(tmp_path))
    p2 = coldstart.snapshot_path(spec, len(state.validators), "b",
                                 str(tmp_path))
    p3 = coldstart.snapshot_path(spec, len(state.validators) + 1, "a",
                                 str(tmp_path))
    assert len({p1, p2, p3}) == 3
