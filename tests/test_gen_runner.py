"""Generator pipeline tests: runner lifecycle (INCOMPLETE/resume/error
log), part writers, and the reflection bridge over a real test module."""

import yaml

import pytest

from consensus_specs_tpu.gen import gen_runner
from consensus_specs_tpu.gen.gen_from_tests import combine_mods, generate_from_tests
from consensus_specs_tpu.gen.gen_typing import TestCase, TestProvider
from consensus_specs_tpu.gen.snappy import decompress
from consensus_specs_tpu.testing import context


@pytest.fixture(autouse=True)
def _restore_pytest_flag():
    yield
    context.is_pytest = True


def _case(name, fn):
    return TestCase(
        fork_name="phase0", preset_name="minimal", runner_name="demo",
        handler_name="h", suite_name="s", case_name=name, case_fn=fn,
    )


def _provider(cases):
    return TestProvider(prepare=lambda: None, make_cases=lambda: iter(cases))


def _run(tmp_path, cases, extra_args=()):
    gen_runner.run_generator(
        "demo", [_provider(cases)], argv=["-o", str(tmp_path), *extra_args]
    )


def test_writes_all_part_kinds(tmp_path):
    def fn():
        yield "pre", "ssz", b"\x01\x02\x03"
        yield "mapping", "data", {"a": 1}
        yield "bls_setting", "meta", 2

    _run(tmp_path, [_case("case_a", fn)])
    case_dir = tmp_path / "minimal/phase0/demo/h/s/case_a"
    assert decompress((case_dir / "pre.ssz_snappy").read_bytes()) == b"\x01\x02\x03"
    assert yaml.safe_load((case_dir / "mapping.yaml").read_text()) == {"a": 1}
    assert yaml.safe_load((case_dir / "meta.yaml").read_text()) == {"bls_setting": 2}
    assert not (case_dir / "INCOMPLETE").exists()


def test_existing_complete_case_skipped_without_force(tmp_path):
    calls = []

    def fn():
        calls.append(1)
        yield "x", "data", 1

    _run(tmp_path, [_case("case_a", fn)])
    _run(tmp_path, [_case("case_a", fn)])
    assert len(calls) == 1
    _run(tmp_path, [_case("case_a", fn)], extra_args=["-f"])
    assert len(calls) == 2


def test_incomplete_case_regenerated(tmp_path):
    def fn():
        yield "x", "data", 1

    _run(tmp_path, [_case("case_a", fn)])
    case_dir = tmp_path / "minimal/phase0/demo/h/s/case_a"
    (case_dir / "INCOMPLETE").write_text("\n")
    (case_dir / "stale.yaml").write_text("junk\n")
    _run(tmp_path, [_case("case_a", fn)])
    assert not (case_dir / "INCOMPLETE").exists()
    assert not (case_dir / "stale.yaml").exists()
    assert (case_dir / "x.yaml").exists()


def test_error_leaves_incomplete_and_logs(tmp_path):
    def fn():
        yield "x", "data", 1
        raise RuntimeError("boom")

    _run(tmp_path, [_case("case_bad", fn)])
    case_dir = tmp_path / "minimal/phase0/demo/h/s/case_bad"
    assert (case_dir / "INCOMPLETE").exists()
    log = (tmp_path / "testgen_error_log.txt").read_text()
    assert "case_bad" in log and "boom" in log


def test_skipped_test_removes_dir(tmp_path):
    from consensus_specs_tpu.testing.exceptions import SkippedTest

    def fn():
        raise SkippedTest("not applicable")
        yield  # pragma: no cover

    _run(tmp_path, [_case("case_skip", fn)])
    assert not (tmp_path / "minimal/phase0/demo/h/s/case_skip").exists()


def test_preset_filter(tmp_path):
    def fn():
        yield "x", "data", 1

    _run(tmp_path, [_case("case_a", fn)], extra_args=["-l", "mainnet"])
    assert not (tmp_path / "minimal").exists()


def test_generate_from_tests_reflection(tmp_path):
    import tests.spec.phase0.sanity.test_slots as mod

    cases = list(generate_from_tests(
        runner_name="sanity", handler_name="slots", src=mod,
        fork_name="phase0", preset_name="minimal",
    ))
    assert cases, "no cases discovered"
    assert all(c.case_name and not c.case_name.startswith("test_") for c in cases)
    context.is_pytest = False
    try:
        parts = list(cases[0].case_fn())
    finally:
        context.is_pytest = True
    kinds = {kind for (_, kind, _) in parts}
    assert "ssz" in kinds  # pre/post states at minimum


def test_combine_mods():
    a = {"x": "mod_a", "y": "mod_y"}
    b = {"x": "mod_b"}
    merged = combine_mods(a, b)
    assert merged["y"] == "mod_y"
    assert sorted(merged["x"]) == ["mod_a", "mod_b"]
