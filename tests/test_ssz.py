"""SSZ type-system tests: serialization, merkleization (vs the standalone
merkle_minimal oracle), mutation/dirty propagation, copy-on-write.
Behavioral model: ssz/simple-serialize.md in the reference.
"""
import pytest

from consensus_specs_tpu.ssz import (
    Bitlist,
    Bitvector,
    ByteList,
    Bytes32,
    Bytes48,
    Container,
    List,
    Union,
    Vector,
    boolean,
    hash_tree_root,
    serialize,
    uint8,
    uint16,
    uint32,
    uint64,
    uint256,
)
from consensus_specs_tpu.ssz.merkle_minimal import (
    merkleize_chunks,
    mix_in_length,
    mix_in_selector,
)


def chunkify(data: bytes):
    if len(data) % 32:
        data = data + b"\x00" * (32 - len(data) % 32)
    return [data[i : i + 32] for i in range(0, len(data), 32)] or [b"\x00" * 32]


# -- basic types -------------------------------------------------------------


def test_uint_serialization():
    assert serialize(uint8(5)) == b"\x05"
    assert serialize(uint16(0x4566)) == b"\x66\x45"
    assert serialize(uint32(0x01020304)) == b"\x04\x03\x02\x01"
    assert serialize(uint64(2**64 - 1)) == b"\xff" * 8
    assert serialize(uint256(1)) == b"\x01" + b"\x00" * 31


def test_uint_bounds_checked():
    with pytest.raises(ValueError):
        uint8(256)
    with pytest.raises(ValueError):
        uint64(-1)
    with pytest.raises(ValueError):
        uint64(2**64)


def test_uint_checked_arithmetic():
    a = uint64(10)
    assert a + 5 == 15 and type(a + 5) is uint64
    assert a - 10 == 0
    with pytest.raises(ValueError):
        a - 11  # underflow is invalid
    with pytest.raises(ValueError):
        uint64(2**63) * 2  # overflow is invalid
    assert a // 3 == 3
    assert a % 3 == 1
    assert uint8(3) * uint8(4) == 12


def test_uint_hash_tree_root():
    assert hash_tree_root(uint64(7)) == (7).to_bytes(32, "little")
    assert hash_tree_root(boolean(1)) == (1).to_bytes(32, "little")


# -- byte vectors/lists ------------------------------------------------------


def test_bytes32_root_is_identity():
    b = Bytes32(b"\x01" * 32)
    assert hash_tree_root(b) == b"\x01" * 32
    assert serialize(b) == b"\x01" * 32


def test_bytes48_root():
    b = Bytes48(b"\xab" * 48)
    assert hash_tree_root(b) == merkleize_chunks(chunkify(b"\xab" * 48))


def test_bytelist_root_mixes_length():
    BL = ByteList[2**5]
    b = BL(b"hello")
    expected = mix_in_length(merkleize_chunks(chunkify(b"hello"), limit=1), 5)
    assert hash_tree_root(b) == expected
    assert serialize(b) == b"hello"


def test_bytelist_empty():
    BL = ByteList[64]
    assert hash_tree_root(BL(b"")) == mix_in_length(merkleize_chunks([], limit=2), 0)


# -- bitvector / bitlist -----------------------------------------------------


def test_bitvector_serialization():
    bv = Bitvector[10](1, 0, 1, 0, 1, 0, 1, 0, 1, 1)
    # bits little-endian within bytes: 0b01010101, 0b00000011
    assert serialize(bv) == bytes([0b01010101, 0b00000011])
    assert hash_tree_root(bv) == merkleize_chunks(chunkify(serialize(bv)))


def test_bitlist_serialization_delimiter():
    bl = Bitlist[8](1, 1, 0)
    # 3 bits -> 0b011 plus delimiter at position 3 -> 0b1011
    assert serialize(bl) == bytes([0b1011])
    empty = Bitlist[8]()
    assert serialize(empty) == bytes([0b1])


def test_bitlist_root():
    bl = Bitlist[2048](*([1] * 10))
    contents = merkleize_chunks(chunkify(bytes([0xFF, 0x03])), limit=(2048 + 255) // 256)
    assert hash_tree_root(bl) == mix_in_length(contents, 10)


def test_bitlist_decode_roundtrip():
    BL = Bitlist[16]
    for bits in ([], [1], [0, 1, 1, 0, 1, 0, 0, 1], [1] * 16):
        bl = BL(*bits)
        assert BL.decode_bytes(serialize(bl)) == bl


# -- vectors / lists ---------------------------------------------------------


def test_vector_uint64_root():
    v = Vector[uint64, 4](1, 2, 3, 4)
    data = b"".join(int(x).to_bytes(8, "little") for x in (1, 2, 3, 4))
    assert serialize(v) == data
    assert hash_tree_root(v) == merkleize_chunks(chunkify(data))


def test_vector_default_is_zero():
    v = Vector[uint64, 8192]()
    assert hash_tree_root(v) == merkleize_chunks([], limit=(8192 * 8) // 32)


def test_list_uint64_root():
    L = List[uint64, 1024]
    l = L(5, 6, 7)
    data = b"".join(int(x).to_bytes(8, "little") for x in (5, 6, 7))
    contents = merkleize_chunks(chunkify(data), limit=(1024 * 8 + 31) // 32)
    assert hash_tree_root(l) == mix_in_length(contents, 3)
    assert serialize(l) == data


def test_list_append_updates_root():
    L = List[uint64, 64]
    l = L()
    roots = set()
    for i in range(5):
        l.append(i)
        roots.add(bytes(hash_tree_root(l)))
    assert len(roots) == 5
    fresh = L(0, 1, 2, 3, 4)
    assert hash_tree_root(l) == hash_tree_root(fresh)


def test_large_packed_list_setitem_incremental():
    L = List[uint64, 2**40]
    l = L(list(range(1000)))
    r1 = hash_tree_root(l)
    l[500] = 123456
    vals = list(range(1000))
    vals[500] = 123456
    assert hash_tree_root(l) == hash_tree_root(L(vals))
    assert hash_tree_root(l) != r1


# -- containers --------------------------------------------------------------


class Checkpoint(Container):
    epoch: uint64
    root: Bytes32


class Inner(Container):
    a: uint64
    b: Bytes32


class Outer(Container):
    x: uint64
    inner: Inner
    items: List[uint64, 32]
    flags: Bitvector[4]


def test_container_root_is_merkle_of_field_roots():
    c = Checkpoint(epoch=3, root=Bytes32(b"\x05" * 32))
    expected = merkleize_chunks(
        [(3).to_bytes(32, "little"), b"\x05" * 32]
    )
    assert hash_tree_root(c) == expected


def test_container_serialization():
    c = Checkpoint(epoch=3, root=Bytes32(b"\x05" * 32))
    assert serialize(c) == (3).to_bytes(8, "little") + b"\x05" * 32
    assert Checkpoint.decode_bytes(serialize(c)) == c


def test_container_variable_field_serialization():
    o = Outer(x=7, items=List[uint64, 32](1, 2))
    data = serialize(o)
    o2 = Outer.decode_bytes(data)
    assert o2 == o
    assert list(o2.items) == [1, 2]


def test_nested_mutation_propagates():
    o = Outer()
    r0 = hash_tree_root(o)
    o.inner.a = uint64(9)
    r1 = hash_tree_root(o)
    assert r0 != r1
    fresh = Outer(inner=Inner(a=9))
    assert r1 == hash_tree_root(fresh)
    # mutate deeper after a flush
    o.inner.b = Bytes32(b"\x01" * 32)
    assert hash_tree_root(o) == hash_tree_root(Outer(inner=Inner(a=9, b=Bytes32(b"\x01" * 32))))


def test_list_element_mutation_propagates():
    class Rec(Container):
        v: uint64

    class Holder(Container):
        recs: List[Rec, 8]

    h = Holder(recs=List[Rec, 8](Rec(v=1), Rec(v=2)))
    h.recs[1].v = uint64(5)
    expect = Holder(recs=List[Rec, 8](Rec(v=1), Rec(v=5)))
    assert hash_tree_root(h) == hash_tree_root(expect)


def test_copy_is_independent():
    o = Outer(x=1)
    c = o.copy()
    o.x = uint64(2)
    assert c.x == 1 and o.x == 2
    assert hash_tree_root(c) != hash_tree_root(o)


def test_assignment_copies_value():
    o = Outer()
    inner = Inner(a=4)
    o.inner = inner
    inner.a = uint64(99)  # must not leak into o
    assert o.inner.a == 4


def test_bitvector_field_mutation():
    o = Outer()
    o.flags[2] = True
    assert hash_tree_root(o) == hash_tree_root(Outer(flags=Bitvector[4](0, 0, 1, 0)))


# -- union -------------------------------------------------------------------


def test_union():
    U = Union[None, uint64, Bytes32]
    u = U(1, uint64(7))
    assert serialize(u) == b"\x01" + (7).to_bytes(8, "little")
    expected = mix_in_selector((7).to_bytes(32, "little"), 1)
    assert hash_tree_root(u) == expected
    u0 = U(0, None)
    assert serialize(u0) == b"\x00"
    assert U.decode_bytes(serialize(u)) == u


# -- incremental hashing sanity ---------------------------------------------


def test_incremental_matches_bulk_on_registry_like_update():
    class Validator(Container):
        pubkey: Bytes48
        balance: uint64

    VL = List[Validator, 2**40]
    n = 300
    vals = [Validator(pubkey=Bytes48(bytes([i % 256]) * 48), balance=32) for i in range(n)]
    l = VL(vals)
    _ = hash_tree_root(l)
    l[37].balance = uint64(31)
    l.append(Validator(pubkey=Bytes48(b"\xaa" * 48), balance=1))
    vals2 = [Validator(pubkey=Bytes48(bytes([i % 256]) * 48), balance=32) for i in range(n)]
    vals2[37].balance = uint64(31)
    vals2.append(Validator(pubkey=Bytes48(b"\xaa" * 48), balance=1))
    assert hash_tree_root(l) == hash_tree_root(VL(vals2))


# -- regression tests from review findings -----------------------------------


def test_vector_of_composite_default():
    v = Vector[Checkpoint, 4]()
    assert v[0] == Checkpoint()
    expected = merkleize_chunks([bytes(hash_tree_root(Checkpoint()))] * 4)
    assert bytes(hash_tree_root(v)) == expected


def test_union_as_container_field():
    class C(Container):
        u: Union[None, uint64]

    c = C()
    assert c.u.selector == 0
    c.u = Union[None, uint64](1, uint64(7))
    assert c.u.value == 7
    assert C.decode_bytes(bytes(serialize(c))) == c


def test_wrong_layout_container_store_rejected():
    class Inner(Container):
        a: uint64

    class Other(Container):
        b: Bytes32

    class Outer(Container):
        inner: Inner

    o = Outer()
    with pytest.raises(TypeError):
        o.inner = Other()


def test_crossfork_same_layout_container_store_allowed():
    # fork-upgrade functions assign containers across fork namespaces;
    # layout-identical (names+types) classes must interoperate
    class CheckpointV2(Container):
        epoch: uint64
        root: Bytes32

    class Holder(Container):
        cp: Checkpoint

    h = Holder()
    h.cp = CheckpointV2(epoch=9, root=Bytes32(b"\x01" * 32))
    assert h.cp.epoch == 9


def test_garbage_decode_rejected():
    class VarC(Container):
        a: List[uint64, 4]
        b: Bytes32

    with pytest.raises(ValueError):
        VarC.decode_bytes(b"\xff" * 40)


def test_empty_bytevector_decode_rejected():
    with pytest.raises(ValueError):
        Bytes32.decode_bytes(b"")


def test_merkleize_over_limit_raises():
    with pytest.raises(AssertionError):
        merkleize_chunks([b"\x00" * 32] * 3, limit=2)


def test_composite_list_pop_restores_zero_chunk():
    class Rec(Container):
        v: uint64

    L = List[Rec, 16]
    l = L(Rec(v=1), Rec(v=2))
    l.pop()
    assert hash_tree_root(l) == hash_tree_root(L(Rec(v=1)))
    assert len(l) == 1


# --- multiproofs (ssz/merkle-proofs.md:249-326) -----------------------------


def test_multiproof_of_beacon_state_fields():
    from consensus_specs_tpu.specs.builder import get_spec
    from consensus_specs_tpu.ssz.gindex import (
        build_multiproof,
        get_generalized_index,
        get_subtree_at_gindex,
        verify_merkle_multiproof,
    )
    from consensus_specs_tpu.ssz.node import merkle_root

    spec = get_spec("altair", "minimal")
    state = spec.BeaconState()
    state.slot = 77
    state.genesis_time = 123456
    state.finalized_checkpoint.epoch = 9

    T = spec.BeaconState
    gindices = [
        get_generalized_index(T, "slot"),
        get_generalized_index(T, "genesis_time"),
        get_generalized_index(T, "finalized_checkpoint", "epoch"),
    ]
    backing = state.get_backing()
    leaves = [merkle_root(get_subtree_at_gindex(backing, g)) for g in gindices]
    proof = build_multiproof(backing, gindices)
    root = state.hash_tree_root()
    assert verify_merkle_multiproof(leaves, proof, gindices, root)
    # tampered leaf fails
    bad = [leaves[0][:-1] + b"\xff"] + leaves[1:]
    assert not verify_merkle_multiproof(bad, proof, gindices, root)
    # wrong order of indices fails (leaves no longer line up)
    assert not verify_merkle_multiproof(
        leaves, proof, list(reversed(gindices)), root)


def test_multiproof_of_single_leaf_matches_branch_proof():
    from consensus_specs_tpu.specs.builder import get_spec
    from consensus_specs_tpu.ssz.gindex import (
        build_multiproof,
        build_proof,
        get_generalized_index,
        get_helper_indices,
        verify_merkle_multiproof,
    )

    spec = get_spec("altair", "minimal")
    state = spec.BeaconState()
    gindex = int(spec.NEXT_SYNC_COMMITTEE_INDEX)
    backing = state.get_backing()
    single = build_proof(backing, gindex)
    multi = build_multiproof(backing, [gindex])
    # a one-leaf multiproof is the branch proof in descending-helper order
    assert sorted(single) == sorted(multi)
    assert len(get_helper_indices([gindex])) == len(single)
    leaf = state.next_sync_committee.hash_tree_root()
    assert verify_merkle_multiproof([leaf], multi, [gindex], state.hash_tree_root())


def test_multiproof_shares_helpers_between_nearby_leaves():
    from consensus_specs_tpu.ssz.gindex import get_helper_indices

    # two sibling leaves need NO helper between them at their own level
    helpers_pair = get_helper_indices([8, 9])
    helpers_single = get_helper_indices([8])
    assert len(helpers_pair) < 2 * len(helpers_single)
    assert 9 not in helpers_pair
