"""Generate→consume round trip: the vector generators write a tree per
the format contract; the consumer replays every case against a fresh
spec build and must reproduce byte-identical results.  This pins BOTH
directions of the L5/L6 layer the way the reference's client ecosystem
does (generator output on one side, client test runner on the other)."""
from pathlib import Path

import pytest

from consensus_specs_tpu.gen import consumer
from consensus_specs_tpu.gen.consumer import VectorFailure, consume_tree
from consensus_specs_tpu.testing import context


@pytest.fixture(autouse=True)
def _restore_pytest_flag():
    yield
    context.is_pytest = True


def _generate(tmp_path, runner_main, argv_extra=()):
    runner_main(argv=["-o", str(tmp_path), "-l", "minimal", *argv_extra])


def test_operations_roundtrip(tmp_path):
    from consensus_specs_tpu.gen.runners.operations import main
    _generate(tmp_path, main)
    stats = consume_tree(tmp_path, preset="minimal", runners={"operations"})
    assert stats["pass"] > 50
    assert stats["skip"] == 0


def test_sanity_and_epoch_processing_roundtrip(tmp_path):
    from consensus_specs_tpu.gen.runners.epoch_processing import main as ep
    from consensus_specs_tpu.gen.runners.sanity import main as sanity
    _generate(tmp_path, sanity)
    _generate(tmp_path, ep)
    stats = consume_tree(tmp_path, preset="minimal",
                         runners={"sanity", "epoch_processing"})
    assert stats["pass"] > 40


def test_shuffling_and_ssz_static_roundtrip(tmp_path):
    from consensus_specs_tpu.gen.runners.shuffling import main as shuffling
    from consensus_specs_tpu.gen.runners.ssz_static import main as ssz_static
    _generate(tmp_path, shuffling)
    _generate(tmp_path, ssz_static)
    stats = consume_tree(tmp_path, preset="minimal",
                         runners={"shuffling", "ssz_static"})
    assert stats["pass"] > 50


def test_forks_and_genesis_roundtrip(tmp_path):
    from consensus_specs_tpu.gen.runners.forks import main as forks
    from consensus_specs_tpu.gen.runners.genesis import main as genesis
    _generate(tmp_path, forks)
    _generate(tmp_path, genesis)
    stats = consume_tree(tmp_path, preset="minimal",
                         runners={"fork", "forks", "genesis"})
    assert stats["pass"] > 3


def test_consumer_detects_corruption(tmp_path):
    """Flipping a byte in a post state must fail the replay — the
    consumer is only meaningful if divergence is actually detected."""
    from consensus_specs_tpu.gen.runners.shuffling import main as shuffling
    import yaml

    _generate(tmp_path, shuffling)
    corrupted = None
    for mapping in Path(tmp_path).rglob("mapping.yaml"):
        data = yaml.safe_load(mapping.read_text())
        if data["mapping"]:
            data["mapping"][0] = int(data["mapping"][0]) + 1
            mapping.write_text(yaml.safe_dump(data))
            corrupted = mapping
            break
    assert corrupted is not None
    with pytest.raises(VectorFailure):
        consume_tree(tmp_path, preset="minimal", runners={"shuffling"})


def test_incomplete_cases_skipped(tmp_path):
    from consensus_specs_tpu.gen.runners.shuffling import main as shuffling
    _generate(tmp_path, shuffling)
    case = next(p for p in Path(tmp_path).rglob("mapping.yaml")).parent
    (case / "INCOMPLETE").write_text("")
    stats = consume_tree(tmp_path, preset="minimal", runners={"shuffling"})
    assert stats["skip"] == 1


def test_cli_entrypoint(tmp_path, capsys):
    from consensus_specs_tpu.gen.runners.shuffling import main as shuffling
    _generate(tmp_path, shuffling)
    consumer.main([str(tmp_path), "--preset", "minimal", "--runner", "shuffling"])
    out = capsys.readouterr().out
    assert "passed" in out


def test_bls_and_transition_roundtrip(tmp_path):
    from consensus_specs_tpu.gen.runners.bls import main as bls_main
    from consensus_specs_tpu.gen.runners.transition import main as transition

    bls_main(argv=["-o", str(tmp_path)])
    _generate(tmp_path, transition)
    stats = consume_tree(tmp_path, runners={"bls", "transition"})
    assert stats["pass"] > 30
    assert stats["skip"] == 0


def test_rewards_roundtrip(tmp_path):
    from consensus_specs_tpu.gen.runners.rewards import main as rewards
    _generate(tmp_path, rewards)
    stats = consume_tree(tmp_path, preset="minimal", runners={"rewards"})
    # phase0 + altair/bellatrix/capella flag layouts both replayed
    assert stats["pass"] > 20
    assert stats["skip"] == 0


def test_config_override_vectors_roundtrip(tmp_path):
    """Cases generated under config overrides record config.yaml; the
    consumer must rebuild the spec with it — and the recorded config must
    be load-bearing (deleting it makes the replay diverge)."""
    from consensus_specs_tpu.gen.runners.sanity import main as sanity
    _generate(tmp_path, sanity)

    override_cases = [
        p.parent for p in Path(tmp_path).rglob("config.yaml")
    ]
    assert override_cases, "no config-override vectors generated"
    stats = consume_tree(tmp_path, preset="minimal", runners={"sanity"})
    assert stats["pass"] > 0

    # strip the recorded config: the replay must now fail on those cases
    for case in override_cases:
        (case / "config.yaml").unlink()
    with pytest.raises(VectorFailure):
        consume_tree(tmp_path, preset="minimal", runners={"sanity"})


def test_fork_choice_roundtrip(tmp_path):
    """Step-scripted fork-choice vectors: anchors, tick/block/attestation/
    attester_slashing steps and store checks replayed by the consumer."""
    from consensus_specs_tpu.gen.runners.fork_choice import main as fork_choice
    _generate(tmp_path, fork_choice)
    stats = consume_tree(tmp_path, preset="minimal", runners={"fork_choice"})
    assert stats["pass"] >= 10
    assert stats["skip"] == 0

    # corrupt a recorded head check: the replay must diverge
    import yaml
    corrupted = False
    for steps_file in Path(tmp_path).rglob("steps.yaml"):
        steps = yaml.safe_load(steps_file.read_text())
        for step in steps:
            if "checks" in step and "head" in step["checks"]:
                step["checks"]["head"]["slot"] = \
                    int(step["checks"]["head"]["slot"]) + 1
                steps_file.write_text(yaml.safe_dump(steps))
                corrupted = True
                break
        if corrupted:
            break
    assert corrupted
    with pytest.raises(VectorFailure):
        consume_tree(tmp_path, preset="minimal", runners={"fork_choice"})


def test_merkle_roundtrip(tmp_path):
    """Light-client single-proof vectors: state + proof.yaml emitted by the
    merkle runner, branch re-verified AND re-derived by the consumer."""
    from consensus_specs_tpu.gen.runners.merkle import main as merkle
    _generate(tmp_path, merkle)
    stats = consume_tree(tmp_path, preset="minimal", runners={"merkle"})
    assert stats["pass"] >= 4  # 2 handler tests x {altair, bellatrix}
    assert stats["skip"] == 0

    # corrupt one branch node: the replay must reject the proof
    import yaml
    proof_file = next(Path(tmp_path).rglob("proof.yaml"))
    proof = yaml.safe_load(proof_file.read_text())
    proof["branch"][0] = "0x" + "ab" * 32
    proof_file.write_text(yaml.safe_dump(proof))
    with pytest.raises(VectorFailure):
        consume_tree(tmp_path, preset="minimal", runners={"merkle"})
