"""Sharded epoch step vs single-device kernel (8-device virtual CPU mesh)."""
import hashlib

import numpy as np

from consensus_specs_tpu.ops.epoch_jax import DeltaInputs, attestation_deltas
from consensus_specs_tpu.parallel import build_mesh
from consensus_specs_tpu.parallel.epoch_sharded import (
    make_sharded_epoch_step,
    shard_delta_inputs,
)


def _random_inputs(n, seed=7):
    rng = np.random.default_rng(seed)
    eff = (rng.integers(16, 33, n) * 10**9).astype(np.int64)
    eligible = rng.random(n) < 0.95
    src = (rng.random(n) < 0.8) & eligible
    tgt = src & (rng.random(n) < 0.9)
    head = tgt & (rng.random(n) < 0.9)
    delay = np.where(src, rng.integers(1, 9, n), 1).astype(np.int64)
    proposer = rng.integers(0, n, n).astype(np.int64)
    total = int(np.sum(np.where(eligible, eff, 0), dtype=np.uint64))
    return DeltaInputs(
        effective_balance=eff,
        eligible=eligible,
        source_part=src,
        target_part=tgt,
        head_part=head,
        incl_delay=delay,
        incl_proposer=proposer,
        total_balance=total,
        sqrt_total=int(np.sqrt(total)),
        finality_delay=2,
        base_reward_factor=64,
        base_rewards_per_epoch=4,
        proposer_reward_quotient=8,
        inactivity_penalty_quotient=2**26,
        min_epochs_to_inactivity_penalty=4,
        effective_balance_increment=10**9,
    )


def test_sharded_step_matches_single_device():
    n = 1024  # multiple of 8*8 so no padding ambiguity
    inp = _random_inputs(n)
    balances = (np.random.default_rng(3).integers(16, 33, n) * 10**9).astype(np.int64)

    rewards, penalties = attestation_deltas(inp)
    expected = balances + rewards
    expected = np.where(penalties > expected, 0, expected - penalties)

    mesh = build_mesh(8)
    step = make_sharded_epoch_step(mesh)
    args, n_orig = shard_delta_inputs(mesh, inp, balances)
    new_balances, digests = step(*args)

    assert np.array_equal(np.asarray(new_balances)[:n_orig], expected)

    # digests: each 64-byte block = 8 consecutive uint64 balances (LE)
    nb = np.asarray(new_balances)
    raw = nb.astype("<u8").tobytes()
    expected_digest0 = hashlib.sha256(raw[:64]).digest()
    got = np.asarray(digests)[:8].astype(">u4").tobytes()
    assert got == expected_digest0


def test_sharded_step_leak_mode():
    n = 512
    inp = _random_inputs(n, seed=11)._replace(finality_delay=9)
    balances = np.full(n, 32 * 10**9, dtype=np.int64)

    rewards, penalties = attestation_deltas(inp)
    expected = balances + rewards
    expected = np.where(penalties > expected, 0, expected - penalties)

    mesh = build_mesh(8)
    step = make_sharded_epoch_step(mesh)
    args, n_orig = shard_delta_inputs(mesh, inp, balances)
    new_balances, _ = step(*args)
    assert np.array_equal(np.asarray(new_balances)[:n_orig], expected)
