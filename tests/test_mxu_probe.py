"""Correctness pin for the MXU int8 limb-mul probe (ops/bls_jax/mxu_probe):
digit codecs, single multiplies, and chained multiplies against python
ints.  The hardware race itself lives in tools/limb_probe_bench.py --mxu."""
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from consensus_specs_tpu.ops.bls_jax import mxu_probe as mp  # noqa: E402
from consensus_specs_tpu.ops.bls_jax.limbs import P_INT  # noqa: E402

rng = random.Random(777)


def test_digit_codec_roundtrip():
    for _ in range(20):
        x = rng.randrange(P_INT)
        assert mp.digits_to_int(mp.int_to_digits(x)) == x


def test_single_muls_match_python():
    cases = [(1, 1), (P_INT - 1, P_INT - 1), (2, P_INT - 1),
             (0, 12345), (1 << 380, (1 << 379) + 17)]
    cases += [(rng.randrange(P_INT), rng.randrange(P_INT)) for _ in range(10)]
    for x, y in cases:
        assert mp.mxu_mul_ints(x, y) == x * y % P_INT


def test_batched_muls_match_python():
    n = 64
    xs = [rng.randrange(P_INT) for _ in range(n)]
    ys = [rng.randrange(P_INT) for _ in range(n)]
    a = jnp.asarray(np.stack([mp.host_to_mont(x) for x in xs]), dtype=jnp.int8)
    b = jnp.asarray(np.stack([mp.host_to_mont(y) for y in ys]), dtype=jnp.int8)
    out = np.asarray(mp._jit_mxu_mul(a, b))
    for i in range(n):
        assert mp.host_from_mont(out[i]) % P_INT == xs[i] * ys[i] % P_INT


def test_chained_muls_stay_canonical():
    """Chaining (the Miller-loop access pattern): outputs feed back as
    inputs; digits must stay int8-canonical and values correct."""
    x = rng.randrange(P_INT)
    a = jnp.asarray(mp.host_to_mont(x)[None], dtype=jnp.int8)
    acc = a
    expect = x
    for _ in range(6):
        acc = mp._jit_mxu_mul(acc, a)
        expect = expect * x % P_INT
        arr = np.asarray(acc)
        assert arr.max() <= mp.MASK, "digits left canonical range"
        assert mp.host_from_mont(arr[0]) % P_INT == expect
