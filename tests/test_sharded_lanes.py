"""Mesh-sharded scale-out seams: KZG MSM lane split and BLS pairing-batch
split, bit-exact vs their host oracles (parallel/bls_sharded.py,
ops/kzg_jax.sharded_msm; executed at driver time by __graft_entry__'s
multichip dryrun).  Runs on the 8-virtual-device CPU mesh the conftest
pins."""
import jax

from consensus_specs_tpu.parallel import build_mesh


def _mesh(n=4):
    return build_mesh(n, devices=jax.devices()[:n])


def test_sharded_kzg_msm_matches_host():
    from consensus_specs_tpu.crypto import fr, kzg
    from consensus_specs_tpu.ops.kzg_jax import sharded_msm

    mesh = _mesh(4)
    pts = kzg.setup_monomial(8)
    scalars = [1, 0, fr.R - 1, 12345, 7, 2**200 % fr.R, 3, fr.R - 2]
    assert sharded_msm(mesh, pts, scalars) == kzg.g1_lincomb(pts, scalars)


def test_sharded_batch_scalar_mul_matches_pointwise():
    from consensus_specs_tpu.crypto import fr, kzg
    from consensus_specs_tpu.ops.kzg_jax import sharded_batch_scalar_mul

    mesh = _mesh(4)
    pts = kzg.setup_monomial(4)
    scalars = [5, 0, fr.R - 1, 99]
    got = sharded_batch_scalar_mul(mesh, pts, scalars)
    for p, s, o in zip(pts, scalars, got):
        assert o == p.mul(s % fr.R)


def test_sharded_bls_batch_verify_matches_oracle():
    from consensus_specs_tpu.crypto.bls import ciphersuite as cs
    from consensus_specs_tpu.parallel.bls_sharded import (
        sharded_batch_fast_aggregate_verify,
    )

    mesh = _mesh(4)
    pk_lists, msgs, sigs = [], [], []
    for b in range(4):
        sk1, sk2 = 300 + 2 * b, 301 + 2 * b
        msg = bytes([0x40 + b]) * 32
        pk_lists.append([cs.SkToPk(sk1), cs.SkToPk(sk2)])
        sig = cs.Aggregate([cs.Sign(sk1, msg), cs.Sign(sk2, msg)])
        if b == 2:
            msg = b"\xAA" * 32  # wrong message: must fail
        msgs.append(msg)
        sigs.append(sig)
    got = sharded_batch_fast_aggregate_verify(mesh, pk_lists, msgs, sigs)
    assert got == [True, True, False, True]
    assert all(isinstance(v, bool) for v in got)


def test_sharded_bls_rejects_malformed_and_empty():
    from consensus_specs_tpu.crypto.bls import ciphersuite as cs
    from consensus_specs_tpu.parallel.bls_sharded import (
        sharded_batch_fast_aggregate_verify,
    )

    mesh = _mesh(2)
    msg = b"\x01" * 32
    pk = cs.SkToPk(11)
    sig = cs.Sign(11, msg)
    got = sharded_batch_fast_aggregate_verify(
        mesh,
        [[], [b"\x00" * 48], [pk], [pk]],
        [msg, msg, msg, msg],
        [sig, sig, sig, b"\x01" * 96],
    )
    assert got[0] is False          # empty pubkey list
    assert got[1] is False          # malformed pubkey
    assert got[2] is True           # valid
    assert got[3] is False          # malformed signature


def test_sharded_bls_pair_count_derived_not_hardcoded(monkeypatch):
    """ADVICE r5 #3: the per-item pair count K is derived from the
    marshalled pairs (K = len(padded[0])), with a clear assert on ragged
    batches — a marshaller change can no longer silently disagree with a
    hardcoded K=2."""
    from consensus_specs_tpu.crypto.bls import ciphersuite as cs
    from consensus_specs_tpu.ops import bls_jax
    from consensus_specs_tpu.parallel.bls_sharded import (
        sharded_batch_fast_aggregate_verify,
    )

    mesh = _mesh(2)
    msg = b"\x07" * 32
    pk, sig = cs.SkToPk(21), cs.Sign(21, msg)

    # a marshaller that returns a ragged batch must trip the uniformity
    # assert, not shape-garble the device program
    real = bls_jax.marshal_fast_aggregate_items

    def ragged(pk_lists, msgs, sigs):
        results, todo = real(pk_lists, msgs, sigs)
        b, pairs = todo[0]
        todo[0] = (b, pairs + [pairs[0]])  # 3 pairs vs 2 elsewhere
        return results, todo

    monkeypatch.setattr(bls_jax, "marshal_fast_aggregate_items", ragged)
    import pytest as _pytest
    with _pytest.raises(AssertionError, match="uniform pair count"):
        sharded_batch_fast_aggregate_verify(
            mesh, [[pk], [pk]], [msg, msg], [sig, sig])
