"""Mesh-sharded scale-out seams: KZG MSM lane split and BLS pairing-batch
split, bit-exact vs their host oracles (parallel/bls_sharded.py,
ops/kzg_jax.sharded_msm; executed at driver time by __graft_entry__'s
multichip dryrun).  Runs on the 8-virtual-device CPU mesh the conftest
pins."""
import jax

from consensus_specs_tpu.parallel import build_mesh


def _mesh(n=4):
    return build_mesh(n, devices=jax.devices()[:n])


def test_sharded_kzg_msm_matches_host():
    from consensus_specs_tpu.crypto import fr, kzg
    from consensus_specs_tpu.ops.kzg_jax import sharded_msm

    mesh = _mesh(4)
    pts = kzg.setup_monomial(8)
    scalars = [1, 0, fr.R - 1, 12345, 7, 2**200 % fr.R, 3, fr.R - 2]
    assert sharded_msm(mesh, pts, scalars) == kzg.g1_lincomb(pts, scalars)


def test_sharded_batch_scalar_mul_matches_pointwise():
    from consensus_specs_tpu.crypto import fr, kzg
    from consensus_specs_tpu.ops.kzg_jax import sharded_batch_scalar_mul

    mesh = _mesh(4)
    pts = kzg.setup_monomial(4)
    scalars = [5, 0, fr.R - 1, 99]
    got = sharded_batch_scalar_mul(mesh, pts, scalars)
    for p, s, o in zip(pts, scalars, got):
        assert o == p.mul(s % fr.R)


def test_sharded_bls_batch_verify_matches_oracle():
    from consensus_specs_tpu.crypto.bls import ciphersuite as cs
    from consensus_specs_tpu.parallel.bls_sharded import (
        sharded_batch_fast_aggregate_verify,
    )

    mesh = _mesh(4)
    pk_lists, msgs, sigs = [], [], []
    for b in range(4):
        sk1, sk2 = 300 + 2 * b, 301 + 2 * b
        msg = bytes([0x40 + b]) * 32
        pk_lists.append([cs.SkToPk(sk1), cs.SkToPk(sk2)])
        sig = cs.Aggregate([cs.Sign(sk1, msg), cs.Sign(sk2, msg)])
        if b == 2:
            msg = b"\xAA" * 32  # wrong message: must fail
        msgs.append(msg)
        sigs.append(sig)
    got = sharded_batch_fast_aggregate_verify(mesh, pk_lists, msgs, sigs)
    assert got == [True, True, False, True]
    assert all(isinstance(v, bool) for v in got)


def test_sharded_bls_rejects_malformed_and_empty():
    from consensus_specs_tpu.crypto.bls import ciphersuite as cs
    from consensus_specs_tpu.parallel.bls_sharded import (
        sharded_batch_fast_aggregate_verify,
    )

    mesh = _mesh(2)
    msg = b"\x01" * 32
    pk = cs.SkToPk(11)
    sig = cs.Sign(11, msg)
    got = sharded_batch_fast_aggregate_verify(
        mesh,
        [[], [b"\x00" * 48], [pk], [pk]],
        [msg, msg, msg, msg],
        [sig, sig, sig, b"\x01" * 96],
    )
    assert got[0] is False          # empty pubkey list
    assert got[1] is False          # malformed pubkey
    assert got[2] is True           # valid
    assert got[3] is False          # malformed signature
