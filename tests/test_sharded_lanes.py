"""Mesh-sharded scale-out seams: KZG MSM lane split and BLS pairing-batch
split, bit-exact vs their host oracles (parallel/bls_sharded.py,
ops/kzg_jax.sharded_msm; executed at driver time by __graft_entry__'s
multichip dryrun).  Runs on the 8-virtual-device CPU mesh the conftest
pins."""
import jax

from consensus_specs_tpu.parallel import build_mesh


def _mesh(n=4):
    return build_mesh(n, devices=jax.devices()[:n])


def test_sharded_kzg_msm_matches_host():
    from consensus_specs_tpu.crypto import fr, kzg
    from consensus_specs_tpu.ops.kzg_jax import sharded_msm

    mesh = _mesh(4)
    pts = kzg.setup_monomial(8)
    scalars = [1, 0, fr.R - 1, 12345, 7, 2**200 % fr.R, 3, fr.R - 2]
    assert sharded_msm(mesh, pts, scalars) == kzg.g1_lincomb(pts, scalars)


def test_sharded_batch_scalar_mul_matches_pointwise():
    from consensus_specs_tpu.crypto import fr, kzg
    from consensus_specs_tpu.ops.kzg_jax import sharded_batch_scalar_mul

    mesh = _mesh(4)
    pts = kzg.setup_monomial(4)
    scalars = [5, 0, fr.R - 1, 99]
    got = sharded_batch_scalar_mul(mesh, pts, scalars)
    for p, s, o in zip(pts, scalars, got):
        assert o == p.mul(s % fr.R)


def test_sharded_bls_batch_verify_matches_oracle():
    from consensus_specs_tpu.crypto.bls import ciphersuite as cs
    from consensus_specs_tpu.parallel.bls_sharded import (
        sharded_batch_fast_aggregate_verify,
    )

    mesh = _mesh(4)
    pk_lists, msgs, sigs = [], [], []
    for b in range(4):
        sk1, sk2 = 300 + 2 * b, 301 + 2 * b
        msg = bytes([0x40 + b]) * 32
        pk_lists.append([cs.SkToPk(sk1), cs.SkToPk(sk2)])
        sig = cs.Aggregate([cs.Sign(sk1, msg), cs.Sign(sk2, msg)])
        if b == 2:
            msg = b"\xAA" * 32  # wrong message: must fail
        msgs.append(msg)
        sigs.append(sig)
    got = sharded_batch_fast_aggregate_verify(mesh, pk_lists, msgs, sigs)
    assert got == [True, True, False, True]
    assert all(isinstance(v, bool) for v in got)


def test_sharded_bls_rejects_malformed_and_empty():
    from consensus_specs_tpu.crypto.bls import ciphersuite as cs
    from consensus_specs_tpu.parallel.bls_sharded import (
        sharded_batch_fast_aggregate_verify,
    )

    mesh = _mesh(2)
    msg = b"\x01" * 32
    pk = cs.SkToPk(11)
    sig = cs.Sign(11, msg)
    got = sharded_batch_fast_aggregate_verify(
        mesh,
        [[], [b"\x00" * 48], [pk], [pk]],
        [msg, msg, msg, msg],
        [sig, sig, sig, b"\x01" * 96],
    )
    assert got[0] is False          # empty pubkey list
    assert got[1] is False          # malformed pubkey
    assert got[2] is True           # valid
    assert got[3] is False          # malformed signature


def _lane_fixture(n_valid, first_sk=500):
    """Lanes of ONE pairing product in the folded verifier's shape: per
    valid (pk, msg, sig) triple an e(pk, H(msg)) lane and an e(-G1, sig)
    lane — the full product is the identity iff every triple verifies."""
    from consensus_specs_tpu.crypto.bls import ciphersuite as cs
    from consensus_specs_tpu.crypto.bls.curve import (
        pubkey_to_point,
        signature_to_point,
    )
    from consensus_specs_tpu.ops.bls_jax import _NEG_G1_GEN, _hash_to_g2_point

    pairs = []
    for i in range(n_valid):
        sk = first_sk + i
        msg = bytes([0x60 + i]) * 32
        pairs.append((pubkey_to_point(cs.SkToPk(sk)), _hash_to_g2_point(msg)))
        pairs.append((_NEG_G1_GEN, signature_to_point(cs.Sign(sk, msg))))
    return pairs


def test_sharded_pairing_lanes_match_oracle():
    """ISSUE 7: the lane-chunk path — ONE pairing product split into
    per-device chunks (partial Fp12 Miller products, fixed merge order,
    one shared final exp) — agrees with the host pairing oracle on both
    verdicts, including a lane count that needs padding."""
    from consensus_specs_tpu.crypto.bls.curve import g1_generator
    from consensus_specs_tpu.crypto.bls.pairing import pairings_are_identity
    from consensus_specs_tpu.parallel.bls_sharded import (
        sharded_pairing_lanes_check,
    )

    mesh = _mesh(4)
    pairs = _lane_fixture(3)  # 6 lanes over 4 chunks: 2 self-canceling pads
    assert pairings_are_identity(pairs) is True
    assert sharded_pairing_lanes_check(mesh, pairs) is True
    # tamper one lane: the whole product must fail, exactly as on host
    bad = list(pairs)
    bad[0] = (g1_generator(), bad[0][1])
    assert pairings_are_identity(bad) is False
    assert sharded_pairing_lanes_check(mesh, bad) is False


def test_sharded_pairing_lanes_ragged_and_infinity():
    """Padding edges of the lane-chunk path: the m == 1 bump (a single
    pad lane cannot cancel, so the chunks widen), an identity product
    that SURVIVES the pads, and infinity lanes dropped on the host."""
    from consensus_specs_tpu.crypto.bls.curve import (
        g1_generator,
        g2_generator,
        g2_infinity,
    )
    from consensus_specs_tpu.parallel.bls_sharded import (
        sharded_pairing_lanes_check,
    )

    mesh = _mesh(2)
    G, H = g1_generator(), g2_generator()
    # 5 lanes on 2 devices: C=3 leaves m=1, which must bump to C=4 / m=3.
    # 2 valid lanes + a 3-lane self-canceling group keep the product at 1.
    pairs = _lane_fixture(1) + [(G, H), (G, H), (-G.mul(2), H)]
    assert len(pairs) == 5
    assert sharded_pairing_lanes_check(mesh, pairs) is True
    # infinity lanes contribute the identity and are dropped host-side
    assert sharded_pairing_lanes_check(
        mesh, pairs + [(g1_generator(), g2_infinity())]) is True
    # all-infinity: empty product, vacuously true
    assert sharded_pairing_lanes_check(
        mesh, [(g1_generator(), g2_infinity())]) is True


def test_sharded_bls_pair_count_derived_not_hardcoded(monkeypatch):
    """ADVICE r5 #3: the per-item pair count K is derived from the
    marshalled pairs (K = len(padded[0])), with a clear assert on ragged
    batches — a marshaller change can no longer silently disagree with a
    hardcoded K=2."""
    from consensus_specs_tpu.crypto.bls import ciphersuite as cs
    from consensus_specs_tpu.ops import bls_jax
    from consensus_specs_tpu.parallel.bls_sharded import (
        sharded_batch_fast_aggregate_verify,
    )

    mesh = _mesh(2)
    msg = b"\x07" * 32
    pk, sig = cs.SkToPk(21), cs.Sign(21, msg)

    # a marshaller that returns a ragged batch must trip the uniformity
    # assert, not shape-garble the device program
    real = bls_jax.marshal_fast_aggregate_items

    def ragged(pk_lists, msgs, sigs):
        results, todo = real(pk_lists, msgs, sigs)
        b, pairs = todo[0]
        todo[0] = (b, pairs + [pairs[0]])  # 3 pairs vs 2 elsewhere
        return results, todo

    monkeypatch.setattr(bls_jax, "marshal_fast_aggregate_items", ragged)
    import pytest as _pytest
    with _pytest.raises(AssertionError, match="uniform pair count"):
        sharded_batch_fast_aggregate_verify(
            mesh, [[pk], [pk]], [msg, msg], [sig, sig])
