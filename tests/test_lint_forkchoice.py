"""FC01 lint rule: the spec ``Store`` and the proto-array engine each hold
a latest-message view; they stay in lockstep only if every write goes
through the spec handlers or ``forkchoice/batch.py``.  The rule flags any
direct ``store.latest_messages`` mutation outside ``specs/`` and
``forkchoice/`` — and the live tree must be clean."""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
import lint  # noqa: E402

REPO = Path(__file__).resolve().parent.parent

_VIOLATIONS = """\
def bad(store, spec, i, msg):
    store.latest_messages[i] = msg          # subscript assign
    store.latest_messages.update({i: msg})  # mutating method
    del store.latest_messages[i]            # deletion
    store.latest_messages = {}              # rebind
"""

_CLEAN = """\
def good(spec, store, att):
    spec.on_attestation(store, att)
    return store.latest_messages.get(0), len(store.latest_messages)
"""


def _findings_for(tmp_path, name, source, code="FC01"):
    p = tmp_path / name
    p.write_text(source)
    return [f for f in lint.check_file(p) if code in f[2]]


def test_fc01_flags_every_mutation_shape(tmp_path):
    found = _findings_for(tmp_path, "helpers.py", _VIOLATIONS)
    assert sorted(f[1] for f in found) == [2, 3, 4, 5]


def test_fc01_ignores_reads(tmp_path):
    assert _findings_for(tmp_path, "helpers.py", _CLEAN) == []


def test_fc01_exempts_spec_and_forkchoice_dirs(tmp_path):
    for exempt in ("specs", "forkchoice"):
        d = tmp_path / exempt
        d.mkdir()
        assert _findings_for(d, "impl.py", _VIOLATIONS) == []


def test_fc01_respects_noqa(tmp_path):
    src = "def f(s, m):\n    s.latest_messages[0] = m  # noqa\n"
    assert _findings_for(tmp_path, "x.py", src) == []


def test_live_tree_is_fc01_clean():
    findings = []
    for f in lint.iter_py_files(
            [REPO / "consensus_specs_tpu", REPO / "tests", REPO / "tools",
             REPO / "bench.py"]):
        findings += [x for x in lint.check_file(f) if "FC01" in x[2]]
    assert findings == [], findings


def test_fc01_ignores_bare_annotations(tmp_path):
    src = ("def f(store, m):\n"
           "    store.latest_messages: dict\n"          # declaration only
           "    store.latest_messages: dict = {0: m}\n")  # annotated write
    found = _findings_for(tmp_path, "x.py", src)
    assert [f[1] for f in found] == [3]
