"""FC01 rule: the spec ``Store`` and the proto-array engine each hold
a latest-message view; they stay in lockstep only if every write goes
through the spec handlers or ``forkchoice/batch.py``.  The rule flags any
direct ``store.latest_messages`` mutation outside ``specs/`` and
``forkchoice/`` — and the live tree must be clean.

Migrated from the legacy ``tools/lint.py`` single-file checker to the
``tools/analysis`` registry API (same fixtures, same assertions); the
legacy ``lint.check_file`` facade keeps working and is pinned by the
compat test at the bottom.
"""
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))
from analysis import all_rules, analyze_file, iter_py_files  # noqa: E402

_VIOLATIONS = """\
def bad(store, spec, i, msg):
    store.latest_messages[i] = msg          # subscript assign
    store.latest_messages.update({i: msg})  # mutating method
    del store.latest_messages[i]            # deletion
    store.latest_messages = {}              # rebind
"""

_CLEAN = """\
def good(spec, store, att):
    spec.on_attestation(store, att)
    return store.latest_messages.get(0), len(store.latest_messages)
"""


def _findings_for(tmp_path, name, source, code="FC01"):
    p = tmp_path / name
    p.write_text(source)
    return [f for f in analyze_file(p) if f.code == code]


def test_fc01_flags_every_mutation_shape(tmp_path):
    found = _findings_for(tmp_path, "helpers.py", _VIOLATIONS)
    assert sorted(f.line for f in found) == [2, 3, 4, 5]


def test_fc01_ignores_reads(tmp_path):
    assert _findings_for(tmp_path, "helpers.py", _CLEAN) == []


def test_fc01_exempts_spec_and_forkchoice_dirs(tmp_path):
    for exempt in ("specs", "forkchoice"):
        d = tmp_path / exempt
        d.mkdir()
        assert _findings_for(d, "impl.py", _VIOLATIONS) == []


def test_fc01_respects_noqa(tmp_path):
    src = "def f(s, m):\n    s.latest_messages[0] = m  # noqa\n"
    assert _findings_for(tmp_path, "x.py", src) == []


def test_fc01_targeted_noqa(tmp_path):
    # a coded noqa for a DIFFERENT rule no longer blankets FC01
    src = "def f(s, m):\n    s.latest_messages[0] = m  # noqa: E501\n"
    assert len(_findings_for(tmp_path, "x.py", src)) == 1
    src = "def f(s, m):\n    s.latest_messages[0] = m  # noqa: FC01\n"
    assert _findings_for(tmp_path, "x.py", src) == []


def test_live_tree_is_fc01_clean():
    fc01 = all_rules(codes=["FC01"])
    findings = []
    for f in iter_py_files(
            [REPO / "consensus_specs_tpu", REPO / "tests", REPO / "tools",
             REPO / "bench.py"]):
        findings += analyze_file(f, rules=fc01)
    assert findings == [], findings


def test_fc01_ignores_bare_annotations(tmp_path):
    src = ("def f(store, m):\n"
           "    store.latest_messages: dict\n"          # declaration only
           "    store.latest_messages: dict = {0: m}\n")  # annotated write
    found = _findings_for(tmp_path, "x.py", src)
    assert [f.line for f in found] == [3]


def test_legacy_check_file_facade_still_works(tmp_path):
    import lint

    p = tmp_path / "helpers.py"
    p.write_text(_VIOLATIONS)
    found = [x for x in lint.check_file(p) if "FC01" in x[2]]
    assert sorted(x[1] for x in found) == [2, 3, 4, 5]
    # non-UTF8 input returns the E902 finding, same as the old checker
    bad = tmp_path / "latin.py"
    bad.write_bytes(b"# caf\xe9\n")
    assert ["E902"] == [x[2].split()[0] for x in lint.check_file(bad)]


_VOTE_STATE_VIOLATIONS = """\
def bad(store, root, i):
    store.proposer_boost_root = root        # boost rebind
    store.equivocating_indices.add(i)       # the spec's own write shape
    store.equivocating_indices.discard(i)
    store.equivocating_indices = set()      # rebind
"""


def test_fc01_flags_widened_vote_state_mutations(tmp_path):
    # ISSUE 12: proposer_boost_root + equivocating_indices (set mutators
    # included — .add is how the spec itself writes it) join
    # latest_messages under the rule
    found = _findings_for(tmp_path, "helpers.py", _VOTE_STATE_VIOLATIONS)
    assert sorted(f.line for f in found) == [2, 3, 4, 5]


def test_fc01_exempts_node_dir(tmp_path):
    d = tmp_path / "node"
    d.mkdir()
    assert _findings_for(d, "service.py", _VOTE_STATE_VIOLATIONS) == []
