"""BLS12-381 tests: RFC 9380 vectors, sign/verify/aggregate semantics,
serialization, selector/stub behavior.
Reference behavior model: eth2spec/utils/bls.py + py_ecc G2ProofOfPossession.
"""
import pytest

import consensus_specs_tpu.crypto.bls as bls
from consensus_specs_tpu.crypto.bls.curve import (
    g1_from_bytes,
    g1_generator,
    g1_to_bytes,
    g2_from_bytes,
    g2_generator,
    g2_to_bytes,
)
from consensus_specs_tpu.crypto.bls.fields import FQ12_ONE, Fq2, P
from consensus_specs_tpu.crypto.bls.hash_to_curve import expand_message_xmd, hash_to_g2
from consensus_specs_tpu.crypto.bls.pairing import pairing, pairings_are_identity


@pytest.fixture(autouse=True)
def _bls_on():
    bls.bls_active = True
    yield
    bls.bls_active = True


# -- external vectors --------------------------------------------------------


def test_expand_message_xmd_rfc9380():
    dst = b"QUUX-V01-CS02-with-expander-SHA256-128"
    assert (
        expand_message_xmd(b"", dst, 0x20).hex()
        == "68a985b87eb6b46952128911f2a4412bbc302a9d759667f87f7a21d803f07235"
    )
    assert (
        expand_message_xmd(b"abc", dst, 0x20).hex()
        == "d8ccab23b5985ccea865c6c97b6e5b8350e794e603b4b97902f53a8a0d605615"
    )


def test_hash_to_g2_rfc9380_vector():
    p = hash_to_g2(b"", b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_")
    x, y = p.to_affine()
    assert x.c0 == 0x0141EBFBDCA40EB85B87142E130AB689C673CF60F1A3E98D69335266F30D9B8D4AC44C1038E9DCDD5393FAF5C41FB78A
    assert x.c1 == 0x05CB8437535E20ECFFAEF7752BADDF98034139C38452458BAEEFAB379BA13DFF5BF5DD71B72418717047F5B0F37DA03D
    assert y.c0 == 0x0503921D7F6A12805E72940B963C0CF3471C7B2A524950CA195D11062EE75EC076DAF2D4BC358C4B190C0C98064FDD92
    assert y.c1 == 0x12424AC32561493F3FE3C260708A12B7C620E7BE00099A974E259DDC7D1F6395C3C811CDD19F1E8DBF3E9ECFDCBAB8D6


def test_sk_to_pk_known_vectors():
    assert (
        bls.SkToPk(1).hex()
        == "97f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac586c55e83ff97a1aeffb3af00adb22c6bb"
    )
    sk = 0x263DBD792F5B1BE47ED85F8938C0F29586AF0D3AC7B977F21C278FE1462040E3
    assert (
        bls.SkToPk(sk).hex()
        == "a491d1b0ecd9bb917989f0e74f0dea0422eac4a873e5e2644f368dffb9a6e20fd6e10c1b77654d067c0618f6e5a7f79a"
    )


# -- pairing -----------------------------------------------------------------


def test_pairing_bilinearity():
    g1, g2 = g1_generator(), g2_generator()
    e = pairing(g1, g2)
    assert e != FQ12_ONE
    assert pairing(g1.mul(2), g2) == e.pow(2)
    assert pairing(g1, g2.mul(2)) == e.pow(2)
    assert pairings_are_identity([(g1.mul(3), g2), (-g1, g2.mul(3))])


# -- sign / verify -----------------------------------------------------------


def test_sign_verify_roundtrip():
    sk, msg = 42, b"test message"
    pk = bls.SkToPk(sk)
    sig = bls.Sign(sk, msg)
    assert bls.Verify(pk, msg, sig)
    assert not bls.Verify(pk, b"wrong", sig)
    assert not bls.Verify(bls.SkToPk(43), msg, sig)


def test_verify_malformed_inputs_return_false():
    pk = bls.SkToPk(5)
    sig = bls.Sign(5, b"m")
    assert not bls.Verify(b"\x00" * 48, b"m", sig)
    assert not bls.Verify(pk, b"m", b"\xFF" * 96)
    assert not bls.Verify(b"short", b"m", sig)
    assert not bls.Verify(pk, b"m", b"short")


def test_aggregate_same_message():
    sks = [1, 2, 3]
    pks = [bls.SkToPk(s) for s in sks]
    sigs = [bls.Sign(s, b"msg") for s in sks]
    agg = bls.Aggregate(sigs)
    assert bls.FastAggregateVerify(pks, b"msg", agg)
    assert not bls.FastAggregateVerify(pks[:2], b"msg", agg)
    assert not bls.FastAggregateVerify([], b"msg", agg)


def test_aggregate_verify_distinct_messages():
    sks = [7, 8]
    msgs = [b"a", b"b"]
    pks = [bls.SkToPk(s) for s in sks]
    agg = bls.Aggregate([bls.Sign(s, m) for s, m in zip(sks, msgs)])
    assert bls.AggregateVerify(pks, msgs, agg)
    assert not bls.AggregateVerify(pks, [b"a", b"x"], agg)


def test_aggregate_pks_matches_sum_of_sks():
    pks = [bls.SkToPk(s) for s in (1, 2, 3)]
    assert bls.AggregatePKs(pks) == bls.SkToPk(6)


def test_aggregate_empty_raises():
    with pytest.raises(ValueError):
        bls.bls.Aggregate([])
    with pytest.raises(ValueError):
        bls.bls.AggregatePKs([])


def test_key_validate():
    assert bls.KeyValidate(bls.SkToPk(11))
    assert not bls.KeyValidate(b"\x00" * 48)
    # identity pubkey rejected
    assert not bls.KeyValidate(bytes([0xC0]) + b"\x00" * 47)


# -- serialization -----------------------------------------------------------


def test_point_serialization_roundtrip():
    pk = bls.SkToPk(77)
    sig = bls.Sign(77, b"x")
    assert g1_to_bytes(g1_from_bytes(pk)) == pk
    assert g2_to_bytes(g2_from_bytes(sig)) == sig


def test_infinity_serialization():
    inf_g1 = bytes([0xC0]) + b"\x00" * 47
    inf_g2 = bytes([0xC0]) + b"\x00" * 95
    assert g1_to_bytes(g1_from_bytes(inf_g1)) == inf_g1
    assert g2_to_bytes(g2_from_bytes(inf_g2)) == inf_g2


def test_fq2_sqrt_property():
    import random

    rng = random.Random(1234)
    for _ in range(20):
        a = Fq2(rng.randrange(P), rng.randrange(P))
        sq = a.square()
        r = sq.sqrt()
        assert r is not None and r.square() == sq


# -- selector / stubbing -----------------------------------------------------


def test_bls_active_stubbing():
    bls.bls_active = False
    assert bls.Verify(b"junk", b"m", b"junk") is True
    assert bls.Sign(1, b"m") == bls.STUB_SIGNATURE
    assert bls.SkToPk(1) == bls.STUB_PUBKEY
    bls.bls_active = True
    assert bls.Verify(b"junk", b"m", b"junk") is False


def test_backend_selector():
    prev = bls.backend_name()
    try:
        bls.use_python()
        assert bls.backend_name() == "python"
        bls.use_fastest()
        assert bls.backend_name() in ("native", "python")
    finally:
        bls.use_backend(prev)
