"""Fault-site registry completeness gate.

Every production fault site (registered via ``faults.site`` at module
import) must be exercised by at least one chaos case — a new site
without a chaos case turns this red, exactly like an analyzer finding.
Coverage claims are the static ``COVERED_SITES`` tables of the chaos
modules, so the gate holds regardless of which subset of tests a run
selects.
"""
import pytest

from consensus_specs_tpu import faults

# importing the instrumented modules registers their sites
import consensus_specs_tpu.dist.dispatch  # noqa: F401  (registers fabric's too)
import consensus_specs_tpu.dist.worker  # noqa: F401
import consensus_specs_tpu.forkchoice.engine  # noqa: F401
import consensus_specs_tpu.node.service  # noqa: F401  (registers ingest's too)
import consensus_specs_tpu.query.coldstart  # noqa: F401
import consensus_specs_tpu.query.engine  # noqa: F401
import consensus_specs_tpu.query.resident  # noqa: F401
import consensus_specs_tpu.stf.engine  # noqa: F401

from . import (
    test_dist_chaos,
    test_forkchoice_chaos,
    test_node_chaos,
    test_persist_chaos,
    test_query_chaos,
    test_stf_chaos,
)


def _production_sites():
    """Registered sites, minus the probes test modules register for the
    fault machinery's own unit tests."""
    return {name for name in faults.registry() if not name.startswith("tests.")}


def test_every_site_has_a_chaos_case():
    registered = _production_sites()
    covered = (set(test_stf_chaos.COVERED_SITES)
               | set(test_forkchoice_chaos.COVERED_SITES)
               | set(test_node_chaos.COVERED_SITES)
               | set(test_persist_chaos.COVERED_SITES)
               | set(test_query_chaos.COVERED_SITES)
               | set(test_dist_chaos.COVERED_SITES))
    missing = registered - covered
    assert not missing, (
        f"fault sites with no chaos case: {sorted(missing)} — add a case to "
        "tests/chaos/ (COVERED_SITES) exercising each new probe")
    phantom = covered - registered
    assert not phantom, (
        f"chaos cases claim unregistered sites: {sorted(phantom)} — typo in "
        "a case table, or a probe was removed without its cases")


def test_registry_depth_meets_the_acceptance_floor():
    """ISSUE 5 acceptance: >= 12 distinct sites across the chaos
    schedules.  The deterministic case tables alone must clear the bar —
    random schedules are extra, not load-bearing."""
    deterministic = {
        f.site for case in (test_stf_chaos._PHASE0_CASES
                            + test_stf_chaos._ALTAIR_CASES) for f in case}
    assert len(deterministic) >= 12, sorted(deterministic)
    assert len(_production_sites()) >= 12


def test_node_survival_sites_are_registered_and_covered():
    """ISSUE 13: the survival layer's seams exist AND each carries a
    chaos case — removing a probe or dropping its case turns this red
    independently of the generic completeness sweep above."""
    expected = {"node.apply", "node.enqueue", "node.admission",
                "node.quarantine", "node.recover", "node.batch_bisect"}
    node_sites = {n for n in _production_sites() if n.startswith("node.")}
    assert expected <= node_sites, sorted(expected - node_sites)
    assert node_sites <= set(test_node_chaos.COVERED_SITES), \
        sorted(node_sites - set(test_node_chaos.COVERED_SITES))


def test_persist_sites_are_registered_and_covered():
    """ISSUE 14: the durable-IO seams exist AND each carries a chaos
    case — an uncovered persist site turns this red independently of the
    generic completeness sweep above."""
    expected = {"persist.write", "persist.replace", "persist.read",
                "persist.digest"}
    persist_sites = {n for n in _production_sites()
                     if n.startswith("persist.")}
    assert expected <= persist_sites, sorted(expected - persist_sites)
    # persist.refault (the eviction re-fault seam) lives with the query
    # chaos cases — the read path owns that probe
    persist_covered = (set(test_persist_chaos.COVERED_SITES)
                       | set(test_query_chaos.COVERED_SITES))
    assert persist_sites <= persist_covered, \
        sorted(persist_sites - persist_covered)


def test_query_sites_are_registered_and_covered():
    """ISSUE 16: the historical read path's seams exist AND each carries
    a chaos case — an uncovered query site turns this red independently
    of the generic completeness sweep above."""
    expected = {"query.proof", "query.restore", "persist.refault"}
    query_sites = {n for n in _production_sites()
                   if n.startswith("query.")} | {"persist.refault"}
    assert expected <= query_sites, sorted(expected - query_sites)
    assert query_sites <= set(test_query_chaos.COVERED_SITES), \
        sorted(query_sites - set(test_query_chaos.COVERED_SITES))


def test_dist_sites_are_registered_and_covered():
    """ISSUE 20: the process-boundary seams exist AND each carries a
    chaos case — both coordinator-side (spawn/dispatch/reply/heartbeat)
    and the worker-side execution probe a scoped plan crosses the
    process boundary to reach."""
    expected = {"dist.spawn", "dist.dispatch", "dist.reply",
                "dist.heartbeat", "dist.worker.exec"}
    dist_sites = {n for n in _production_sites() if n.startswith("dist.")}
    assert expected <= dist_sites, sorted(expected - dist_sites)
    assert dist_sites <= set(test_dist_chaos.COVERED_SITES), \
        sorted(dist_sites - set(test_dist_chaos.COVERED_SITES))


def test_site_names_are_unique_and_dotted():
    for name in _production_sites():
        assert "." in name, f"site {name!r} is not a dotted path"
    with pytest.raises(ValueError, match="duplicate"):
        faults.site("stf.engine.header")
