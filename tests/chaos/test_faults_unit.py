"""Unit pins for the fault-injection machinery itself
(consensus_specs_tpu/faults.py): hit counting, disarm-after-fire, sticky
rules, deterministic corruption, env-directive parsing, plan nesting, and
registry uniqueness.  The chaos differential suites build on these
semantics — if a probe misfires, every containment assertion downstream
is measuring the wrong thing."""
import numpy as np
import pytest

from consensus_specs_tpu import faults

SITE = faults.site("tests.chaos.unit_probe")
VALUE_SITE = faults.site("tests.chaos.unit_value_probe")


def test_probe_is_passthrough_without_plan():
    assert faults.active_plan() is None
    assert SITE() is None
    assert VALUE_SITE(17) == 17


def test_fires_on_nth_hit_then_disarms():
    plan = faults.FaultPlan([faults.Fault(SITE.name, nth=2)])
    with faults.inject(plan):
        SITE()  # hit 1: armed but not yet
        with pytest.raises(faults.InjectedFault, match="hit 2"):
            SITE()
        SITE()  # hit 3: fired once, disarmed
    assert plan.hits[SITE.name] == 3
    assert plan.fired == [(SITE.name, 2, "error")]


def test_sticky_fires_from_nth_on():
    plan = faults.FaultPlan([faults.Fault(SITE.name, nth=2, sticky=True)])
    with faults.inject(plan):
        SITE()
        for expected_hit in (2, 3, 4):
            with pytest.raises(faults.InjectedFault):
                SITE()
    assert [h for _, h, _ in plan.fired] == [2, 3, 4]


def test_crash_kind_is_backend_crash():
    plan = faults.FaultPlan([faults.Fault(SITE.name, kind="crash")])
    with faults.inject(plan):
        with pytest.raises(faults.InjectedBackendCrash):
            SITE()
    # the crash exception is an OSError (a dead ctypes backend), NOT the
    # generic InjectedFault the engine's replay contract swallows
    assert not issubclass(faults.InjectedBackendCrash, faults.InjectedFault)
    assert issubclass(faults.InjectedBackendCrash, OSError)


def test_corrupt_copies_and_is_deterministic():
    arr = np.array([5, 6, 7], dtype=np.int64)
    plan = faults.FaultPlan([faults.Fault(VALUE_SITE.name, kind="corrupt")])
    with faults.inject(plan):
        out = VALUE_SITE(arr)
    assert out[0] == 6 and arr[0] == 5  # copy corrupted, original intact
    with faults.inject(faults.FaultPlan(
            [faults.Fault(VALUE_SITE.name, kind="corrupt")])):
        assert VALUE_SITE(b"\x10\x20") == b"\x11\x20"
    with faults.inject(faults.FaultPlan(
            [faults.Fault(VALUE_SITE.name, kind="corrupt")])):
        assert VALUE_SITE(True) is False
    bools = np.array([True, False])
    with faults.inject(faults.FaultPlan(
            [faults.Fault(VALUE_SITE.name, kind="corrupt")])):
        assert not VALUE_SITE(bools)[0]


def test_corrupt_on_valueless_probe_degenerates_to_error():
    plan = faults.FaultPlan([faults.Fault(SITE.name, kind="corrupt")])
    with faults.inject(plan):
        with pytest.raises(faults.InjectedFault):
            SITE()


def test_plan_from_env_directives():
    plan = faults.plan_from_env(
        "a.b@2=corrupt, c.d ,e.f@3+=crash")
    reprs = sorted(repr(f) for f in plan.faults())
    assert reprs == ["a.b@2=corrupt", "c.d@1=error", "e.f@3+=crash"]


def test_seeded_plan_is_reproducible():
    sites = ["s.one", "s.two", "s.three"]
    a = faults.FaultPlan.seeded(42, sites, n_faults=5, kinds=("error", "corrupt"))
    b = faults.FaultPlan.seeded(42, sites, n_faults=5, kinds=("error", "corrupt"))
    assert [repr(f) for f in a.faults()] == [repr(f) for f in b.faults()]
    c = faults.FaultPlan.seeded(43, sites, n_faults=5, kinds=("error", "corrupt"))
    assert [repr(f) for f in a.faults()] != [repr(f) for f in c.faults()]


def test_inject_nesting_restores_outer_plan():
    outer = faults.FaultPlan([])
    inner = faults.FaultPlan([])
    with faults.inject(outer):
        assert faults.active_plan() is outer
        with faults.inject(inner):
            assert faults.active_plan() is inner
        assert faults.active_plan() is outer
    assert faults.active_plan() is None


def test_duplicate_site_registration_rejected():
    with pytest.raises(ValueError, match="duplicate fault site"):
        faults.site(SITE.name)


def test_assert_sites_registered_catches_typos():
    """A typo'd site name must fail fast, not silently disarm the run
    (CSTPU_FAULTS schedules have no in-test `plan.fired` assert)."""
    good = faults.FaultPlan([faults.Fault(SITE.name)])
    faults.assert_sites_registered(good)  # registered: no raise
    typo = faults.plan_from_env("tests.chaos.unit_prob=error")  # missing 'e'
    with pytest.raises(ValueError, match="unregistered sites"):
        faults.assert_sites_registered(typo)
    faults.assert_sites_registered(None)  # no plan active: no-op
    with faults.inject(typo):
        with pytest.raises(ValueError, match="unregistered"):
            faults.assert_sites_registered()  # defaults to the active plan


def test_fault_validates_inputs():
    with pytest.raises(ValueError, match="1-based"):
        faults.Fault("x", nth=0)
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.Fault("x", kind="explode")
    with pytest.raises(ValueError, match="malformed process scope"):
        faults.Fault("x", proc="worker1")
    with pytest.raises(ValueError, match="malformed process scope"):
        faults.Fault("x", proc="proc")


# -- per-process scope (ISSUE 20): site[@nth][=kind][@procK] ------------------

def test_plan_from_env_parses_process_scope():
    plan = faults.plan_from_env(
        "a.b@2=corrupt@proc1, c.d@proc0 ,e.f@3+=crash@proc12")
    reprs = sorted(repr(f) for f in plan.faults())
    assert reprs == ["a.b@2=corrupt@proc1", "c.d@1=error@proc0",
                     "e.f@3+=crash@proc12"]
    # round-trip: the coordinator ships its plan to workers this way
    again = faults.plan_from_env(faults.plan_to_env(plan))
    assert sorted(repr(f) for f in again.faults()) == reprs


@pytest.mark.parametrize("bad", [
    "a.b@proc",        # bare prefix, no ordinal
    "a.b@procX",       # non-decimal ordinal
    "a.b@proc1x",      # trailing junk
    "a.b=error@proc-1",  # negative ordinal
])
def test_plan_from_env_rejects_malformed_scopes_loudly(bad):
    """A typo'd scope must never silently arm the fault everywhere."""
    with pytest.raises(ValueError, match="malformed process scope"):
        faults.plan_from_env(bad)


def test_scope_ignored_when_no_fabric_active():
    """With no process scope set (the default, outside a fabric), a
    scoped fault fires everywhere — existing plans behave identically."""
    assert faults.process_scope() is None
    plan = faults.FaultPlan([faults.Fault(SITE.name, proc="proc1")])
    with faults.inject(plan):
        with pytest.raises(faults.InjectedFault):
            SITE()
    assert plan.fired == [(SITE.name, 1, "error")]


def test_scoped_fault_fires_only_in_its_process():
    plan = faults.FaultPlan([faults.Fault(SITE.name, proc="proc2",
                                          sticky=True)])
    faults.set_process_scope("proc1")
    try:
        with faults.inject(plan):
            SITE()  # addressed to proc2: skipped here
            assert plan.fired == []
        faults.set_process_scope("proc2")
        with faults.inject(plan):
            with pytest.raises(faults.InjectedFault):
                SITE()
    finally:
        faults.set_process_scope(None)
    # hits counted in BOTH processes — only the firing is scoped, so the
    # per-site hit cadence matches an unscoped run
    assert plan.hits[SITE.name] == 2


def test_unscoped_fault_fires_inside_a_fabric_process():
    plan = faults.FaultPlan([faults.Fault(SITE.name)])
    faults.set_process_scope("proc0")
    try:
        with faults.inject(plan):
            with pytest.raises(faults.InjectedFault):
                SITE()
    finally:
        faults.set_process_scope(None)


def test_set_process_scope_validates():
    with pytest.raises(ValueError, match="malformed process scope"):
        faults.set_process_scope("coordinator")
    assert faults.process_scope() is None
